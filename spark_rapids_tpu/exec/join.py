"""Join physical operators.

Counterpart of the reference's join family (GpuShuffledHashJoinBase,
GpuBroadcastHashJoinExec, GpuHashJoin trait with null-key filtering +
JoinGatherer chunked materialization — SURVEY.md section 2.4 "Joins").
One exec covers the single-process path: build side collected and
concatenated on device, probe side streamed, with the combined-sort kernel
from ops/joins.py.  Join types: inner, left, right, full, semi (left semi),
anti (left anti), cross.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, bucket_capacity
from spark_rapids_tpu.exec.base import JOIN_TIME, Schema, TpuExec
from spark_rapids_tpu.ops import joins as J
from spark_rapids_tpu.ops import selection
from spark_rapids_tpu.ops.compiler import StageFn
from spark_rapids_tpu.ops.concat import concat_batches
from spark_rapids_tpu.ops.expressions import ColVal, Expression
from spark_rapids_tpu.utils import hostsync


def _to_colvals(batch: ColumnarBatch) -> List[ColVal]:
    return [ColVal(c.dtype, c.data, c.validity, c.offsets)
            for c in batch.columns.values()]


def _to_columns(cols: Sequence[ColVal], nrows: int) -> List[Column]:
    return [Column(c.dtype, c.values, nrows, validity=c.validity,
                   offsets=c.offsets) for c in cols]


class _JoinKeyEncoder:
    """Shared host dictionary for string join keys (codes match across
    sides, so code equality == string equality)."""

    def __init__(self):
        self.codes: Dict[Optional[str], int] = {}
        self._values: List[Optional[str]] = []

    def encode(self, col: Column) -> Column:
        from spark_rapids_tpu.ops.dictionary import dict_encode_stable
        out = dict_encode_stable(col, self.codes, self._values,
                                 null_code=-1)
        validity = None
        hv = col.host_validity()
        if hv is not None:
            validity = hv[:col.nrows]
        return Column.from_numpy(out, dtype=dts.INT64, validity=validity,
                                 capacity=col.capacity)


class TpuHashJoinExec(TpuExec):
    ephemeral_output = True

    def __init__(self, left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression], join_type: str,
                 left: TpuExec, right: TpuExec,
                 using: Optional[List[str]] = None,
                 max_output_rows: int = 1 << 22):
        super().__init__(left, right)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.join_type = join_type
        self.using = using
        self.max_output_rows = max_output_rows
        self._register_metric(JOIN_TIME)
        self._lkey_fn = StageFn(self.left_keys,
                                [dt for _, dt in left.schema])
        self._rkey_fn = StageFn(self.right_keys,
                                [dt for _, dt in right.schema])
        self._encoders = [
            _JoinKeyEncoder() if e.dtype.is_string else None
            for e in self.left_keys]

    # ------------------------------------------------------------------ plan --
    @property
    def left(self) -> TpuExec:
        return self.children[0]

    @property
    def right(self) -> TpuExec:
        return self.children[1]

    @property
    def schema(self) -> Schema:
        lschema, rschema = self.left.schema, self.right.schema
        if self.join_type in ("semi", "anti"):
            return list(lschema)
        if self.using:
            keyset = set(self.using)
            out = [(n, dt) for n, dt in lschema if n in keyset]
            out += [(n, dt) for n, dt in lschema if n not in keyset]
            out += [(n, dt) for n, dt in rschema if n not in keyset]
            return out
        return list(lschema) + list(rschema)

    def describe(self):
        return (f"TpuHashJoinExec[{self.join_type}, "
                f"{[e.name for e in self.left_keys]}]")

    # ------------------------------------------------------------------ exec --
    def _encoded_keys(self, batch: ColumnarBatch, fn: StageFn) -> List[ColVal]:
        cols = fn(batch)
        out = []
        for enc, c in zip(self._encoders, cols):
            if enc is not None:
                c = enc.encode(c)
            out.append(ColVal(c.dtype, c.data, c.validity, c.offsets))
        return out

    def do_execute(self) -> Iterator[ColumnarBatch]:
        if self.join_type == "cross":
            yield from self._execute_cross()
            return
        # build = right side normally (the reference also builds the right,
        # GpuSortMergeJoinMeta -> shuffled hash join); a RIGHT outer join
        # swaps roles so the preserved side streams as the probe.
        self._swap = self.join_type == "right"
        probe_exec, build_exec = (self.right, self.left) if self._swap \
            else (self.left, self.right)
        probe_fn, build_fn = (self._rkey_fn, self._lkey_fn) if self._swap \
            else (self._lkey_fn, self._rkey_fn)
        from spark_rapids_tpu.memory.coalesce import (
            RequireSingleBatch, coalesce_iterator)
        from spark_rapids_tpu.memory.retry import (
            with_retry, with_retry_no_split)
        # build side is a RequireSingleBatch coalesce: pending batches
        # register spillable while accumulating (GpuCoalesceBatches with
        # the single-batch goal feeding GpuShuffledHashJoin's build)
        coalesced = coalesce_iterator(build_exec.execute(),
                                      RequireSingleBatch())
        # the join's single largest device allocation — guard it
        build = with_retry_no_split(lambda: next(coalesced, None))
        if build is None:
            from spark_rapids_tpu.columnar.batch import empty_batch
            build = empty_batch(build_exec.schema, capacity=1)
        build_keys = with_retry_no_split(
            lambda: self._encoded_keys(build, build_fn))
        build_payload = _to_colvals(build)
        b_matched_acc = None

        outer = self.join_type in ("left", "right", "full")

        # the match phase per probe batch; OOM recovery may split the
        # probe side — safe for every join type (build-matched flags
        # accumulate across splits the same way they do across batches,
        # and logical_or is idempotent under re-attempts)
        from spark_rapids_tpu.ops import pallas_kernels as pk
        hash_on, _hash_slots = pk.hash_dispatch_conf()

        def match_hash(probe_keys, probe_nrows):
            """Hash phase-A attempt: None means run the sort merge
            (disabled, ineligible, or table overflow — outputs of an
            overflowed table are garbage and are discarded whole)."""
            if not (hash_on and
                    J.hash_join_eligible(build_keys, probe_keys)):
                return None
            from spark_rapids_tpu.exec.fusion import fusion_metrics
            b_cap = build_keys[0].values.shape[0]
            m = J.hash_join_match(build_keys, probe_keys,
                                  jnp.int32(build.nrows),
                                  jnp.int32(probe_nrows),
                                  J.hash_join_table_slots(b_cap))
            fusion_metrics.bump("hashKernelLaunches")
            if bool(hostsync.fetch(m["overflow"])):
                fusion_metrics.bump("hashOverflowFallbacks")
                return None
            m.pop("overflow")
            return m

        def match_one(batch):
            nonlocal b_matched_acc
            with self.timer(JOIN_TIME):
                probe_keys = self._encoded_keys(batch, probe_fn)
                m = match_hash(probe_keys, batch.nrows)
                if m is None:
                    m = J.join_match(build_keys, probe_keys,
                                     jnp.int32(build.nrows),
                                     jnp.int32(batch.nrows))
                if self.join_type == "full":
                    bm = m["build_matched"]
                    b_matched_acc = bm if b_matched_acc is None else \
                        jnp.logical_or(b_matched_acc, bm)
            return batch, m

        for batch, m in with_retry(probe_exec.execute(), match_one):
            with self.timer(JOIN_TIME):
                if self.join_type in ("semi", "anti"):
                    # output <= one probe batch: spill-retry suffices
                    yield from with_retry_no_split(
                        lambda: list(self._emit_semi_anti(batch, m)))
                    continue
                count, starts, ends, total = with_retry_no_split(
                    lambda: J.join_out_starts(
                        m["probe_count"], jnp.int32(batch.nrows), outer))
                total = int(total)
                # chunks stream one at a time (peak HBM stays bounded by
                # max_output_rows); each emit gets spill-retry only — its
                # size is already the configured bound, not splittable
                for off in range(0, total, self.max_output_rows):
                    n_out = min(self.max_output_rows, total - off)
                    yield with_retry_no_split(
                        lambda off=off, n_out=n_out: self._emit_chunk(
                            batch, build, build_payload, m,
                            count, starts, ends, off, n_out))
        if self.join_type == "full":
            if b_matched_acc is None:
                # probe side produced zero batches: every build row is
                # unmatched
                b_matched_acc = jnp.zeros(build.capacity, dtype=bool)
            yield from with_retry_no_split(
                lambda: list(self._emit_unmatched_build(
                    build, build_payload, b_matched_acc)))

    def _emit_chunk(self, probe_batch, build, build_payload, m, count,
                    starts, ends, offset, n_out) -> ColumnarBatch:
        out_cap = bucket_capacity(n_out)
        # note: starts/ends use the outer-adjusted counts (row emission),
        # while `matched` must test the RAW match count so outer rows get
        # a null build side
        p, brow, matched, _ = J.join_gather_indices(
            starts - offset if offset else starts,
            ends - offset if offset else ends,
            m["probe_count"], m["probe_bstart"], m["sorted_to_build"],
            jnp.int64(n_out), out_cap)
        probe_cols = selection.gather(
            _to_colvals(probe_batch), p, jnp.int32(n_out),
            char_capacity=self._char_cap(probe_batch, p, n_out))
        build_cols = J.gather_build_side(
            build_payload, brow, matched, jnp.int32(n_out),
            char_capacity=self._char_cap_cols(build_payload, brow, n_out))
        return self._assemble(probe_cols, build_cols, n_out,
                              probe_valid=None)

    @staticmethod
    def _char_cap(batch: ColumnarBatch, indices, n_out) -> int:
        """Static char capacity covering a row-duplicating string gather."""
        needed = 0
        for c in batch.columns.values():
            if c.offsets is not None:
                needed = max(needed, int(selection.gathered_char_count(
                    c.offsets, indices, jnp.int32(n_out))))
        return bucket_capacity(needed) if needed else 0

    @staticmethod
    def _char_cap_cols(cols: Sequence[ColVal], indices, n_out) -> int:
        needed = 0
        for c in cols:
            if c.offsets is not None:
                needed = max(needed, int(selection.gathered_char_count(
                    c.offsets, indices, jnp.int32(n_out))))
        return bucket_capacity(needed) if needed else 0

    def _emit_semi_anti(self, batch, m) -> Iterator[ColumnarBatch]:
        count = m["probe_count"]
        in_range = jnp.arange(count.shape[0],
                              dtype=jnp.int32) < batch.nrows
        if self.join_type == "semi":
            keep = jnp.logical_and(count > 0, in_range)
        else:
            keep = jnp.logical_and(count == 0, in_range)
        cols, n = selection.compact(_to_colvals(batch), keep)
        n = int(n)
        if n == 0:
            return
        names = [nm for nm, _ in self.schema]
        yield ColumnarBatch(dict(zip(names, _to_columns(cols, n))), n)

    def _emit_unmatched_build(self, build, build_payload, matched_acc
                              ) -> Iterator[ColumnarBatch]:
        in_range = jnp.arange(
            matched_acc.shape[0], dtype=jnp.int32) < build.nrows
        keep = jnp.logical_and(jnp.logical_not(matched_acc), in_range)
        cols, n = selection.compact(build_payload, keep)
        n = int(n)
        if n == 0:
            return
        # left side all-null
        lschema = self.left.schema
        null_left = []
        cap = cols[0].values.shape[0] if cols else bucket_capacity(n)
        for _, dt in lschema:
            if dt.is_string:
                c = Column.from_strings([None] * n, capacity=cap)
                null_left.append(ColVal(dt, c.data, c.validity, c.offsets))
            else:
                null_left.append(ColVal(
                    dt, jnp.zeros(cap, dtype=dt.storage),
                    jnp.zeros(cap, dtype=jnp.bool_)))
        yield self._assemble(null_left, cols, n, probe_valid=False)

    def _assemble(self, probe_cols: List[ColVal], build_cols: List[ColVal],
                  n_out: int, probe_valid) -> ColumnarBatch:
        """Stitch left+right columns into the output schema (handling
        USING-style key deduplication and full-outer key coalescing)."""
        lschema, rschema = self.left.schema, self.right.schema
        if getattr(self, "_swap", False):
            lmap = {nm: c for (nm, _), c in zip(lschema, build_cols)}
            rmap = {nm: c for (nm, _), c in zip(rschema, probe_cols)}
        else:
            lmap = {nm: c for (nm, _), c in zip(lschema, probe_cols)}
            rmap = {nm: c for (nm, _), c in zip(rschema, build_cols)}
        out_cols: Dict[str, Column] = {}
        for nm, dt in self.schema:
            if self.using and nm in self.using:
                # preserved (probe) side supplies the key
                c = rmap[nm] if getattr(self, "_swap", False) else lmap[nm]
                if self.join_type == "full":
                    rc = rmap.get(nm)
                    if rc is not None:
                        lv = c.validity if c.validity is not None else \
                            jnp.ones_like(c.values, dtype=jnp.bool_) \
                            if not dt.is_string else None
                        if dt.is_string:
                            # coalesce handled by unmatched-build batches
                            # carrying the key in the right map
                            c = rc if probe_valid is False else c
                        else:
                            c = ColVal(
                                dt,
                                jnp.where(lv, c.values, rc.values),
                                None if c.validity is None or
                                rc.validity is None else
                                jnp.logical_or(c.validity, rc.validity))
                elif probe_valid is False:
                    c = rmap.get(nm, c)
            elif nm in lmap:
                c = lmap[nm]
            else:
                c = rmap[nm]
            out_cols[nm] = Column(c.dtype, c.values, n_out,
                                  validity=c.validity, offsets=c.offsets)
        return ColumnarBatch(out_cols, n_out)

    def _execute_cross(self) -> Iterator[ColumnarBatch]:
        right_batches = list(self.right.execute())
        if not right_batches:
            return
        build = concat_batches(right_batches)
        bn = build.nrows
        build_payload = _to_colvals(build)
        for batch in self.left.execute():
            total = batch.nrows * bn
            for off in range(0, total, self.max_output_rows):
                n_out = min(self.max_output_rows, total - off)
                out_cap = bucket_capacity(n_out)
                j = jnp.arange(out_cap, dtype=jnp.int64) + off
                p = (j // bn).astype(jnp.int32)
                b = (j % bn).astype(jnp.int32)
                probe_cols = selection.gather(
                    _to_colvals(batch), jnp.clip(p, 0, batch.capacity - 1),
                    jnp.int32(n_out))
                build_cols = selection.gather(
                    build_payload, jnp.clip(b, 0, build.capacity - 1),
                    jnp.int32(n_out))
                yield self._assemble(probe_cols, build_cols, n_out, None)
