"""Basic physical operators: scan, project, filter, range, union, limit.

Counterpart of ``basicPhysicalOperators.scala`` (GpuProjectExec:111,
GpuFilterExec:297, GpuRangeExec:358, GpuUnionExec:493) — with the stage-fusion
twist: project and filter own compiled StageFns, so their whole expression
forest is one XLA computation per capacity bucket.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, bucket_capacity
from spark_rapids_tpu.exec.base import (
    NUM_INPUT_BATCHES, NUM_INPUT_ROWS, Schema, TpuExec)
from spark_rapids_tpu.ops.compiler import FilterStageFn, StageFn
from spark_rapids_tpu.ops.expressions import BoundReference, Expression


class TpuCoalesceBatchesExec(TpuExec):
    """Planner-inserted batch coalescing: accumulate undersized
    upstream batches to the goal before handing them downstream — the
    GpuCoalesceBatches.scala operator in the position
    GpuTransitionOverrides.scala:57-64 inserts it (above multi-file
    scans here, where PERFILE readers emit one small batch per
    file)."""

    def __init__(self, child: TpuExec, goal):
        super().__init__(child)
        self.goal = goal

    @property
    def child(self) -> TpuExec:
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def describe(self):
        return f"TpuCoalesceBatchesExec[{self.goal}]"

    def do_execute(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.memory.coalesce import coalesce_iterator
        return coalesce_iterator(self.child.execute(), self.goal)


class TpuScanExec(TpuExec):
    """In-memory relation scan: re-chunks host/device batches to target rows."""

    def __init__(self, batches: Sequence[ColumnarBatch], schema: Schema,
                 max_rows: Optional[int] = None):
        super().__init__()
        self.batches = list(batches)
        self._schema = list(schema)
        self.max_rows = max_rows

    @property
    def schema(self) -> Schema:
        return self._schema

    def do_execute(self) -> Iterator[ColumnarBatch]:
        for b in self.batches:
            if self.max_rows is None or b.nrows <= self.max_rows:
                yield b
            else:
                table = b.to_arrow()
                for off in range(0, b.nrows, self.max_rows):
                    yield ColumnarBatch.from_arrow(
                        table.slice(off, self.max_rows))

    def describe(self):
        return f"TpuScanExec[{sum(b.nrows for b in self.batches)} rows]"


class TpuProjectExec(TpuExec):
    ephemeral_output = True

    def __init__(self, exprs: Sequence[Expression], child: TpuExec,
                 donate: bool = False):
        super().__init__(child)
        self.exprs = list(exprs)
        self._fn = StageFn(self.exprs, [dt for _, dt in child.schema],
                           donate=donate and child.ephemeral_output)

    @property
    def child(self) -> TpuExec:
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return [(e.name, e.dtype) for e in self.exprs]

    def do_execute(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.memory.retry import with_retry
        names = [e.name for e in self.exprs]

        def compute(batch):
            cols = self._fn(batch)
            # row_count, not nrows: a deferred upstream count passes
            # through without forcing a host sync
            return ColumnarBatch(dict(zip(names, cols)),
                                 batch.row_count)

        if self._fn.donate:
            # donated inputs are consumed by the kernel, so operator-
            # level OOM retry (which re-runs over the same batch) is
            # unsafe; faults escalate to query-level recovery, which
            # re-executes from source (docs/performance.md#donation)
            for batch in self.child.execute():
                yield compute(batch)
            return
        yield from with_retry(self.child.execute(), compute)

    def describe(self):
        return f"TpuProjectExec[{', '.join(e.name for e in self.exprs)}]"


class TpuFilterExec(TpuExec):
    """Fused predicate + compaction (+ pass-through projection)."""

    ephemeral_output = True

    def __init__(self, condition: Expression, child: TpuExec,
                 donate: bool = False):
        super().__init__(child)
        self.condition = condition
        in_schema = child.schema
        passthrough = [BoundReference(i, dt, name=n)
                       for i, (n, dt) in enumerate(in_schema)]
        self._fn = FilterStageFn(condition, passthrough,
                                 [dt for _, dt in in_schema],
                                 donate=donate and child.ephemeral_output)
        self._register_metric(NUM_INPUT_ROWS)

    @property
    def child(self) -> TpuExec:
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def do_execute(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.memory.retry import with_retry
        names = [n for n, _ in self.schema]

        def tallied():
            for batch in self.child.execute():
                self.metrics[NUM_INPUT_ROWS] += batch.row_count
                yield batch

        def compute(batch):
            cols, n = self._fn(batch)
            return None if n == 0 else \
                ColumnarBatch(dict(zip(names, cols)), n)

        if self._fn.donate:
            # see TpuProjectExec: donation forfeits operator-level retry
            for batch in tallied():
                out = compute(batch)
                if out is not None:
                    yield out
            return
        for out in with_retry(tallied(), compute):
            if out is not None:
                yield out

    def describe(self):
        return f"TpuFilterExec[{self.condition}]"


class TpuRangeExec(TpuExec):
    """range(start, end, step) -> bigint id column (GpuRangeExec:358)."""

    ephemeral_output = True

    def __init__(self, start: int, end: int, step: int,
                 max_rows: int = 1 << 20):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self.max_rows = max_rows
        self._schema = [("id", dts.INT64)]

    @property
    def schema(self) -> Schema:
        return self._schema

    def do_execute(self) -> Iterator[ColumnarBatch]:
        total = max(0, -(-(self.end - self.start) // self.step))
        emitted = 0
        while emitted < total:
            n = min(self.max_rows, total - emitted)
            cap = bucket_capacity(n)
            base = self.start + emitted * self.step
            vals = base + jnp.arange(cap, dtype=jnp.int64) * self.step
            yield ColumnarBatch({"id": Column(dts.INT64, vals, n)}, n)
            emitted += n


class TpuUnionExec(TpuExec):
    def __init__(self, *children: TpuExec):
        super().__init__(*children)

    @property
    def ephemeral_output(self) -> bool:
        # pass-through: output batches share every child's buffers
        return all(c.ephemeral_output for c in self.children)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def do_execute(self) -> Iterator[ColumnarBatch]:
        names = [n for n, _ in self.schema]
        for child in self.children:
            for batch in child.execute():
                cols = dict(zip(names, batch.columns.values()))
                yield ColumnarBatch(cols, batch.row_count)


class TpuLocalLimitExec(TpuExec):
    def __init__(self, n: int, child: TpuExec):
        super().__init__(child)
        self.n = n

    @property
    def child(self):
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def do_execute(self) -> Iterator[ColumnarBatch]:
        remaining = self.n
        for batch in self.child.execute():
            if remaining <= 0:
                return
            if batch.nrows <= remaining:
                remaining -= batch.nrows
                yield batch
            else:
                cols = {n: c.with_nrows(remaining)
                        for n, c in batch.columns.items()}
                yield ColumnarBatch(cols, remaining)
                return

    def describe(self):
        return f"TpuLocalLimitExec[{self.n}]"
