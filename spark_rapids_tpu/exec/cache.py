"""df.cache(): compressed host-resident columnar caching.

The reference caches DataFrames as in-memory *Parquet-encoded* batches
(ParquetCachedBatchSerializer, shims/spark311/.../
ParquetCachedBatchSerializer.scala) — compact host bytes, decoded on the
device when re-read.  The TPU analog uses the native columnar frame codec
(zero-RLE compressed, native/host_runtime.cpp) as the storage format:
first execution streams batches through a materializing exec that frames
them to host RAM; later executions deserialize and re-upload.

Cache identity is plan-object identity: any query whose logical tree
contains a cached plan node reuses the materialized bytes (the planner
substitutes at conversion time).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.exec.base import Schema, TpuExec


def batch_to_frame(batch: ColumnarBatch, compress=True) -> bytes:
    """Serialize one device batch to a compressed host frame."""
    import jax
    from spark_rapids_tpu import native
    cols = []
    device_bufs = []
    for c in batch.columns.values():
        for buf in (c.data, c.validity, c.offsets):
            if buf is not None and not isinstance(buf, np.ndarray):
                device_bufs.append(buf)
    fetched = jax.device_get(device_bufs) if device_bufs else []
    host = {id(d): h for d, h in zip(device_bufs, fetched)}

    def h(buf):
        if buf is None:
            return None
        return host.get(id(buf), buf)

    for (name, dt), c in zip(batch.schema, batch.columns.values()):
        cols.append((native.dtype_code(dt), h(c.data), h(c.validity),
                     h(c.offsets)))
    return native.serialize_batch(batch.nrows, cols, compress=compress)


def frame_to_batch(blob: bytes, schema: Schema) -> ColumnarBatch:
    import jax.numpy as jnp
    from spark_rapids_tpu import native
    nrows, cols = native.deserialize_batch(blob)
    out = {}
    for (name, dt), (_, d, v, o) in zip(schema, cols):
        if d is None:
            # zero-length buffers come back from the codec as absent; an
            # empty chars/data buffer must rebuild as empty, not None
            d = np.zeros(0, dtype=np.uint8 if dt.is_string else dt.storage)
        data = jnp.asarray(d if dt.is_string else d.view(dt.storage))
        validity = None if v is None else jnp.asarray(v.view(np.bool_))
        offsets = None if o is None else jnp.asarray(o.view(np.int32))
        out[name] = Column(dt, data, nrows, validity=validity,
                           offsets=offsets)
    return ColumnarBatch(out, nrows)


class CacheEntry:
    def __init__(self, plan):
        self.plan = plan
        self.schema: Schema = list(plan.schema)
        self.frames: Optional[List[bytes]] = None

    @property
    def materialized(self) -> bool:
        return self.frames is not None

    @property
    def cached_bytes(self) -> int:
        return sum(len(f) for f in self.frames) if self.frames else 0


class CacheManager:
    """Session-level registry of cached logical plans (CacheManager /
    InMemoryRelation role)."""

    def __init__(self):
        self._entries: Dict[int, CacheEntry] = {}

    def register(self, plan) -> CacheEntry:
        e = self._entries.get(id(plan))
        if e is None:
            e = CacheEntry(plan)
            self._entries[id(plan)] = e
        return e

    def unregister(self, plan) -> None:
        self._entries.pop(id(plan), None)

    def lookup(self, plan) -> Optional[CacheEntry]:
        return self._entries.get(id(plan))

    def clear(self) -> None:
        self._entries.clear()


class TpuMaterializeCacheExec(TpuExec):
    """First pass over a cached plan: stream child batches through,
    framing each to host; the cache only becomes visible when the pass
    completes (a LIMIT that stops early must not publish a partial
    cache)."""

    def __init__(self, entry: CacheEntry, child: TpuExec,
                 codec_level: int = 2):
        super().__init__(child)
        self.entry = entry
        # the owning session's conf codec (per-session, not process
        # global — a second session must not change this plan's codec)
        self.codec_level = codec_level

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def describe(self):
        return "TpuMaterializeCacheExec"

    def do_execute(self) -> Iterator[ColumnarBatch]:
        frames: List[bytes] = []
        for batch in self.children[0].execute():
            frames.append(batch_to_frame(batch,
                                         compress=self.codec_level))
            yield batch
        self.entry.frames = frames


class TpuCachedScanExec(TpuExec):
    """Later passes: deserialize host frames and re-upload (InMemory
    TableScanExec analog)."""

    def __init__(self, entry: CacheEntry):
        super().__init__()
        self.entry = entry

    @property
    def schema(self) -> Schema:
        return self.entry.schema

    def describe(self):
        n = len(self.entry.frames or [])
        return f"TpuCachedScanExec[{n} batches, " \
               f"{self.entry.cached_bytes} bytes]"

    def do_execute(self) -> Iterator[ColumnarBatch]:
        for blob in self.entry.frames or []:
            yield frame_to_batch(blob, self.entry.schema)
