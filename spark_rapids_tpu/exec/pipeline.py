"""Bounded asynchronous pipeline driver.

The sequential pull loop (``list(exec_plan.execute())``) serializes
every stage of a query against the host: the reader decodes a file,
uploads it, dispatches the XLA stage, then ``int(n)``-style syncs block
until the device answers before the next batch even starts decoding.
On a tunnel-attached TPU each of those round trips is milliseconds of
dead pipeline (the r05 bench's 10x group-by gap).

``pipelined(iterator, depth)`` re-drives the same operator iterator
from a worker thread with a bounded in-flight queue:

* the worker pulls batches — running reader host decode
  (io/multifile.py's MULTITHREADED pool), host->device upload
  (columnar ``jnp.asarray``) and XLA dispatch (async by construction)
  — while the consuming thread drains already-produced batches;
* every in-flight batch is registered in the spill catalog before it
  enters the queue, so backpressure is HBM-aware: a stalled consumer
  never pins more than ``depth`` batches and the catalog may demote
  them to host under memory pressure;
* ``depth`` bounds the queue (``spark.rapids.tpu.pipeline.depth``,
  default 2): the worker blocks on a full queue, the consumer on an
  empty one;
* exceptions on the worker re-raise on the driving thread with their
  original traceback and injection context intact — the recovery
  ladder (robustness/driver.py) classifies them exactly as it would
  sequential faults.  The worker adopts the driving thread's identity
  for fault-injection rules (robustness/inject.py) and for the
  host-sync / retry attribution views, so per-query accounting and
  thread-scoped chaos rules keep working.

Batch identity is preserved: the pipelined iterator yields the same
batches in the same order as the sequential loop — it is a pure
overlap optimization (tier-1 runs it on CPU with identical results).
"""

from __future__ import annotations

import contextlib
import queue
import threading
import time
from typing import Iterator, Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch

_DONE = object()


@contextlib.contextmanager
def worker_attribution(owner_ident: int, stats=None):
    """Make the calling thread act as ``owner_ident`` for every
    thread-attributed registry at once: fault-injection rules
    (robustness/inject.py), host-sync accounting and upload timing
    (utils/hostsync.py), and OOM-retry counters (memory/retry.py).

    The single place that knows the full adoption set — any future
    worker thread (another pipeline stage, a reader pool that runs
    engine code) should use this rather than hand-rolling the adopt/
    release pairs, where forgetting one silently mis-attributes
    metrics or stops thread-scoped chaos rules from firing."""
    from spark_rapids_tpu.memory.retry import retry_metrics
    from spark_rapids_tpu.robustness import inject, watchdog
    from spark_rapids_tpu.serving import context as qcontext
    from spark_rapids_tpu.utils import hostsync
    inject.adopt_thread(owner_ident)
    watchdog.adopt_thread(owner_ident)
    qcontext.adopt_thread(owner_ident)
    hostsync.host_sync_metrics.adopt(owner_ident)
    retry_metrics.adopt(owner_ident)
    if stats is not None:
        hostsync.watch_uploads(stats)
    try:
        yield
    finally:
        if stats is not None:
            hostsync.unwatch_uploads()
        retry_metrics.release()
        hostsync.host_sync_metrics.release()
        qcontext.release_thread()
        watchdog.release_thread()
        inject.release_thread()


def disown_worker(ident: int) -> None:
    """Sever a worker thread's adopted identity in EVERY registry
    worker_attribution enrolled it in — the counterpart operation,
    invoked by a driver abandoning a wedged worker.  The zombie must
    not consume the driver's next attempt's cancellation token or
    rule budgets, nor mis-attribute its dying syncs/retries into the
    next query's thread-local deltas."""
    from spark_rapids_tpu.memory.retry import retry_metrics
    from spark_rapids_tpu.robustness import inject, watchdog
    from spark_rapids_tpu.serving import context as qcontext
    from spark_rapids_tpu.utils import hostsync
    watchdog.disown(ident)
    inject.disown(ident)
    qcontext.disown(ident)
    hostsync.host_sync_metrics.disown(ident)
    retry_metrics.disown(ident)


class PipelineStats:
    """One pipelined drive's observability counters.

    ``fill_ratio``: mean queue occupancy (0..1) sampled at each consumer
    get — 1.0 means the worker always had a batch ready (compute-bound
    consumer), ~0 means the consumer starved (producer-bound query).
    ``host_sync_count``: device->host syncs attributed to the query
    while the pipeline ran (utils/hostsync.py).  ``upload_overlap_ms``:
    host->device transfer time spent on the worker thread — time the
    sequential loop would have serialized against consumption.
    """

    def __init__(self, depth: int):
        self.depth = depth
        self.batches = 0
        self.gets = 0
        self.fill_sum = 0.0
        self.upload_overlap_ns = 0
        self.host_sync_count = 0
        self.wait_ns = 0  # consumer time blocked on an empty queue

    @property
    def fill_ratio(self) -> float:
        return (self.fill_sum / self.gets) if self.gets else 0.0

    def as_dict(self) -> dict:
        from spark_rapids_tpu.exec.base import (
            HOST_SYNC_COUNT, PIPELINE_FILL_RATIO, UPLOAD_OVERLAP_MS)
        return {
            "depth": self.depth,
            "batches": self.batches,
            PIPELINE_FILL_RATIO: round(self.fill_ratio, 4),
            HOST_SYNC_COUNT: self.host_sync_count,
            UPLOAD_OVERLAP_MS: round(self.upload_overlap_ns / 1e6, 3),
            "consumerWaitMs": round(self.wait_ns / 1e6, 3),
        }


def _put_final(q: "queue.Queue", stop: threading.Event, item) -> None:
    """Deliver the worker's terminal item (sentinel or exception)
    without deadlocking against a departed consumer: on a full queue,
    keep trying until space frees or the consumer signals stop (its
    shutdown drain then makes room or makes delivery moot)."""
    while True:
        try:
            q.put(item, timeout=0.1)
            return
        except queue.Full:
            if stop.is_set():
                return


def pipelined(source: Iterator[ColumnarBatch], depth: int,
              catalog=None,
              stats: Optional[PipelineStats] = None,
              semaphore=None) -> Iterator[ColumnarBatch]:
    """Drive ``source`` from a worker thread with ``depth`` batches of
    lookahead.  Yields the identical batch sequence.

    The returned generator owns the worker: closing it early (LIMIT
    queries, an exception in the consumer) stops the worker at its next
    queue put, closes every still-queued spill registration, and joins
    the thread — no leaked registrations, no orphan threads."""
    from spark_rapids_tpu.memory.spill import (
        ACTIVE_ON_DECK_PRIORITY, default_catalog)
    from spark_rapids_tpu.utils.hostsync import host_sync_metrics

    depth = max(int(depth), 1)
    catalog = catalog or default_catalog()
    stats = stats or PipelineStats(depth)
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()
    owner_ident = threading.get_ident()
    sync0 = host_sync_metrics.snapshot_local()

    def worker() -> None:
        # act as the driving thread for injection rules and metric
        # attribution (worker_attribution); host->device uploads
        # anywhere in the operator chain (columnar/column.py
        # materialization) time themselves into stats while this
        # thread runs the iterator — that is work the sequential loop
        # would have serialized against consumption.
        from spark_rapids_tpu.robustness import watchdog
        try:
            with worker_attribution(owner_ident, stats):
                try:
                    # heartbeat section: the deadline measures SILENCE
                    # (time since the last produced batch / queue
                    # wait), so a worker wedged inside the operator
                    # iterator trips while a merely busy one never
                    # does.  The trip cancels the DRIVING thread's
                    # token (this thread adopted its identity), which
                    # the consumer's queue-wait checkpoint delivers as
                    # a retryable TimeoutFault.
                    with watchdog.section("pipeline.worker") as beat:
                        for batch in source:
                            if beat is not None:
                                beat.beat()
                            if stop.is_set():
                                break
                            # registration charges the DEVICE budget
                            # with the batch PLUS any transient wire
                            # reservation (a shuffle-received batch's
                            # packed exchange payload,
                            # memory/spill.py SpillableHandle), so
                            # depth x footprint backpressure can't
                            # undercount mid-exchange; the handle
                            # consumes the reservation, releasing it
                            # when the batch leaves DEVICE
                            handle = catalog.register(
                                batch, ACTIVE_ON_DECK_PRIORITY)
                            while not stop.is_set():
                                if beat is not None:
                                    # backpressure (full queue) is a
                                    # slow consumer, not a hang
                                    beat.beat()
                                try:
                                    q.put(handle, timeout=0.1)
                                    break
                                except queue.Full:
                                    continue
                            else:
                                handle.close()
                                break
                    _put_final(q, stop, _DONE)
                except BaseException as exc:  # noqa: BLE001 — re-raised
                    _put_final(q, stop, exc)
        finally:
            if semaphore is not None:
                # the worker is the "task thread": any admission it
                # holds (UDF execs re-admit per batch, TpuSemaphore)
                # must not die with it
                semaphore.release_all_held()

    from spark_rapids_tpu.robustness import watchdog

    t = threading.Thread(target=worker, name="tpu-pipeline", daemon=True)
    t.start()
    try:
        while True:
            stats.fill_sum += min(q.qsize() / depth, 1.0)
            stats.gets += 1
            t0 = time.perf_counter_ns()
            # the queue wait is the driving thread's cancellation
            # checkpoint: when the watchdog trips (wedged worker, query
            # deadline) the TimeoutFault is raised HERE instead of
            # blocking forever on a queue no one will ever fill.  It is
            # also a stage boundary: any async exchange this thread
            # still has in flight (a distributed sub-execution feeding
            # this pipeline) verifies here, after downstream work was
            # dispatched — the exchange/compute-overlap contract
            # (parallel/exchange_async.py)
            from spark_rapids_tpu.parallel.exchange_async import (
                resolve_pending)
            while True:
                watchdog.checkpoint()
                resolve_pending()
                try:
                    item = q.get(timeout=0.05)
                    break
                except queue.Empty:
                    continue
            stats.wait_ns += time.perf_counter_ns() - t0
            if item is _DONE:
                break
            if isinstance(item, BaseException):
                # original traceback (and injection point/note for
                # InjectedFaults) intact: the recovery ladder classifies
                # the re-raise exactly like a sequential fault
                raise item
            try:
                batch = item.materialize()
            finally:
                # close even when materialize raises (disk unspill
                # failure): a dequeued handle is no longer in the
                # queue, so the shutdown drain cannot reach it —
                # without this the dead registration and its spill
                # file would leak for the session lifetime
                item.close()
            stats.batches += 1
            yield batch
    finally:
        stop.set()
        # drain whatever the worker had queued so spill registrations
        # never leak on early close; keep draining until the worker is
        # gone (it may slip one more item in between drain and join)
        def drain() -> None:
            while True:
                try:
                    leftover = q.get_nowait()
                except queue.Empty:
                    return
                if leftover is not _DONE and \
                        not isinstance(leftover, BaseException):
                    leftover.close()

        # bound the join: waiting forever on a WEDGED worker would
        # re-create the very hang the watchdog just converted into a
        # fault.  A healthy worker exits within the grace period; an
        # abandoned one is a daemon that self-cleans when it unwedges
        # (sees ``stop`` set, closes its in-flight registration, drops
        # its terminal put — the drain above already made delivery
        # moot).
        grace_until = time.monotonic() + 1.0
        while t.is_alive() and time.monotonic() < grace_until:
            drain()
            t.join(timeout=0.05)
        drain()
        if t.is_alive() and t.ident is not None:
            # sever the zombie's adopted identity everywhere: when it
            # unwedges it must not consume the driver's NEXT attempt's
            # one-shot cancellation token, its armed rule budgets, or
            # its per-thread metric attribution
            disown_worker(t.ident)
        stats.host_sync_count = \
            host_sync_metrics.snapshot_local() - sync0
