"""Hash-aggregate physical operator (sort-based under the hood).

Pipeline mirrors the reference's GpuHashAggregateIterator (aggregate.scala:
184-209): per input batch run the *update* aggregation (fused with key/child
expression evaluation in one XLA computation), cache the partial result
batches, then concatenate on device and run the *merge* aggregation +
finalization.  The reference's sort-based fallback is unnecessary: the primary
algorithm here already IS sort+segment-reduce, which degrades gracefully with
cardinality instead of blowing up a hash table.

String group keys are dictionary-encoded on the host per operator instance
(codes are stable across batches) — the acknowledged round-1 compromise for
strings under XLA static shapes (SURVEY.md section 7 "hard parts").
"""

from __future__ import annotations

import functools
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.batch import ColumnarBatch, empty_batch
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.exec.base import (
    AGG_TIME, CONCAT_TIME, NUM_INPUT_BATCHES, NUM_INPUT_ROWS, Schema, TpuExec)
from spark_rapids_tpu.ops import aggregates as agg
from spark_rapids_tpu.ops.compiler import (
    StageFn, batch_to_flat, capacity_of, colvals_to_columns, flat_to_colvals)
from spark_rapids_tpu.ops.concat import concat_batches
from spark_rapids_tpu.ops.expressions import ColVal, EmitContext, Expression
from spark_rapids_tpu.plan.logical import AggregateExpression


class _StringKeyEncoder:
    """Host dictionary encoder with codes stable across batches.

    Vectorized: per batch the Python-level work is O(distinct values) via
    ``ops.dictionary`` (round 1 looped over every row, which dominated the
    runtime for string group-by keys)."""

    def __init__(self):
        self.codes: Dict[Optional[str], int] = {}
        self.values: List[Optional[str]] = []

    def encode(self, col: Column) -> Column:
        from spark_rapids_tpu.ops.dictionary import dict_encode_stable
        out = dict_encode_stable(col, self.codes, self.values).astype(
            np.int32)
        return Column.from_numpy(out, dtype=dts.INT32, capacity=col.capacity)

    def decode(self, col: Column) -> Column:
        codes = col.to_numpy()
        return Column.from_strings([self.values[c] for c in codes],
                                   capacity=col.capacity)


def _merge_kind(update_kind: str) -> str:
    return {"sum": "sum", "count": "sum", "min": "min", "max": "max",
            "first": "first", "last": "last"}[update_kind]


@functools.lru_cache(maxsize=None)
def _grouped_kernel(kinds: Tuple[str, ...], nkeys: int):
    """Group-by over pre-evaluated fixed-width (values, validity) columns."""

    @jax.jit
    def run(keys_flat, bufs_flat, nrows):
        capacity = keys_flat[0][0].shape[0]
        keys = [ColVal(None, v, val) for v, val in keys_flat]
        buf_inputs = [(k, ColVal(None, v, val))
                      for k, (v, val) in zip(kinds, bufs_flat)]
        out_keys, out_bufs, n = agg.groupby_aggregate(
            keys, buf_inputs, nrows, capacity)
        return ([(k.values, k.validity) for k in out_keys],
                [(b.values, b.validity) for b in out_bufs], n)

    return run


class TpuHashAggregateExec(TpuExec):
    def __init__(self, group_exprs: Sequence[Expression],
                 agg_exprs: Sequence[Tuple[str, AggregateExpression]],
                 child: TpuExec,
                 pre_filter: Optional[Expression] = None,
                 merge_chunk_rows: int = 1 << 22):
        """``pre_filter``: a fused upstream Filter condition (whole-stage
        fusion: predicate becomes a row mask inside the aggregation kernel —
        no compaction pass at all)."""
        super().__init__(child)
        self.merge_chunk_rows = merge_chunk_rows
        self.group_exprs = list(group_exprs)
        self.agg_exprs = list(agg_exprs)
        self.pre_filter = pre_filter
        self.funcs = [ae.func for _, ae in agg_exprs]
        self._register_metric(NUM_INPUT_ROWS)
        self._register_metric(NUM_INPUT_BATCHES)
        self._register_metric(AGG_TIME)
        self._register_metric(CONCAT_TIME)

        self._in_dtypes = [dt for _, dt in child.schema]
        self._single_pass = any(getattr(f, "single_pass", False)
                                for f in self.funcs)
        self._string_key_idx = [i for i, e in enumerate(self.group_exprs)
                                if e.dtype.is_string]
        self._encoders = {i: _StringKeyEncoder()
                          for i in self._string_key_idx}

        if self._single_pass:
            # collect aggregates: one grouped pass over the concatenated
            # input (no partial/merge pipeline); jitted kernel below
            from spark_rapids_tpu.ops.jit_cache import cached_jit
            sig = ("agg_single_pass",
                   tuple(dt.name for dt in self._in_dtypes),
                   tuple(e.cache_key() for e in self.group_exprs),
                   tuple(f.cache_key() for f in self.funcs),
                   self.pre_filter.cache_key()
                   if self.pre_filter is not None else None)
            self._single_fn = cached_jit(sig, lambda: self._single_kernel)
            return
        # buffer layout: per func, a slice of the flat buffer-column list
        self._buf_specs: List[agg.BufferSpec] = []
        self._buf_slices: List[slice] = []
        for f in self.funcs:
            specs = f.buffers()
            self._buf_slices.append(
                slice(len(self._buf_specs), len(self._buf_specs) + len(specs)))
            self._buf_specs.extend(specs)
        self._update_kinds = tuple(s.kind for s in self._buf_specs)
        self._merge_kinds = tuple(_merge_kind(k) for k in self._update_kinds)

        from spark_rapids_tpu.ops.jit_cache import cached_jit
        base_sig = (tuple(dt.name for dt in self._in_dtypes),
                    tuple(e.cache_key() for e in self.group_exprs),
                    tuple(f.cache_key() for f in self.funcs))
        if self._string_key_idx:
            # stage A evaluates keys + agg children; the group kernel runs in
            # stage B after host dictionary encoding of string keys
            pre_exprs = list(self.group_exprs) + \
                [f.child for f in self.funcs if f.child is not None]
            self._pre_fn = StageFn(pre_exprs, self._in_dtypes)
        else:
            self._pre_fn = None
            update_sig = ("agg_update",) + base_sig + (
                self.pre_filter.cache_key()
                if self.pre_filter is not None else None,)
            self._update_fn = cached_jit(update_sig,
                                         lambda: self._update_fused)
        # merge never evaluates pre_filter: exclude it so queries differing
        # only in filter constants share the merge executable
        self._merge_fn = cached_jit(("agg_merge",) + base_sig,
                                    lambda: self._merge)
        self._merge_partial_fn = cached_jit(
            ("agg_merge_partial",) + base_sig, lambda: self._merge_partial)

    # ------------------------------------------------------------------ plan --
    @property
    def child(self) -> TpuExec:
        return self.children[0]

    @property
    def schema(self) -> Schema:
        out = [(e.name, e.dtype) for e in self.group_exprs]
        out += [(name, ae.dtype) for name, ae in self.agg_exprs]
        return out

    def describe(self):
        return (f"TpuHashAggregateExec[keys="
                f"{[e.name for e in self.group_exprs]}, aggs="
                f"{[n for n, _ in self.agg_exprs]}]")

    @property
    def _partial_schema(self) -> Schema:
        keys = []
        for i, e in enumerate(self.group_exprs):
            dt = dts.INT32 if i in self._string_key_idx else e.dtype
            keys.append((f"_k{i}", dt))
        bufs = [(f"_b{j}", spec.dtype)
                for j, spec in enumerate(self._buf_specs)]
        return keys + bufs

    # ---------------------------------------------------------- update stage --
    def _eval_update_inputs(self, ctx: EmitContext) -> List[Tuple[str, ColVal]]:
        pairs: List[Tuple[str, ColVal]] = []
        for f in self.funcs:
            c = f.child.emit(ctx) if f.child is not None else None
            if c is not None and getattr(c.values, "ndim", 0) == 0 and \
                    c.offsets is None:
                c = ColVal(c.dtype,
                           jnp.broadcast_to(c.values, (ctx.capacity,)),
                           c.validity)
            for spec, cv in zip(f.buffers(), f.update_inputs(c, ctx.capacity)):
                pairs.append((spec.kind, cv))
        return pairs

    def _update_fused(self, flat_cols, nrows):
        """No string keys: key eval + buffer eval + group-by, one computation.

        A fused pre_filter predicate contributes a row mask — the whole
        filter+project+partial-agg stage is a single XLA program."""
        capacity = capacity_of(flat_cols)
        inputs = flat_to_colvals(flat_cols, self._in_dtypes)
        ctx = EmitContext(inputs, nrows, capacity)
        row_mask = None
        if self.pre_filter is not None:
            pred = self.pre_filter.emit(ctx)
            keep = pred.values
            if getattr(keep, "ndim", 0) == 0:
                keep = jnp.broadcast_to(keep, (capacity,))
            if pred.validity is not None:
                keep = jnp.logical_and(keep, pred.validity)
            row_mask = jnp.logical_and(keep, ctx.row_mask())
        keys = [e.emit(ctx) for e in self.group_exprs]
        buf_inputs = self._eval_update_inputs(ctx)
        if not keys:
            outs = agg.reduce_aggregate(buf_inputs, nrows, capacity,
                                        row_mask=row_mask)
            return ([], [(o.values, o.validity, o.offsets) for o in outs],
                    jnp.int32(1))
        out_keys, out_bufs, n = agg.groupby_aggregate(
            keys, buf_inputs, nrows, capacity, row_mask=row_mask)
        return ([(k.values, k.validity, k.offsets) for k in out_keys],
                [(b.values, b.validity, b.offsets) for b in out_bufs], n)

    def _partial_batches(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.memory.retry import with_retry
        names = [n for n, _ in self._partial_schema]
        dtypes = [dt for _, dt in self._partial_schema]

        def tallied():
            for batch in self.child.execute():
                self.metrics[NUM_INPUT_ROWS] += batch.nrows
                self.metrics[NUM_INPUT_BATCHES] += 1
                if batch.nrows:
                    yield batch

        def compute(batch):
            with self.timer(AGG_TIME):
                if self._string_key_idx:
                    return self._partial_with_string_keys(
                        batch, names, dtypes)
                key_flat, buf_flat, n = self._update_fn(
                    batch_to_flat(batch), jnp.int32(batch.nrows))
                # keyless reductions have statically one output row;
                # skip the device->host sync (it costs a full tunnel
                # round-trip per batch)
                n = 1 if not self.group_exprs else int(n)
                outs = [ColVal(dt, v, val, offs)
                        for dt, (v, val, offs) in
                        zip(dtypes, list(key_flat) + list(buf_flat))]
                cols = colvals_to_columns(outs, n, batch.capacity)
                return ColumnarBatch(dict(zip(names, cols)), n)

        yield from with_retry(tallied(), compute)

    def _partial_with_string_keys(self, batch, names, dtypes):
        nkeys = len(self.group_exprs)
        pre_cols = self._pre_fn(batch)
        key_cols, child_cols = pre_cols[:nkeys], pre_cols[nkeys:]
        enc_keys = [self._encoders[i].encode(c) if i in self._string_key_idx
                    else c for i, c in enumerate(key_cols)]
        child_iter = iter(child_cols)
        buf_inputs: List[Tuple[str, ColVal]] = []
        for f in self.funcs:
            cc = next(child_iter) if f.child is not None else None
            cv = None if cc is None else \
                ColVal(cc.dtype, cc.data, cc.validity, cc.offsets)
            for spec, bi in zip(f.buffers(),
                                f.update_inputs(cv, batch.capacity)):
                buf_inputs.append((spec.kind, bi))
        kernel = _grouped_kernel(self._update_kinds, nkeys)
        key_flat, buf_flat, n = kernel(
            [(c.data, c.validity) for c in enc_keys],
            [(c.values, c.validity) for _, c in buf_inputs],
            jnp.int32(batch.nrows))
        n = int(n)
        outs = [ColVal(dt, v, val) for dt, (v, val) in
                zip(dtypes, list(key_flat) + list(buf_flat))]
        cols = colvals_to_columns(outs, n, batch.capacity)
        return ColumnarBatch(dict(zip(names, cols)), n)

    # ------------------------------------------------------------ merge stage --
    def _merge_body(self, flat_cols, nrows):
        """Shared merge group-by/reduce over partial-schema columns."""
        dtypes = [dt for _, dt in self._partial_schema]
        nkeys = len(self.group_exprs)
        capacity = capacity_of(flat_cols)
        cols = flat_to_colvals(flat_cols, dtypes)
        keys, bufs = cols[:nkeys], cols[nkeys:]
        merge_inputs = [(k, c) for k, c in zip(self._merge_kinds, bufs)]
        if keys:
            return agg.groupby_aggregate(keys, merge_inputs, nrows,
                                         capacity)
        out_bufs = agg.reduce_aggregate(merge_inputs, nrows, capacity)
        return [], out_bufs, jnp.int32(1)

    def _merge(self, flat_cols, nrows):
        out_keys, out_bufs, n = self._merge_body(flat_cols, nrows)
        results = [f.finalize(out_bufs[sl])
                   for f, sl in zip(self.funcs, self._buf_slices)]
        return ([(k.values, k.validity, k.offsets) for k in out_keys],
                [(r.values, r.validity, r.offsets) for r in results], n)

    def _merge_partial(self, flat_cols, nrows):
        """Merge partial batches into one partial batch (no finalize) —
        the tree-reduction step bounding the final concat (the reference's
        sort-based fallback serves the same purpose, aggregate.scala:
        184-197: never require every partial in memory at once)."""
        out_keys, out_bufs, n = self._merge_body(flat_cols, nrows)
        return ([(k.values, k.validity, k.offsets) for k in out_keys],
                [(b.values, b.validity, b.offsets) for b in out_bufs], n)

    def _tree_merge(self, handles, catalog):
        """Reduce partial handles until their total rows fit one merge
        chunk; each step merges >=2 partials into one (still-partial)
        spillable batch, so the device never holds every partial."""
        names = [n for n, _ in self._partial_schema]
        dtypes = [dt for _, dt in self._partial_schema]
        chunk = self.merge_chunk_rows
        while len(handles) > 1 and \
                sum(h.nrows for h in handles) > chunk:
            group = []
            rows = 0
            while handles and (len(group) < 2 or
                               rows + handles[0].nrows <= chunk):
                h = handles.pop(0)
                group.append(h)
                rows += h.nrows
                if rows >= chunk and len(group) >= 2:
                    break
            with self.timer(CONCAT_TIME):
                merged_in = concat_batches([h.materialize()
                                            for h in group])
            for h in group:
                h.close()
            with self.timer(AGG_TIME):
                key_flat, buf_flat, n = self._merge_partial_fn(
                    batch_to_flat(merged_in), jnp.int32(merged_in.nrows))
                n = 1 if not self.group_exprs else int(n)
            outs = [ColVal(dt, v, val, offs)
                    for dt, (v, val, offs) in
                    zip(dtypes, list(key_flat) + list(buf_flat))]
            # compact to the live row count before registering: n is
            # already concrete here, and keeping the concat capacity
            # would make padding, not rows, dominate the spill bytes
            from spark_rapids_tpu.columnar.column import bucket_capacity
            out_cap = min(bucket_capacity(n), merged_in.capacity)
            if out_cap < merged_in.capacity:
                outs = [ColVal(c.dtype, c.values[:out_cap],
                               None if c.validity is None
                               else c.validity[:out_cap], c.offsets)
                        for c in outs]
            cols = colvals_to_columns(outs, n, out_cap)
            handles.append(
                catalog.register(ColumnarBatch(dict(zip(names, cols)), n)))
        return handles

    def _single_kernel(self, flat_cols, nrows):
        """Grouped pass mixing collect arrays with regular reductions."""
        capacity = capacity_of(flat_cols)
        inputs = flat_to_colvals(flat_cols, self._in_dtypes)
        ctx = EmitContext(inputs, nrows, capacity)
        row_mask = None
        if self.pre_filter is not None:
            pred = self.pre_filter.emit(ctx)
            keep = pred.values
            if pred.validity is not None:
                keep = jnp.logical_and(keep, pred.validity)
            row_mask = jnp.logical_and(keep, ctx.row_mask())
        keys = [e.emit(ctx) for e in self.group_exprs]
        keyless = not keys
        if keyless:
            # constant key -> exactly one group over the live rows; the
            # key column is dropped from the output below
            keys = [ColVal(dts.INT64,
                           jnp.zeros(capacity, dtype=jnp.int64))]
        collect_inputs = []
        buffer_inputs = []
        layout = []  # ("collect", idx) | ("buf", slice) per func
        for f in self.funcs:
            c = f.child.emit(ctx) if f.child is not None else None
            if c is not None and getattr(c.values, "ndim", 0) == 0 and                     c.offsets is None:
                c = ColVal(c.dtype,
                           jnp.broadcast_to(c.values, (capacity,)),
                           c.validity)
            if getattr(f, "single_pass", False):
                layout.append(("collect", len(collect_inputs)))
                collect_inputs.append((c, f.dedup))
            else:
                start = len(buffer_inputs)
                for spec, cv in zip(f.buffers(),
                                    f.update_inputs(c, capacity)):
                    buffer_inputs.append((spec.kind, cv))
                layout.append(("buf", slice(start, len(buffer_inputs))))
        out_keys, out_bufs, collects, n = agg.groupby_collect(
            keys, collect_inputs, nrows, capacity,
            buffer_inputs=buffer_inputs, row_mask=row_mask)
        if keyless:
            out_keys = []
        results = []
        for f, (kind, ref) in zip(self.funcs, layout):
            if kind == "collect":
                results.append(collects[ref])
            else:
                results.append(f.finalize(out_bufs[ref]))
        outs = list(out_keys) + results
        return ([(o.values, o.validity, o.offsets) for o in outs], n)

    def _single_pass_execute(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.memory.spill import default_catalog
        catalog = default_catalog()
        handles = []
        for b in self.child.execute():
            self.metrics[NUM_INPUT_ROWS] += b.nrows
            self.metrics[NUM_INPUT_BATCHES] += 1
            handles.append(catalog.register(b))
        if not handles:
            if self.group_exprs:
                return
            # Spark keyless aggregation of empty input is ONE row:
            # empty arrays for collects, identity for the rest
            yield self._keyless_empty_result()
            return
        batches = [h.materialize() for h in handles]
        with self.timer(CONCAT_TIME):
            merged = concat_batches(batches)
        for h in handles:
            h.close()
        with self.timer(AGG_TIME):
            out_flat, n = self._single_fn(batch_to_flat(merged),
                                          jnp.int32(merged.nrows))
            n = int(n)
        if n == 0 and not self.group_exprs:
            yield self._keyless_empty_result()
            return
        names = [nm for nm, _ in self.schema]
        dtypes = [dt for _, dt in self.schema]
        outs = [ColVal(dt, v, val, offs)
                for dt, (v, val, offs) in zip(dtypes, out_flat)]
        cols = colvals_to_columns(outs, n, merged.capacity)
        yield ColumnarBatch(dict(zip(names, cols)), n)

    def _keyless_empty_result(self) -> ColumnarBatch:
        cols = {}
        for (name, dt), f in zip(self.schema, self.funcs):
            if getattr(f, "single_pass", False):
                cols[name] = Column.from_arrays([[]], dt.element)
            elif f.name == "count":
                cols[name] = Column.from_numpy(
                    np.zeros(1, dtype=np.int64), dtype=dts.INT64)
            else:
                cols[name] = Column.from_numpy(
                    np.zeros(1, dtype=dt.storage), dtype=dt,
                    validity=np.array([False]))
        return ColumnarBatch(cols, 1)

    def do_execute(self) -> Iterator[ColumnarBatch]:
        if self._single_pass:
            yield from self._single_pass_execute()
            return
        from spark_rapids_tpu.memory.spill import default_catalog
        catalog = default_catalog()
        # cache partials as spillable batches (the reference caches
        # SpillableColumnarBatch between update and merge, aggregate.scala)
        handles = [catalog.register(b) for b in self._partial_batches()]
        nkeys = len(self.group_exprs)
        if not handles:
            if nkeys:
                return
            partials = [empty_batch(self._partial_schema)]
        else:
            handles = self._tree_merge(handles, catalog)
            partials = [h.materialize() for h in handles]
        with self.timer(CONCAT_TIME):
            merged_in = concat_batches(partials)
        for h in handles:
            h.close()
        with self.timer(AGG_TIME):
            key_flat, res_flat, n = self._merge_fn(
                batch_to_flat(merged_in), jnp.int32(merged_in.nrows))
            n = 1 if not self.group_exprs else int(n)
        out_names = [name for name, _ in self.schema]
        outs: List[ColVal] = []
        for i, (e, (v, val, offs)) in enumerate(zip(self.group_exprs,
                                                    key_flat)):
            dt = dts.INT32 if i in self._string_key_idx else e.dtype
            outs.append(ColVal(dt, v, val, offs))
        for (name, ae), (v, val, offs) in zip(self.agg_exprs, res_flat):
            outs.append(ColVal(ae.dtype, v, val, offs))
        cols = colvals_to_columns(outs, n, merged_in.capacity)
        for i in self._string_key_idx:
            cols[i] = self._encoders[i].decode(cols[i])
        yield ColumnarBatch(dict(zip(out_names, cols)), n)
