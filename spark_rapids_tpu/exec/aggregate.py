"""Hash-aggregate physical operator (sort-based under the hood).

Pipeline mirrors the reference's GpuHashAggregateIterator (aggregate.scala:
184-209): per input batch run the *update* aggregation (fused with key/child
expression evaluation in one XLA computation), cache the partial result
batches, then concatenate on device and run the *merge* aggregation +
finalization.  The reference's sort-based fallback is unnecessary: the primary
algorithm here already IS sort+segment-reduce, which degrades gracefully with
cardinality instead of blowing up a hash table.

String group keys are dictionary-encoded on the host per operator instance
(codes are stable across batches) — the acknowledged round-1 compromise for
strings under XLA static shapes (SURVEY.md section 7 "hard parts").
"""

from __future__ import annotations

import functools
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.batch import ColumnarBatch, empty_batch
from spark_rapids_tpu.columnar.column import Column, RowCount
from spark_rapids_tpu.utils import hostsync
from spark_rapids_tpu.exec.base import (
    AGG_TIME, CONCAT_TIME, NUM_INPUT_BATCHES, NUM_INPUT_ROWS, Schema, TpuExec)
from spark_rapids_tpu.ops import aggregates as agg
from spark_rapids_tpu.ops.compiler import (
    StageFn, batch_to_flat, capacity_of, colvals_to_columns, flat_to_colvals,
    param_args, params_dict)
from spark_rapids_tpu.ops.concat import concat_batches
from spark_rapids_tpu.ops.expressions import (
    Alias, BoundReference, ColVal, EmitContext, Expression,
    collect_param_slots)
from spark_rapids_tpu.plan.logical import AggregateExpression


class _StringKeyEncoder:
    """Host dictionary encoder with codes stable across batches.

    Vectorized: per batch the Python-level work is O(distinct values) via
    ``ops.dictionary`` (round 1 looped over every row, which dominated the
    runtime for string group-by keys)."""

    def __init__(self):
        self.codes: Dict[Optional[str], int] = {}
        self.values: List[Optional[str]] = []

    def encode(self, col: Column) -> Column:
        from spark_rapids_tpu.ops.dictionary import dict_encode_stable
        out = dict_encode_stable(col, self.codes, self.values).astype(
            np.int32)
        return Column.from_numpy(out, dtype=dts.INT32, capacity=col.capacity)

    def decode(self, col: Column) -> Column:
        codes = col.to_numpy()
        return Column.from_strings([self.values[c] for c in codes],
                                   capacity=col.capacity)


from spark_rapids_tpu.ops.aggregates import merge_kind as _merge_kind  # noqa: E402


def _collect_bound_ordinals(e: Expression, out: set) -> None:
    if isinstance(e, BoundReference):
        out.add(e.ordinal)
    for c in e.children:
        _collect_bound_ordinals(c, out)


@functools.lru_cache(maxsize=None)
def _grouped_kernel(kinds: Tuple[str, ...], nkeys: int):
    """Group-by over pre-evaluated fixed-width (values, validity) columns."""

    @jax.jit
    def run(keys_flat, bufs_flat, nrows):
        capacity = keys_flat[0][0].shape[0]
        keys = [ColVal(None, v, val) for v, val in keys_flat]
        buf_inputs = [(k, ColVal(None, v, val))
                      for k, (v, val) in zip(kinds, bufs_flat)]
        out_keys, out_bufs, n = agg.groupby_aggregate(
            keys, buf_inputs, nrows, capacity)
        return ([(k.values, k.validity) for k in out_keys],
                [(b.values, b.validity) for b in out_bufs], n)

    return run


@functools.lru_cache(maxsize=None)
def _keyless_kernel(kinds: Tuple[str, ...]):
    """Grand-total reduction over pre-evaluated buffer columns (the
    staged path's keyless case, e.g. SELECT min(s))."""

    @jax.jit
    def run(bufs_flat, nrows):
        capacity = bufs_flat[0][0].shape[0]
        buf_inputs = [(k, ColVal(None, v, val))
                      for k, (v, val) in zip(kinds, bufs_flat)]
        outs = agg.reduce_aggregate(buf_inputs, nrows, capacity)
        return [(o.values, o.validity) for o in outs]

    return run


@functools.lru_cache(maxsize=None)
def _coded_kernel(kinds: Tuple[str, ...], k_bucket: int):
    """Sort-free radix-coded group-by (stage B when the key-space
    product fits ``k_bucket`` slots) — the hash-aggregation regime of
    the reference (aggregate.scala:184-209), realized as direct
    addressing + segment reduce."""

    @jax.jit
    def run(keys_flat, bufs_flat, mins, slot_ranges, mask):
        capacity = keys_flat[0][0].shape[0]
        keys = [ColVal(None, v, val) for v, val in keys_flat]
        buf_inputs = [(k, ColVal(None, v, val))
                      for k, (v, val) in zip(kinds, bufs_flat)]
        out_keys, out_bufs, n = agg.groupby_aggregate_coded(
            keys, buf_inputs, jnp.int32(0), capacity, mins, slot_ranges,
            k_bucket, row_mask=mask)
        return ([(k.values, k.validity) for k in out_keys],
                [(b.values, b.validity) for b in out_bufs], n)

    return run


def _pow2_bucket(n: int) -> int:
    from spark_rapids_tpu.columnar.column import bucket_capacity
    return bucket_capacity(n, minimum=64)


@functools.lru_cache(maxsize=None)
def _probe_kernel(nkeys: int):
    """Key-range probe over pre-evaluated key columns (string path and
    merge stage, where keys already exist as columns)."""

    @jax.jit
    def run(keys_flat, nrows):
        capacity = keys_flat[0][0].shape[0]
        keys = [ColVal(None, v, val) for v, val in keys_flat]
        live = jnp.arange(capacity, dtype=jnp.int32) < nrows
        return agg.key_range_probe(keys, live)

    return run


class TpuHashAggregateExec(TpuExec):
    ephemeral_output = True

    def __init__(self, group_exprs: Sequence[Expression],
                 agg_exprs: Sequence[Tuple[str, AggregateExpression]],
                 child: TpuExec,
                 pre_filter: Optional[Expression] = None,
                 merge_chunk_rows: int = 1 << 22,
                 defer_syncs: bool = True,
                 spec_slots: int = 4096,
                 encoded_exec: bool = False,
                 max_dict_size: int = (1 << 31) - 1):
        """``pre_filter``: a fused upstream Filter condition (whole-stage
        fusion: predicate becomes a row mask inside the aggregation kernel —
        no compaction pass at all).

        ``defer_syncs``: carry per-batch group counts as device-resident
        ``RowCount``s and dispatch the coded path speculatively
        (``spec_slots`` slots, one sync per batch instead of
        probe+count), so XLA dispatch never serializes against the host.
        ``defer_syncs=False`` restores the eager two-pass sequential
        behavior (the baseline tests/test_pipeline.py measures against).

        ``encoded_exec``: encoded execution (ISSUE 11) — string group
        keys that are bare input references dictionary-encode to stable
        i32 codes BEFORE the kernels, so the whole
        filter+project+partial-aggregate stage runs the fully fused
        (speculative coded) path and strings materialize only at the
        final key decode.  Shapes the encoder cannot prove
        equality-faithful (computed keys, a key column consumed by any
        other expression, string min/max buffers) silently keep the
        decoded host-dictionary path.  A dictionary outgrowing
        ``max_dict_size`` latches encoded execution off on the session
        and raises a retryable EncodingOverflowFault (the re-planned
        attempt runs decoded — exact results)."""
        super().__init__(child)
        self.merge_chunk_rows = merge_chunk_rows
        self.defer_syncs = defer_syncs
        self.spec_slots = spec_slots
        self._spec_misses = 0
        self.group_exprs = list(group_exprs)
        self.agg_exprs = list(agg_exprs)
        # fused upstream predicates, BOTTOM-FIRST chain order: each
        # conjunct's ANSI checks are masked by the conjuncts below it
        # (_pre_filter_mask — the FilterStageFn discipline)
        self.pre_filters = list(pre_filter) if isinstance(
            pre_filter, (list, tuple)) else (
            [pre_filter] if pre_filter is not None else [])
        self._pre_sig = tuple(c.cache_key() for c in self.pre_filters) \
            if self.pre_filters else None
        self.funcs = [ae.func for _, ae in agg_exprs]
        self._register_metric(NUM_INPUT_ROWS)
        self._register_metric(NUM_INPUT_BATCHES)
        self._register_metric(AGG_TIME)
        self._register_metric(CONCAT_TIME)

        self._in_dtypes = [dt for _, dt in child.schema]
        self._merge_dicts: Dict[int, List] = {}
        self._single_pass = any(getattr(f, "single_pass", False)
                                for f in self.funcs)
        self._string_key_idx = [i for i, e in enumerate(self.group_exprs)
                                if e.dtype.is_string]
        self._encoders = {i: _StringKeyEncoder()
                          for i in self._string_key_idx}
        # encoded execution state (set up below, after the buffer
        # layout is known): kernel-side group exprs default to the
        # logical ones; schema/decode always read self.group_exprs
        self._encoded_exec = False
        self._enc_ords: List[int] = []
        self._ord_encoders: Dict[int, _StringKeyEncoder] = {}
        self._kgroup: List[Expression] = list(self.group_exprs)
        self.max_dict_size = int(max_dict_size)
        # hoisted-literal slots across every kernel-evaluated expression
        # (keys, agg children, fused pre-filter conjuncts): the jitted
        # bodies take them as one trailing argument vector, so template
        # signatures (value-free ParamSlot cache keys) share executables
        # across literal bindings
        self._slots = collect_param_slots(
            list(self.group_exprs)
            + [f.child for f in self.funcs if f.child is not None]
            + self.pre_filters)

        if self._single_pass:
            # collect aggregates: one grouped pass over the concatenated
            # input (no partial/merge pipeline); jitted kernel below
            from spark_rapids_tpu.ops.jit_cache import cached_jit
            sig = ("agg_single_pass",
                   tuple(dt.name for dt in self._in_dtypes),
                   tuple(e.cache_key() for e in self.group_exprs),
                   tuple(f.cache_key() for f in self.funcs),
                   self._pre_sig)
            self._single_fn = cached_jit(sig, lambda: self._single_kernel)
            return
        # buffer layout: per func, a slice of the flat buffer-column list
        self._buf_specs: List[agg.BufferSpec] = []
        self._buf_slices: List[slice] = []
        for f in self.funcs:
            specs = f.buffers()
            self._buf_slices.append(
                slice(len(self._buf_specs), len(self._buf_specs) + len(specs)))
            self._buf_specs.extend(specs)
        self._update_kinds = tuple(s.kind for s in self._buf_specs)
        self._merge_kinds = tuple(_merge_kind(k) for k in self._update_kinds)
        # string-valued min/max/first/last buffers: batch-local
        # order-preserving dictionary codes on device, strings in the
        # partial batches (buffer position -> func index)
        self._string_buf_pos: Dict[int, int] = {
            sl.start: j for j, (f, sl) in
            enumerate(zip(self.funcs, self._buf_slices))
            if f.child is not None and f.child.dtype.is_string and
            f.name in ("min", "max", "first", "last")}

        if encoded_exec and self._string_key_idx and \
                not self._string_buf_pos:
            ords = self.encoded_key_ordinals(
                self.group_exprs,
                [f.child for f in self.funcs if f.child is not None]
                + self.pre_filters)
            if ords is not None:
                # rewrite: the kernels see the key columns as i32 codes
                # (stable across batches, nulls interned as a code that
                # decodes back to None) — the fused/speculative update
                # path applies; the decoded strings reappear only at
                # the final key decode in do_execute
                self._encoded_exec = True
                self._enc_ords = sorted(set(ords))
                self._ord_encoders = {o: _StringKeyEncoder()
                                      for o in self._enc_ords}
                for i, o in zip(self._string_key_idx, ords):
                    self._encoders[i] = self._ord_encoders[o]
                    e = self.group_exprs[i]
                    self._kgroup[i] = BoundReference(
                        o, dts.INT32, name=e.name, nullable=False)
                self._in_dtypes = [
                    dts.INT32 if j in self._enc_ords else dt
                    for j, dt in enumerate(self._in_dtypes)]
        if self.pre_filters and self._needs_string_stage:
            # planner invariant: a fused pre_filter never reaches the
            # two-stage string path (which cannot apply it) — the
            # planner either proves encoded eligibility or leaves the
            # chain unfused
            raise ValueError(
                "fused pre_filter with string keys/buffers requires "
                "encoded execution; plan the chain unfused instead")

        from spark_rapids_tpu.ops.jit_cache import cached_jit
        base_sig = (tuple(dt.name for dt in self._in_dtypes),
                    tuple(e.cache_key() for e in self._kgroup),
                    tuple(f.cache_key() for f in self.funcs))
        if self._encoded_exec:
            base_sig += (("encexec", tuple(self._enc_ords)),)
        self._base_sig = base_sig
        # coded (sort-free) dispatch: all keys fixed-width integral after
        # string dictionary encoding, all buffers fixed-width
        key_dts = [dts.INT32 if i in self._string_key_idx else e.dtype
                   for i, e in enumerate(self.group_exprs)]
        self._coded_eligible = bool(self.group_exprs) and \
            agg.coded_key_eligible(key_dts) and \
            not any(s.dtype.has_offsets for s in self._buf_specs)
        if self._needs_string_stage:
            # stage A evaluates keys + agg children; the group kernel runs in
            # stage B after host dictionary encoding of string keys /
            # string agg children
            pre_exprs = list(self.group_exprs) + \
                [f.child for f in self.funcs if f.child is not None]
            self._pre_fn = StageFn(pre_exprs, self._in_dtypes)
        else:
            self._pre_fn = None
            update_sig = ("agg_update",) + base_sig + (
                self._pre_sig,)
            self._update_fn = cached_jit(update_sig,
                                         lambda: self._update_fused)
            if self._coded_eligible:
                # stage A evaluates filter mask + key-range probe only
                # (one cheap pass); stage B re-evaluates keys/buffers
                # FUSED with the coded reduction, picked on the host from
                # the probed key-space size (falls back to _update_fn's
                # sort kernel when the space is too large)
                stage_a_sig = ("agg_stage_a",) + base_sig + (
                    self._pre_sig,)
                self._stage_a_fn = cached_jit(stage_a_sig,
                                              lambda: self._stage_a)
        # merge never evaluates pre_filter: exclude it so queries differing
        # only in filter constants share the merge executable
        self._merge_fn = cached_jit(("agg_merge",) + base_sig,
                                    lambda: self._merge)
        self._merge_partial_fn = cached_jit(
            ("agg_merge_partial",) + base_sig, lambda: self._merge_partial)

    # ------------------------------------------------------------------ plan --
    @property
    def child(self) -> TpuExec:
        return self.children[0]

    @property
    def schema(self) -> Schema:
        out = [(e.name, e.dtype) for e in self.group_exprs]
        out += [(name, ae.dtype) for name, ae in self.agg_exprs]
        return out

    def describe(self):
        enc = ", encoded" if self._encoded_exec else ""
        return (f"TpuHashAggregateExec[keys="
                f"{[e.name for e in self.group_exprs]}, aggs="
                f"{[n for n, _ in self.agg_exprs]}{enc}]")

    @property
    def _needs_string_stage(self) -> bool:
        """True when the two-stage (pre-eval + host dictionary) string
        path must run: string keys NOT rewritten to codes, or
        string-valued min/max/first/last buffers."""
        return ((bool(self._string_key_idx) and not self._encoded_exec)
                or bool(getattr(self, "_string_buf_pos", None)))

    @staticmethod
    def encoded_key_ordinals(group_exprs, consumers
                             ) -> Optional[List[int]]:
        """Input ordinals behind the string group keys when encoded
        execution is equality-faithful, else None.  Faithful means:
        every string key is a bare input reference (optionally
        aliased), and no other kernel consumer — non-string keys, agg
        children, fused predicates (``consumers``) — reads those
        columns, so replacing them with stable dense codes changes no
        evaluated value.  The SAME test gates the planner's fused-chain
        fold and the exec's own rewrite: they must not diverge."""
        ords: List[int] = []
        for e in group_exprs:
            if not e.dtype.is_string:
                continue
            inner = e.children[0] if isinstance(e, Alias) else e
            if not isinstance(inner, BoundReference):
                return None  # computed key: codes are not the value
            ords.append(inner.ordinal)
        if not ords:
            return None
        refs: set = set()
        for e in list(consumers) + [g for g in group_exprs
                                    if not g.dtype.is_string]:
            if e is not None:
                _collect_bound_ordinals(e, refs)
        if refs & set(ords):
            return None  # the column's BYTES are consumed elsewhere
        return ords

    def _encode_input_batch(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Replace encoded-ordinal string columns with stable i32 code
        columns (codes stable across batches via the per-ordinal
        encoder; null rows intern as their own code and decode back to
        None, so validity is folded into the code space).  The code
        Column carries its dictionary.  Python work is O(distinct per
        batch) — ops/dictionary vectorized encode."""
        names = list(batch.columns)
        cols = dict(batch.columns)
        for o in self._enc_ords:
            name = names[o]
            enc = self._ord_encoders[o]
            ncol = enc.encode(cols[name])
            if len(enc.values) > self.max_dict_size:
                self._latch_encoding_off(len(enc.values))
            ncol.dictionary = enc.values
            cols[name] = ncol
        return ColumnarBatch(cols, batch.row_count)

    def _latch_encoding_off(self, size: int) -> None:
        """Dictionary overflow: latch encoded execution off for the
        session and raise the retryable fault — the ladder's re-planned
        attempt takes the decoded path (exact results; codes already
        issued die with this attempt)."""
        from spark_rapids_tpu.api.session import TpuSession
        from spark_rapids_tpu.robustness.driver import record_degradation
        from spark_rapids_tpu.robustness.faults import (
            EncodingOverflowFault)
        s = TpuSession._active
        if s is not None:
            s.encoding_exec_latched = True
        err = EncodingOverflowFault(self.describe(), size,
                                    self.max_dict_size)
        record_degradation(s, err.kind, "encoded-exec-latched-off",
                           str(err))
        raise err

    @property
    def _partial_schema(self) -> Schema:
        keys = []
        for i, e in enumerate(self.group_exprs):
            dt = dts.INT32 if i in self._string_key_idx else e.dtype
            keys.append((f"_k{i}", dt))
        bufs = [(f"_b{j}", spec.dtype)
                for j, spec in enumerate(self._buf_specs)]
        return keys + bufs

    # ---------------------------------------------------------- update stage --
    def _eval_update_inputs(self, ctx: EmitContext) -> List[Tuple[str, ColVal]]:
        pairs: List[Tuple[str, ColVal]] = []
        for f in self.funcs:
            c = f.child.emit(ctx) if f.child is not None else None
            if c is not None and getattr(c.values, "ndim", 0) == 0 and \
                    c.offsets is None:
                c = ColVal(c.dtype,
                           jnp.broadcast_to(c.values, (ctx.capacity,)),
                           c.validity)
            for spec, cv in zip(f.buffers(), f.update_inputs(c, ctx.capacity)):
                pairs.append((spec.kind, cv))
        return pairs

    def _pre_filter_mask(self, ctx: EmitContext):
        """Row mask from the fused pre-filter conjuncts (bottom-first,
        progressive ANSI-check masking: each conjunct — and finally the
        keys/agg children — only checks rows the conjuncts below it
        kept, exactly the rows the unfused stages would have
        evaluated).  None when there is no fused filter."""
        from spark_rapids_tpu.ops.expressions import fold_conjuncts
        if not self.pre_filters:
            return None
        return fold_conjuncts(ctx, self.pre_filters)

    def _pargs(self):
        """Dispatch-time ParamSlot argument vector (empty when the
        operator's expressions carry no hoisted literals)."""
        return param_args(self._slots)

    def _update_fused(self, flat_cols, nrows, params=()):
        """No string keys: key eval + buffer eval + group-by, one computation.

        A fused pre_filter predicate contributes a row mask — the whole
        filter+project+partial-agg stage is a single XLA program."""
        capacity = capacity_of(flat_cols)
        inputs = flat_to_colvals(flat_cols, self._in_dtypes)
        ctx = EmitContext(inputs, nrows, capacity,
                          params=params_dict(self._slots, params))
        row_mask = self._pre_filter_mask(ctx)
        keys = [e.emit(ctx) for e in self._kgroup]
        buf_inputs = self._eval_update_inputs(ctx)
        if not keys:
            outs = agg.reduce_aggregate(buf_inputs, nrows, capacity,
                                        row_mask=row_mask)
            return ([], [(o.values, o.validity, o.offsets) for o in outs],
                    jnp.int32(1))
        out_keys, out_bufs, n = agg.groupby_aggregate(
            keys, buf_inputs, nrows, capacity, row_mask=row_mask)
        return ([(k.values, k.validity, k.offsets) for k in out_keys],
                [(b.values, b.validity, b.offsets) for b in out_bufs], n)

    def _stage_a(self, flat_cols, nrows, params=()):
        """Filter mask + key-range probe: the cheap pass whose scalars
        the host needs before picking stage B (coded path)."""
        capacity = capacity_of(flat_cols)
        inputs = flat_to_colvals(flat_cols, self._in_dtypes)
        ctx = EmitContext(inputs, nrows, capacity,
                          params=params_dict(self._slots, params))
        mask = self._pre_filter_mask(ctx)
        if mask is None:
            mask = ctx.row_mask()
        keys = [agg.widen_colval(e.emit(ctx), capacity)
                for e in self._kgroup]
        mins, maxs = agg.key_range_probe(keys, mask)
        return mask, mins, maxs

    def _coded_update(self, k_bucket: int):
        """Build the coded stage-B body (cached_jit per k_bucket): key
        and buffer expressions re-evaluate HERE, fused straight into the
        segment reductions — no materialized intermediate columns."""

        def run(flat_cols, nrows, mask, mins, slot_ranges, params=()):
            capacity = capacity_of(flat_cols)
            inputs = flat_to_colvals(flat_cols, self._in_dtypes)
            ctx = EmitContext(inputs, nrows, capacity,
                              params=params_dict(self._slots, params))
            if self.pre_filters:
                ctx.extra_check_mask = mask
            keys = [agg.widen_colval(e.emit(ctx), capacity)
                    for e in self._kgroup]
            buf_inputs = self._eval_update_inputs(ctx)
            out_keys, out_bufs, n = agg.groupby_aggregate_coded(
                keys, buf_inputs, nrows, capacity, mins, slot_ranges,
                k_bucket, row_mask=mask)
            return ([(k.values, k.validity) for k in out_keys],
                    [(b.values, b.validity) for b in out_bufs], n)

        return run

    def _coded_update_auto(self, k_bucket: int):
        """Speculative stage body (cached_jit per k_bucket): filter
        mask, key-range discovery, fit check AND the coded reduction in
        ONE XLA computation — the probe pass and its host round trip
        only ever happen on a speculation miss."""

        def run(flat_cols, nrows, params=()):
            capacity = capacity_of(flat_cols)
            inputs = flat_to_colvals(flat_cols, self._in_dtypes)
            ctx = EmitContext(inputs, nrows, capacity,
                              params=params_dict(self._slots, params))
            mask = self._pre_filter_mask(ctx)
            if mask is None:
                mask = ctx.row_mask()
            keys = [agg.widen_colval(e.emit(ctx), capacity)
                    for e in self._kgroup]
            buf_inputs = self._eval_update_inputs(ctx)
            out_keys, out_bufs, n, fits, mins, maxs = \
                agg.groupby_aggregate_coded_auto(
                    keys, buf_inputs, nrows, capacity, k_bucket,
                    row_mask=mask)
            return ([(k.values, k.validity) for k in out_keys],
                    [(b.values, b.validity) for b in out_bufs],
                    n, fits, mins, maxs, mask)

        return run

    def _coded_pick_host(self, mins_h, maxs_h):
        """Size the key space from host-resident probe results; None
        when the coded path does not apply."""
        mins_h = np.asarray(mins_h)
        maxs_h = np.asarray(maxs_h)
        pick = agg.coded_slot_ranges(mins_h, maxs_h)
        if pick is None:
            return None
        slots, total = pick
        return (_pow2_bucket(total),
                jnp.asarray(np.minimum(mins_h, maxs_h)),
                jnp.asarray(np.asarray(slots, dtype=np.int64)))

    def _sync_range(self, mins, maxs):
        """Sync the probe scalars (one batched transfer when syncs are
        deferred, the legacy two when not)."""
        if self.defer_syncs:
            return hostsync.fetch(mins, maxs)
        hostsync.count_sync(2)
        return np.asarray(mins), np.asarray(maxs)

    def _coded_pick(self, mins, maxs):
        """Sync the probe scalars and size the key space."""
        return self._coded_pick_host(*self._sync_range(mins, maxs))

    def _hash_pick_host(self, mins_h, maxs_h):
        """Size the hashed key space from host-resident probe results:
        the cap is only that the radix strides fit int64 (2^62), far
        past the coded path's materialized-directory bound.  None when
        a key column is non-radixable or the product overflows."""
        mins_h = np.asarray(mins_h)
        maxs_h = np.asarray(maxs_h)
        pick = agg.hashed_slot_ranges(mins_h, maxs_h)
        if pick is None:
            return None
        slots, _total = pick
        return (jnp.asarray(np.minimum(mins_h, maxs_h)),
                jnp.asarray(np.asarray(slots, dtype=np.int64)))

    def _hashed_update(self, table_slots: int):
        """Build the hashed stage-B body (cached_jit per table size):
        the same fused expression re-evaluation as the coded body, but
        the group directory is an open-addressing hash table over the
        radix code — used when the key space exceeds the coded cap."""

        def run(flat_cols, nrows, mask, mins, slot_ranges, params=()):
            capacity = capacity_of(flat_cols)
            inputs = flat_to_colvals(flat_cols, self._in_dtypes)
            ctx = EmitContext(inputs, nrows, capacity,
                              params=params_dict(self._slots, params))
            if self.pre_filters:
                ctx.extra_check_mask = mask
            keys = [agg.widen_colval(e.emit(ctx), capacity)
                    for e in self._kgroup]
            buf_inputs = self._eval_update_inputs(ctx)
            out_keys, out_bufs, n, ovf = agg.groupby_aggregate_hashed(
                keys, buf_inputs, nrows, capacity, mins, slot_ranges,
                table_slots, row_mask=mask)
            return ([(k.values, k.validity) for k in out_keys],
                    [(b.values, b.validity) for b in out_bufs], n, ovf)

        return run

    def _try_hashed(self, flat, nrows, mask, mins_h, maxs_h):
        """Attempt the hash-table stage B.  Returns ``(key_out,
        buf_out, n)`` or None — disabled, ineligible key space, or
        table overflow; the caller then runs the exact sort kernel, so
        rows are never dropped.  Overflow fallbacks leave a breadcrumb
        for the "fusible chain ran unfused" health-check family."""
        from spark_rapids_tpu.ops import pallas_kernels as pk
        enabled, table_slots = pk.hash_dispatch_conf()
        if not enabled:
            return None
        hp = self._hash_pick_host(mins_h, maxs_h)
        if hp is None:
            return None
        from spark_rapids_tpu.exec.fusion import fusion_metrics
        from spark_rapids_tpu.ops.jit_cache import cached_jit
        mins_d, slots_d = hp
        fn = cached_jit(
            ("agg_hashed_update", table_slots) + self._base_sig,
            lambda: self._hashed_update(table_slots))
        key_out, buf_out, n, ovf = fn(flat, nrows, mask, mins_d,
                                      slots_d, self._pargs())
        fusion_metrics.bump("hashKernelLaunches")
        if bool(hostsync.fetch(ovf)):
            fusion_metrics.bump("hashOverflowFallbacks")
            return None
        return key_out, buf_out, n

    def _wrap_count(self, n) -> RowCount:
        """Device group count -> RowCount; eager mode forces (and
        counts) the sync immediately, preserving the sequential
        baseline's behavior."""
        rc = RowCount(device=n)
        if not self.defer_syncs:
            int(rc)
        return rc

    def _partial_coded(self, batch, names, dtypes):
        from spark_rapids_tpu.ops.jit_cache import cached_jit
        flat = batch_to_flat(batch)
        nrows = batch.row_count.device_i32()
        # speculative single-pass dispatch: stop speculating after two
        # misses (the operator's key space clearly exceeds the bucket)
        spec_k = self.spec_slots if self.defer_syncs else 0
        if spec_k and self._spec_misses < 2:
            fn = cached_jit(
                ("agg_coded_auto", spec_k) + self._base_sig + (
                    self._pre_sig,),
                lambda: self._coded_update_auto(spec_k))
            key_out, buf_out, n, fits, mins, maxs, mask = fn(
                flat, nrows, self._pargs())
            fits_h, mins_h, maxs_h = hostsync.fetch(fits, mins, maxs)
            if bool(fits_h):
                outs = [ColVal(dt, v, val) for dt, (v, val) in
                        zip(dtypes, list(key_out) + list(buf_out))]
                out_cap = key_out[0][0].shape[0] if key_out else \
                    buf_out[0][0].shape[0]
                n_rc = self._wrap_count(n)
                cols = colvals_to_columns(outs, n_rc, out_cap)
                return ColumnarBatch(dict(zip(names, cols)), n_rc)
            self._spec_misses += 1
            pick = self._coded_pick_host(mins_h, maxs_h)
        else:
            mask, mins, maxs = self._stage_a_fn(flat, nrows, self._pargs())
            mins_h, maxs_h = self._sync_range(mins, maxs)
            pick = self._coded_pick_host(mins_h, maxs_h)
        if pick is None:
            # key space past the coded directory: the hash table next,
            # then (disabled/overflow) the fully fused sort kernel
            got = self._try_hashed(flat, nrows, mask, mins_h, maxs_h)
            if got is not None:
                key_out, buf_out, n = got
                n_rc = self._wrap_count(n)
                outs = [ColVal(dt, v, val) for dt, (v, val) in
                        zip(dtypes, list(key_out) + list(buf_out))]
                out_cap = key_out[0][0].shape[0] if key_out else \
                    buf_out[0][0].shape[0]
                cols = colvals_to_columns(outs, n_rc, out_cap)
                return ColumnarBatch(dict(zip(names, cols)), n_rc)
            key_flat, buf_flat, n = self._update_fn(flat, nrows,
                                                    self._pargs())
            n_rc = self._wrap_count(n)
            outs = [ColVal(dt, v, val, offs)
                    for dt, (v, val, offs) in
                    zip(dtypes, list(key_flat) + list(buf_flat))]
            cols = colvals_to_columns(outs, n_rc, batch.capacity)
            return ColumnarBatch(dict(zip(names, cols)), n_rc)
        k_bucket, mins_d, slots_d = pick
        fn = cached_jit(
            ("agg_coded_update", k_bucket) + self._base_sig,
            lambda: self._coded_update(k_bucket))
        key_out, buf_out, n = fn(flat, nrows, mask, mins_d, slots_d,
                                 self._pargs())
        n_rc = self._wrap_count(n)
        outs = [ColVal(dt, v, val) for dt, (v, val) in
                zip(dtypes, list(key_out) + list(buf_out))]
        out_cap = key_out[0][0].shape[0] if key_out else \
            buf_out[0][0].shape[0]
        cols = colvals_to_columns(outs, n_rc, out_cap)
        return ColumnarBatch(dict(zip(names, cols)), n_rc)

    def _partial_batches(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.memory.retry import with_retry
        names = [n for n, _ in self._partial_schema]
        dtypes = [dt for _, dt in self._partial_schema]

        def tallied():
            for batch in self.child.execute():
                # row_count: deferred upstream counts accumulate lazily
                # in the metric and skip the per-batch empty check (not
                # worth a round trip — the kernels mask empty input)
                self.metrics[NUM_INPUT_ROWS] += batch.row_count
                self.metrics[NUM_INPUT_BATCHES] += 1
                if not batch.row_count.is_concrete or batch.nrows:
                    yield batch

        def compute(batch):
            with self.timer(AGG_TIME):
                if self._encoded_exec:
                    batch = self._encode_input_batch(batch)
                if self._needs_string_stage:
                    return self._partial_with_string_keys(
                        batch, names, dtypes)
                if self._coded_eligible:
                    return self._partial_coded(batch, names, dtypes)
                key_flat, buf_flat, n = self._update_fn(
                    batch_to_flat(batch), batch.row_count.device_i32(),
                    self._pargs())
                # keyless reductions have statically one output row;
                # grouped counts stay device-resident (deferred) — the
                # per-batch int(n) costs a full tunnel round trip
                n = 1 if not self.group_exprs else self._wrap_count(n)
                outs = [ColVal(dt, v, val, offs)
                        for dt, (v, val, offs) in
                        zip(dtypes, list(key_flat) + list(buf_flat))]
                cols = colvals_to_columns(outs, n, batch.capacity)
                return ColumnarBatch(dict(zip(names, cols)), n)

        yield from with_retry(tallied(), compute)

    def _partial_with_string_keys(self, batch, names, dtypes):
        from spark_rapids_tpu.ops.dictionary import ordered_dict_encode
        nkeys = len(self.group_exprs)
        pre_cols = self._pre_fn(batch)
        key_cols, child_cols = pre_cols[:nkeys], pre_cols[nkeys:]
        enc_keys = [self._encoders[i].encode(c) if i in self._string_key_idx
                    else c for i, c in enumerate(key_cols)]
        child_iter = iter(child_cols)
        buf_inputs: List[Tuple[str, ColVal]] = []
        buf_dicts: Dict[int, List] = {}
        for f in self.funcs:
            cc = next(child_iter) if f.child is not None else None
            if cc is not None and len(buf_inputs) in self._string_buf_pos:
                # batch-local ORDER-PRESERVING codes: min/max over codes
                # equals min/max over strings within this batch
                codes, d = ordered_dict_encode(cc)
                buf_dicts[len(buf_inputs)] = d
                pad = np.zeros(batch.capacity, dtype=np.int64)
                pad[: len(codes)] = codes
                cv = ColVal(dts.INT64, jnp.asarray(pad), cc.validity)
            else:
                cv = None if cc is None else \
                    ColVal(cc.dtype, cc.data, cc.validity, cc.offsets)
            for spec, bi in zip(f.buffers(),
                                f.update_inputs(cv, batch.capacity)):
                buf_inputs.append((spec.kind, bi))
        key_flat_in = [(c.data, c.validity) for c in enc_keys]
        buf_flat_in = [(c.values, c.validity) for _, c in buf_inputs]
        nrows = batch.row_count.device_i32()
        if not enc_keys:
            # keyless (e.g. SELECT min(s)): one output row
            kernel = _keyless_kernel(self._update_kinds)
            buf_flat = kernel(buf_flat_in, nrows)
            key_flat, n = [], 1
            out_cap = 1024
        else:
            pick = None
            if self._coded_eligible:
                mins, maxs = _probe_kernel(nkeys)(key_flat_in, nrows)
                pick = self._coded_pick(mins, maxs)
            if pick is not None:
                k_bucket, mins_d, slots_d = pick
                mask = jnp.arange(batch.capacity,
                                  dtype=jnp.int32) < nrows
                key_flat, buf_flat, n = _coded_kernel(
                    self._update_kinds, k_bucket)(
                    key_flat_in, buf_flat_in, mins_d, slots_d, mask)
            else:
                kernel = _grouped_kernel(self._update_kinds, nkeys)
                key_flat, buf_flat, n = kernel(key_flat_in, buf_flat_in,
                                               nrows)
            # string buffers re-decode per batch below: a genuine host
            # decision point, so the count syncs (and is counted) here
            n = int(RowCount(device=n))
            out_cap = key_flat[0][0].shape[0]
        cols_out = {}
        for name, dt, (v, val) in zip(names, dtypes,
                                      list(key_flat) + list(buf_flat)):
            pos = len(cols_out) - nkeys
            if pos in buf_dicts:
                d = buf_dicts[pos]
                codes = np.asarray(v[:n] if getattr(v, "ndim", 0)
                                   else jnp.broadcast_to(v, (1,)))
                ok = np.ones(n, dtype=bool) if val is None else \
                    np.asarray(val[:n] if getattr(val, "ndim", 0)
                               else jnp.broadcast_to(val, (1,)))
                strs = [d[int(c)] if o and d else None
                        for c, o in zip(codes, ok)]
                cols_out[name] = Column.from_strings(strs,
                                                     capacity=out_cap)
            else:
                cv = ColVal(dt, v, val)
                cols_out[name] = colvals_to_columns([cv], n, out_cap)[0]
        return ColumnarBatch(cols_out, n)

    # ------------------------------------------------------------ merge stage --
    @property
    def _merge_dtypes(self) -> List:
        """Partial-schema dtypes as the merge kernels see them: string
        buffers arrive re-encoded as int64 codes."""
        nkeys = len(self.group_exprs)
        out = []
        for i, (_, dt) in enumerate(self._partial_schema):
            pos = i - nkeys
            out.append(dts.INT64 if pos in self._string_buf_pos else dt)
        return out

    def _merge_body(self, flat_cols, nrows):
        """Shared merge group-by/reduce over partial-schema columns."""
        dtypes = self._merge_dtypes
        nkeys = len(self.group_exprs)
        capacity = capacity_of(flat_cols)
        cols = flat_to_colvals(flat_cols, dtypes)
        keys, bufs = cols[:nkeys], cols[nkeys:]
        merge_inputs = [(k, c) for k, c in zip(self._merge_kinds, bufs)]
        if keys:
            return agg.groupby_aggregate(keys, merge_inputs, nrows,
                                         capacity)
        out_bufs = agg.reduce_aggregate(merge_inputs, nrows, capacity)
        return [], out_bufs, jnp.int32(1)

    def _merge(self, flat_cols, nrows):
        out_keys, out_bufs, n = self._merge_body(flat_cols, nrows)
        results = [f.finalize(out_bufs[sl])
                   for f, sl in zip(self.funcs, self._buf_slices)]
        return ([(k.values, k.validity, k.offsets) for k in out_keys],
                [(r.values, r.validity, r.offsets) for r in results], n)

    def _merge_partial(self, flat_cols, nrows):
        """Merge partial batches into one partial batch (no finalize) —
        the tree-reduction step bounding the final concat (the reference's
        sort-based fallback serves the same purpose, aggregate.scala:
        184-197: never require every partial in memory at once)."""
        out_keys, out_bufs, n = self._merge_body(flat_cols, nrows)
        return ([(k.values, k.validity, k.offsets) for k in out_keys],
                [(b.values, b.validity, b.offsets) for b in out_bufs], n)

    def _merge_coded(self, k_bucket: int, finalize: bool):
        """Build the coded (sort-free) merge kernel body for cached_jit."""
        dtypes = [dt for _, dt in self._partial_schema]
        nkeys = len(self.group_exprs)

        def run(flat_cols, mins, slot_ranges, nrows):
            capacity = capacity_of(flat_cols)
            cols = flat_to_colvals(flat_cols, dtypes)
            keys, bufs = cols[:nkeys], cols[nkeys:]
            merge_inputs = [(k, c)
                            for k, c in zip(self._merge_kinds, bufs)]
            out_keys, out_bufs, n = agg.groupby_aggregate_coded(
                keys, merge_inputs, nrows, capacity, mins, slot_ranges,
                k_bucket)
            if finalize:
                results = [f.finalize(out_bufs[sl])
                           for f, sl in zip(self.funcs, self._buf_slices)]
            else:
                results = out_bufs
            return ([(k.values, k.validity, k.offsets) for k in out_keys],
                    [(r.values, r.validity, r.offsets) for r in results],
                    n)

        return run

    def _merge_hashed(self, table_slots: int, finalize: bool):
        """Build the hash-table merge kernel body for cached_jit."""
        dtypes = [dt for _, dt in self._partial_schema]
        nkeys = len(self.group_exprs)

        def run(flat_cols, mins, slot_ranges, nrows):
            capacity = capacity_of(flat_cols)
            cols = flat_to_colvals(flat_cols, dtypes)
            keys, bufs = cols[:nkeys], cols[nkeys:]
            merge_inputs = [(k, c)
                            for k, c in zip(self._merge_kinds, bufs)]
            out_keys, out_bufs, n, ovf = agg.groupby_aggregate_hashed(
                keys, merge_inputs, nrows, capacity, mins, slot_ranges,
                table_slots)
            if finalize:
                results = [f.finalize(out_bufs[sl])
                           for f, sl in zip(self.funcs, self._buf_slices)]
            else:
                results = out_bufs
            return ([(k.values, k.validity, k.offsets) for k in out_keys],
                    [(r.values, r.validity, r.offsets) for r in results],
                    n, ovf)

        return run

    def _merge_try_hashed(self, flat, mins_h, maxs_h, nrows, finalize):
        """Hash-table merge attempt; None means fall through to the
        sort merge (disabled, ineligible, or table overflow)."""
        from spark_rapids_tpu.ops import pallas_kernels as pk
        enabled, table_slots = pk.hash_dispatch_conf()
        if not enabled:
            return None
        hp = self._hash_pick_host(mins_h, maxs_h)
        if hp is None:
            return None
        from spark_rapids_tpu.exec.fusion import fusion_metrics
        from spark_rapids_tpu.ops.jit_cache import cached_jit
        mins_d, slots_d = hp
        fn = cached_jit(
            ("agg_merge_hashed", finalize, table_slots) + self._base_sig,
            lambda: self._merge_hashed(table_slots, finalize))
        key_flat, buf_flat, n, ovf = fn(flat, mins_d, slots_d, nrows)
        fusion_metrics.bump("hashKernelLaunches")
        if bool(hostsync.fetch(ovf)):
            fusion_metrics.bump("hashOverflowFallbacks")
            return None
        return key_flat, buf_flat, n

    def _merge_exec(self, merged_in: ColumnarBatch, finalize: bool):
        """Merge-stage dispatch mirroring the update stage: probe the
        partials' key ranges, run the coded kernel when the space fits.
        String buffer columns (min/max/first/last partial winners) are
        re-encoded to order-preserving codes over ALL partials first —
        comparisons across batches are then exact; outputs decode via
        ``self._merge_dicts``."""
        flat = batch_to_flat(merged_in)
        nrows = merged_in.row_count.device_i32()
        nkeys = len(self.group_exprs)
        self._merge_dicts = {}
        if self._string_buf_pos:
            from spark_rapids_tpu.ops.dictionary import ordered_dict_encode
            cols = list(merged_in.columns.values())
            for pos in self._string_buf_pos:
                ci = nkeys + pos
                col = cols[ci]
                codes, d = ordered_dict_encode(col)
                self._merge_dicts[pos] = d
                pad = np.zeros(col.capacity, dtype=np.int64)
                pad[: len(codes)] = codes
                flat[ci] = (jnp.asarray(pad), col.validity, None)
        if self._coded_eligible:
            key_flat = [(v, val) for v, val, _ in flat[:nkeys]]
            mins, maxs = _probe_kernel(nkeys)(key_flat, nrows)
            mins_h, maxs_h = self._sync_range(mins, maxs)
            pick = self._coded_pick_host(mins_h, maxs_h)
            if pick is not None:
                from spark_rapids_tpu.ops.jit_cache import cached_jit
                kb, mins_d, slots_d = pick
                fn = cached_jit(
                    ("agg_merge_coded", finalize, kb) + self._base_sig,
                    lambda: self._merge_coded(kb, finalize))
                return fn(flat, mins_d, slots_d, nrows)
            got = self._merge_try_hashed(flat, mins_h, maxs_h, nrows,
                                         finalize)
            if got is not None:
                return got
        fn = self._merge_fn if finalize else self._merge_partial_fn
        return fn(flat, nrows)

    def _tree_merge(self, handles, catalog):
        """Reduce partial handles until their total rows fit one merge
        chunk; each step merges >=2 partials into one (still-partial)
        spillable batch, so the device never holds every partial."""
        names = [n for n, _ in self._partial_schema]
        dtypes = [dt for _, dt in self._partial_schema]
        chunk = self.merge_chunk_rows
        # merge sizing is a host decision point — but first check the
        # sync-free capacity bound: when even the upper bound fits one
        # merge chunk (the common coded-path case), no deferred count
        # ever materializes here.  Otherwise resolve every handle's
        # count in ONE batched transfer.
        if len(handles) > 1 and \
                sum(h.nrows_bound for h in handles) > chunk:
            RowCount.materialize_all([h.row_count for h in handles])
        while len(handles) > 1 and \
                sum(h.nrows_bound for h in handles) > chunk:
            group = []
            rows = 0
            while handles and (len(group) < 2 or
                               rows + handles[0].nrows <= chunk):
                h = handles.pop(0)
                group.append(h)
                rows += h.nrows
                if rows >= chunk and len(group) >= 2:
                    break
            with self.timer(CONCAT_TIME):
                merged_in = concat_batches([h.materialize()
                                            for h in group])
            for h in group:
                h.close()
            with self.timer(AGG_TIME):
                key_flat, buf_flat, n = self._merge_exec(
                    merged_in, finalize=False)
                # compaction below sizes the spill registration from n:
                # a genuine host decision point (counted sync)
                n = 1 if not self.group_exprs else int(RowCount(device=n))
            outs = [ColVal(dt, v, val, offs)
                    for dt, (v, val, offs) in
                    zip(dtypes, list(key_flat) + list(buf_flat))]
            # compact to the live row count before registering: n is
            # already concrete here, and keeping the concat capacity
            # would make padding, not rows, dominate the spill bytes
            # (coded-path outputs are already key-space sized)
            from spark_rapids_tpu.columnar.column import bucket_capacity
            cur_cap = int(outs[0].values.shape[0])
            out_cap = min(bucket_capacity(n), cur_cap)
            if out_cap < cur_cap:
                outs = [ColVal(c.dtype, c.values[:out_cap],
                               None if c.validity is None
                               else c.validity[:out_cap], c.offsets)
                        for c in outs]
            nkeys = len(self.group_exprs)
            cols = {}
            for name, c in zip(names, outs):
                pos = len(cols) - nkeys
                if pos in self._merge_dicts:
                    cols[name] = self._decode_codes(c, n, out_cap,
                                                    self._merge_dicts[pos])
                else:
                    cols[name] = colvals_to_columns([c], n, out_cap)[0]
            handles.append(
                catalog.register(ColumnarBatch(cols, n)))
        return handles

    @staticmethod
    def _decode_codes(c: ColVal, n: int, out_cap: int, d: List) -> Column:
        """codes ColVal -> string Column via a merge-stage dictionary."""
        codes = np.asarray(c.values[:n]) if getattr(c.values, "ndim", 0) \
            else np.broadcast_to(np.asarray(c.values), (n,))
        if c.validity is None:
            ok = np.ones(n, dtype=bool)
        else:
            ok = np.asarray(c.validity[:n]) \
                if getattr(c.validity, "ndim", 0) else \
                np.broadcast_to(np.asarray(c.validity), (n,))
        strs = [d[int(v)] if o and d else None
                for v, o in zip(codes, ok)]
        return Column.from_strings(strs, capacity=out_cap)

    def _single_kernel(self, flat_cols, nrows, params=()):
        """Grouped pass mixing collect arrays with regular reductions."""
        capacity = capacity_of(flat_cols)
        inputs = flat_to_colvals(flat_cols, self._in_dtypes)
        ctx = EmitContext(inputs, nrows, capacity,
                          params=params_dict(self._slots, params))
        row_mask = self._pre_filter_mask(ctx)
        keys = [e.emit(ctx) for e in self.group_exprs]
        keyless = not keys
        if keyless:
            # constant key -> exactly one group over the live rows; the
            # key column is dropped from the output below
            keys = [ColVal(dts.INT64,
                           jnp.zeros(capacity, dtype=jnp.int64))]
        collect_inputs = []
        buffer_inputs = []
        layout = []  # ("collect", idx) | ("buf", slice) per func
        for f in self.funcs:
            c = f.child.emit(ctx) if f.child is not None else None
            if c is not None and getattr(c.values, "ndim", 0) == 0 and                     c.offsets is None:
                c = ColVal(c.dtype,
                           jnp.broadcast_to(c.values, (capacity,)),
                           c.validity)
            if getattr(f, "single_pass", False):
                layout.append(("collect", len(collect_inputs)))
                collect_inputs.append((c, f.dedup))
            else:
                start = len(buffer_inputs)
                for spec, cv in zip(f.buffers(),
                                    f.update_inputs(c, capacity)):
                    buffer_inputs.append((spec.kind, cv))
                layout.append(("buf", slice(start, len(buffer_inputs))))
        out_keys, out_bufs, collects, n = agg.groupby_collect(
            keys, collect_inputs, nrows, capacity,
            buffer_inputs=buffer_inputs, row_mask=row_mask)
        if keyless:
            out_keys = []
        results = []
        for f, (kind, ref) in zip(self.funcs, layout):
            if kind == "collect":
                results.append(collects[ref])
            else:
                results.append(f.finalize(out_bufs[ref]))
        outs = list(out_keys) + results
        return ([(o.values, o.validity, o.offsets) for o in outs], n)

    def _single_pass_execute(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.memory.spill import default_catalog
        catalog = default_catalog()
        handles = []
        for b in self.child.execute():
            self.metrics[NUM_INPUT_ROWS] += b.row_count
            self.metrics[NUM_INPUT_BATCHES] += 1
            handles.append(catalog.register(b))
        if not handles:
            if self.group_exprs:
                return
            # Spark keyless aggregation of empty input is ONE row:
            # empty arrays for collects, identity for the rest
            yield self._keyless_empty_result()
            return
        batches = [h.materialize() for h in handles]
        with self.timer(CONCAT_TIME):
            merged = concat_batches(batches)
        for h in handles:
            h.close()
        with self.timer(AGG_TIME):
            out_flat, n = self._single_fn(batch_to_flat(merged),
                                          merged.row_count.device_i32(),
                                          self._pargs())
            # collect arrays re-decode on the host right below: the
            # count is needed concretely either way (counted sync)
            n = int(RowCount(device=n))
        if n == 0 and not self.group_exprs:
            yield self._keyless_empty_result()
            return
        names = [nm for nm, _ in self.schema]
        dtypes = [dt for _, dt in self.schema]
        outs = [ColVal(dt, v, val, offs)
                for dt, (v, val, offs) in zip(dtypes, out_flat)]
        cols = colvals_to_columns(outs, n, merged.capacity)
        yield ColumnarBatch(dict(zip(names, cols)), n)

    def _keyless_empty_result(self) -> ColumnarBatch:
        cols = {}
        for (name, dt), f in zip(self.schema, self.funcs):
            if getattr(f, "single_pass", False):
                cols[name] = Column.from_arrays([[]], dt.element)
            elif f.name == "count":
                cols[name] = Column.from_numpy(
                    np.zeros(1, dtype=np.int64), dtype=dts.INT64)
            else:
                cols[name] = Column.from_numpy(
                    np.zeros(1, dtype=dt.storage), dtype=dt,
                    validity=np.array([False]))
        return ColumnarBatch(cols, 1)

    def do_execute(self) -> Iterator[ColumnarBatch]:
        if self._single_pass:
            yield from self._single_pass_execute()
            return
        from spark_rapids_tpu.memory.spill import default_catalog
        catalog = default_catalog()
        # cache partials as spillable batches (the reference caches
        # SpillableColumnarBatch between update and merge, aggregate.scala)
        handles = [catalog.register(b) for b in self._partial_batches()]
        nkeys = len(self.group_exprs)
        if not handles:
            if nkeys:
                return
            partials = [empty_batch(self._partial_schema)]
        else:
            handles = self._tree_merge(handles, catalog)
            partials = [h.materialize() for h in handles]
        with self.timer(CONCAT_TIME):
            merged_in = concat_batches(partials)
        for h in handles:
            h.close()
        with self.timer(AGG_TIME):
            key_flat, res_flat, n = self._merge_exec(
                merged_in, finalize=True)
            if not self.group_exprs:
                n = 1
            elif self._string_key_idx or self._merge_dicts:
                # string re-decode below walks codes on the host: a
                # genuine host decision point (counted sync)
                n = int(RowCount(device=n))
            else:
                # fully deferred: the final count rides to collect()
                n = self._wrap_count(n)
        out_names = [name for name, _ in self.schema]
        outs: List[ColVal] = []
        for i, (e, (v, val, offs)) in enumerate(zip(self.group_exprs,
                                                    key_flat)):
            dt = dts.INT32 if i in self._string_key_idx else e.dtype
            outs.append(ColVal(dt, v, val, offs))
        for (name, ae), (v, val, offs) in zip(self.agg_exprs, res_flat):
            outs.append(ColVal(ae.dtype, v, val, offs))
        out_cap = next((int(o.values.shape[0]) for o in outs
                        if getattr(o.values, "ndim", 0) >= 1),
                       merged_in.capacity)
        cols = []
        for j, c in enumerate(outs):
            fj = j - nkeys  # func index for agg outputs
            bpos = self._buf_slices[fj].start if 0 <= fj < len(
                self.funcs) else None
            if bpos is not None and bpos in self._merge_dicts:
                cols.append(self._decode_codes(c, n, out_cap,
                                               self._merge_dicts[bpos]))
            else:
                cols.append(colvals_to_columns([c], n, out_cap)[0])
        for i in self._string_key_idx:
            cols[i] = self._encoders[i].decode(cols[i])
        yield ColumnarBatch(dict(zip(out_names, cols)), n)
