"""Sort and TopN physical operators.

Counterpart of ``GpuSortExec.scala`` (per-batch / single-batch / out-of-core
modes) and ``GpuTopN`` (limit.scala:148).  The single-process path sorts the
concatenated input with the same lexsort kernel the group-by uses (Spark
ordering: NaN largest, -0.0 == 0.0, null placement per sort key).  The
out-of-core merge path arrives with the spill framework.

TopN streams: each batch is sorted and truncated to n, the survivors are
concatenated and re-sorted — a tournament reduction that never materializes
more than batch+n rows (the GpuTopN iterator does the same with cudf sorts).
"""

from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, bucket_capacity
from spark_rapids_tpu.exec.base import SORT_TIME, Schema, TpuExec
from spark_rapids_tpu.ops import aggregates as agg
from spark_rapids_tpu.ops import selection
from spark_rapids_tpu.ops.compiler import StageFn
from spark_rapids_tpu.ops.concat import concat_batches
from spark_rapids_tpu.ops.expressions import ColVal, Expression

# orders: (expr, descending, nulls_first)
Order = Tuple[Expression, bool, bool]


class TpuSortExec(TpuExec):
    ephemeral_output = True

    def __init__(self, orders: Sequence[Order], child: TpuExec,
                 ooc_threshold_bytes: int = 256 << 20,
                 ooc_window_rows: int = 1 << 16):
        super().__init__(child)
        self.ooc_threshold_bytes = ooc_threshold_bytes
        self.ooc_window_rows = ooc_window_rows
        self.orders = list(orders)
        self._key_fn = StageFn([e for e, _, _ in orders],
                               [dt for _, dt in child.schema])
        self._register_metric(SORT_TIME)
        from spark_rapids_tpu.ops.jit_cache import cached_jit
        sig = ("sort", tuple((e.cache_key(), d, nf)
                             for e, d, nf in self.orders),
               tuple(dt.name for _, dt in child.schema))
        self._sort = cached_jit(sig, lambda: self._sort_batch)

    @property
    def child(self) -> TpuExec:
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def describe(self):
        parts = [f"{e.name} {'DESC' if d else 'ASC'}"
                 for e, d, _ in self.orders]
        return f"TpuSortExec[{', '.join(parts)}]"

    def _eval_keys(self, batch: ColumnarBatch) -> List[ColVal]:
        """Evaluate sort keys; string keys become order-preserving int32
        ranks (host-vectorized, per materialized batch — exact for the
        single-batch sort because ranks are dense over its value set).
        The device lexsort kernel then treats them as plain numerics."""
        from spark_rapids_tpu.ops.dictionary import rank_encode
        keys = []
        for c in self._key_fn(batch):
            if c.dtype.is_string:
                ranks = rank_encode(c)
                enc = Column.from_numpy(ranks, validity=None,
                                        capacity=c.capacity)
                keys.append(ColVal(enc.dtype, enc.data,
                                   c.validity, None))
            else:
                keys.append(ColVal(c.dtype, c.data, c.validity, c.offsets))
        return keys

    def _sort_batch(self, key_cols: List[ColVal], payload: List[ColVal],
                    nrows):
        # row capacity: a string column's .values is its byte buffer, so
        # derive from offsets (len+1) when present
        first = payload[0]
        capacity = (first.offsets.shape[0] - 1 if first.offsets is not None
                    else first.values.shape[0])
        live = jnp.arange(capacity, dtype=jnp.int32) < nrows
        perm = agg.sort_permutation(
            key_cols, live, capacity,
            descending=[d for _, d, _ in self.orders],
            nulls_first=[nf for _, _, nf in self.orders])
        return selection.gather(payload, perm, nrows)

    def _sorted_batch(self, batch: ColumnarBatch,
                      extra_payload: Sequence[ColVal] = ()
                      ) -> List[ColVal]:
        """Device-sort one batch; extra payload columns ride the same
        permutation (used for the merge-phase source tags)."""
        key_cols = self._eval_keys(batch)
        payload = [ColVal(c.dtype, c.data, c.validity, c.offsets)
                   for c in batch.columns.values()] + list(extra_payload)
        return self._sort(key_cols, payload, jnp.int32(batch.nrows))

    def _emit(self, outs: Sequence[ColVal], nrows: int) -> ColumnarBatch:
        names = [n for n, _ in self.schema]
        cols = {nm: Column(o.dtype, o.values, nrows,
                           validity=o.validity, offsets=o.offsets)
                for nm, o in zip(names, outs)}
        return ColumnarBatch(cols, nrows)

    def do_execute(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.memory.spill import default_catalog
        catalog = default_catalog()
        handles = [catalog.register(b) for b in self.child.execute()]
        if not handles:
            return
        total_bytes = sum(h.size_bytes for h in handles)
        if len(handles) > 1 and total_bytes > self.ooc_threshold_bytes:
            yield from self._out_of_core(handles, catalog)
            return
        from spark_rapids_tpu.memory.retry import with_retry_no_split
        with self.timer(SORT_TIME):
            # materialize + concat is this operator's peak allocation;
            # it needs the spill-retry guard as much as the sort itself
            def gather_input():
                batches = [h.materialize() for h in handles]
                return concat_batches(batches)

            merged = with_retry_no_split(gather_input, catalog=catalog)
            for h in handles:
                h.close()
            outs = with_retry_no_split(
                lambda: self._sorted_batch(merged), catalog=catalog)
        yield self._emit(outs, merged.nrows)

    # ------------------------------------------------------- out-of-core --
    def _slice_rows(self, batch: ColumnarBatch, start: int, count: int,
                    out_capacity: int) -> ColumnarBatch:
        """Rows [start, start+count) into a fresh batch of out_capacity;
        string char buffers are resized to the slice's own char count (a
        window of a big string run must not inherit the run's full char
        capacity, or the merge working-set bound fails for strings)."""
        idx = jnp.clip(jnp.arange(out_capacity, dtype=jnp.int32) + start,
                       0, max(batch.capacity - 1, 0))
        cols = [ColVal(c.dtype, c.data, c.validity, c.offsets)
                for c in batch.columns.values()]
        char_cap = 0
        for c in cols:
            if c.offsets is not None:
                cc = int(selection.gathered_char_count(
                    c.offsets, idx, jnp.int32(count)))
                char_cap = max(char_cap, cc)
        outs = selection.gather(
            cols, idx, jnp.int32(count),
            char_capacity=bucket_capacity(char_cap) if char_cap else 0)
        names = [n for n, _ in batch.schema]
        return ColumnarBatch(
            {nm: Column(o.dtype, o.values, count, validity=o.validity,
                        offsets=o.offsets)
             for nm, o in zip(names, outs)}, count)

    def _out_of_core(self, handles, catalog) -> Iterator[ColumnarBatch]:
        """Windowed merge of sorted spillable runs
        (GpuOutOfCoreSortIterator, GpuSortExec.scala:225 — redesigned for
        the device: the merge step is itself a bounded device sort).

        Each input batch is device-sorted and split into window-sized
        spillable chunks, so a merge step unspills exactly one chunk per
        refilled run — never a whole run.  Per step, the carry plus the
        refill windows are sorted together; every row up to the earliest
        live-run boundary is globally final and is emitted.  The boundary
        needs NO key comparisons: each live run's last resident row
        carries an int32 source tag through the sort (persisting in the
        carry across steps), and the earliest tagged position bounds the
        emit.  Only runs whose tagged row was emitted are refilled, so
        the carry holds at most one window per live run and the working
        set stays <= ~2 * runs * window rows even for disjoint-range
        runs (e.g. pre-sorted input split into batches)."""
        from spark_rapids_tpu.memory.retry import (
            with_retry, with_retry_no_split)
        window = self.ooc_window_rows
        with self.timer(SORT_TIME):
            runs = []  # {"chunks": [spillable handles], "next": int}

            # materialize is itself a device allocation: guard it with
            # spill-retry (a generator would die on the first raise, so
            # the pull happens in the loop body, not upstream of
            # with_retry)
            def materialized():
                for h in handles:
                    b = with_retry_no_split(h.materialize, catalog=catalog)
                    h.close()
                    yield b

            # OOM during run building splits the input batch; each half
            # simply becomes its own sorted run — the merge phase is
            # indifferent to run count
            def build_run(b):
                outs = self._sorted_batch(b)
                sb = self._emit(outs, b.nrows)
                chunks = []
                try:
                    for start in range(0, sb.nrows, window):
                        take = min(window, sb.nrows - start)
                        chunks.append(catalog.register(self._slice_rows(
                            sb, start, take, bucket_capacity(take))))
                except BaseException:
                    # a retry re-runs the whole function; orphaned
                    # handles from the failed attempt must not stay
                    # pinned in the catalog
                    for ch in chunks:
                        ch.close()
                    raise
                if chunks:
                    runs.append({"chunks": chunks, "next": 0})
                return True

            for _ in with_retry(materialized(), build_run,
                                catalog=catalog):
                pass
        carry: ColumnarBatch = None
        carry_tags = np.zeros(0, dtype=np.int32)
        need = set(range(len(runs)))
        while True:
            with self.timer(SORT_TIME):
                windows = []
                tags = [carry_tags] if carry is not None else []
                for rid in sorted(need):
                    run = runs[rid]
                    if run["next"] >= len(run["chunks"]):
                        continue
                    ch = run["chunks"][run["next"]]
                    run["next"] += 1
                    win = with_retry_no_split(ch.materialize,
                                              catalog=catalog)
                    ch.close()
                    exhausted = run["next"] >= len(run["chunks"])
                    tag = np.full(win.nrows, -1, dtype=np.int32)
                    if not exhausted:
                        tag[win.nrows - 1] = rid
                    windows.append(win)
                    tags.append(tag)
                need = set()
                parts = ([carry] if carry is not None else []) + windows
                if not parts:
                    return
                merged = concat_batches(parts)
                tag_np = np.concatenate(tags) if tags else \
                    np.zeros(0, dtype=np.int32)
                padded = np.full(merged.capacity, -1, dtype=np.int32)
                padded[: len(tag_np)] = tag_np
                tag_col = ColVal(None, jnp.asarray(padded), None)
                outs = self._sorted_batch(merged, extra_payload=[tag_col])
                sorted_tags = np.asarray(outs[-1].values[:merged.nrows])
                outs = outs[:-1]
                batch = self._emit(outs, merged.nrows)
                tagged = np.nonzero(sorted_tags >= 0)[0]
                if not len(tagged):
                    # no live boundaries left: everything is final
                    if batch.nrows:
                        yield batch
                    return
                safe = int(tagged[0]) + 1
                # refill exactly the runs whose boundary row was emitted
                for pos in tagged:
                    if pos < safe:
                        need.add(int(sorted_tags[pos]))
                out = self._slice_rows(batch, 0, safe,
                                       bucket_capacity(safe))
                rest = merged.nrows - safe
                if rest:
                    carry = self._slice_rows(batch, safe, rest,
                                             bucket_capacity(rest))
                    carry_tags = sorted_tags[safe:].astype(np.int32)
                else:
                    carry = None
                    carry_tags = np.zeros(0, dtype=np.int32)
            if out.nrows:
                yield out


class TpuTopNExec(TpuExec):
    """TakeOrderedAndProject (GpuOverrides.scala:3002 TakeOrderedAndProject
    -> GpuTopN)."""

    def __init__(self, n: int, orders: Sequence[Order], child: TpuExec):
        super().__init__(child)
        self.n = n
        self.orders = list(orders)
        self._inner = TpuSortExec(orders, child)
        self._register_metric(SORT_TIME)

    @property
    def child(self) -> TpuExec:
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def describe(self):
        return f"TpuTopNExec[{self.n}]"

    def _sorted_head(self, batch: ColumnarBatch) -> ColumnarBatch:
        key_cols = self._inner._eval_keys(batch)
        payload = [ColVal(c.dtype, c.data, c.validity, c.offsets)
                   for c in batch.columns.values()]
        outs = self._inner._sort(key_cols, payload, jnp.int32(batch.nrows))
        take = min(self.n, batch.nrows)
        names = [nm for nm, _ in self.schema]
        cols = {nm: Column(o.dtype, o.values, take, validity=o.validity,
                           offsets=o.offsets)
                for nm, o in zip(names, outs)}
        return ColumnarBatch(cols, take)

    def do_execute(self) -> Iterator[ColumnarBatch]:
        pending: List[ColumnarBatch] = []
        with self.timer(SORT_TIME):
            for batch in self.child.execute():
                if batch.nrows == 0:
                    continue
                pending.append(self._sorted_head(batch))
                if len(pending) > 8:
                    pending = [self._sorted_head(concat_batches(pending))]
            if not pending:
                return
            yield self._sorted_head(concat_batches(pending))
