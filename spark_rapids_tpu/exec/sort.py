"""Sort and TopN physical operators.

Counterpart of ``GpuSortExec.scala`` (per-batch / single-batch / out-of-core
modes) and ``GpuTopN`` (limit.scala:148).  The single-process path sorts the
concatenated input with the same lexsort kernel the group-by uses (Spark
ordering: NaN largest, -0.0 == 0.0, null placement per sort key).  The
out-of-core merge path arrives with the spill framework.

TopN streams: each batch is sorted and truncated to n, the survivors are
concatenated and re-sorted — a tournament reduction that never materializes
more than batch+n rows (the GpuTopN iterator does the same with cudf sorts).
"""

from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.exec.base import SORT_TIME, Schema, TpuExec
from spark_rapids_tpu.ops import aggregates as agg
from spark_rapids_tpu.ops import selection
from spark_rapids_tpu.ops.compiler import StageFn
from spark_rapids_tpu.ops.concat import concat_batches
from spark_rapids_tpu.ops.expressions import ColVal, Expression

# orders: (expr, descending, nulls_first)
Order = Tuple[Expression, bool, bool]


class TpuSortExec(TpuExec):
    def __init__(self, orders: Sequence[Order], child: TpuExec):
        super().__init__(child)
        self.orders = list(orders)
        self._key_fn = StageFn([e for e, _, _ in orders],
                               [dt for _, dt in child.schema])
        self._register_metric(SORT_TIME)
        from spark_rapids_tpu.ops.jit_cache import cached_jit
        sig = ("sort", tuple((e.cache_key(), d, nf)
                             for e, d, nf in self.orders),
               tuple(dt.name for _, dt in child.schema))
        self._sort = cached_jit(sig, lambda: self._sort_batch)

    @property
    def child(self) -> TpuExec:
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def describe(self):
        parts = [f"{e.name} {'DESC' if d else 'ASC'}"
                 for e, d, _ in self.orders]
        return f"TpuSortExec[{', '.join(parts)}]"

    def _eval_keys(self, batch: ColumnarBatch) -> List[ColVal]:
        """Evaluate sort keys; string keys become order-preserving int32
        ranks (host-vectorized, per materialized batch — exact for the
        single-batch sort because ranks are dense over its value set).
        The device lexsort kernel then treats them as plain numerics."""
        from spark_rapids_tpu.ops.dictionary import rank_encode
        keys = []
        for c in self._key_fn(batch):
            if c.dtype.is_string:
                ranks = rank_encode(c)
                enc = Column.from_numpy(ranks, validity=None,
                                        capacity=c.capacity)
                keys.append(ColVal(enc.dtype, enc.data,
                                   c.validity, None))
            else:
                keys.append(ColVal(c.dtype, c.data, c.validity, c.offsets))
        return keys

    def _sort_batch(self, key_cols: List[ColVal], payload: List[ColVal],
                    nrows):
        # row capacity: a string column's .values is its byte buffer, so
        # derive from offsets (len+1) when present
        first = payload[0]
        capacity = (first.offsets.shape[0] - 1 if first.offsets is not None
                    else first.values.shape[0])
        live = jnp.arange(capacity, dtype=jnp.int32) < nrows
        perm = agg.sort_permutation(
            key_cols, live, capacity,
            descending=[d for _, d, _ in self.orders],
            nulls_first=[nf for _, _, nf in self.orders])
        return selection.gather(payload, perm, nrows)

    def do_execute(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.memory.spill import default_catalog
        catalog = default_catalog()
        handles = [catalog.register(b) for b in self.child.execute()]
        if not handles:
            return
        with self.timer(SORT_TIME):
            batches = [h.materialize() for h in handles]
            merged = concat_batches(batches)
            for h in handles:
                h.close()
            key_cols = self._eval_keys(merged)
            payload = [ColVal(c.dtype, c.data, c.validity, c.offsets)
                       for c in merged.columns.values()]
            outs = self._sort(key_cols, payload, jnp.int32(merged.nrows))
        names = [n for n, _ in self.schema]
        cols = {nm: Column(o.dtype, o.values, merged.nrows,
                           validity=o.validity, offsets=o.offsets)
                for nm, o in zip(names, outs)}
        yield ColumnarBatch(cols, merged.nrows)


class TpuTopNExec(TpuExec):
    """TakeOrderedAndProject (GpuOverrides.scala:3002 TakeOrderedAndProject
    -> GpuTopN)."""

    def __init__(self, n: int, orders: Sequence[Order], child: TpuExec):
        super().__init__(child)
        self.n = n
        self.orders = list(orders)
        self._inner = TpuSortExec(orders, child)
        self._register_metric(SORT_TIME)

    @property
    def child(self) -> TpuExec:
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def describe(self):
        return f"TpuTopNExec[{self.n}]"

    def _sorted_head(self, batch: ColumnarBatch) -> ColumnarBatch:
        key_cols = self._inner._eval_keys(batch)
        payload = [ColVal(c.dtype, c.data, c.validity, c.offsets)
                   for c in batch.columns.values()]
        outs = self._inner._sort(key_cols, payload, jnp.int32(batch.nrows))
        take = min(self.n, batch.nrows)
        names = [nm for nm, _ in self.schema]
        cols = {nm: Column(o.dtype, o.values, take, validity=o.validity,
                           offsets=o.offsets)
                for nm, o in zip(names, outs)}
        return ColumnarBatch(cols, take)

    def do_execute(self) -> Iterator[ColumnarBatch]:
        pending: List[ColumnarBatch] = []
        with self.timer(SORT_TIME):
            for batch in self.child.execute():
                if batch.nrows == 0:
                    continue
                pending.append(self._sorted_head(batch))
                if len(pending) > 8:
                    pending = [self._sorted_head(concat_batches(pending))]
            if not pending:
                return
            yield self._sorted_head(concat_batches(pending))
