"""Expand: emit one output row per projection list per input row.

Counterpart of ``GpuExpandExec`` (reference ``GpuOverrides.scala:3170``
rule; ``GpuExpandExec.scala``): the lowering target for ROLLUP / CUBE /
GROUPING SETS.  Spark plans ``GROUP BY ROLLUP(a, b)`` as::

    Aggregate(keys = [a, b, spark_grouping_id])
      Expand(projections = [[a, b, 0], [a, null, 1], [null, null, 3]])

Where cudf evaluates each projection per batch and concatenates, the TPU
formulation emits each projection as its own output batch (static
shapes, K compiled projections per input batch) — the downstream
hash-aggregate consumes multiple batches natively, so no concatenation
is needed at all.

``grouping_id`` bit semantics match Spark: bit i (MSB-first over the
grouping columns) is 1 when grouping column i is aggregated away (null
in that projection).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.exec.base import Schema, TpuExec
from spark_rapids_tpu.ops.compiler import StageFn
from spark_rapids_tpu.ops.expressions import (
    Alias, BoundReference, Expression, Literal)
from spark_rapids_tpu.plan import logical as L


class NullLiteral(Expression):
    """A typed NULL column (the aggregated-away key slot in an Expand
    projection).  Spark uses Literal(null, dataType); the engine's
    ``Literal`` is non-null, so this emits a zero column with an all-
    false validity mask."""

    def __init__(self, dtype: DataType):
        self._dtype = dtype
        self.children = ()

    @property
    def dtype(self) -> DataType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return True

    @property
    def name(self) -> str:
        return "NULL"

    def emit(self, ctx):
        import jax.numpy as jnp
        from spark_rapids_tpu.ops.expressions import ColVal
        if self._dtype.is_string:
            zeros = jnp.zeros(ctx.capacity, dtype=jnp.uint8)
            offsets = jnp.zeros(ctx.capacity + 1, dtype=jnp.int32)
            return ColVal(self._dtype, zeros,
                          jnp.zeros(ctx.capacity, dtype=jnp.bool_),
                          offsets)
        zeros = jnp.zeros(ctx.capacity, dtype=self._dtype.storage)
        return ColVal(self._dtype, zeros,
                      jnp.zeros(ctx.capacity, dtype=jnp.bool_))

    def cache_key(self):
        return ("NullLiteral", self._dtype.name)

    def __str__(self):
        return f"NULL:{self._dtype.name}"


class Expand(L.LogicalPlan):
    """Logical Expand: ``projections[k][j]`` supplies output column j of
    replica k; all projection lists share the output schema."""

    def __init__(self, projections: Sequence[Sequence[Expression]],
                 names: Sequence[str], child: L.LogicalPlan):
        self.projections = [[e.bind(child.schema) for e in p]
                            for p in projections]
        self.names = list(names)
        self.children = (child,)
        first = self.projections[0]
        for p in self.projections[1:]:
            if len(p) != len(first):
                raise ValueError("expand projections differ in arity")

    @property
    def child(self):
        return self.children[0]

    @property
    def schema(self) -> Schema:
        # a column is nullable if ANY projection can make it null
        out = []
        for j, name in enumerate(self.names):
            dt = self.projections[0][j].dtype
            out.append((name, dt))
        return out

    def describe(self):
        return f"Expand[{len(self.projections)} projections]"


class TpuExpandExec(TpuExec):
    """Physical Expand: K compiled projections per input batch, each
    emitted as its own output batch."""

    def __init__(self, node: Expand, child: TpuExec):
        super().__init__(child)
        self.node = node
        in_dtypes = [dt for _, dt in child.schema]
        self._fns = [StageFn(list(p), in_dtypes)
                     for p in node.projections]

    @property
    def child(self) -> TpuExec:
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return self.node.schema

    def describe(self):
        return f"TpuExpandExec[{len(self._fns)} projections]"

    def do_execute(self) -> Iterator[ColumnarBatch]:
        names = self.node.names
        for batch in self.child.execute():
            for fn in self._fns:
                cols = fn(batch)
                yield ColumnarBatch(
                    {n: c for n, c in zip(names, cols)}, batch.nrows)


GROUPING_ID_COL = "spark_grouping_id"


def grouping_set_projections(group_exprs: Sequence[Expression],
                             sets: Sequence[Sequence[int]],
                             passthrough: Sequence[Expression]
                             ) -> List[List[Expression]]:
    """Build Expand projections for grouping sets.

    ``group_exprs``: the N distinct grouping expressions;
    ``sets``: per output replica, the indices of group_exprs that stay
    live; ``passthrough``: non-key expressions the downstream aggregate
    reads (agg children).  Output column order: group_exprs...,
    passthrough..., grouping_id."""
    import numpy as np
    n = len(group_exprs)
    out: List[List[Expression]] = []
    for live in sets:
        live_set = set(live)
        proj: List[Expression] = []
        gid = 0
        for i, e in enumerate(group_exprs):
            if i in live_set:
                proj.append(e)
            else:
                proj.append(NullLiteral(e.dtype))
                gid |= 1 << (n - 1 - i)
        proj.extend(passthrough)
        proj.append(Literal(np.int64(gid)))
        out.append(proj)
    return out


def rollup_sets(n: int) -> List[List[int]]:
    """ROLLUP(a,b,...) -> [(0..n-1), (0..n-2), ..., ()]."""
    return [list(range(k)) for k in range(n, -1, -1)]


def cube_sets(n: int) -> List[List[int]]:
    """CUBE over n columns: all 2^n subsets, Spark's enumeration order
    (subset bitmask descending by included-ness)."""
    out = []
    for mask in range((1 << n) - 1, -1, -1):
        out.append([i for i in range(n) if mask & (1 << (n - 1 - i))])
    return out
