"""TpuExec: base class for columnar physical operators + metrics.

Counterpart of ``GpuExec.scala`` (metric registry with ESSENTIAL/MODERATE/
DEBUG levels, standard names like opTime/numOutputRows/numOutputBatches).
Operators produce an iterator of device-resident ColumnarBatches; crossing to
the host happens only in collect/transition nodes.
"""

from __future__ import annotations

import time
from typing import Dict, Iterator, List, Tuple

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.dtypes import DataType

Schema = List[Tuple[str, DataType]]

ESSENTIAL = 0
MODERATE = 1
DEBUG = 2

# standard metric names (GpuExec.scala:43-160)
# pipeline-level names (exec/pipeline.py PipelineStats -> QueryEnd
# "pipeline" dict -> tools/eventlog.QueryInfo.pipeline)
PIPELINE_FILL_RATIO = "pipelineFillRatio"
HOST_SYNC_COUNT = "hostSyncCount"
UPLOAD_OVERLAP_MS = "uploadOverlapMs"
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
OP_TIME = "opTime"
NUM_INPUT_ROWS = "numInputRows"
NUM_INPUT_BATCHES = "numInputBatches"
SORT_TIME = "sortTime"
AGG_TIME = "computeAggTime"
CONCAT_TIME = "concatTime"
JOIN_TIME = "joinTime"
SPILL_AMOUNT = "spillData"


class TpuMetric:
    """One counter.  Accepts lazy ``RowCount`` additions: deferred
    device-resident counts accumulate unmaterialized and resolve in a
    single batched device fetch when ``value`` is first read (at
    QueryEnd metric collection), so per-batch row tallies never force
    a per-batch host sync."""

    __slots__ = ("name", "level", "_value", "_pending")

    def __init__(self, name: str, level: int = MODERATE):
        self.name = name
        self.level = level
        self._value = 0
        self._pending = None  # deferred RowCounts, resolved on read

    @property
    def value(self):
        if self._pending:
            from spark_rapids_tpu.columnar.column import RowCount
            RowCount.materialize_all(self._pending)
            self._value += sum(int(rc) for rc in self._pending)
            self._pending = None
        return self._value

    @value.setter
    def value(self, v) -> None:
        self._value = v
        self._pending = None

    def add(self, v) -> None:
        from spark_rapids_tpu.columnar.column import RowCount
        if isinstance(v, RowCount):
            if v.is_concrete:
                self._value += int(v)
            else:
                if self._pending is None:
                    self._pending = []
                self._pending.append(v)
            return
        self._value += v

    def __iadd__(self, v):
        self.add(v)
        return self


class MetricTimer:
    def __init__(self, metric: TpuMetric):
        self.metric = metric

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        self.metric.add(time.perf_counter_ns() - self._t0)
        return False


class TpuExec:
    """Base physical operator."""

    # per-plan: set by the planner from spark.rapids.tpu.profile.trace;
    # when True each iteration step wraps in a jax.profiler
    # TraceAnnotation (NVTX-range analog)
    trace_ops = False

    # True when every batch this operator yields is freshly allocated
    # per pull and never retained by the operator (or anyone upstream) —
    # the safety precondition for a consumer stage to DONATE the batch's
    # buffers to XLA (ops/compiler.py).  Retaining scans (in-memory,
    # cache) and pass-through operators keep the default False.
    ephemeral_output = False

    def __init__(self, *children: "TpuExec"):
        self.children: Tuple[TpuExec, ...] = tuple(children)
        self.metrics: Dict[str, TpuMetric] = {}
        self._register_metric(NUM_OUTPUT_ROWS, ESSENTIAL)
        self._register_metric(NUM_OUTPUT_BATCHES, MODERATE)
        self._register_metric(OP_TIME, MODERATE)

    def _register_metric(self, name: str, level: int = MODERATE) -> TpuMetric:
        m = self.metrics.setdefault(name, TpuMetric(name, level))
        return m

    def metric(self, name: str) -> TpuMetric:
        return self.metrics[name]

    def timer(self, name: str) -> MetricTimer:
        return MetricTimer(self.metrics[name])

    # ---- interface -----------------------------------------------------------
    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    def execute(self) -> Iterator[ColumnarBatch]:
        """Produce device batches, updating numOutputRows/Batches.

        opTime covers the operator's own iteration steps (the pull of each
        batch), not just generator construction — generators return
        instantly, the work happens in ``next()``."""
        from spark_rapids_tpu.utils import tracing
        trace = None
        if self.trace_ops:
            from jax.profiler import TraceAnnotation
            trace = TraceAnnotation
        it = self.do_execute()
        timer = self.metrics[OP_TIME]
        name = self.node_name()
        while True:
            t0 = time.perf_counter_ns()
            try:
                # single branch per pull when tracing is off; spans
                # nest through the child iterator pulls, so the
                # rollup's exclusive time per operator matches the
                # opTimeSelf discipline at span granularity.  The
                # profile.trace jax annotation composes (nests inside
                # the span) rather than being displaced by it.
                if tracing._armed:
                    with tracing.span("operator.batch", op=name):
                        if trace is not None:
                            with trace(name):
                                batch = next(it)
                        else:
                            batch = next(it)
                elif trace is not None:
                    with trace(name):
                        batch = next(it)
                else:
                    batch = next(it)
            except StopIteration:
                timer.add(time.perf_counter_ns() - t0)
                return
            timer.add(time.perf_counter_ns() - t0)
            # row_count, not nrows: a deferred device-resident count
            # accumulates lazily instead of forcing a per-batch sync
            self.metrics[NUM_OUTPUT_ROWS] += batch.row_count
            self.metrics[NUM_OUTPUT_BATCHES] += 1
            yield batch

    def do_execute(self) -> Iterator[ColumnarBatch]:
        raise NotImplementedError

    # ---- plan display --------------------------------------------------------
    def node_name(self) -> str:
        return type(self).__name__

    def describe(self) -> str:
        return self.node_name()

    def tree_string(self) -> str:
        from spark_rapids_tpu.utils.trees import render_tree
        return render_tree(self)

    def collect_metrics(self) -> Dict[str, Dict[str, int]]:
        """Per-node metric dicts keyed by tree path.  ``opTime`` is
        inclusive of the child subtree (iterator pulls); the derived
        ``opTimeSelf`` subtracts direct children so consumers can
        aggregate without double counting."""
        out = {}

        def rec(node, path):
            key = f"{path}{node.node_name()}"
            m = {metric.name: metric.value
                 for metric in node.metrics.values()}
            child_time = sum(c.metrics[OP_TIME].value
                             for c in node.children)
            m["opTimeSelf"] = max(m.get(OP_TIME, 0) - child_time, 0)
            out[key] = m
            for i, c in enumerate(node.children):
                rec(c, f"{key}.{i}.")
        rec(self, "")
        return out
