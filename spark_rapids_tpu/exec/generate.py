"""Generate (explode/posexplode) physical operator.

Counterpart of ``GpuGenerateExec.scala`` (559 LoC).  Where cudf explodes
via a libcudf gather table, the TPU version is a single fused XLA program:
the flat element buffer of the array column already IS the output rows —
one ``searchsorted`` over the offsets maps every element to its source
row, pass-through columns are gathered by that map (string columns rebuild
their offsets inside ``selection.gather``), and the position column is
``arange - offsets[row]``.  No per-row work at any point.
"""

from __future__ import annotations

from typing import Iterator, List, Sequence

import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.exec.base import Schema, TpuExec
from spark_rapids_tpu.ops import selection
from spark_rapids_tpu.ops.compiler import StageFn
from spark_rapids_tpu.ops.expressions import ColVal, Expression


class TpuGenerateExec(TpuExec):
    def __init__(self, generator: Expression, required: Sequence[Expression],
                 position: bool, child: TpuExec,
                 col_name: str = "col", pos_name: str = "pos",
                 generator2: Expression = None):
        super().__init__(child)
        self.generator = generator
        # map explode: generator2 is the value array (same offsets as
        # the key array in `generator`), emitted as a second column
        self.generator2 = generator2
        self.required = list(required)
        self.position = position
        self.col_name = col_name
        self.pos_name = pos_name
        in_dtypes = [dt for _, dt in child.schema]
        gens = [generator] + ([generator2] if generator2 is not None
                              else [])
        self._n_gens = len(gens)
        self._eval_fn = StageFn(gens + self.required, in_dtypes)

    @property
    def child(self) -> TpuExec:
        return self.children[0]

    @property
    def schema(self) -> Schema:
        out = [(e.name, e.dtype) for e in self.required]
        if self.position:
            out.append((self.pos_name, dts.INT32))
        if self.generator2 is not None:
            out.append(("key", self.generator.dtype.element))
            out.append(("value", self.generator2.dtype.element))
        else:
            out.append((self.col_name, self.generator.dtype.element))
        return out

    def describe(self):
        kind = "posexplode" if self.position else "explode"
        return f"TpuGenerateExec[{kind}({self.generator.name})]"

    def do_execute(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.ops.collections_ops import element_rows
        for batch in self.child.execute():
            if batch.nrows == 0:
                continue
            cols = self._eval_fn(batch)
            arr, req = cols[0], cols[self._n_gens:]
            arr2 = cols[1] if self._n_gens == 2 else None
            cap = batch.capacity
            acv = ColVal(arr.dtype, arr.data, arr.validity, arr.offsets)
            total = int(arr.offsets[batch.nrows])
            ecap = arr.data.shape[0]
            row = element_rows(acv, cap)
            req_cvs = [ColVal(c.dtype, c.data, c.validity, c.offsets)
                       for c in req]
            char_cap = 0
            for c in req_cvs:
                if c.offsets is not None:  # strings AND arrays duplicate
                    cc = int(selection.gathered_char_count(
                        c.offsets, row, jnp.int32(total)))
                    char_cap = max(char_cap, cc)
            from spark_rapids_tpu.columnar.column import bucket_capacity
            gathered = selection.gather(
                req_cvs, row, jnp.int32(total),
                char_capacity=bucket_capacity(char_cap) if char_cap else 0)
            out = {}
            for e, g in zip(self.required, gathered):
                out[e.name] = Column(g.dtype, g.values, total,
                                     validity=g.validity, offsets=g.offsets)
            if self.position:
                pos = jnp.arange(ecap, dtype=jnp.int32) - arr.offsets[row]
                out[self.pos_name] = Column(dts.INT32, pos, total)
            if arr2 is not None:
                out["key"] = Column(self.generator.dtype.element,
                                    arr.data, total)
                out["value"] = Column(self.generator2.dtype.element,
                                      arr2.data, total)
            else:
                out[self.col_name] = Column(self.generator.dtype.element,
                                            arr.data, total)
            yield ColumnarBatch(out, total)
