"""TPC-H: synthetic data generator + query pipelines.

The engine's "model zoo": BASELINE.md configs 1-2 call for TPC-H q6 and the
22-query suite.  ``gen_tables(sf)`` produces schema-faithful synthetic data
(uniform approximations of the spec's distributions — enough for perf work
and CPU-oracle correctness testing; it is not a dbgen replacement), and
``QUERIES`` maps query names to DataFrame-API pipelines.

Dates are date32 columns; money columns are float64 (the reference snapshot
has decimals disabled by default too, RapidsConf.scala:564).
"""

from __future__ import annotations

import datetime
from typing import Callable, Dict

import numpy as np
import pandas as pd

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.dataframe import DataFrame
from spark_rapids_tpu.api.session import TpuSession


def _d(s: str):
    return np.datetime64(s, "D").astype("datetime64[D]")


SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
RETURNFLAGS = ["R", "A", "N"]
LINESTATUS = ["O", "F"]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = ["ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "EGYPT", "ETHIOPIA",
           "FRANCE", "GERMANY", "INDIA", "INDONESIA", "IRAN", "IRAQ",
           "JAPAN", "JORDAN", "KENYA", "MOROCCO", "MOZAMBIQUE", "PERU",
           "CHINA", "ROMANIA", "SAUDI ARABIA", "VIETNAM", "RUSSIA",
           "UNITED KINGDOM", "UNITED STATES"]
TYPES = [f"{a} {b} {c}" for a in ("STANDARD", "SMALL", "MEDIUM", "LARGE",
                                  "ECONOMY", "PROMO")
         for b in ("ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED")
         for c in ("TIN", "NICKEL", "BRASS", "STEEL", "COPPER")]


def gen_tables(sf: float = 0.01, seed: int = 7) -> Dict[str, pd.DataFrame]:
    rng = np.random.default_rng(seed)
    n_orders = max(int(1_500_000 * sf), 100)
    n_line = max(int(6_000_000 * sf), 400)
    n_cust = max(int(150_000 * sf), 50)
    n_part = max(int(200_000 * sf), 40)
    n_supp = max(int(10_000 * sf), 10)

    base = _d("1992-01-01")
    order_dates = base + rng.integers(0, 2405, n_orders)
    orders = pd.DataFrame({
        "o_orderkey": np.arange(1, n_orders + 1, dtype=np.int64),
        "o_custkey": rng.integers(1, n_cust + 1, n_orders),
        "o_orderstatus": rng.choice(["O", "F", "P"], n_orders),
        "o_totalprice": rng.uniform(800, 500000, n_orders).round(2),
        "o_orderdate": order_dates.astype("datetime64[D]"),
        "o_orderpriority": rng.choice(PRIORITIES, n_orders),
        "o_shippriority": np.zeros(n_orders, dtype=np.int32),
    })

    okeys = rng.integers(1, n_orders + 1, n_line)
    ship_delay = rng.integers(1, 122, n_line)
    odate_for_line = np.asarray(order_dates)[okeys - 1]
    shipdate = odate_for_line + ship_delay
    lineitem = pd.DataFrame({
        "l_orderkey": okeys.astype(np.int64),
        "l_partkey": rng.integers(1, n_part + 1, n_line),
        "l_suppkey": rng.integers(1, n_supp + 1, n_line),
        "l_linenumber": rng.integers(1, 8, n_line).astype(np.int32),
        "l_quantity": rng.integers(1, 51, n_line).astype(np.float64),
        "l_extendedprice": rng.uniform(900, 105000, n_line).round(2),
        "l_discount": (rng.integers(0, 11, n_line) / 100.0),
        "l_tax": (rng.integers(0, 9, n_line) / 100.0),
        "l_returnflag": rng.choice(RETURNFLAGS, n_line),
        "l_linestatus": rng.choice(LINESTATUS, n_line),
        "l_shipdate": shipdate.astype("datetime64[D]"),
        "l_commitdate": (odate_for_line +
                         rng.integers(30, 92, n_line)).astype(
                             "datetime64[D]"),
        "l_receiptdate": (shipdate +
                          rng.integers(1, 31, n_line)).astype(
                              "datetime64[D]"),
        "l_shipinstruct": rng.choice(
            ["DELIVER IN PERSON", "COLLECT COD", "NONE",
             "TAKE BACK RETURN"], n_line),
        "l_shipmode": rng.choice(SHIPMODES, n_line),
    })

    customer = pd.DataFrame({
        "c_custkey": np.arange(1, n_cust + 1, dtype=np.int64),
        "c_name": [f"Customer#{i:09d}" for i in range(1, n_cust + 1)],
        "c_nationkey": rng.integers(0, 25, n_cust).astype(np.int64),
        "c_acctbal": rng.uniform(-999, 9999, n_cust).round(2),
        "c_mktsegment": rng.choice(SEGMENTS, n_cust),
    })

    part = pd.DataFrame({
        "p_partkey": np.arange(1, n_part + 1, dtype=np.int64),
        "p_name": [f"part {i}" for i in range(1, n_part + 1)],
        "p_brand": [f"Brand#{rng.integers(1, 6)}{rng.integers(1, 6)}"
                    for _ in range(n_part)],
        "p_type": rng.choice(TYPES, n_part),
        "p_size": rng.integers(1, 51, n_part).astype(np.int32),
        "p_container": rng.choice(
            ["SM CASE", "SM BOX", "MED BAG", "MED BOX", "LG CASE",
             "LG BOX", "JUMBO PKG", "WRAP PACK"], n_part),
        "p_retailprice": rng.uniform(900, 2000, n_part).round(2),
    })

    supplier = pd.DataFrame({
        "s_suppkey": np.arange(1, n_supp + 1, dtype=np.int64),
        "s_name": [f"Supplier#{i:09d}" for i in range(1, n_supp + 1)],
        "s_nationkey": rng.integers(0, 25, n_supp).astype(np.int64),
        "s_acctbal": rng.uniform(-999, 9999, n_supp).round(2),
    })

    nation = pd.DataFrame({
        "n_nationkey": np.arange(25, dtype=np.int64),
        "n_name": NATIONS,
        "n_regionkey": np.arange(25, dtype=np.int64) % 5,
    })
    region = pd.DataFrame({
        "r_regionkey": np.arange(5, dtype=np.int64),
        "r_name": REGIONS,
    })
    return {"lineitem": lineitem, "orders": orders, "customer": customer,
            "part": part, "supplier": supplier, "nation": nation,
            "region": region}


def load(session: TpuSession, tables: Dict[str, pd.DataFrame]
         ) -> Dict[str, DataFrame]:
    return {name: session.create_dataframe(df)
            for name, df in tables.items()}


# ------------------------------------------------------------------- queries

def q1(t: Dict[str, DataFrame]) -> DataFrame:
    """Pricing summary report."""
    l = t["lineitem"]
    disc_price = F.col("l_extendedprice") * (1 - F.col("l_discount"))
    charge = disc_price * (1 + F.col("l_tax"))
    return (l.filter(F.col("l_shipdate") <=
                     F.lit(datetime.date(1998, 9, 2)))
            .groupBy("l_returnflag", "l_linestatus")
            .agg(F.sum("l_quantity").alias("sum_qty"),
                 F.sum("l_extendedprice").alias("sum_base_price"),
                 F.sum(disc_price).alias("sum_disc_price"),
                 F.sum(charge).alias("sum_charge"),
                 F.avg("l_quantity").alias("avg_qty"),
                 F.avg("l_extendedprice").alias("avg_price"),
                 F.avg("l_discount").alias("avg_disc"),
                 F.count().alias("count_order"))
            .orderBy("l_returnflag", "l_linestatus"))


def q3(t: Dict[str, DataFrame]) -> DataFrame:
    """Shipping priority."""
    cutoff = datetime.date(1995, 3, 15)
    c = t["customer"].filter(F.col("c_mktsegment") == F.lit("BUILDING"))
    o = t["orders"].filter(F.col("o_orderdate") < F.lit(cutoff))
    l = t["lineitem"].filter(F.col("l_shipdate") > F.lit(cutoff))
    rev = F.col("l_extendedprice") * (1 - F.col("l_discount"))
    joined = c.select("c_custkey") \
        .withColumnRenamed("c_custkey", "o_custkey") \
        .join(o, on="o_custkey", how="inner")
    joined = joined.withColumnRenamed("o_orderkey", "l_orderkey") \
        .join(l, on="l_orderkey", how="inner")
    return (joined.groupBy("l_orderkey", "o_orderdate", "o_shippriority")
            .agg(F.sum(rev).alias("revenue"))
            .orderBy(F.col("revenue").desc(), "o_orderdate")
            .limit(10))


def q5(t: Dict[str, DataFrame]) -> DataFrame:
    """Local supplier volume: ASIA, 1994."""
    o = t["orders"].filter(
        (F.col("o_orderdate") >= F.lit(datetime.date(1994, 1, 1))) &
        (F.col("o_orderdate") < F.lit(datetime.date(1995, 1, 1))))
    r = t["region"].filter(F.col("r_name") == F.lit("ASIA"))
    n = t["nation"].withColumnRenamed("n_regionkey", "r_regionkey") \
        .join(r, on="r_regionkey", how="inner")
    s = t["supplier"].withColumnRenamed("s_nationkey", "n_nationkey") \
        .join(n.select("n_nationkey", "n_name"), on="n_nationkey")
    c = t["customer"].withColumnRenamed("c_nationkey", "n_nationkey")
    rev = F.col("l_extendedprice") * (1 - F.col("l_discount"))
    l = t["lineitem"].withColumnRenamed("l_suppkey", "s_suppkey")
    oc = o.withColumnRenamed("o_custkey", "c_custkey") \
        .join(c.select("c_custkey", "n_nationkey"), on="c_custkey")
    lo = l.withColumnRenamed("l_orderkey", "o_orderkey") \
        .join(oc.select("o_orderkey", "n_nationkey"), on="o_orderkey")
    # supplier nation must equal customer nation
    ls = lo.join(s.select("s_suppkey", "n_nationkey", "n_name")
                 .withColumnRenamed("n_nationkey", "s_nation")
                 .withColumnRenamed("n_name", "n_name"),
                 on="s_suppkey")
    same = ls.filter(F.col("n_nationkey") == F.col("s_nation"))
    return (same.groupBy("n_name").agg(F.sum(rev).alias("revenue"))
            .orderBy(F.col("revenue").desc()))


def q6(t: Dict[str, DataFrame]) -> DataFrame:
    """Forecasting revenue change (the benchmark slice)."""
    l = t["lineitem"]
    return (l.filter(
        (F.col("l_shipdate") >= F.lit(datetime.date(1994, 1, 1))) &
        (F.col("l_shipdate") < F.lit(datetime.date(1995, 1, 1))) &
        (F.col("l_discount") >= 0.05) & (F.col("l_discount") <= 0.07) &
        (F.col("l_quantity") < 24.0))
        .select((F.col("l_extendedprice") * F.col("l_discount"))
                .alias("rev"))
        .agg(F.sum("rev").alias("revenue")))


def q12(t: Dict[str, DataFrame]) -> DataFrame:
    """Shipping modes and order priority."""
    l = t["lineitem"].filter(
        (F.col("l_shipmode").isin("MAIL", "SHIP")) &
        (F.col("l_commitdate") < F.col("l_receiptdate")) &
        (F.col("l_shipdate") < F.col("l_commitdate")) &
        (F.col("l_receiptdate") >= F.lit(datetime.date(1994, 1, 1))) &
        (F.col("l_receiptdate") < F.lit(datetime.date(1995, 1, 1))))
    o = t["orders"]
    j = l.withColumnRenamed("l_orderkey", "o_orderkey") \
        .join(o.select("o_orderkey", "o_orderpriority"), on="o_orderkey")
    high = F.when((F.col("o_orderpriority") == F.lit("1-URGENT")) |
                  (F.col("o_orderpriority") == F.lit("2-HIGH")), 1) \
        .otherwise(0)
    low = F.when((F.col("o_orderpriority") != F.lit("1-URGENT")) &
                 (F.col("o_orderpriority") != F.lit("2-HIGH")), 1) \
        .otherwise(0)
    return (j.groupBy("l_shipmode")
            .agg(F.sum(high).alias("high_line_count"),
                 F.sum(low).alias("low_line_count"))
            .orderBy("l_shipmode"))


def q14(t: Dict[str, DataFrame]) -> DataFrame:
    """Promotion effect."""
    l = t["lineitem"].filter(
        (F.col("l_shipdate") >= F.lit(datetime.date(1995, 9, 1))) &
        (F.col("l_shipdate") < F.lit(datetime.date(1995, 10, 1))))
    p = t["part"]
    j = l.withColumnRenamed("l_partkey", "p_partkey") \
        .join(p.select("p_partkey", "p_type"), on="p_partkey")
    rev = F.col("l_extendedprice") * (1 - F.col("l_discount"))
    promo = F.when(F.col("p_type").like("PROMO%"), rev).otherwise(0.0)
    return j.agg((F.sum(promo) * 100.0).alias("promo_sum"),
                 F.sum(rev).alias("total_sum"))


QUERIES: Dict[str, Callable] = {
    "q1": q1, "q3": q3, "q5": q5, "q6": q6, "q12": q12, "q14": q14,
}
