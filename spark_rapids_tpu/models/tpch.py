"""TPC-H: synthetic data generator + query pipelines.

The engine's "model zoo": BASELINE.md configs 1-2 call for TPC-H q6 and the
22-query suite.  ``gen_tables(sf)`` produces schema-faithful synthetic data
(uniform approximations of the spec's distributions — enough for perf work
and CPU-oracle correctness testing; it is not a dbgen replacement), and
``QUERIES`` maps query names to DataFrame-API pipelines.

Dates are date32 columns; money columns are float64 (the reference snapshot
has decimals disabled by default too, RapidsConf.scala:564).
"""

from __future__ import annotations

import datetime
from typing import Callable, Dict

import numpy as np
import pandas as pd

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.dataframe import DataFrame
from spark_rapids_tpu.api.session import TpuSession


def _d(s: str):
    return np.datetime64(s, "D").astype("datetime64[D]")


SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
RETURNFLAGS = ["R", "A", "N"]
LINESTATUS = ["O", "F"]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = ["ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "EGYPT", "ETHIOPIA",
           "FRANCE", "GERMANY", "INDIA", "INDONESIA", "IRAN", "IRAQ",
           "JAPAN", "JORDAN", "KENYA", "MOROCCO", "MOZAMBIQUE", "PERU",
           "CHINA", "ROMANIA", "SAUDI ARABIA", "VIETNAM", "RUSSIA",
           "UNITED KINGDOM", "UNITED STATES"]
TYPES = [f"{a} {b} {c}" for a in ("STANDARD", "SMALL", "MEDIUM", "LARGE",
                                  "ECONOMY", "PROMO")
         for b in ("ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED")
         for c in ("TIN", "NICKEL", "BRASS", "STEEL", "COPPER")]
COLORS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
          "black", "blanched", "blue", "blush", "brown", "burlywood",
          "burnished", "chartreuse", "chiffon", "chocolate", "coral",
          "cornflower", "cornsilk", "cream", "cyan", "dark", "deep",
          "dim", "dodger", "drab", "firebrick", "floral", "forest",
          "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey",
          "honeydew", "hot", "hotpink", "indian", "ivory", "khaki",
          "lace", "lavender", "lawn", "lemon", "light", "lime", "linen",
          "magenta", "maroon", "medium", "metallic", "midnight", "mint",
          "misty", "moccasin", "navajo", "navy", "olive", "orange",
          "orchid", "pale", "papaya", "peach", "peru", "pink", "plum",
          "powder", "puff", "purple", "red", "rose", "rosy", "royal",
          "saddle", "salmon", "sandy", "seashell", "sienna", "sky",
          "slate", "smoke", "snow", "spring", "steel", "tan", "thistle",
          "tomato", "turquoise", "violet", "wheat", "white", "yellow"]
COMMENT_WORDS = ["carefully", "quickly", "furiously", "slyly", "blithely",
                 "pending", "final", "express", "regular", "ironic",
                 "deposits", "packages", "accounts", "theodolites",
                 "instructions", "foxes", "pinto", "beans", "requests",
                 "special", "even", "bold", "unusual", "silent"]


def gen_tables(sf: float = 0.01, seed: int = 7) -> Dict[str, pd.DataFrame]:
    rng = np.random.default_rng(seed)
    n_orders = max(int(1_500_000 * sf), 100)
    n_line = max(int(6_000_000 * sf), 400)
    n_cust = max(int(150_000 * sf), 50)
    n_part = max(int(200_000 * sf), 40)
    n_supp = max(int(10_000 * sf), 10)

    def comments(n, special_frac=0.05):
        w = rng.choice(COMMENT_WORDS, (n, 4))
        out = np.array([" ".join(r) for r in w], dtype=object)
        k = max(int(n * special_frac), 1)
        idx = rng.choice(n, k, replace=False)
        out[idx] = np.array(
            [f"{a} special {b} requests {c}"
             for a, b, c in rng.choice(COMMENT_WORDS, (k, 3))],
            dtype=object)
        return out

    base = _d("1992-01-01")
    order_dates = base + rng.integers(0, 2405, n_orders)
    # spec: customers with custkey % 3 == 0 place no orders (drives q13/q22)
    with_orders = np.arange(1, n_cust + 1, dtype=np.int64)
    with_orders = with_orders[with_orders % 3 != 0]
    orders = pd.DataFrame({
        "o_orderkey": np.arange(1, n_orders + 1, dtype=np.int64),
        "o_custkey": rng.choice(with_orders, n_orders),
        "o_orderstatus": rng.choice(["O", "F", "P"], n_orders),
        "o_totalprice": rng.uniform(800, 500000, n_orders).round(2),
        "o_orderdate": order_dates.astype("datetime64[D]"),
        "o_orderpriority": rng.choice(PRIORITIES, n_orders),
        "o_shippriority": np.zeros(n_orders, dtype=np.int32),
        "o_comment": comments(n_orders),
    })

    okeys = rng.integers(1, n_orders + 1, n_line)
    ship_delay = rng.integers(1, 122, n_line)
    odate_for_line = np.asarray(order_dates)[okeys - 1]
    shipdate = odate_for_line + ship_delay
    lineitem = pd.DataFrame({
        "l_orderkey": okeys.astype(np.int64),
        "l_partkey": rng.integers(1, n_part + 1, n_line),
        "l_suppkey": rng.integers(1, n_supp + 1, n_line),
        "l_linenumber": rng.integers(1, 8, n_line).astype(np.int32),
        "l_quantity": rng.integers(1, 51, n_line).astype(np.float64),
        "l_extendedprice": rng.uniform(900, 105000, n_line).round(2),
        "l_discount": (rng.integers(0, 11, n_line) / 100.0),
        "l_tax": (rng.integers(0, 9, n_line) / 100.0),
        "l_returnflag": rng.choice(RETURNFLAGS, n_line),
        "l_linestatus": rng.choice(LINESTATUS, n_line),
        "l_shipdate": shipdate.astype("datetime64[D]"),
        "l_commitdate": (odate_for_line +
                         rng.integers(30, 92, n_line)).astype(
                             "datetime64[D]"),
        "l_receiptdate": (shipdate +
                          rng.integers(1, 31, n_line)).astype(
                              "datetime64[D]"),
        "l_shipinstruct": rng.choice(
            ["DELIVER IN PERSON", "COLLECT COD", "NONE",
             "TAKE BACK RETURN"], n_line),
        "l_shipmode": rng.choice(SHIPMODES, n_line),
    })

    cnation = rng.integers(0, 25, n_cust).astype(np.int64)
    customer = pd.DataFrame({
        "c_custkey": np.arange(1, n_cust + 1, dtype=np.int64),
        "c_name": [f"Customer#{i:09d}" for i in range(1, n_cust + 1)],
        "c_nationkey": cnation,
        "c_phone": [f"{nk + 10}-{rng.integers(100, 999)}-"
                    f"{rng.integers(100, 999)}-{rng.integers(1000, 9999)}"
                    for nk in cnation],
        "c_acctbal": rng.uniform(-999, 9999, n_cust).round(2),
        "c_mktsegment": rng.choice(SEGMENTS, n_cust),
        "c_comment": comments(n_cust),
    })

    name_words = rng.choice(COLORS, (n_part, 5))
    part = pd.DataFrame({
        "p_partkey": np.arange(1, n_part + 1, dtype=np.int64),
        "p_name": [" ".join(r) for r in name_words],
        "p_mfgr": [f"Manufacturer#{rng.integers(1, 6)}"
                   for _ in range(n_part)],
        "p_brand": [f"Brand#{rng.integers(1, 6)}{rng.integers(1, 6)}"
                    for _ in range(n_part)],
        "p_type": rng.choice(TYPES, n_part),
        "p_size": rng.integers(1, 51, n_part).astype(np.int32),
        "p_container": rng.choice(
            ["SM CASE", "SM BOX", "MED BAG", "MED BOX", "LG CASE",
             "LG BOX", "JUMBO PKG", "WRAP PACK"], n_part),
        "p_retailprice": rng.uniform(900, 2000, n_part).round(2),
    })

    scomment = comments(n_supp)
    k = max(n_supp // 20, 1)
    idx = rng.choice(n_supp, k, replace=False)
    scomment[idx] = np.array(
        [f"{a} Customer {b} Complaints {c}"
         for a, b, c in rng.choice(COMMENT_WORDS, (k, 3))], dtype=object)
    supplier = pd.DataFrame({
        "s_suppkey": np.arange(1, n_supp + 1, dtype=np.int64),
        "s_name": [f"Supplier#{i:09d}" for i in range(1, n_supp + 1)],
        "s_address": [f"addr {i}" for i in range(1, n_supp + 1)],
        "s_nationkey": rng.integers(0, 25, n_supp).astype(np.int64),
        "s_phone": [f"{rng.integers(10, 35)}-{rng.integers(100, 999)}-"
                    f"{rng.integers(100, 999)}-{rng.integers(1000, 9999)}"
                    for _ in range(n_supp)],
        "s_acctbal": rng.uniform(-999, 9999, n_supp).round(2),
        "s_comment": scomment,
    })

    # partsupp: each part has 4 suppliers; spec formula
    # s = (p + i*(S/4 + (p-1)/S)) % S + 1 guarantees distinct suppliers
    ps_part = np.repeat(np.arange(1, n_part + 1, dtype=np.int64), 4)
    i = np.tile(np.arange(4, dtype=np.int64), n_part)
    ps_supp = ((ps_part + i * (n_supp // 4 + (ps_part - 1) // n_supp))
               % n_supp) + 1
    partsupp = pd.DataFrame({
        "ps_partkey": ps_part,
        "ps_suppkey": ps_supp,
        "ps_availqty": rng.integers(1, 10000, len(ps_part)).astype(
            np.int32),
        "ps_supplycost": rng.uniform(1, 1000, len(ps_part)).round(2),
    })

    nation = pd.DataFrame({
        "n_nationkey": np.arange(25, dtype=np.int64),
        "n_name": NATIONS,
        "n_regionkey": np.arange(25, dtype=np.int64) % 5,
    })
    region = pd.DataFrame({
        "r_regionkey": np.arange(5, dtype=np.int64),
        "r_name": REGIONS,
    })
    return {"lineitem": lineitem, "orders": orders, "customer": customer,
            "part": part, "supplier": supplier, "partsupp": partsupp,
            "nation": nation, "region": region}


def load(session: TpuSession, tables: Dict[str, pd.DataFrame]
         ) -> Dict[str, DataFrame]:
    return {name: session.create_dataframe(df)
            for name, df in tables.items()}


# ------------------------------------------------------------------- queries

def _join(left: DataFrame, right: DataFrame, lk, rk=None,
          how: str = "inner") -> DataFrame:
    """Join helper: renames right-side keys to the left-side names so the
    using-columns join applies, mirroring the rename-then-join idiom."""
    lk = [lk] if isinstance(lk, str) else list(lk)
    rk = lk if rk is None else ([rk] if isinstance(rk, str) else list(rk))
    for a, b in zip(lk, rk):
        if a != b:
            right = right.withColumnRenamed(b, a)
    return left.join(right, on=lk, how=how)


def q1(t: Dict[str, DataFrame]) -> DataFrame:
    """Pricing summary report."""
    l = t["lineitem"]
    disc_price = F.col("l_extendedprice") * (1 - F.col("l_discount"))
    charge = disc_price * (1 + F.col("l_tax"))
    return (l.filter(F.col("l_shipdate") <=
                     F.lit(datetime.date(1998, 9, 2)))
            .groupBy("l_returnflag", "l_linestatus")
            .agg(F.sum("l_quantity").alias("sum_qty"),
                 F.sum("l_extendedprice").alias("sum_base_price"),
                 F.sum(disc_price).alias("sum_disc_price"),
                 F.sum(charge).alias("sum_charge"),
                 F.avg("l_quantity").alias("avg_qty"),
                 F.avg("l_extendedprice").alias("avg_price"),
                 F.avg("l_discount").alias("avg_disc"),
                 F.count().alias("count_order"))
            .orderBy("l_returnflag", "l_linestatus"))


def q3(t: Dict[str, DataFrame]) -> DataFrame:
    """Shipping priority."""
    cutoff = datetime.date(1995, 3, 15)
    c = t["customer"].filter(F.col("c_mktsegment") == F.lit("BUILDING"))
    o = t["orders"].filter(F.col("o_orderdate") < F.lit(cutoff))
    l = t["lineitem"].filter(F.col("l_shipdate") > F.lit(cutoff))
    rev = F.col("l_extendedprice") * (1 - F.col("l_discount"))
    joined = c.select("c_custkey") \
        .withColumnRenamed("c_custkey", "o_custkey") \
        .join(o, on="o_custkey", how="inner")
    joined = joined.withColumnRenamed("o_orderkey", "l_orderkey") \
        .join(l, on="l_orderkey", how="inner")
    return (joined.groupBy("l_orderkey", "o_orderdate", "o_shippriority")
            .agg(F.sum(rev).alias("revenue"))
            .orderBy(F.col("revenue").desc(), "o_orderdate")
            .limit(10))


def q5(t: Dict[str, DataFrame]) -> DataFrame:
    """Local supplier volume: ASIA, 1994."""
    o = t["orders"].filter(
        (F.col("o_orderdate") >= F.lit(datetime.date(1994, 1, 1))) &
        (F.col("o_orderdate") < F.lit(datetime.date(1995, 1, 1))))
    r = t["region"].filter(F.col("r_name") == F.lit("ASIA"))
    n = t["nation"].withColumnRenamed("n_regionkey", "r_regionkey") \
        .join(r, on="r_regionkey", how="inner")
    s = t["supplier"].withColumnRenamed("s_nationkey", "n_nationkey") \
        .join(n.select("n_nationkey", "n_name"), on="n_nationkey")
    c = t["customer"].withColumnRenamed("c_nationkey", "n_nationkey")
    rev = F.col("l_extendedprice") * (1 - F.col("l_discount"))
    l = t["lineitem"].withColumnRenamed("l_suppkey", "s_suppkey")
    oc = o.withColumnRenamed("o_custkey", "c_custkey") \
        .join(c.select("c_custkey", "n_nationkey"), on="c_custkey")
    lo = l.withColumnRenamed("l_orderkey", "o_orderkey") \
        .join(oc.select("o_orderkey", "n_nationkey"), on="o_orderkey")
    # supplier nation must equal customer nation
    ls = lo.join(s.select("s_suppkey", "n_nationkey", "n_name")
                 .withColumnRenamed("n_nationkey", "s_nation")
                 .withColumnRenamed("n_name", "n_name"),
                 on="s_suppkey")
    same = ls.filter(F.col("n_nationkey") == F.col("s_nation"))
    return (same.groupBy("n_name").agg(F.sum(rev).alias("revenue"))
            .orderBy(F.col("revenue").desc()))


def q6(t: Dict[str, DataFrame]) -> DataFrame:
    """Forecasting revenue change (the benchmark slice)."""
    l = t["lineitem"]
    return (l.filter(
        (F.col("l_shipdate") >= F.lit(datetime.date(1994, 1, 1))) &
        (F.col("l_shipdate") < F.lit(datetime.date(1995, 1, 1))) &
        (F.col("l_discount") >= 0.05) & (F.col("l_discount") <= 0.07) &
        (F.col("l_quantity") < 24.0))
        .select((F.col("l_extendedprice") * F.col("l_discount"))
                .alias("rev"))
        .agg(F.sum("rev").alias("revenue")))


def q12(t: Dict[str, DataFrame]) -> DataFrame:
    """Shipping modes and order priority."""
    l = t["lineitem"].filter(
        (F.col("l_shipmode").isin("MAIL", "SHIP")) &
        (F.col("l_commitdate") < F.col("l_receiptdate")) &
        (F.col("l_shipdate") < F.col("l_commitdate")) &
        (F.col("l_receiptdate") >= F.lit(datetime.date(1994, 1, 1))) &
        (F.col("l_receiptdate") < F.lit(datetime.date(1995, 1, 1))))
    o = t["orders"]
    j = l.withColumnRenamed("l_orderkey", "o_orderkey") \
        .join(o.select("o_orderkey", "o_orderpriority"), on="o_orderkey")
    high = F.when((F.col("o_orderpriority") == F.lit("1-URGENT")) |
                  (F.col("o_orderpriority") == F.lit("2-HIGH")), 1) \
        .otherwise(0)
    low = F.when((F.col("o_orderpriority") != F.lit("1-URGENT")) &
                 (F.col("o_orderpriority") != F.lit("2-HIGH")), 1) \
        .otherwise(0)
    return (j.groupBy("l_shipmode")
            .agg(F.sum(high).alias("high_line_count"),
                 F.sum(low).alias("low_line_count"))
            .orderBy("l_shipmode"))


def q14(t: Dict[str, DataFrame]) -> DataFrame:
    """Promotion effect."""
    l = t["lineitem"].filter(
        (F.col("l_shipdate") >= F.lit(datetime.date(1995, 9, 1))) &
        (F.col("l_shipdate") < F.lit(datetime.date(1995, 10, 1))))
    p = t["part"]
    j = l.withColumnRenamed("l_partkey", "p_partkey") \
        .join(p.select("p_partkey", "p_type"), on="p_partkey")
    rev = F.col("l_extendedprice") * (1 - F.col("l_discount"))
    promo = F.when(F.col("p_type").like("PROMO%"), rev).otherwise(0.0)
    return j.agg((F.sum(promo) * 100.0).alias("promo_sum"),
                 F.sum(rev).alias("total_sum"))


def q2(t: Dict[str, DataFrame]) -> DataFrame:
    """Minimum cost supplier: size-15 %BRASS parts, EUROPE."""
    p = t["part"].filter((F.col("p_size") == 15) &
                         F.col("p_type").like("%BRASS"))
    r = t["region"].filter(F.col("r_name") == F.lit("EUROPE"))
    n = _join(t["nation"], r.select("r_regionkey"),
              "n_regionkey", "r_regionkey")
    s = _join(t["supplier"], n.select("n_nationkey", "n_name"),
              "s_nationkey", "n_nationkey")
    ps = _join(t["partsupp"], p.select("p_partkey", "p_mfgr"),
               "ps_partkey", "p_partkey")
    ps = _join(ps, s.select("s_suppkey", "s_acctbal", "s_name", "s_address",
                            "s_phone", "n_name"),
               "ps_suppkey", "s_suppkey")
    minc = ps.groupBy("ps_partkey").agg(
        F.min("ps_supplycost").alias("min_cost"))
    best = _join(ps, minc, "ps_partkey").filter(
        F.col("ps_supplycost") == F.col("min_cost"))
    return (best.select("s_acctbal", "s_name", "n_name", "ps_partkey",
                        "p_mfgr", "s_address", "s_phone")
            .orderBy(F.col("s_acctbal").desc(), "n_name", "s_name",
                     "ps_partkey")
            .limit(100))


def q4(t: Dict[str, DataFrame]) -> DataFrame:
    """Order priority checking (EXISTS -> semi join)."""
    o = t["orders"].filter(
        (F.col("o_orderdate") >= F.lit(datetime.date(1993, 7, 1))) &
        (F.col("o_orderdate") < F.lit(datetime.date(1993, 10, 1))))
    late = t["lineitem"].filter(
        F.col("l_commitdate") < F.col("l_receiptdate")) \
        .select("l_orderkey")
    j = _join(o, late, "o_orderkey", "l_orderkey", how="semi")
    return (j.groupBy("o_orderpriority")
            .agg(F.count().alias("order_count"))
            .orderBy("o_orderpriority"))


def q7(t: Dict[str, DataFrame]) -> DataFrame:
    """Volume shipping FRANCE <-> GERMANY."""
    n = t["nation"].select("n_nationkey", "n_name")
    s = _join(t["supplier"].select("s_suppkey", "s_nationkey"),
              n.withColumnRenamed("n_name", "supp_nation"),
              "s_nationkey", "n_nationkey")
    c = _join(t["customer"].select("c_custkey", "c_nationkey"),
              n.withColumnRenamed("n_name", "cust_nation"),
              "c_nationkey", "n_nationkey")
    o = _join(t["orders"].select("o_orderkey", "o_custkey"),
              c.select("c_custkey", "cust_nation"), "o_custkey", "c_custkey")
    l = t["lineitem"].filter(
        (F.col("l_shipdate") >= F.lit(datetime.date(1995, 1, 1))) &
        (F.col("l_shipdate") <= F.lit(datetime.date(1996, 12, 31))))
    j = _join(l, o.select("o_orderkey", "cust_nation"),
              "l_orderkey", "o_orderkey")
    j = _join(j, s.select("s_suppkey", "supp_nation"),
              "l_suppkey", "s_suppkey")
    j = j.filter(
        ((F.col("supp_nation") == F.lit("FRANCE")) &
         (F.col("cust_nation") == F.lit("GERMANY"))) |
        ((F.col("supp_nation") == F.lit("GERMANY")) &
         (F.col("cust_nation") == F.lit("FRANCE"))))
    j = j.withColumn("l_year", F.year(F.col("l_shipdate"))) \
        .withColumn("volume",
                    F.col("l_extendedprice") * (1 - F.col("l_discount")))
    return (j.groupBy("supp_nation", "cust_nation", "l_year")
            .agg(F.sum("volume").alias("revenue"))
            .orderBy("supp_nation", "cust_nation", "l_year"))


def q8(t: Dict[str, DataFrame]) -> DataFrame:
    """National market share: BRAZIL in AMERICA, ECONOMY ANODIZED STEEL."""
    p = t["part"].filter(
        F.col("p_type") == F.lit("ECONOMY ANODIZED STEEL")) \
        .select("p_partkey")
    n2 = t["nation"].select("n_nationkey", "n_name") \
        .withColumnRenamed("n_name", "nation")
    s = _join(t["supplier"].select("s_suppkey", "s_nationkey"), n2,
              "s_nationkey", "n_nationkey")
    r = t["region"].filter(F.col("r_name") == F.lit("AMERICA"))
    n1 = _join(t["nation"].select("n_nationkey", "n_regionkey"),
               r.select("r_regionkey"), "n_regionkey", "r_regionkey",
               how="semi")
    c = _join(t["customer"].select("c_custkey", "c_nationkey"),
              n1.select("n_nationkey"), "c_nationkey", "n_nationkey",
              how="semi")
    o = t["orders"].filter(
        (F.col("o_orderdate") >= F.lit(datetime.date(1995, 1, 1))) &
        (F.col("o_orderdate") <= F.lit(datetime.date(1996, 12, 31)))) \
        .select("o_orderkey", "o_custkey", "o_orderdate")
    o = _join(o, c.select("c_custkey"), "o_custkey", "c_custkey",
              how="semi")
    l = _join(t["lineitem"], p, "l_partkey", "p_partkey", how="semi")
    j = _join(l, o.select("o_orderkey", "o_orderdate"),
              "l_orderkey", "o_orderkey")
    j = _join(j, s.select("s_suppkey", "nation"), "l_suppkey", "s_suppkey")
    j = j.withColumn("o_year", F.year(F.col("o_orderdate"))) \
        .withColumn("volume",
                    F.col("l_extendedprice") * (1 - F.col("l_discount")))
    brazil = F.when(F.col("nation") == F.lit("BRAZIL"),
                    F.col("volume")).otherwise(0.0)
    agg = j.groupBy("o_year").agg(F.sum(brazil).alias("brazil_vol"),
                                  F.sum("volume").alias("total_vol"))
    return (agg.withColumn("mkt_share",
                           F.col("brazil_vol") / F.col("total_vol"))
            .select("o_year", "mkt_share").orderBy("o_year"))


def q9(t: Dict[str, DataFrame]) -> DataFrame:
    """Product type profit measure: parts named %green%."""
    p = t["part"].filter(F.col("p_name").contains("green")) \
        .select("p_partkey")
    l = _join(t["lineitem"], p, "l_partkey", "p_partkey", how="semi")
    n = t["nation"].select("n_nationkey", "n_name") \
        .withColumnRenamed("n_name", "nation")
    s = _join(t["supplier"].select("s_suppkey", "s_nationkey"), n,
              "s_nationkey", "n_nationkey")
    j = _join(l, s.select("s_suppkey", "nation"), "l_suppkey", "s_suppkey")
    j = _join(j, t["partsupp"].select("ps_partkey", "ps_suppkey",
                                      "ps_supplycost"),
              ["l_partkey", "l_suppkey"], ["ps_partkey", "ps_suppkey"])
    j = _join(j, t["orders"].select("o_orderkey", "o_orderdate"),
              "l_orderkey", "o_orderkey")
    j = j.withColumn("o_year", F.year(F.col("o_orderdate"))) \
        .withColumn(
            "amount",
            F.col("l_extendedprice") * (1 - F.col("l_discount")) -
            F.col("ps_supplycost") * F.col("l_quantity"))
    return (j.groupBy("nation", "o_year")
            .agg(F.sum("amount").alias("sum_profit"))
            .orderBy("nation", F.col("o_year").desc()))


def q10(t: Dict[str, DataFrame]) -> DataFrame:
    """Returned item reporting: top 20 customers by lost revenue."""
    o = t["orders"].filter(
        (F.col("o_orderdate") >= F.lit(datetime.date(1993, 10, 1))) &
        (F.col("o_orderdate") < F.lit(datetime.date(1994, 1, 1)))) \
        .select("o_orderkey", "o_custkey")
    l = t["lineitem"].filter(F.col("l_returnflag") == F.lit("R"))
    j = _join(l, o, "l_orderkey", "o_orderkey")
    j = _join(j, t["customer"].select("c_custkey", "c_name", "c_acctbal",
                                      "c_phone", "c_nationkey",
                                      "c_comment"),
              "o_custkey", "c_custkey")
    j = _join(j, t["nation"].select("n_nationkey", "n_name"),
              "c_nationkey", "n_nationkey")
    rev = F.col("l_extendedprice") * (1 - F.col("l_discount"))
    return (j.groupBy("o_custkey", "c_name", "c_acctbal", "c_phone",
                      "n_name", "c_comment")
            .agg(F.sum(rev).alias("revenue"))
            .orderBy(F.col("revenue").desc())
            .limit(20))


def q11(t: Dict[str, DataFrame], fraction: float = 0.0001) -> DataFrame:
    """Important stock identification (HAVING with scalar subquery)."""
    g = t["nation"].filter(F.col("n_name") == F.lit("GERMANY")) \
        .select("n_nationkey")
    s = _join(t["supplier"].select("s_suppkey", "s_nationkey"), g,
              "s_nationkey", "n_nationkey", how="semi")
    ps = _join(t["partsupp"], s.select("s_suppkey"),
               "ps_suppkey", "s_suppkey", how="semi")
    value = F.col("ps_supplycost") * F.col("ps_availqty").cast("double")
    per_part = ps.groupBy("ps_partkey").agg(F.sum(value).alias("value"))
    total = per_part.agg(F.sum("value").alias("total")).collect()[0][0]
    return (per_part.filter(F.col("value") > float(total) * fraction)
            .orderBy(F.col("value").desc()))


def q13(t: Dict[str, DataFrame]) -> DataFrame:
    """Customer distribution (left outer join + count of non-null)."""
    o = t["orders"].filter(
        ~F.col("o_comment").like("%special%requests%")) \
        .select("o_orderkey", "o_custkey")
    j = _join(t["customer"].select("c_custkey"), o,
              "c_custkey", "o_custkey", how="left")
    per_cust = j.groupBy("c_custkey").agg(
        F.count(F.col("o_orderkey")).alias("c_count"))
    return (per_cust.groupBy("c_count").agg(F.count().alias("custdist"))
            .orderBy(F.col("custdist").desc(), F.col("c_count").desc()))


def q15(t: Dict[str, DataFrame]) -> DataFrame:
    """Top supplier (view + max scalar subquery)."""
    l = t["lineitem"].filter(
        (F.col("l_shipdate") >= F.lit(datetime.date(1996, 1, 1))) &
        (F.col("l_shipdate") < F.lit(datetime.date(1996, 4, 1))))
    rev = l.groupBy("l_suppkey").agg(
        F.sum(F.col("l_extendedprice") * (1 - F.col("l_discount")))
        .alias("total_revenue"))
    m = rev.agg(F.max("total_revenue").alias("m")).collect()[0][0]
    j = _join(t["supplier"].select("s_suppkey", "s_name", "s_address",
                                   "s_phone"),
              rev, "s_suppkey", "l_suppkey")
    return (j.filter(F.col("total_revenue") >= float(m))
            .select("s_suppkey", "s_name", "s_address", "s_phone",
                    "total_revenue")
            .orderBy("s_suppkey"))


def q16(t: Dict[str, DataFrame]) -> DataFrame:
    """Parts/supplier relationship (NOT IN -> anti join, count distinct)."""
    p = t["part"].filter(
        (F.col("p_brand") != F.lit("Brand#45")) &
        ~F.col("p_type").like("MEDIUM POLISHED%") &
        F.col("p_size").isin(49, 14, 23, 45, 19, 3, 36, 9))
    bad = t["supplier"].filter(
        F.col("s_comment").like("%Customer%Complaints%")) \
        .select("s_suppkey")
    ps = _join(t["partsupp"].select("ps_partkey", "ps_suppkey"), bad,
               "ps_suppkey", "s_suppkey", how="anti")
    j = _join(ps, p.select("p_partkey", "p_brand", "p_type", "p_size"),
              "ps_partkey", "p_partkey")
    d = j.select("p_brand", "p_type", "p_size", "ps_suppkey").distinct()
    return (d.groupBy("p_brand", "p_type", "p_size")
            .agg(F.count().alias("supplier_cnt"))
            .orderBy(F.col("supplier_cnt").desc(), "p_brand", "p_type",
                     "p_size"))


def q17(t: Dict[str, DataFrame]) -> DataFrame:
    """Small-quantity-order revenue (correlated avg subquery -> join)."""
    p = t["part"].filter((F.col("p_brand") == F.lit("Brand#23")) &
                         (F.col("p_container") == F.lit("MED BOX"))) \
        .select("p_partkey")
    l = _join(t["lineitem"].select("l_partkey", "l_quantity",
                                   "l_extendedprice"),
              p, "l_partkey", "p_partkey", how="semi")
    avgq = l.groupBy("l_partkey").agg(
        (F.avg("l_quantity") * 0.2).alias("qty_limit"))
    j = _join(l, avgq, "l_partkey")
    return (j.filter(F.col("l_quantity") < F.col("qty_limit"))
            .agg((F.sum("l_extendedprice") / 7.0).alias("avg_yearly")))


def q18(t: Dict[str, DataFrame], threshold: float = 300.0) -> DataFrame:
    """Large volume customer (IN subquery with HAVING)."""
    big = t["lineitem"].groupBy("l_orderkey").agg(
        F.sum("l_quantity").alias("sum_qty"))
    big = big.filter(F.col("sum_qty") > threshold)
    o = _join(t["orders"].select("o_orderkey", "o_custkey", "o_orderdate",
                                 "o_totalprice"),
              big, "o_orderkey", "l_orderkey")
    j = _join(o, t["customer"].select("c_custkey", "c_name"),
              "o_custkey", "c_custkey")
    return (j.select("c_name", "o_custkey", "o_orderkey", "o_orderdate",
                     "o_totalprice", "sum_qty")
            .orderBy(F.col("o_totalprice").desc(), "o_orderdate")
            .limit(100))


def q19(t: Dict[str, DataFrame]) -> DataFrame:
    """Discounted revenue (disjunction of conjunctive predicate groups)."""
    j = _join(t["lineitem"].select("l_partkey", "l_quantity",
                                   "l_extendedprice", "l_discount",
                                   "l_shipmode", "l_shipinstruct"),
              t["part"].select("p_partkey", "p_brand", "p_container",
                               "p_size"),
              "l_partkey", "p_partkey")
    qty, size = F.col("l_quantity"), F.col("p_size")
    g1 = (F.col("p_brand").like("Brand#1%") &
          F.col("p_container").isin("SM CASE", "SM BOX") &
          (qty >= 1) & (qty <= 11) & (size >= 1) & (size <= 15))
    g2 = (F.col("p_brand").like("Brand#2%") &
          F.col("p_container").isin("MED BAG", "MED BOX") &
          (qty >= 10) & (qty <= 20) & (size >= 1) & (size <= 25))
    g3 = (F.col("p_brand").like("Brand#3%") &
          F.col("p_container").isin("LG CASE", "LG BOX") &
          (qty >= 20) & (qty <= 30) & (size >= 1) & (size <= 35))
    common = (F.col("l_shipmode").isin("AIR", "REG AIR") &
              (F.col("l_shipinstruct") == F.lit("DELIVER IN PERSON")))
    rev = F.col("l_extendedprice") * (1 - F.col("l_discount"))
    return (j.filter(common & (g1 | g2 | g3))
            .agg(F.sum(rev).alias("revenue")))


def q20(t: Dict[str, DataFrame]) -> DataFrame:
    """Potential part promotion (nested IN subqueries -> semi joins)."""
    p = t["part"].filter(F.col("p_name").like("forest%")) \
        .select("p_partkey")
    qty = t["lineitem"].filter(
        (F.col("l_shipdate") >= F.lit(datetime.date(1994, 1, 1))) &
        (F.col("l_shipdate") < F.lit(datetime.date(1995, 1, 1)))) \
        .groupBy("l_partkey", "l_suppkey") \
        .agg((F.sum("l_quantity") * 0.5).alias("half_qty"))
    ps = _join(t["partsupp"].select("ps_partkey", "ps_suppkey",
                                    "ps_availqty"),
               p, "ps_partkey", "p_partkey", how="semi")
    ps = _join(ps, qty, ["ps_partkey", "ps_suppkey"],
               ["l_partkey", "l_suppkey"])
    good = ps.filter(F.col("ps_availqty").cast("double") >
                     F.col("half_qty")) \
        .select("ps_suppkey").distinct()
    s = _join(t["supplier"], good, "s_suppkey", "ps_suppkey", how="semi")
    n = t["nation"].filter(F.col("n_name") == F.lit("CANADA")) \
        .select("n_nationkey")
    s = _join(s, n, "s_nationkey", "n_nationkey", how="semi")
    return s.select("s_name", "s_address").orderBy("s_name")


def q21(t: Dict[str, DataFrame]) -> DataFrame:
    """Suppliers who kept orders waiting (EXISTS + NOT EXISTS)."""
    pairs = t["lineitem"].select("l_orderkey", "l_suppkey").distinct()
    cnt_all = pairs.groupBy("l_orderkey").agg(F.count().alias("n_supp"))
    late = t["lineitem"].filter(
        F.col("l_receiptdate") > F.col("l_commitdate")) \
        .select("l_orderkey", "l_suppkey")
    cnt_late = late.distinct().groupBy("l_orderkey").agg(
        F.count().alias("n_late"))
    o = t["orders"].filter(F.col("o_orderstatus") == F.lit("F")) \
        .select("o_orderkey")
    l1 = late
    j = _join(l1, o, "l_orderkey", "o_orderkey", how="semi")
    j = _join(j, cnt_all, "l_orderkey")
    j = _join(j, cnt_late, "l_orderkey")
    j = j.filter((F.col("n_supp") > 1) & (F.col("n_late") == 1))
    n = t["nation"].filter(F.col("n_name") == F.lit("SAUDI ARABIA")) \
        .select("n_nationkey")
    s = _join(t["supplier"].select("s_suppkey", "s_name", "s_nationkey"),
              n, "s_nationkey", "n_nationkey", how="semi")
    j = _join(j, s.select("s_suppkey", "s_name"),
              "l_suppkey", "s_suppkey")
    return (j.groupBy("s_name").agg(F.count().alias("numwait"))
            .orderBy(F.col("numwait").desc(), "s_name")
            .limit(100))


def q22(t: Dict[str, DataFrame]) -> DataFrame:
    """Global sales opportunity (substring country codes, NOT EXISTS)."""
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    cust = t["customer"].withColumn(
        "cntrycode", F.substring(F.col("c_phone"), 1, 2)) \
        .filter(F.col("cntrycode").isin(*codes))
    avg_bal = cust.filter(F.col("c_acctbal") > 0.0) \
        .agg(F.avg("c_acctbal").alias("a")).collect()[0][0]
    good = cust.filter(F.col("c_acctbal") > float(avg_bal))
    noord = _join(good, t["orders"].select("o_custkey"),
                  "c_custkey", "o_custkey", how="anti")
    return (noord.groupBy("cntrycode")
            .agg(F.count().alias("numcust"),
                 F.sum("c_acctbal").alias("totacctbal"))
            .orderBy("cntrycode"))


QUERIES: Dict[str, Callable] = {
    "q1": q1, "q2": q2, "q3": q3, "q4": q4, "q5": q5, "q6": q6, "q7": q7,
    "q8": q8, "q9": q9, "q10": q10, "q11": q11, "q12": q12, "q13": q13,
    "q14": q14, "q15": q15, "q16": q16, "q17": q17, "q18": q18,
    "q19": q19, "q20": q20, "q21": q21, "q22": q22,
}
