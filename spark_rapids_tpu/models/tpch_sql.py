"""The 22 TPC-H queries as SQL text for ``session.sql``.

Counterpart of the reference's SQL-side TPC-H coverage (its integration
suite runs the queries through Spark SQL).  The statements follow the
official query set with two systematic adaptations, both standard for
engines without correlated-subquery support (and mirroring how
``models/tpch.py`` translated them for the DataFrame API):

* correlated EXISTS / scalar subqueries decorrelate into joins against
  grouped FROM-subqueries (q2, q4 via LEFT SEMI JOIN, q17, q20, q21);
* ``count(distinct ...)`` becomes DISTINCT in a FROM-subquery + count
  (q16).

Uncorrelated scalar subqueries (q11, q15, q22) and IN-subqueries
(q16, q18, q20, q22) use the SQL frontend's native support.

``register(session, tables)`` installs the temp views; ``QUERIES[name]``
is the SQL text.
"""

from __future__ import annotations

from typing import Dict

TABLES = ("lineitem", "orders", "customer", "supplier", "nation",
          "region", "part", "partsupp")


def register(session, t) -> None:
    """t: dict of table name -> DataFrame (tpch.load output)."""
    for name in TABLES:
        t[name].createOrReplaceTempView(name)


QUERIES: Dict[str, str] = {}

QUERIES["q1"] = """
SELECT l_returnflag, l_linestatus,
       sum(l_quantity) AS sum_qty,
       sum(l_extendedprice) AS sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax))
         AS sum_charge,
       avg(l_quantity) AS avg_qty,
       avg(l_extendedprice) AS avg_price,
       avg(l_discount) AS avg_disc,
       count(*) AS count_order
FROM lineitem
WHERE l_shipdate <= DATE '1998-09-02'
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""

QUERIES["q2"] = """
SELECT s_acctbal, s_name, n_name, ps_partkey, p_mfgr, s_address,
       s_phone
FROM (
  SELECT ps.ps_partkey, ps.ps_supplycost, p.p_mfgr,
         s.s_acctbal, s.s_name, s.s_address, s.s_phone, n.n_name
  FROM partsupp ps
  JOIN part p ON ps.ps_partkey = p.p_partkey
  JOIN supplier s ON ps.ps_suppkey = s.s_suppkey
  JOIN nation n ON s.s_nationkey = n.n_nationkey
  JOIN region r ON n.n_regionkey = r.r_regionkey
  WHERE p.p_size = 15 AND p.p_type LIKE '%BRASS'
    AND r.r_name = 'EUROPE'
) e
JOIN (
  SELECT ps.ps_partkey AS mk, min(ps.ps_supplycost) AS min_cost
  FROM partsupp ps
  JOIN part p ON ps.ps_partkey = p.p_partkey
  JOIN supplier s ON ps.ps_suppkey = s.s_suppkey
  JOIN nation n ON s.s_nationkey = n.n_nationkey
  JOIN region r ON n.n_regionkey = r.r_regionkey
  WHERE p.p_size = 15 AND p.p_type LIKE '%BRASS'
    AND r.r_name = 'EUROPE'
  GROUP BY ps.ps_partkey
) m ON e.ps_partkey = m.mk AND e.ps_supplycost = m.min_cost
ORDER BY s_acctbal DESC, n_name, s_name, ps_partkey
LIMIT 100
"""

QUERIES["q3"] = """
SELECT l_orderkey,
       sum(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer c
JOIN orders o ON c.c_custkey = o.o_custkey
JOIN lineitem l ON o.o_orderkey = l.l_orderkey
WHERE c.c_mktsegment = 'BUILDING'
  AND o.o_orderdate < DATE '1995-03-15'
  AND l.l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate
LIMIT 10
"""

QUERIES["q4"] = """
SELECT o_orderpriority, count(*) AS order_count
FROM orders o
LEFT SEMI JOIN (
  SELECT l_orderkey FROM lineitem WHERE l_commitdate < l_receiptdate
) late ON o.o_orderkey = late.l_orderkey
WHERE o_orderdate >= DATE '1993-07-01'
  AND o_orderdate < DATE '1993-10-01'
GROUP BY o_orderpriority
ORDER BY o_orderpriority
"""

QUERIES["q5"] = """
SELECT n_name, sum(l_extendedprice * (1 - l_discount)) AS revenue
FROM customer c
JOIN orders o ON c.c_custkey = o.o_custkey
JOIN lineitem l ON o.o_orderkey = l.l_orderkey
JOIN supplier s
  ON l.l_suppkey = s.s_suppkey AND c.c_nationkey = s.s_nationkey
JOIN nation n ON s.s_nationkey = n.n_nationkey
JOIN region r ON n.n_regionkey = r.r_regionkey
WHERE r.r_name = 'ASIA'
  AND o.o_orderdate >= DATE '1994-01-01'
  AND o.o_orderdate < DATE '1995-01-01'
GROUP BY n_name
ORDER BY revenue DESC
"""

QUERIES["q6"] = """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= DATE '1994-01-01'
  AND l_shipdate < DATE '1995-01-01'
  AND l_discount BETWEEN 0.05 AND 0.07
  AND l_quantity < 24
"""

QUERIES["q7"] = """
SELECT supp_nation, cust_nation, l_year, sum(volume) AS revenue
FROM (
  SELECT n1.n_name AS supp_nation, n2.n_name AS cust_nation,
         year(l.l_shipdate) AS l_year,
         l.l_extendedprice * (1 - l.l_discount) AS volume
  FROM supplier s
  JOIN lineitem l ON s.s_suppkey = l.l_suppkey
  JOIN orders o ON o.o_orderkey = l.l_orderkey
  JOIN customer c ON c.c_custkey = o.o_custkey
  JOIN nation n1 ON s.s_nationkey = n1.n_nationkey
  JOIN nation n2 ON c.c_nationkey = n2.n_nationkey
  WHERE l.l_shipdate >= DATE '1995-01-01'
    AND l.l_shipdate <= DATE '1996-12-31'
    AND ((n1.n_name = 'FRANCE' AND n2.n_name = 'GERMANY')
         OR (n1.n_name = 'GERMANY' AND n2.n_name = 'FRANCE'))
) shipping
GROUP BY supp_nation, cust_nation, l_year
ORDER BY supp_nation, cust_nation, l_year
"""

QUERIES["q8"] = """
SELECT o_year,
       sum(CASE WHEN nation = 'BRAZIL' THEN volume ELSE 0.0 END)
         / sum(volume) AS mkt_share
FROM (
  SELECT year(o.o_orderdate) AS o_year,
         l.l_extendedprice * (1 - l.l_discount) AS volume,
         n2.n_name AS nation
  FROM part p
  JOIN lineitem l ON p.p_partkey = l.l_partkey
  JOIN supplier s ON s.s_suppkey = l.l_suppkey
  JOIN orders o ON l.l_orderkey = o.o_orderkey
  JOIN customer c ON o.o_custkey = c.c_custkey
  JOIN nation n1 ON c.c_nationkey = n1.n_nationkey
  JOIN region r ON n1.n_regionkey = r.r_regionkey
  JOIN nation n2 ON s.s_nationkey = n2.n_nationkey
  WHERE r.r_name = 'AMERICA'
    AND o.o_orderdate >= DATE '1995-01-01'
    AND o.o_orderdate <= DATE '1996-12-31'
    AND p.p_type = 'ECONOMY ANODIZED STEEL'
) all_nations
GROUP BY o_year
ORDER BY o_year
"""

QUERIES["q9"] = """
SELECT nation, o_year, sum(amount) AS sum_profit
FROM (
  SELECT n.n_name AS nation, year(o.o_orderdate) AS o_year,
         l.l_extendedprice * (1 - l.l_discount)
           - ps.ps_supplycost * l.l_quantity AS amount
  FROM part p
  JOIN lineitem l ON p.p_partkey = l.l_partkey
  JOIN supplier s ON s.s_suppkey = l.l_suppkey
  JOIN partsupp ps
    ON ps.ps_suppkey = l.l_suppkey AND ps.ps_partkey = l.l_partkey
  JOIN orders o ON o.o_orderkey = l.l_orderkey
  JOIN nation n ON s.s_nationkey = n.n_nationkey
  WHERE p.p_name LIKE '%green%'
) profit
GROUP BY nation, o_year
ORDER BY nation, o_year DESC
"""

QUERIES["q10"] = """
SELECT o_custkey, c_name, sum(l_extendedprice * (1 - l_discount))
         AS revenue,
       c_acctbal, n_name, c_phone, c_comment
FROM customer c
JOIN orders o ON c.c_custkey = o.o_custkey
JOIN lineitem l ON l.l_orderkey = o.o_orderkey
JOIN nation n ON c.c_nationkey = n.n_nationkey
WHERE o.o_orderdate >= DATE '1993-10-01'
  AND o.o_orderdate < DATE '1994-01-01'
  AND l.l_returnflag = 'R'
GROUP BY o_custkey, c_name, c_acctbal, c_phone, n_name, c_comment
ORDER BY revenue DESC
LIMIT 20
"""

QUERIES["q11"] = """
SELECT ps_partkey, sum(ps_supplycost * CAST(ps_availqty AS double))
         AS value
FROM partsupp ps
JOIN supplier s ON ps.ps_suppkey = s.s_suppkey
JOIN nation n ON s.s_nationkey = n.n_nationkey
WHERE n.n_name = 'GERMANY'
GROUP BY ps_partkey
HAVING sum(ps_supplycost * CAST(ps_availqty AS double)) > (
  SELECT sum(ps_supplycost * CAST(ps_availqty AS double)) * 0.0001
  FROM partsupp ps
  JOIN supplier s ON ps.ps_suppkey = s.s_suppkey
  JOIN nation n ON s.s_nationkey = n.n_nationkey
  WHERE n.n_name = 'GERMANY'
)
ORDER BY value DESC
"""

QUERIES["q12"] = """
SELECT l_shipmode,
       sum(CASE WHEN o_orderpriority = '1-URGENT'
                  OR o_orderpriority = '2-HIGH'
                THEN 1 ELSE 0 END) AS high_line_count,
       sum(CASE WHEN o_orderpriority <> '1-URGENT'
                 AND o_orderpriority <> '2-HIGH'
                THEN 1 ELSE 0 END) AS low_line_count
FROM orders o
JOIN lineitem l ON o.o_orderkey = l.l_orderkey
WHERE l.l_shipmode IN ('MAIL', 'SHIP')
  AND l.l_commitdate < l.l_receiptdate
  AND l.l_shipdate < l.l_commitdate
  AND l.l_receiptdate >= DATE '1994-01-01'
  AND l.l_receiptdate < DATE '1995-01-01'
GROUP BY l_shipmode
ORDER BY l_shipmode
"""

QUERIES["q13"] = """
SELECT c_count, count(*) AS custdist
FROM (
  SELECT c.c_custkey, count(o.o_orderkey) AS c_count
  FROM customer c
  LEFT JOIN (
    SELECT o_orderkey, o_custkey FROM orders
    WHERE NOT o_comment LIKE '%special%requests%'
  ) o ON c.c_custkey = o.o_custkey
  GROUP BY c.c_custkey
) c_orders
GROUP BY c_count
ORDER BY custdist DESC, c_count DESC
"""

QUERIES["q14"] = """
SELECT 100.00 * sum(CASE WHEN p_type LIKE 'PROMO%'
                         THEN l_extendedprice * (1 - l_discount)
                         ELSE 0.0 END)
         / sum(l_extendedprice * (1 - l_discount)) AS promo_revenue
FROM lineitem l
JOIN part p ON l.l_partkey = p.p_partkey
WHERE l.l_shipdate >= DATE '1995-09-01'
  AND l.l_shipdate < DATE '1995-10-01'
"""

QUERIES["q15"] = """
SELECT s_suppkey, s_name, s_address, s_phone, total_revenue
FROM supplier s
JOIN (
  SELECT l_suppkey, sum(l_extendedprice * (1 - l_discount))
           AS total_revenue
  FROM lineitem
  WHERE l_shipdate >= DATE '1996-01-01'
    AND l_shipdate < DATE '1996-04-01'
  GROUP BY l_suppkey
) revenue ON s.s_suppkey = revenue.l_suppkey
WHERE total_revenue >= (
  SELECT max(total_revenue) FROM (
    SELECT l_suppkey, sum(l_extendedprice * (1 - l_discount))
             AS total_revenue
    FROM lineitem
    WHERE l_shipdate >= DATE '1996-01-01'
      AND l_shipdate < DATE '1996-04-01'
    GROUP BY l_suppkey
  ) r
)
ORDER BY s_suppkey
"""

QUERIES["q16"] = """
SELECT p_brand, p_type, p_size, count(*) AS supplier_cnt
FROM (
  SELECT DISTINCT p.p_brand, p.p_type, p.p_size, ps.ps_suppkey
  FROM partsupp ps
  JOIN part p ON p.p_partkey = ps.ps_partkey
  WHERE p.p_brand <> 'Brand#45'
    AND NOT p.p_type LIKE 'MEDIUM POLISHED%'
    AND p.p_size IN (49, 14, 23, 45, 19, 3, 36, 9)
    AND ps.ps_suppkey NOT IN (
      SELECT s_suppkey FROM supplier
      WHERE s_comment LIKE '%Customer%Complaints%'
    )
) d
GROUP BY p_brand, p_type, p_size
ORDER BY supplier_cnt DESC, p_brand, p_type, p_size
"""

QUERIES["q17"] = """
SELECT sum(l_extendedprice) / 7.0 AS avg_yearly
FROM lineitem l
JOIN (
  SELECT l_partkey AS agg_partkey,
         0.2 * avg(l_quantity) AS avg_quantity
  FROM lineitem
  WHERE l_partkey IN (
    SELECT p_partkey FROM part
    WHERE p_brand = 'Brand#23' AND p_container = 'MED BOX'
  )
  GROUP BY l_partkey
) pa ON l.l_partkey = pa.agg_partkey
WHERE l.l_quantity < pa.avg_quantity
"""

QUERIES["q18"] = """
SELECT c_name, o_custkey, o_orderkey, o_orderdate, o_totalprice,
       sum(l_quantity) AS sum_qty
FROM customer c
JOIN orders o ON c.c_custkey = o.o_custkey
JOIN lineitem l ON o.o_orderkey = l.l_orderkey
WHERE o.o_orderkey IN (
  SELECT l_orderkey FROM lineitem
  GROUP BY l_orderkey HAVING sum(l_quantity) > 300
)
GROUP BY c_name, o_custkey, o_orderkey, o_orderdate, o_totalprice
ORDER BY o_totalprice DESC, o_orderdate
LIMIT 100
"""

QUERIES["q19"] = """
SELECT sum(l_extendedprice * (1 - l_discount)) AS revenue
FROM lineitem l
JOIN part p ON p.p_partkey = l.l_partkey
WHERE l.l_shipmode IN ('AIR', 'REG AIR')
  AND l.l_shipinstruct = 'DELIVER IN PERSON'
  AND ((p.p_brand LIKE 'Brand#1%'
        AND p.p_container IN ('SM CASE', 'SM BOX')
        AND l.l_quantity >= 1 AND l.l_quantity <= 11
        AND p.p_size BETWEEN 1 AND 15)
    OR (p.p_brand LIKE 'Brand#2%'
        AND p.p_container IN ('MED BAG', 'MED BOX')
        AND l.l_quantity >= 10 AND l.l_quantity <= 20
        AND p.p_size BETWEEN 1 AND 25)
    OR (p.p_brand LIKE 'Brand#3%'
        AND p.p_container IN ('LG CASE', 'LG BOX')
        AND l.l_quantity >= 20 AND l.l_quantity <= 30
        AND p.p_size BETWEEN 1 AND 35))
"""

QUERIES["q20"] = """
SELECT s_name, s_address
FROM supplier s
JOIN nation n ON s.s_nationkey = n.n_nationkey
WHERE n.n_name = 'CANADA'
  AND s.s_suppkey IN (
    SELECT ps_suppkey FROM (
      SELECT ps.ps_suppkey, ps.ps_availqty, q.half_qty
      FROM partsupp ps
      JOIN (
        SELECT l_partkey, l_suppkey,
               0.5 * sum(l_quantity) AS half_qty
        FROM lineitem
        WHERE l_shipdate >= DATE '1994-01-01'
          AND l_shipdate < DATE '1995-01-01'
        GROUP BY l_partkey, l_suppkey
      ) q ON ps.ps_partkey = q.l_partkey
         AND ps.ps_suppkey = q.l_suppkey
      WHERE ps.ps_partkey IN (
        SELECT p_partkey FROM part WHERE p_name LIKE 'forest%'
      )
    ) avail
    WHERE CAST(ps_availqty AS double) > half_qty
  )
ORDER BY s_name
"""

QUERIES["q21"] = """
SELECT s_name, count(*) AS numwait
FROM (
  SELECT DISTINCT late.l_orderkey, late.l_suppkey
  FROM (
    SELECT l_orderkey, l_suppkey FROM lineitem
    WHERE l_receiptdate > l_commitdate
  ) late
  JOIN (
    SELECT aa.l_orderkey AS ok2, count(*) AS n_supp FROM (
      SELECT DISTINCT l_orderkey, l_suppkey FROM lineitem
    ) aa GROUP BY aa.l_orderkey
  ) ca ON late.l_orderkey = ca.ok2
  JOIN (
    SELECT bb.l_orderkey AS ok3, count(*) AS n_late FROM (
      SELECT DISTINCT l_orderkey, l_suppkey FROM lineitem
      WHERE l_receiptdate > l_commitdate
    ) bb GROUP BY bb.l_orderkey
  ) cl ON late.l_orderkey = cl.ok3
  WHERE ca.n_supp > 1 AND cl.n_late = 1
    AND late.l_orderkey IN (
      SELECT o_orderkey FROM orders WHERE o_orderstatus = 'F'
    )
) waiting
JOIN supplier s ON waiting.l_suppkey = s.s_suppkey
JOIN nation n ON s.s_nationkey = n.n_nationkey
WHERE n.n_name = 'SAUDI ARABIA'
GROUP BY s_name
ORDER BY numwait DESC, s_name
LIMIT 100
"""

QUERIES["q22"] = """
SELECT cntrycode, count(*) AS numcust, sum(c_acctbal) AS totacctbal
FROM (
  SELECT substring(c_phone, 1, 2) AS cntrycode, c_acctbal, c_custkey
  FROM customer
  WHERE substring(c_phone, 1, 2) IN
        ('13', '31', '23', '29', '30', '18', '17')
) custsale
WHERE c_acctbal > (
  SELECT avg(c_acctbal) FROM customer
  WHERE c_acctbal > 0.0
    AND substring(c_phone, 1, 2) IN
        ('13', '31', '23', '29', '30', '18', '17')
)
AND c_custkey NOT IN (SELECT o_custkey FROM orders)
GROUP BY cntrycode
ORDER BY cntrycode
"""
