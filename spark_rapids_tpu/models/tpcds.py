"""TPC-DS model family: schema subset, seeded data generator, and an
18-query suite as SQL text.

The reference validates against TPC-DS in its integration suite
(integration_tests/src/main/python/tpcds_test.py; BASELINE.md's AQE
north star is TPC-DS-shaped) — this module is the engine-native
equivalent: the 12 tables and the columns the query subset touches,
generated with seeded numpy at a scale factor (store_sales rows
cluster into per-ticket trips), plus adapted query text exercising the
TPC-DS-heavy features (multi-way star joins, rollup + grouping(),
windowed monthly/quarterly averages via CTEs, per-ticket trip counts,
scalar-subquery promo ratios, CASE, IN-lists).

Query text is adapted from the public TPC-DS specification queries,
constrained to this engine's SQL grammar (explicit JOIN ... ON, CTEs
instead of inline windowed aggregates).
"""

from __future__ import annotations

import datetime
from typing import Dict

import numpy as np
import pandas as pd

_BASE_DATE = datetime.date(1998, 1, 1)
_N_DAYS = 6 * 365  # 1998-01-01 .. 2003-12-29


def gen_tables(sf: float = 0.01, seed: int = 42) -> Dict[str, pd.DataFrame]:
    """Seeded star-schema subset at scale factor ``sf``
    (sf=0.01 -> ~6k store_sales rows; columns limited to the suite's
    needs, names and domains per the TPC-DS spec)."""
    rng = np.random.default_rng(seed)
    out: Dict[str, pd.DataFrame] = {}

    # ---- date_dim: one row per calendar day -------------------------------
    days = np.arange(_N_DAYS)
    dates = np.array([_BASE_DATE + datetime.timedelta(days=int(d))
                      for d in days])
    out["date_dim"] = pd.DataFrame({
        "d_date_sk": 2450815 + days.astype(np.int64),
        "d_date": pd.to_datetime(dates),
        "d_year": np.array([d.year for d in dates], dtype=np.int64),
        "d_moy": np.array([d.month for d in dates], dtype=np.int64),
        "d_dom": np.array([d.day for d in dates], dtype=np.int64),
        "d_qoy": np.array([(d.month - 1) // 3 + 1 for d in dates],
                          dtype=np.int64),
        "d_month_seq": np.array(
            [(d.year - 1998) * 12 + d.month - 1 + 1189 for d in dates],
            dtype=np.int64),
        # weeks count from the Sunday on/before the base date (TPC-DS
        # weeks start Sunday; base 1998-01-01 was a Thursday -> offset 4)
        "d_week_seq": ((days + 4) // 7 + 5270).astype(np.int64),
        "d_day_name": np.array(
            [d.strftime("%A") for d in dates], dtype=object),
        "d_dow": np.array([(d.weekday() + 1) % 7 for d in dates],
                          dtype=np.int64),  # 0 = Sunday (TPC-DS)
    })

    # ---- time_dim: one row per minute of day ------------------------------
    mins = np.arange(24 * 60)
    out["time_dim"] = pd.DataFrame({
        "t_time_sk": mins.astype(np.int64),
        "t_hour": (mins // 60).astype(np.int64),
        "t_minute": (mins % 60).astype(np.int64),
    })

    # ---- item -------------------------------------------------------------
    n_item = max(int(200 * max(sf * 100, 1)), 60)
    isk = np.arange(1, n_item + 1)
    brand_id = rng.integers(1, 60, n_item) * 1000 + \
        rng.integers(1, 10, n_item)
    cats = np.array(["Books", "Electronics", "Home", "Jewelry", "Men",
                     "Music", "Shoes", "Sports", "Children", "Women"])
    cat_id = rng.integers(0, len(cats), n_item)
    classes = np.array(["accent", "bathroom", "bedding", "blinds",
                        "curtains", "decor", "fiction", "reference",
                        "self-help", "romance"])
    manufact_id = rng.integers(1, 200, n_item)
    manager_id = rng.integers(1, 40, n_item)
    # guarantee the suite's literal filters hit at every scale factor
    manufact_id[0:6] = 128          # q3
    manager_id[6:12] = 1            # q42 / q52
    manager_id[12:18] = 8           # q19
    manager_id[18:24] = 28          # q55
    out["item"] = pd.DataFrame({
        "i_item_sk": isk.astype(np.int64),
        "i_item_id": np.array([f"AAAAAAAA{k:08d}" for k in isk],
                              dtype=object),
        "i_product_name": np.array([f"product#{k}" for k in isk],
                                   dtype=object),
        "i_brand_id": brand_id.astype(np.int64),
        "i_brand": np.array([f"brand#{b}" for b in brand_id],
                            dtype=object),
        "i_class": classes[rng.integers(0, len(classes), n_item)],
        "i_category_id": (cat_id + 1).astype(np.int64),
        "i_category": cats[cat_id],
        "i_manufact_id": manufact_id.astype(np.int64),
        "i_manufact": np.array(
            [f"manufact#{m}" for m in manufact_id], dtype=object),
        "i_manager_id": manager_id.astype(np.int64),
        "i_current_price": (rng.integers(100, 9900, n_item) / 100.0),
        "i_item_desc": np.array(
            [f"desc of item {k}" for k in isk], dtype=object),
    })

    # ---- store ------------------------------------------------------------
    n_store = 6
    states = np.array(["TN", "SD", "AL", "GA", "MN", "NC"])
    out["store"] = pd.DataFrame({
        "s_store_sk": np.arange(1, n_store + 1, dtype=np.int64),
        "s_store_name": np.array(["ought", "able", "pri", "ese",
                                  "anti", "cally"], dtype=object),
        "s_state": states[:n_store],
        "s_city": np.array(["Midway", "Fairview", "Midway", "Oakland",
                            "Fairview", "Glendale"], dtype=object),
        "s_zip": np.array([f"{z:05d}" for z in
                           rng.integers(10000, 99999, n_store)],
                          dtype=object),
        "s_number_employees": rng.integers(200, 300,
                                           n_store).astype(np.int64),
    })

    # ---- customer_address / demographics ----------------------------------
    n_ca = max(int(300 * max(sf * 100, 1)), 100)
    cities = np.array(["Midway", "Fairview", "Oakland", "Glendale",
                       "Springdale", "Riverside", "Centerville",
                       "Pleasant Hill"])
    out["customer_address"] = pd.DataFrame({
        "ca_address_sk": np.arange(1, n_ca + 1, dtype=np.int64),
        "ca_state": states[rng.integers(0, len(states), n_ca)],
        "ca_city": cities[rng.integers(0, len(cities), n_ca)],
        "ca_zip": np.array([f"{z:05d}" for z in
                            rng.integers(10000, 99999, n_ca)],
                           dtype=object),
        "ca_country": np.array(["United States"] * n_ca, dtype=object),
    })
    genders = np.array(["M", "F"])
    marital = np.array(["S", "M", "D", "W", "U"])
    edu = np.array(["Primary", "Secondary", "College",
                    "2 yr Degree", "4 yr Degree", "Advanced Degree",
                    "Unknown"])
    n_cd = len(genders) * len(marital) * len(edu)
    gg, mm, ee = np.meshgrid(np.arange(2), np.arange(5), np.arange(7),
                             indexing="ij")
    out["customer_demographics"] = pd.DataFrame({
        "cd_demo_sk": np.arange(1, n_cd + 1, dtype=np.int64),
        "cd_gender": genders[gg.ravel()],
        "cd_marital_status": marital[mm.ravel()],
        "cd_education_status": edu[ee.ravel()],
    })
    n_hd = 50
    buy_pot = np.array(["0-500", "501-1000", "1001-5000", ">10000",
                        "Unknown"])
    out["household_demographics"] = pd.DataFrame({
        "hd_demo_sk": np.arange(1, n_hd + 1, dtype=np.int64),
        "hd_dep_count": rng.integers(0, 10, n_hd).astype(np.int64),
        "hd_vehicle_count": rng.integers(-1, 5, n_hd).astype(np.int64),
        "hd_buy_potential": buy_pot[rng.integers(0, len(buy_pot),
                                                 n_hd)],
    })

    # ---- customer ---------------------------------------------------------
    n_cust = max(int(500 * max(sf * 100, 1)), 200)
    out["customer"] = pd.DataFrame({
        "c_customer_sk": np.arange(1, n_cust + 1, dtype=np.int64),
        "c_first_name": np.array(
            [f"First{i % 97}" for i in range(n_cust)], dtype=object),
        "c_last_name": np.array(
            [f"Last{i % 89}" for i in range(n_cust)], dtype=object),
        "c_salutation": np.array(["Mr.", "Ms.", "Dr.", "Mrs.", "Sir"]
                                 )[rng.integers(0, 5, n_cust)],
        "c_current_addr_sk": rng.integers(1, n_ca + 1,
                                          n_cust).astype(np.int64),
        "c_current_cdemo_sk": rng.integers(1, n_cd + 1,
                                           n_cust).astype(np.int64),
        "c_current_hdemo_sk": rng.integers(1, n_hd + 1,
                                           n_cust).astype(np.int64),
        "c_birth_year": rng.integers(1920, 1995,
                                     n_cust).astype(np.int64),
    })

    # ---- promotion --------------------------------------------------------
    n_promo = 30
    yn = np.array(["Y", "N"])
    out["promotion"] = pd.DataFrame({
        "p_promo_sk": np.arange(1, n_promo + 1, dtype=np.int64),
        "p_channel_email": yn[rng.integers(0, 2, n_promo)],
        "p_channel_event": yn[rng.integers(0, 2, n_promo)],
    })

    # ---- store_sales (the fact table) -------------------------------------
    n_ss = max(int(600000 * sf), 1000)
    # rows cluster into TRIPS (one ticket number per trip, the TPC-DS
    # ss_ticket_number grain): items of a trip share the customer,
    # date, time, store, and demographics — q34/q73/q79 group by ticket
    n_trip = max(n_ss // 3, 1)
    trip_day = rng.integers(0, _N_DAYS, n_trip)
    # mild skews so the suite's joint filters (manager x november,
    # demographic-combo x state x year) keep hits at small scale
    # factors: 12% of trips in November, 10% on the (M, S, College)
    # demographics row, 15% of items pinned-attribute
    nov_days = np.array([i for i in range(_N_DAYS)
                         if (_BASE_DATE
                             + datetime.timedelta(days=i)).month == 11])
    nov = rng.random(n_trip) < 0.12
    trip_day[nov] = rng.choice(nov_days, int(nov.sum()))
    trip_cust = rng.integers(1, n_cust + 1, n_trip)
    trip_store = rng.integers(1, n_store + 1, n_trip)
    trip_hd = rng.integers(1, n_hd + 1, n_trip)
    trip_time = rng.integers(0, 24 * 60, n_trip)
    trip_cd = rng.integers(1, n_cd + 1, n_trip)
    target_cd = out["customer_demographics"]
    target_sk = int(target_cd[
        (target_cd.cd_gender == "M")
        & (target_cd.cd_marital_status == "S")
        & (target_cd.cd_education_status == "College")
    ]["cd_demo_sk"].iloc[0])
    trip_cd[rng.random(n_trip) < 0.10] = target_sk

    trip_of = rng.integers(0, n_trip, n_ss)
    day_off = trip_day[trip_of]
    cdemo_fk = trip_cd[trip_of]
    item_fk = rng.integers(1, n_item + 1, n_ss)
    pin = rng.random(n_ss) < 0.15
    item_fk[pin] = rng.integers(1, 25, int(pin.sum()))
    qty = rng.integers(1, 101, n_ss)
    list_price = rng.integers(100, 20000, n_ss) / 100.0
    pct = rng.integers(0, 101, n_ss) / 100.0
    sales_price = np.round(list_price * pct, 2)
    ext = np.round(sales_price * qty, 2)
    coupon = np.where(rng.random(n_ss) < 0.1,
                      np.round(ext * rng.random(n_ss) * 0.5, 2), 0.0)
    wholesale = np.round(list_price * 0.6, 2)
    # ~2% null store fks (q76's null-channel accounting; inner joins on
    # the store dim drop them identically in engine and oracle)
    store_fk = pd.array(trip_store[trip_of].astype(np.int64),
                        dtype="Int64")
    store_fk[rng.random(n_ss) < 0.02] = pd.NA
    # per-trip purchase address (q46/q68's bought-city vs current-city)
    trip_addr = rng.integers(1, n_ca + 1, n_trip)
    out["store_sales"] = pd.DataFrame({
        "ss_sold_date_sk": (2450815 + day_off).astype(np.int64),
        "ss_sold_time_sk": trip_time[trip_of].astype(np.int64),
        "ss_ticket_number": (trip_of + 1).astype(np.int64),
        "ss_item_sk": item_fk.astype(np.int64),
        "ss_customer_sk": trip_cust[trip_of].astype(np.int64),
        "ss_cdemo_sk": cdemo_fk.astype(np.int64),
        "ss_hdemo_sk": trip_hd[trip_of].astype(np.int64),
        "ss_addr_sk": trip_addr[trip_of].astype(np.int64),
        "ss_store_sk": store_fk,
        "ss_promo_sk": rng.integers(1, n_promo + 1,
                                    n_ss).astype(np.int64),
        "ss_quantity": qty.astype(np.int64),
        "ss_list_price": list_price,
        "ss_sales_price": sales_price,
        "ss_ext_sales_price": ext,
        "ss_coupon_amt": coupon,
        "ss_wholesale_cost": wholesale,
        "ss_net_profit": np.round(ext - wholesale * qty - coupon, 2),
    })

    # ---- reason / call_center / warehouse / ship_mode / web_page ----------
    out["reason"] = pd.DataFrame({
        "r_reason_sk": np.arange(1, 11, dtype=np.int64),
        "r_reason_desc": np.array(
            ["Package was damaged", "Stopped working",
             "Did not get it on time", "Not the product that was "
             "ordred", "Parts missing", "Does not work with a product "
             "that I have", "Gift exchange", "Did not like the color",
             "Did not like the model", "Did not fit"], dtype=object),
    })
    out["call_center"] = pd.DataFrame({
        "cc_call_center_sk": np.arange(1, 5, dtype=np.int64),
        "cc_name": np.array(["NY Metro", "Mid Atlantic",
                             "North Midwest", "Pacific NW"],
                            dtype=object),
        "cc_county": np.array(["Ziebach County"] * 4, dtype=object),
    })
    out["warehouse"] = pd.DataFrame({
        "w_warehouse_sk": np.arange(1, 6, dtype=np.int64),
        "w_warehouse_name": np.array(
            ["Conventional childr", "Important issues liv",
             "Doors canno", "Bad cards must make", "Rooms cook"],
            dtype=object),
        "w_warehouse_sq_ft": rng.integers(50000, 1000000,
                                          5).astype(np.int64),
        "w_state": states[rng.integers(0, len(states), 5)],
    })
    out["ship_mode"] = pd.DataFrame({
        "sm_ship_mode_sk": np.arange(1, 11, dtype=np.int64),
        "sm_type": np.array(["EXPRESS", "NEXT DAY", "OVERNIGHT",
                             "REGULAR", "TWO DAY"] * 2, dtype=object),
        "sm_code": np.array(["AIR", "SURFACE", "SEA", "AIR", "SURFACE",
                             "SEA", "AIR", "SURFACE", "SEA", "AIR"],
                            dtype=object),
    })
    out["web_page"] = pd.DataFrame({
        "wp_web_page_sk": np.arange(1, 21, dtype=np.int64),
        "wp_char_count": rng.integers(100, 8000, 20).astype(np.int64),
    })

    # ---- store_returns: ~9% of store_sales rows come back ------------------
    ret_of = np.flatnonzero(rng.random(n_ss) < 0.09)
    ss = out["store_sales"]
    ret_delta = rng.integers(1, 60, len(ret_of))
    ret_qty = np.minimum(qty[ret_of],
                         rng.integers(1, 101, len(ret_of)))
    ret_amt = np.round(sales_price[ret_of] * ret_qty, 2)
    out["store_returns"] = pd.DataFrame({
        "sr_returned_date_sk": np.minimum(
            ss["ss_sold_date_sk"].to_numpy()[ret_of] + ret_delta,
            2450815 + _N_DAYS - 1).astype(np.int64),
        "sr_item_sk": ss["ss_item_sk"].to_numpy()[ret_of],
        "sr_customer_sk": ss["ss_customer_sk"].to_numpy()[ret_of],
        "sr_ticket_number": ss["ss_ticket_number"].to_numpy()[ret_of],
        "sr_store_sk": ss["ss_store_sk"].to_numpy(
            dtype=np.float64, na_value=np.nan)[ret_of],
        "sr_reason_sk": rng.integers(1, 11,
                                     len(ret_of)).astype(np.int64),
        "sr_return_quantity": ret_qty.astype(np.int64),
        "sr_return_amt": ret_amt,
        "sr_net_loss": np.round(ret_amt * 0.1 + 5.0, 2),
    })
    out["store_returns"]["sr_store_sk"] = \
        out["store_returns"]["sr_store_sk"].astype("Int64")

    # ---- catalog_sales ----------------------------------------------------
    n_cs = max(int(n_ss * 0.5), 500)
    cs_day = rng.integers(0, _N_DAYS - 8, n_cs)
    cs_ship_addr = pd.array(rng.integers(1, n_ca + 1,
                                         n_cs).astype(np.int64),
                            dtype="Int64")
    cs_ship_addr[rng.random(n_cs) < 0.02] = pd.NA
    cs_qty = rng.integers(1, 101, n_cs)
    cs_list = rng.integers(100, 20000, n_cs) / 100.0
    cs_price = np.round(cs_list * (rng.integers(0, 101, n_cs) / 100.0),
                        2)
    cs_ext = np.round(cs_price * cs_qty, 2)
    cs_whole = np.round(cs_list * 0.6, 2)
    cs_item = rng.integers(1, n_item + 1, n_cs)
    pin2 = rng.random(n_cs) < 0.15
    cs_item[pin2] = rng.integers(1, 25, int(pin2.sum()))
    out["catalog_sales"] = pd.DataFrame({
        "cs_sold_date_sk": (2450815 + cs_day).astype(np.int64),
        "cs_ship_date_sk": (2450815 + cs_day +
                            rng.integers(1, 8, n_cs)).astype(np.int64),
        "cs_item_sk": cs_item.astype(np.int64),
        "cs_bill_customer_sk": rng.integers(1, n_cust + 1,
                                            n_cs).astype(np.int64),
        "cs_bill_cdemo_sk": np.where(
            rng.random(n_cs) < 0.10, target_sk,
            rng.integers(1, n_cd + 1, n_cs)).astype(np.int64),
        "cs_bill_addr_sk": rng.integers(1, n_ca + 1,
                                        n_cs).astype(np.int64),
        "cs_ship_addr_sk": cs_ship_addr,
        "cs_call_center_sk": rng.integers(1, 5, n_cs).astype(np.int64),
        "cs_ship_mode_sk": rng.integers(1, 11, n_cs).astype(np.int64),
        "cs_warehouse_sk": rng.integers(1, 6, n_cs).astype(np.int64),
        "cs_promo_sk": rng.integers(1, n_promo + 1,
                                    n_cs).astype(np.int64),
        "cs_quantity": cs_qty.astype(np.int64),
        "cs_list_price": cs_list,
        "cs_sales_price": cs_price,
        "cs_ext_sales_price": cs_ext,
        "cs_wholesale_cost": cs_whole,
        "cs_net_profit": np.round(cs_ext - cs_whole * cs_qty, 2),
    })

    # ---- web_sales --------------------------------------------------------
    n_ws = max(int(n_ss * 0.35), 400)
    ws_day = rng.integers(0, _N_DAYS, n_ws)
    ws_ship_cust = pd.array(rng.integers(1, n_cust + 1,
                                         n_ws).astype(np.int64),
                            dtype="Int64")
    ws_ship_cust[rng.random(n_ws) < 0.02] = pd.NA
    ws_qty = rng.integers(1, 101, n_ws)
    ws_list = rng.integers(100, 20000, n_ws) / 100.0
    ws_price = np.round(ws_list * (rng.integers(0, 101, n_ws) / 100.0),
                        2)
    ws_ext = np.round(ws_price * ws_qty, 2)
    ws_whole = np.round(ws_list * 0.6, 2)
    ws_item = rng.integers(1, n_item + 1, n_ws)
    pin3 = rng.random(n_ws) < 0.15
    ws_item[pin3] = rng.integers(1, 25, int(pin3.sum()))
    out["web_sales"] = pd.DataFrame({
        "ws_sold_date_sk": (2450815 + ws_day).astype(np.int64),
        "ws_sold_time_sk": rng.integers(0, 24 * 60,
                                        n_ws).astype(np.int64),
        "ws_item_sk": ws_item.astype(np.int64),
        "ws_bill_customer_sk": rng.integers(1, n_cust + 1,
                                            n_ws).astype(np.int64),
        "ws_bill_addr_sk": rng.integers(1, n_ca + 1,
                                        n_ws).astype(np.int64),
        "ws_ship_customer_sk": ws_ship_cust,
        "ws_web_page_sk": rng.integers(1, 21, n_ws).astype(np.int64),
        "ws_promo_sk": rng.integers(1, n_promo + 1,
                                    n_ws).astype(np.int64),
        "ws_quantity": ws_qty.astype(np.int64),
        "ws_list_price": ws_list,
        "ws_sales_price": ws_price,
        "ws_ext_sales_price": ws_ext,
        "ws_wholesale_cost": ws_whole,
        "ws_net_profit": np.round(ws_ext - ws_whole * ws_qty, 2),
    })
    return out


def load(session, data: Dict[str, pd.DataFrame]):
    """Create engine DataFrames + temp views for every table."""
    tables = {}
    for name, df in data.items():
        t = session.create_dataframe(df)
        t.createOrReplaceTempView(name)
        tables[name] = t
    return tables


# --------------------------------------------------------------- queries --

QUERIES: Dict[str, str] = {}

QUERIES["q3"] = """
select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
       sum(ss.ss_ext_sales_price) sum_agg
from store_sales ss
join date_dim dt on dt.d_date_sk = ss.ss_sold_date_sk
join item on ss.ss_item_sk = item.i_item_sk
where item.i_manufact_id = 128 and dt.d_moy = 11
group by dt.d_year, item.i_brand_id, item.i_brand
order by dt.d_year, sum_agg desc, brand_id
limit 100
"""

QUERIES["q7"] = """
select i.i_item_id,
       avg(ss.ss_quantity) agg1,
       avg(ss.ss_list_price) agg2,
       avg(ss.ss_coupon_amt) agg3,
       avg(ss.ss_sales_price) agg4
from store_sales ss
join customer_demographics cd on ss.ss_cdemo_sk = cd.cd_demo_sk
join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
join item i on ss.ss_item_sk = i.i_item_sk
join promotion p on ss.ss_promo_sk = p.p_promo_sk
where cd.cd_gender = 'M' and cd.cd_marital_status = 'S'
  and cd.cd_education_status = 'College'
  and (p.p_channel_email = 'N' or p.p_channel_event = 'N')
  and d.d_year = 2000
group by i.i_item_id
order by i.i_item_id
limit 100
"""

QUERIES["q19"] = """
select i.i_brand_id brand_id, i.i_brand brand, i.i_manufact_id,
       i.i_manufact, sum(ss.ss_ext_sales_price) ext_price
from store_sales ss
join date_dim d on d.d_date_sk = ss.ss_sold_date_sk
join item i on ss.ss_item_sk = i.i_item_sk
join customer c on ss.ss_customer_sk = c.c_customer_sk
join customer_address ca on c.c_current_addr_sk = ca.ca_address_sk
join store s on ss.ss_store_sk = s.s_store_sk
where i.i_manager_id = 8 and d.d_moy = 11 and d.d_year = 1998
  and substr(ca.ca_zip, 1, 5) <> substr(s.s_zip, 1, 5)
group by i.i_brand_id, i.i_brand, i.i_manufact_id, i.i_manufact
order by ext_price desc, brand_id
limit 100
"""

QUERIES["q27"] = """
select i.i_item_id, s.s_state, grouping(s.s_state) g_state,
       avg(ss.ss_quantity) agg1,
       avg(ss.ss_list_price) agg2,
       avg(ss.ss_coupon_amt) agg3,
       avg(ss.ss_sales_price) agg4
from store_sales ss
join customer_demographics cd on ss.ss_cdemo_sk = cd.cd_demo_sk
join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
join store s on ss.ss_store_sk = s.s_store_sk
join item i on ss.ss_item_sk = i.i_item_sk
where cd.cd_gender = 'M' and cd.cd_marital_status = 'S'
  and cd.cd_education_status = 'College'
  and d.d_year = 2002 and s.s_state in ('TN', 'SD', 'AL')
group by rollup(i.i_item_id, s.s_state)
order by i.i_item_id, s.s_state
limit 100
"""

QUERIES["q42"] = """
select dt.d_year, item.i_category_id, item.i_category,
       sum(ss.ss_ext_sales_price) total
from store_sales ss
join date_dim dt on dt.d_date_sk = ss.ss_sold_date_sk
join item on ss.ss_item_sk = item.i_item_sk
where item.i_manager_id = 1 and dt.d_moy = 11 and dt.d_year = 2000
group by dt.d_year, item.i_category_id, item.i_category
order by total desc, dt.d_year, item.i_category_id, item.i_category
limit 100
"""

QUERIES["q52"] = """
select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
       sum(ss.ss_ext_sales_price) ext_price
from store_sales ss
join date_dim dt on dt.d_date_sk = ss.ss_sold_date_sk
join item on ss.ss_item_sk = item.i_item_sk
where item.i_manager_id = 1 and dt.d_moy = 11 and dt.d_year = 2000
group by dt.d_year, item.i_brand_id, item.i_brand
order by dt.d_year, ext_price desc, brand_id
limit 100
"""

QUERIES["q53"] = """
with quarterly as (
  select i.i_manufact_id, d.d_qoy,
         sum(ss.ss_sales_price) sum_sales
  from item i
  join store_sales ss on ss.ss_item_sk = i.i_item_sk
  join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
  where d.d_year = 2001
    and i.i_category in ('Books', 'Home', 'Sports')
  group by i.i_manufact_id, d.d_qoy
)
select * from (
  select i_manufact_id, sum_sales,
         avg(sum_sales) over (partition by i_manufact_id)
           avg_quarterly_sales
  from quarterly
) t
where case when avg_quarterly_sales > 0
           then abs(sum_sales - avg_quarterly_sales)
                / avg_quarterly_sales
           else null end > 0.1
order by avg_quarterly_sales, sum_sales, i_manufact_id
limit 100
"""

QUERIES["q55"] = """
select i.i_brand_id brand_id, i.i_brand brand,
       sum(ss.ss_ext_sales_price) ext_price
from date_dim d
join store_sales ss on d.d_date_sk = ss.ss_sold_date_sk
join item i on ss.ss_item_sk = i.i_item_sk
where i.i_manager_id = 28 and d.d_moy = 11 and d.d_year = 1999
group by i.i_brand_id, i.i_brand
order by ext_price desc, brand_id
limit 100
"""

QUERIES["q96"] = """
select count(*) cnt
from store_sales ss
join household_demographics hd on ss.ss_hdemo_sk = hd.hd_demo_sk
join time_dim t on ss.ss_sold_time_sk = t.t_time_sk
join store s on ss.ss_store_sk = s.s_store_sk
where t.t_hour = 20 and t.t_minute >= 30
  and hd.hd_dep_count = 7 and s.s_store_name = 'ese'
"""

QUERIES["q98"] = """
with rev as (
  select i.i_item_id, i.i_category, i.i_class, i.i_current_price,
         sum(ss.ss_ext_sales_price) itemrevenue
  from store_sales ss
  join item i on ss.ss_item_sk = i.i_item_sk
  join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
  where i.i_category in ('Sports', 'Books', 'Home')
    and d.d_year = 1999 and d.d_moy between 2 and 3
  group by i.i_item_id, i.i_category, i.i_class, i.i_current_price
)
select i_item_id, i_category, i_class, i_current_price, itemrevenue,
       itemrevenue * 100.0
         / sum(itemrevenue) over (partition by i_class) revenueratio
from rev
order by i_category, i_class, i_item_id, revenueratio
limit 100
"""

QUERIES["q34"] = """
with dn as (
  select ss.ss_ticket_number, ss.ss_customer_sk, count(*) cnt
  from store_sales ss
  join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
  join store s on ss.ss_store_sk = s.s_store_sk
  join household_demographics hd on ss.ss_hdemo_sk = hd.hd_demo_sk
  where (d.d_dom between 1 and 3 or d.d_dom between 25 and 28)
    and hd.hd_buy_potential = '>10000'
    and hd.hd_vehicle_count > 0
    and s.s_state in ('TN', 'SD', 'AL')
  group by ss.ss_ticket_number, ss.ss_customer_sk
)
select c.c_last_name, c.c_first_name, c.c_salutation,
       dn.ss_ticket_number, dn.cnt
from dn
join customer c on dn.ss_customer_sk = c.c_customer_sk
where dn.cnt between 2 and 6
order by c.c_last_name, c.c_first_name, dn.ss_ticket_number
limit 100
"""

QUERIES["q36"] = """
select sum(ss.ss_net_profit) / sum(ss.ss_ext_sales_price) gross_margin,
       i.i_category, i.i_class,
       grouping(i.i_category) + grouping(i.i_class) lochierarchy
from store_sales ss
join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
join item i on ss.ss_item_sk = i.i_item_sk
join store s on ss.ss_store_sk = s.s_store_sk
where d.d_year = 2001 and s.s_state in ('TN', 'SD', 'AL', 'GA')
group by rollup(i.i_category, i.i_class)
order by lochierarchy desc, i.i_category, i.i_class
limit 100
"""

QUERIES["q48"] = """
select sum(ss.ss_quantity) q
from store_sales ss
join store s on ss.ss_store_sk = s.s_store_sk
join customer_demographics cd on ss.ss_cdemo_sk = cd.cd_demo_sk
join customer c on ss.ss_customer_sk = c.c_customer_sk
join customer_address ca on c.c_current_addr_sk = ca.ca_address_sk
join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
where d.d_year = 2000
  and ((cd.cd_marital_status = 'M'
        and cd.cd_education_status = '4 yr Degree'
        and ss.ss_sales_price between 100.00 and 150.00)
    or (cd.cd_marital_status = 'D'
        and cd.cd_education_status = '2 yr Degree'
        and ss.ss_sales_price between 50.00 and 100.00)
    or (cd.cd_marital_status = 'S'
        and cd.cd_education_status = 'College'
        and ss.ss_sales_price between 150.00 and 200.00))
  and ((ca.ca_state in ('TN', 'SD', 'GA')
        and ss.ss_net_profit between 0 and 2000)
    or (ca.ca_state in ('AL', 'MN', 'NC')
        and ss.ss_net_profit between 150 and 3000))
"""

QUERIES["q61"] = """
select (select sum(ss.ss_ext_sales_price)
        from store_sales ss
        join promotion p on ss.ss_promo_sk = p.p_promo_sk
        join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
        where (p.p_channel_email = 'Y' or p.p_channel_event = 'Y')
          and d.d_year = 1998 and d.d_moy = 11) promotions,
       (select sum(ss.ss_ext_sales_price)
        from store_sales ss
        join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
        where d.d_year = 1998 and d.d_moy = 11) total,
       (select sum(ss.ss_ext_sales_price)
        from store_sales ss
        join promotion p on ss.ss_promo_sk = p.p_promo_sk
        join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
        where (p.p_channel_email = 'Y' or p.p_channel_event = 'Y')
          and d.d_year = 1998 and d.d_moy = 11) * 100.0 /
       (select sum(ss.ss_ext_sales_price)
        from store_sales ss
        join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
        where d.d_year = 1998 and d.d_moy = 11) ratio
"""

QUERIES["q65"] = """
with sa as (
  select ss.ss_store_sk, ss.ss_item_sk,
         sum(ss.ss_sales_price) revenue
  from store_sales ss
  join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
  where d.d_month_seq between 1200 and 1211
  group by ss.ss_store_sk, ss.ss_item_sk
),
sb as (
  select ss_store_sk, avg(revenue) ave from sa group by ss_store_sk
)
select s.s_store_name, i.i_item_desc, sa.revenue, i.i_current_price,
       i.i_brand
from sa
join sb on sa.ss_store_sk = sb.ss_store_sk
join store s on sa.ss_store_sk = s.s_store_sk
join item i on sa.ss_item_sk = i.i_item_sk
where sa.revenue <= 0.1 * sb.ave
order by s.s_store_name, i.i_item_desc
limit 100
"""

QUERIES["q73"] = """
with dn as (
  select ss.ss_ticket_number, ss.ss_customer_sk, count(*) cnt
  from store_sales ss
  join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
  join store s on ss.ss_store_sk = s.s_store_sk
  join household_demographics hd on ss.ss_hdemo_sk = hd.hd_demo_sk
  where d.d_dom between 1 and 2
    and (hd.hd_buy_potential = '>10000'
         or hd.hd_buy_potential = 'Unknown')
    and hd.hd_vehicle_count > 0
    and s.s_city in ('Midway', 'Fairview')
  group by ss.ss_ticket_number, ss.ss_customer_sk
)
select c.c_last_name, c.c_first_name, c.c_salutation,
       dn.ss_ticket_number, dn.cnt
from dn
join customer c on dn.ss_customer_sk = c.c_customer_sk
where dn.cnt between 1 and 5
order by dn.cnt desc, c.c_last_name
limit 100
"""

QUERIES["q79"] = """
with pt as (
  select ss.ss_ticket_number, ss.ss_customer_sk, s.s_city,
         sum(ss.ss_coupon_amt) amt, sum(ss.ss_net_profit) profit
  from store_sales ss
  join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
  join store s on ss.ss_store_sk = s.s_store_sk
  join household_demographics hd on ss.ss_hdemo_sk = hd.hd_demo_sk
  where (hd.hd_dep_count = 7 or hd.hd_vehicle_count > 1)
    and d.d_dow = 1
    and d.d_year in (1998, 1999, 2000)
    and s.s_number_employees between 200 and 295
  group by ss.ss_ticket_number, ss.ss_customer_sk, s.s_city
)
select c.c_last_name, c.c_first_name,
       substr(pt.s_city, 1, 30) city, pt.ss_ticket_number, pt.amt,
       pt.profit
from pt
join customer c on pt.ss_customer_sk = c.c_customer_sk
order by c.c_last_name, c.c_first_name, city, pt.profit
limit 100
"""

QUERIES["q89"] = """
with msales as (
  select i.i_category, i.i_class, i.i_brand, s.s_store_name, d.d_moy,
         sum(ss.ss_sales_price) sum_sales
  from item i
  join store_sales ss on ss.ss_item_sk = i.i_item_sk
  join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
  join store s on ss.ss_store_sk = s.s_store_sk
  where d.d_year = 1999
    and i.i_category in ('Books', 'Electronics', 'Sports',
                         'Men', 'Jewelry', 'Women')
  group by i.i_category, i.i_class, i.i_brand, s.s_store_name, d.d_moy
)
select * from (
  select i_category, i_class, i_brand, s_store_name, d_moy, sum_sales,
         avg(sum_sales) over (partition by i_category, i_brand,
                              s_store_name) avg_monthly_sales
  from msales
) t
where case when avg_monthly_sales > 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else null end > 0.1
order by sum_sales - avg_monthly_sales, s_store_name, d_moy
limit 100
"""

# ---- round-5 batch A: store-channel breadth -------------------------------

QUERIES["q43"] = """
select s.s_store_name,
       sum(case when d.d_day_name = 'Sunday'
                then ss.ss_sales_price else null end) sun_sales,
       sum(case when d.d_day_name = 'Monday'
                then ss.ss_sales_price else null end) mon_sales,
       sum(case when d.d_day_name = 'Tuesday'
                then ss.ss_sales_price else null end) tue_sales,
       sum(case when d.d_day_name = 'Wednesday'
                then ss.ss_sales_price else null end) wed_sales,
       sum(case when d.d_day_name = 'Thursday'
                then ss.ss_sales_price else null end) thu_sales,
       sum(case when d.d_day_name = 'Friday'
                then ss.ss_sales_price else null end) fri_sales,
       sum(case when d.d_day_name = 'Saturday'
                then ss.ss_sales_price else null end) sat_sales
from date_dim d
join store_sales ss on d.d_date_sk = ss.ss_sold_date_sk
join store s on ss.ss_store_sk = s.s_store_sk
where d.d_year = 2000
group by s.s_store_name
order by s.s_store_name
limit 100
"""

QUERIES["q44"] = """
with profits as (
  select ss.ss_item_sk item_sk, avg(ss.ss_net_profit) rank_col
  from store_sales ss
  where ss.ss_store_sk = 4
  group by ss.ss_item_sk
),
asceding as (
  select item_sk, rank() over (order by rank_col) rnk from profits
),
descending as (
  select item_sk, rank() over (order by rank_col desc) rnk
  from profits
)
select asceding.rnk,
       i1.i_product_name best_performing,
       i2.i_product_name worst_performing
from asceding
join descending on asceding.rnk = descending.rnk
join item i1 on i1.i_item_sk = asceding.item_sk
join item i2 on i2.i_item_sk = descending.item_sk
where asceding.rnk < 11
order by asceding.rnk
"""

QUERIES["q46"] = """
with dn as (
  select ss.ss_ticket_number, ss.ss_customer_sk,
         ca.ca_city bought_city,
         sum(ss.ss_coupon_amt) amt, sum(ss.ss_net_profit) profit
  from store_sales ss
  join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
  join store s on ss.ss_store_sk = s.s_store_sk
  join household_demographics hd on ss.ss_hdemo_sk = hd.hd_demo_sk
  join customer_address ca on ss.ss_addr_sk = ca.ca_address_sk
  where (hd.hd_dep_count = 7 or hd.hd_vehicle_count = 3)
    and d.d_dow in (6, 0)
    and d.d_year in (1999, 2000, 2001)
    and s.s_city in ('Fairview', 'Midway')
  group by ss.ss_ticket_number, ss.ss_customer_sk, ca.ca_city
)
select c.c_last_name, c.c_first_name, ca.ca_city current_city,
       dn.bought_city, dn.ss_ticket_number, dn.amt, dn.profit
from dn
join customer c on dn.ss_customer_sk = c.c_customer_sk
join customer_address ca on c.c_current_addr_sk = ca.ca_address_sk
where dn.bought_city <> ca.ca_city
order by c.c_last_name, c.c_first_name, ca.ca_city, dn.bought_city,
         dn.ss_ticket_number
limit 100
"""

QUERIES["q47"] = """
with v1 as (
  select i.i_category, i.i_brand, s.s_store_name,
         d.d_year, d.d_moy, sum(ss.ss_sales_price) sum_sales
  from item i
  join store_sales ss on ss.ss_item_sk = i.i_item_sk
  join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
  join store s on ss.ss_store_sk = s.s_store_sk
  where d.d_year = 2000
     or (d.d_year = 1999 and d.d_moy = 12)
     or (d.d_year = 2001 and d.d_moy = 1)
  group by i.i_category, i.i_brand, s.s_store_name, d.d_year, d.d_moy
),
v2 as (
  select i_category, i_brand, s_store_name, d_year, d_moy, sum_sales,
         avg(sum_sales) over (partition by i_category, i_brand,
                              s_store_name, d_year) avg_monthly_sales,
         lag(sum_sales, 1) over (partition by i_category, i_brand,
                                 s_store_name
                                 order by d_year, d_moy) psum,
         lead(sum_sales, 1) over (partition by i_category, i_brand,
                                  s_store_name
                                  order by d_year, d_moy) nsum
  from v1
)
select i_category, i_brand, s_store_name, d_year, d_moy, sum_sales,
       avg_monthly_sales, psum, nsum
from v2
where d_year = 2000 and avg_monthly_sales > 0
  and abs(sum_sales - avg_monthly_sales) / avg_monthly_sales > 0.1
order by sum_sales - avg_monthly_sales, s_store_name, d_moy
limit 100
"""

QUERIES["q59"] = """
with wss as (
  select d.d_week_seq d_week_seq, ss.ss_store_sk ss_store_sk,
         sum(case when d.d_day_name = 'Sunday'
                  then ss.ss_sales_price else null end) sun_sales,
         sum(case when d.d_day_name = 'Monday'
                  then ss.ss_sales_price else null end) mon_sales,
         sum(case when d.d_day_name = 'Wednesday'
                  then ss.ss_sales_price else null end) wed_sales,
         sum(case when d.d_day_name = 'Friday'
                  then ss.ss_sales_price else null end) fri_sales
  from store_sales ss
  join date_dim d on d.d_date_sk = ss.ss_sold_date_sk
  group by d.d_week_seq, ss.ss_store_sk
)
select s.s_store_name s_store_name1, y.d_week_seq d_week_seq1,
       y.sun_sales / x.sun_sales sun_ratio,
       y.mon_sales / x.mon_sales mon_ratio,
       y.wed_sales / x.wed_sales wed_ratio,
       y.fri_sales / x.fri_sales fri_ratio
from wss y
join wss x on y.ss_store_sk = x.ss_store_sk
          and y.d_week_seq = x.d_week_seq - 52
join store s on y.ss_store_sk = s.s_store_sk
where y.d_week_seq between 5270 and 5322
order by s.s_store_name, y.d_week_seq
limit 100
"""

QUERIES["q63"] = """
with monthly as (
  select i.i_manager_id, d.d_moy, sum(ss.ss_sales_price) sum_sales
  from item i
  join store_sales ss on ss.ss_item_sk = i.i_item_sk
  join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
  where d.d_year = 2001
    and i.i_category in ('Books', 'Children', 'Electronics')
  group by i.i_manager_id, d.d_moy
)
select i_manager_id, sum_sales, avg_monthly_sales
from (
  select i_manager_id, sum_sales,
         avg(sum_sales) over (partition by i_manager_id)
           avg_monthly_sales
  from monthly
) t
where case when avg_monthly_sales > 0
           then abs(sum_sales - avg_monthly_sales)
                / avg_monthly_sales
           else null end > 0.1
order by i_manager_id, avg_monthly_sales, sum_sales
limit 100
"""

QUERIES["q67"] = """
select i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
       d_moy, s_store_name, sumsales, rk
from (
  select i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
         d_moy, s_store_name, sumsales,
         rank() over (partition by i_category
                      order by sumsales desc) rk
  from (
    select i.i_category, i.i_class, i.i_brand, i.i_product_name,
           d.d_year, d.d_qoy, d.d_moy, s.s_store_name,
           sum(ss.ss_sales_price * ss.ss_quantity) sumsales
    from store_sales ss
    join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
    join store s on ss.ss_store_sk = s.s_store_sk
    join item i on ss.ss_item_sk = i.i_item_sk
    where d.d_month_seq between 1200 and 1211
    group by rollup(i.i_category, i.i_class, i.i_brand,
                    i.i_product_name, d.d_year, d.d_qoy, d.d_moy,
                    s.s_store_name)
  ) dw1
) dw2
where rk <= 3
order by i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
         d_moy, s_store_name, sumsales, rk
"""

QUERIES["q68"] = """
with dn as (
  select ss.ss_ticket_number, ss.ss_customer_sk,
         ca.ca_city bought_city,
         sum(ss.ss_ext_sales_price) extended_price,
         sum(ss.ss_coupon_amt) amt,
         sum(ss.ss_net_profit) profit
  from store_sales ss
  join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
  join store s on ss.ss_store_sk = s.s_store_sk
  join household_demographics hd on ss.ss_hdemo_sk = hd.hd_demo_sk
  join customer_address ca on ss.ss_addr_sk = ca.ca_address_sk
  where d.d_dom between 1 and 2
    and (hd.hd_dep_count = 7 or hd.hd_vehicle_count = 3)
    and d.d_year in (1998, 1999, 2000)
    and s.s_city in ('Midway', 'Fairview')
  group by ss.ss_ticket_number, ss.ss_customer_sk, ca.ca_city
)
select c.c_last_name, c.c_first_name, ca.ca_city current_city,
       dn.bought_city, dn.extended_price, dn.amt, dn.profit,
       dn.ss_ticket_number
from dn
join customer c on dn.ss_customer_sk = c.c_customer_sk
join customer_address ca on c.c_current_addr_sk = ca.ca_address_sk
where dn.bought_city <> ca.ca_city
order by c.c_last_name, dn.ss_ticket_number
limit 100
"""

QUERIES["q88"] = """
select
 (select count(*) from store_sales ss
  join household_demographics hd on ss.ss_hdemo_sk = hd.hd_demo_sk
  join time_dim t on ss.ss_sold_time_sk = t.t_time_sk
  join store s on ss.ss_store_sk = s.s_store_sk
  where t.t_hour = 8 and t.t_minute >= 30 and hd.hd_dep_count = 4
    and s.s_store_name = 'ese') h8_30_to_9,
 (select count(*) from store_sales ss
  join household_demographics hd on ss.ss_hdemo_sk = hd.hd_demo_sk
  join time_dim t on ss.ss_sold_time_sk = t.t_time_sk
  join store s on ss.ss_store_sk = s.s_store_sk
  where t.t_hour = 9 and t.t_minute < 30 and hd.hd_dep_count = 4
    and s.s_store_name = 'ese') h9_to_9_30,
 (select count(*) from store_sales ss
  join household_demographics hd on ss.ss_hdemo_sk = hd.hd_demo_sk
  join time_dim t on ss.ss_sold_time_sk = t.t_time_sk
  join store s on ss.ss_store_sk = s.s_store_sk
  where t.t_hour = 9 and t.t_minute >= 30 and hd.hd_dep_count = 4
    and s.s_store_name = 'ese') h9_30_to_10,
 (select count(*) from store_sales ss
  join household_demographics hd on ss.ss_hdemo_sk = hd.hd_demo_sk
  join time_dim t on ss.ss_sold_time_sk = t.t_time_sk
  join store s on ss.ss_store_sk = s.s_store_sk
  where t.t_hour = 10 and t.t_minute < 30 and hd.hd_dep_count = 4
    and s.s_store_name = 'ese') h10_to_10_30
"""

QUERIES["q13"] = """
select avg(ss.ss_quantity) a1, avg(ss.ss_ext_sales_price) a2,
       avg(ss.ss_wholesale_cost) a3, sum(ss.ss_wholesale_cost) s1
from store_sales ss
join store s on s.s_store_sk = ss.ss_store_sk
join customer_demographics cd on cd.cd_demo_sk = ss.ss_cdemo_sk
join household_demographics hd on ss.ss_hdemo_sk = hd.hd_demo_sk
join customer_address ca on ss.ss_addr_sk = ca.ca_address_sk
join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
where d.d_year = 2001
  and ((cd.cd_marital_status = 'M'
        and cd.cd_education_status = '4 yr Degree'
        and ss.ss_sales_price between 100.00 and 150.00
        and hd.hd_dep_count = 3)
    or (cd.cd_marital_status = 'S'
        and cd.cd_education_status = 'College'
        and ss.ss_sales_price between 50.00 and 100.00
        and hd.hd_dep_count = 1)
    or (cd.cd_marital_status = 'W'
        and cd.cd_education_status = '2 yr Degree'
        and ss.ss_sales_price between 150.00 and 200.00
        and hd.hd_dep_count = 1))
  and ((ca.ca_country = 'United States'
        and ca.ca_state in ('TN', 'SD', 'GA')
        and ss.ss_net_profit between 100 and 200)
    or (ca.ca_country = 'United States'
        and ca.ca_state in ('AL', 'MN', 'NC')
        and ss.ss_net_profit between 150 and 300)
    or (ca.ca_country = 'United States'
        and ca.ca_state in ('TN', 'MN', 'NC')
        and ss.ss_net_profit between 50 and 250))
"""

QUERIES["q6"] = """
with ia as (
  select i_category cat, avg(i_current_price) avg_price
  from item group by i_category
)
select ca.ca_state state, count(*) cnt
from customer_address ca
join customer c on ca.ca_address_sk = c.c_current_addr_sk
join store_sales ss on c.c_customer_sk = ss.ss_customer_sk
join date_dim d on ss.ss_sold_date_sk = d.d_date_sk
join item i on ss.ss_item_sk = i.i_item_sk
join ia on i.i_category = ia.cat
where d.d_year = 2001 and d.d_moy = 1
  and i.i_current_price > 1.2 * ia.avg_price
group by ca.ca_state
having count(*) >= 10
order by cnt, ca.ca_state
limit 100
"""
