"""Cast expression — numeric/bool/datetime matrix.

Counterpart of ``GpuCast.scala`` (1,444 LoC — the compatibility-heavy one).
This module covers the non-string portion of the matrix with Spark's
non-ANSI semantics:

* float -> integral saturates at the target range like Java's ``toInt``
  (NaN -> 0, +/-Inf -> MIN/MAX);
* integral -> integral wraps (narrowing keeps low bits);
* bool <-> numeric as 1/0 and != 0;
* date <-> timestamp via UTC midnight (86_400_000_000 us/day);
* integral -> timestamp treats the value as *seconds* since epoch.

String casts live in ``stringops.py`` (they need the chars/offsets layout).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.expressions import (
    ColVal, EmitContext, Expression, UnaryExpression,
)

US_PER_DAY = 86_400_000_000
US_PER_SEC = 1_000_000


def cast_supported(src: DataType, dst: DataType):
    """None when the cast runs on device; else the reason string (the
    planner tags it and the query falls back to CPU) — the TypeSig role
    GpuCast.scala's matrix plays in the reference."""
    if src.name == dst.name:
        return None
    if src.is_array or dst.is_array:
        return f"cast {src} -> {dst}: array casts not supported"
    if src.is_string:
        if dst.is_decimal:
            return "cast string -> decimal not supported on TPU"
        return None  # numeric/bool/date/timestamp parse on device
    if dst.is_string:
        if src.is_floating or src.is_decimal:
            return (f"cast {src} -> string needs shortest-round-trip "
                    "float formatting (host fallback)")
        return None  # int/bool/date/timestamp format on device
    return None

_INT_RANGE = {
    "tinyint": (-(1 << 7), (1 << 7) - 1),
    "smallint": (-(1 << 15), (1 << 15) - 1),
    "int": (-(1 << 31), (1 << 31) - 1),
    "bigint": (-(1 << 63), (1 << 63) - 1),
}


def cast_colval(c: ColVal, target: DataType, ctx: EmitContext) -> ColVal:
    src = c.dtype
    if src.name == target.name:
        return c
    if src.is_string or target.is_string:
        from spark_rapids_tpu.ops import stringops
        return stringops.cast_string(c, target, ctx)
    v = c.values
    validity = c.validity

    if target.is_boolean:
        out = v != 0
    elif src.is_boolean:
        out = v.astype(target.storage)
    elif src.is_floating and target.is_integral:
        lo, hi = _INT_RANGE[target.name]
        t = jnp.trunc(jnp.where(jnp.isnan(v), 0.0, v))
        # XLA's float->int conversion is inexact at the range edge; saturate
        # in the integer domain (Java toInt/toLong semantics).
        i64 = jnp.clip(t, -9.2233720368547e18, 9.2233720368547e18).astype(
            jnp.int64)
        i64 = jnp.where(t >= float(1 << 63), (1 << 63) - 1, i64)
        i64 = jnp.where(t <= float(-(1 << 63)), -(1 << 63), i64)
        out = jnp.clip(i64, lo, hi).astype(target.storage)
    elif src.is_date and target.is_timestamp:
        out = v.astype(jnp.int64) * US_PER_DAY
    elif src.is_timestamp and target.is_date:
        out = (v // US_PER_DAY).astype(jnp.int32)
    elif src.is_integral and target.is_timestamp:
        out = v.astype(jnp.int64) * US_PER_SEC
    elif src.is_timestamp and target.is_integral:
        out = _saturate_int(v // US_PER_SEC, target)
    elif src.is_timestamp and target.is_floating:
        out = v.astype(target.storage) / US_PER_SEC
    elif src.is_floating and target.is_timestamp:
        out = jnp.trunc(v * US_PER_SEC).astype(jnp.int64)
    elif src.is_decimal and target.is_decimal:
        out = _rescale_decimal(v, src.scale, target.scale)
    elif src.is_decimal:
        scaled = v.astype(jnp.float64) / (10 ** src.scale)
        if target.is_integral:
            out = jnp.trunc(scaled).astype(target.storage)
        else:
            out = scaled.astype(target.storage)
    elif target.is_decimal:
        if src.is_integral:
            out = v.astype(jnp.int64) * (10 ** target.scale)
        else:
            out = jnp.round(v * (10 ** target.scale)).astype(jnp.int64)
    elif src.is_integral and target.is_integral:
        out = v.astype(target.storage)  # wrapping narrow
    else:
        out = v.astype(target.storage)
    return ColVal(target, out, validity)


def _saturate_int(v, target: DataType):
    lo, hi = _INT_RANGE[target.name]
    return jnp.clip(v, lo, hi).astype(target.storage)


def _rescale_decimal(v, from_scale: int, to_scale: int):
    if to_scale >= from_scale:
        return v * (10 ** (to_scale - from_scale))
    f = 10 ** (from_scale - to_scale)
    # HALF_UP rescale
    half = f // 2
    return jnp.where(v >= 0, (v + half) // f, -((-v + half) // f))


class Cast(Expression):
    """Non-ANSI cast: invalid parses/overflow produce null/truncation
    (Spark default).  ``ansi=True`` is the AnsiCast analog: any row that
    fails to convert registers a runtime check that raises host-side
    after the stage executes (GpuCast.scala ansi mode throws)."""

    def __init__(self, child: Expression, target: DataType,
                 ansi: bool = False):
        self.children = (child,)
        self.target = target
        self.ansi = ansi

    @property
    def child(self):
        return self.children[0]

    def with_children(self, children):
        return Cast(children[0], self.target, self.ansi)

    @property
    def dtype(self) -> DataType:
        return self.target

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        out = cast_colval(c, self.target, ctx)
        if self.ansi:
            self._ansi_checks(c, out, ctx)
        return out

    def _ansi_checks(self, c: ColVal, out: ColVal, ctx: EmitContext):
        # check_mask, not row_mask: inside a fused stage, rows a fused
        # upstream filter drops must not raise (the unfused plan would
        # have compacted them away before this cast ever ran)
        live = ctx.check_mask()
        src, dst = c.dtype, self.target
        bad = None
        if src.is_string and out.validity is not None:
            # rows that were valid input but failed to parse
            bad = jnp.logical_not(out.validity)
        elif src.is_floating and dst.is_integral:
            # Spark ANSI bounds the TRUNCATED value (cast(127.6 as
            # tinyint) is 127, not an overflow)
            lo, hi = _INT_RANGE[dst.name]
            v = c.values
            t = jnp.trunc(v)
            bad = jnp.isnan(v) | (t < float(lo)) | (t > float(hi))
        elif src.is_integral and dst.is_integral:
            lo, hi = _INT_RANGE[dst.name]
            bad = (c.values < lo) | (c.values > hi)
        if bad is None:
            return
        msg = (f"invalid input for cast to {dst}" if src.is_string
               else f"overflow casting {src} to {dst}")
        bad = jnp.logical_and(bad, live)
        if c.validity is not None:  # null inputs never error
            bad = jnp.logical_and(bad, c.validity)
        ctx.add_check(msg, jnp.any(bad))

    def cache_key(self):
        return ("Cast", self.target.name, self.ansi,
                self.child.cache_key())

    def __str__(self):
        kind = "ansi_cast" if self.ansi else "cast"
        return f"{kind}({self.child} as {self.target})"
