"""Equi-join kernels: combined-sort run matching + two-phase materialization.

The reference drives cudf hash joins and materializes unbounded outputs
through chunked gather maps (``GpuHashJoin.scala:96``, ``JoinGatherer.scala:
36-60``).  Hash tables scatter serially; the TPU formulation is sort-merge:

* phase A (``join_match``): concatenate build+probe key columns, lexsort by
  (keys, side) so each equal-key run holds its build rows first; segment
  arithmetic yields, for every probe row, its match count and the sorted
  position of its first build match.  Null keys never match (Spark equi-join
  semantics) but outer/anti rows survive via count adjustment.
* phase B (``join_gather``): with the total match count known on the host,
  a bucketed output capacity is chosen and every output row is mapped back
  to (probe row, k-th build match) with two searchsorted/gather passes —
  the same static-shape expansion trick as the string gather.

Semi/anti joins skip phase B entirely (a compaction of the probe side).
Full outer adds one extra batch of never-matched build rows.
"""

from __future__ import annotations

from functools import lru_cache
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.ops.expressions import ColVal
from spark_rapids_tpu.ops import selection


def _concat_col(b: ColVal, p: ColVal) -> ColVal:
    values = jnp.concatenate([b.values, p.values])
    validity = None
    if b.validity is not None or p.validity is not None:
        bv = b.validity if b.validity is not None else \
            jnp.ones(b.values.shape[0], dtype=jnp.bool_)
        pv = p.validity if p.validity is not None else \
            jnp.ones(p.values.shape[0], dtype=jnp.bool_)
        validity = jnp.concatenate([bv, pv])
    return ColVal(b.dtype, values, validity)


def _norm_key(v):
    if jnp.issubdtype(v.dtype, jnp.floating):
        v = jnp.where(v == 0.0, 0.0, v)
        bits = v.astype(jnp.float64).view(jnp.int64)
        v = jnp.where(bits < 0, jnp.int64(-1) ^ bits, bits)
    elif v.dtype == jnp.bool_:
        v = v.astype(jnp.int8)
    return v


@jax.jit
def join_match(build_keys: Sequence[ColVal], probe_keys: Sequence[ColVal],
               build_n, probe_n):
    """Phase A. Returns a dict of device arrays (see keys below)."""
    b_cap = build_keys[0].values.shape[0]
    p_cap = probe_keys[0].values.shape[0]
    cap = b_cap + p_cap
    pos = jnp.arange(cap, dtype=jnp.int32)
    is_build = pos < b_cap
    side = jnp.where(is_build, 0, 1).astype(jnp.int8)

    # live = in-range AND all keys non-null (null never matches)
    live_b = pos < build_n
    live_p = (pos >= b_cap) & (pos < b_cap + probe_n)
    live = live_b | live_p
    norm_keys = []
    for bk, pk in zip(build_keys, probe_keys):
        c = _concat_col(bk, pk)
        if c.validity is not None:
            live = live & c.validity
        norm_keys.append(_norm_key(c.values))

    # sort: dead rows last, then by keys, then build before probe
    lex = [side]
    for k in reversed(norm_keys):
        lex.append(k)
    lex.append(jnp.logical_not(live).astype(jnp.int8))
    perm = jnp.lexsort(lex).astype(jnp.int32)
    n_live = live.sum().astype(jnp.int32)

    s_keys = [k[perm] for k in norm_keys]
    s_side = side[perm]
    s_live = jnp.arange(cap, dtype=jnp.int32) < n_live

    same = jnp.ones(cap, dtype=jnp.bool_)
    for k in s_keys:
        same = same & (k == jnp.roll(k, 1))
    boundary = jnp.logical_and(jnp.logical_not(same.at[0].set(True)) |
                               (jnp.arange(cap) == 0), s_live)
    run_id = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    run_id = jnp.where(s_live, run_id, cap)  # trash segment

    sb = jnp.logical_and(s_side == 0, s_live)
    sp = jnp.logical_and(s_side == 1, s_live)
    build_per_run = jax.ops.segment_sum(sb.astype(jnp.int32), run_id,
                                        num_segments=cap + 1)[:cap]
    probe_per_run = jax.ops.segment_sum(sp.astype(jnp.int32), run_id,
                                        num_segments=cap + 1)[:cap]
    spos = jnp.arange(cap, dtype=jnp.int32)
    first_build = jax.ops.segment_min(
        jnp.where(sb, spos, cap), run_id, num_segments=cap + 1)[:cap]

    # scatter per-sorted-probe-row info back to original probe row ids
    orig = perm - b_cap  # original probe row (valid where s_side==1)
    probe_tgt = jnp.where(sp, orig, p_cap)
    probe_count = jnp.zeros(p_cap, dtype=jnp.int32).at[probe_tgt].set(
        jnp.where(sp, build_per_run[jnp.clip(run_id, 0, cap - 1)], 0),
        mode="drop")
    probe_bstart = jnp.zeros(p_cap, dtype=jnp.int32).at[probe_tgt].set(
        jnp.where(sp, first_build[jnp.clip(run_id, 0, cap - 1)], 0),
        mode="drop")

    # sorted position -> original build row
    sorted_to_build = jnp.where(s_side == 0, perm, 0).astype(jnp.int32)

    # build rows that matched no probe row (for full outer)
    build_matched = jnp.zeros(b_cap, dtype=jnp.bool_)
    build_tgt = jnp.where(sb, perm, b_cap)
    build_matched = build_matched.at[build_tgt].set(
        jnp.where(sb, probe_per_run[jnp.clip(run_id, 0, cap - 1)] > 0,
                  False), mode="drop")
    return {
        "probe_count": probe_count,
        "probe_bstart": probe_bstart,
        "sorted_to_build": sorted_to_build,
        "build_matched": build_matched,
    }


from functools import partial


def hash_join_eligible(build_keys: Sequence[ColVal],
                       probe_keys: Sequence[ColVal],
                       max_table_slots: int = 1 << 20) -> bool:
    """Trace-time gate for the hash phase-A: single key column (the
    normalized key IS the 64-bit table code — multi-key needs a range
    probe the sort path doesn't) and a build side small enough that a
    half-load table fits the VMEM bound."""
    if len(build_keys) != 1 or len(probe_keys) != 1:
        return False
    b_cap = build_keys[0].values.shape[0]
    return hash_join_table_slots(b_cap) <= max_table_slots


def hash_join_table_slots(b_cap: int) -> int:
    """Power-of-two table sized for load factor <= 0.5 over the build
    capacity (distinct build keys <= b_cap, so insertion never runs out
    of slots; only pathological probe chains can still overflow)."""
    t = 64
    while t < 2 * max(b_cap, 1):
        t *= 2
    return t


@partial(jax.jit, static_argnames=("num_slots", "interpret"))
def hash_join_match(build_keys: Sequence[ColVal],
                    probe_keys: Sequence[ColVal],
                    build_n, probe_n, num_slots: int,
                    interpret: bool | None = None):
    """Hash phase-A: same contract as :func:`join_match` plus an
    ``overflow`` flag — when True the outputs are garbage to DISCARD and
    the caller re-runs the sort-merge phase (rows are never dropped).

    Bit-compatibility with the sort path: the table groups build rows by
    exact normalized key; ``sorted_to_build`` lists each slot's build
    rows in ORIGINAL index order (stable sort by slot), which is exactly
    the within-run order the stable lexsort produces — so phase B
    materializes byte-identical output, whichever phase A ran."""
    from spark_rapids_tpu.ops import pallas_kernels as pk
    bk, pk_col = build_keys[0], probe_keys[0]
    b_cap = bk.values.shape[0]
    p_cap = pk_col.values.shape[0]
    T = num_slots

    live_b = jnp.arange(b_cap, dtype=jnp.int32) < build_n
    if bk.validity is not None:
        live_b = live_b & bk.validity
    code_b = _norm_key(bk.values).astype(jnp.int64)
    blo = code_b.astype(jnp.int32)
    bhi = (code_b >> 32).astype(jnp.int32)
    if interpret is None:
        slot_b, tlo, thi, occ, overflow = pk.hash_table_insert(
            blo, bhi, live_b, T)
    else:
        slot_b, tlo, thi, occ, overflow = pk.hash_insert(
            blo, bhi, live_b, T, interpret=interpret)
    slot_b = slot_b.astype(jnp.int32)  # T for dead/overflowed rows

    # build rows grouped by slot, ORIGINAL order within a slot (stable)
    sorted_to_build = jnp.lexsort([slot_b]).astype(jnp.int32)
    counts = jnp.bincount(slot_b, length=T + 1)[:T].astype(jnp.int32)
    starts = (jnp.cumsum(counts) - counts).astype(jnp.int32)

    live_p = jnp.arange(p_cap, dtype=jnp.int32) < probe_n
    if pk_col.validity is not None:
        live_p = live_p & pk_col.validity
    code_p = _norm_key(pk_col.values).astype(jnp.int64)
    if interpret is None:
        pslot = pk.hash_table_probe(
            code_p.astype(jnp.int32), (code_p >> 32).astype(jnp.int32),
            live_p, tlo, thi, occ)
    else:
        pslot = pk.hash_probe(
            code_p.astype(jnp.int32), (code_p >> 32).astype(jnp.int32),
            live_p, tlo, thi, occ, interpret=interpret)
    hit = pslot < T
    safe = jnp.clip(pslot, 0, T - 1)
    probe_count = jnp.where(hit, counts[safe], 0).astype(jnp.int32)
    probe_bstart = jnp.where(hit, starts[safe], 0).astype(jnp.int32)

    matched_slot = jnp.zeros(T + 1, dtype=jnp.bool_)
    matched_slot = matched_slot.at[pslot].set(True)  # T = trash
    build_matched = live_b & (slot_b < T) & \
        matched_slot[jnp.clip(slot_b, 0, T - 1)]
    return {
        "probe_count": probe_count,
        "probe_bstart": probe_bstart,
        "sorted_to_build": sorted_to_build,
        "build_matched": build_matched,
        "overflow": overflow,
    }


@partial(jax.jit, static_argnames=("outer",))
def join_out_starts(probe_count, probe_n, outer: bool):
    """Adjusted counts (left outer keeps unmatched with one null row),
    exclusive starts, inclusive ends, and total."""
    p_cap = probe_count.shape[0]
    in_range = jnp.arange(p_cap, dtype=jnp.int32) < probe_n
    count = probe_count
    if outer:
        count = jnp.where(in_range & (count == 0), 1, count)
    count = jnp.where(in_range, count, 0)
    ends = jnp.cumsum(count, dtype=jnp.int64)
    starts = (ends - count).astype(jnp.int64)
    return count, starts, ends, ends[p_cap - 1]


@lru_cache(maxsize=None)
def _gather_indices_kernel(out_cap: int):
    @jax.jit
    def run(starts, ends, probe_count, probe_bstart, sorted_to_build, total):
        j = jnp.arange(out_cap, dtype=jnp.int64)
        p = jnp.searchsorted(ends, j, side="right").astype(jnp.int32)
        p = jnp.clip(p, 0, probe_count.shape[0] - 1)
        k = (j - starts[p]).astype(jnp.int32)
        matched = k < probe_count[p]
        bpos = probe_bstart[p] + k
        brow = sorted_to_build[jnp.clip(bpos, 0,
                                        sorted_to_build.shape[0] - 1)]
        in_range = j < total
        return p, jnp.clip(brow, 0, None), matched & in_range, in_range
    return run


def join_gather_indices(starts, ends, probe_count, probe_bstart,
                        sorted_to_build, total, out_cap: int):
    """Phase B mapping: output row j -> (probe row, build row, matched?)."""
    return _gather_indices_kernel(out_cap)(
        starts, ends, probe_count, probe_bstart, sorted_to_build, total)


def gather_build_side(cols: Sequence[ColVal], brow, matched,
                      out_count, char_capacity: int = 0) -> List[ColVal]:
    """Gather build columns at brow; unmatched rows become null."""
    outs = selection.gather(cols, brow, out_count,
                            char_capacity=char_capacity)
    res = []
    for o in outs:
        validity = o.validity
        validity = matched if validity is None else \
            jnp.logical_and(validity, matched)
        res.append(ColVal(o.dtype, o.values, validity, o.offsets))
    return res
