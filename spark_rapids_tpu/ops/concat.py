"""Device-side batch concatenation.

The TPU analog of ``GpuCoalesceBatches``' cudf ``Table.concatenate``
(GpuCoalesceBatches.scala:195): small batches are appended into a larger
fixed-capacity buffer entirely on device — no host round trip between a
partial aggregation and its merge pass.

``append_cols`` is shape-polymorphic only over (out_capacity, in_capacity)
pairs, both power-of-two buckets, so the jit cache stays small.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, bucket_capacity
from spark_rapids_tpu.ops.expressions import ColVal


@jax.jit
def _append_fixed(out_vals, out_valid, out_n, in_vals, in_valid, in_n):
    out_cap = out_vals.shape[0]
    pos = jnp.arange(out_cap, dtype=jnp.int32)
    src = jnp.clip(pos - out_n, 0, in_vals.shape[0] - 1)
    write = (pos >= out_n) & (pos < out_n + in_n)
    vals = jnp.where(write, in_vals[src], out_vals)
    valid = jnp.where(write, in_valid[src], out_valid)
    return vals, valid


@jax.jit
def _append_string(out_chars, out_offs, out_valid, out_n,
                   in_chars, in_offs, in_valid, in_n):
    out_cap = out_offs.shape[0] - 1
    pos = jnp.arange(out_cap + 1, dtype=jnp.int32)
    base = out_offs[out_n]
    src = jnp.clip(pos - out_n, 0, in_offs.shape[0] - 1)
    new_offs = jnp.where((pos >= out_n) & (pos <= out_n + in_n),
                         base + in_offs[src], out_offs)
    # rows past the appended region keep the final offset (monotone padding)
    end = base + in_offs[in_n]
    new_offs = jnp.where(pos > out_n + in_n, end, new_offs)

    cpos = jnp.arange(out_chars.shape[0], dtype=jnp.int32)
    csrc = jnp.clip(cpos - base, 0, in_chars.shape[0] - 1)
    cwrite = (cpos >= base) & (cpos < end)
    chars = jnp.where(cwrite, in_chars[csrc], out_chars)

    rpos = jnp.arange(out_cap, dtype=jnp.int32)
    rsrc = jnp.clip(rpos - out_n, 0, in_valid.shape[0] - 1)
    rwrite = (rpos >= out_n) & (rpos < out_n + in_n)
    valid = jnp.where(rwrite, in_valid[rsrc], out_valid)
    return chars, new_offs, valid


def _ensure_validity(col: Column):
    if col.validity is not None:
        return col.validity
    return jnp.ones(col.capacity, dtype=jnp.bool_)


def concat_batches(batches: Sequence[ColumnarBatch]) -> ColumnarBatch:
    """Concatenate same-schema batches into one device batch.

    Batches carrying deferred (device-resident) row counts concatenate
    WITHOUT forcing a host sync: appends run off the device scalars and
    the output capacity is bounded by the input capacities (offset
    columns are the exception — char-buffer sizing is a host decision,
    so string batches resolve their counts in one batched transfer).
    """
    from spark_rapids_tpu.columnar.column import RowCount
    # drop only KNOWN-empty batches; a deferred count is not worth a
    # round trip just to skip an empty input
    batches = [b for b in batches
               if not (b.row_count.is_concrete and b.nrows == 0)] \
        or list(batches[:1])
    if len(batches) == 1:
        return batches[0]
    lazy = any(not b.row_count.is_concrete for b in batches)
    if lazy and any(dt.has_offsets for _, dt in batches[0].schema):
        RowCount.materialize_all([b.row_count for b in batches])
        lazy = False
    if lazy:
        return _concat_batches_lazy(batches)
    total = sum(b.nrows for b in batches)
    cap = bucket_capacity(total)
    names = batches[0].names
    out_cols = {}
    for name in names:
        first = batches[0].column(name)
        dt = first.dtype
        any_nulls = any(b.column(name).validity is not None for b in batches)
        if dt.has_offsets:
            total_chars = sum(
                int(b.column(name).offsets[b.nrows]) for b in batches)
            ccap = bucket_capacity(max(total_chars, 1))
            chars = jnp.zeros(ccap, dtype=dt.storage)
            offs = jnp.zeros(cap + 1, dtype=jnp.int32)
            valid = jnp.zeros(cap, dtype=jnp.bool_)
            n = 0
            for b in batches:
                c = b.column(name)
                chars, offs, valid = _append_string(
                    chars, offs, valid, jnp.int32(n),
                    c.data, c.offsets, _ensure_validity(c),
                    jnp.int32(c.nrows))
                n += c.nrows
            out_cols[name] = Column(dt, chars, total,
                                    validity=valid if any_nulls else None,
                                    offsets=offs)
        else:
            vals = jnp.zeros(cap, dtype=dt.storage)
            valid = jnp.zeros(cap, dtype=jnp.bool_)
            n = 0
            for b in batches:
                c = b.column(name)
                vals, valid = _append_fixed(
                    vals, valid, jnp.int32(n), c.data, _ensure_validity(c),
                    jnp.int32(c.nrows))
                n += c.nrows
            out_cols[name] = Column(dt, vals, total,
                                    validity=valid if any_nulls else None)
    return ColumnarBatch(out_cols, total)


def _concat_batches_lazy(batches: Sequence[ColumnarBatch]) -> ColumnarBatch:
    """Sync-free concat for fixed-width batches with deferred counts:
    append positions come from the device-resident counts, the output
    capacity from the (host-known) input capacities — an upper bound, so
    rows beyond the true total stay padding exactly as shape-bucket
    padding always does."""
    from spark_rapids_tpu.columnar.column import RowCount
    cap = bucket_capacity(sum(b.capacity for b in batches))
    names = batches[0].names
    counts = [b.row_count.device_i32() for b in batches]
    total_dev = counts[0]
    for c in counts[1:]:
        total_dev = total_dev + c
    total_rc = RowCount(device=total_dev)
    out_cols = {}
    for name in names:
        dt = batches[0].column(name).dtype
        any_nulls = any(b.column(name).validity is not None
                        for b in batches)
        vals = jnp.zeros(cap, dtype=dt.storage)
        valid = jnp.zeros(cap, dtype=jnp.bool_)
        n_dev = None
        for b, c in zip(batches, counts):
            col = b.column(name)
            vals, valid = _append_fixed(
                vals, valid, jnp.int32(0) if n_dev is None else n_dev,
                col.data, _ensure_validity(col), c)
            n_dev = c if n_dev is None else n_dev + c
        out_cols[name] = Column(dt, vals, total_rc,
                                validity=valid if any_nulls else None)
    return ColumnarBatch(out_cols, total_rc)
