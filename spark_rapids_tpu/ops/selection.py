"""Row selection kernels: mask compaction and permutation gather.

The TPU replacement for cudf's ``Table.filter`` / gather-map machinery
(reference ``basicPhysicalOperators.scala:297`` GpuFilterExec and
``JoinGatherer.scala``).  cudf allocates an exact-size output; XLA wants
static shapes, so these kernels keep the input capacity and return a traced
``new_nrows`` — the caller re-buckets later if occupancy gets low.

String gather is fully vectorized: new offsets by cumsum of gathered lengths,
then a searchsorted over char positions maps every output byte to its source
byte (O(C log N) for C chars — bandwidth-bound, which is what TPUs like).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.ops.expressions import ColVal


def gather(cols: Sequence[ColVal], indices, out_count,
           char_capacity: int = 0) -> List[ColVal]:
    """Gather rows of every column at ``indices`` (int array, len=capacity).

    Rows at positions >= out_count are padding. ``indices`` entries for
    padding rows may be arbitrary but must be in-range.  ``char_capacity``
    (static) sizes offset-bearing outputs (string chars / array elements)
    when the gather can *expand* totals (join/explode duplication); 0
    keeps each input's capacity.
    """
    capacity = indices.shape[0]
    out_mask = jnp.arange(capacity, dtype=jnp.int32) < out_count
    outs: List[ColVal] = []
    for c in cols:
        validity = None if c.validity is None else c.validity[indices]
        if c.offsets is None:
            outs.append(ColVal(c.dtype, c.values[indices], validity))
            continue
        # string column: rebuild offsets + chars
        lengths = c.offsets[indices + 1] - c.offsets[indices]
        lengths = jnp.where(out_mask, lengths, 0)
        new_offsets = jnp.concatenate(
            [jnp.zeros(1, dtype=jnp.int32), jnp.cumsum(lengths,
                                                       dtype=jnp.int32)])
        in_char_cap = c.values.shape[0]
        out_char_cap = char_capacity or in_char_cap
        pos = jnp.arange(out_char_cap, dtype=jnp.int32)
        # row containing each output byte (last offset <= pos)
        row = jnp.searchsorted(new_offsets, pos, side="right") - 1
        row = jnp.clip(row, 0, capacity - 1)
        src = c.offsets[indices[row]] + (pos - new_offsets[row])
        src = jnp.clip(src, 0, in_char_cap - 1)
        total = new_offsets[capacity]
        # keep the element buffer's own dtype: uint8 chars for strings,
        # the element storage dtype for arrays (a hardcoded uint8 cast
        # silently truncated array elements, e.g. 300 -> 44)
        chars = jnp.where(pos < total, c.values[src],
                          jnp.zeros((), dtype=c.values.dtype))
        outs.append(ColVal(c.dtype, chars, validity, new_offsets))
    return outs


@jax.jit
def gathered_char_count(offsets, indices, out_count):
    """Total chars a gather of ``indices`` would produce (for sizing)."""
    capacity = indices.shape[0]
    mask = jnp.arange(capacity, dtype=jnp.int32) < out_count
    lengths = offsets[indices + 1] - offsets[indices]
    return jnp.where(mask, lengths, 0).sum()


def compact(cols: Sequence[ColVal], keep) -> Tuple[List[ColVal], jnp.ndarray]:
    """Move rows where ``keep`` is True to the front, preserving order.

    Returns (columns, new_nrows). ``keep`` must already exclude padding rows.
    Linear cost: a prefix-sum gives each kept row its target slot and one
    scatter builds the permutation — no sort (cudf's apply_boolean_mask does
    a similar stream compaction; an argsort here would be O(n log^2 n) on
    TPU's bitonic sorter).
    """
    capacity = keep.shape[0]
    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    new_nrows = keep.sum().astype(jnp.int32)
    tgt = jnp.where(keep, pos, capacity)  # dropped rows scatter out of range
    perm = jnp.zeros(capacity, dtype=jnp.int32).at[tgt].set(
        jnp.arange(capacity, dtype=jnp.int32), mode="drop")
    return gather(cols, perm, new_nrows), new_nrows
