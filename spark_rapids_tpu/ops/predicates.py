"""Comparison, logic, null and conditional expressions.

Coverage target: the reference's ``predicates.scala`` (651 LoC),
``nullExpressions.scala`` (281) and ``conditionalExpressions.scala`` (151)
(SURVEY.md Appendix A.1).  Spark semantics replicated here:

* AND/OR use Kleene three-valued logic (null AND false = false);
* floating comparisons treat NaN = NaN as true and NaN as the largest value
  (matching Spark's ordering, `docs/compatibility.md:76-81` in the reference);
* -0.0 compares equal to 0.0 (IEEE, jnp default).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.expressions import (
    BinaryExpression, ColVal, EmitContext, Expression, UnaryExpression,
    cast_value, combine_validity, promote_types,
)


def _is_float(v) -> bool:
    return jnp.issubdtype(v.dtype, jnp.floating)


class _Comparison(BinaryExpression):
    # name of the stringops comparator for string operands (ordering
    # comparisons are exact byte-wise lex — stringops._string_lex_compare)
    _string_op: Optional[str] = None

    @property
    def dtype(self) -> DataType:
        return dts.BOOL

    def emit(self, ctx: EmitContext) -> ColVal:
        if self.left.dtype.is_string and self.right.dtype.is_string and \
                self._string_op is not None:
            from spark_rapids_tpu.ops import stringops
            l = self.left.emit(ctx)
            r = self.right.emit(ctx)
            vals = getattr(stringops, self._string_op)(l, r, ctx)
            return ColVal(dts.BOOL, vals,
                          combine_validity(l.validity, r.validity))
        return super().emit(ctx)


class EqualTo(_Comparison):
    _string_op = "string_equal"

    def eval_values(self, l, r):
        eq = l == r
        if _is_float(l):
            eq = eq | (jnp.isnan(l) & jnp.isnan(r))
        return eq, None


class LessThan(_Comparison):
    _string_op = "string_lt"

    def eval_values(self, l, r):
        lt = l < r
        if _is_float(l):  # NaN is largest: NaN < x is false, x < NaN true unless x NaN
            lt = jnp.where(jnp.isnan(l), False,
                           jnp.where(jnp.isnan(r), True, lt))
        return lt, None


class LessThanOrEqual(_Comparison):
    _string_op = "string_le"

    def eval_values(self, l, r):
        le = l <= r
        if _is_float(l):
            le = jnp.where(jnp.isnan(l), jnp.isnan(r),
                           jnp.where(jnp.isnan(r), True, le))
        return le, None


class GreaterThan(_Comparison):
    _string_op = "string_gt"

    def eval_values(self, l, r):
        gt = l > r
        if _is_float(l):
            gt = jnp.where(jnp.isnan(l), ~jnp.isnan(r),
                           jnp.where(jnp.isnan(r), False, gt))
        return gt, None


class GreaterThanOrEqual(_Comparison):
    _string_op = "string_ge"

    def eval_values(self, l, r):
        ge = l >= r
        if _is_float(l):
            ge = jnp.where(jnp.isnan(l), True,
                           jnp.where(jnp.isnan(r), False, ge))
        return ge, None


class EqualNullSafe(_Comparison):
    """<=> : null-safe equality, never returns null."""

    @property
    def nullable(self) -> bool:
        return False

    def emit(self, ctx: EmitContext) -> ColVal:
        t = promote_types(self.left.dtype, self.right.dtype)
        l = cast_value(self.left.emit(ctx), t)
        r = cast_value(self.right.emit(ctx), t)
        eq = l.values == r.values
        if _is_float(l.values):
            eq = eq | (jnp.isnan(l.values) & jnp.isnan(r.values))
        lv = l.validity if l.validity is not None else jnp.bool_(True)
        rv = r.validity if r.validity is not None else jnp.bool_(True)
        both_valid = jnp.logical_and(lv, rv)
        both_null = jnp.logical_and(jnp.logical_not(lv), jnp.logical_not(rv))
        return ColVal(dts.BOOL, jnp.where(both_valid, eq, both_null))


class And(BinaryExpression):
    """Kleene AND: false dominates null."""

    @property
    def dtype(self):
        return dts.BOOL

    def emit(self, ctx: EmitContext) -> ColVal:
        l = self.left.emit(ctx)
        r = self.right.emit(ctx)
        values = jnp.logical_and(l.values, r.values)
        if l.validity is None and r.validity is None:
            return ColVal(dts.BOOL, values)
        lv = l.validity if l.validity is not None else jnp.bool_(True)
        rv = r.validity if r.validity is not None else jnp.bool_(True)
        # result valid if both valid, or either side is a valid False
        validity = (lv & rv) | (lv & jnp.logical_not(l.values)) | \
            (rv & jnp.logical_not(r.values))
        return ColVal(dts.BOOL, values, validity)


class Or(BinaryExpression):
    """Kleene OR: true dominates null."""

    @property
    def dtype(self):
        return dts.BOOL

    def emit(self, ctx: EmitContext) -> ColVal:
        l = self.left.emit(ctx)
        r = self.right.emit(ctx)
        values = jnp.logical_or(l.values, r.values)
        if l.validity is None and r.validity is None:
            return ColVal(dts.BOOL, values)
        lv = l.validity if l.validity is not None else jnp.bool_(True)
        rv = r.validity if r.validity is not None else jnp.bool_(True)
        validity = (lv & rv) | (lv & l.values) | (rv & r.values)
        return ColVal(dts.BOOL, values, validity)


class Not(UnaryExpression):
    @property
    def dtype(self):
        return dts.BOOL

    def eval_values(self, v, cv):
        return jnp.logical_not(v)


class IsNull(UnaryExpression):
    @property
    def dtype(self):
        return dts.BOOL

    @property
    def nullable(self):
        return False

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        if c.validity is None:
            shape = () if c.is_scalar else (ctx.capacity,)
            return ColVal(dts.BOOL, jnp.zeros(shape, dtype=jnp.bool_))
        return ColVal(dts.BOOL, jnp.logical_not(c.validity))


class IsNotNull(UnaryExpression):
    @property
    def dtype(self):
        return dts.BOOL

    @property
    def nullable(self):
        return False

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        if c.validity is None:
            shape = () if c.is_scalar else (ctx.capacity,)
            return ColVal(dts.BOOL, jnp.ones(shape, dtype=jnp.bool_))
        return ColVal(dts.BOOL, c.validity)


class IsNaN(UnaryExpression):
    @property
    def dtype(self):
        return dts.BOOL

    @property
    def nullable(self):
        return False

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        nan = jnp.isnan(c.values) if _is_float(c.values) else \
            jnp.zeros_like(c.values, dtype=jnp.bool_)
        if c.validity is not None:  # null is not NaN
            nan = jnp.logical_and(nan, c.validity)
        return ColVal(dts.BOOL, nan)


class NaNvl(BinaryExpression):
    """nanvl(a, b): b where a is NaN else a."""

    def eval_values(self, l, r):
        return jnp.where(jnp.isnan(l), r, l), None


class Coalesce(Expression):
    def __init__(self, *children: Expression):
        self.children = tuple(children)

    def with_children(self, children):
        return Coalesce(*children)

    @property
    def dtype(self) -> DataType:
        t = self.children[0].dtype
        for c in self.children[1:]:
            t = promote_types(t, c.dtype)
        return t

    @property
    def nullable(self):
        return all(c.nullable for c in self.children)

    def emit(self, ctx: EmitContext) -> ColVal:
        t = self.dtype
        out = cast_value(self.children[-1].emit(ctx), t)
        for child in reversed(self.children[:-1]):
            c = cast_value(child.emit(ctx), t)
            if c.validity is None:
                out = c
            else:
                values = jnp.where(c.validity, c.values, out.values)
                if out.validity is None:
                    validity = jnp.logical_or(
                        c.validity, jnp.ones((), dtype=jnp.bool_))
                    validity = None
                else:
                    validity = jnp.logical_or(c.validity, out.validity)
                out = ColVal(t, values, validity)
        return out


class If(Expression):
    def __init__(self, pred: Expression, if_true: Expression,
                 if_false: Expression):
        self.children = (pred, if_true, if_false)

    def with_children(self, children):
        return If(*children)

    @property
    def dtype(self) -> DataType:
        return promote_types(self.children[1].dtype, self.children[2].dtype)

    @property
    def nullable(self):
        return (self.children[0].nullable or self.children[1].nullable
                or self.children[2].nullable)

    def emit(self, ctx: EmitContext) -> ColVal:
        t = self.dtype
        p = self.children[0].emit(ctx)
        # null predicate selects the else branch (Spark semantics)
        cond = p.values
        if p.validity is not None:
            cond = jnp.logical_and(cond, p.validity)
        if getattr(cond, "ndim", 0) == 0:
            cond = jnp.broadcast_to(cond, (ctx.capacity,))
        if t.is_string:
            from spark_rapids_tpu.ops.stringops import string_select
            return string_select(
                [cond, jnp.ones(ctx.capacity, dtype=jnp.bool_)],
                [self.children[1].emit(ctx),
                 self.children[2].emit(ctx)], ctx.capacity)
        a = cast_value(self.children[1].emit(ctx), t)
        b = cast_value(self.children[2].emit(ctx), t)
        values = jnp.where(cond, a.values, b.values)
        if a.validity is None and b.validity is None:
            return ColVal(t, values)
        av = a.validity if a.validity is not None else jnp.bool_(True)
        bv = b.validity if b.validity is not None else jnp.bool_(True)
        return ColVal(t, values, jnp.where(cond, av, bv))


class CaseWhen(Expression):
    """CASE WHEN p1 THEN v1 ... [ELSE e] END, lowered to a chain of Ifs."""

    def __init__(self, branches: Sequence[tuple],
                 else_value: Optional[Expression] = None):
        self.branches = [(p, v) for p, v in branches]
        self.else_value = else_value
        flat = [e for pv in self.branches for e in pv]
        if else_value is not None:
            flat.append(else_value)
        self.children = tuple(flat)

    def with_children(self, children):
        n = len(self.branches)
        branches = [(children[2 * i], children[2 * i + 1]) for i in range(n)]
        els = children[2 * n] if self.else_value is not None else None
        return CaseWhen(branches, els)

    def bind(self, schema):
        return self.with_children([c.bind(schema) for c in self.children])

    def _as_if_chain(self) -> Expression:
        from spark_rapids_tpu.ops.expressions import Literal
        els = self.else_value
        if els is None:
            els = Literal(None, self.branches[0][1].dtype)
        out = els
        for pred, val in reversed(self.branches):
            out = If(pred, val, out)
        return out

    @property
    def dtype(self) -> DataType:
        return self._as_if_chain().dtype

    @property
    def nullable(self):
        return self.else_value is None or self._as_if_chain().nullable

    def emit(self, ctx: EmitContext) -> ColVal:
        if self.dtype.is_string:
            # one fused N-branch select instead of a chain of Ifs (each
            # link would materialize an intermediate string column)
            from spark_rapids_tpu.ops.expressions import Literal
            from spark_rapids_tpu.ops.stringops import string_select
            masks, branches = [], []
            for pred, val in self.branches:
                p = pred.emit(ctx)
                cond = p.values
                if p.validity is not None:
                    cond = jnp.logical_and(cond, p.validity)
                if getattr(cond, "ndim", 0) == 0:
                    cond = jnp.broadcast_to(cond, (ctx.capacity,))
                masks.append(cond)
                branches.append(val.emit(ctx))
            els = self.else_value if self.else_value is not None else \
                Literal(None, self.branches[0][1].dtype)
            masks.append(jnp.ones(ctx.capacity, dtype=jnp.bool_))
            branches.append(els.emit(ctx))
            return string_select(masks, branches, ctx.capacity)
        return self._as_if_chain().emit(ctx)

    def cache_key(self):
        return ("CaseWhen", tuple(c.cache_key() for c in self.children),
                self.else_value is not None)


class In(Expression):
    """value IN (literals...)."""

    def __init__(self, value: Expression, options: Sequence[Expression]):
        self.children = (value,) + tuple(options)

    def with_children(self, children):
        return In(children[0], children[1:])

    @property
    def dtype(self):
        return dts.BOOL

    def emit(self, ctx: EmitContext) -> ColVal:
        v = self.children[0].emit(ctx)
        hit = jnp.zeros((), dtype=jnp.bool_)
        has_null_option = jnp.zeros((), dtype=jnp.bool_)
        for opt in self.children[1:]:
            o = opt.emit(ctx)
            if self.children[0].dtype.is_string:
                from spark_rapids_tpu.ops import stringops
                eq = stringops.string_equal(v, o, ctx)
            else:
                eq = v.values == o.values.astype(v.values.dtype)
            if o.validity is not None:
                eq = jnp.logical_and(eq, o.validity)
                has_null_option = jnp.logical_or(
                    has_null_option, jnp.logical_not(o.validity))
            hit = jnp.logical_or(hit, eq)
        # match -> true; no match with a null anywhere -> null; else false
        base = v.validity if v.validity is not None else jnp.bool_(True)
        validity = jnp.logical_and(
            base, jnp.logical_or(hit, jnp.logical_not(has_null_option)))
        hit = jnp.broadcast_to(hit, (ctx.capacity,)) if hit.ndim == 0 else hit
        return ColVal(dts.BOOL, hit, validity)


class InSet(Expression):
    """value IN (large literal set) — the GpuInSet analog: instead of
    chaining K equality ops (the ``In`` lowering), the distinct values
    sit in a sorted device table and membership is one searchsorted +
    gather per row.  Fixed-width types only; ``functions.isin`` switches
    to this form past a size threshold."""

    def __init__(self, child: Expression, values):
        import numpy as np
        self.children = (child,)
        vals = [v for v in values if v is not None]
        self.has_null = len(vals) != len(list(values))
        self.table = np.unique(np.asarray(vals)) if vals else \
            np.zeros(0, dtype=np.int64)

    def with_children(self, children):
        vals = list(self.table)
        if self.has_null:
            vals.append(None)
        return InSet(children[0], vals)

    @property
    def dtype(self):
        return dts.BOOL

    def emit(self, ctx: EmitContext) -> ColVal:
        v = self.children[0].emit(ctx)
        if len(self.table) == 0:
            hit = jnp.zeros(ctx.capacity, dtype=jnp.bool_)
        elif self.children[0].dtype.is_string:
            # byte-equality against each literal (the set came through
            # the isin threshold, so K is user-list sized, not data
            # sized); one length compare + |s| single-byte gathers per
            # literal — no device string table needed
            from spark_rapids_tpu.ops.stringops import (_literal_bytes,
                                                        row_lengths)
            lens = row_lengths(v)
            ccap = v.values.shape[0]
            hit = jnp.zeros(ctx.capacity, dtype=jnp.bool_)
            for s in self.table:
                pat = _literal_bytes(str(s))
                ok = lens == len(pat)
                for i, b in enumerate(pat):
                    idx = jnp.clip(v.offsets[:-1] + i, 0, ccap - 1)
                    ok = jnp.logical_and(ok, v.values[idx] == b)
                hit = jnp.logical_or(hit, ok)
        else:
            table = jnp.asarray(
                self.table.astype(self.children[0].dtype.storage))
            idx = jnp.searchsorted(table, v.values)
            idx = jnp.clip(idx, 0, len(self.table) - 1)
            hit = table[idx] == v.values
        base = v.validity if v.validity is not None else jnp.bool_(True)
        # match -> true; no match with a null in the set -> null
        validity = jnp.logical_and(
            base, jnp.logical_or(hit, not self.has_null))
        if getattr(hit, "ndim", 0) == 0:
            hit = jnp.broadcast_to(hit, (ctx.capacity,))
        return ColVal(dts.BOOL, hit, validity)

    def cache_key(self):
        return ("InSet", self.children[0].cache_key(), self.has_null,
                self.table.tobytes())

    def __str__(self):
        return f"{self.children[0]} INSET[{len(self.table)}]"


class Greatest(Expression):
    def __init__(self, *children: Expression):
        self.children = tuple(children)

    def with_children(self, children):
        return Greatest(*children)

    @property
    def dtype(self):
        t = self.children[0].dtype
        for c in self.children[1:]:
            t = promote_types(t, c.dtype)
        return t

    def emit(self, ctx: EmitContext) -> ColVal:
        # greatest skips nulls; null only if all null
        t = self.dtype
        out = None
        for child in self.children:
            c = cast_value(child.emit(ctx), t)
            if out is None:
                out = c
                continue
            if c.validity is None and out.validity is None:
                out = ColVal(t, jnp.maximum(out.values, c.values))
            else:
                ov = out.validity if out.validity is not None else jnp.bool_(True)
                cv = c.validity if c.validity is not None else jnp.bool_(True)
                bigger = jnp.where(
                    ov & cv, jnp.maximum(out.values, c.values),
                    jnp.where(ov, out.values, c.values))
                out = ColVal(t, bigger, jnp.logical_or(ov, cv))
        return out


class Least(Expression):
    def __init__(self, *children: Expression):
        self.children = tuple(children)

    def with_children(self, children):
        return Least(*children)

    @property
    def dtype(self):
        t = self.children[0].dtype
        for c in self.children[1:]:
            t = promote_types(t, c.dtype)
        return t

    def emit(self, ctx: EmitContext) -> ColVal:
        t = self.dtype
        out = None
        for child in self.children:
            c = cast_value(child.emit(ctx), t)
            if out is None:
                out = c
                continue
            if c.validity is None and out.validity is None:
                out = ColVal(t, jnp.minimum(out.values, c.values))
            else:
                ov = out.validity if out.validity is not None else jnp.bool_(True)
                cv = c.validity if c.validity is not None else jnp.bool_(True)
                smaller = jnp.where(
                    ov & cv, jnp.minimum(out.values, c.values),
                    jnp.where(ov, out.values, c.values))
                out = ColVal(t, smaller, jnp.logical_or(ov, cv))
        return out


class AtLeastNNonNulls(Expression):
    def __init__(self, n: int, *children: Expression):
        self.n = int(n)
        self.children = tuple(children)

    def with_children(self, children):
        return AtLeastNNonNulls(self.n, *children)

    @property
    def dtype(self):
        return dts.BOOL

    @property
    def nullable(self):
        return False

    def emit(self, ctx: EmitContext) -> ColVal:
        count = jnp.zeros((), dtype=jnp.int32)
        total = None
        for child in self.children:
            c = child.emit(ctx)
            valid = c.validity if c.validity is not None else jnp.bool_(True)
            if _is_float(c.values):
                valid = jnp.logical_and(valid, jnp.logical_not(
                    jnp.isnan(c.values)))
            inc = valid.astype(jnp.int32)
            total = inc if total is None else total + inc
        return ColVal(dts.BOOL, total >= self.n)

    def cache_key(self):
        return ("AtLeastNNonNulls", self.n,
                tuple(c.cache_key() for c in self.children))


class KnownNotNull(UnaryExpression):
    @property
    def nullable(self):
        return False

    def emit(self, ctx):
        c = self.child.emit(ctx)
        return ColVal(c.dtype, c.values, None, c.offsets)


class KnownFloatingPointNormalized(UnaryExpression):
    def emit(self, ctx):
        return self.child.emit(ctx)


class NormalizeNaNAndZero(UnaryExpression):
    """Canonicalize NaN payloads and -0.0 -> 0.0 before grouping/joining
    (reference NormalizeFloatingNumbers.scala:38)."""

    def eval_values(self, v, cv):
        v = jnp.where(jnp.isnan(v), jnp.nan, v)
        return jnp.where(v == 0.0, 0.0, v)
