"""String expressions over the chars+offsets layout.

Coverage target: reference ``stringFunctions.scala`` (1,053 LoC).  Filled in
incrementally; cast_string is the GpuCast string-path hook.
"""

from __future__ import annotations

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.expressions import ColVal, EmitContext


def cast_string(c: ColVal, target: DataType, ctx: EmitContext) -> ColVal:
    raise NotImplementedError(
        f"cast {c.dtype} -> {target} not yet supported on TPU")
