"""String expressions over the chars+offsets device layout.

Coverage target: the reference's ``stringFunctions.scala`` (1,053 LoC,
SURVEY.md Appendix A.1 "Strings").  Everything here is expressed as
bandwidth-friendly vector ops over the flat uint8 chars array plus per-row
offsets:

* per-row scalars (length, startswith, contains, ...) reduce over byte
  ranges via a byte->row segment map (searchsorted over offsets);
* producers (substring, concat, trim, pad, upper/lower) compute output
  lengths first, then map every output byte back to its source byte — the
  same two-searchsorted pattern the row gather uses;
* character (not byte) positions honor UTF-8 via a prefix sum over
  non-continuation bytes.

Case mapping is ASCII-only (documented incompat, like several cudf string
ops in the reference).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.expressions import (
    ColVal, EmitContext, Expression, UnaryExpression, combine_validity,
)


# ------------------------------------------------------------ layout helpers

def row_lengths(c: ColVal):
    """byte length per row."""
    return c.offsets[1:] - c.offsets[:-1]


def char_lengths(c: ColVal, ctx: EmitContext):
    """UTF-8 character count per row (non-continuation bytes)."""
    is_start = (c.values & 0xC0) != 0x80
    prefix = jnp.concatenate([jnp.zeros(1, dtype=jnp.int32),
                              jnp.cumsum(is_start.astype(jnp.int32))])
    return prefix[c.offsets[1:]] - prefix[c.offsets[:-1]]


def byte_to_row(c: ColVal, capacity: int):
    """row index of every byte position in the chars array."""
    pos = jnp.arange(c.values.shape[0], dtype=jnp.int32)
    row = jnp.searchsorted(c.offsets, pos, side="right") - 1
    return jnp.clip(row, 0, capacity - 1)


def build_strings(lengths, src_byte_fn, src_chars, out_char_cap: int,
                  capacity: int):
    """Construct (chars, offsets) given per-row output lengths and a
    function mapping (out_byte_pos, out_row, offset_in_row) -> source byte
    index into ``src_chars`` (already clipped)."""
    lengths = jnp.maximum(lengths, 0).astype(jnp.int32)
    offsets = jnp.concatenate([jnp.zeros(1, dtype=jnp.int32),
                               jnp.cumsum(lengths, dtype=jnp.int32)])
    pos = jnp.arange(out_char_cap, dtype=jnp.int32)
    row = jnp.searchsorted(offsets, pos, side="right") - 1
    row = jnp.clip(row, 0, capacity - 1)
    k = pos - offsets[row]
    src = src_byte_fn(pos, row, k)
    total = offsets[capacity]
    chars = jnp.where(pos < total,
                      src_chars[jnp.clip(src, 0, src_chars.shape[0] - 1)],
                      0).astype(jnp.uint8)
    return chars, offsets


def _literal_bytes(s: str) -> np.ndarray:
    return np.frombuffer(s.encode("utf-8"), dtype=np.uint8)


def string_select(masks, branches, capacity: int):
    """CASE over string branches: per row, the first true mask picks its
    branch's string; no true mask -> null.

    ``branches`` are string ColVals — full columns (offsets of
    capacity+1) or 1-row literals (offsets of length 2, broadcast to
    every row).  One fused pass: the chosen branch's (start, len) per
    row indexes a concatenation of all branch char buffers, and
    ``build_strings`` lays out the output — no per-branch materializing,
    no host loop."""
    nb = len(branches)
    ar = jnp.arange(capacity, dtype=jnp.int32)
    idx = jnp.full(capacity, nb, dtype=jnp.int32)
    for i in reversed(range(nb)):
        idx = jnp.where(masks[i], jnp.int32(i), idx)
    chosen = idx < nb
    safe = jnp.clip(idx, 0, nb - 1)
    starts, lens, valids, chunks = [], [], [], []
    base = 0
    out_char_cap = 0
    # literals contribute capacity * MAX literal length once (each row
    # picks at most one branch), not per-branch
    lit_max = 0
    for b in branches:
        if b.offsets is None:
            # null literal branch: zero-length slice, never valid
            chunks.append(jnp.zeros(0, dtype=jnp.uint8))
            starts.append(jnp.full(capacity, base, dtype=jnp.int32))
            lens.append(jnp.zeros(capacity, dtype=jnp.int32))
            valids.append(jnp.zeros(capacity, dtype=jnp.bool_))
            continue
        ch = b.values
        chunks.append(ch)
        if b.offsets.shape[0] == capacity + 1:
            st = b.offsets[:capacity].astype(jnp.int32)
            ln = (b.offsets[1:] - b.offsets[:-1]).astype(jnp.int32)
            out_char_cap += int(ch.shape[0])
        else:  # 1-row literal: same slice for every row
            st = jnp.zeros(capacity, dtype=jnp.int32)
            ln = jnp.broadcast_to(b.offsets[-1].astype(jnp.int32),
                                  (capacity,))
            lit_max = max(lit_max, int(ch.shape[0]))
        starts.append(st + base)
        lens.append(ln)
        if b.validity is None:
            valids.append(jnp.ones(capacity, dtype=jnp.bool_))
        elif getattr(b.validity, "ndim", 0) == 0:
            valids.append(jnp.broadcast_to(b.validity, (capacity,)))
        elif b.validity.shape[0] == capacity:
            valids.append(b.validity)
        else:
            valids.append(jnp.broadcast_to(b.validity[0], (capacity,)))
        base += int(ch.shape[0])
    out_char_cap += lit_max * capacity
    all_chars = jnp.concatenate(chunks) if chunks else \
        jnp.zeros(0, dtype=jnp.uint8)
    smat = jnp.stack(starts)
    lmat = jnp.stack(lens)
    vmat = jnp.stack(valids)
    row_start = smat[safe, ar]
    validity = jnp.logical_and(chosen, vmat[safe, ar])
    row_len = jnp.where(validity, lmat[safe, ar], 0)
    from spark_rapids_tpu.columnar.column import bucket_capacity
    chars, offsets = build_strings(
        row_len, lambda pos, row, k: row_start[row] + k, all_chars,
        bucket_capacity(out_char_cap, minimum=8), capacity)
    return ColVal(dts.STRING, chars, validity=validity, offsets=offsets)


# ------------------------------------------------------------------- scalars

class Length(UnaryExpression):
    """character length (Spark length())."""

    @property
    def dtype(self):
        return dts.INT32

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        return ColVal(dts.INT32, char_lengths(c, ctx).astype(jnp.int32),
                      c.validity)


class OctetLength(UnaryExpression):
    @property
    def dtype(self):
        return dts.INT32

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        return ColVal(dts.INT32, row_lengths(c).astype(jnp.int32),
                      c.validity)


class _PatternPredicate(Expression):
    """Base for startswith/endswith/contains with a literal pattern."""

    def __init__(self, child: Expression, pattern: str):
        self.children = (child,)
        self.pattern = pattern

    @property
    def child(self):
        return self.children[0]

    def with_children(self, children):
        return type(self)(children[0], self.pattern)

    @property
    def dtype(self):
        return dts.BOOL

    def cache_key(self):
        return (type(self).__name__, self.pattern, self.child.cache_key())


class StartsWith(_PatternPredicate):
    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        pat = _literal_bytes(self.pattern)
        lens = row_lengths(c)
        ok = lens >= len(pat)
        ccap = c.values.shape[0]
        for i, b in enumerate(pat):
            idx = jnp.clip(c.offsets[:-1] + i, 0, ccap - 1)
            ok = jnp.logical_and(ok, c.values[idx] == b)
        return ColVal(dts.BOOL, ok, c.validity)


class EndsWith(_PatternPredicate):
    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        pat = _literal_bytes(self.pattern)
        lens = row_lengths(c)
        ok = lens >= len(pat)
        ccap = c.values.shape[0]
        base = c.offsets[1:] - len(pat)
        for i, b in enumerate(pat):
            idx = jnp.clip(base + i, 0, ccap - 1)
            ok = jnp.logical_and(ok, c.values[idx] == b)
        return ColVal(dts.BOOL, ok, c.validity)


def _match_starts(c: ColVal, pat: np.ndarray, capacity: int):
    """bool per byte position: pattern matches starting here, within row."""
    ccap = c.values.shape[0]
    pos = jnp.arange(ccap, dtype=jnp.int32)
    m = jnp.ones(ccap, dtype=jnp.bool_)
    for i, b in enumerate(pat):
        m = jnp.logical_and(
            m, c.values[jnp.clip(pos + i, 0, ccap - 1)] == b)
    row = byte_to_row(c, capacity)
    # match must fit inside the row
    fits = pos + len(pat) <= c.offsets[row + 1]
    return jnp.logical_and(m, fits), row


class Contains(_PatternPredicate):
    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        pat = _literal_bytes(self.pattern)
        if len(pat) == 0:
            shape = row_lengths(c).shape
            return ColVal(dts.BOOL, jnp.ones(shape, dtype=jnp.bool_),
                          c.validity)
        m, row = _match_starts(c, pat, ctx.capacity)
        hit = jax.ops.segment_max(m.astype(jnp.int32), row,
                                  num_segments=ctx.capacity) > 0
        # rows with no bytes at all never match non-empty patterns
        return ColVal(dts.BOOL, hit, c.validity)


class Like(_PatternPredicate):
    """SQL LIKE with arbitrary ``%`` wildcards (``_`` falls back via the
    planner).  Single-wildcard forms decompose into prefix/suffix/infix
    tests; multi-wildcard patterns like ``%special%requests%`` run a fully
    data-parallel ordered-infix match: per segment, find the earliest match
    position at-or-after the previous segment's end with a masked
    ``segment_min`` (the device analog of cudf's ``strings::like``)."""

    def __init__(self, child: Expression, pattern: str):
        super().__init__(child, pattern)
        self._plan = self._compile(pattern)

    @staticmethod
    def _compile(p: str):
        if "_" in p:
            return None
        parts = p.split("%")
        if "%" not in p:
            return ("exact", p)
        if set(p) == {"%"}:
            return ("any",)
        inner = [s for s in parts if s]
        if p.startswith("%") and p.endswith("%") and len(inner) == 1:
            return ("contains", inner[0])
        if p.endswith("%") and not p.startswith("%") and len(inner) == 1:
            return ("prefix", inner[0])
        if p.startswith("%") and not p.endswith("%") and len(inner) == 1:
            return ("suffix", inner[0])
        if not p.startswith("%") and not p.endswith("%") and \
                len(inner) == 2 and len(parts) == 2:
            return ("prefix_suffix", inner[0], inner[1])
        # general: ordered segments, optionally anchored at either end
        return ("general", not p.startswith("%"), not p.endswith("%"),
                tuple(inner))

    @property
    def supported(self) -> bool:
        return self._plan is not None

    def emit(self, ctx: EmitContext) -> ColVal:
        plan = self._plan
        if plan is None:
            raise NotImplementedError(f"LIKE pattern {self.pattern!r}")
        kind = plan[0]
        if kind == "any":
            c = self.child.emit(ctx)
            return ColVal(dts.BOOL,
                          jnp.ones(ctx.capacity, dtype=jnp.bool_),
                          c.validity)
        if kind == "exact":
            return EqualsLiteral(self.child, plan[1]).emit(ctx)
        if kind == "contains":
            return Contains(self.child, plan[1]).emit(ctx)
        if kind == "prefix":
            return StartsWith(self.child, plan[1]).emit(ctx)
        if kind == "suffix":
            return EndsWith(self.child, plan[1]).emit(ctx)
        if kind == "prefix_suffix":
            # both, non-overlapping
            c = self.child.emit(ctx)
            pre = StartsWith(self.child, plan[1]).emit(ctx)
            suf = EndsWith(self.child, plan[2]).emit(ctx)
            long_enough = row_lengths(c) >= (len(_literal_bytes(plan[1])) +
                                             len(_literal_bytes(plan[2])))
            return ColVal(dts.BOOL,
                          pre.values & suf.values & long_enough, c.validity)
        # general: ordered infix chain with optional anchors
        _, anchor_start, anchor_end, segments = plan
        c = self.child.emit(ctx)
        ccap = c.values.shape[0]
        starts = c.offsets[:-1]
        ends = c.offsets[1:]
        INF = jnp.int32(2**30)
        ok = jnp.ones(ctx.capacity, dtype=jnp.bool_)
        # cur[row] = earliest byte position the next segment may start at
        cur = starts.astype(jnp.int32)
        segs = list(segments)
        if anchor_start and segs:
            pre = StartsWith(self.child, segs[0]).emit(ctx)
            ok = jnp.logical_and(ok, pre.values)
            cur = cur + len(_literal_bytes(segs[0]))
            segs = segs[1:]
        last = None
        if anchor_end and segs:
            last = segs[-1]
            segs = segs[:-1]
        for seg in segs:
            pat = _literal_bytes(seg)
            m, row = _match_starts(c, pat, ctx.capacity)
            pos = jnp.arange(ccap, dtype=jnp.int32)
            eligible = jnp.logical_and(m, pos >= cur[row])
            first = jax.ops.segment_min(
                jnp.where(eligible, pos, INF), row,
                num_segments=ctx.capacity)
            ok = jnp.logical_and(ok, first < INF)
            cur = jnp.where(first < INF, first + len(pat), cur)
        if last is not None:
            pat = _literal_bytes(last)
            suf = EndsWith(self.child, last).emit(ctx)
            ok = jnp.logical_and(ok, suf.values)
            ok = jnp.logical_and(ok,
                                 ends.astype(jnp.int32) - len(pat) >= cur)
        return ColVal(dts.BOOL, ok, c.validity)


class EqualsLiteral(_PatternPredicate):
    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        pat = _literal_bytes(self.pattern)
        ok = row_lengths(c) == len(pat)
        ccap = c.values.shape[0]
        for i, b in enumerate(pat):
            idx = jnp.clip(c.offsets[:-1] + i, 0, ccap - 1)
            ok = jnp.logical_and(ok, c.values[idx] == b)
        return ColVal(dts.BOOL, ok, c.validity)


class StringLocate(Expression):
    """locate(substr, str[, start]) — 1-based char position, 0 if absent."""

    def __init__(self, substr: str, child: Expression, start: int = 1):
        self.children = (child,)
        self.substr = substr
        self.start = start

    @property
    def child(self):
        return self.children[0]

    def with_children(self, children):
        return StringLocate(self.substr, children[0], self.start)

    @property
    def dtype(self):
        return dts.INT32

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        pat = _literal_bytes(self.substr)
        if len(pat) == 0:
            return ColVal(dts.INT32,
                          jnp.full(ctx.capacity, self.start, jnp.int32),
                          c.validity)
        m, row = _match_starts(c, pat, ctx.capacity)
        ccap = c.values.shape[0]
        pos = jnp.arange(ccap, dtype=jnp.int32)
        # char index of each byte within its row
        is_start = (c.values & 0xC0) != 0x80
        cprefix = jnp.cumsum(is_start.astype(jnp.int32))
        char_in_row = cprefix - cprefix[jnp.clip(c.offsets[row], 0,
                                                 ccap - 1)] + \
            is_start[jnp.clip(c.offsets[row], 0, ccap - 1)].astype(jnp.int32)
        eligible = jnp.logical_and(m, char_in_row >= self.start)
        first = jax.ops.segment_min(
            jnp.where(eligible, char_in_row, jnp.int32(2**31 - 1)), row,
            num_segments=ctx.capacity)
        out = jnp.where(first == 2**31 - 1, 0, first)
        return ColVal(dts.INT32, out, c.validity)

    def cache_key(self):
        return ("StringLocate", self.substr, self.start,
                self.child.cache_key())


# ----------------------------------------------------------------- producers

class _StringProducer(Expression):
    """Base for expressions producing a string column: subclasses provide
    output lengths + a source-byte mapping."""

    @property
    def dtype(self):
        return dts.STRING


class Upper(UnaryExpression):
    @property
    def dtype(self):
        return dts.STRING

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        v = c.values
        out = jnp.where((v >= 97) & (v <= 122), v - 32, v)
        return ColVal(dts.STRING, out, c.validity, c.offsets)


class Lower(UnaryExpression):
    @property
    def dtype(self):
        return dts.STRING

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        v = c.values
        out = jnp.where((v >= 65) & (v <= 90), v + 32, v)
        return ColVal(dts.STRING, out, c.validity, c.offsets)


class InitCap(UnaryExpression):
    """Capitalize first letter of each space-separated word (ASCII)."""

    @property
    def dtype(self):
        return dts.STRING

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        v = c.values
        prev = jnp.roll(v, 1)
        row = byte_to_row(c, ctx.capacity)
        at_row_start = jnp.arange(v.shape[0], dtype=jnp.int32) == \
            c.offsets[row]
        word_start = jnp.logical_or(at_row_start, prev == 32)
        up = jnp.where((v >= 97) & (v <= 122) & word_start, v - 32, v)
        lo = jnp.where((v >= 65) & (v <= 90) & ~word_start, v + 32, up)
        out = jnp.where(word_start, up, lo)
        return ColVal(dts.STRING, out, c.validity, c.offsets)


class Substring(Expression):
    """substring(str, pos, len) — 1-based char position (Spark semantics:
    pos 0 behaves like 1, negative counts from the end)."""

    def __init__(self, child: Expression, pos: int, length: int = 2**31 - 1):
        self.children = (child,)
        self.pos = pos
        self.length = length

    @property
    def child(self):
        return self.children[0]

    def with_children(self, children):
        return Substring(children[0], self.pos, self.length)

    @property
    def dtype(self):
        return dts.STRING

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        nchars = char_lengths(c, ctx)
        pos = self.pos
        if pos >= 0:
            start_char = jnp.maximum(pos - 1, 0)
        else:
            start_char = jnp.maximum(nchars + pos, 0)
        end_char = jnp.minimum(
            start_char.astype(jnp.int64) + self.length,
            nchars.astype(jnp.int64)).astype(jnp.int32)
        start_char = jnp.minimum(start_char, nchars)
        # char index -> byte offset per row: global positions of char starts
        is_start = ((c.values & 0xC0) != 0x80).astype(jnp.int32)
        cprefix = jnp.concatenate(
            [jnp.zeros(1, dtype=jnp.int32), jnp.cumsum(is_start)])
        # for row r: byte pos of its k-th char = index of (cprefix[o_r]+k)-th
        # char start; find via searchsorted over cprefix (monotone)
        base_chars = cprefix[c.offsets[:-1]]
        start_byte = jnp.searchsorted(
            cprefix[1:], base_chars + start_char + 1, side="left"
        ).astype(jnp.int32)
        end_byte = jnp.searchsorted(
            cprefix[1:], base_chars + end_char + 1, side="left"
        ).astype(jnp.int32)
        start_byte = jnp.clip(start_byte, c.offsets[:-1], c.offsets[1:])
        end_byte = jnp.clip(end_byte, start_byte, c.offsets[1:])
        lengths = end_byte - start_byte
        chars, offsets = build_strings(
            lengths, lambda p, r, k: start_byte[r] + k, c.values,
            c.values.shape[0], ctx.capacity)
        return ColVal(dts.STRING, chars, c.validity, offsets)

    def cache_key(self):
        return ("Substring", self.pos, self.length, self.child.cache_key())


class _TrimBase(UnaryExpression):
    @property
    def dtype(self):
        return dts.STRING

    trim_left = True
    trim_right = True

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        ccap = c.values.shape[0]
        pos = jnp.arange(ccap, dtype=jnp.int32)
        row = byte_to_row(c, ctx.capacity)
        space = c.values == 32
        big = jnp.int32(2**31 - 1)
        if self.trim_left:
            first_ns = jax.ops.segment_min(
                jnp.where(~space, pos, big), row,
                num_segments=ctx.capacity)
            start = jnp.minimum(
                jnp.where(first_ns == big, c.offsets[1:], first_ns),
                c.offsets[1:])
            start = jnp.maximum(start, c.offsets[:-1])
        else:
            start = c.offsets[:-1]
        if self.trim_right:
            last_ns = jax.ops.segment_max(
                jnp.where(~space, pos, -1), row, num_segments=ctx.capacity)
            end = jnp.where(last_ns < c.offsets[:-1], start, last_ns + 1)
            end = jnp.clip(end, start, c.offsets[1:])
        else:
            end = c.offsets[1:]
        lengths = end - start
        chars, offsets = build_strings(
            lengths, lambda p, r, k: start[r] + k, c.values, ccap,
            ctx.capacity)
        return ColVal(dts.STRING, chars, c.validity, offsets)


class StringTrim(_TrimBase):
    pass


class StringTrimLeft(_TrimBase):
    trim_right = False


class StringTrimRight(_TrimBase):
    trim_left = False


class ConcatStrings(Expression):
    """concat(s1, s2, ...) — null if any input is null (Spark concat)."""

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    def with_children(self, children):
        return ConcatStrings(*children)

    @property
    def dtype(self):
        return dts.STRING

    def emit(self, ctx: EmitContext) -> ColVal:
        cols = [_as_string_col(c.emit(ctx), ctx) for c in self.children]
        lens = [row_lengths(c) for c in cols]
        total = lens[0]
        for l in lens[1:]:
            total = total + l
        # cumulative start of each part within the output row
        part_starts = [jnp.zeros_like(total)]
        for l in lens[:-1]:
            part_starts.append(part_starts[-1] + l)
        out_cap = _next_pow2(sum(int(c.values.shape[0]) for c in cols))

        def src(p, r, k):
            # select which part byte k falls into
            src_idx = jnp.zeros_like(p)
            for part, (c, ps, l) in enumerate(zip(cols, part_starts, lens)):
                inside = jnp.logical_and(k >= ps[r], k < ps[r] + l[r])
                byte = c.offsets[r] + (k - ps[r])
                # offset into the concatenated source pool
                src_idx = jnp.where(inside, byte + self._pool_base[part],
                                    src_idx)
            return src_idx

        self._pool_base = []
        base = 0
        pool_parts = []
        for c in cols:
            self._pool_base.append(base)
            base += int(c.values.shape[0])
            pool_parts.append(c.values)
        pool = jnp.concatenate(pool_parts)
        chars, offsets = build_strings(total, src, pool, out_cap,
                                       ctx.capacity)
        validity = combine_validity(*[c.validity for c in cols])
        return ColVal(dts.STRING, chars, validity, offsets)


class StringRepeat(Expression):
    def __init__(self, child: Expression, times: int):
        self.children = (child,)
        self.times = max(int(times), 0)

    @property
    def child(self):
        return self.children[0]

    def with_children(self, children):
        return StringRepeat(children[0], self.times)

    @property
    def dtype(self):
        return dts.STRING

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        lens = row_lengths(c)
        total = lens * self.times
        out_cap = _next_pow2(int(c.values.shape[0]) * max(self.times, 1))
        safe = jnp.maximum(lens, 1)

        def src(p, r, k):
            return c.offsets[r] + (k % safe[r])

        chars, offsets = build_strings(total, src, c.values, out_cap,
                                       ctx.capacity)
        return ColVal(dts.STRING, chars, c.validity, offsets)

    def cache_key(self):
        return ("StringRepeat", self.times, self.child.cache_key())


class _PadBase(Expression):
    def __init__(self, child: Expression, width: int, pad: str = " "):
        self.children = (child,)
        self.width = int(width)
        self.pad = pad or " "

    @property
    def child(self):
        return self.children[0]

    def with_children(self, children):
        return type(self)(children[0], self.width, self.pad)

    @property
    def dtype(self):
        return dts.STRING

    def cache_key(self):
        return (type(self).__name__, self.width, self.pad,
                self.child.cache_key())

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        lens = row_lengths(c)  # ASCII pad assumption: chars == bytes
        width = jnp.int32(self.width)
        pad_bytes = _literal_bytes(self.pad)
        pool = jnp.concatenate([c.values, jnp.asarray(pad_bytes)])
        pad_base = int(c.values.shape[0])
        out_cap = _next_pow2(self.width * ctx.capacity)
        npad = len(pad_bytes)
        left = isinstance(self, StringLPad)

        def src(p, r, k):
            pad_n = jnp.maximum(width - lens[r], 0)
            if left:
                in_pad = k < pad_n
                data_k = k - pad_n
                pad_k = k
            else:
                in_pad = k >= lens[r]
                data_k = k
                pad_k = k - lens[r]
            return jnp.where(in_pad,
                             pad_base + (jnp.clip(pad_k, 0, None) % npad),
                             c.offsets[r] + jnp.clip(data_k, 0, None))

        # Spark pads OR truncates to exactly `width`
        out_len = jnp.broadcast_to(width, lens.shape)
        chars, offsets = build_strings(out_len, src, pool, out_cap,
                                       ctx.capacity)
        return ColVal(dts.STRING, chars, c.validity, offsets)


class StringLPad(_PadBase):
    pass


class StringRPad(_PadBase):
    pass


class SubstringIndex(Expression):
    """substring_index(str, delim, count) for single-char delim."""

    def __init__(self, child: Expression, delim: str, count: int):
        self.children = (child,)
        self.delim = delim
        self.count = int(count)

    @property
    def child(self):
        return self.children[0]

    def with_children(self, children):
        return SubstringIndex(children[0], self.delim, self.count)

    @property
    def dtype(self):
        return dts.STRING

    def cache_key(self):
        return ("SubstringIndex", self.delim, self.count,
                self.child.cache_key())

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        d = _literal_bytes(self.delim)
        ccap = c.values.shape[0]
        pos = jnp.arange(ccap, dtype=jnp.int32)
        row = byte_to_row(c, ctx.capacity)
        m, _ = _match_starts(c, d, ctx.capacity)
        # delim occurrence index within row
        mcum = jnp.cumsum(m.astype(jnp.int32))
        base = mcum[jnp.clip(c.offsets[row], 0, ccap - 1)] - \
            m[jnp.clip(c.offsets[row], 0, ccap - 1)].astype(jnp.int32)
        occ = mcum - base  # count of delims at-or-before this byte, in row
        total_occ = jax.ops.segment_max(
            jnp.where(m, occ, 0), row, num_segments=ctx.capacity)
        big = jnp.int32(2**31 - 1)
        if self.count > 0:
            # bytes before the count-th delimiter
            nth = jax.ops.segment_min(
                jnp.where(m & (occ == self.count), pos, big), row,
                num_segments=ctx.capacity)
            end = jnp.where(total_occ >= self.count, nth, c.offsets[1:])
            end = jnp.minimum(end, c.offsets[1:])
            start = c.offsets[:-1]
        else:
            # occurrence index (from the left) of the split point, per byte
            want = total_occ[row] + self.count + 1
            nth = jax.ops.segment_min(
                jnp.where(m & (occ == want), pos, big), row,
                num_segments=ctx.capacity)
            start = jnp.where(total_occ >= -self.count,
                              jnp.minimum(nth + len(d), c.offsets[1:]),
                              c.offsets[:-1])
            end = c.offsets[1:]
        lengths = end - start
        chars, offsets = build_strings(
            lengths, lambda p, r, k: start[r] + k, c.values, ccap,
            ctx.capacity)
        return ColVal(dts.STRING, chars, c.validity, offsets)


def _as_string_col(c: ColVal, ctx: EmitContext) -> ColVal:
    if c.dtype.is_string:
        if c.offsets.shape[0] == 2 and ctx.capacity != 1:
            # scalar literal: broadcast to per-row
            length = c.offsets[1]
            offsets = jnp.arange(ctx.capacity + 1, dtype=jnp.int32) * 0
            # every row points at the same literal bytes
            lens = jnp.broadcast_to(length, (ctx.capacity,))
            offs = jnp.concatenate([jnp.zeros(1, dtype=jnp.int32),
                                    jnp.cumsum(lens, dtype=jnp.int32)])
            reps = int(ctx.capacity)
            chars = jnp.tile(c.values, reps)
            return ColVal(dts.STRING, chars, None, offs)
        return c
    raise TypeError(f"expected string, got {c.dtype}")


def _next_pow2(n: int) -> int:
    cap = 1024
    while cap < n:
        cap <<= 1
    return cap


def string_equal(l: ColVal, r: ColVal, ctx: EmitContext):
    """Per-row equality of two string ColVals (either may be a scalar
    literal: offsets of length 2).  Returns a bool values array."""
    l_scalar = l.offsets.shape[0] == 2 and ctx.capacity != 1
    r_scalar = r.offsets.shape[0] == 2 and ctx.capacity != 1
    if l_scalar and not r_scalar:
        return string_equal(r, l, ctx)
    if r_scalar:
        lens_l = row_lengths(l)
        rlen = r.offsets[1]
        ok = lens_l == rlen
        ccap = l.values.shape[0]
        rcap = int(r.values.shape[0])
        # compare byte-by-byte over the literal's (small) length
        for i in range(rcap):
            idx = jnp.clip(l.offsets[:-1] + i, 0, ccap - 1)
            ok = jnp.logical_and(
                ok, jnp.logical_or(i >= rlen, l.values[idx] == r.values[i]))
        return ok
    # column vs column
    lens_l = row_lengths(l)
    lens_r = row_lengths(r)
    same_len = lens_l == lens_r
    ccap = l.values.shape[0]
    pos = jnp.arange(ccap, dtype=jnp.int32)
    row = byte_to_row(l, ctx.capacity)
    k = pos - l.offsets[row]
    r_idx = jnp.clip(r.offsets[row] + k, 0, r.values.shape[0] - 1)
    byte_ok = l.values == r.values[r_idx]
    total = l.offsets[ctx.capacity]
    byte_bad = jnp.logical_and(jnp.logical_not(byte_ok), pos < total)
    any_bad = jax.ops.segment_max(byte_bad.astype(jnp.int32), row,
                                  num_segments=ctx.capacity) > 0
    return jnp.logical_and(same_len, jnp.logical_not(any_bad))


def _string_lex_compare(l: ColVal, r: ColVal, ctx: EmitContext):
    """(has_diff, l_byte_lt, len_lt, len_le): first-differing-byte verdict
    for per-row lexicographic comparison of two string ColVals.

    Single pass over l's char buffer (the byte->row map + segment_min find
    the first position where the rows differ); ties fall to length
    comparison.  UTF-8 byte-wise lex order == code-point order, so this is
    exact Spark string ordering.
    """
    l = _as_string_col(l, ctx)
    r = _as_string_col(r, ctx)
    # An empty-string literal (or all-empty column) has a zero-length char
    # buffer; every gather below would clip to bound -1 and crash.  Pad to
    # one byte — offsets are all zero so the byte is never semantically
    # read (the `within`/has_diff masks exclude it).
    if l.values.shape[0] == 0:
        l = ColVal(l.dtype, jnp.zeros(1, dtype=jnp.uint8), l.validity,
                   l.offsets)
    if r.values.shape[0] == 0:
        r = ColVal(r.dtype, jnp.zeros(1, dtype=jnp.uint8), r.validity,
                   r.offsets)
    cap = ctx.capacity
    len_l = row_lengths(l)
    len_r = row_lengths(r)
    minlen = jnp.minimum(len_l, len_r)
    ccap = l.values.shape[0]
    pos = jnp.arange(ccap, dtype=jnp.int32)
    row = byte_to_row(l, cap)
    k = pos - l.offsets[row]
    r_idx = jnp.clip(r.offsets[row] + k, 0, r.values.shape[0] - 1)
    within = jnp.logical_and(k < minlen[row], pos < l.offsets[cap])
    differ = jnp.logical_and(within, l.values != r.values[r_idx])
    big = jnp.int32(1 << 30)
    first_k = jax.ops.segment_min(jnp.where(differ, k, big), row,
                                  num_segments=cap)
    has_diff = first_k < big
    safe_k = jnp.where(has_diff, first_k, 0)
    rows = jnp.arange(cap, dtype=jnp.int32)
    lb = l.values[jnp.clip(l.offsets[rows] + safe_k, 0, ccap - 1)]
    rb = r.values[jnp.clip(r.offsets[rows] + safe_k, 0,
                           r.values.shape[0] - 1)]
    return has_diff, lb < rb, len_l < len_r, len_l <= len_r


def string_lt(l: ColVal, r: ColVal, ctx: EmitContext):
    has_diff, byte_lt, len_lt, _ = _string_lex_compare(l, r, ctx)
    return jnp.where(has_diff, byte_lt, len_lt)


def string_le(l: ColVal, r: ColVal, ctx: EmitContext):
    has_diff, byte_lt, _, len_le = _string_lex_compare(l, r, ctx)
    return jnp.where(has_diff, byte_lt, len_le)


def string_gt(l: ColVal, r: ColVal, ctx: EmitContext):
    return jnp.logical_not(string_le(l, r, ctx))


def string_ge(l: ColVal, r: ColVal, ctx: EmitContext):
    return jnp.logical_not(string_lt(l, r, ctx))


# -------------------------------------------------------------------- casts

def cast_string(c: ColVal, target: DataType, ctx: EmitContext) -> ColVal:
    if c.dtype.is_string and (target.is_integral or target.is_floating):
        return _parse_number(c, target, ctx)
    if c.dtype.is_string and target.is_date:
        return _parse_date(c, ctx)
    if c.dtype.is_string and target.is_timestamp:
        return _parse_timestamp(c, ctx)
    if c.dtype.is_string and target.is_boolean:
        return _parse_bool(c, ctx)
    if (c.dtype.is_integral or c.dtype.is_boolean) and target.is_string:
        return _format_int(c, ctx)
    if c.dtype.is_date and target.is_string:
        return _format_date(c, ctx)
    if c.dtype.is_timestamp and target.is_string:
        return _format_timestamp(c, ctx)
    raise NotImplementedError(
        f"cast {c.dtype} -> {target} not yet supported on TPU")


_MAX_NUM_BYTES = 24


def _row_window(c: ColVal, width: int, ctx: EmitContext):
    """[capacity, width] matrix of each row's first bytes (0 padded)."""
    ccap = c.values.shape[0]
    starts = c.offsets[:-1]
    lens = row_lengths(c)
    j = jnp.arange(width, dtype=jnp.int32)[None, :]
    idx = jnp.clip(starts[:, None] + j, 0, ccap - 1)
    window = c.values[idx]
    return jnp.where(j < lens[:, None], window, 0), lens


def _parse_number(c: ColVal, target: DataType, ctx: EmitContext) -> ColVal:
    win, lens = _row_window(c, _MAX_NUM_BYTES, ctx)
    j = jnp.arange(_MAX_NUM_BYTES, dtype=jnp.int32)[None, :]
    in_row = j < lens[:, None]
    neg = win[:, 0] == ord("-")
    plus = win[:, 0] == ord("+")
    signed = neg | plus
    digit = (win >= ord("0")) & (win <= ord("9"))
    dot = win == ord(".")
    start = signed.astype(jnp.int32)

    is_int_char = digit | ~in_row
    int_ok = jnp.all(is_int_char | (j < start[:, None]) |
                     (j >= lens[:, None]), axis=1)
    # integer value via Horner over the window
    val = jnp.zeros(win.shape[0], dtype=jnp.int64)
    frac = jnp.zeros(win.shape[0], dtype=jnp.float64)
    scale = jnp.zeros(win.shape[0], dtype=jnp.float64)
    seen_dot = jnp.zeros(win.shape[0], dtype=jnp.bool_)
    fdigits = jnp.zeros(win.shape[0], dtype=jnp.float64)
    has_digit = jnp.zeros(win.shape[0], dtype=jnp.bool_)
    ok = lens > 0
    for k in range(_MAX_NUM_BYTES):
        ch = win[:, k]
        active = (k >= start) & (k < lens)
        d = (ch - ord("0")).astype(jnp.int64)
        isd = digit[:, k]
        this_dot = dot[:, k] & ~seen_dot
        val = jnp.where(active & isd & ~seen_dot, val * 10 + d, val)
        has_digit = has_digit | (active & isd)
        fdigits = jnp.where(active & isd & seen_dot,
                            fdigits * 10 + d.astype(jnp.float64), fdigits)
        scale = jnp.where(active & isd & seen_dot, scale + 1, scale)
        seen_dot = seen_dot | (active & dot[:, k])
        bad = active & ~isd & ~this_dot
        ok = ok & ~bad
    ok = ok & (lens <= _MAX_NUM_BYTES) & (lens > start) & has_digit
    fval = val.astype(jnp.float64) + fdigits / jnp.power(10.0, scale)
    fval = jnp.where(neg, -fval, fval)
    ival = jnp.where(neg, -val, val)
    validity = combine_validity(c.validity, ok)
    if target.is_floating:
        return ColVal(target, fval.astype(target.storage), validity)
    int_valid = combine_validity(validity, ~seen_dot)
    return ColVal(target, ival.astype(target.storage), int_valid)


def _parse_date(c: ColVal, ctx: EmitContext) -> ColVal:
    """yyyy-MM-dd (the default Spark date cast format)."""
    from spark_rapids_tpu.ops.datetime_ops import _days_from_civil
    win, lens = _row_window(c, 10, ctx)
    digits = (win - ord("0")).astype(jnp.int32)

    def num(sl):
        out = jnp.zeros(win.shape[0], dtype=jnp.int32)
        for i in sl:
            out = out * 10 + digits[:, i]
        return out
    ok = (lens == 10) & (win[:, 4] == ord("-")) & (win[:, 7] == ord("-"))
    for i in (0, 1, 2, 3, 5, 6, 8, 9):
        ok = ok & (win[:, i] >= ord("0")) & (win[:, i] <= ord("9"))
    y = num((0, 1, 2, 3)).astype(jnp.int64)
    m_raw = num((5, 6)).astype(jnp.int64)
    d_raw = num((8, 9)).astype(jnp.int64)
    m = jnp.clip(m_raw, 1, 12)
    month_days = _days_from_civil(
        jnp.where(m == 12, y + 1, y), jnp.where(m == 12, 1, m + 1),
        jnp.ones_like(m)) - _days_from_civil(y, m, jnp.ones_like(m))
    ok = ok & (m_raw >= 1) & (m_raw <= 12) & (d_raw >= 1) & \
        (d_raw <= month_days)
    days = _days_from_civil(y, m, jnp.clip(d_raw, 1, 31)).astype(jnp.int32)
    return ColVal(dts.DATE32, days, combine_validity(c.validity, ok))


def _parse_timestamp(c: ColVal, ctx: EmitContext) -> ColVal:
    """'yyyy-MM-dd[ HH:mm:ss[.SSSSSS]]' -> micros since epoch UTC (the
    default-format quadrant of GpuCast.scala's string->timestamp rules;
    zone suffixes are not accepted — the engine is UTC-only)."""
    from spark_rapids_tpu.ops.datetime_ops import _days_from_civil
    width = 26
    win, lens = _row_window(c, width, ctx)
    digits = (win - ord("0")).astype(jnp.int64)
    isd = (win >= ord("0")) & (win <= ord("9"))

    def num(sl):
        out = jnp.zeros(win.shape[0], dtype=jnp.int64)
        for i in sl:
            out = out * 10 + digits[:, i]
        return out

    date_ok = (lens >= 10) & (win[:, 4] == ord("-")) & \
        (win[:, 7] == ord("-"))
    for i in (0, 1, 2, 3, 5, 6, 8, 9):
        date_ok = date_ok & isd[:, i]
    y, m, d = num((0, 1, 2, 3)), num((5, 6)), num((8, 9))
    mc = jnp.clip(m, 1, 12)
    # real month length: civil-day difference to the next month
    month_days = _days_from_civil(
        jnp.where(mc == 12, y + 1, y), jnp.where(mc == 12, 1, mc + 1),
        jnp.ones_like(mc)) - _days_from_civil(y, mc, jnp.ones_like(mc))
    date_ok = date_ok & (m >= 1) & (m <= 12) & (d >= 1) & (d <= month_days)
    days = _days_from_civil(y, mc, jnp.clip(d, 1, 31))

    has_time = lens >= 19
    time_ok = (win[:, 10] == ord(" ")) | (win[:, 10] == ord("T"))
    time_ok = time_ok & (win[:, 13] == ord(":")) & (win[:, 16] == ord(":"))
    for i in (11, 12, 14, 15, 17, 18):
        time_ok = time_ok & isd[:, i]
    hh, mi, ss = num((11, 12)), num((14, 15)), num((17, 18))
    secs = jnp.clip(hh, 0, 23) * 3600 + jnp.clip(mi, 0, 59) * 60 + \
        jnp.clip(ss, 0, 59)
    time_ok = time_ok & (hh <= 23) & (mi <= 59) & (ss <= 59)

    # optional .fraction (1-6 digits)
    has_frac = lens >= 21
    frac_ok = win[:, 19] == ord(".")
    frac = jnp.zeros(win.shape[0], dtype=jnp.int64)
    fdig = jnp.zeros(win.shape[0], dtype=jnp.int64)
    for i in range(20, 26):
        in_frac = (i < lens) & isd[:, i]
        frac = jnp.where(in_frac, frac * 10 + digits[:, i], frac)
        fdig = fdig + in_frac.astype(jnp.int64)
        frac_ok = frac_ok & ((i >= lens) | isd[:, i])
    # frac has fdig digits; scale to micros: frac * 10^(6-fdig)
    micros_frac = frac * (10 ** 6) // jnp.asarray(
        [1, 10, 100, 1000, 10 ** 4, 10 ** 5, 10 ** 6],
        dtype=jnp.int64)[jnp.clip(fdig, 0, 6)]

    ok = date_ok & (
        (lens == 10) |
        ((lens == 19) & time_ok) |
        ((lens >= 21) & (lens <= 26) & time_ok & frac_ok))
    micros = days * 86_400_000_000 + \
        jnp.where(has_time, secs * 1_000_000, 0) + \
        jnp.where(has_frac, micros_frac, 0)
    return ColVal(dts.TIMESTAMP_US, micros,
                  combine_validity(c.validity, ok))


_BOOL_TRUE = ("true", "t", "yes", "y", "1")
_BOOL_FALSE = ("false", "f", "no", "n", "0")


def _parse_bool(c: ColVal, ctx: EmitContext) -> ColVal:
    """Spark string->boolean: true/t/yes/y/1 and false/f/no/n/0
    (case-insensitive, whitespace-trimmed like UTF8String.trim);
    anything else is null."""
    width = 16
    win, lens = _row_window(c, width, ctx)
    ws = win <= 0x20
    in_row = jnp.arange(width, dtype=jnp.int32)[None, :] < lens[:, None]
    # leading whitespace count + trimmed length
    lead = jnp.zeros(win.shape[0], dtype=jnp.int32)
    still = jnp.ones(win.shape[0], dtype=jnp.bool_)
    for i in range(width):
        hit = still & ws[:, i] & in_row[:, i]
        lead = lead + hit.astype(jnp.int32)
        still = hit
    trail = jnp.zeros(win.shape[0], dtype=jnp.int32)
    for i in range(width):
        j = jnp.clip(lens - 1 - i, 0, width - 1)
        hit = (trail == i) & (win[jnp.arange(win.shape[0]), j] <= 0x20) & \
            (lens - i > lead)
        trail = trail + hit.astype(jnp.int32)
    tlen = jnp.maximum(lens - lead - trail, 0)
    rows = jnp.arange(win.shape[0])
    lower = jnp.where((win >= ord("A")) & (win <= ord("Z")), win + 32, win)

    def matches(word: str):
        ok = (tlen == len(word)) & (lens <= width)
        for i, ch in enumerate(word):
            ok = ok & (lower[rows, jnp.clip(lead + i, 0, width - 1)] ==
                       ord(ch))
        return ok

    is_true = jnp.zeros(win.shape[0], dtype=jnp.bool_)
    for w in _BOOL_TRUE:
        is_true = is_true | matches(w)
    is_false = jnp.zeros(win.shape[0], dtype=jnp.bool_)
    for w in _BOOL_FALSE:
        is_false = is_false | matches(w)
    ok = is_true | is_false
    return ColVal(dts.BOOL, is_true, combine_validity(c.validity, ok))


def _format_timestamp(c: ColVal, ctx: EmitContext) -> ColVal:
    """micros -> 'yyyy-MM-dd HH:mm:ss[.ffffff]' with trailing fraction
    zeros trimmed (Spark's cast timestamp->string)."""
    from spark_rapids_tpu.ops.datetime_ops import _civil_from_days
    v = c.values.astype(jnp.int64)
    days = jnp.floor_divide(v, 86_400_000_000)
    in_day = v - days * 86_400_000_000
    secs = in_day // 1_000_000
    micros = in_day - secs * 1_000_000
    y, m, d = _civil_from_days(days)
    hh = secs // 3600
    mi = (secs // 60) % 60
    ss = secs % 60

    # fraction length: 0 (none) or 1-6 digits with trailing zeros cut
    fdig = jnp.zeros(v.shape[0], dtype=jnp.int32)
    for k in range(6, 0, -1):
        # number of digits needed so micros % 10^(6-k) == 0
        fdig = jnp.where((micros % (10 ** (6 - k + 1))) != 0,
                         jnp.maximum(fdig, k), fdig)
    lens = jnp.where(micros > 0, 20 + fdig, 19).astype(jnp.int32)

    def digit_at(p, r, k):
        # returns the BYTE for output position k of row r
        yy = y[r]
        out = jnp.zeros_like(p)

        def dig(val, power):
            return (val // power) % 10 + ord("0")

        out = jnp.where(k == 0, dig(yy, 1000), out)
        out = jnp.where(k == 1, dig(yy, 100), out)
        out = jnp.where(k == 2, dig(yy, 10), out)
        out = jnp.where(k == 3, dig(yy, 1), out)
        out = jnp.where(k == 4, ord("-"), out)
        out = jnp.where(k == 5, dig(m[r], 10), out)
        out = jnp.where(k == 6, dig(m[r], 1), out)
        out = jnp.where(k == 7, ord("-"), out)
        out = jnp.where(k == 8, dig(d[r], 10), out)
        out = jnp.where(k == 9, dig(d[r], 1), out)
        out = jnp.where(k == 10, ord(" "), out)
        out = jnp.where(k == 11, dig(hh[r], 10), out)
        out = jnp.where(k == 12, dig(hh[r], 1), out)
        out = jnp.where(k == 13, ord(":"), out)
        out = jnp.where(k == 14, dig(mi[r], 10), out)
        out = jnp.where(k == 15, dig(mi[r], 1), out)
        out = jnp.where(k == 16, ord(":"), out)
        out = jnp.where(k == 17, dig(ss[r], 10), out)
        out = jnp.where(k == 18, dig(ss[r], 1), out)
        out = jnp.where(k == 19, ord("."), out)
        frac_pos = k - 20  # 0-based fraction digit index
        fr = micros[r]
        for i in range(6):
            out = jnp.where(frac_pos == i,
                            dig(fr, 10 ** (5 - i)), out)
        return out

    # build via a byte pool trick: we need computed bytes, not copied
    # bytes, so build offsets/chars directly
    offsets = jnp.concatenate([jnp.zeros(1, dtype=jnp.int32),
                               jnp.cumsum(lens, dtype=jnp.int32)])
    out_cap = _next_pow2(26 * ctx.capacity)
    pos = jnp.arange(out_cap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(offsets, pos, side="right") - 1,
                   0, ctx.capacity - 1)
    k = pos - offsets[row]
    total = offsets[ctx.capacity]
    chars = jnp.where(pos < total, digit_at(pos, row, k),
                      0).astype(jnp.uint8)
    return ColVal(dts.STRING, chars, c.validity, offsets)


def _format_int(c: ColVal, ctx: EmitContext) -> ColVal:
    v = c.values.astype(jnp.int64)
    if c.dtype.is_boolean:
        # 'true'/'false'
        lens = jnp.where(c.values, 4, 5).astype(jnp.int32)
        pool = jnp.asarray(_literal_bytes("truefalse"))

        def src(p, r, k):
            return jnp.where(c.values[r], k, 4 + k)
        chars, offsets = build_strings(lens, src, pool,
                                       _next_pow2(5 * ctx.capacity),
                                       ctx.capacity)
        return ColVal(dts.STRING, chars, c.validity, offsets)
    neg = v < 0
    mag = jnp.where(neg, -v, v).astype(jnp.uint64)
    # digit count
    ndig = jnp.ones(v.shape[0], dtype=jnp.int32)
    p = jnp.full(v.shape[0], 10, dtype=jnp.uint64)
    for _ in range(19):
        ndig = jnp.where(mag >= p, ndig + 1, ndig)
        p = p * 10
    lens = ndig + neg.astype(jnp.int32)
    # digit matrix [cap, 20]: digit at output position k
    digmat = jnp.zeros((v.shape[0], 21), dtype=jnp.uint8)
    mags = mag
    # compute digits right-to-left into a [cap,20] then index by position
    digs = []
    for _ in range(20):
        digs.append((mags % 10).astype(jnp.uint8))
        mags = mags // 10
    digs = jnp.stack(digs, axis=1)  # [cap, 20] least-significant first

    pool_minus = ord("-")

    def src(pz, r, k):
        # k-th output byte of row r
        is_minus = neg[r] & (k == 0)
        pos_in_num = k - neg[r].astype(jnp.int32)
        digit_idx = ndig[r] - 1 - pos_in_num
        dval = digs[r, jnp.clip(digit_idx, 0, 19)]
        return jnp.where(is_minus, 10, dval).astype(jnp.int32)

    # src returns an index into pool '0123456789-'
    pool = jnp.asarray(_literal_bytes("0123456789-"))
    chars, offsets = build_strings(lens, src, pool,
                                   _next_pow2(21 * ctx.capacity),
                                   ctx.capacity)
    return ColVal(dts.STRING, chars, c.validity, offsets)


def _format_date(c: ColVal, ctx: EmitContext) -> ColVal:
    from spark_rapids_tpu.ops.datetime_ops import _civil_from_days
    y, m, d = _civil_from_days(c.values)
    digits = jnp.stack([
        (y // 1000) % 10, (y // 100) % 10, (y // 10) % 10, y % 10,
        jnp.full_like(y, 10),
        (m // 10) % 10, m % 10,
        jnp.full_like(y, 10),
        (d // 10) % 10, d % 10,
    ], axis=1).astype(jnp.int32)  # [cap, 10]; 10 = '-'
    lens = jnp.full(c.values.shape[0], 10, dtype=jnp.int32)
    pool = jnp.asarray(_literal_bytes("0123456789-"))

    def src(p, r, k):
        return digits[r, jnp.clip(k, 0, 9)]

    chars, offsets = build_strings(lens, src, pool,
                                   _next_pow2(10 * ctx.capacity),
                                   ctx.capacity)
    return ColVal(dts.STRING, chars, c.validity, offsets)


class Ascii(UnaryExpression):
    """Code point of the first character (Spark ascii(); full UTF-8
    decode of the leading character, 0 for the empty string —
    stringFunctions.scala GpuAscii role)."""

    @property
    def dtype(self):
        return dts.INT32

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        cap = ctx.capacity
        nbytes = int(c.values.shape[0])
        starts = c.offsets[:cap]
        lens = c.offsets[1:cap + 1] - starts
        if nbytes == 0:
            return ColVal(dts.INT32,
                          jnp.zeros(cap, dtype=jnp.int32), c.validity)

        def byte(k):
            return c.values[jnp.clip(starts + k, 0, nbytes - 1)] \
                .astype(jnp.int32)

        b0 = byte(0)
        cp = jnp.where(
            b0 < 0x80, b0,
            jnp.where(
                b0 < 0xE0,
                ((b0 & 0x1F) << 6) | (byte(1) & 0x3F),
                jnp.where(
                    b0 < 0xF0,
                    ((b0 & 0x0F) << 12) | ((byte(1) & 0x3F) << 6)
                    | (byte(2) & 0x3F),
                    ((b0 & 0x07) << 18) | ((byte(1) & 0x3F) << 12)
                    | ((byte(2) & 0x3F) << 6) | (byte(3) & 0x3F))))
        cp = jnp.where(lens > 0, cp, 0)
        return ColVal(dts.INT32, cp, c.validity)


class Chr(UnaryExpression):
    """Character for a code point modulo 256 (Spark chr(): negative
    input yields the empty string; 128-255 encode as 2-byte UTF-8)."""

    @property
    def dtype(self):
        return dts.STRING

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        cap = ctx.capacity
        n = c.values.astype(jnp.int64)
        b = jnp.mod(n, 256).astype(jnp.int32)
        lens = jnp.where(n < 0, 0, jnp.where(b < 128, 1, 2))
        lens = jnp.where(ctx.row_mask(), lens, 0).astype(jnp.int32)
        first = jnp.where(b < 128, b, 0xC0 | (b >> 6)).astype(jnp.uint8)
        second = (0x80 | (b & 0x3F)).astype(jnp.uint8)
        pool = jnp.stack([first, second], axis=1).reshape(-1)
        chars, offsets = build_strings(
            lens, lambda pos, row, k: row * 2 + k, pool,
            _next_pow2(2 * cap), cap)
        return ColVal(dts.STRING, chars, c.validity, offsets)
