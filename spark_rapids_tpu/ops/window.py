"""Window function kernels over sorted segments.

Counterpart of ``GpuWindowExec.scala`` + ``GpuWindowExpression.scala`` (2,797
LoC driving cudf rolling/scan ops).  TPU formulation: one sort by (partition
keys, order keys), then every window function is segment arithmetic on the
sorted arrays —

* ranking (row_number / rank / dense_rank) from positions and order-key run
  boundaries;
* running and sliding ROWS-frame sums/counts/averages from masked prefix
  sums differenced at clamped frame edges;
* running min/max from a segmented associative scan;
* whole-partition aggregates from segment reductions gathered back;
* lead/lag from shifted gathers with segment-bound nulling.

Like Spark's WindowExec, output rows are emitted in (partition, order)
sorted order.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.ops.aggregates import _sentinel
from spark_rapids_tpu.ops.expressions import ColVal


class SortedPartitions:
    """Per-trace context: sorted segment structure shared by all window fns.

    ``seg_id``    int32[cap]  partition id per sorted row (trash for dead)
    ``seg_start`` int32[cap]  sorted position of this row's partition start
    ``seg_end``   int32[cap]  inclusive end position of this row's partition
    ``pos``       int32[cap]
    ``live``      bool[cap]
    ``run_start`` int32[cap]  start position of this row's order-key run
    ``run_end``   int32[cap]  inclusive end of the order-key run
    ``run_id_in_seg`` int32[cap] dense run index within the partition
    """

    def __init__(self, seg_boundary, run_boundary, live, capacity: int):
        pos = jnp.arange(capacity, dtype=jnp.int32)
        self.pos = pos
        self.live = live
        seg_id = jnp.cumsum(seg_boundary.astype(jnp.int32)) - 1
        self.seg_id = jnp.where(live, seg_id, capacity)
        self.seg_start = jnp.where(seg_boundary, pos, 0)
        self.seg_start = jax.lax.associative_scan(jnp.maximum,
                                                  self.seg_start)
        # inclusive segment end: scan max from the right; a segment ends
        # before the next boundary OR at the last live row
        nxt_boundary = jnp.concatenate(
            [seg_boundary[1:], jnp.ones(1, dtype=jnp.bool_)])
        last_live = jnp.logical_and(live, jnp.logical_not(jnp.concatenate(
            [live[1:], jnp.zeros(1, dtype=jnp.bool_)])))
        big = jnp.int32(2**31 - 1)
        end_marker = jnp.where(jnp.logical_or(nxt_boundary, last_live),
                               pos, big)
        # nearest end at-or-after each row: reverse min-scan
        self.seg_end = jax.lax.associative_scan(
            jnp.minimum, end_marker, reverse=True)
        # order-key runs (ties)
        rb = jnp.logical_or(run_boundary, seg_boundary)
        self.run_start = jax.lax.associative_scan(
            jnp.maximum, jnp.where(rb, pos, 0))
        run_next = jnp.logical_or(
            jnp.concatenate([rb[1:], jnp.ones(1, dtype=jnp.bool_)]),
            last_live)
        self.run_end = jax.lax.associative_scan(
            jnp.minimum, jnp.where(run_next, pos, big), reverse=True)
        run_counter = jnp.cumsum(rb.astype(jnp.int32)) - 1
        run_at_seg_start = run_counter[self.seg_start]
        self.run_id_in_seg = run_counter - run_at_seg_start


def row_number(sp: SortedPartitions) -> ColVal:
    from spark_rapids_tpu.columnar import dtypes as dts
    return ColVal(dts.INT32, sp.pos - sp.seg_start + 1)


def rank(sp: SortedPartitions) -> ColVal:
    from spark_rapids_tpu.columnar import dtypes as dts
    return ColVal(dts.INT32, sp.run_start - sp.seg_start + 1)


def dense_rank(sp: SortedPartitions) -> ColVal:
    from spark_rapids_tpu.columnar import dtypes as dts
    return ColVal(dts.INT32, sp.run_id_in_seg + 1)


def percent_rank(sp: SortedPartitions) -> ColVal:
    from spark_rapids_tpu.columnar import dtypes as dts
    n = (sp.seg_end - sp.seg_start).astype(jnp.float64)
    r = (sp.run_start - sp.seg_start).astype(jnp.float64)
    return ColVal(dts.FLOAT64, jnp.where(n > 0, r / jnp.maximum(n, 1), 0.0))


def lead_lag(sp: SortedPartitions, c: ColVal, offset: int,
             default: Optional[ColVal] = None) -> ColVal:
    """lead(+offset) / lag(-offset) within the partition."""
    capacity = sp.pos.shape[0]
    tgt = sp.pos + offset
    in_seg = jnp.logical_and(tgt >= sp.seg_start, tgt <= sp.seg_end)
    safe = jnp.clip(tgt, 0, capacity - 1)
    values = c.values[safe]
    validity = c.validity[safe] if c.validity is not None else None
    if default is not None:
        dvals = jnp.broadcast_to(default.values.astype(values.dtype),
                                 values.shape)
        values = jnp.where(in_seg, values, dvals)
        if default.validity is not None or validity is not None:
            dv = jnp.broadcast_to(
                default.validity if default.validity is not None else True,
                (capacity,))
            sv = validity if validity is not None else \
                jnp.ones(capacity, dtype=jnp.bool_)
            validity = jnp.where(in_seg, sv, dv)
    else:
        base = validity if validity is not None else \
            jnp.ones(capacity, dtype=jnp.bool_)
        validity = jnp.logical_and(base, in_seg)
    return ColVal(c.dtype, values, validity)


# ------------------------------------------------------------- frame helpers

UNBOUNDED = None


def _frame_edges(sp: SortedPartitions, lo, hi, rows: bool):
    """Inclusive [lo_idx, hi_idx] per sorted row for a ROWS frame, or the
    running-with-ties RANGE frame when rows=False (lo=None, hi=0)."""
    if rows:
        lo_idx = sp.seg_start if lo is UNBOUNDED else \
            jnp.maximum(sp.seg_start, sp.pos + lo)
        hi_idx = sp.seg_end if hi is UNBOUNDED else \
            jnp.minimum(sp.seg_end, sp.pos + hi)
    else:
        # RANGE unbounded preceding -> current row (ties included)
        lo_idx = sp.seg_start if lo is UNBOUNDED else sp.run_start
        hi_idx = sp.seg_end if hi is UNBOUNDED else sp.run_end
    return lo_idx, hi_idx


def frame_sum(sp: SortedPartitions, c: ColVal, lo, hi, rows: bool,
              count: bool = False) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(sum, nonnull_count) of c over each row's frame via prefix sums."""
    capacity = sp.pos.shape[0]
    valid = sp.live if c.validity is None else \
        jnp.logical_and(sp.live, c.validity)
    vals = jnp.where(valid, c.values, jnp.zeros((), dtype=c.values.dtype))
    prefix = jnp.cumsum(vals)
    cprefix = jnp.cumsum(valid.astype(jnp.int64))
    lo_idx, hi_idx = _frame_edges(sp, lo, hi, rows)
    empty = lo_idx > hi_idx
    lo_safe = jnp.clip(lo_idx, 0, capacity - 1)
    hi_safe = jnp.clip(hi_idx, 0, capacity - 1)
    def window(p):
        below = jnp.where(lo_safe > 0, p[jnp.maximum(lo_safe - 1, 0)], 0)
        return jnp.where(empty, 0, p[hi_safe] - below)
    return window(prefix), window(cprefix)


def _segmented_scan(op, vals, boundary, reverse=False):
    """Segmented associative scan: restart ``op`` at boundaries."""
    flags = boundary
    if reverse:
        vals = vals[::-1]
        flags = jnp.concatenate(
            [boundary[1:], jnp.ones(1, dtype=jnp.bool_)])[::-1]

    def combine(a, b):
        af, av = a
        bf, bv = b
        return jnp.logical_or(af, bf), jnp.where(bf, bv, op(av, bv))

    _, out = jax.lax.associative_scan(combine, (flags, vals))
    return out[::-1] if reverse else out


def running_minmax(sp: SortedPartitions, c: ColVal, kind: str,
                   seg_boundary) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(value, nonnull_count) for min/max over unbounded-preceding frames."""
    valid = sp.live if c.validity is None else \
        jnp.logical_and(sp.live, c.validity)
    sent = _sentinel(kind, c.values.dtype)
    vals = jnp.where(valid, c.values, sent)
    op = jnp.minimum if kind == "min" else jnp.maximum
    out = _segmented_scan(op, vals, seg_boundary)
    counts = _segmented_scan(jnp.add, valid.astype(jnp.int64), seg_boundary)
    # extend over ties (range frame): take the run-end value
    return out, counts


def partition_reduce(sp: SortedPartitions, c: ColVal, kind: str,
                     capacity: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(value, count) of whole-partition aggregate, broadcast to rows."""
    valid = sp.live if c.validity is None else \
        jnp.logical_and(sp.live, c.validity)
    seg = sp.seg_id
    counts = jax.ops.segment_sum(valid.astype(jnp.int64), seg,
                                 num_segments=capacity + 1)[:capacity]
    if kind == "sum":
        vals = jnp.where(valid, c.values, jnp.zeros((), c.values.dtype))
        red = jax.ops.segment_sum(vals, seg,
                                  num_segments=capacity + 1)[:capacity]
    elif kind == "min":
        vals = jnp.where(valid, c.values, _sentinel("min", c.values.dtype))
        red = jax.ops.segment_min(vals, seg,
                                  num_segments=capacity + 1)[:capacity]
    else:
        vals = jnp.where(valid, c.values, _sentinel("max", c.values.dtype))
        red = jax.ops.segment_max(vals, seg,
                                  num_segments=capacity + 1)[:capacity]
    safe_seg = jnp.clip(seg, 0, capacity - 1)
    return red[safe_seg], counts[safe_seg]
