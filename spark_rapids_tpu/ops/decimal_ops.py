"""Decimal arithmetic semantics (DECIMAL_64).

The reference's decimal surface is the rule set around
``GpuOverrides.scala:777-2826`` — ``PromotePrecision`` / ``CheckOverflow``
wrappers Catalyst inserts around decimal arithmetic, plus
``MakeDecimal`` / ``UnscaledValue`` used by partial aggregation — with
storage capped at DECIMAL_64 (TypeChecks.scala DECIMAL_64 notes).  The
TPU build stores decimals as unscaled int64 and implements the same
Spark result-type rules (``allowPrecisionLoss`` defaults, capped at
precision 18); anything wider tags off the device and runs on the CPU
fallback, exactly like the reference falls back past DECIMAL_64.

Rounding is HALF_UP (away from zero) wherever Spark rounds.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.dtypes import DataType, DecimalType
from spark_rapids_tpu.ops.expressions import (
    ColVal, EmitContext, Expression, UnaryExpression, combine_validity,
)

MAX_PRECISION = 18  # DECIMAL_64


def _dec_params(dt: DataType):
    """(precision, scale) an operand contributes to decimal result-type
    inference (Spark's DecimalPrecision integral conversions), or None
    when it cannot participate.  bigint's (20, 0) exceeds DECIMAL_64 as
    a stored type but is fine as an INFERENCE input — the adjusted
    result caps at 18 with overflow -> null."""
    if dt.is_decimal:
        return dt.precision, dt.scale
    return {"tinyint": (3, 0), "smallint": (5, 0), "int": (10, 0),
            "bigint": (20, 0)}.get(dt.name)


def as_decimal_type(dt: DataType) -> Optional[DataType]:
    """The DECIMAL_64 type an operand implicitly converts to, or None."""
    if dt.is_decimal:
        return dt
    ps = _dec_params(dt)
    if ps is None or ps[0] > MAX_PRECISION:
        return DecimalType(MAX_PRECISION, 0) if ps is not None else None
    return DecimalType(*ps)


def adjust_precision_scale(p: int, s: int) -> DataType:
    """Spark's DecimalPrecision.adjustPrecisionScale with the cap at
    DECIMAL_64's 18 instead of 38: keep integral digits, surrender
    fractional digits down to min(scale, 6).  Results that still don't
    fit produce overflow -> null at runtime (CheckOverflow)."""
    if p <= MAX_PRECISION:
        return DecimalType(p, s)
    int_digits = p - s
    min_scale = min(s, 6)
    adj_scale = max(MAX_PRECISION - int_digits, min_scale)
    return DecimalType(MAX_PRECISION, adj_scale)


def binary_result(op: str, a: DataType, b: DataType) -> DataType:
    """Spark's decimal result-type rules (+,-,*,/ and comparison
    promotion), precision-adjusted to the DECIMAL_64 cap."""
    pa_, pb = _dec_params(a), _dec_params(b)
    if pa_ is None or pb is None:
        raise TypeError(f"cannot run decimal {op} over {a} and {b} "
                        "on DECIMAL_64")
    p1, s1 = pa_
    p2, s2 = pb
    if op in ("add", "sub"):
        s = max(s1, s2)
        p = max(p1 - s1, p2 - s2) + s + 1
    elif op == "mul":
        p, s = p1 + p2 + 1, s1 + s2
    elif op == "div":
        s = max(6, s1 + p2 + 1)
        p = p1 - s1 + s2 + s
    elif op == "cmp":
        s = max(s1, s2)
        p = max(p1 - s1, p2 - s2) + s
    else:
        raise ValueError(op)
    return adjust_precision_scale(p, s)


def overflow_validity(values, precision: int):
    """False where |unscaled| has more than ``precision`` digits (the
    CheckOverflow role: overflow -> null in non-ANSI mode)."""
    bound = 10 ** precision
    return jnp.logical_and(values > -bound, values < bound)


def rescale(values, from_scale: int, to_scale: int):
    """Unscaled-value rescale; scale-down rounds HALF_UP."""
    if to_scale >= from_scale:
        return values * (10 ** (to_scale - from_scale))
    f = 10 ** (from_scale - to_scale)
    q = _trunc_div(values, jnp.int64(f))
    rem = values - q * f
    up = jnp.abs(rem) * 2 >= f
    return jnp.where(up, q + jnp.sign(values), q)


def _trunc_div(num, den):
    """Integer division truncating toward zero (Java semantics)."""
    q = num // den
    rem = num - q * den
    return jnp.where((rem != 0) & ((num < 0) != (den < 0)), q + 1, q)


_I64_MAX = (1 << 63) - 1


def _scale_up_guarded(v, factor: int):
    """(v * factor, ok): int64 scale-up with overflow -> invalid (the
    value existed in Spark's 128-bit world; here it nulls out)."""
    if factor == 1:
        return v, None
    lim = _I64_MAX // factor
    ok = jnp.logical_and(v >= -lim, v <= lim)
    return v * factor, ok


def to_unscaled(c: ColVal, dt: DataType, out: DataType):
    """Operand -> (unscaled int64 at ``out.scale``, overflow-ok mask)
    — the PromotePrecision role."""
    v = c.values
    if dt.is_decimal:
        if out.scale >= dt.scale:
            return _scale_up_guarded(v.astype(jnp.int64),
                                     10 ** (out.scale - dt.scale))
        return rescale(v.astype(jnp.int64), dt.scale, out.scale), None
    return _scale_up_guarded(v.astype(jnp.int64), 10 ** out.scale)


def emit_binary(op: str, left: ColVal, right: ColVal, out: DataType
                ) -> ColVal:
    """Device decimal +,-,*,/ over unscaled int64 with inline overflow
    check (PromotePrecision + op + CheckOverflow fused).  int64
    intermediate overflow nulls out like a CheckOverflow would."""
    ldt, rdt = left.dtype, right.dtype
    extra = []
    if op in ("add", "sub"):
        l, ok1 = to_unscaled(left, ldt, out)
        r, ok2 = to_unscaled(right, rdt, out)
        vals = l + r if op == "add" else l - r
        extra += [ok1, ok2]
    elif op == "mul":
        # scales add: raw unscaled product (an integral operand is its
        # own scale-0 unscaled value), guarded against int64 overflow
        l = left.values.astype(jnp.int64)
        r = right.values.astype(jnp.int64)
        lim = _I64_MAX // jnp.maximum(jnp.abs(l), 1)
        extra.append(jnp.logical_or(l == 0, jnp.abs(r) <= lim))
        vals = l * r
        ds = (ldt.scale if ldt.is_decimal else 0) + \
            (rdt.scale if rdt.is_decimal else 0)
        if ds != out.scale:  # precision-adjusted result: round down
            vals = rescale(vals, ds, out.scale)
    elif op == "div":
        da, db = as_decimal_type(ldt), as_decimal_type(rdt)
        l = left.values.astype(jnp.int64)
        r = right.values.astype(jnp.int64)
        # numerator scaled so the quotient lands at out.scale
        shift = out.scale + db.scale - da.scale
        if shift >= 0:
            num, ok = _scale_up_guarded(l, 10 ** shift)
        else:
            num, ok = rescale(l, -shift, 0), None
        extra.append(ok)
        zero = r == 0
        den = jnp.where(zero, 1, r)
        q = _trunc_div(num, den)
        rem = num - q * den
        up = jnp.abs(rem) * 2 >= jnp.abs(den)
        sign = jnp.where((num < 0) == (den < 0), 1, -1)
        vals = jnp.where(up, q + sign, q)
        extra.append(jnp.logical_not(zero))
    else:
        raise ValueError(op)
    ok = overflow_validity(vals, out.precision)
    validity = combine_validity(left.validity, right.validity, ok,
                                *extra)
    return ColVal(out, vals, validity)


# --------------------------------------------------- named parity exprs --

class PromotePrecision(UnaryExpression):
    """Rescale a decimal child to a wider decimal type (the Catalyst
    wrapper; arithmetic here fuses it, the class exists for parity and
    for plans built programmatically).  Reference:
    GpuOverrides.scala:824-830."""

    def __init__(self, child: Expression, target: DataType):
        super().__init__(child)
        self.target = target

    def with_children(self, children):
        return PromotePrecision(children[0], self.target)

    @property
    def dtype(self) -> DataType:
        return self.target

    def eval_values(self, v, cv):
        return rescale(v.astype(jnp.int64), cv.dtype.scale,
                       self.target.scale)

    def cache_key(self):
        return ("PromotePrecision", self.child.cache_key(),
                self.target.name)


class CheckOverflow(UnaryExpression):
    """Null out values whose unscaled magnitude exceeds the declared
    precision (non-ANSI overflow -> null).  Reference:
    GpuOverrides.scala:831-838 GpuCheckOverflow."""

    def __init__(self, child: Expression, target: DataType,
                 null_on_overflow: bool = True):
        super().__init__(child)
        self.target = target
        self.null_on_overflow = null_on_overflow

    def with_children(self, children):
        return CheckOverflow(children[0], self.target,
                             self.null_on_overflow)

    @property
    def dtype(self) -> DataType:
        return self.target

    def supported_reason(self) -> Optional[str]:
        if not self.null_on_overflow:
            return ("ANSI CheckOverflow (exception on overflow) runs on "
                    "the CPU fallback")
        return None

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        v = rescale(c.values.astype(jnp.int64), c.dtype.scale,
                    self.target.scale)
        ok = overflow_validity(v, self.target.precision)
        return ColVal(self.target, v, combine_validity(c.validity, ok))

    def cache_key(self):
        return ("CheckOverflow", self.child.cache_key(),
                self.target.name, self.null_on_overflow)


class MakeDecimal(UnaryExpression):
    """Reinterpret an int64 of unscaled values as a decimal (partial
    aggregation plumbing; GpuOverrides GpuMakeDecimal analog)."""

    def __init__(self, child: Expression, precision: int, scale: int):
        super().__init__(child)
        self.precision = int(precision)
        self.scale = int(scale)

    def with_children(self, children):
        return MakeDecimal(children[0], self.precision, self.scale)

    @property
    def dtype(self) -> DataType:
        return DecimalType(self.precision, self.scale)

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        v = c.values.astype(jnp.int64)
        ok = overflow_validity(v, self.precision)
        return ColVal(self.dtype, v, combine_validity(c.validity, ok))

    def cache_key(self):
        return ("MakeDecimal", self.child.cache_key(), self.precision,
                self.scale)


class UnscaledValue(UnaryExpression):
    """Decimal -> raw unscaled int64 (GpuUnscaledValue analog)."""

    @property
    def dtype(self) -> DataType:
        return dts.INT64

    def eval_values(self, v, cv):
        return v.astype(jnp.int64)

    def cache_key(self):
        return ("UnscaledValue", self.child.cache_key())
