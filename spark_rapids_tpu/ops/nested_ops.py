"""Struct / map expressions over shredded nested columns.

Counterpart of the reference's ``complexTypeCreator.scala`` /
``complexTypeExtractors.scala`` rules (CreateNamedStruct, GetStructField,
CreateMap, GetMapValue, MapKeys, MapValues — ``GpuOverrides.scala``
registrations around lines 777-2826).

The execution model differs by design (see ``columnar/nested.py``): nested
columns are shredded to flat physical columns, so most of these expressions
COMPILE AWAY at bind time instead of running device kernels:

* ``GetStructField(col("s"), "a")``       binds to flat column ``s.a``
* ``MapKeys(col("m"))``                   binds to array column ``m.__key``
* ``CreateNamedStruct`` / ``CreateMap``   expand at select() time into one
  projection per shredded child
* ``GetMapValue`` is the one real kernel: a segmented first-match over the
  key elements followed by a value gather — single fused XLA program, no
  per-row loop.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.dtypes import DataType, MapType, StructType
from spark_rapids_tpu.columnar.nested import MAP_KEY_SUFFIX, MAP_VALUE_SUFFIX
from spark_rapids_tpu.ops.expressions import (
    Alias, ColVal, EmitContext, Expression, UnresolvedColumn)


def _base_name(e: Expression, what: str) -> str:
    if isinstance(e, UnresolvedColumn):
        return e.col_name
    raise ValueError(
        f"{what} requires a direct column reference, got {e}")


class GetStructField(Expression):
    """s.a / s["a"]: resolves to the shredded flat column ``s.a`` (chains
    compose: s.a.b).  Applied to a CreateNamedStruct it short-circuits to
    the field's defining expression."""

    def __init__(self, child: Expression, field: str):
        self.children = (child,)
        self.field = field

    def with_children(self, children):
        return GetStructField(children[0], self.field)

    @property
    def dtype(self) -> DataType:
        raise RuntimeError("GetStructField resolves at bind time")

    @property
    def nullable(self) -> bool:
        return True

    def bind(self, schema) -> Expression:
        base = self.children[0]
        if isinstance(base, CreateNamedStruct):
            return Alias(base.field_expr(self.field),
                         self.field).bind(schema)
        parts = [self.field]
        while isinstance(base, GetStructField):
            parts.append(base.field)
            base = base.children[0]
        root = _base_name(base, "struct field access")
        path = ".".join([root] + parts[::-1])
        names = [n for n, _ in schema]
        if path not in names:
            hits = [n for n in names if n.startswith(path + ".")]
            if hits:
                raise KeyError(
                    f"{path!r} is a nested struct; select it whole or "
                    f"access a leaf field ({hits})")
            raise KeyError(
                f"struct field {path!r} not found; flat columns: {names}")
        return Alias(UnresolvedColumn(path).bind(schema), self.field)

    def references(self):
        return self.children[0].references()

    @property
    def name(self) -> str:
        return self.field

    def __str__(self):
        return f"{self.children[0]}.{self.field}"


class CreateNamedStruct(Expression):
    """struct(a, b, ...) — expands at select() time into one shredded
    projection per field (``<out>.<field>``); never emits device code."""

    def __init__(self, pairs: Sequence[Tuple[str, Expression]]):
        if not pairs:
            raise ValueError("struct() needs at least one field")
        self.pairs = [(str(n), e) for n, e in pairs]
        self.children = tuple(e for _, e in self.pairs)

    def with_children(self, children):
        return CreateNamedStruct(
            list(zip([n for n, _ in self.pairs], children)))

    def field_expr(self, field: str) -> Expression:
        for n, e in self.pairs:
            if n == field:
                return e
        raise KeyError(f"struct has no field {field!r}; "
                       f"fields: {[n for n, _ in self.pairs]}")

    @property
    def dtype(self) -> DataType:
        return StructType((n, e.dtype) for n, e in self.pairs)

    @property
    def nullable(self) -> bool:
        return False

    def expand(self, out_name: str) -> List[Expression]:
        return [Alias(e, f"{out_name}.{n}") for n, e in self.pairs]

    @property
    def name(self) -> str:
        return "struct(" + ", ".join(n for n, _ in self.pairs) + ")"

    def emit(self, ctx):
        raise NotImplementedError(
            "CreateNamedStruct must be expanded by select(); it cannot "
            "appear nested inside another expression")


class CreateMap(Expression):
    """map(k1, v1, k2, v2, ...) — expands at select() time into the two
    aligned array projections ``<out>.__key`` / ``<out>.__value``."""

    def __init__(self, *entries: Expression):
        if not entries or len(entries) % 2:
            raise ValueError("map() needs alternating key, value pairs")
        self.children = tuple(entries)

    def with_children(self, children):
        return CreateMap(*children)

    @property
    def keys(self):
        return self.children[0::2]

    @property
    def values(self):
        return self.children[1::2]

    @property
    def dtype(self) -> DataType:
        return MapType(self.keys[0].dtype, self.values[0].dtype)

    @property
    def nullable(self) -> bool:
        return False

    def expand(self, out_name: str) -> List[Expression]:
        from spark_rapids_tpu.ops.collections_ops import CreateArray
        # enforce MapType's fixed-width restriction up front where the
        # entry dtypes are already known (literals, resolved refs) —
        # otherwise a string-keyed map would shred into byte garbage and
        # only fail (confusingly) at CreateArray.dtype time
        for e in self.children:
            try:
                dt = e.dtype
            except Exception:
                continue
            if dt.has_offsets or dt.is_nested:
                raise ValueError(
                    f"map() entry {e} has type {dt}: map keys/values "
                    "must be fixed-width scalar types")
        return [
            Alias(CreateArray(*self.keys), out_name + MAP_KEY_SUFFIX),
            Alias(CreateArray(*self.values), out_name + MAP_VALUE_SUFFIX),
        ]

    @property
    def name(self) -> str:
        return "map"

    def emit(self, ctx):
        raise NotImplementedError(
            "CreateMap must be expanded by select(); it cannot appear "
            "nested inside another expression")


class _MapPart(Expression):
    """Shared base for MapKeys/MapValues: binds to the shredded array."""

    suffix = ""
    fn_name = ""

    def __init__(self, child: Expression):
        self.children = (child,)

    def with_children(self, children):
        return type(self)(children[0])

    @property
    def dtype(self) -> DataType:
        raise RuntimeError(f"{type(self).__name__} resolves at bind time")

    @property
    def nullable(self) -> bool:
        return False

    def bind(self, schema) -> Expression:
        base = _base_name(self.children[0], self.fn_name)
        return Alias(UnresolvedColumn(base + self.suffix).bind(schema),
                     f"{self.fn_name}({base})")

    @property
    def name(self) -> str:
        c = self.children[0]
        n = c.col_name if isinstance(c, UnresolvedColumn) else str(c)
        return f"{self.fn_name}({n})"


class MapKeys(_MapPart):
    suffix = MAP_KEY_SUFFIX
    fn_name = "map_keys"


class MapValues(_MapPart):
    suffix = MAP_VALUE_SUFFIX
    fn_name = "map_values"


class GetMapValue(Expression):
    """m[key] / element_at(m, key): per-row first-match lookup.

    Pre-bind children are (map_ref, key_expr); bind rewires to the two
    shredded array columns.  The kernel: every key element compares
    against its row's probe key in one vector op, the earliest matching
    element position per row comes from a segmented min, and the value
    gathers at that position — null when the row has no match (Spark
    ``element_at``/``GetMapValue`` null semantics)."""

    def __init__(self, *children: Expression):
        # (map_ref, key) pre-bind; (keys_arr, values_arr, key) post-bind
        self.children = tuple(children)

    def with_children(self, children):
        return GetMapValue(*children)

    @property
    def _bound(self) -> bool:
        return len(self.children) == 3

    @property
    def dtype(self) -> DataType:
        if not self._bound:
            raise RuntimeError("GetMapValue resolves dtypes at bind time")
        return self.children[1].dtype.element

    @property
    def nullable(self) -> bool:
        return True

    def bind(self, schema) -> Expression:
        if self._bound:
            return self
        base = _base_name(self.children[0], "map lookup")
        keys = UnresolvedColumn(base + MAP_KEY_SUFFIX).bind(schema)
        values = UnresolvedColumn(base + MAP_VALUE_SUFFIX).bind(schema)
        key = self.children[1].bind(schema)
        return Alias(GetMapValue(keys, values, key),
                     f"{base}[{key}]")

    def emit(self, ctx: EmitContext) -> ColVal:
        from spark_rapids_tpu.ops.collections_ops import element_rows
        keys_e, values_e, key_e = self.children
        kc = keys_e.emit(ctx)
        vc = values_e.emit(ctx)
        probe = key_e.emit(ctx)
        ecap = kc.values.shape[0]
        pos = jnp.arange(ecap, dtype=jnp.int32)
        row = element_rows(kc, ctx.capacity)
        total = jnp.take(kc.offsets, jnp.int32(ctx.nrows))
        pv = probe.values
        if getattr(pv, "ndim", 0) == 0:
            per_elem = pv
        else:
            per_elem = pv[row]
        # compare under the promoted common dtype (Spark casts both
        # sides): a fractional float probe must MISS an integer key,
        # not truncate onto it
        ct = jnp.result_type(kc.values.dtype, pv.dtype)
        match = jnp.logical_and(
            pos < total,
            kc.values.astype(ct) == per_elem.astype(ct))
        big = jnp.int32(ecap)
        first = jax.ops.segment_min(
            jnp.where(match, pos, big), row,
            num_segments=ctx.capacity)
        found = first < big
        idx = jnp.clip(first, 0, max(ecap - 1, 0))
        vals = vc.values[idx]
        valid = found
        if vc.validity is not None:
            valid = jnp.logical_and(valid, vc.validity[idx])
        if probe.validity is not None:
            valid = jnp.logical_and(valid, probe.validity)
        return ColVal(self.dtype, vals, valid)

    @property
    def name(self) -> str:
        return "element_at"


def expand_nested_projections(exprs: List[Expression],
                              child_schema) -> List[Expression]:
    """select()-time rewrite: CreateNamedStruct/CreateMap outputs expand
    into their shredded projections, and a whole-column reference to a
    shredded nested column expands to all its flat members (so
    ``select("s", "v")`` keeps the struct)."""
    names = [n for n, _ in child_schema]
    out: List[Expression] = []
    for e in exprs:
        inner = e.children[0] if isinstance(e, Alias) else e
        out_name = e.alias if isinstance(e, Alias) else None
        if isinstance(inner, (CreateNamedStruct, CreateMap)):
            if out_name is None:
                raise ValueError(
                    f"{inner.name}: struct()/map() outputs must be "
                    "aliased (.alias('name'))")
            out.extend(inner.expand(out_name))
            continue
        if isinstance(inner, UnresolvedColumn) and \
                inner.col_name not in names:
            members = [n for n in names
                       if n.startswith(inner.col_name + ".")]
            if members:
                if out_name is not None and out_name != inner.col_name:
                    members_out = [
                        Alias(UnresolvedColumn(n),
                              out_name + n[len(inner.col_name):])
                        for n in members]
                else:
                    members_out = [UnresolvedColumn(n) for n in members]
                out.extend(members_out)
                continue
        out.append(e)
    return out
