"""Pallas TPU kernels for the engine's hot data-parallel primitives.

Two kernels where hand-scheduling beats what XLA emits for the generic
lowering (see /opt/skills/guides/pallas_guide.md):

- ``partition_histogram``: per-row partition-id counts.  XLA lowers
  ``segment_sum`` / one-hot scatter to a serialized scatter on TPU; here
  each grid step one-hot-expands a row block in VMEM and accumulates a
  (1, num_parts) running sum — the TPU grid is sequential, so the
  accumulate-into-output pattern is race-free.  Feeds shuffle partition
  sizing and AQE statistics (the reference gets these numbers from cudf's
  ``contiguousSplit`` metadata, GpuPartitioning.scala:50).

- ``masked_multi_reduce``: one pass over N value columns + a shared row
  mask producing per-column (sum, count).  The keyless aggregation path
  (grand totals, TPC-H q6 shape) otherwise reads each column twice (sum
  pass + count pass) from HBM; fusing halves the bandwidth on the
  bandwidth-bound side of the roofline.

Both kernels run under ``interpret=True`` off-TPU so the CPU-mesh test
suite exercises the same code path the chip runs.  ``use_pallas()`` gates
dispatch: real TPU backends only (the interpreter is for tests — the XLA
fallback is faster on CPU).
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_BLOCK_ROWS = 1024


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


@functools.lru_cache(maxsize=1)
def use_pallas() -> bool:
    """True when the default backend is a real TPU."""
    import os
    if os.environ.get("SPARK_RAPIDS_TPU_DISABLE_PALLAS"):
        return False
    return _on_tpu()


def reset_use_pallas() -> None:
    """Drop the cached ``use_pallas()`` decision.

    The gate is ``lru_cache``'d over env+backend; a test (or an embedder)
    that flips ``SPARK_RAPIDS_TPU_DISABLE_PALLAS`` mid-process must call
    this or the stale decision poisons every later dispatch."""
    use_pallas.cache_clear()


def hash_dispatch_conf(conf=None):
    """Resolve ``(enabled, tableSlots)`` for the hash-kernel dispatch:
    explicit conf > active session > entry defaults.  Consumers read
    this per dispatch (the table size keys the jit-cache signature, so
    a conf flip can never be masked by a cached trace)."""
    from spark_rapids_tpu.config import rapids_conf as rc
    if conf is None:
        from spark_rapids_tpu.api.session import TpuSession
        s = TpuSession._active
        conf = s.conf if s is not None else None
    if conf is None:
        return (rc.PALLAS_HASH_ENABLED.default,
                rc.PALLAS_HASH_TABLE_SLOTS.default)
    return (conf.get(rc.PALLAS_HASH_ENABLED),
            conf.get(rc.PALLAS_HASH_TABLE_SLOTS))


# ---------------------------------------------------------------- histogram --

def _hist_kernel(pid_ref, mask_ref, out_ref, *, num_parts: int):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    pids = pid_ref[...]            # (1, BLOCK)
    mask = mask_ref[...]           # (1, BLOCK)
    # one-hot (BLOCK, num_parts) via broadcast compare; masked rows
    # contribute nothing.  The sum over the row axis is a dense reduction
    # the VPU handles natively — no scatter.
    cols = jax.lax.broadcasted_iota(jnp.int32, (pids.shape[1], num_parts), 1)
    onehot = (pids.reshape(-1, 1) == cols) & mask.reshape(-1, 1)
    # dtype= pins the accumulator: under x64 an int32 sum promotes to
    # int64 and the store into the int32 out ref refuses
    out_ref[...] += onehot.sum(axis=0, keepdims=True, dtype=jnp.int32)


def partition_histogram(pids: jnp.ndarray, mask: jnp.ndarray,
                        num_parts: int,
                        interpret: bool | None = None) -> jnp.ndarray:
    """counts[p] = number of rows with pids[i] == p and mask[i].

    ``pids`` int32[capacity], ``mask`` bool[capacity]; capacity is padded
    up to a whole number of blocks internally.
    """
    if interpret is None:
        interpret = not _on_tpu()
    capacity = pids.shape[0]
    if capacity == 0:
        # grid would be 0: the step-0 output init never runs
        return jnp.zeros(num_parts, dtype=jnp.int32)
    padded = ((capacity + _BLOCK_ROWS - 1) // _BLOCK_ROWS) * _BLOCK_ROWS
    if padded != capacity:
        pids = jnp.pad(pids, (0, padded - capacity))
        mask = jnp.pad(mask, (0, padded - capacity))
    pids2 = pids.reshape(1, padded).astype(jnp.int32)
    mask2 = mask.reshape(1, padded)
    grid = padded // _BLOCK_ROWS
    out = pl.pallas_call(
        functools.partial(_hist_kernel, num_parts=num_parts),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1, _BLOCK_ROWS), lambda i: (0, i)),
            pl.BlockSpec((1, _BLOCK_ROWS), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((1, num_parts), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, num_parts), jnp.int32),
        interpret=interpret,
    )(pids2, mask2)
    return out[0]


def partition_histogram_xla(pids, mask, num_parts):
    """One-hot XLA formulation with identical semantics (used as the
    test oracle; O(n*num_parts), so not the production fallback)."""
    cols = jax.lax.broadcasted_iota(jnp.int32, (pids.shape[0], num_parts), 1)
    onehot = (pids.reshape(-1, 1) == cols) & mask.reshape(-1, 1)
    return onehot.astype(jnp.int32).sum(axis=0)


def histogram(pids, mask, num_parts):
    """Partition counts: pallas on TPU (scatter serializes there);
    segment_sum elsewhere (cheap O(n) scatter on CPU/GPU)."""
    if use_pallas():
        return partition_histogram(pids, mask, num_parts, interpret=False)
    key = jnp.where(mask, pids, num_parts)
    return jax.ops.segment_sum(
        jnp.ones_like(pids, dtype=jnp.int32), key,
        num_segments=num_parts + 1)[:num_parts]


# ---------------------------------------------------- fused masked reduce --

def _multi_reduce_kernel(mask_ref, *refs, num_cols: int):
    # refs = (val_ref_0..val_ref_{n-1}, valid_ref_0.., sum_out, cnt_out)
    val_refs = refs[:num_cols]
    valid_refs = refs[num_cols:2 * num_cols]
    sum_ref = refs[2 * num_cols]
    cnt_ref = refs[2 * num_cols + 1]
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        cnt_ref[...] = jnp.zeros_like(cnt_ref)

    mask = mask_ref[...]  # (1, BLOCK) bool
    for c in range(num_cols):
        v = val_refs[c][...]
        ok = mask & valid_refs[c][...]
        contrib = jnp.where(ok, v, 0.0).sum(axis=1, dtype=sum_ref.dtype)
        cnt = ok.sum(axis=1, dtype=jnp.int32)
        sum_ref[0, c] += contrib[0]
        cnt_ref[0, c] += cnt[0]


def masked_multi_reduce(values: Sequence[jnp.ndarray],
                        validities: Sequence[jnp.ndarray],
                        mask: jnp.ndarray,
                        interpret: bool | None = None
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One HBM pass: per column c, (sum of values[c] where mask &
    validity[c], count of those rows).  Values are float64 accumulated in
    float64 (emulated on TPU but still single-pass)."""
    if interpret is None:
        interpret = not _on_tpu()
    n = len(values)
    capacity = values[0].shape[0]
    if capacity == 0:
        return (jnp.zeros(n, dtype=jnp.float64),
                jnp.zeros(n, dtype=jnp.int32))
    padded = ((capacity + _BLOCK_ROWS - 1) // _BLOCK_ROWS) * _BLOCK_ROWS
    vals2, valid2 = [], []
    for v, ok in zip(values, validities):
        v = v.astype(jnp.float64)
        if padded != capacity:
            v = jnp.pad(v, (0, padded - capacity))
            ok = jnp.pad(ok, (0, padded - capacity))
        vals2.append(v.reshape(1, padded))
        valid2.append(ok.reshape(1, padded))
    m = mask
    if padded != capacity:
        m = jnp.pad(m, (0, padded - capacity))
    m2 = m.reshape(1, padded)
    grid = padded // _BLOCK_ROWS
    block = pl.BlockSpec((1, _BLOCK_ROWS), lambda i: (0, i))
    sums, cnts = pl.pallas_call(
        functools.partial(_multi_reduce_kernel, num_cols=n),
        grid=(grid,),
        in_specs=[block] * (2 * n + 1),
        out_specs=[pl.BlockSpec((1, n), lambda i: (0, 0))] * 2,
        out_shape=[jax.ShapeDtypeStruct((1, n), jnp.float64),
                   jax.ShapeDtypeStruct((1, n), jnp.int32)],
        interpret=interpret,
    )(m2, *vals2, *valid2)
    return sums[0], cnts[0]


def masked_multi_reduce_xla(values, validities, mask):
    sums, cnts = [], []
    for v, ok in zip(values, validities):
        live = jnp.logical_and(mask, ok)
        sums.append(jnp.where(live, v.astype(jnp.float64), 0.0).sum())
        cnts.append(live.astype(jnp.int32).sum())
    return jnp.stack(sums), jnp.stack(cnts)


# ------------------------------------------------- hash table insert/probe --
# Single-pass open-addressing hash table over a 64-bit row code carried as
# two i32 lanes (TPU pallas avoids i64 lanes; the lo/hi split keeps the
# kernel i32-native and the XLA formulation bit-compatible).  Linear
# probing; a probe chain longer than ``_MAX_PROBE`` raises the overflow
# flag and the row parks in the trash slot ``T`` — callers DISCARD the
# whole output and re-run the segment-sum path (rows are never dropped,
# the shuffle slot-overflow discipline).  Table layout is impl-defined;
# only the stored code SET is contractual — callers order their output by
# stored code, so the pallas kernel and the XLA fallback are
# bit-interchangeable.
#
# VMEM bound: the table is 3 lanes x 4 bytes x num_slots resident per
# grid step — 12*T bytes, so T = 2^20 is ~12 MB and the practical ceiling
# (document in docs/performance.md).

_MAX_PROBE = 256


def _hash_index(lo, hi, num_slots: int, salt: int = 0):
    """murmur3 fmix32 over the two code lanes -> slot in [0, num_slots).

    Identical arithmetic in the pallas kernel and the XLA fallback for
    ``salt == 0``.  (Layouts can still diverge slot-for-slot — the
    sequential pallas insert and the multi-level XLA insert place
    contended keys differently — which is why only the stored-code set
    is contractual.)  ``salt`` decorrelates the XLA fallback's
    sub-table levels: without it, two keys colliding in a level would
    collide in every smaller level too (equal low hash bits imply
    equal lower ones)."""
    h = lo.astype(jnp.uint32) ^ jnp.uint32(salt & 0xFFFFFFFF) \
        ^ (hi.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B))
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return (h & jnp.uint32(num_slots - 1)).astype(jnp.int32)


# The XLA fallback's table layout: a fixed cascade of geometrically
# shrinking sub-tables (T/2, T/4, ..., the last two equal) summing to
# exactly T slots.  CPU XLA pays ~ms for every n-index scatter but ~us
# for gathers, so the insert does ONE unconditional last-writer
# scatter per level and verifies by gather — no arbitration rounds, no
# while_loop, a fixed 7 scatters total.  Keys whose level slot was
# taken by a different key cascade to the next level (salted hash per
# level keeps the cascades decorrelated); anything unresolved past the
# last level raises the overflow flag.  The pallas kernel keeps the
# sequential linear-probe layout — only the stored-code SET is
# contractual, and each impl's insert/probe pair is self-consistent.
_XLA_LEVELS = 6


def _xla_level_plan(num_slots: int):
    """[(offset, size)] of the XLA fallback's sub-table cascade."""
    assert num_slots >= 64 and num_slots & (num_slots - 1) == 0, \
        num_slots
    sizes = []
    s = num_slots // 2
    for _ in range(_XLA_LEVELS - 1):
        sizes.append(s)
        s //= 2
    sizes.append(sizes[-1])
    plan, off = [], 0
    for s in sizes:
        plan.append((off, s))
        off += s
    return plan


def hash_insert_xla(code_lo, code_hi, live, num_slots: int,
                    max_probe: int = _MAX_PROBE):
    """Vectorized XLA insert (production path off-TPU).

    Per level of the sub-table cascade: every unresolved row
    scatter-writes its packed code into its salted-hash slot
    (last-writer-wins — any winner is a correct winner, the loser key
    just cascades), then a gather checks which rows' codes were the
    ones stored; those resolve, the rest descend a level.  Duplicate
    rows of one key share every level slot, so the whole key resolves
    the moment one of its rows survives a write.  Returns
    ``(slot i32[n], table_lo i32[T], table_hi i32[T], occupied bool[T],
    overflow bool[])`` with dead/overflowed rows parked at ``slot == T``
    — overflow means a key was still homeless after the last level and
    the whole output must be DISCARDED (``max_probe`` is accepted for
    signature compatibility with the pallas kernel)."""
    del max_probe
    n = code_lo.shape[0]
    T = num_slots
    code_lo = code_lo.astype(jnp.int32)
    code_hi = code_hi.astype(jnp.int32)
    code64 = (code_hi.astype(jnp.int64) << 32) \
        | (code_lo.astype(jnp.int64) & jnp.int64(0xFFFFFFFF))
    t64 = jnp.zeros(T, jnp.int64)
    slot = jnp.where(live, jnp.int32(-1), jnp.int32(T))
    for lvl, (off, size) in enumerate(_xla_level_plan(T)):
        idx = off + _hash_index(code_lo, code_hi, size,
                                salt=lvl * 0x9E3779B9)
        unresolved = slot < 0
        t64 = t64.at[jnp.where(unresolved, idx, T)].set(
            code64, mode="drop")
        placed = unresolved & (t64[idx] == code64)
        slot = jnp.where(placed, idx, slot)
    ovf = jnp.any(slot < 0)
    slot = jnp.where(slot < 0, jnp.int32(T), slot)
    # occupancy from the resolved rows themselves (dead/overflowed rows
    # sit at T and drop): overwritten loser codes leave occ False, so
    # the probe can never false-match them, and no code value is
    # reserved as an empty sentinel (join codes may be ANY i64)
    occ = jnp.zeros(T, jnp.bool_).at[slot].set(True, mode="drop")
    tlo = t64.astype(jnp.int32)
    thi = (t64 >> 32).astype(jnp.int32)
    return slot, tlo, thi, occ, ovf


def hash_probe_xla(code_lo, code_hi, live, table_lo, table_hi, occupied,
                   max_probe: int = _MAX_PROBE):
    """Vectorized XLA lookup: slot of each live row's code, or ``T`` on
    miss.  Pure gathers — one salted-hash lookup per cascade level; a
    stored key matches at exactly the level that stored it (insert
    placement is unique), so the levels just OR together.  Only valid
    against a table built by :func:`hash_insert_xla` (the pallas pair
    owns the linear-probe layout)."""
    del max_probe
    T = occupied.shape[0]
    code_lo = code_lo.astype(jnp.int32)
    code_hi = code_hi.astype(jnp.int32)
    code64 = (code_hi.astype(jnp.int64) << 32) \
        | (code_lo.astype(jnp.int64) & jnp.int64(0xFFFFFFFF))
    t64 = (table_hi.astype(jnp.int64) << 32) \
        | (table_lo.astype(jnp.int64) & jnp.int64(0xFFFFFFFF))
    slot = jnp.full(code_lo.shape[0], T, jnp.int32)
    for lvl, (off, size) in enumerate(_xla_level_plan(T)):
        idx = off + _hash_index(code_lo, code_hi, size,
                                salt=lvl * 0x9E3779B9)
        hit = live & occupied[idx] & (t64[idx] == code64)
        slot = jnp.where(hit, idx, slot)
    return slot


def _hash_insert_kernel(lo_ref, hi_ref, live_ref, slot_ref, tlo_ref,
                        thi_ref, occ_ref, ovf_ref, *, num_slots: int,
                        max_probe: int):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        tlo_ref[...] = jnp.zeros_like(tlo_ref)
        thi_ref[...] = jnp.zeros_like(thi_ref)
        occ_ref[...] = jnp.zeros_like(occ_ref)
        ovf_ref[...] = jnp.zeros_like(ovf_ref)

    block = lo_ref.shape[1]

    def row_body(r, _):
        lo = lo_ref[0, r]
        hi = hi_ref[0, r]
        alive = live_ref[0, r]
        home = _hash_index(lo, hi, num_slots)
        # status: 0 probing, 1 match, 2 claim-empty, 3 overflow, 4 dead
        init = (jnp.where(alive, jnp.int32(0), jnp.int32(4)), home,
                jnp.int32(0))

        def cond(s):
            return s[0] == 0

        def probe_body(s):
            _, probe, cnt = s
            occ = occ_ref[0, probe]
            is_match = (occ != 0) & (tlo_ref[0, probe] == lo) \
                & (thi_ref[0, probe] == hi)
            status = jnp.where(is_match, jnp.int32(1),
                               jnp.where(occ == 0, jnp.int32(2),
                                         jnp.int32(0)))
            cnt = cnt + 1
            status = jnp.where((status == 0) & (cnt >= max_probe),
                               jnp.int32(3), status)
            probe = jnp.where(status == 0,
                              (probe + 1) & (num_slots - 1), probe)
            return (status, probe, cnt)

        status, pos, _ = jax.lax.while_loop(cond, probe_body, init)

        @pl.when(status == 2)
        def _claim():
            occ_ref[0, pos] = jnp.int32(1)
            tlo_ref[0, pos] = lo
            thi_ref[0, pos] = hi

        @pl.when(status == 3)
        def _overflow():
            ovf_ref[0, 0] = jnp.int32(1)

        slot_ref[0, r] = jnp.where(
            (status == 1) | (status == 2), pos, jnp.int32(num_slots))
        return 0

    jax.lax.fori_loop(0, block, row_body, 0)


def hash_insert(code_lo, code_hi, live, num_slots: int,
                max_probe: int = _MAX_PROBE,
                interpret: bool | None = None):
    """Pallas insert: the TPU grid is sequential, so the per-row probe
    loop owns the VMEM-resident table race-free.  Same contract and
    table layout as :func:`hash_insert_xla`."""
    if interpret is None:
        interpret = not _on_tpu()
    n = code_lo.shape[0]
    T = num_slots
    if n == 0:
        return (jnp.zeros(0, jnp.int32), jnp.zeros(T, jnp.int32),
                jnp.zeros(T, jnp.int32), jnp.zeros(T, jnp.bool_),
                jnp.asarray(False, jnp.bool_))
    padded = ((n + _BLOCK_ROWS - 1) // _BLOCK_ROWS) * _BLOCK_ROWS
    lo = code_lo.astype(jnp.int32)
    hi = code_hi.astype(jnp.int32)
    if padded != n:
        lo = jnp.pad(lo, (0, padded - n))
        hi = jnp.pad(hi, (0, padded - n))
        live = jnp.pad(live, (0, padded - n))
    block = pl.BlockSpec((1, _BLOCK_ROWS), lambda i: (0, i))
    table = pl.BlockSpec((1, T), lambda i: (0, 0))
    flag = pl.BlockSpec((1, 1), lambda i: (0, 0))
    slot, tlo, thi, occ, ovf = pl.pallas_call(
        functools.partial(_hash_insert_kernel, num_slots=T,
                          max_probe=max_probe),
        grid=(padded // _BLOCK_ROWS,),
        in_specs=[block, block, block],
        out_specs=[block, table, table, table, flag],
        out_shape=[jax.ShapeDtypeStruct((1, padded), jnp.int32),
                   jax.ShapeDtypeStruct((1, T), jnp.int32),
                   jax.ShapeDtypeStruct((1, T), jnp.int32),
                   jax.ShapeDtypeStruct((1, T), jnp.int32),
                   jax.ShapeDtypeStruct((1, 1), jnp.int32)],
        interpret=interpret,
    )(lo.reshape(1, padded), hi.reshape(1, padded),
      live.reshape(1, padded))
    return (slot[0, :n], tlo[0], thi[0], occ[0].astype(jnp.bool_),
            ovf[0, 0] != 0)


def _hash_probe_kernel(lo_ref, hi_ref, live_ref, tlo_ref, thi_ref,
                       occ_ref, slot_ref, *, num_slots: int,
                       max_probe: int):
    block = lo_ref.shape[1]

    def row_body(r, _):
        lo = lo_ref[0, r]
        hi = hi_ref[0, r]
        alive = live_ref[0, r]
        home = _hash_index(lo, hi, num_slots)
        init = (jnp.where(alive, jnp.int32(0), jnp.int32(3)), home,
                jnp.int32(0))

        def cond(s):
            return s[0] == 0

        def probe_body(s):
            _, probe, cnt = s
            occ = occ_ref[0, probe]
            is_match = (occ != 0) & (tlo_ref[0, probe] == lo) \
                & (thi_ref[0, probe] == hi)
            status = jnp.where(is_match, jnp.int32(1),
                               jnp.where(occ == 0, jnp.int32(2),
                                         jnp.int32(0)))
            cnt = cnt + 1
            status = jnp.where((status == 0) & (cnt >= max_probe),
                               jnp.int32(2), status)
            probe = jnp.where(status == 0,
                              (probe + 1) & (num_slots - 1), probe)
            return (status, probe, cnt)

        status, pos, _ = jax.lax.while_loop(cond, probe_body, init)
        slot_ref[0, r] = jnp.where(status == 1, pos,
                                   jnp.int32(num_slots))
        return 0

    jax.lax.fori_loop(0, block, row_body, 0)


def hash_probe(code_lo, code_hi, live, table_lo, table_hi, occupied,
               max_probe: int = _MAX_PROBE,
               interpret: bool | None = None):
    """Pallas lookup matching :func:`hash_probe_xla`."""
    if interpret is None:
        interpret = not _on_tpu()
    n = code_lo.shape[0]
    T = occupied.shape[0]
    if n == 0:
        return jnp.zeros(0, jnp.int32)
    padded = ((n + _BLOCK_ROWS - 1) // _BLOCK_ROWS) * _BLOCK_ROWS
    lo = code_lo.astype(jnp.int32)
    hi = code_hi.astype(jnp.int32)
    if padded != n:
        lo = jnp.pad(lo, (0, padded - n))
        hi = jnp.pad(hi, (0, padded - n))
        live = jnp.pad(live, (0, padded - n))
    block = pl.BlockSpec((1, _BLOCK_ROWS), lambda i: (0, i))
    table = pl.BlockSpec((1, T), lambda i: (0, 0))
    slot = pl.pallas_call(
        functools.partial(_hash_probe_kernel, num_slots=T,
                          max_probe=max_probe),
        grid=(padded // _BLOCK_ROWS,),
        in_specs=[block, block, block, table, table, table],
        out_specs=block,
        out_shape=jax.ShapeDtypeStruct((1, padded), jnp.int32),
        interpret=interpret,
    )(lo.reshape(1, padded), hi.reshape(1, padded),
      live.reshape(1, padded), table_lo.reshape(1, T).astype(jnp.int32),
      table_hi.reshape(1, T).astype(jnp.int32),
      occupied.reshape(1, T).astype(jnp.int32))
    return slot[0, :n]


def hash_table_insert(code_lo, code_hi, live, num_slots: int,
                      max_probe: int = _MAX_PROBE):
    """Production dispatch: pallas on a real TPU, XLA elsewhere (the
    round-based formulation vectorizes well on CPU; the sequential
    kernel only wins where VMEM residency does)."""
    if use_pallas():
        return hash_insert(code_lo, code_hi, live, num_slots,
                           max_probe=max_probe, interpret=False)
    return hash_insert_xla(code_lo, code_hi, live, num_slots,
                           max_probe=max_probe)


def hash_table_probe(code_lo, code_hi, live, table_lo, table_hi,
                     occupied, max_probe: int = _MAX_PROBE):
    """Production dispatch for the lookup side."""
    if use_pallas():
        return hash_probe(code_lo, code_hi, live, table_lo, table_hi,
                          occupied, max_probe=max_probe,
                          interpret=False)
    return hash_probe_xla(code_lo, code_hi, live, table_lo, table_hi,
                          occupied, max_probe=max_probe)
