"""Pallas TPU kernels for the engine's hot data-parallel primitives.

Two kernels where hand-scheduling beats what XLA emits for the generic
lowering (see /opt/skills/guides/pallas_guide.md):

- ``partition_histogram``: per-row partition-id counts.  XLA lowers
  ``segment_sum`` / one-hot scatter to a serialized scatter on TPU; here
  each grid step one-hot-expands a row block in VMEM and accumulates a
  (1, num_parts) running sum — the TPU grid is sequential, so the
  accumulate-into-output pattern is race-free.  Feeds shuffle partition
  sizing and AQE statistics (the reference gets these numbers from cudf's
  ``contiguousSplit`` metadata, GpuPartitioning.scala:50).

- ``masked_multi_reduce``: one pass over N value columns + a shared row
  mask producing per-column (sum, count).  The keyless aggregation path
  (grand totals, TPC-H q6 shape) otherwise reads each column twice (sum
  pass + count pass) from HBM; fusing halves the bandwidth on the
  bandwidth-bound side of the roofline.

Both kernels run under ``interpret=True`` off-TPU so the CPU-mesh test
suite exercises the same code path the chip runs.  ``use_pallas()`` gates
dispatch: real TPU backends only (the interpreter is for tests — the XLA
fallback is faster on CPU).
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_BLOCK_ROWS = 1024


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


@functools.lru_cache(maxsize=1)
def use_pallas() -> bool:
    """True when the default backend is a real TPU."""
    import os
    if os.environ.get("SPARK_RAPIDS_TPU_DISABLE_PALLAS"):
        return False
    return _on_tpu()


# ---------------------------------------------------------------- histogram --

def _hist_kernel(pid_ref, mask_ref, out_ref, *, num_parts: int):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    pids = pid_ref[...]            # (1, BLOCK)
    mask = mask_ref[...]           # (1, BLOCK)
    # one-hot (BLOCK, num_parts) via broadcast compare; masked rows
    # contribute nothing.  The sum over the row axis is a dense reduction
    # the VPU handles natively — no scatter.
    cols = jax.lax.broadcasted_iota(jnp.int32, (pids.shape[1], num_parts), 1)
    onehot = (pids.reshape(-1, 1) == cols) & mask.reshape(-1, 1)
    out_ref[...] += onehot.astype(jnp.int32).sum(axis=0, keepdims=True)


def partition_histogram(pids: jnp.ndarray, mask: jnp.ndarray,
                        num_parts: int,
                        interpret: bool | None = None) -> jnp.ndarray:
    """counts[p] = number of rows with pids[i] == p and mask[i].

    ``pids`` int32[capacity], ``mask`` bool[capacity]; capacity is padded
    up to a whole number of blocks internally.
    """
    if interpret is None:
        interpret = not _on_tpu()
    capacity = pids.shape[0]
    if capacity == 0:
        # grid would be 0: the step-0 output init never runs
        return jnp.zeros(num_parts, dtype=jnp.int32)
    padded = ((capacity + _BLOCK_ROWS - 1) // _BLOCK_ROWS) * _BLOCK_ROWS
    if padded != capacity:
        pids = jnp.pad(pids, (0, padded - capacity))
        mask = jnp.pad(mask, (0, padded - capacity))
    pids2 = pids.reshape(1, padded).astype(jnp.int32)
    mask2 = mask.reshape(1, padded)
    grid = padded // _BLOCK_ROWS
    out = pl.pallas_call(
        functools.partial(_hist_kernel, num_parts=num_parts),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1, _BLOCK_ROWS), lambda i: (0, i)),
            pl.BlockSpec((1, _BLOCK_ROWS), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((1, num_parts), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, num_parts), jnp.int32),
        interpret=interpret,
    )(pids2, mask2)
    return out[0]


def partition_histogram_xla(pids, mask, num_parts):
    """One-hot XLA formulation with identical semantics (used as the
    test oracle; O(n*num_parts), so not the production fallback)."""
    cols = jax.lax.broadcasted_iota(jnp.int32, (pids.shape[0], num_parts), 1)
    onehot = (pids.reshape(-1, 1) == cols) & mask.reshape(-1, 1)
    return onehot.astype(jnp.int32).sum(axis=0)


def histogram(pids, mask, num_parts):
    """Partition counts: pallas on TPU (scatter serializes there);
    segment_sum elsewhere (cheap O(n) scatter on CPU/GPU)."""
    if use_pallas():
        return partition_histogram(pids, mask, num_parts, interpret=False)
    key = jnp.where(mask, pids, num_parts)
    return jax.ops.segment_sum(
        jnp.ones_like(pids, dtype=jnp.int32), key,
        num_segments=num_parts + 1)[:num_parts]


# ---------------------------------------------------- fused masked reduce --

def _multi_reduce_kernel(mask_ref, *refs, num_cols: int):
    # refs = (val_ref_0..val_ref_{n-1}, valid_ref_0.., sum_out, cnt_out)
    val_refs = refs[:num_cols]
    valid_refs = refs[num_cols:2 * num_cols]
    sum_ref = refs[2 * num_cols]
    cnt_ref = refs[2 * num_cols + 1]
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        cnt_ref[...] = jnp.zeros_like(cnt_ref)

    mask = mask_ref[...]  # (1, BLOCK) bool
    for c in range(num_cols):
        v = val_refs[c][...]
        ok = mask & valid_refs[c][...]
        contrib = jnp.where(ok, v, 0.0).sum(axis=1)
        cnt = ok.astype(jnp.int32).sum(axis=1)
        sum_ref[0, c] += contrib[0]
        cnt_ref[0, c] += cnt[0]


def masked_multi_reduce(values: Sequence[jnp.ndarray],
                        validities: Sequence[jnp.ndarray],
                        mask: jnp.ndarray,
                        interpret: bool | None = None
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One HBM pass: per column c, (sum of values[c] where mask &
    validity[c], count of those rows).  Values are float64 accumulated in
    float64 (emulated on TPU but still single-pass)."""
    if interpret is None:
        interpret = not _on_tpu()
    n = len(values)
    capacity = values[0].shape[0]
    if capacity == 0:
        return (jnp.zeros(n, dtype=jnp.float64),
                jnp.zeros(n, dtype=jnp.int32))
    padded = ((capacity + _BLOCK_ROWS - 1) // _BLOCK_ROWS) * _BLOCK_ROWS
    vals2, valid2 = [], []
    for v, ok in zip(values, validities):
        v = v.astype(jnp.float64)
        if padded != capacity:
            v = jnp.pad(v, (0, padded - capacity))
            ok = jnp.pad(ok, (0, padded - capacity))
        vals2.append(v.reshape(1, padded))
        valid2.append(ok.reshape(1, padded))
    m = mask
    if padded != capacity:
        m = jnp.pad(m, (0, padded - capacity))
    m2 = m.reshape(1, padded)
    grid = padded // _BLOCK_ROWS
    block = pl.BlockSpec((1, _BLOCK_ROWS), lambda i: (0, i))
    sums, cnts = pl.pallas_call(
        functools.partial(_multi_reduce_kernel, num_cols=n),
        grid=(grid,),
        in_specs=[block] * (2 * n + 1),
        out_specs=[pl.BlockSpec((1, n), lambda i: (0, 0))] * 2,
        out_shape=[jax.ShapeDtypeStruct((1, n), jnp.float64),
                   jax.ShapeDtypeStruct((1, n), jnp.int32)],
        interpret=interpret,
    )(m2, *vals2, *valid2)
    return sums[0], cnts[0]


def masked_multi_reduce_xla(values, validities, mask):
    sums, cnts = [], []
    for v, ok in zip(values, validities):
        live = jnp.logical_and(mask, ok)
        sums.append(jnp.where(live, v.astype(jnp.float64), 0.0).sum())
        cnts.append(live.astype(jnp.int32).sum())
    return jnp.stack(sums), jnp.stack(cnts)
