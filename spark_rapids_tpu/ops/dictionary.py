"""Vectorized host-side string key encoding.

Round 1 dictionary-encoded string group-by/join keys with a per-row Python
loop (``for i, s in enumerate(col.to_pylist())``) — at TPC-DS scale that
loop IS the runtime.  This module replaces it with numpy-vectorized
encoders built on one primitive: a zero-padded ``(nrows, width+4)`` byte
matrix of every row's UTF-8 bytes plus a big-endian length tail.

* Row-wise lexicographic comparison of matrix rows == Spark string
  ordering (UTF-8 byte-wise lex order equals code-point order; zero
  padding sorts prefixes first; the length tail only breaks ties between
  strings that differ in trailing NUL bytes, in the correct direction).
* ``np.unique(matrix, axis=0)`` therefore yields sorted-by-string uniques
  and an inverse that is an *order-preserving* dense rank — the device
  sort kernel consumes the ranks as plain int32 keys.
* Stable-across-batches dictionary codes (group-by / join keys) loop only
  over the *distinct* values of each batch, not its rows.

The reference keeps string keys device-side in cudf hash tables
(stringFunctions.scala, SortUtils); under XLA static shapes the dictionary
hop stays on host, but vectorized it is a bandwidth copy, not a Python
interpreter loop.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu.columnar.column import Column


def row_byte_matrix(col: Column) -> Tuple[np.ndarray, np.ndarray]:
    """``(nrows, width+4)`` uint8 matrix of each row's bytes (zero-padded)
    with a big-endian length tail, plus the row validity mask.

    Null rows encode as all-zero (callers mask them via validity).
    """
    n = col.nrows
    # host_* readers: exact numpy when the column is still host-built,
    # no device round trip (see Column docstring)
    offs = col.host_offsets()[: n + 1].astype(np.int64)
    chars = col.host_values()
    valid = col.validity_numpy()
    lens = (offs[1:] - offs[:-1]) if n else np.zeros(0, dtype=np.int64)
    if not valid.all():
        lens = np.where(valid, lens, 0)
    width = int(lens.max()) if n and lens.size else 0
    mat = np.zeros((n, width + 4), dtype=np.uint8)
    if width and len(chars):
        idx = offs[:-1, None] + np.arange(width, dtype=np.int64)[None, :]
        mask = (np.arange(width, dtype=np.int64)[None, :] < lens[:, None])
        np.copyto(mat[:, :width],
                  np.where(mask, chars[np.minimum(idx, len(chars) - 1)], 0))
    mat[:, width + 0] = (lens >> 24) & 0xFF
    mat[:, width + 1] = (lens >> 16) & 0xFF
    mat[:, width + 2] = (lens >> 8) & 0xFF
    mat[:, width + 3] = lens & 0xFF
    return mat, valid


def _hash_rows(mat: np.ndarray) -> np.ndarray:
    """Vectorized FNV-1a over the byte columns (one pass per matrix
    column, not per row)."""
    h = np.full(mat.shape[0], 0xCBF29CE484222325, dtype=np.uint64)
    prime = np.uint64(0x100000001B3)
    for j in range(mat.shape[1]):
        h = (h ^ mat[:, j].astype(np.uint64)) * prime
    return h


def _unique_rows(mat: np.ndarray):
    """(uniq_rows, inverse): uniques in string-lexicographic order,
    inverse[i] = order-preserving dense rank of row i.

    Hash-based: ``np.unique(mat, axis=0)`` sorts n void rows with per-row
    memcmp (measured ~12x slower than the round-1 Python loop at 1M rows);
    instead dedupe on a 64-bit row hash, verify exactness by comparing
    every row against its representative (any collision — astronomically
    rare — falls back to the exact sort), then lexsort only the distinct
    representatives."""
    n = mat.shape[0]
    h = _hash_rows(mat)
    _, first_idx, inv = np.unique(h, return_index=True,
                                  return_inverse=True)
    reps = mat[first_idx]
    if not np.array_equal(mat, reps[inv]):
        uniq, inverse = np.unique(mat, axis=0, return_inverse=True)
        return uniq, inverse.reshape(-1)
    order = np.lexsort(reps.T[::-1])  # primary key = first byte column
    rank_of = np.empty(len(order), dtype=np.int64)
    rank_of[order] = np.arange(len(order))
    return reps[order], rank_of[inv]


def _unique_bytes(uniq_row: np.ndarray) -> bytes:
    length = int.from_bytes(uniq_row[-4:].tobytes(), "big")
    return uniq_row[:length].tobytes()


def _arrow_dictionary(col: Column):
    """pyarrow hash-based dictionary encode over the column's buffers,
    zero-copy (~10x the numpy matrix fallback).  Returns
    ``(inverse, dictionary: pa.Array)`` or None when pyarrow is absent."""
    try:
        import pyarrow as pa
    except ImportError:
        return None
    n = col.nrows
    valid = col.validity_numpy()
    offs = np.ascontiguousarray(
        col.host_offsets()[: n + 1].astype(np.int32, copy=False))
    chars = np.ascontiguousarray(col.host_values())
    validity_buf = None
    if not valid.all():
        validity_buf = pa.py_buffer(np.packbits(valid, bitorder="little"))
    arr = pa.Array.from_buffers(
        pa.utf8(), n,
        [validity_buf, pa.py_buffer(offs), pa.py_buffer(chars)])
    d = arr.dictionary_encode()
    inverse = np.asarray(d.indices.fill_null(0)).astype(np.int64)
    return inverse, d.dictionary


def _encode_distinct(col: Column):
    """(inverse, distinct, valid): per-row index into the batch-local
    distinct-value list (arbitrary index for null rows), the distinct
    values as Python strings, and the validity mask."""
    valid = col.validity_numpy()
    enc = _arrow_dictionary(col)
    if enc is not None:
        inverse, dictionary = enc
        distinct = dictionary.to_pylist()
        if not distinct and col.nrows:  # all rows null: keep luts non-empty
            return np.zeros(col.nrows, dtype=np.int64), [""], valid
        return inverse, distinct, valid
    mat, _ = row_byte_matrix(col)
    uniq, inverse = _unique_rows(mat)
    distinct = [_unique_bytes(u).decode("utf-8") for u in uniq]
    return inverse, distinct, valid


def rank_encode(col: Column) -> np.ndarray:
    """Order-preserving int32 dense ranks of the column's values (within
    this column's value set only — not stable across batches).  Null rows
    get rank 0; callers order them via the validity mask.

    Only the *distinct* values are ordered — via arrow's C++ sort (utf8
    sorts byte-wise lexicographic == Spark string order), so even
    near-unique sort keys never hit Python-per-value work.  The numpy
    fallback's ``_unique_rows`` inverse is already an order-preserving
    rank."""
    n = col.nrows
    if n == 0:
        return np.zeros(0, dtype=np.int32)
    enc = _arrow_dictionary(col)
    if enc is not None:
        import pyarrow.compute as pc
        inverse, dictionary = enc
        k = len(dictionary)
        if k == 0:
            return np.zeros(n, dtype=np.int32)
        order = np.asarray(pc.sort_indices(dictionary))
        rank = np.empty(k, dtype=np.int32)
        rank[order] = np.arange(k, dtype=np.int32)
        return rank[inverse]
    mat, _ = row_byte_matrix(col)
    _, inverse = _unique_rows(mat)
    return inverse.astype(np.int32)


def ordered_dict_encode(col: Column
                        ) -> Tuple[np.ndarray, List[str]]:
    """(codes int64, sorted distinct values): ORDER-PRESERVING dictionary
    encode of the whole column — code order == Spark string order — so
    the distributed planner can group, sort, min/max, and compare codes
    on device and decode at collect.  Null rows get code 0; callers keep
    the validity mask."""
    n = col.nrows
    if n == 0:
        return np.zeros(0, dtype=np.int64), []
    enc = _arrow_dictionary(col)
    if enc is not None:
        import pyarrow.compute as pc
        inverse, dictionary = enc
        k = len(dictionary)
        if k == 0:
            return np.zeros(n, dtype=np.int64), []
        order = np.asarray(pc.sort_indices(dictionary))
        rank = np.empty(k, dtype=np.int64)
        rank[order] = np.arange(k, dtype=np.int64)
        return rank[inverse], dictionary.take(order).to_pylist()
    mat, _ = row_byte_matrix(col)
    uniq, inverse = _unique_rows(mat)
    return (inverse.astype(np.int64),
            [_unique_bytes(u).decode("utf-8") for u in uniq])


def dict_encode_stable(col: Column, codes: Dict[Optional[str], int],
                       values: List[Optional[str]],
                       null_code: Optional[int] = None) -> np.ndarray:
    """Dictionary-encode with codes stable across batches: the first
    appearance of a value (across all calls sharing ``codes``/``values``)
    fixes its code.  Python work is O(distinct per batch), not O(rows).

    ``null_code``: fixed code for null rows; None means nulls intern like
    values (keyed on the None entry), matching the group-by encoder.
    """
    n = col.nrows
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    inverse, distinct, valid = _encode_distinct(col)
    lut = np.empty(len(distinct), dtype=np.int64)
    for j, s in enumerate(distinct):
        code = codes.get(s)
        if code is None:
            code = len(values)
            codes[s] = code
            values.append(s)
        lut[j] = code
    out = lut[inverse]
    if not valid.all():
        if null_code is not None:
            out = np.where(valid, out, null_code)
        else:
            code = codes.get(None)
            if code is None:
                code = len(values)
                codes[None] = code
                values.append(None)
            out = np.where(valid, out, code)
    return out
