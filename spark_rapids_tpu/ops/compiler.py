"""The stage compiler: expression forests -> one jitted XLA function.

This is the architectural pivot away from the reference: where a GpuExec calls
one libcudf kernel per expression per batch over JNI
(``GpuExpression.columnarEval``, GpuExpressions.scala:113), here an operator
hands its *entire* bound expression forest to :func:`make_stage_fn` and gets a
single ``jax.jit``-compiled function.  XLA fuses the whole stage — filter
predicate, projections, partial aggregation pre-work — into a few TPU kernels,
amortizing dispatch and keeping intermediates in vector registers/VMEM instead
of HBM round-trips.

Shape discipline: the traced signature is one (capacity,) array set per input
column plus an int32 ``nrows`` scalar.  Because Column capacities are bucketed
powers of two, re-tracing is bounded by O(log max_rows) buckets per stage.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.expressions import (
    ColVal, EmitContext, Expression, collect_param_slots)

# A column crosses the jit boundary as (values, validity|None, offsets|None).
FlatCol = Tuple


def donation_supported() -> bool:
    """Buffer donation is a no-op on the CPU backend (XLA:CPU ignores
    donated buffers and warns); only request it where it frees HBM."""
    import jax
    return jax.default_backend() in ("tpu", "gpu")


def _donate_kwargs(donate: bool) -> dict:
    """jit kwargs for a stage whose flat-column arg (argument 0) may be
    donated.  The effective flag — not the requested one — is folded
    into cache signatures, so a CPU process and a TPU process never
    share a signature with different donation semantics."""
    return {"donate_argnums": (0,)} if donate else {}


def effective_donate(donate: bool) -> bool:
    return bool(donate) and donation_supported()


def batch_to_flat(batch: ColumnarBatch) -> List[FlatCol]:
    return [(c.data, c.validity, c.offsets) for c in batch.columns.values()]


def flat_to_colvals(flat: Sequence[FlatCol],
                    dtypes: Sequence[DataType]) -> List[ColVal]:
    return [ColVal(dt, v, validity, offsets)
            for (v, validity, offsets), dt in zip(flat, dtypes)]


def capacity_of(flat: Sequence[FlatCol]) -> int:
    for values, _, offsets in flat:
        if offsets is not None:
            return int(offsets.shape[0]) - 1
        return int(values.shape[0])
    raise ValueError("no columns")


def colvals_to_columns(outs: Sequence[ColVal], nrows: int,
                       capacity: int) -> List[Column]:
    cols = []
    for o in outs:
        values, validity, offsets = o.values, o.validity, o.offsets
        if getattr(values, "ndim", 0) == 0 and offsets is None:
            values = jnp.broadcast_to(values, (capacity,))
        if validity is not None and getattr(validity, "ndim", 1) == 0:
            validity = jnp.broadcast_to(validity, (capacity,))
        cols.append(Column(o.dtype, values, nrows, validity=validity,
                           offsets=offsets))
    return cols


# ANSI check messages per stage signature: the jit cache shares traced
# functions across StageFn instances with the same signature, so messages
# recorded at trace time must be shared the same way.  The canonical dict
# lives in ops/jit_cache.py (STAGE_CHECKS) so the persistent tier can
# serialize messages into entry headers — a warm start that never traces
# still raises the exact ANSI message.
from spark_rapids_tpu.ops.jit_cache import STAGE_CHECKS as _CHECK_MSGS


def raise_failed_checks(messages, flags) -> None:
    """Host-side surfacing of in-trace ANSI checks (Spark ANSI throws)."""
    if flags and any(bool(f) for f in flags):
        failed = [m for m, f in zip(messages, flags) if bool(f)]
        raise ArithmeticError("; ".join(failed) or "ANSI check failed")


def param_args(slots) -> Tuple:
    """Dispatch-time argument vector for a stage's ParamSlots: the
    current binding of each slot as a 0-d storage scalar.  Empty tuple
    (an empty pytree — free at the jit boundary) when the stage has no
    slots, so unparameterized stages pay nothing."""
    return tuple(s.device_value() for s in slots)


def params_dict(slots, params):
    """Traced param arguments -> the slot-index map EmitContext reads.
    Slot INDEX ordering matches :func:`collect_param_slots`, so any
    instance sharing the cached executable builds the same mapping."""
    if not slots:
        return None
    return {s.index: p for s, p in zip(slots, params)}


class StageFn:
    """A compiled per-batch function for a fixed expression forest.

    ``__call__(batch) -> list[Column]`` with the same nrows as the input.
    jax.jit's shape cache gives one XLA executable per capacity bucket.
    """

    def __init__(self, exprs: Sequence[Expression],
                 input_dtypes: Sequence[DataType],
                 donate: bool = False):
        from spark_rapids_tpu.ops.jit_cache import cached_jit
        self.exprs = list(exprs)
        self.input_dtypes = list(input_dtypes)
        self.donate = effective_donate(donate)
        self._slots = collect_param_slots(self.exprs)
        self._sig = ("stage", tuple(e.cache_key() for e in self.exprs),
                     tuple(dt.name for dt in self.input_dtypes),
                     ("donate", self.donate))
        self._jitted = cached_jit(self._sig, lambda: self._run,
                                  **_donate_kwargs(self.donate))

    def _run(self, flat_cols, nrows, params=()):
        capacity = capacity_of(flat_cols) if flat_cols else 0
        inputs = flat_to_colvals(flat_cols, self.input_dtypes)
        ctx = EmitContext(inputs, nrows, capacity,
                          params=params_dict(self._slots, params))
        outs = [e.emit(ctx) for e in self.exprs]
        # messages are static per expression tree: record them at trace
        # time so a failure needs no re-execution
        _CHECK_MSGS[self._sig] = [m for m, _ in ctx.checks]
        return ([(o.values, o.validity, o.offsets) for o in outs],
                tuple(flag for _, flag in ctx.checks))

    def __call__(self, batch: ColumnarBatch) -> List[Column]:
        flat = batch_to_flat(batch)
        # device_i32: a deferred upstream count flows straight into the
        # stage without a host sync
        nrows = batch.row_count.device_i32()
        out_flat, check_flags = self._jitted(flat, nrows,
                                             param_args(self._slots))
        raise_failed_checks(_CHECK_MSGS.get(self._sig, []), check_flags)
        outs = [ColVal(e.dtype, v, validity, offsets)
                for e, (v, validity, offsets) in zip(self.exprs, out_flat)]
        return colvals_to_columns(outs, batch.row_count, batch.capacity)


class FilterStageFn:
    """Fused predicate(s) + compaction: batch -> (columns, new_nrows).

    The predicate and the gather-to-dense run in one XLA computation; only the
    selected-row count syncs back to the host (to set the logical length).

    ``predicate`` may be a LIST of conjuncts in bottom-first chain order
    (whole-stage fusion, exec/fusion.py): each conjunct evaluates with
    the mask of the conjuncts BELOW it as its ANSI check mask, so a
    fused chain's checks fire for exactly the rows the corresponding
    unfused filter stage would have evaluated.  Rows dropped by LATER
    members may skip their checks — the same latitude Spark's optimizer
    takes when collapsing projects and reordering filters; a bad value
    can never reach the output (the final keep mask gates everything).
    """

    def __init__(self, predicate, project: Sequence[Expression],
                 input_dtypes: Sequence[DataType],
                 donate: bool = False):
        from spark_rapids_tpu.ops.jit_cache import cached_jit
        conjuncts = list(predicate) if isinstance(
            predicate, (list, tuple)) else [predicate]
        self.conjuncts = conjuncts  # bottom-first evaluation order
        self.predicate = conjuncts[0]
        self.project = list(project)
        self.input_dtypes = list(input_dtypes)
        self.donate = effective_donate(donate)
        self._slots = collect_param_slots(self.conjuncts + self.project)
        self._sig = ("filter_stage",
                     tuple(p.cache_key() for p in conjuncts),
                     tuple(e.cache_key() for e in self.project),
                     tuple(dt.name for dt in self.input_dtypes),
                     ("donate", self.donate))
        self._jitted = cached_jit(self._sig, lambda: self._run,
                                  **_donate_kwargs(self.donate))

    def _run(self, flat_cols, nrows, params=()):
        from spark_rapids_tpu.ops import selection
        from spark_rapids_tpu.ops.expressions import fold_conjuncts
        capacity = capacity_of(flat_cols)
        inputs = flat_to_colvals(flat_cols, self.input_dtypes)
        ctx = EmitContext(inputs, nrows, capacity,
                          params=params_dict(self._slots, params))
        # projections then evaluate over PRE-filter rows (compaction is
        # one pass at the end): fold_conjuncts leaves the check mask at
        # the survivor set, so ANSI checks only fire for survivors
        keep = fold_conjuncts(ctx, self.conjuncts)
        outs = [e.emit(ctx) for e in self.project]
        # scalar projection outputs (literals, scalar-validity
        # expressions) widen to the capacity before compaction — the
        # gather indexes every buffer, including validity (fused chains
        # project arbitrary expressions here, not just passthroughs)
        outs = [ColVal(o.dtype,
                       jnp.broadcast_to(o.values, (capacity,))
                       if getattr(o.values, "ndim", 0) == 0 and
                       o.offsets is None else o.values,
                       jnp.broadcast_to(o.validity, (capacity,))
                       if o.validity is not None and
                       getattr(o.validity, "ndim", 1) == 0
                       else o.validity, o.offsets)
                for o in outs]
        compacted, new_nrows = selection.compact(outs, keep)
        _CHECK_MSGS[self._sig] = [m for m, _ in ctx.checks]
        return ([(o.values, o.validity, o.offsets) for o in compacted],
                new_nrows, tuple(flag for _, flag in ctx.checks))

    def __call__(self, batch: ColumnarBatch) -> Tuple[List[Column], int]:
        from spark_rapids_tpu.columnar.column import RowCount
        flat = batch_to_flat(batch)
        out_flat, new_nrows, check_flags = self._jitted(
            flat, batch.row_count.device_i32(), param_args(self._slots))
        raise_failed_checks(_CHECK_MSGS.get(self._sig, []), check_flags)
        # the selected-row count is a genuine host decision (empty-batch
        # skip); RowCount makes the sync visible to the accounting
        n = int(RowCount(device=new_nrows))
        outs = [ColVal(e.dtype, v, validity, offsets)
                for e, (v, validity, offsets) in zip(self.project, out_flat)]
        return colvals_to_columns(outs, n, batch.capacity), n
