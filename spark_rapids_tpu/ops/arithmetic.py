"""Arithmetic, math and bitwise expressions.

Coverage target: the reference's ``arithmetic.scala`` (691 LoC),
``mathExpressions.scala`` (472) and ``bitwise.scala`` (149) rule sets
(SURVEY.md Appendix A.1).  ANSI mode is off as in the reference defaults:
integer overflow wraps, division by zero yields null.
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.expressions import (
    BinaryExpression, ColVal, EmitContext, Expression, UnaryExpression,
    cast_value, combine_validity, promote_types,
)


class _DecimalAwareBinary(BinaryExpression):
    """Binary arithmetic with Spark's decimal result-type rules fused
    in: PromotePrecision (operand rescale) + op + CheckOverflow
    (overflow -> null) execute as one emit when either side is a
    decimal (GpuOverrides.scala:824-838 wrapper pair, fused)."""

    _dec_op: str = ""

    def _decimal_mode(self) -> bool:
        return self.left.dtype.is_decimal or self.right.dtype.is_decimal

    def operand_type(self) -> DataType:
        if self._decimal_mode():
            from spark_rapids_tpu.ops import decimal_ops as D
            a, b = self.left.dtype, self.right.dtype
            if a.is_floating or b.is_floating:
                return dts.FLOAT64  # decimal promotes to double
            return D.binary_result(self._dec_op, a, b)
        return super().operand_type()

    @property
    def dtype(self) -> DataType:
        return self.operand_type()

    def emit(self, ctx: EmitContext) -> ColVal:
        if self._decimal_mode() and not (self.left.dtype.is_floating or
                                         self.right.dtype.is_floating):
            from spark_rapids_tpu.ops import decimal_ops as D
            out = D.binary_result(self._dec_op, self.left.dtype,
                                  self.right.dtype)
            return D.emit_binary(self._dec_op, self.left.emit(ctx),
                                 self.right.emit(ctx), out)
        return super().emit(ctx)


class Add(_DecimalAwareBinary):
    _dec_op = "add"

    def eval_values(self, l, r):
        return l + r, None


class Subtract(_DecimalAwareBinary):
    _dec_op = "sub"

    def eval_values(self, l, r):
        return l - r, None


class Multiply(_DecimalAwareBinary):
    _dec_op = "mul"

    def eval_values(self, l, r):
        return l * r, None


class Divide(_DecimalAwareBinary):
    """Spark `/`: double (fractional) division — decimal division when
    both sides are decimal-convertible; x/0 -> null."""

    _dec_op = "div"

    def operand_type(self) -> DataType:
        if self._decimal_mode():
            return super().operand_type()
        return dts.FLOAT64

    def eval_values(self, l, r):
        zero = r == 0
        safe = jnp.where(zero, 1.0, r)
        return l / safe, jnp.logical_not(zero)


class IntegralDivide(BinaryExpression):
    """Spark `div`: long division; x div 0 -> null."""

    def operand_type(self) -> DataType:
        return dts.INT64

    @property
    def dtype(self):
        return dts.INT64

    def eval_values(self, l, r):
        zero = r == 0
        safe = jnp.where(zero, 1, r)
        # Spark truncates toward zero; jnp // floors. Adjust.
        q = l // safe
        rem = l - q * safe
        q = jnp.where((rem != 0) & ((l < 0) != (safe < 0)), q + 1, q)
        return q, jnp.logical_not(zero)


class Remainder(BinaryExpression):
    """Spark `%`: sign follows dividend; x % 0 -> null."""

    def eval_values(self, l, r):
        zero = r == 0
        safe = jnp.where(zero, 1, r)
        if jnp.issubdtype(l.dtype, jnp.integer):
            # truncated-division remainder (Java % semantics): sign of dividend
            m = jnp.mod(l, safe)  # floored
            rem = jnp.where((m != 0) & ((l < 0) != (safe < 0)), m - safe, m)
        else:
            rem = jnp.fmod(l, safe)
        return rem, jnp.logical_not(zero)


class Pmod(BinaryExpression):
    """Positive modulus; x pmod 0 -> null."""

    def eval_values(self, l, r):
        zero = r == 0
        safe = jnp.where(zero, 1, r)
        m = jnp.mod(l, safe)  # floored mod: sign follows divisor
        m = jnp.where(m < 0, m + jnp.abs(safe), m)
        return m, jnp.logical_not(zero)


class UnaryMinus(UnaryExpression):
    def eval_values(self, v, cv):
        return -v


class UnaryPositive(UnaryExpression):
    def eval_values(self, v, cv):
        return v


class Abs(UnaryExpression):
    def eval_values(self, v, cv):
        return jnp.abs(v)


# ------------------------------------------------------------------ math fns --

class _MathUnary(UnaryExpression):
    """Double-typed unary math fn (reference CudfUnaryMathExpression)."""

    fn = None

    @property
    def dtype(self):
        return dts.FLOAT64

    def emit(self, ctx: EmitContext) -> ColVal:
        c = cast_value(self.child.emit(ctx), dts.FLOAT64)
        return ColVal(self.dtype, type(self).fn(c.values), c.validity)


class Sqrt(_MathUnary):
    fn = staticmethod(jnp.sqrt)


class Cbrt(_MathUnary):
    fn = staticmethod(jnp.cbrt)


class Exp(_MathUnary):
    fn = staticmethod(jnp.exp)


class Expm1(_MathUnary):
    fn = staticmethod(jnp.expm1)


class Log(_MathUnary):
    fn = staticmethod(jnp.log)


class Log2(_MathUnary):
    fn = staticmethod(jnp.log2)


class Log10(_MathUnary):
    fn = staticmethod(jnp.log10)


class Log1p(_MathUnary):
    fn = staticmethod(jnp.log1p)


class Sin(_MathUnary):
    fn = staticmethod(jnp.sin)


class Cos(_MathUnary):
    fn = staticmethod(jnp.cos)


class Tan(_MathUnary):
    fn = staticmethod(jnp.tan)


class Cot(_MathUnary):
    fn = staticmethod(lambda v: 1.0 / jnp.tan(v))


class Asin(_MathUnary):
    fn = staticmethod(jnp.arcsin)


class Acos(_MathUnary):
    fn = staticmethod(jnp.arccos)


class Atan(_MathUnary):
    fn = staticmethod(jnp.arctan)


class Sinh(_MathUnary):
    fn = staticmethod(jnp.sinh)


class Cosh(_MathUnary):
    fn = staticmethod(jnp.cosh)


class Tanh(_MathUnary):
    fn = staticmethod(jnp.tanh)


class Asinh(_MathUnary):
    fn = staticmethod(jnp.arcsinh)


class Acosh(_MathUnary):
    fn = staticmethod(jnp.arccosh)


class Atanh(_MathUnary):
    fn = staticmethod(jnp.arctanh)


class ToDegrees(_MathUnary):
    fn = staticmethod(jnp.degrees)


class ToRadians(_MathUnary):
    fn = staticmethod(jnp.radians)


class Rint(_MathUnary):
    fn = staticmethod(jnp.rint)


class Signum(_MathUnary):
    fn = staticmethod(jnp.sign)


class Floor(UnaryExpression):
    @property
    def dtype(self):
        return dts.INT64 if self.child.dtype.is_floating else self.child.dtype

    def eval_values(self, v, cv):
        if self.child.dtype.is_floating:
            return jnp.floor(v).astype(jnp.int64)
        return v


class Ceil(UnaryExpression):
    @property
    def dtype(self):
        return dts.INT64 if self.child.dtype.is_floating else self.child.dtype

    def eval_values(self, v, cv):
        if self.child.dtype.is_floating:
            return jnp.ceil(v).astype(jnp.int64)
        return v


class Pow(BinaryExpression):
    def operand_type(self):
        return dts.FLOAT64

    def eval_values(self, l, r):
        return jnp.power(l, r), None


class Logarithm(BinaryExpression):
    """log(base, x)."""

    def operand_type(self):
        return dts.FLOAT64

    def eval_values(self, l, r):
        return jnp.log(r) / jnp.log(l), None


class Atan2(BinaryExpression):
    def operand_type(self):
        return dts.FLOAT64

    def eval_values(self, l, r):
        return jnp.arctan2(l, r), None


class Hypot(BinaryExpression):
    """sqrt(l^2 + r^2) without intermediate overflow (Spark HYPOT)."""

    def operand_type(self):
        return dts.FLOAT64

    def eval_values(self, l, r):
        return jnp.hypot(l, r), None


class _RoundBase(Expression):
    def __init__(self, child: Expression, scale: int = 0):
        self.children = (child,)
        self.scale = int(scale)

    @property
    def child(self):
        return self.children[0]

    def with_children(self, children):
        return type(self)(children[0], self.scale)

    @property
    def dtype(self):
        return self.child.dtype

    def cache_key(self):
        return (type(self).__name__, self.scale, self.child.cache_key())


class Round(_RoundBase):
    """HALF_UP rounding (Spark Round)."""

    def emit(self, ctx):
        c = self.child.emit(ctx)
        v = c.values
        if self.child.dtype.is_floating:
            f = 10.0 ** self.scale
            out = jnp.trunc(jnp.abs(v) * f + 0.5) / f * jnp.sign(v)
        elif self.scale >= 0:
            out = v
        else:
            f = 10 ** (-self.scale)
            half = f // 2
            out = (jnp.abs(v) + half) // f * f * jnp.sign(v)
        return ColVal(self.dtype, out.astype(self.dtype.storage), c.validity)


class BRound(_RoundBase):
    """HALF_EVEN (banker's) rounding (Spark BRound)."""

    def emit(self, ctx):
        c = self.child.emit(ctx)
        v = c.values
        if self.child.dtype.is_floating:
            f = 10.0 ** self.scale
            out = jnp.round(v * f) / f  # jnp.round is half-even
        elif self.scale >= 0:
            out = v
        else:
            f = 10 ** (-self.scale)
            q, rem = v // f, v % f
            half = f / 2.0
            round_up = (rem > half) | ((rem == half) & (q % 2 != 0))
            out = (q + round_up.astype(v.dtype)) * f
        return ColVal(self.dtype, out.astype(self.dtype.storage), c.validity)


# ------------------------------------------------------------------- bitwise --

class BitwiseAnd(BinaryExpression):
    def eval_values(self, l, r):
        return l & r, None


class BitwiseOr(BinaryExpression):
    def eval_values(self, l, r):
        return l | r, None


class BitwiseXor(BinaryExpression):
    def eval_values(self, l, r):
        return l ^ r, None


class BitwiseNot(UnaryExpression):
    def eval_values(self, v, cv):
        return ~v


class _ShiftBase(BinaryExpression):
    """Java shift semantics: shift amount masked by value bit-width."""

    def operand_type(self):
        return self.left.dtype

    def emit(self, ctx: EmitContext) -> ColVal:
        l = self.left.emit(ctx)
        r = self.right.emit(ctx)
        bits = l.values.dtype.itemsize * 8
        amount = r.values.astype(jnp.int32) & (bits - 1)
        values = self.shift(l.values, amount)
        return ColVal(self.dtype, values,
                      combine_validity(l.validity, r.validity))


class ShiftLeft(_ShiftBase):
    def shift(self, v, amount):
        return v << amount.astype(v.dtype)


class ShiftRight(_ShiftBase):
    def shift(self, v, amount):
        return v >> amount.astype(v.dtype)


class ShiftRightUnsigned(_ShiftBase):
    def shift(self, v, amount):
        unsigned = v.view(jnp.uint32 if v.dtype.itemsize == 4 else jnp.uint64)
        return (unsigned >> amount.astype(unsigned.dtype)).view(v.dtype)


# -------------------------------------------------------------------- random --

class Rand(Expression):
    """rand([seed]) — uniform [0,1) double, seeded per batch + row position.

    TPU-first: threefry via jax.random keyed on (seed, batch ordinal).
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)

    @property
    def dtype(self):
        return dts.FLOAT64

    @property
    def nullable(self):
        return False

    def emit(self, ctx: EmitContext) -> ColVal:
        import jax
        key = jax.random.PRNGKey(self.seed)
        vals = jax.random.uniform(key, (ctx.capacity,), dtype=jnp.float64)
        return ColVal(self.dtype, vals)

    def cache_key(self):
        return ("Rand", self.seed)
