"""Date/time expressions — pure integer math on date32 days / int64 micros.

Coverage target: the reference's ``datetimeExpressions.scala`` (1,040 LoC,
SURVEY.md Appendix A.1 "Date/time").  UTC only, like the reference
(Appendix B "Timestamps: UTC only").  Calendar conversion uses the civil-
from-days algorithm (Euclidean affine transforms), which is branch-free and
vectorizes cleanly on the VPU.
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.ops.cast import US_PER_DAY, US_PER_SEC
from spark_rapids_tpu.ops.expressions import (
    BinaryExpression, ColVal, EmitContext, Expression, UnaryExpression,
    cast_value, combine_validity,
)


def _civil_from_days(z):
    """days since 1970-01-01 -> (year, month, day), proleptic Gregorian."""
    z = z.astype(jnp.int64) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097                                  # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)          # [0, 365]
    mp = (5 * doy + 2) // 153                                # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1                        # [1, 31]
    m = jnp.where(mp < 10, mp + 3, mp - 9)                   # [1, 12]
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def _days_from_civil(y, m, d):
    y = y - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = 365 * yoe + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _to_days(c: ColVal):
    if c.dtype.is_timestamp:
        return c.values // US_PER_DAY
    return c.values.astype(jnp.int64)


class _DatePart(UnaryExpression):
    @property
    def dtype(self):
        return dts.INT32

    def eval_values(self, v, cv):
        y, m, d = _civil_from_days(_to_days(cv))
        return self.part(y, m, d, _to_days(cv)).astype(jnp.int32)


class Year(_DatePart):
    def part(self, y, m, d, days):
        return y


class Month(_DatePart):
    def part(self, y, m, d, days):
        return m


class DayOfMonth(_DatePart):
    def part(self, y, m, d, days):
        return d


class Quarter(_DatePart):
    def part(self, y, m, d, days):
        return (m - 1) // 3 + 1


class DayOfWeek(_DatePart):
    """1 = Sunday ... 7 = Saturday (Spark)."""

    def part(self, y, m, d, days):
        return (days + 4) % 7 + 1


class WeekDay(_DatePart):
    """0 = Monday ... 6 = Sunday (Spark weekday)."""

    def part(self, y, m, d, days):
        return (days + 3) % 7


class DayOfYear(_DatePart):
    def part(self, y, m, d, days):
        jan1 = _days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
        return (days - jan1 + 1).astype(jnp.int32)


class LastDay(UnaryExpression):
    """Last day of the month, as a date."""

    @property
    def dtype(self):
        return dts.DATE32

    def eval_values(self, v, cv):
        y, m, d = _civil_from_days(_to_days(cv))
        ny = jnp.where(m == 12, y + 1, y)
        nm = jnp.where(m == 12, 1, m + 1)
        first_next = _days_from_civil(ny, nm, jnp.ones_like(d))
        return (first_next - 1).astype(jnp.int32)


_DOW_NAMES = {"mo": 0, "tu": 1, "we": 2, "th": 3, "fr": 4, "sa": 5,
              "su": 6}  # Monday=0 ... Sunday=6 (ISO)


class NextDay(UnaryExpression):
    """First date later than the input that falls on the given weekday
    (Spark next_day; the day-of-week argument must be a literal — the
    reference requires a literal too, GpuOverrides dateExpressions)."""

    def __init__(self, child, day_of_week: str):
        super().__init__(child)
        self.day_of_week = str(day_of_week)
        key = self.day_of_week.strip().lower()[:2]
        self.target = _DOW_NAMES.get(key)  # None = invalid -> all null

    def with_children(self, children):
        return type(self)(children[0], self.day_of_week)

    def cache_key(self):
        return (type(self).__name__, self.day_of_week,
                self.child.cache_key())

    @property
    def dtype(self):
        return dts.DATE32

    def emit(self, ctx):
        c = self.child.emit(ctx)
        days = _to_days(c)
        if self.target is None:  # Spark returns null for bad names
            zeros = jnp.zeros(ctx.capacity, dtype=jnp.int32)
            return ColVal(dts.DATE32, zeros,
                          jnp.zeros(ctx.capacity, dtype=jnp.bool_))
        # 1970-01-01 was a Thursday: ISO dow (Mon=0) = (days + 3) % 7
        dow = jnp.mod(days + 3, 7)
        ahead = jnp.mod(self.target - dow + 7, 7)
        ahead = jnp.where(ahead == 0, 7, ahead)  # strictly later
        return ColVal(dts.DATE32, (days + ahead).astype(jnp.int32),
                      c.validity)


class Hour(UnaryExpression):
    @property
    def dtype(self):
        return dts.INT32

    def eval_values(self, v, cv):
        return (jnp.mod(v, US_PER_DAY) // 3_600_000_000).astype(jnp.int32)


class Minute(UnaryExpression):
    @property
    def dtype(self):
        return dts.INT32

    def eval_values(self, v, cv):
        return (jnp.mod(v, 3_600_000_000) // 60_000_000).astype(jnp.int32)


class Second(UnaryExpression):
    @property
    def dtype(self):
        return dts.INT32

    def eval_values(self, v, cv):
        return (jnp.mod(v, 60_000_000) // US_PER_SEC).astype(jnp.int32)


class DateAdd(BinaryExpression):
    """date_add(date, n_days)."""

    @property
    def dtype(self):
        return dts.DATE32

    def emit(self, ctx: EmitContext) -> ColVal:
        l = self.left.emit(ctx)
        r = self.right.emit(ctx)
        out = l.values.astype(jnp.int32) + r.values.astype(jnp.int32)
        return ColVal(dts.DATE32, out,
                      combine_validity(l.validity, r.validity))


class DateSub(BinaryExpression):
    @property
    def dtype(self):
        return dts.DATE32

    def emit(self, ctx: EmitContext) -> ColVal:
        l = self.left.emit(ctx)
        r = self.right.emit(ctx)
        out = l.values.astype(jnp.int32) - r.values.astype(jnp.int32)
        return ColVal(dts.DATE32, out,
                      combine_validity(l.validity, r.validity))


class DateDiff(BinaryExpression):
    """datediff(end, start) in days."""

    @property
    def dtype(self):
        return dts.INT32

    def emit(self, ctx: EmitContext) -> ColVal:
        l = self.left.emit(ctx)
        r = self.right.emit(ctx)
        out = (_to_days(l) - _to_days(r)).astype(jnp.int32)
        return ColVal(dts.INT32, out,
                      combine_validity(l.validity, r.validity))


class AddMonths(BinaryExpression):
    @property
    def dtype(self):
        return dts.DATE32

    def emit(self, ctx: EmitContext) -> ColVal:
        l = self.left.emit(ctx)
        r = self.right.emit(ctx)
        y, m, d = _civil_from_days(_to_days(l))
        months = y * 12 + (m - 1) + r.values.astype(jnp.int64)
        ny, nm = months // 12, months % 12 + 1
        # clamp day to target month length
        nny = jnp.where(nm == 12, ny + 1, ny)
        nnm = jnp.where(nm == 12, 1, nm + 1)
        month_len = (_days_from_civil(nny, nnm, jnp.ones_like(d)) -
                     _days_from_civil(ny, nm, jnp.ones_like(d)))
        nd = jnp.minimum(d, month_len)
        out = _days_from_civil(ny, nm, nd).astype(jnp.int32)
        return ColVal(dts.DATE32, out,
                      combine_validity(l.validity, r.validity))


class MonthsBetween(BinaryExpression):
    @property
    def dtype(self):
        return dts.FLOAT64

    def emit(self, ctx: EmitContext) -> ColVal:
        a = self.left.emit(ctx)
        b = self.right.emit(ctx)
        ya, ma, da = _civil_from_days(_to_days(a))
        yb, mb, db = _civil_from_days(_to_days(b))
        whole = (ya * 12 + ma) - (yb * 12 + mb)
        frac = (da - db).astype(jnp.float64) / 31.0
        out = whole.astype(jnp.float64) + frac
        return ColVal(dts.FLOAT64, out,
                      combine_validity(a.validity, b.validity))


class TruncDate(Expression):
    """trunc(date, fmt) for fmt in year/month/week/quarter."""

    def __init__(self, child: Expression, fmt: str):
        self.children = (child,)
        self.fmt = fmt.lower()

    @property
    def child(self):
        return self.children[0]

    def with_children(self, children):
        return TruncDate(children[0], self.fmt)

    @property
    def dtype(self):
        return dts.DATE32

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        days = _to_days(c)
        y, m, d = _civil_from_days(days)
        one = jnp.ones_like(m)
        if self.fmt in ("year", "yyyy", "yy"):
            out = _days_from_civil(y, one, one)
        elif self.fmt in ("month", "mon", "mm"):
            out = _days_from_civil(y, m, one)
        elif self.fmt == "quarter":
            qm = ((m - 1) // 3) * 3 + 1
            out = _days_from_civil(y, qm, one)
        elif self.fmt == "week":
            out = days - (days + 3) % 7  # Monday
        else:
            raise ValueError(f"unsupported trunc format {self.fmt}")
        return ColVal(dts.DATE32, out.astype(jnp.int32), c.validity)

    def cache_key(self):
        return ("TruncDate", self.fmt, self.child.cache_key())


class UnixTimestamp(UnaryExpression):
    """to_unix_timestamp(ts_or_date) -> seconds."""

    @property
    def dtype(self):
        return dts.INT64

    def eval_values(self, v, cv):
        if cv.dtype.is_date:
            return v.astype(jnp.int64) * 86_400
        return v // US_PER_SEC


class FromUnixTime(UnaryExpression):
    """seconds -> timestamp (formatting to string is a separate cast)."""

    @property
    def dtype(self):
        return dts.TIMESTAMP_US

    def eval_values(self, v, cv):
        return v.astype(jnp.int64) * US_PER_SEC


class TimeAdd(BinaryExpression):
    """timestamp + interval microseconds (literal)."""

    @property
    def dtype(self):
        return dts.TIMESTAMP_US

    def emit(self, ctx: EmitContext) -> ColVal:
        l = self.left.emit(ctx)
        r = self.right.emit(ctx)
        return ColVal(dts.TIMESTAMP_US, l.values + r.values.astype(jnp.int64),
                      combine_validity(l.validity, r.validity))


class DateFormatClass(UnaryExpression):
    """date_format(ts_or_date, pattern) — device string production for
    fixed-width patterns (yyyy/MM/dd/HH/mm/ss plus literal separators,
    the reference's GpuDateFormatClass common cases); other pattern
    letters tag off to CPU fallback via ``supported``."""

    _TOKENS = {"yyyy": 4, "MM": 2, "dd": 2, "HH": 2, "mm": 2, "ss": 2}

    def __init__(self, child: Expression, fmt: str):
        super().__init__(child)
        self.fmt = fmt
        self.tokens = []  # ("tok", name) | ("lit", byte)
        self.supported = True
        i = 0
        while i < len(fmt):
            for tok in ("yyyy", "MM", "dd", "HH", "mm", "ss"):
                if fmt.startswith(tok, i):
                    self.tokens.append(("tok", tok))
                    i += len(tok)
                    break
            else:
                ch = fmt[i]
                if ch.isalpha():
                    self.supported = False  # unknown pattern letter
                self.tokens.append(("lit", ord(ch) & 0x7F))
                i += 1
        self.width = sum(self._TOKENS[t] if k == "tok" else 1
                         for k, t in self.tokens)

    def with_children(self, children):
        return DateFormatClass(children[0], self.fmt)

    @property
    def dtype(self):
        return dts.STRING

    def cache_key(self):
        return ("DateFormatClass", self.child.cache_key(), self.fmt)

    def emit(self, ctx: EmitContext) -> ColVal:
        cv = self.child.emit(ctx)
        days = _to_days(cv)
        y, m, d = _civil_from_days(days)
        if cv.dtype.is_timestamp:
            rem = jnp.mod(cv.values, US_PER_DAY)
            hh = rem // 3_600_000_000
            mi = jnp.mod(rem, 3_600_000_000) // 60_000_000
            ss = jnp.mod(rem, 60_000_000) // US_PER_SEC
        else:
            hh = mi = ss = jnp.zeros_like(days)
        vals = {"yyyy": jnp.clip(y, 0, 9999), "MM": m, "dd": d,
                "HH": hh, "mm": mi, "ss": ss}
        cols = []
        for k, t in self.tokens:
            if k == "lit":
                cols.append(jnp.full(ctx.capacity, t, dtype=jnp.uint8))
            else:
                v = vals[t].astype(jnp.int64)
                w = self._TOKENS[t]
                for p in range(w - 1, -1, -1):
                    digit = jnp.mod(v // (10 ** p), 10)
                    cols.append((digit + ord("0")).astype(jnp.uint8))
        mat = jnp.stack(cols, axis=1)  # [cap, width]
        chars = mat.reshape(-1)
        ccap = 1
        while ccap < chars.shape[0]:
            ccap <<= 1
        if ccap > chars.shape[0]:
            chars = jnp.concatenate(
                [chars, jnp.zeros(ccap - chars.shape[0],
                                  dtype=jnp.uint8)])
        offsets = (jnp.arange(ctx.capacity + 1, dtype=jnp.int32)
                   * self.width)
        return ColVal(dts.STRING, chars, cv.validity, offsets)


class TimeWindow(UnaryExpression):
    """window(ts, windowDuration[, slideDuration[, startTime]]) bucket
    edge (GpuTimeWindow analog): floor the timestamp to its slide bucket
    and emit the start or end edge.  ``functions.window`` wraps a pair
    of these into the (start, end) struct."""

    def __init__(self, child: Expression, window_us: int, slide_us: int,
                 start_us: int = 0, field: str = "start",
                 shift_us: int = 0):
        super().__init__(child)
        self.window_us = int(window_us)
        self.slide_us = int(slide_us)
        self.start_us = int(start_us)
        self.field = field
        # sliding windows: the i-th overlapping window is the slide
        # bucket shifted back by i slides (Spark expands rows per
        # overlap via Expand; functions.window wires that up)
        self.shift_us = int(shift_us)

    def with_children(self, children):
        return TimeWindow(children[0], self.window_us, self.slide_us,
                          self.start_us, self.field, self.shift_us)

    @property
    def dtype(self):
        return dts.TIMESTAMP_US

    def cache_key(self):
        return ("TimeWindow", self.child.cache_key(), self.window_us,
                self.slide_us, self.start_us, self.field, self.shift_us)

    def eval_values(self, v, cv):
        ts = v.astype(jnp.int64) * 86_400 * 1_000_000 \
            if cv.dtype.is_date else v.astype(jnp.int64)
        off = jnp.mod(ts - self.start_us, self.slide_us)
        start = ts - off - self.shift_us
        if self.field == "start":
            return start
        return start + self.window_us


class ToUnixTimestamp(UnaryExpression):
    """to_unix_timestamp(x): strings parse via the string->timestamp
    cast; dates/timestamps convert directly.  Resolves at bind time into
    ``UnixTimestamp`` (optionally over a Cast), so the planner only ever
    sees registered expressions."""

    @property
    def dtype(self):
        return dts.INT64

    def bind(self, schema):
        from spark_rapids_tpu.ops.cast import Cast
        bound = self.child.bind(schema)
        if bound.dtype.is_string:
            bound = Cast(bound, dts.TIMESTAMP_US)
        return UnixTimestamp(bound)
