"""Expression trees and the traced column-value representation.

Counterpart of ``GpuExpressions.scala:113-425`` (`GpuExpression` hierarchy and
``columnarEval``), re-designed for XLA tracing: instead of each expression
issuing a cudf kernel per batch, ``Expression.emit(ctx)`` runs *inside a jax
trace* and returns a :class:`ColVal`; an operator's whole expression forest
therefore lowers into one fused XLA computation per stage
(see ``ops/compiler.py``).

Null semantics follow Spark SQL: null-propagating binary ops, Kleene logic for
AND/OR, null on division by zero, etc.  Validity is a dense bool array (or
``None`` = all valid) carried alongside the value array.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.dtypes import DataType


@dataclasses.dataclass
class ColVal:
    """A column value inside a trace: values + optional validity (+ offsets).

    ``values`` is a (capacity,) array, or a 0-d array for scalar literals —
    broadcasting against row arrays is left to jnp.  For strings ``values``
    holds uint8 chars and ``offsets`` the int32 row offsets.
    """
    dtype: DataType
    values: Any
    validity: Optional[Any] = None   # bool array, None = all valid
    offsets: Optional[Any] = None    # strings only

    @property
    def is_scalar(self) -> bool:
        return getattr(self.values, "ndim", 0) == 0 and self.offsets is None


import jax.tree_util as _tree_util

_tree_util.register_pytree_node(
    ColVal,
    lambda c: ((c.values, c.validity, c.offsets), c.dtype),
    lambda dtype, children: ColVal(dtype, children[0], children[1],
                                   children[2]))


def combine_validity(*vs: Optional[Any]) -> Optional[Any]:
    """AND together validity masks, treating None as all-valid."""
    present = [v for v in vs if v is not None]
    if not present:
        return None
    out = present[0]
    for v in present[1:]:
        out = jnp.logical_and(out, v)
    return out


class EmitContext:
    """Per-trace state handed to ``Expression.emit``.

    ``inputs``: ColVal per input ordinal (the operator's child output).
    ``nrows``: traced int32 scalar — logical row count of the batch.
    ``capacity``: static int — the shape bucket.
    """

    def __init__(self, inputs: Sequence[ColVal], nrows, capacity: int,
                 params: Optional[Dict[int, Any]] = None):
        self.inputs = list(inputs)
        self.nrows = nrows
        self.capacity = capacity
        # hoisted-literal bindings: slot index -> traced 0-d scalar.
        # Stages compiled from a parameterized template pass their
        # ParamSlot values here as runtime arguments, so the SAME
        # executable serves every literal binding (zero retrace).
        self.params = params
        # (message, traced bool scalar) pairs appended by ANSI-mode
        # expressions; stage runners surface them and raise host-side
        # (Spark ANSI throws, GpuCast ansi mode)
        self.checks = []
        # fused stages evaluate downstream expressions over PRE-filter
        # rows (the predicate travels as a mask, compaction happens
        # once at the stage boundary): the stage sets this to its keep
        # mask so ANSI checks only fire for rows that SURVIVE — exactly
        # the rows the unfused plan would have evaluated.  Checks only;
        # value semantics are untouched (dropped rows never reach the
        # output either way).
        self.extra_check_mask = None

    def add_check(self, message: str, failed) -> None:
        self.checks.append((message, failed))

    def row_mask(self):
        """bool[capacity], True for rows < nrows (padding mask)."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.nrows

    def check_mask(self):
        """Rows whose failures ANSI checks may report: live rows, minus
        rows a fused upstream filter already dropped."""
        m = self.row_mask()
        if self.extra_check_mask is not None:
            m = jnp.logical_and(m, self.extra_check_mask)
        return m


def fold_conjuncts(ctx: "EmitContext", conds) -> "jnp.ndarray":
    """AND a BOTTOM-FIRST conjunct list into one keep mask with
    progressive ANSI-check masking: each conjunct (and, afterwards,
    anything else emitted under ``ctx``) only checks rows the conjuncts
    below it kept — exactly the rows the corresponding unfused filter
    stages would have evaluated.  The one shared implementation for
    every fused-stage body (FilterStageFn, the aggregate pre-filter,
    the distributed fused kernels): the masking discipline must not be
    able to diverge between them.  Leaves ``ctx.extra_check_mask`` set
    to the returned mask."""
    mask = ctx.row_mask()
    for p in conds:
        ctx.extra_check_mask = mask
        pred = p.emit(ctx)
        keep = pred.values
        if getattr(keep, "ndim", 0) == 0:
            keep = jnp.broadcast_to(keep, (ctx.capacity,))
        if pred.validity is not None:
            keep = jnp.logical_and(keep, pred.validity)
        mask = jnp.logical_and(mask, keep)
    ctx.extra_check_mask = mask
    return mask


class Expression:
    """Base class. Subclasses define ``children`` and are immutable after bind."""

    children: Tuple["Expression", ...] = ()

    # ---- resolution ----------------------------------------------------------
    @property
    def dtype(self) -> DataType:
        raise NotImplementedError

    @property
    def nullable(self) -> bool:
        return any(c.nullable for c in self.children)

    @property
    def resolved(self) -> bool:
        return all(c.resolved for c in self.children)

    def bind(self, schema: Sequence[Tuple[str, DataType]]) -> "Expression":
        """Resolve column names to ordinals recursively."""
        new_children = [c.bind(schema) for c in self.children]
        return self.with_children(new_children)

    def with_children(self, children: Sequence["Expression"]) -> "Expression":
        if not self.children:
            return self
        raise NotImplementedError(
            f"{type(self).__name__} must implement with_children")

    # ---- evaluation ----------------------------------------------------------
    def emit(self, ctx: EmitContext) -> ColVal:
        raise NotImplementedError(type(self).__name__)

    # ---- misc ----------------------------------------------------------------
    @property
    def name(self) -> str:
        """Output name when this expression is projected without an alias."""
        return str(self)

    def cache_key(self) -> Tuple:
        """Structural identity used by the stage-compiler cache."""
        return (type(self).__name__,
                tuple(c.cache_key() for c in self.children))

    def references(self) -> List[str]:
        out: List[str] = []
        for c in self.children:
            out.extend(c.references())
        return out

    def __str__(self) -> str:
        args = ", ".join(str(c) for c in self.children)
        return f"{type(self).__name__}({args})"


# ------------------------------------------------------------------- leaves --

class UnresolvedColumn(Expression):
    def __init__(self, col_name: str):
        self.col_name = col_name

    @property
    def dtype(self) -> DataType:
        raise RuntimeError(f"unresolved column {self.col_name}")

    @property
    def nullable(self) -> bool:
        raise RuntimeError(f"unresolved column {self.col_name}")

    @property
    def resolved(self) -> bool:
        return False

    def bind(self, schema) -> "Expression":
        for i, (name, dt) in enumerate(schema):
            if name == self.col_name:
                return BoundReference(i, dt, name=name)
        # a bare reference to a shredded MAP column denotes its key
        # array (size(m), explode cardinality, ...); whole-map/struct
        # projection expands earlier, in select()
        from spark_rapids_tpu.columnar.nested import (
            MAP_KEY_SUFFIX, is_shredded_map)
        flat = [n for n, _ in schema]
        if is_shredded_map(self.col_name, flat):
            alt = self.col_name + MAP_KEY_SUFFIX
            for i, (name, dt) in enumerate(schema):
                if name == alt:
                    return BoundReference(i, dt, name=name)
        members = [n for n in flat if n.startswith(self.col_name + ".")]
        if members:
            raise KeyError(
                f"column {self.col_name!r} is a shredded struct "
                f"({members}); access fields via getField or select it "
                "whole")
        raise KeyError(
            f"column {self.col_name!r} not in schema {flat}")

    @property
    def name(self) -> str:
        return self.col_name

    def references(self):
        return [self.col_name]

    def cache_key(self):
        return ("UnresolvedColumn", self.col_name)

    def __str__(self):
        return f"'{self.col_name}"


class BoundReference(Expression):
    """Input column by ordinal (GpuBoundAttribute.scala:125 analog)."""

    def __init__(self, ordinal: int, dtype: DataType, name: str = "",
                 nullable: bool = True):
        self.ordinal = ordinal
        self._dtype = dtype
        self._name = name
        self._nullable = nullable

    @property
    def dtype(self) -> DataType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self._nullable

    @property
    def name(self) -> str:
        return self._name or f"c{self.ordinal}"

    def emit(self, ctx: EmitContext) -> ColVal:
        return ctx.inputs[self.ordinal]

    def references(self):
        return [self._name] if self._name else []

    def cache_key(self):
        return ("BoundReference", self.ordinal, self._dtype.name)

    def __str__(self):
        return f"input[{self.ordinal}, {self._dtype}]"


class Literal(Expression):
    def __init__(self, value, dtype: Optional[DataType] = None):
        self.value = value
        if dtype is None:
            dtype = _infer_literal_type(value)
        self._dtype = dtype

    @property
    def dtype(self) -> DataType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self.value is None

    def emit(self, ctx: EmitContext) -> ColVal:
        if self.value is None:
            zeros = jnp.zeros((), dtype=self._dtype.storage)
            return ColVal(self._dtype, zeros,
                          validity=jnp.zeros((), dtype=jnp.bool_))
        if self._dtype.is_string:
            data = np.frombuffer(str(self.value).encode("utf-8"),
                                 dtype=np.uint8)
            offs = jnp.asarray(
                np.array([0, len(data)], dtype=np.int32))
            return ColVal(self._dtype, jnp.asarray(data), offsets=offs)
        v = literal_storage_value(self.value, self._dtype)
        return ColVal(self._dtype, jnp.asarray(v, dtype=self._dtype.storage))

    @property
    def name(self) -> str:
        return str(self.value)

    def cache_key(self):
        return ("Literal", self._dtype.name, self.value)

    def __str__(self):
        return f"lit({self.value!r})"


def literal_storage_value(value, dtype: DataType):
    """Host value -> its storage representation, exactly the conversion
    ``Literal.emit`` bakes into a trace (timestamp/date strings
    normalize to their integer storage).  Shared with ``ParamSlot`` so
    a hoisted literal binds to the bit-identical scalar the inline
    literal would have traced as a constant."""
    if dtype.is_timestamp and not isinstance(value, (int, np.integer)):
        return np.datetime64(value, "us").astype(np.int64)
    if dtype.is_date and not isinstance(value, (int, np.integer)):
        return np.datetime64(value, "D").astype(np.int32)
    return value


class ParamSlot(Expression):
    """A hoisted literal: a typed parameter position in a plan template.

    ``cache_key`` is VALUE-FREE — stages compiled from a parameterized
    template share one signature across all literal bindings, and the
    slot evaluates to a runtime scalar argument (``ctx.params[index]``)
    inside the trace instead of a baked-in constant.  The current
    binding lives on the slot (``bind_value``) so dispatch can collect
    the argument vector; ``device_value()`` converts it exactly the way
    ``Literal.emit`` would have traced it.  Evaluating a slot in a
    context with no params is a hard error, never a stale answer.
    """

    def __init__(self, index: int, dtype: DataType, value=None):
        self.index = index
        self._dtype = dtype
        self.value = value

    @property
    def dtype(self) -> DataType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return False

    def bind_value(self, value) -> None:
        self.value = value

    def device_value(self):
        """Current binding as the 0-d storage-dtype scalar the kernels
        consume (the dispatch-time argument for this slot)."""
        v = literal_storage_value(self.value, self._dtype)
        return jnp.asarray(v, dtype=self._dtype.storage)

    def emit(self, ctx: EmitContext) -> ColVal:
        if ctx.params is None or self.index not in ctx.params:
            raise RuntimeError(
                f"ParamSlot ${self.index} evaluated in a stage that "
                "does not thread template parameters (ctx.params "
                "missing) — refusing rather than baking a stale value")
        return ColVal(self._dtype, ctx.params[self.index])

    @property
    def name(self) -> str:
        return f"$p{self.index}"

    def cache_key(self):
        return ("Param", self.index, self._dtype.name)

    def __str__(self):
        return f"$p{self.index}:{self._dtype.name}"


def collect_param_slots(exprs) -> List["ParamSlot"]:
    """Unique ParamSlots in an expression forest, ordered by slot index
    (the dispatch argument order — deterministic for a given template
    regardless of which instance compiled the shared stage)."""
    slots: Dict[int, ParamSlot] = {}

    def walk(e: Expression) -> None:
        if isinstance(e, ParamSlot):
            slots.setdefault(e.index, e)
        for c in e.children:
            walk(c)

    for e in exprs:
        if e is not None:
            walk(e)
    return [slots[i] for i in sorted(slots)]


def _infer_literal_type(value) -> DataType:
    if value is None:
        raise ValueError("null literal needs an explicit dtype")
    if isinstance(value, bool):
        return dts.BOOL
    if isinstance(value, (int, np.integer)):
        return dts.INT64 if not isinstance(value, np.int32) else dts.INT32
    if isinstance(value, (float, np.floating)):
        return dts.FLOAT64
    if isinstance(value, str):
        return dts.STRING
    if isinstance(value, np.datetime64):
        return dts.TIMESTAMP_US
    import datetime
    if isinstance(value, datetime.datetime):
        return dts.TIMESTAMP_US
    if isinstance(value, datetime.date):
        return dts.DATE32
    raise ValueError(f"cannot infer literal type for {value!r}")


class Alias(Expression):
    def __init__(self, child: Expression, alias: str):
        self.children = (child,)
        self.alias = alias

    @property
    def child(self) -> Expression:
        return self.children[0]

    @property
    def dtype(self) -> DataType:
        return self.child.dtype

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    def with_children(self, children):
        return Alias(children[0], self.alias)

    def bind(self, schema):
        return Alias(self.child.bind(schema), self.alias)

    def emit(self, ctx: EmitContext) -> ColVal:
        return self.child.emit(ctx)

    @property
    def name(self) -> str:
        return self.alias

    def cache_key(self):
        return ("Alias", self.alias, self.child.cache_key())

    def __str__(self):
        return f"{self.child} AS {self.alias}"


# ----------------------------------------------------------- op scaffolding --

class UnaryExpression(Expression):
    """Null-propagating unary op (CudfUnaryExpression analog)."""

    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def child(self) -> Expression:
        return self.children[0]

    def with_children(self, children):
        return type(self)(children[0])

    @property
    def dtype(self) -> DataType:
        return self.child.dtype

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        values = self.eval_values(c.values, c)
        return ColVal(self.dtype, values, c.validity)

    def eval_values(self, v, cv: ColVal):
        raise NotImplementedError


class BinaryExpression(Expression):
    """Null-propagating binary op with implicit numeric promotion."""

    # subclasses may force the promoted operand type / result type
    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def left(self) -> Expression:
        return self.children[0]

    @property
    def right(self) -> Expression:
        return self.children[1]

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def operand_type(self) -> DataType:
        return promote_types(self.left.dtype, self.right.dtype)

    @property
    def dtype(self) -> DataType:
        return self.operand_type()

    def emit(self, ctx: EmitContext) -> ColVal:
        t = self.operand_type()
        l = cast_value(self.left.emit(ctx), t)
        r = cast_value(self.right.emit(ctx), t)
        values, extra_validity = self.eval_values(l.values, r.values)
        validity = combine_validity(l.validity, r.validity, extra_validity)
        return ColVal(self.dtype, values, validity)

    def eval_values(self, l, r):
        """Return (values, extra_invalidity-mask-or-None)."""
        raise NotImplementedError


# --------------------------------------------------------- shared emit helpers

def substitute_bound(expr: Expression,
                     replacements: Sequence[Expression]) -> Expression:
    """Replace each BoundReference(i) with replacements[i] (expression
    composition — used by whole-stage fusion to push aggregate/filter
    expressions through an intermediate Project)."""
    if isinstance(expr, BoundReference):
        return replacements[expr.ordinal]
    if not expr.children:
        return expr
    return expr.with_children(
        [substitute_bound(c, replacements) for c in expr.children])


def promote_types(a: DataType, b: DataType) -> DataType:
    """Numeric widening used when binding binary arithmetic/comparison."""
    if a.name == b.name:
        return a
    order = ["tinyint", "smallint", "int", "bigint", "float", "double"]
    if a.name in order and b.name in order:
        return dts.dtype_from_name(order[max(order.index(a.name),
                                             order.index(b.name))])
    if a.is_decimal or b.is_decimal:
        if a.is_floating or b.is_floating:
            return dts.FLOAT64  # decimal promotes to double
        if (a.is_decimal or a.is_integral) and \
                (b.is_decimal or b.is_integral):
            from spark_rapids_tpu.ops.decimal_ops import binary_result
            return binary_result("cmp", a, b)
    if a.is_datetime and b.is_datetime:
        return dts.TIMESTAMP_US
    raise TypeError(f"cannot promote {a} and {b}")


def cast_value(v: ColVal, target: DataType) -> ColVal:
    """In-trace cast used for implicit promotions (full semantics — a
    date->timestamp promotion must convert days to micros, not reinterpret
    storage)."""
    if v.dtype.name == target.name:
        return v
    from spark_rapids_tpu.ops.cast import cast_colval
    return cast_colval(v, target, None)
