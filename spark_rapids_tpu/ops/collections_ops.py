"""Array (collection) expressions.

Counterpart of the reference's ``collectionOperations.scala`` (272 LoC) +
``complexTypeCreator.scala`` / ``complexTypeExtractors.scala`` rules
(CreateArray / Size / SortArray / ArrayContains / GetArrayItem / ElementAt,
``GpuOverrides.scala:777-2826``).  An array ColVal is flat element values +
int32 row offsets — the string chars layout generalized — so these kernels
are the string byte-map tricks applied to typed elements:

* per-element row ids come from ``searchsorted`` over the offsets;
* per-row reductions over elements are ``segment_*`` ops;
* SortArray is one ``lexsort`` keyed (row, element) — every row's segment
  sorts in a single fused device pass, no per-row loop.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.dtypes import ArrayType, DataType
from spark_rapids_tpu.ops.expressions import (
    ColVal, EmitContext, Expression, UnaryExpression, combine_validity,
    promote_types)


def element_rows(c: ColVal, capacity: int):
    """row index of every element position in the flat buffer."""
    pos = jnp.arange(c.values.shape[0], dtype=jnp.int32)
    row = jnp.searchsorted(c.offsets, pos, side="right") - 1
    return jnp.clip(row, 0, capacity - 1)


def row_lengths(c: ColVal):
    return c.offsets[1:] - c.offsets[:-1]


class CreateArray(Expression):
    """array(e1, e2, ...): row i -> [e1[i], e2[i], ...]."""

    def __init__(self, *children: Expression):
        if not children:
            raise ValueError("array() needs at least one element")
        self.children = tuple(children)

    @property
    def _element_dtype(self) -> DataType:
        dt = self.children[0].dtype
        for c in self.children[1:]:
            dt = promote_types(dt, c.dtype)
        return dt

    @property
    def dtype(self) -> DataType:
        return ArrayType(self._element_dtype)

    @property
    def nullable(self) -> bool:
        return False

    def with_children(self, children):
        return CreateArray(*children)

    def emit(self, ctx: EmitContext) -> ColVal:
        k = len(self.children)
        elem = self._element_dtype
        vals = []
        validity = None
        for c in self.children:
            cv = c.emit(ctx)
            v = cv.values.astype(elem.storage)
            if getattr(v, "ndim", 0) == 0:
                v = jnp.broadcast_to(v, (ctx.capacity,))
            vals.append(v)
            validity = combine_validity(validity, cv.validity)
        if validity is not None:
            raise NotImplementedError(
                "null array elements not supported (the planner tags "
                "CreateArray over nullable children as not-on-TPU)")
        flat = jnp.stack(vals, axis=1).reshape(-1)
        offsets = jnp.arange(ctx.capacity + 1, dtype=jnp.int32) * k
        return ColVal(self.dtype, flat, None, offsets)


class Size(UnaryExpression):
    """size(array): element count; -1 for null input (Spark's default
    ``spark.sql.legacy.sizeOfNull=true``)."""

    @property
    def dtype(self) -> DataType:
        return dts.INT32

    @property
    def nullable(self) -> bool:
        return False

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        lens = row_lengths(c).astype(jnp.int32)
        if c.validity is not None:
            lens = jnp.where(c.validity, lens, jnp.int32(-1))
        return ColVal(dts.INT32, lens, None)


class ArrayContains(Expression):
    """array_contains(arr, value-literal)."""

    def __init__(self, child: Expression, value: Expression):
        self.children = (child, value)

    @property
    def dtype(self) -> DataType:
        return dts.BOOL

    def with_children(self, children):
        return ArrayContains(children[0], children[1])

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.children[0].emit(ctx)
        v = self.children[1].emit(ctx)
        cap = ctx.capacity
        row = element_rows(c, cap)
        target = v.values if v.is_scalar else v.values[row]
        live = jnp.arange(c.values.shape[0],
                          dtype=jnp.int32) < c.offsets[cap]
        hit = jnp.logical_and(live, c.values == target)
        found = jax.ops.segment_max(hit.astype(jnp.int32), row,
                                    num_segments=cap) > 0
        return ColVal(dts.BOOL, found, c.validity)


class GetArrayItem(Expression):
    """arr[i] (0-based ordinal, Spark GetArrayItem); null when out of
    range."""

    def __init__(self, child: Expression, index: Expression):
        self.children = (child, index)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype.element

    @property
    def nullable(self) -> bool:
        return True

    def with_children(self, children):
        return GetArrayItem(children[0], children[1])

    def bind(self, schema):
        # applied to a shredded MAP column, m[k] is a key lookup (Spark
        # GetMapValue), not a positional index into the key array
        from spark_rapids_tpu.columnar.nested import is_shredded_map
        from spark_rapids_tpu.ops.expressions import UnresolvedColumn
        from spark_rapids_tpu.ops.nested_ops import GetMapValue
        base = self.children[0]
        if isinstance(base, UnresolvedColumn) and \
                is_shredded_map(base.col_name, [n for n, _ in schema]):
            return GetMapValue(base, self.children[1]).bind(schema)
        return super().bind(schema)

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.children[0].emit(ctx)
        i = self.children[1].emit(ctx)
        idx = i.values.astype(jnp.int32)
        if getattr(idx, "ndim", 0) == 0:
            idx = jnp.broadcast_to(idx, (ctx.capacity,))
        lens = row_lengths(c)
        in_range = jnp.logical_and(idx >= 0, idx < lens)
        ecap = c.values.shape[0]
        pos = jnp.clip(c.offsets[:-1] + idx, 0, max(ecap - 1, 0))
        vals = c.values[pos]
        validity = combine_validity(c.validity, in_range)
        validity = combine_validity(validity, i.validity)
        return ColVal(self.dtype, vals, validity)


class ElementAt(GetArrayItem):
    """element_at(arr, i): 1-based; negative indexes from the end.
    Applied to a shredded MAP column it dispatches to GetMapValue via
    the inherited bind (Spark's ElementAt handles both container
    kinds)."""

    def with_children(self, children):
        return ElementAt(children[0], children[1])

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.children[0].emit(ctx)
        i = self.children[1].emit(ctx)
        idx = i.values.astype(jnp.int32)
        if getattr(idx, "ndim", 0) == 0:
            idx = jnp.broadcast_to(idx, (ctx.capacity,))
        lens = row_lengths(c).astype(jnp.int32)
        zero_based = jnp.where(idx > 0, idx - 1, lens + idx)
        in_range = jnp.logical_and(zero_based >= 0, zero_based < lens)
        ecap = c.values.shape[0]
        pos = jnp.clip(c.offsets[:-1] + zero_based, 0, max(ecap - 1, 0))
        vals = c.values[pos]
        validity = combine_validity(c.validity, in_range)
        validity = combine_validity(validity, i.validity)
        return ColVal(self.dtype, vals, validity)


class SortArray(Expression):
    """sort_array(arr, asc): every row's elements sorted in one fused
    lexsort over (row, element) — the data-parallel form of cudf's
    segmented sort."""

    def __init__(self, child: Expression, ascending: bool = True):
        self.children = (child,)
        self.ascending = ascending

    @property
    def child(self) -> Expression:
        return self.children[0]

    @property
    def dtype(self) -> DataType:
        return self.child.dtype

    def with_children(self, children):
        return SortArray(children[0], self.ascending)

    def cache_key(self):
        return ("SortArray", self.ascending, self.child.cache_key())

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        cap = ctx.capacity
        row = element_rows(c, cap)
        v = c.values
        # dead elements (buffer padding beyond the last row's end) must
        # sort AFTER every real segment, not into row cap-1
        live = jnp.arange(v.shape[0], dtype=jnp.int32) < c.offsets[cap]
        row_key = jnp.where(live, row, jnp.int32(cap))
        if jnp.issubdtype(v.dtype, jnp.floating):
            key = jnp.where(v == 0.0, 0.0, v)  # -0.0 == 0.0
            nan_flag = jnp.isnan(v).astype(jnp.int8)  # NaN sorts largest
            key = jnp.where(jnp.isnan(v), 0.0, key)
            elem_keys = [-key, -nan_flag] if not self.ascending else \
                [key, nan_flag]
        elif v.dtype == jnp.bool_:
            k = v.astype(jnp.int8)
            elem_keys = [-k] if not self.ascending else [k]
        else:
            elem_keys = [~v] if not self.ascending else [v]
        perm = jnp.lexsort(elem_keys + [row_key])
        return ColVal(c.dtype, v[perm], c.validity, c.offsets)


class _ArrayExtreme(UnaryExpression):
    """array_min / array_max: one segment reduction over (row, element)
    — the segmented-reduce form of cudf's list min/max.  Empty arrays
    yield null; float NaN follows Spark's total order (NaN greater than
    every number: any NaN wins max, NaN wins min only when the row is
    all-NaN)."""

    is_max = False

    @property
    def dtype(self) -> DataType:
        return self.child.dtype.element

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        cap = ctx.capacity
        v = c.values
        row = element_rows(c, cap)
        live = jnp.arange(v.shape[0], dtype=jnp.int32) < c.offsets[cap]
        seg = jnp.where(live, row, jnp.int32(cap))  # dead -> spare seg
        lens = c.offsets[1:cap + 1] - c.offsets[:cap]
        is_float = jnp.issubdtype(v.dtype, jnp.floating)
        data = v
        if is_float:
            nan = jnp.isnan(v)
            data = jnp.where(nan, -jnp.inf if self.is_max else jnp.inf, v)
            nan_cnt = jax.ops.segment_sum(
                jnp.where(live, nan.astype(jnp.int32), 0), seg,
                num_segments=cap + 1)[:cap]
        reduce = jax.ops.segment_max if self.is_max else \
            jax.ops.segment_min
        out = reduce(data, seg, num_segments=cap + 1)[:cap]
        if is_float:
            # Spark total order: NaN > everything
            all_nan = nan_cnt == lens
            out = jnp.where(all_nan & (lens > 0), jnp.nan, out)
            if self.is_max:
                out = jnp.where(nan_cnt > 0, jnp.nan, out)
        validity = combine_validity(c.validity, lens > 0)
        return ColVal(self.dtype, out.astype(v.dtype), validity)


class ArrayMin(_ArrayExtreme):
    is_max = False


class ArrayMax(_ArrayExtreme):
    is_max = True


class Reverse(UnaryExpression):
    """reverse() over arrays (element order) and strings (bytes — the
    ASCII-only incompat class, like the engine's other byte-semantics
    string kernels)."""

    @property
    def dtype(self) -> DataType:
        return self.child.dtype

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        cap = ctx.capacity
        v = c.values
        row = element_rows(c, cap)
        i = jnp.arange(v.shape[0], dtype=jnp.int32)
        live = i < c.offsets[cap]
        start = c.offsets[row]
        end = c.offsets[row + 1]
        j = jnp.where(live, start + end - 1 - i, i)
        j = jnp.clip(j, 0, v.shape[0] - 1)
        return ColVal(c.dtype, v[j], c.validity, c.offsets)


class Slice(UnaryExpression):
    """slice(arr, start, length) with LITERAL bounds (1-based start,
    negative counts from the end — Spark semantics; the reference's
    GpuSlice also requires literal bounds for the common case)."""

    def __init__(self, child: Expression, start: int, length: int):
        super().__init__(child)
        if start == 0:
            raise ValueError("slice start must not be 0 (SQL is "
                             "1-based)")
        if length < 0:
            raise ValueError("slice length must be >= 0")
        self.start = int(start)
        self.length = int(length)

    def with_children(self, children):
        return Slice(children[0], self.start, self.length)

    def cache_key(self):
        return ("Slice", self.start, self.length,
                self.child.cache_key())

    @property
    def dtype(self) -> DataType:
        return self.child.dtype

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        cap = ctx.capacity
        lens = (c.offsets[1:cap + 1] - c.offsets[:cap]).astype(jnp.int32)
        if self.start > 0:
            s_raw = jnp.full(cap, self.start - 1, dtype=jnp.int32)
        else:
            s_raw = lens + self.start
        # Spark: a negative start reaching before the array yields the
        # EMPTY array (collectionOperations.scala startIndex < 0 check)
        s = jnp.clip(s_raw, 0, lens)
        out_len = jnp.clip(jnp.int32(self.length), 0, lens - s)
        out_len = jnp.where(s_raw < 0, 0, out_len)
        out_len = jnp.where(ctx.row_mask(), out_len, 0)
        out_offsets = jnp.concatenate(
            [jnp.zeros(1, dtype=jnp.int32),
             jnp.cumsum(out_len, dtype=jnp.int32)])
        ecap = int(c.values.shape[0])
        pos = jnp.arange(ecap, dtype=jnp.int32)
        row = jnp.clip(
            jnp.searchsorted(out_offsets, pos, side="right") - 1,
            0, cap - 1)
        k = pos - out_offsets[row]
        src = jnp.clip(c.offsets[row] + s[row] + k, 0, ecap - 1)
        vals = jnp.where(pos < out_offsets[cap], c.values[src],
                         jnp.zeros((), dtype=c.values.dtype))
        return ColVal(c.dtype, vals, c.validity, out_offsets)


class ArrayRepeat(Expression):
    """array_repeat(value, n) with a LITERAL count: fixed-stride array
    construction (every row length n)."""

    def __init__(self, child: Expression, times: int):
        if times < 0:
            times = 0
        self.children = (child,)
        self.times = int(times)

    @property
    def child(self) -> Expression:
        return self.children[0]

    def with_children(self, children):
        return ArrayRepeat(children[0], self.times)

    def cache_key(self):
        return ("ArrayRepeat", self.times, self.child.cache_key())

    @property
    def dtype(self) -> DataType:
        return ArrayType(self.child.dtype)

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    def emit(self, ctx: EmitContext) -> ColVal:
        c = self.child.emit(ctx)
        cap = ctx.capacity
        n = self.times
        lens = jnp.where(ctx.row_mask(), jnp.int32(n), 0)
        out_offsets = jnp.concatenate(
            [jnp.zeros(1, dtype=jnp.int32),
             jnp.cumsum(lens, dtype=jnp.int32)])
        ecap = 1
        while ecap < max(n, 1) * cap:
            ecap <<= 1
        pos = jnp.arange(ecap, dtype=jnp.int32)
        row = jnp.clip(
            jnp.searchsorted(out_offsets, pos, side="right") - 1,
            0, cap - 1)
        vals = jnp.where(pos < out_offsets[cap], c.values[row],
                         jnp.zeros((), dtype=c.values.dtype))
        return ColVal(ArrayType(c.dtype), vals, c.validity, out_offsets)
