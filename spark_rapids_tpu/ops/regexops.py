"""Regex-family and remaining string expressions.

Counterpart of the reference's regex surface (RLike / RegExpReplace via
shim rules, StringSplit / ConcatWs in ``stringFunctions.scala:1-1053``).
The reference flags regex ops *incompat* because cudf's dialect differs
from Java's; the TPU build goes further and compiles only a restricted
subset onto the device, tagging everything else "will NOT run" so the
planner falls back to CPU (exactly the meta-layer contract).

Device-supported subset (``RegexProgram``): concatenations of
fixed-length char-class atoms — literals, ``.``, ``[...]`` classes with
ranges/negation, ``\\d \\w \\s`` escapes, ``{m}`` repetition — separated
by ``.*`` / ``.+`` gaps, with optional ``^`` / ``$`` anchors.  Each atom
is a 256-entry byte mask; a segment match at byte position p is the AND
of ``mask_i[chars[p+i]]``, and gap ordering reuses the masked
``segment_min`` earliest-match trick from LIKE's general matcher.  ``.``
matches one BYTE (ASCII semantics — multi-byte UTF-8 code points count
per byte), mirroring the reference's documented regex incompatibilities.

RegExpReplace additionally requires a gap-free, unanchored pattern whose
self-overlap is impossible (checked via class-mask intersections on the
host), so every raw match is a greedy match and replacement is one fused
flat-map over the char buffer: per input byte an emission length (1 =
copy, R = replacement at a match start, 0 = swallowed), an exclusive
cumsum for output positions, and one gather — no sequential pass.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.ops.expressions import (
    ColVal, EmitContext, Expression, UnaryExpression, combine_validity)
from spark_rapids_tpu.ops.stringops import (
    _as_string_col, _literal_bytes, _next_pow2, build_strings, byte_to_row,
    row_lengths)


# ------------------------------------------------------------ pattern compile

_CLASS_D = np.zeros(256, dtype=bool)
_CLASS_D[ord("0"):ord("9") + 1] = True
_CLASS_W = _CLASS_D.copy()
_CLASS_W[ord("a"):ord("z") + 1] = True
_CLASS_W[ord("A"):ord("Z") + 1] = True
_CLASS_W[ord("_")] = True
_CLASS_S = np.zeros(256, dtype=bool)
for _c in " \t\n\r\f\v":
    _CLASS_S[ord(_c)] = True
_CLASS_ANY = np.ones(256, dtype=bool)

_META = set(".[]()*+?{}|^$\\")


def _parse_class(pat: str, i: int) -> Tuple[Optional[np.ndarray], int]:
    """Parse [...] starting at pat[i] == '['; returns (mask, next_i)."""
    mask = np.zeros(256, dtype=bool)
    i += 1
    negate = False
    if i < len(pat) and pat[i] == "^":
        negate = True
        i += 1
    first = True
    while i < len(pat) and (pat[i] != "]" or first):
        first = False
        ch = pat[i]
        if ch == "\\" and i + 1 < len(pat):
            nxt = pat[i + 1]
            sub = {"d": _CLASS_D, "w": _CLASS_W, "s": _CLASS_S}.get(nxt)
            if sub is not None:
                mask |= sub
                i += 2
                continue
            ch = nxt
            i += 1
        o = ord(ch)
        if o > 255:
            return None, i  # non-ASCII class member: unsupported
        if i + 2 < len(pat) and pat[i + 1] == "-" and pat[i + 2] != "]":
            hi = ord(pat[i + 2])
            if hi > 255:
                return None, i
            mask[o:hi + 1] = True
            i += 3
        else:
            mask[o] = True
            i += 1
    if i >= len(pat):
        return None, i  # unterminated
    if negate:
        mask = ~mask
    return mask, i + 1


class RegexProgram:
    """Compiled restricted pattern: ``segments`` of byte-class masks
    separated by gaps; None when the pattern is outside the subset."""

    def __init__(self, anchored_start: bool, anchored_end: bool,
                 segments: List[List[np.ndarray]], gap_min: List[int]):
        self.anchored_start = anchored_start
        self.anchored_end = anchored_end
        self.segments = segments          # each: list of (256,) bool masks
        self.gap_min = gap_min            # min bytes before segment k (k>0)

    @property
    def single_fixed(self) -> bool:
        return (len(self.segments) == 1 and not self.anchored_start and
                not self.anchored_end)

    def no_self_overlap(self) -> bool:
        """True when a raw match can never overlap another (so all raw
        matches are greedy matches).  Shift-d overlap is impossible when
        some position i has mask[i] disjoint from mask[i+d]."""
        if len(self.segments) != 1:
            return False
        atoms = self.segments[0]
        n = len(atoms)
        for d in range(1, n):
            if not any(not (atoms[i] & atoms[i + d]).any()
                       for i in range(n - d)):
                return False
        return True


def compile_pattern(pat: str) -> Optional[RegexProgram]:
    """Compile to the device subset; None = unsupported (CPU fallback)."""
    i = 0
    anchored_start = False
    anchored_end = False
    if pat.startswith("^"):
        anchored_start = True
        i = 1
    body = pat
    if body.endswith("$") and not body.endswith("\\$"):
        anchored_end = True
        body = body[:-1]
    segments: List[List[np.ndarray]] = [[]]
    gap_min: List[int] = []
    while i < len(body):
        ch = body[i]
        mask: Optional[np.ndarray] = None
        if ch == "\\" and i + 1 < len(body):
            nxt = body[i + 1]
            named = {"d": _CLASS_D, "D": ~_CLASS_D, "w": _CLASS_W,
                     "W": ~_CLASS_W, "s": _CLASS_S, "S": ~_CLASS_S}
            if nxt in named:
                mask = named[nxt].copy()
            elif nxt in _META or not nxt.isalnum():
                mask = np.zeros(256, dtype=bool)
                mask[ord(nxt)] = True
            else:
                return None  # \b, \A, backrefs...
            i += 2
        elif ch == ".":
            # ".*" / ".+" are gaps between segments
            if i + 1 < len(body) and body[i + 1] in "*+":
                if not segments[-1] and len(segments) > 1:
                    return None  # consecutive gaps
                segments.append([])
                gap_min.append(1 if body[i + 1] == "+" else 0)
                i += 2
                continue
            mask = _CLASS_ANY.copy()
            i += 1
        elif ch == "[":
            mask, ni = _parse_class(body, i)
            if mask is None:
                return None
            i = ni
        elif ch in "*+?{}|()$^":
            if ch == "{":
                # fixed repetition {m} of the previous atom
                j = body.find("}", i)
                if j < 0 or not segments[-1]:
                    return None
                spec = body[i + 1:j]
                if not spec.isdigit():
                    return None  # {m,n} ranges unsupported
                prev = segments[-1][-1]
                for _ in range(int(spec) - 1):
                    segments[-1].append(prev.copy())
                i = j + 1
                continue
            return None  # alternation, groups, variable quantifiers
        else:
            enc = ch.encode("utf-8")
            for b in enc:
                m = np.zeros(256, dtype=bool)
                m[b] = True
                segments[-1].append(m)
            i += 1
            continue
        segments[-1].append(mask)
    if any(not s for s in segments):
        return None  # empty segment (e.g. bare ".*" pattern or gap at end)
    return RegexProgram(anchored_start, anchored_end, segments, gap_min)


# ------------------------------------------------------------- device match

def _class_match_starts(c: ColVal, atoms: Sequence[np.ndarray],
                        capacity: int):
    """bool per byte position: the class sequence matches starting here,
    entirely within the row."""
    ccap = c.values.shape[0]
    pos = jnp.arange(ccap, dtype=jnp.int32)
    m = jnp.ones(ccap, dtype=jnp.bool_)
    for i, mask in enumerate(atoms):
        lut = jnp.asarray(mask)
        byte = c.values[jnp.clip(pos + i, 0, ccap - 1)].astype(jnp.int32)
        m = jnp.logical_and(m, lut[byte])
    row = byte_to_row(c, capacity)
    fits = pos + len(atoms) <= c.offsets[row + 1]
    return jnp.logical_and(m, fits), row


def match_program(c: ColVal, prog: RegexProgram, ctx: EmitContext):
    """bool per row: unanchored-find semantics (Java Matcher.find) with
    the program's own anchors applied."""
    cap = ctx.capacity
    big = jnp.int32(1 << 30)
    row = byte_to_row(c, cap)
    row_start = c.offsets[:-1]
    row_end = c.offsets[1:]
    # earliest allowed start position per row, advanced segment by segment
    earliest = row_start
    ok = jnp.ones(cap, dtype=jnp.bool_)
    for k, atoms in enumerate(prog.segments):
        starts, _ = _class_match_starts(c, atoms, cap)
        pos = jnp.arange(c.values.shape[0], dtype=jnp.int32)
        candidate = jnp.logical_and(starts, pos >= earliest[row])
        if k == 0 and prog.anchored_start:
            candidate = jnp.logical_and(candidate, pos == row_start[row])
        first = jax.ops.segment_min(jnp.where(candidate, pos, big), row,
                                    num_segments=cap)
        if k == 0 and prog.anchored_start:
            # anchored first segment must match at the exact row start
            ok = jnp.logical_and(ok, first == row_start)
        found = first < big
        ok = jnp.logical_and(ok, found)
        seg_end = jnp.where(found, first + len(atoms), earliest)
        gap = prog.gap_min[k] if k < len(prog.gap_min) else 0
        if prog.anchored_end and k == len(prog.segments) - 1:
            # the LAST segment must end at the row end; take the latest
            # candidate instead of the earliest
            last = jax.ops.segment_max(
                jnp.where(candidate, pos, jnp.int32(-1)), row,
                num_segments=cap)
            ok = jnp.logical_and(ok, last + len(atoms) == row_end)
        earliest = seg_end + gap
    if prog.anchored_end and len(prog.segments) == 1 and \
            prog.anchored_start:
        # fully anchored: exact length already enforced by start+end
        pass
    # rows with zero bytes: only match when every segment could be empty
    # (segments are non-empty by construction, so no-byte rows never match
    # unless the whole pattern is empty, rejected at compile)
    return ok


# -------------------------------------------------------------- expressions

class RLike(UnaryExpression):
    """rlike / regexp: unanchored find over the restricted subset."""

    def __init__(self, child: Expression, pattern: str):
        super().__init__(child)
        self.pattern = pattern
        self._prog = compile_pattern(pattern)

    def with_children(self, children):
        return RLike(children[0], self.pattern)

    @property
    def supported(self) -> bool:
        return self._prog is not None

    @property
    def dtype(self):
        return dts.BOOL

    def cache_key(self):
        return ("RLike", self.pattern, self.child.cache_key())

    def __str__(self):
        return f"RLike({self.child}, {self.pattern!r})"

    def emit(self, ctx: EmitContext) -> ColVal:
        if self._prog is None:
            raise NotImplementedError(
                f"regex {self.pattern!r} outside the TPU subset")
        c = _as_string_col(self.child.emit(ctx), ctx)
        ok = match_program(c, self._prog, ctx)
        return ColVal(dts.BOOL, ok, c.validity)


class RegExpReplace(Expression):
    """regexp_replace(s, pattern, replacement): device path for gap-free,
    unanchored, non-self-overlapping patterns with a literal replacement
    (no ``$n`` group references); everything else is tagged off."""

    def __init__(self, child: Expression, pattern: str, replacement: str):
        self.children = (child,)
        self.pattern = pattern
        self.replacement = replacement
        prog = compile_pattern(pattern)
        self._prog = prog if (prog is not None and prog.single_fixed and
                              prog.no_self_overlap() and
                              "$" not in replacement) else None

    @property
    def child(self) -> Expression:
        return self.children[0]

    def with_children(self, children):
        return RegExpReplace(children[0], self.pattern, self.replacement)

    @property
    def supported(self) -> bool:
        return self._prog is not None

    @property
    def dtype(self):
        return dts.STRING

    def cache_key(self):
        return ("RegExpReplace", self.pattern, self.replacement,
                self.child.cache_key())

    def emit(self, ctx: EmitContext) -> ColVal:
        if self._prog is None:
            raise NotImplementedError(
                f"regexp_replace {self.pattern!r} outside the TPU subset")
        c = _as_string_col(self.child.emit(ctx), ctx)
        atoms = self._prog.segments[0]
        L = len(atoms)
        repl = _literal_bytes(self.replacement)
        R = len(repl)
        cap = ctx.capacity
        starts, row = _class_match_starts(c, atoms, cap)
        ccap = c.values.shape[0]
        pos = jnp.arange(ccap, dtype=jnp.int32)
        # within-match coverage via a windowed OR (cumsum difference):
        # byte i is inside a match iff a match starts in (i-L, i]
        ms = starts.astype(jnp.int32)
        cum = jnp.concatenate([jnp.zeros(1, dtype=jnp.int32),
                               jnp.cumsum(ms)])
        in_match = (cum[pos + 1] - cum[jnp.maximum(pos - L + 1, 0)]) > 0
        live = pos < c.offsets[cap]
        emit_len = jnp.where(starts, R,
                             jnp.where(in_match, 0, 1))
        emit_len = jnp.where(live, emit_len, 0)
        out_pos = jnp.concatenate([jnp.zeros(1, dtype=jnp.int32),
                                   jnp.cumsum(emit_len, dtype=jnp.int32)])
        new_offsets = out_pos[c.offsets]
        new_lens = new_offsets[1:] - new_offsets[:-1]
        out_cap = _next_pow2(
            max(int(ccap) * max(R, 1) // max(L, 1), int(ccap), 1))
        repl_dev = jnp.asarray(repl if R else np.zeros(1, dtype=np.uint8))
        pos_out = jnp.arange(out_cap, dtype=jnp.int32)
        i = jnp.clip(jnp.searchsorted(out_pos, pos_out, side="right") - 1,
                     0, ccap - 1)
        off = pos_out - out_pos[i]
        is_repl = starts[i]
        copy_byte = c.values[i]
        repl_byte = repl_dev[jnp.clip(off, 0, max(R - 1, 0))]
        total = new_offsets[cap]
        chars = jnp.where(pos_out < total,
                          jnp.where(is_repl, repl_byte, copy_byte),
                          0).astype(jnp.uint8)
        return ColVal(dts.STRING, chars, c.validity, new_offsets)


class ConcatWs(Expression):
    """concat_ws(sep, s1, s2, ...): null inputs are SKIPPED (result is
    never null), Spark semantics."""

    def __init__(self, sep: str, *children: Expression):
        self.sep = sep
        self.children = tuple(children)

    def with_children(self, children):
        return ConcatWs(self.sep, *children)

    @property
    def dtype(self):
        return dts.STRING

    @property
    def nullable(self) -> bool:
        return False

    def cache_key(self):
        return ("ConcatWs", self.sep,
                tuple(c.cache_key() for c in self.children))

    def emit(self, ctx: EmitContext) -> ColVal:
        cap = ctx.capacity
        cols = [_as_string_col(c.emit(ctx), ctx) for c in self.children]
        sep = _literal_bytes(self.sep)
        S = len(sep)
        valids = [jnp.ones(cap, dtype=jnp.bool_) if c.validity is None
                  else c.validity for c in cols]
        eff_lens = [jnp.where(v, row_lengths(c), 0)
                    for c, v in zip(cols, valids)]
        # separator precedes part j when part j is present and some part
        # before j is present
        any_before = jnp.zeros(cap, dtype=jnp.bool_)
        sep_flags = []
        for v in valids:
            sep_flags.append(jnp.logical_and(v, any_before))
            any_before = jnp.logical_or(any_before, v)
        total = jnp.zeros(cap, dtype=jnp.int32)
        part_starts = []
        for l, sf in zip(eff_lens, sep_flags):
            total = total + jnp.where(sf, S, 0)
            part_starts.append(total)
            total = total + l
        pool_base = []
        base = 0
        pool_parts = []
        for c in cols:
            pool_base.append(base)
            base += int(c.values.shape[0])
            pool_parts.append(c.values)
        pool_base.append(base)  # separator bytes live at the pool tail
        pool_parts.append(jnp.asarray(
            sep if S else np.zeros(1, dtype=np.uint8)))
        pool = jnp.concatenate(pool_parts)
        out_cap = _next_pow2(base + S * max(len(cols) - 1, 1) * cap
                             if S else max(base, 1))

        def src(p, r, k):
            src_idx = jnp.zeros_like(p)
            for c, ps, l, sf, pb in zip(cols, part_starts, eff_lens,
                                        sep_flags, pool_base):
                sep_start = ps[r] - S
                in_sep = jnp.logical_and(
                    sf[r], jnp.logical_and(k >= sep_start, k < ps[r]))
                src_idx = jnp.where(
                    in_sep, pool_base[-1] + (k - sep_start), src_idx)
                inside = jnp.logical_and(k >= ps[r], k < ps[r] + l[r])
                src_idx = jnp.where(inside, pb + c.offsets[r] + (k - ps[r]),
                                    src_idx)
            return src_idx

        chars, offsets = build_strings(total, src, pool, out_cap, cap)
        return ColVal(dts.STRING, chars, None, offsets)


class Translate(Expression):
    """translate(s, from, to): per-byte LUT; bytes of ``from`` beyond
    ``len(to)`` are deleted.  ``from``/``to`` must be ASCII (non-ASCII
    data bytes pass through untouched — UTF-8 continuation bytes are
    >= 0x80 and the LUT only maps ASCII)."""

    def __init__(self, child: Expression, from_str: str, to_str: str):
        self.children = (child,)
        self.from_str = from_str
        self.to_str = to_str

    @property
    def child(self) -> Expression:
        return self.children[0]

    def with_children(self, children):
        return Translate(children[0], self.from_str, self.to_str)

    @property
    def supported(self) -> bool:
        return all(ord(ch) < 128 for ch in self.from_str + self.to_str)

    @property
    def dtype(self):
        return dts.STRING

    def cache_key(self):
        return ("Translate", self.from_str, self.to_str,
                self.child.cache_key())

    def emit(self, ctx: EmitContext) -> ColVal:
        if not self.supported:
            raise NotImplementedError("translate maps must be ASCII")
        c = _as_string_col(self.child.emit(ctx), ctx)
        cap = ctx.capacity
        lut = np.arange(256, dtype=np.int32)   # identity
        keep = np.ones(256, dtype=bool)
        seen = set()
        for i, ch in enumerate(self.from_str):
            b = ord(ch)
            if b in seen:
                continue  # Spark: first occurrence wins
            seen.add(b)
            if i < len(self.to_str):
                lut[b] = ord(self.to_str[i])
            else:
                keep[b] = False
        ccap = c.values.shape[0]
        pos = jnp.arange(ccap, dtype=jnp.int32)
        live = pos < c.offsets[cap]
        byte = c.values.astype(jnp.int32)
        emit_len = jnp.where(jnp.logical_and(live,
                                             jnp.asarray(keep)[byte]), 1, 0)
        out_pos = jnp.concatenate([jnp.zeros(1, dtype=jnp.int32),
                                   jnp.cumsum(emit_len, dtype=jnp.int32)])
        new_offsets = out_pos[c.offsets]
        pos_out = jnp.arange(ccap, dtype=jnp.int32)
        i = jnp.clip(jnp.searchsorted(out_pos, pos_out, side="right") - 1,
                     0, max(ccap - 1, 0))
        mapped = jnp.asarray(lut)[c.values[i].astype(jnp.int32)]
        total = new_offsets[cap]
        chars = jnp.where(pos_out < total, mapped, 0).astype(jnp.uint8)
        return ColVal(dts.STRING, chars, c.validity, new_offsets)


class StringReplace(Expression):
    """replace(s, search, replacement) with a LITERAL search string —
    Spark's StringReplace (regexp_replace handles patterns).  Runs on
    device whenever the search literal cannot self-overlap; bordered
    literals (e.g. "aa") are tagged off."""

    def __init__(self, child: Expression, search: str, replacement: str):
        self.children = (child,)
        self.search = search
        self.replacement = replacement
        escaped = "".join(
            "\\" + ch if ch in _META else ch for ch in search)
        self._impl = RegExpReplace(child, escaped, replacement) \
            if search else None

    @property
    def child(self) -> Expression:
        return self.children[0]

    def with_children(self, children):
        return StringReplace(children[0], self.search, self.replacement)

    @property
    def supported(self) -> bool:
        return self._impl is not None and self._impl.supported

    @property
    def dtype(self):
        return dts.STRING

    def cache_key(self):
        return ("StringReplace", self.search, self.replacement,
                self.child.cache_key())

    def emit(self, ctx: EmitContext) -> ColVal:
        if not self.supported:
            raise NotImplementedError(
                f"replace search {self.search!r} unsupported on TPU")
        return self._impl.emit(ctx)


class SplitPart(Expression):
    """split(s, delim)[n] fused: Spark has no array<string>-free form, but
    ``split(col, d).getItem(n)`` is the dominant usage and our arrays hold
    fixed-width elements only — so the planner fuses the pair into this
    expression (delimiter is a literal; n is a 0-based static ordinal).
    Returns null when the row has fewer than n+1 parts... except n==0,
    which returns the whole string when no delimiter occurs (Spark
    getItem(0) of a splitless string is the string itself)."""

    def __init__(self, child: Expression, delim: str, index: int):
        self.children = (child,)
        self.delim = delim
        self.index = index

    @property
    def child(self) -> Expression:
        return self.children[0]

    def with_children(self, children):
        return SplitPart(children[0], self.delim, self.index)

    @property
    def supported(self) -> bool:
        return len(self.delim) > 0 and self.index >= 0 and not any(
            ch in _META for ch in self.delim)

    @property
    def dtype(self):
        return dts.STRING

    def cache_key(self):
        return ("SplitPart", self.delim, self.index,
                self.child.cache_key())

    def emit(self, ctx: EmitContext) -> ColVal:
        from spark_rapids_tpu.ops.stringops import _match_starts
        if not self.supported:
            raise NotImplementedError("split delimiter must be a literal")
        c = _as_string_col(self.child.emit(ctx), ctx)
        cap = ctx.capacity
        pat = _literal_bytes(self.delim)
        D = len(pat)
        n = self.index
        starts, row = _match_starts(c, pat, cap)
        ccap = c.values.shape[0]
        pos = jnp.arange(ccap, dtype=jnp.int32)
        # delimiter index within its row (0-based, at delimiter positions)
        ms = starts.astype(jnp.int32)
        cum = jnp.cumsum(ms)
        cum_incl = cum  # inclusive
        row_cum_base = jnp.concatenate(
            [jnp.zeros(1, dtype=jnp.int32), cum])[c.offsets[:-1]]
        idx_in_row = cum_incl - 1 - row_cum_base[row]
        big = jnp.int32(1 << 30)
        # position of the n-th delimiter (part n's end) and (n-1)-th
        # (part n's start - D)
        def delim_pos(k):
            cand = jnp.logical_and(starts, idx_in_row == k)
            return jax.ops.segment_min(jnp.where(cand, pos, big), row,
                                       num_segments=cap)
        end_n = delim_pos(n)
        start_prev = delim_pos(n - 1) if n > 0 else None
        row_start = c.offsets[:-1]
        row_end = c.offsets[1:]
        part_start = row_start if n == 0 else \
            jnp.where(start_prev < big, start_prev + D, big)
        part_end = jnp.where(end_n < big, end_n, row_end)
        have = part_start < big
        lens = jnp.where(have, jnp.maximum(part_end - part_start, 0), 0)
        out_cap = _next_pow2(max(int(ccap), 1))

        def src(p, r, k):
            return jnp.clip(part_start[r], 0, max(ccap - 1, 0)) + k

        chars, offsets = build_strings(lens, src, c.values, out_cap, cap)
        validity = combine_validity(c.validity, have)
        return ColVal(dts.STRING, chars, validity, offsets)
