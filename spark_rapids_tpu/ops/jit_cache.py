"""Process-wide cache of jitted executables keyed by structural signature.

Physical plans are rebuilt per query, so per-instance ``jax.jit(bound
method)`` would recompile the same XLA program on every run — the dominant
cost for repeated queries (an aggregate stage costs seconds to compile,
microseconds to run).  The reference relies on cudf's precompiled kernels;
the TPU analog is this cache: executables are shared across plan instances
whose expression forests are structurally identical (``Expression.
cache_key`` includes literal values, so constants bake correctly).

The cached callable still goes through jax.jit's own shape-bucket cache, so
one signature may hold several XLA executables (one per input capacity).

Thread safety: the pipeline driver (exec/pipeline.py) and concurrent
sessions hit the cache from multiple threads, so every map access holds
``_LOCK``.  ``jax.jit`` construction happens OUTSIDE the lock (it only
wraps, tracing is deferred to first call); on a build race the first
insert wins so every thread shares one executable.

Donation: callers pass ``jit_kwargs`` (e.g. ``donate_argnums``) through to
``jax.jit``; anything that changes the compiled program MUST be part of
``signature`` (stage compilers fold their donation flag in — see
ops/compiler.py).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, Hashable

import jax

# LRU-bounded: cached entries close over their originating plan instance
# (and thus its child tree), so an unbounded map would pin every distinct
# query shape ever run.  256 signatures comfortably covers a working set
# of queries while keeping retention bounded.
_MAX_ENTRIES = 256
_CACHE: "OrderedDict[Hashable, Callable]" = OrderedDict()
_LOCK = threading.Lock()
_HITS = 0
_MISSES = 0


def cached_jit(signature: Hashable, make: Callable[[], Callable],
               **jit_kwargs: Any) -> Callable:
    """Return a jitted callable for ``signature``; build via ``make()`` on
    miss.  ``make`` returns the plain (untraced) function to jit — it is
    only invoked when the signature is new, so closures over a freshly
    constructed plan instance are safe as long as everything the function's
    trace depends on is captured in the signature."""
    global _HITS, _MISSES
    with _LOCK:
        fn = _CACHE.get(signature)
        if fn is not None:
            _CACHE.move_to_end(signature)
            _HITS += 1
            return fn
    built = jax.jit(make(), **jit_kwargs)
    with _LOCK:
        fn = _CACHE.get(signature)
        if fn is not None:
            # lost the build race: share the winner's executable (its
            # jit shape-cache is what every thread must hit)
            _CACHE.move_to_end(signature)
            _HITS += 1
            return fn
        _MISSES += 1
        _CACHE[signature] = built
        while len(_CACHE) > _MAX_ENTRIES:
            _CACHE.popitem(last=False)
    return built


def cache_info() -> Dict[str, int]:
    with _LOCK:
        return {"entries": len(_CACHE), "hits": _HITS, "misses": _MISSES}


def clear() -> None:
    global _HITS, _MISSES
    with _LOCK:
        _CACHE.clear()
        _HITS = 0
        _MISSES = 0
