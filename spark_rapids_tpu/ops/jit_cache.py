"""Process-wide cache of jitted executables keyed by structural signature,
with an optional PERSISTENT tier of AOT-serialized executables.

Physical plans are rebuilt per query, so per-instance ``jax.jit(bound
method)`` would recompile the same XLA program on every run — the dominant
cost for repeated queries (an aggregate stage costs seconds to compile,
microseconds to run).  The reference relies on cudf's precompiled kernels;
the TPU analog is this cache: executables are shared across plan instances
whose expression forests are structurally identical (``Expression.
cache_key`` includes literal values, so constants bake correctly).

The cached callable still goes through jax.jit's own shape-bucket cache, so
one signature may hold several XLA executables (one per input capacity).

Thread safety: the pipeline driver (exec/pipeline.py) and concurrent
sessions hit the cache from multiple threads, so every map access holds
``_LOCK``.  A signature MISS serializes builders through a per-signature
build lock: concurrent queries racing into the same new signature share ONE
``jax.jit`` wrapper (and therefore one trace/compile on first call) instead
of building N duplicates with first-insert-wins.

Persistent tier (``spark.rapids.tpu.jitCache.dir``): on the first call of a
(signature, input-shapes) pair the cache consults an on-disk store of
AOT-lowered executables serialized via ``jax.export`` — a warm hit
deserializes the StableHLO module and skips Python tracing entirely (the
dominant repeat-query cost); a miss traces once, then exports and persists
the module so the NEXT process compiles nothing.  Entries are keyed by
sha256 over (structural signature, input avals, backend, jax/jaxlib
versions) — the same full-width-digest discipline as the PR5 checkpoint
``stage_id`` (a colliding key would run the wrong program; the payload CRC
cannot catch that).  Safety: every load verifies a crc32 over the payload
and the recorded environment header; truncation, bit rot
(``jitcache.load`` fire_mutate chaos hook), or a store written by a
different jax/jaxlib falls back to a fresh trace+compile — the entry is
dropped with a ``JitCacheInvalid`` event, never a failed or wrong query.
Cold-path execution always runs the canonical in-process jit (donation
semantics preserved); only warm starts route through the deserialized
module.

Donation: callers pass ``jit_kwargs`` (e.g. ``donate_argnums``) through to
``jax.jit``; anything that changes the compiled program MUST be part of
``signature`` (stage compilers fold their donation flag in — see
ops/compiler.py).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import zlib
from collections import OrderedDict
from typing import Any, Callable, Dict, Hashable, List, Optional, Tuple

import jax

# LRU-bounded: cached entries close over their originating plan instance
# (and thus its child tree), so an unbounded map would pin every distinct
# query shape ever run.  256 signatures comfortably covers a working set
# of queries while keeping retention bounded.
_MAX_ENTRIES = 256
_CACHE: "OrderedDict[Hashable, _Entry]" = OrderedDict()
_LOCK = threading.Lock()
_BUILD_LOCKS: Dict[Hashable, threading.Lock] = {}
_HITS = 0
_MISSES = 0
# dispatches of entries the LRU already evicted (live entries carry
# their own per-entry counter — no global lock on the dispatch path)
_EVICTED_DISPATCHES = 0

# ANSI check messages recorded at trace time by the stage compilers
# (ops/compiler.py aliases this as _CHECK_MSGS).  Living here lets the
# persistent tier serialize them into entry headers, so a warm start
# that never traces still raises the exact ANSI message.
STAGE_CHECKS: Dict[Hashable, List[str]] = {}

_MAGIC = "srtpu-jit"
_FORMAT_VERSION = 1


def _shape_key(args) -> Tuple:
    """Aval bucket of one call: pytree structure plus per-leaf
    (dtype, shape, weak) — what jax.jit's own shape cache keys on."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    parts = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dt = getattr(leaf, "dtype", None)
        if shape is not None and dt is not None:
            parts.append((str(dt), tuple(int(s) for s in shape),
                          bool(getattr(leaf, "weak_type", False))))
        else:
            parts.append(("py", type(leaf).__name__))
    return (str(treedef), tuple(parts))


class PersistentJitCache:
    """On-disk store of ``jax.export``-serialized executables.

    One file per (signature, shapes) pair: a JSON header line (magic,
    environment, payload crc32, recorded ANSI check messages) followed
    by the serialized module.  Writes are atomic (temp + os.replace);
    reads verify environment and checksum and NEVER raise into the
    query — any problem degrades to a fresh compile."""

    def __init__(self, dirpath: str, max_bytes: int = 1 << 30):
        self.dir = dirpath
        self.max_bytes = max_bytes
        os.makedirs(dirpath, exist_ok=True)
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {
            "hits": 0, "misses": 0, "invalid": 0, "stores": 0,
            "storeErrors": 0, "bytesWritten": 0}

    def _bump(self, field: str, by: int = 1) -> None:
        with self._lock:
            self.counters[field] += int(by)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self.counters)
        out["dir"] = self.dir
        return out

    @staticmethod
    def _env() -> Dict[str, str]:
        import jaxlib
        return {"jax": jax.__version__, "jaxlib": jaxlib.__version__,
                "backend": jax.default_backend(),
                "fmt": _FORMAT_VERSION}

    def _path(self, sig, shape_key) -> str:
        # full-width sha256 (the checkpoint.stage_id discipline): a key
        # collision would execute the WRONG program's valid bytes — the
        # one failure the payload checksum cannot catch
        digest = hashlib.sha256(
            repr((sig, shape_key,
                  sorted(self._env().items()))).encode()).hexdigest()
        return os.path.join(self.dir, f"{digest}.jit")

    # ------------------------------------------------------------- load --
    def load(self, sig, shape_key):
        """Deserialized ``jax.export.Exported`` for the pair, or None
        (miss / invalid — the caller compiles fresh either way)."""
        from spark_rapids_tpu.robustness.faults import TimeoutFault
        from spark_rapids_tpu.robustness.inject import (fire, fire_mutate)
        path = self._path(sig, shape_key)
        try:
            fire("jitcache.load")
            if not os.path.exists(path):
                self._bump("misses")
                return None
            with open(path, "rb") as f:
                raw = f.read()
            head, sep, payload = raw.partition(b"\n")
            if not sep:
                raise ValueError("truncated header")
            header = json.loads(head.decode("utf-8"))
            if header.get("magic") != _MAGIC:
                raise ValueError("bad magic")
            if header.get("env") != self._env():
                self._invalid(path, "env-mismatch: entry written by "
                                    f"{header.get('env')}")
                return None
            # chaos hook: offer the payload to an armed corrupt rule so
            # the CRC gate has real rot to catch (checkpoint.restore
            # discipline); raise/delay rules also apply here
            payload = fire_mutate("jitcache.load", payload)
            if len(payload) != header.get("len") or \
                    zlib.crc32(payload) != header.get("crc"):
                self._invalid(path, "crc/length mismatch")
                return None
            from jax import export as jexport
            exported = jexport.deserialize(bytearray(payload))
            checks = header.get("checks")
            if checks is not None:
                STAGE_CHECKS[sig] = list(checks)
            self._bump("hits")
            return exported
        except TimeoutFault:
            raise  # watchdog cancellation at the fire() checkpoint
        except Exception as e:  # noqa: BLE001 - degrade, never fail
            self._invalid(path, f"{type(e).__name__}: {e}")
            return None

    def _invalid(self, path: str, reason: str) -> None:
        """Drop an unusable entry: unlink, count, event — the caller
        falls back to a fresh compile (also counted as a miss: the
        warm-start acceptance pins misses, and an invalid entry DID
        cost a compile)."""
        try:
            os.unlink(path)
        except OSError:
            pass
        self._bump("invalid")
        self._bump("misses")
        try:
            from spark_rapids_tpu.utils.events import emit_on_session
            emit_on_session("JitCacheInvalid", reason=reason,
                            entry=os.path.basename(path))
        except Exception:
            pass  # observability must never mask the degraded load

    # ------------------------------------------------------------ store --
    def store(self, sig, shape_key, jitted, args) -> None:
        """AOT-export the traced program for ``args`` and persist it.
        Best-effort: anything unexportable (exotic primitives, device
        contexts jax.export cannot describe) just skips persistence."""
        try:
            from jax import export as jexport
            exported = jexport.export(jitted)(*args)
            payload = exported.serialize()
            header = {"magic": _MAGIC, "env": self._env(),
                      "crc": zlib.crc32(bytes(payload)),
                      "len": len(payload),
                      # export already traced the function, so trace-
                      # time ANSI messages exist by now
                      "checks": STAGE_CHECKS.get(sig)}
            path = self._path(sig, shape_key)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(json.dumps(header).encode("utf-8"))
                f.write(b"\n")
                f.write(bytes(payload))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self._bump("stores")
            self._bump("bytesWritten", len(payload))
            self._prune()
        except Exception:  # noqa: BLE001 - persistence is an optimization
            self._bump("storeErrors")

    def _prune(self) -> None:
        """Oldest-first eviction keeps the store under ``max_bytes``
        (the checkpoint maxBytes discipline)."""
        try:
            entries = []
            total = 0
            with os.scandir(self.dir) as it:
                for de in it:
                    if de.name.endswith(".jit"):
                        st = de.stat()
                        entries.append((st.st_mtime, st.st_size, de.path))
                        total += st.st_size
            entries.sort()
            while total > self.max_bytes and entries:
                _, size, path = entries.pop(0)
                try:
                    os.unlink(path)
                    total -= size
                except OSError:
                    break
        except OSError:
            pass


_TIER: Optional[PersistentJitCache] = None


def configure_persistent(dirpath: Optional[str],
                         max_bytes: int = 1 << 30) -> None:
    """Enable (or disable, dirpath=None) the persistent tier.  Called at
    session construction from ``spark.rapids.tpu.jitCache.dir``; the
    tier is process-global (the in-memory cache it backs is too).  A
    dir change resets every live entry's shape bindings so already-
    cached signatures re-consult the new store on their next call."""
    global _TIER
    with _LOCK:
        cur_dir = _TIER.dir if _TIER is not None else None
        new_dir = dirpath or None
        if new_dir == cur_dir:
            if _TIER is not None:
                _TIER.max_bytes = max_bytes
            return
        _TIER = PersistentJitCache(new_dir, max_bytes) \
            if new_dir else None
        entries = list(_CACHE.values())
    for e in entries:
        e.rebind()


def persistent_info() -> Dict[str, Any]:
    """Persistent-tier counters (zeros + enabled=False when off)."""
    tier = _TIER
    if tier is None:
        return {"enabled": False, "hits": 0, "misses": 0, "invalid": 0,
                "stores": 0, "storeErrors": 0, "bytesWritten": 0}
    out = tier.snapshot()
    out["enabled"] = True
    return out


class _Entry:
    """The callable ``cached_jit`` returns: counts dispatches and binds
    each input-shape bucket to either the in-process jitted function or
    a warm executable deserialized from the persistent tier."""

    __slots__ = ("sig", "_jit", "_bound", "_lock", "dispatches",
                 "_cold")

    def __init__(self, sig, jitted):
        self.sig = sig
        self._jit = jitted
        self._bound: Dict[Tuple, Callable] = {}
        self._lock = threading.Lock()
        self.dispatches = 0
        self._cold = True  # first dispatch = trace+compile (span site)

    def rebind(self) -> None:
        with self._lock:
            self._bound = {}

    def __call__(self, *args):
        # unlocked bump: a launch counter for tests/observability —
        # losing a rare racing increment beats serializing every
        # dispatch in the process on one mutex
        self.dispatches += 1
        if self._cold:
            # the entry's first dispatch pays the Python trace + XLA
            # compile (or the AOT deserialize): span it and feed the
            # site's compile_ms observation.  Later shape-bucket
            # recompiles (rare) ride untraced — warm dispatches stay a
            # single branch.  The flag flips even when tracing is off
            # so arming mid-process never mis-labels a warm site.
            from spark_rapids_tpu.utils import tracing
            self._cold = False
            if tracing._armed:
                with tracing.span("jit.trace", site=self.sig,
                                  observe="compile_ms"):
                    return self._dispatch(args)
        return self._dispatch(args)

    def _dispatch(self, args):
        tier = _TIER
        if tier is None:
            return self._jit(*args)
        key = _shape_key(args)
        fn = self._bound.get(key)
        if fn is None:
            fn = self._bind(key, args, tier)
        return fn(*args)

    def _bind(self, key, args, tier: PersistentJitCache) -> Callable:
        from spark_rapids_tpu.utils import tracing
        store = False
        with self._lock:
            fn = self._bound.get(key)
            if fn is None:
                with tracing.span("jit.aotLoad", site=self.sig):
                    exported = tier.load(self.sig, key)
                if exported is not None:
                    fn = self._guarded(key, jax.jit(exported.call))
                else:
                    # miss: execution stays on the canonical jit
                    # (donation semantics preserved); the module is
                    # exported below so the NEXT process skips tracing
                    fn = self._jit
                    store = True
                self._bound[key] = fn
        if store:
            # outside the entry lock: export performs its own trace
            # (jax.export cannot reuse the jit call's lowering), so a
            # cold run with the tier on pays the Python trace twice —
            # the documented price of a zero-trace warm start; holding
            # the lock here would also stall concurrent dispatches
            with tracing.span("jit.aotStore", site=self.sig):
                tier.store(self.sig, key, self._jit, args)
        return fn

    def _guarded(self, key, loaded: Callable) -> Callable:
        """First call through a deserialized executable is guarded: an
        export that cannot run in this context (the device set moved
        between save and use) falls back to a fresh trace/compile —
        a degraded load must never fail the query.  Device kernels
        raise no data-dependent Python exceptions (ANSI checks travel
        as output flags), so a first-call exception here can only be a
        binding problem; the fallback re-runs the same computation."""
        ok: List[bool] = []

        def run(*args):
            if ok:
                return loaded(*args)
            try:
                out = loaded(*args)
            except Exception as e:  # noqa: BLE001 - see docstring
                # genuine runtime faults the recovery stack owns must
                # propagate: device OOM belongs to the retry ladder and
                # a watchdog cancellation to the driver — neither means
                # the ENTRY is bad (re-tracing under the same memory
                # pressure would just OOM again, minus one cache entry)
                from spark_rapids_tpu.memory.retry import is_oom
                from spark_rapids_tpu.robustness.faults import \
                    TimeoutFault
                if isinstance(e, TimeoutFault) or is_oom(e):
                    raise
                tier = _TIER
                if tier is not None:
                    tier._invalid(tier._path(self.sig, key),
                                  "deserialized executable failed to "
                                  "bind in this process")
                with self._lock:
                    self._bound[key] = self._jit
                return self._jit(*args)
            ok.append(True)
            return out

        return run


def cached_jit(signature: Hashable, make: Callable[[], Callable],
               **jit_kwargs: Any) -> Callable:
    """Return a jitted callable for ``signature``; build via ``make()``
    on miss.  ``make`` returns the plain (untraced) function to jit — it
    is only invoked when the signature is new (exactly once even under a
    thread race: builders serialize on a per-signature lock), so
    closures over a freshly constructed plan instance are safe as long
    as everything the function's trace depends on is captured in the
    signature."""
    global _HITS, _MISSES
    with _LOCK:
        fn = _CACHE.get(signature)
        if fn is not None:
            _CACHE.move_to_end(signature)
            _HITS += 1
            return fn
        build_lock = _BUILD_LOCKS.setdefault(signature, threading.Lock())
    with build_lock:
        with _LOCK:
            fn = _CACHE.get(signature)
            if fn is not None:
                # a racing builder finished while we waited: share its
                # executable (its jit shape-cache is what every thread
                # must hit)
                _CACHE.move_to_end(signature)
                _HITS += 1
                return fn
        built = _Entry(signature, jax.jit(make(), **jit_kwargs))
        with _LOCK:
            global _EVICTED_DISPATCHES
            _MISSES += 1
            _CACHE[signature] = built
            _BUILD_LOCKS.pop(signature, None)
            while len(_CACHE) > _MAX_ENTRIES:
                old_sig, old = _CACHE.popitem(last=False)
                _EVICTED_DISPATCHES += old.dispatches
                # the trace-time ANSI messages die with the entry, or
                # STAGE_CHECKS would leak one list per evicted shape
                STAGE_CHECKS.pop(old_sig, None)
    return built


def cache_info() -> Dict[str, int]:
    with _LOCK:
        return {"entries": len(_CACHE), "hits": _HITS, "misses": _MISSES}


def dispatch_count() -> int:
    """Total calls through cached executables — the launch counter the
    fusion tests pin (one fused stage = one dispatch per batch)."""
    with _LOCK:
        return _EVICTED_DISPATCHES + sum(e.dispatches
                                         for e in _CACHE.values())


def clear() -> None:
    global _HITS, _MISSES, _EVICTED_DISPATCHES
    with _LOCK:
        # dispatch totals survive: tests pin DELTAS across clear()s
        _EVICTED_DISPATCHES += sum(e.dispatches
                                   for e in _CACHE.values())
        _CACHE.clear()
        _BUILD_LOCKS.clear()
        STAGE_CHECKS.clear()
        _HITS = 0
        _MISSES = 0
