"""Process-wide cache of jitted executables keyed by structural signature.

Physical plans are rebuilt per query, so per-instance ``jax.jit(bound
method)`` would recompile the same XLA program on every run — the dominant
cost for repeated queries (an aggregate stage costs seconds to compile,
microseconds to run).  The reference relies on cudf's precompiled kernels;
the TPU analog is this cache: executables are shared across plan instances
whose expression forests are structurally identical (``Expression.
cache_key`` includes literal values, so constants bake correctly).

The cached callable still goes through jax.jit's own shape-bucket cache, so
one signature may hold several XLA executables (one per input capacity).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Dict, Hashable

import jax

# LRU-bounded: cached entries close over their originating plan instance
# (and thus its child tree), so an unbounded map would pin every distinct
# query shape ever run.  256 signatures comfortably covers a working set
# of queries while keeping retention bounded.
_MAX_ENTRIES = 256
_CACHE: "OrderedDict[Hashable, Callable]" = OrderedDict()


def cached_jit(signature: Hashable, make: Callable[[], Callable],
               **jit_kwargs: Any) -> Callable:
    """Return a jitted callable for ``signature``; build via ``make()`` on
    miss.  ``make`` returns the plain (untraced) function to jit — it is
    only invoked when the signature is new, so closures over a freshly
    constructed plan instance are safe as long as everything the function's
    trace depends on is captured in the signature."""
    fn = _CACHE.get(signature)
    if fn is None:
        fn = jax.jit(make(), **jit_kwargs)
        _CACHE[signature] = fn
        while len(_CACHE) > _MAX_ENTRIES:
            _CACHE.popitem(last=False)
    else:
        _CACHE.move_to_end(signature)
    return fn


def cache_info() -> Dict[str, int]:
    return {"entries": len(_CACHE)}


def clear() -> None:
    _CACHE.clear()
