"""Aggregation kernels: sort-based group-by + masked grand-total reductions.

The reference drives cudf's *hash* group-by (``aggregate.scala:209``
GpuHashAggregateIterator) with a sort-based fallback.  Hash tables scatter
serially and map poorly onto the MXU/VPU, so the TPU-first formulation is the
opposite: group-by IS sort-based — ``lexsort`` by key columns, boundary flags,
prefix-sum segment ids, then ``jax.ops.segment_*`` reductions.  Everything is
static-shaped: a batch of capacity C yields at most C groups, so outputs keep
capacity C with a traced ``num_groups``.

Aggregate functions follow the reference's update/merge split
(AggregateFunctions.scala:334-762): ``update`` reduces raw input into typed
buffer columns; ``merge`` re-reduces buffers across batches/shards; and
``finalize`` computes the result column.  That split is exactly what the
distributed exchange needs (partial agg -> shuffle by key -> final agg).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.expressions import ColVal, Expression, combine_validity


# ------------------------------------------------------------- sort utilities

def _row_mask(nrows, capacity: int, row_mask=None):
    """bool[capacity] of live rows: row_mask overrides the nrows prefix."""
    if row_mask is not None:
        return row_mask
    return jnp.arange(capacity, dtype=jnp.int32) < nrows


def _sortable_keys(keys: Sequence[ColVal], valid_rows, capacity: int,
                   descending: Optional[Sequence[bool]] = None,
                   nulls_first: Optional[Sequence[bool]] = None):
    """Build jnp.lexsort key list (least-significant first) from key columns.

    Dead rows (padding or filtered) always sort last.  Floats are normalized
    so NaN sorts largest and -0.0 == 0.0 (Spark ordering).
    """
    n = len(keys)
    descending = descending or [False] * n
    nulls_first = nulls_first or [not d for d in descending]
    pad = jnp.logical_not(valid_rows)
    lex: List = []
    # jnp.lexsort sorts by last key first; we append least-significant first
    for c, desc, nf in zip(reversed(list(keys)), reversed(list(descending)),
                           reversed(list(nulls_first))):
        c = widen_colval(c, capacity)
        v = c.values
        if c.validity is not None:
            # canonicalize raw values under null BEFORE building the
            # order keys: otherwise null rows scatter by their garbage
            # payload, splitting the null group whenever a
            # lower-significance key varies (the coded/hashed group-by
            # paths treat all nulls as one digit, and SQL groups nulls
            # together)
            v = jnp.where(c.validity, v, jnp.zeros_like(v))
        lex.extend(_order_keys(v, desc))
        if c.validity is not None:
            null_key = jnp.logical_not(c.validity).astype(jnp.int8)
            lex.append(-null_key if nf else null_key)
    lex.append(pad.astype(jnp.int8))  # most significant: dead rows last
    return lex


def _order_keys(v, desc: bool) -> List:
    """Lexsort key pieces (least-significant first) realizing the Spark
    total order for one column.  No 64-bit bitcasts: TPU's X64 rewriter
    cannot lower f64<->u64 bitcast-convert, so floats sort as a normalized
    float key plus a more-significant NaN flag (NaN largest, -0.0 == 0.0),
    ints directly (descending via bitwise-not, monotone-decreasing for
    two's-complement)."""
    if jnp.issubdtype(v.dtype, jnp.floating):
        nan = jnp.isnan(v)
        f = jnp.where(v == 0.0, 0.0, v)
        f = jnp.where(nan, 0.0, f)
        flag = nan.astype(jnp.int8)
        if desc:
            return [-f, -flag]
        return [f, flag]
    if v.dtype == jnp.bool_:
        v = v.astype(jnp.int8)
        return [~v] if desc else [v]
    return [~v] if desc else [v]


def widen_colval(c: ColVal, capacity: int) -> ColVal:
    """Scalar-broadcast values/validity (e.g. from literal-operand
    arithmetic) widen to full columns before sort/gather — lexsort and
    row gathers require uniform shapes."""
    v, val = c.values, c.validity
    if getattr(v, "ndim", 0) == 0:
        v = jnp.broadcast_to(v, (capacity,))
    if val is not None and getattr(val, "ndim", 0) == 0:
        val = jnp.broadcast_to(val, (capacity,))
    if v is c.values and val is c.validity:
        return c
    return ColVal(c.dtype, v, val, c.offsets)


def sort_permutation(keys: Sequence[ColVal], valid_rows, capacity: int,
                     descending: Optional[Sequence[bool]] = None,
                     nulls_first: Optional[Sequence[bool]] = None):
    lex = _sortable_keys(keys, valid_rows, capacity, descending, nulls_first)
    return jnp.lexsort(lex).astype(jnp.int32)


def _keys_equal_prev(sorted_keys: Sequence[ColVal], capacity: int):
    """bool[capacity]: row i has identical keys to row i-1 (nulls equal)."""
    eq = jnp.ones(capacity, dtype=jnp.bool_)
    for c in sorted_keys:
        v = c.values
        if jnp.issubdtype(v.dtype, jnp.floating):
            v = jnp.where(v == 0.0, 0.0, v)
            same = (v == jnp.roll(v, 1)) | (jnp.isnan(v) &
                                            jnp.isnan(jnp.roll(v, 1)))
        else:
            same = v == jnp.roll(v, 1)
        if c.validity is not None:
            pv = jnp.roll(c.validity, 1)
            same = jnp.where(c.validity & pv, same,
                             jnp.logical_not(c.validity | pv))
        eq = jnp.logical_and(eq, same)
    return eq.at[0].set(False)


# --------------------------------------------------------- aggregate functions

@dataclasses.dataclass(frozen=True)
class BufferSpec:
    """One reduction buffer: how to seed it from input and re-reduce it."""
    kind: str          # 'sum' | 'min' | 'max' | 'count' | 'first' |
    #                    'last' | 'first_any' | 'last_any'
    dtype: DataType


def merge_kind(update_kind: str) -> str:
    """Reduction kind applied when re-reducing PARTIAL buffer rows
    (chunked merge and the mesh exchange).  The one mapping both the
    single-host merge (exec/aggregate.py) and the distributed merge
    (parallel/distributed.py) import — the *_any update kinds collapse
    to plain first/last because their partial validity means
    "observed >=1 live row" (presence), and first-present IS the
    ignoreNulls=false merge rule."""
    return {"sum": "sum", "count": "sum", "min": "min", "max": "max",
            "first": "first", "last": "last",
            "first_any": "first", "last_any": "last"}[update_kind]


class AggregateFunction:
    """Base: declares buffers, update transform, and finalize."""

    name = "agg"

    def __init__(self, child: Optional[Expression]):
        self.child = child

    # buffer schema produced by update (and consumed/produced by merge)
    def buffers(self) -> List[BufferSpec]:
        raise NotImplementedError

    def update_inputs(self, c: Optional[ColVal], capacity: int) -> List[ColVal]:
        """Map the evaluated child column to one ColVal per buffer."""
        raise NotImplementedError

    def finalize(self, bufs: List[ColVal]) -> ColVal:
        raise NotImplementedError

    @property
    def result_dtype(self) -> DataType:
        raise NotImplementedError

    @property
    def result_nullable(self) -> bool:
        return True

    def cache_key(self):
        return (type(self).__name__,
                self.child.cache_key() if self.child is not None else None)

    def supported_reason(self) -> Optional[str]:
        """None when the device can run this aggregate; else why not
        (the planner tags it and the query falls back)."""
        return None


def _sum_result_type(t: DataType) -> DataType:
    if t.is_floating:
        return dts.FLOAT64
    if t.is_decimal:
        # Spark: sum(decimal(p,s)) = decimal(p+10, s), capped at
        # DECIMAL_64 (device eligibility is gated separately in
        # supported_reason: p+10 > 18 falls back to CPU)
        from spark_rapids_tpu.columnar.dtypes import DecimalType
        return DecimalType(min(t.precision + 10, 18), t.scale)
    return dts.INT64


class Sum(AggregateFunction):
    name = "sum"

    @property
    def result_dtype(self):
        return _sum_result_type(self.child.dtype)

    def supported_reason(self):
        t = self.child.dtype
        if t.is_decimal and t.precision + 10 > 18:
            # the int64 accumulator could silently wrap past DECIMAL_64
            # (the reference's DECIMAL_64 sum gate)
            return (f"sum over {t} needs decimal({t.precision + 10},"
                    f"{t.scale}) > DECIMAL_64; falls back to CPU")
        return None

    def buffers(self):
        return [BufferSpec("sum", self.result_dtype)]

    def update_inputs(self, c, capacity):
        t = self.result_dtype
        return [ColVal(t, c.values.astype(t.storage), c.validity)]

    def finalize(self, bufs):
        return bufs[0]


class Count(AggregateFunction):
    """count(expr) — count(Literal(1)) is count(*)."""

    name = "count"

    @property
    def result_dtype(self):
        return dts.INT64

    @property
    def result_nullable(self):
        return False

    def buffers(self):
        return [BufferSpec("sum", dts.INT64)]

    def update_inputs(self, c, capacity):
        if c is None or c.validity is None:
            ones = jnp.ones(capacity, dtype=jnp.int64)
            return [ColVal(dts.INT64, ones)]
        return [ColVal(dts.INT64, c.validity.astype(jnp.int64))]

    def finalize(self, bufs):
        v = bufs[0]
        # count is 0, never null, for empty groups
        if v.validity is not None:
            return ColVal(dts.INT64, jnp.where(v.validity, v.values, 0))
        return v


class Min(AggregateFunction):
    name = "min"

    @property
    def result_dtype(self):
        return self.child.dtype

    def buffers(self):
        return [BufferSpec("min", self.child.dtype)]

    def update_inputs(self, c, capacity):
        return [c]

    def finalize(self, bufs):
        return bufs[0]


class Max(AggregateFunction):
    name = "max"

    @property
    def result_dtype(self):
        return self.child.dtype

    def buffers(self):
        return [BufferSpec("max", self.child.dtype)]

    def update_inputs(self, c, capacity):
        return [c]

    def finalize(self, bufs):
        return bufs[0]


class Average(AggregateFunction):
    name = "avg"

    @property
    def result_dtype(self):
        if self.child is not None and self.child.dtype.is_decimal:
            # Spark avg(decimal(p,s)) = decimal(p+4, s+4) (capped)
            from spark_rapids_tpu.ops.decimal_ops import (
                adjust_precision_scale)
            t = self.child.dtype
            return adjust_precision_scale(t.precision + 4, t.scale + 4)
        return dts.FLOAT64

    def supported_reason(self):
        if self.child is not None and self.child.dtype.is_decimal:
            # the rounded unscaled division needs a 128-bit intermediate
            return (f"avg over {self.child.dtype} not supported on "
                    "device; falls back to CPU")
        return None

    def buffers(self):
        return [BufferSpec("sum", dts.FLOAT64), BufferSpec("sum", dts.INT64)]

    def update_inputs(self, c, capacity):
        return [ColVal(dts.FLOAT64, c.values.astype(jnp.float64), c.validity),
                ColVal(dts.INT64,
                       c.validity.astype(jnp.int64) if c.validity is not None
                       else jnp.ones(capacity, dtype=jnp.int64))]

    def finalize(self, bufs):
        s, n = bufs
        cnt = jnp.where(n.values == 0, 1, n.values)
        validity = combine_validity(s.validity, n.values > 0)
        return ColVal(dts.FLOAT64, s.values / cnt, validity)


class _CentralMoment(AggregateFunction):
    """Base for variance/stddev: buffers are sum(x), sum(x^2), n — all
    merge-by-sum, so chunked partial merge and the mesh exchange work
    unchanged.  Spark's CPU path uses Welford updates; the sum-of-squares
    form fits the engine's single-pass variadic reduce and matches to
    ~1e-9 relative on double inputs (documented incompat class, like
    cudf's).  Reference: GpuStddevSamp/GpuVariancePop rules in
    GpuOverrides.scala (aggregate section)."""

    ddof = 0          # 0 = population, 1 = sample
    sqrt_result = False

    @property
    def result_dtype(self):
        return dts.FLOAT64

    def supported_reason(self):
        t = self.child.dtype
        if not (t.is_numeric or t.is_boolean):
            return (f"{self.name} over {t.name} values has no device "
                    "implementation")
        return None

    def buffers(self):
        return [BufferSpec("sum", dts.FLOAT64),
                BufferSpec("sum", dts.FLOAT64),
                BufferSpec("sum", dts.INT64)]

    def update_inputs(self, c, capacity):
        x = c.values.astype(jnp.float64)
        ones = (c.validity.astype(jnp.int64) if c.validity is not None
                else jnp.ones(capacity, dtype=jnp.int64))
        return [ColVal(dts.FLOAT64, x, c.validity),
                ColVal(dts.FLOAT64, x * x, c.validity),
                ColVal(dts.INT64, ones)]

    def finalize(self, bufs):
        s, s2, n = bufs
        cnt = n.values.astype(jnp.float64)
        denom = cnt - self.ddof
        safe_cnt = jnp.where(cnt == 0, 1.0, cnt)
        safe_denom = jnp.where(denom <= 0, 1.0, denom)
        m2 = s2.values - (s.values * s.values) / safe_cnt
        m2 = jnp.maximum(m2, 0.0)  # clamp catastrophic cancellation
        out = m2 / safe_denom
        if self.sqrt_result:
            out = jnp.sqrt(out)
        # var_pop defined for n>=1; *_samp needs n>=2 (Spark returns
        # NaN for n==1 sample variance, null for n==0)
        nan = jnp.where(jnp.logical_and(self.ddof == 1, cnt == 1),
                        jnp.float64(jnp.nan), out)
        validity = combine_validity(s.validity, n.values > 0)
        return ColVal(dts.FLOAT64, nan, validity)


class VariancePop(_CentralMoment):
    name = "var_pop"
    ddof = 0


class VarianceSamp(_CentralMoment):
    name = "var_samp"
    ddof = 1


class StddevPop(_CentralMoment):
    name = "stddev_pop"
    ddof = 0
    sqrt_result = True


class StddevSamp(_CentralMoment):
    name = "stddev_samp"
    ddof = 1
    sqrt_result = True


class First(AggregateFunction):
    name = "first"

    def __init__(self, child, ignore_nulls: bool = False):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    @property
    def result_dtype(self):
        return self.child.dtype

    _any_kind = "first_any"

    def cache_key(self):
        # the buffer schema depends on _classic, so jit-cache keys must
        # distinguish ignoreNulls and child nullability
        return (type(self).__name__, self._classic,
                self.child.cache_key() if self.child is not None else None)

    def buffers(self):
        # Spark default ignoreNulls=false: the group's first ROW wins,
        # null or not.  Two buffers: the value at the first live row
        # (buffer validity = "this partial observed >=1 live row", so a
        # filtered-empty partial can never win the merge) plus the
        # selected row's validity bit as a VALUE.  Merge reduces both
        # with plain first/last over partial presence.  With
        # ignoreNulls the single classic first-valid buffer suffices.
        if self._classic:
            return [BufferSpec(self.name, self.child.dtype)]
        return [BufferSpec(self._any_kind, self.child.dtype),
                BufferSpec(self._any_kind, dts.BOOL)]

    @property
    def _classic(self) -> bool:
        """Single first-valid buffer suffices: ignoreNulls requested, or
        the child is statically non-nullable (first-valid == first-row)."""
        return self.ignore_nulls or not self.child.nullable

    def update_inputs(self, c, capacity):
        if self._classic:
            return [c]
        vbit = c.validity if c.validity is not None else \
            jnp.ones(capacity, dtype=jnp.bool_)
        return [ColVal(c.dtype, c.values, None),
                ColVal(dts.BOOL, vbit, None)]

    def finalize(self, bufs):
        if self._classic:
            return bufs[0]
        v, bit = bufs
        validity = combine_validity(v.validity, bit.values)
        return ColVal(v.dtype, v.values, validity)


class Last(First):
    name = "last"
    _any_kind = "last_any"


# ------------------------------------------------------------ reduction cores

def _sentinel(kind: str, np_dtype):
    np_dtype = np.dtype(np_dtype)
    if np_dtype.kind == "f":
        info = np.finfo(np_dtype)
        return info.max if kind == "min" else info.min
    if np_dtype.kind == "b":
        return True if kind == "min" else False
    info = np.iinfo(np_dtype)
    return info.max if kind == "min" else info.min


def _segment_reduce(kind: str, c: ColVal, seg_ids, num_segments: int,
                    valid_rows):
    """Reduce one buffer column by segment. Returns (values, nonnull_counts)."""
    contrib_valid = valid_rows if c.validity is None else \
        jnp.logical_and(valid_rows, c.validity)
    counts = jax.ops.segment_sum(contrib_valid.astype(jnp.int64), seg_ids,
                                 num_segments=num_segments)
    if kind == "sum":
        vals = jnp.where(contrib_valid, c.values,
                         jnp.zeros((), dtype=c.values.dtype))
        out = jax.ops.segment_sum(vals, seg_ids, num_segments=num_segments)
    elif kind == "min":
        vals = jnp.where(contrib_valid, c.values, _sentinel("min", c.values.dtype))
        out = jax.ops.segment_min(vals, seg_ids, num_segments=num_segments)
    elif kind == "max":
        vals = jnp.where(contrib_valid, c.values, _sentinel("max", c.values.dtype))
        out = jax.ops.segment_max(vals, seg_ids, num_segments=num_segments)
    elif kind in ("first", "last"):
        n = c.values.shape[0]
        idx = jnp.arange(n, dtype=jnp.int64)
        if kind == "first":
            pick = jnp.where(contrib_valid, idx, n)
            best = jax.ops.segment_min(pick, seg_ids, num_segments=num_segments)
        else:
            pick = jnp.where(contrib_valid, idx, -1)
            best = jax.ops.segment_max(pick, seg_ids, num_segments=num_segments)
        safe = jnp.clip(best, 0, n - 1).astype(jnp.int32)
        out = c.values[safe]
    elif kind in ("first_any", "last_any"):
        # ignoreNulls=false update: the first/last LIVE row wins
        # regardless of value validity.  counts = LIVE rows, so the
        # buffer's validity means "this partial observed any row"
        # (presence) — the merge then reduces with plain first/last
        # over presence and First.finalize re-applies the selected
        # row's validity bit from the companion buffer.
        n = c.values.shape[0]
        idx = jnp.arange(n, dtype=jnp.int64)
        if kind == "first_any":
            pick = jnp.where(valid_rows, idx, n)
            best = jax.ops.segment_min(pick, seg_ids,
                                       num_segments=num_segments)
        else:
            pick = jnp.where(valid_rows, idx, -1)
            best = jax.ops.segment_max(pick, seg_ids,
                                       num_segments=num_segments)
        safe = jnp.clip(best, 0, n - 1).astype(jnp.int32)
        out = c.values[safe]
        counts = jax.ops.segment_sum(
            valid_rows.astype(jnp.int64), seg_ids,
            num_segments=num_segments)
    else:
        raise ValueError(f"unknown reduce kind {kind}")
    return out, counts


def groupby_aggregate(keys: Sequence[ColVal],
                      buffer_inputs: Sequence[Tuple[str, ColVal]],
                      nrows, capacity: int, row_mask=None):
    """Group by ``keys``, reduce each (kind, column) buffer input.

    All arguments are traced values; runs inside jit.  ``row_mask`` (if
    given) marks live rows — a fused upstream filter — overriding the
    ``nrows`` prefix.  Returns (out_keys, out_buffers, num_groups); output
    rows beyond num_groups are padding.
    """
    from spark_rapids_tpu.ops import selection

    keys = [widen_colval(c, capacity) for c in keys]
    buffer_inputs = [(k, widen_colval(c, capacity))
                     for k, c in buffer_inputs]
    live = _row_mask(nrows, capacity, row_mask)
    n_live = live.sum().astype(jnp.int32)
    perm = sort_permutation(keys, live, capacity)
    # after the sort all live rows form a prefix of length n_live
    valid_sorted_mask = jnp.arange(capacity, dtype=jnp.int32) < n_live
    sorted_keys = selection.gather(keys, perm, n_live)
    sorted_bufs = selection.gather([c for _, c in buffer_inputs], perm,
                                   n_live)

    same_as_prev = _keys_equal_prev(sorted_keys, capacity)
    boundary = jnp.logical_and(jnp.logical_not(same_as_prev),
                               valid_sorted_mask)
    num_groups = boundary.sum().astype(jnp.int32)
    seg_ids = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    # padding rows -> a trash segment that segment_* drops (>= num_segments)
    seg_ids = jnp.where(valid_sorted_mask, seg_ids, capacity)

    out_bufs: List[ColVal] = []
    for (kind, _), sc in zip(buffer_inputs, sorted_bufs):
        vals, counts = _segment_reduce(kind, sc, seg_ids, capacity,
                                       valid_sorted_mask)
        out_bufs.append(ColVal(sc.dtype, vals, counts > 0))

    # representative row (first) of each group for the key values
    first_idx = jax.ops.segment_min(
        jnp.arange(capacity, dtype=jnp.int64), seg_ids, num_segments=capacity)
    first_idx = jnp.clip(first_idx, 0, capacity - 1).astype(jnp.int32)
    out_keys = selection.gather(sorted_keys, first_idx, num_groups)
    return out_keys, out_bufs, num_groups


# --------------------------------------------------- coded (sort-free) path
# XLA's variadic sort is the dominant cost of the sort-based group-by
# (seconds per multi-million-row batch on CPU, and serial on TPU's VPU);
# when every key is fixed-width integral and the key-space product is
# small, groups are addressed DIRECTLY: code = radix-mix of (key - min)
# digits, one segment-reduce per buffer into the code table, then a
# cumsum-compaction of occupied slots.  No sort anywhere.  The reference
# reaches the same regime with cudf's hash aggregation
# (aggregate.scala:184-209 hash first, sort only as fallback).

MAX_CODED_GROUPS = 1 << 21


def coded_key_eligible(dtypes) -> bool:
    """Keys a radix code can address: fixed-width, non-float (floats
    have no dense integer range)."""
    return all(
        not dt.has_offsets and not dt.is_floating
        for dt in dtypes)


def key_range_probe(keys: Sequence[ColVal], live):
    """Per-key (min, max) over live valid rows as int64[nkeys] pair —
    fused into stage A so range discovery costs one pass, synced to the
    host to pick coded vs sort dispatch.  All 2*nkeys reductions ride a
    single multi-operand lax.reduce (one pass over the key columns)."""
    operands, inits = [], []
    for c in keys:
        v = c.values
        if v.dtype == jnp.bool_:
            v = v.astype(jnp.int32)
        info = jnp.iinfo(v.dtype)
        valid = live if c.validity is None else \
            jnp.logical_and(live, c.validity)
        operands.append(jnp.where(valid, v, info.max))
        inits.append(jnp.asarray(info.max, dtype=v.dtype))
        operands.append(jnp.where(valid, v, info.min))
        inits.append(jnp.asarray(info.min, dtype=v.dtype))

    def comp(acc, x):
        out = []
        for i, (a, b) in enumerate(zip(acc, x)):
            out.append(jnp.minimum(a, b) if i % 2 == 0
                       else jnp.maximum(a, b))
        return tuple(out)

    res = jax.lax.reduce(tuple(operands), tuple(inits), comp, [0])
    mins = jnp.stack([res[2 * i].astype(jnp.int64)
                      for i in range(len(keys))])
    maxs = jnp.stack([res[2 * i + 1].astype(jnp.int64)
                      for i in range(len(keys))])
    return mins, maxs


def coded_slot_ranges(mins: np.ndarray, maxs: np.ndarray):
    """Host-side: per-key slot count (digit 0 is ALWAYS the null slot,
    whether or not the key is nullable — keeps the host sizing and the
    traced validity structure trivially consistent) and the total
    key-space size; None when the space is too large for the coded
    path."""
    slots = []
    total = 1
    for mn, mx in zip(mins.tolist(), maxs.tolist()):
        rn = max(0, int(mx) - int(mn) + 1)
        slots.append(rn + 1)
        total *= rn + 1
        if total > MAX_CODED_GROUPS:
            return None
    return slots, total


def _segment_reduce_coded(kind: str, c: ColVal, code, ns: int,
                          counts_of):
    """One buffer reduction for the coded path.  Null/dead rows are
    folded into the TRASH SEGMENT of the code vector instead of masking
    the value column — an int32 pass (or none) replaces the full-width
    ``where`` pass per buffer.  ``counts_of(validity)`` returns (cached)
    per-slot live counts for a validity array."""
    capacity = code.shape[0]
    vals = c.values
    if getattr(vals, "ndim", 0) == 0:
        vals = jnp.broadcast_to(vals, (capacity,))
    if kind in ("first_any", "last_any"):
        # ignoreNulls=false update: route by the LIVE code (null-valued
        # rows stay in their group); counts = live rows (presence)
        idx = jnp.arange(capacity, dtype=jnp.int32)
        seg_op = jax.ops.segment_min if kind == "first_any" \
            else jax.ops.segment_max
        best = seg_op(idx, code, num_segments=ns)
        safe = jnp.clip(best, 0, capacity - 1)
        return vals[safe][: ns - 1], counts_of(None, code)
    if c.validity is not None:
        bcode = jnp.where(c.validity, code, ns - 1)
    else:
        bcode = code
    counts = counts_of(c.validity, bcode)
    if kind == "sum":
        out = jax.ops.segment_sum(vals, bcode, num_segments=ns)
    elif kind == "min":
        out = jax.ops.segment_min(vals, bcode, num_segments=ns)
    elif kind == "max":
        out = jax.ops.segment_max(vals, bcode, num_segments=ns)
    elif kind in ("first", "last"):
        idx = jnp.arange(capacity, dtype=jnp.int32)
        if kind == "first":
            best = jax.ops.segment_min(idx, bcode, num_segments=ns)
        else:
            best = jax.ops.segment_max(idx, bcode, num_segments=ns)
        safe = jnp.clip(best, 0, capacity - 1)
        out = vals[safe]
    else:
        raise ValueError(f"unknown reduce kind {kind}")
    return out[: ns - 1], counts


def groupby_aggregate_coded(keys: Sequence[ColVal],
                            buffer_inputs: Sequence[Tuple[str, ColVal]],
                            nrows, capacity: int, mins, slot_ranges,
                            k_bucket: int, row_mask=None):
    """Sort-free group-by: keys must be fixed-width integral with the
    key-space product <= ``k_bucket`` (static).  ``mins``/``slot_ranges``
    are traced int64[nkeys] (data-dependent, but only k_bucket shapes the
    program).  Output groups are ordered ascending with nulls first —
    identical to the sort path's order.  Output arrays are sized by the
    key space (max(k_bucket, 1024)), NOT the input capacity."""
    nkeys = len(keys)
    keys = [widen_colval(c, capacity) for c in keys]
    live = _row_mask(nrows, capacity, row_mask)

    # row codes: digit 0 = null (nulls first), 1.. = value - min + 1
    # (digit 0 is reserved even for non-nullable keys — see
    # coded_slot_ranges)
    code = jnp.zeros(capacity, dtype=jnp.int64)
    stride = jnp.int64(1)
    strides_rev = []
    for i in reversed(range(nkeys)):
        c = keys[i]
        v = c.values
        if v.dtype == jnp.bool_:
            v = v.astype(jnp.int32)
        v = v.astype(jnp.int64)
        rn = slot_ranges[i] - 1
        d = jnp.clip(v - mins[i], 0, jnp.maximum(rn - 1, 0)) + 1
        if c.validity is not None:
            d = jnp.where(c.validity, d, 0)
        code = code + d * stride
        strides_rev.append(stride)
        stride = stride * slot_ranges[i]
    strides = strides_rev[::-1]
    # clamp before the narrowing cast: the speculative path
    # (groupby_aggregate_coded_auto) runs this body even when the key
    # space overflows the bucket — codes must stay in-range garbage
    # (the trash segment), never wrap through int32
    code = jnp.clip(code, 0, k_bucket)
    code = jnp.where(live, code, k_bucket).astype(jnp.int32)
    ns = k_bucket + 1

    # ---- batched sum scatter -------------------------------------------
    # Same-dtype/same-validity "sum" buffers stack into ONE 2D
    # segment-sum: the scatter index is computed once per row for all of
    # them instead of once per buffer.  A validity-free float64/integer
    # group additionally carries a ones column, so the per-slot live
    # counts (the bincount) ride the same scatter — q1's four sums plus
    # its counts collapse from five scatters to one.  (ones ride only in
    # dtypes where the count sums exactly: f64 up to 2^53, integers.)
    sum_groups: Dict[tuple, List[int]] = {}
    for j, (kind, c) in enumerate(buffer_inputs):
        v = c.values
        if kind == "sum" and getattr(v, "ndim", 0) == 1:
            key = (v.dtype,
                   id(c.validity) if c.validity is not None else None)
            sum_groups.setdefault(key, []).append(j)
    slot_counts_all = None
    batched_sums: Dict[int, Tuple] = {}  # j -> (per-slot sums, validity)
    for (dt, vid), idxs in sum_groups.items():
        exact_ones = dt == jnp.float64 or jnp.issubdtype(dt, jnp.integer)
        fuse_counts = vid is None and exact_ones and \
            slot_counts_all is None
        if len(idxs) < 2 and not fuse_counts:
            continue
        cs = [buffer_inputs[j][1] for j in idxs]
        validity = cs[0].validity
        bcode = code if validity is None else \
            jnp.where(validity, code, ns - 1)
        cols = [c.values for c in cs]
        if fuse_counts:
            cols = cols + [jnp.ones(capacity, dtype=dt)]
        stacked = jnp.stack(cols, axis=1)
        summed = jax.ops.segment_sum(stacked, bcode, num_segments=ns)
        if fuse_counts:
            slot_counts_all = summed[:, -1].astype(jnp.int64)
        for col_i, j in enumerate(idxs):
            batched_sums[j] = (summed[:, col_i], validity)

    # per-slot live counts, shared by every buffer whose validity is None
    if slot_counts_all is None:
        slot_counts_all = jnp.bincount(code, length=ns)
    counts_cache = {}

    def counts_of(validity, bcode):
        if validity is None:
            return slot_counts_all[:k_bucket]
        key = id(validity)
        got = counts_cache.get(key)
        if got is None:
            got = jnp.bincount(bcode, length=ns)[:k_bucket]
            counts_cache[key] = got
        return got

    slot_counts = slot_counts_all[:k_bucket]
    occupied = slot_counts > 0
    num_groups = occupied.sum().astype(jnp.int32)
    pos = jnp.cumsum(occupied.astype(jnp.int32)) - 1
    # compaction scatter target: occupied slot -> dense position,
    # unoccupied -> out_cap (dropped); outputs are key-space sized
    out_cap = max(k_bucket, 1024)
    out_idx = jnp.where(occupied, pos, out_cap)

    slots = jnp.arange(k_bucket, dtype=jnp.int64)
    out_keys: List[ColVal] = []
    for i, c in enumerate(keys):
        digit = (slots // jnp.maximum(strides[i], 1)) % \
            jnp.maximum(slot_ranges[i], 1)
        vals = mins[i] + digit - 1
        if c.validity is not None:
            vd = jnp.zeros(out_cap, dtype=jnp.bool_)
            vd = vd.at[out_idx].set(digit > 0, mode="drop")
        else:
            vd = None  # digit 0 never occupied without nulls
        out_dt = c.values.dtype
        if out_dt == jnp.bool_:
            vals = vals.astype(jnp.int64) != 0
        dst = jnp.zeros(out_cap, dtype=out_dt)
        dst = dst.at[out_idx].set(vals.astype(out_dt), mode="drop")
        out_keys.append(ColVal(c.dtype, dst, vd))

    def compact(c, vals, counts):
        vals, counts = vals[:k_bucket], counts[:k_bucket]
        dv = jnp.zeros(out_cap, dtype=vals.dtype)
        dv = dv.at[out_idx].set(vals, mode="drop")
        dvalid = jnp.zeros(out_cap, dtype=jnp.bool_)
        dvalid = dvalid.at[out_idx].set(counts > 0, mode="drop")
        return ColVal(c.dtype, dv, dvalid)

    out_bufs: List[Optional[ColVal]] = [None] * len(buffer_inputs)
    for j, (kind, c) in enumerate(buffer_inputs):
        got = batched_sums.get(j)
        if got is not None:
            summed_col, validity = got
            bcode = code if validity is None else \
                jnp.where(validity, code, ns - 1)
            out_bufs[j] = compact(c, summed_col[: ns - 1],
                                  counts_of(validity, bcode))
            continue
        vals, counts = _segment_reduce_coded(kind, c, code, ns,
                                             counts_of)
        out_bufs[j] = compact(c, vals, counts)
    return out_keys, out_bufs, num_groups


def coded_ranges_on_device(keys: Sequence[ColVal], live, k_bucket: int):
    """On-device analog of probe + ``coded_slot_ranges``: per-key
    (min, max), clamped per-key slot counts, and a ``fits`` flag for
    ``total key space <= k_bucket``.  Everything stays device-resident,
    so the coded-vs-sort dispatch needs ONE host sync (the flag) instead
    of a probe round trip followed by a second kernel launch.

    Overflow discipline: slot counts and the running product are clamped
    (the clamps only bite when ``fits`` is already False, where the coded
    output is discarded anyway), so the arithmetic never wraps into a
    spuriously-fitting total."""
    mins, maxs = key_range_probe(keys, live)
    rn = jnp.maximum(maxs - mins + 1, 0)
    slot_ranges = rn + 1  # +1: digit 0 is always the null slot
    total = jnp.int64(1)
    total_cap = jnp.int64(1) << 40
    for i in range(len(keys)):
        s = jnp.clip(slot_ranges[i], 1, jnp.int64(1) << 20)
        total = jnp.minimum(total * s, total_cap)
    fits = total <= k_bucket
    safe_ranges = jnp.minimum(slot_ranges, jnp.int64(k_bucket) + 1)
    return mins, maxs, safe_ranges, fits


def groupby_aggregate_coded_auto(keys: Sequence[ColVal],
                                 buffer_inputs: Sequence[Tuple[str, ColVal]],
                                 nrows, capacity: int, k_bucket: int,
                                 row_mask=None):
    """Single-pass speculative coded group-by: range discovery, fit
    check and the coded reduction run in ONE computation against a
    fixed speculative ``k_bucket``.  Returns
    (out_keys, out_bufs, num_groups, fits, mins, maxs): when ``fits``
    is True the outputs are exact (identical ordering to the sort
    path); when False they are garbage to discard, and the caller
    re-dispatches from the already-computed (mins, maxs) — the old
    two-pass probe cost is only ever paid on speculation misses."""
    keys = [widen_colval(c, capacity) for c in keys]
    live = _row_mask(nrows, capacity, row_mask)
    mins, maxs, safe_ranges, fits = coded_ranges_on_device(
        keys, live, k_bucket)
    out_keys, out_bufs, num_groups = groupby_aggregate_coded(
        keys, buffer_inputs, nrows, capacity, mins, safe_ranges,
        k_bucket, row_mask=row_mask)
    return out_keys, out_bufs, num_groups, fits, mins, maxs


MAX_HASHED_KEYSPACE = 1 << 62


def hashed_slot_ranges(mins: np.ndarray, maxs: np.ndarray):
    """Host-side analog of :func:`coded_slot_ranges` for the HASH path:
    no dense-table cap — the radix code only needs to stay injective in
    int64, so the bound is the key-space product staying under 2**62
    (strides never overflow).  None when even that fails (the sort path
    remains the backstop)."""
    slots = []
    total = 1
    for mn, mx in zip(mins.tolist(), maxs.tolist()):
        rn = max(0, int(mx) - int(mn) + 1)
        slots.append(rn + 1)
        total *= rn + 1
        if total > MAX_HASHED_KEYSPACE:
            return None
    return slots, total


def groupby_aggregate_hashed(keys: Sequence[ColVal],
                             buffer_inputs: Sequence[Tuple[str, ColVal]],
                             nrows, capacity: int, mins, slot_ranges,
                             table_slots: int, row_mask=None,
                             interpret=None):
    """Single-pass hash group-by: the same injective radix code as the
    coded path (digit 0 = null, so nulls-first ordering falls out of the
    arithmetic) but addressed through a ``table_slots``-entry
    open-addressing table instead of a dense code-space table — the key
    space may be astronomically larger than the live group count.

    Returns ``(out_keys, out_bufs, num_groups, overflow)``.  When
    ``overflow`` is True (probe-chain blowout or more groups than the
    table holds) the outputs are garbage to DISCARD — the caller re-runs
    the sort/segment-sum path; rows are never dropped.  When False the
    outputs are bit-identical to the coded/sort paths: occupied slots
    compact in stored-code-ascending order, and group membership,
    per-group reductions, and first/last representatives (original row
    index) do not depend on table layout."""
    from spark_rapids_tpu.ops import pallas_kernels as pk
    nkeys = len(keys)
    keys = [widen_colval(c, capacity) for c in keys]
    live = _row_mask(nrows, capacity, row_mask)

    code = jnp.zeros(capacity, dtype=jnp.int64)
    stride = jnp.int64(1)
    strides_rev = []
    for i in reversed(range(nkeys)):
        c = keys[i]
        v = c.values
        if v.dtype == jnp.bool_:
            v = v.astype(jnp.int32)
        v = v.astype(jnp.int64)
        rn = slot_ranges[i] - 1
        d = jnp.clip(v - mins[i], 0, jnp.maximum(rn - 1, 0)) + 1
        if c.validity is not None:
            d = jnp.where(c.validity, d, 0)
        code = code + d * stride
        strides_rev.append(stride)
        stride = stride * slot_ranges[i]
    strides = strides_rev[::-1]

    lo = code.astype(jnp.int32)          # low 32 bits (truncating cast)
    hi = (code >> 32).astype(jnp.int32)
    if interpret is None:
        slot, tlo, thi, occupied, overflow = pk.hash_table_insert(
            lo, hi, live, table_slots)
    else:
        slot, tlo, thi, occupied, overflow = pk.hash_insert(
            lo, hi, live, table_slots, interpret=interpret)
    T = table_slots
    ns = T + 1
    slot = slot.astype(jnp.int32)
    slot_code = (thi.astype(jnp.int64) << 32) \
        | (tlo.astype(jnp.int64) & jnp.int64(0xFFFFFFFF))

    # compaction ordered by STORED CODE ascending — exactly the coded
    # path's slot-index order (slot == code there), so the output is
    # independent of table layout (pallas vs XLA insert)
    sortkey = jnp.where(occupied, slot_code,
                        jnp.iinfo(jnp.int64).max)
    order = jnp.argsort(sortkey)
    rank = jnp.zeros(T, dtype=jnp.int32).at[order].set(
        jnp.arange(T, dtype=jnp.int32))
    num_groups = occupied.sum().astype(jnp.int32)
    out_cap = max(T, 1024)
    out_idx = jnp.where(occupied, rank, out_cap)

    out_keys: List[ColVal] = []
    for i, c in enumerate(keys):
        digit = (slot_code // jnp.maximum(strides[i], 1)) % \
            jnp.maximum(slot_ranges[i], 1)
        vals = mins[i] + digit - 1
        if c.validity is not None:
            vd = jnp.zeros(out_cap, dtype=jnp.bool_)
            vd = vd.at[out_idx].set(digit > 0, mode="drop")
        else:
            vd = None
        out_dt = c.values.dtype
        if out_dt == jnp.bool_:
            vals = vals.astype(jnp.int64) != 0
        dst = jnp.zeros(out_cap, dtype=out_dt)
        dst = dst.at[out_idx].set(vals.astype(out_dt), mode="drop")
        out_keys.append(ColVal(c.dtype, dst, vd))

    slot_counts_all = jnp.bincount(slot, length=ns)
    counts_cache = {}

    def counts_of(validity, bcode):
        if validity is None:
            return slot_counts_all[:T]
        key = id(validity)
        got = counts_cache.get(key)
        if got is None:
            got = jnp.bincount(bcode, length=ns)[:T]
            counts_cache[key] = got
        return got

    def compact(c, vals, counts):
        vals, counts = vals[:T], counts[:T]
        dv = jnp.zeros(out_cap, dtype=vals.dtype)
        dv = dv.at[out_idx].set(vals, mode="drop")
        dvalid = jnp.zeros(out_cap, dtype=jnp.bool_)
        dvalid = dvalid.at[out_idx].set(counts > 0, mode="drop")
        return ColVal(c.dtype, dv, dvalid)

    out_bufs: List[ColVal] = []
    for kind, c in buffer_inputs:
        vals, counts = _segment_reduce_coded(kind, c, slot, ns,
                                             counts_of)
        out_bufs.append(compact(c, vals, counts))
    return out_keys, out_bufs, num_groups, overflow


def reduce_aggregate(buffer_inputs: Sequence[Tuple[str, ColVal]],
                     nrows, capacity: int, row_mask=None) -> List[ColVal]:
    """Grand-total (no keys) reduction: one output row per buffer.

    Dense masked reductions, NOT segment ops: XLA lowers segment_* to
    scatter, which serializes on TPU; a masked jnp.sum/min/max is a native
    tree reduction on the VPU (orders of magnitude faster at multi-million
    row capacities)."""
    valid_rows = _row_mask(nrows, capacity, row_mask)
    # all-float all-sum shape (count buffers are int sums handled below):
    # fuse every column into one HBM pass on TPU via the pallas kernel
    from spark_rapids_tpu.ops import pallas_kernels as pk
    import os
    float_sums = [(k, c) for k, c in buffer_inputs
                  if k == "sum" and jnp.issubdtype(c.values.dtype,
                                                  jnp.floating)]
    # opt-in until f64-in-pallas is validated on the target chip
    # (interpret-mode tests pass; hardware lowering of f64 is the risk)
    if pk.use_pallas() and \
            os.environ.get("SPARK_RAPIDS_TPU_PALLAS_REDUCE") and \
            len(float_sums) == len(buffer_inputs) and buffer_inputs:
        vals = [c.values for _, c in buffer_inputs]
        valids = [jnp.ones(capacity, dtype=jnp.bool_)
                  if c.validity is None else c.validity
                  for _, c in buffer_inputs]
        sums, cnts = pk.masked_multi_reduce(vals, valids, valid_rows,
                                            interpret=False)
        return [ColVal(c.dtype, sums[i:i + 1].astype(c.values.dtype),
                       (cnts[i:i + 1] > 0))
                for i, (_, c) in enumerate(buffer_inputs)]
    # ONE multi-operand lax.reduce: every buffer's reduction plus the
    # contribution counts ride a single pass over the input — XLA fuses
    # the predicate/projection producers into the reduce loop, so a
    # filter+sum query (TPC-H q6) touches each input byte exactly once
    # (measured ~5x over one jnp-reduction per buffer on CPU)
    operands: List = []
    inits: List = []
    comb: List[str] = []

    def add_slot(op, init, how) -> int:
        operands.append(op)
        inits.append(init)
        comb.append(how)
        return len(operands) - 1

    if not buffer_inputs:
        return []
    count_slot: dict = {}
    plan = []  # per buffer: (kind, c, contrib_key, value_slot)
    for kind, c in buffer_inputs:
        contrib_valid = valid_rows if c.validity is None else \
            jnp.logical_and(valid_rows, c.validity)
        # *_any kinds count LIVE rows (presence), not valid values
        vkey = id(c.validity) if (
            c.validity is not None and
            kind not in ("first_any", "last_any")) else None
        if vkey not in count_slot:
            count_slot[vkey] = add_slot(
                (contrib_valid if vkey is not None else valid_rows
                 ).astype(jnp.int64), jnp.int64(0), "add")
        v = c.values
        if getattr(v, "ndim", 0) == 0:
            v = jnp.broadcast_to(v, (capacity,))
        if kind == "sum":
            slot = add_slot(
                jnp.where(contrib_valid, v,
                          jnp.zeros((), dtype=v.dtype)).astype(v.dtype),
                jnp.zeros((), dtype=v.dtype), "add")
        elif kind in ("min", "max"):
            s = _sentinel(kind, v.dtype)
            slot = add_slot(jnp.where(contrib_valid, v, s),
                            jnp.asarray(s, dtype=v.dtype), kind)
        elif kind in ("first", "last"):
            idx = jnp.arange(capacity, dtype=jnp.int64)
            if kind == "first":
                slot = add_slot(
                    jnp.where(contrib_valid, idx, capacity),
                    jnp.int64(capacity), "min")
            else:
                slot = add_slot(jnp.where(contrib_valid, idx, -1),
                                jnp.int64(-1), "max")
        elif kind in ("first_any", "last_any"):
            # ignoreNulls=false: pick by row liveness alone (the count
            # slot above already rides liveness via vkey=None)
            idx = jnp.arange(capacity, dtype=jnp.int64)
            if kind == "first_any":
                slot = add_slot(jnp.where(valid_rows, idx, capacity),
                                jnp.int64(capacity), "min")
            else:
                slot = add_slot(jnp.where(valid_rows, idx, -1),
                                jnp.int64(-1), "max")
        else:
            raise ValueError(f"unknown reduce kind {kind}")
        plan.append((kind, c, vkey, slot))

    def comp(acc, x):
        out = []
        for a, b, how in zip(acc, x, comb):
            if how == "add":
                out.append(a + b)
            elif how == "min":
                out.append(jnp.minimum(a, b))
            else:
                out.append(jnp.maximum(a, b))
        return tuple(out)

    res = jax.lax.reduce(tuple(operands), tuple(inits), comp, [0])

    outs: List[ColVal] = []
    for kind, c, vkey, slot in plan:
        count = res[count_slot[vkey]]
        if kind in ("first", "last", "first_any", "last_any"):
            best = jnp.clip(res[slot], 0, capacity - 1).astype(jnp.int32)
            v = c.values
            if getattr(v, "ndim", 0) == 0:
                v = jnp.broadcast_to(v, (capacity,))
            out = v[best]
        else:
            out = res[slot]
        outs.append(ColVal(c.dtype, out[None], (count > 0)[None]))
    return outs


# ----------------------------------------------------- collect aggregates

class CollectList(AggregateFunction):
    """collect_list(x): per-group array of non-null values
    (CudfCollectList, AggregateFunctions.scala:256).  Evaluated in a
    single grouped pass — after the group sort the group's values are
    already contiguous, so the array column is a compaction, not a
    per-group loop.  ``single_pass``: the exec concatenates its input
    instead of the partial/merge pipeline."""

    name = "collect_list"
    single_pass = True
    dedup = False

    @property
    def result_dtype(self):
        from spark_rapids_tpu.columnar.dtypes import ArrayType
        return ArrayType(self.child.dtype)

    @property
    def result_nullable(self):
        return False

    def buffers(self):
        raise NotImplementedError("collect runs in the single-pass path")


class CollectSet(CollectList):
    """collect_set(x): distinct non-null values per group, ascending
    (CudfCollectSet, AggregateFunctions.scala:278 — Spark leaves set
    order unspecified)."""

    name = "collect_set"
    dedup = True


def groupby_collect(keys: Sequence[ColVal], collect_inputs, nrows,
                    capacity: int,
                    buffer_inputs: Sequence[Tuple[str, ColVal]] = (),
                    row_mask=None):
    """Group by ``keys``; for each (child, dedup) in collect_inputs build
    a per-group array column, and reduce ``buffer_inputs`` as usual.

    Returns (out_keys, out_buffers, collect_arrays, num_groups) where
    each collect array is a ColVal with offsets (ARRAY layout).
    """
    from spark_rapids_tpu.ops import selection

    live = _row_mask(nrows, capacity, row_mask)
    n_live = live.sum().astype(jnp.int32)
    perm = sort_permutation(keys, live, capacity)
    valid_sorted_mask = jnp.arange(capacity, dtype=jnp.int32) < n_live
    sorted_keys = selection.gather(keys, perm, n_live)
    same_as_prev = _keys_equal_prev(sorted_keys, capacity)
    boundary = jnp.logical_and(jnp.logical_not(same_as_prev),
                               valid_sorted_mask)
    num_groups = boundary.sum().astype(jnp.int32)
    seg_ids = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    seg_ids = jnp.where(valid_sorted_mask, seg_ids, capacity)

    out_bufs: List[ColVal] = []
    if buffer_inputs:
        sorted_bufs = selection.gather([c for _, c in buffer_inputs], perm,
                                       n_live)
        for (kind, _), sc in zip(buffer_inputs, sorted_bufs):
            vals, counts = _segment_reduce(kind, sc, seg_ids, capacity,
                                           valid_sorted_mask)
            out_bufs.append(ColVal(sc.dtype, vals, counts > 0))

    collect_outs: List[ColVal] = []
    for child, dedup in collect_inputs:
        if dedup:
            # per-group value order + dedup need values as a secondary
            # sort key: same group order (keys primary), nulls pushed to
            # the group end so they can never split a run of equal values
            null_flag = jnp.zeros(capacity, dtype=jnp.int8) \
                if child.validity is None else \
                jnp.logical_not(child.validity).astype(jnp.int8)
            perm2 = jnp.lexsort(
                _order_keys(child.values, False) + [null_flag] +
                _sortable_keys(keys, live, capacity))
            sc = selection.gather([child] + list(keys), perm2, n_live)
            schild, skeys2 = sc[0], sc[1:]
            same2 = _keys_equal_prev(skeys2, capacity)
            seg2 = jnp.cumsum(jnp.logical_and(
                jnp.logical_not(same2), valid_sorted_mask)
                .astype(jnp.int32)) - 1
            seg2 = jnp.where(valid_sorted_mask, seg2, capacity)
            v = schild.values
            same_val = v == jnp.roll(v, 1)
            if jnp.issubdtype(v.dtype, jnp.floating):
                same_val = same_val | (jnp.isnan(v) &
                                       jnp.isnan(jnp.roll(v, 1)))
            if schild.validity is not None:
                # a null row's LANE value may equal a valid value; runs
                # must only merge valid-with-valid
                vv = schild.validity
                same_val = jnp.logical_and(
                    same_val, jnp.logical_and(vv, jnp.roll(vv, 1)))
            first_of_run = jnp.logical_not(
                jnp.logical_and(same2, same_val))
            keep = jnp.logical_and(valid_sorted_mask, first_of_run)
            if schild.validity is not None:
                keep = jnp.logical_and(keep, schild.validity)
            seg_for = seg2
        else:
            sc = selection.gather([child], perm, n_live)
            schild = sc[0]
            keep = valid_sorted_mask
            if schild.validity is not None:
                keep = jnp.logical_and(keep, schild.validity)
            seg_for = seg_ids
        lengths = jax.ops.segment_sum(keep.astype(jnp.int32), seg_for,
                                      num_segments=capacity)
        offsets = jnp.concatenate([jnp.zeros(1, dtype=jnp.int32),
                                   jnp.cumsum(lengths, dtype=jnp.int32)])
        compacted, _ = selection.compact(
            [ColVal(child.dtype, schild.values, None)], keep)
        from spark_rapids_tpu.columnar.dtypes import ArrayType
        collect_outs.append(ColVal(ArrayType(child.dtype),
                                   compacted[0].values, None, offsets))

    first_idx = jax.ops.segment_min(
        jnp.arange(capacity, dtype=jnp.int64), seg_ids,
        num_segments=capacity)
    first_idx = jnp.clip(first_idx, 0, capacity - 1).astype(jnp.int32)
    out_keys = selection.gather(sorted_keys, first_idx, num_groups)
    return out_keys, out_bufs, collect_outs, num_groups
