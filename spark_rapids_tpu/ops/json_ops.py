"""JSON + array-producing string expressions (CPU-fallback surface).

``GetJsonObject`` (reference GpuGetJsonObject rule) and ``StringSplit``
(array-producing split; the indexed form is the device-side
``regexops.SplitPart``) have no dense device representation here —
JSONPath needs a byte-level parser and array<string> needs two offset
levels — so both are *tagged* expressions: the planner routes any node
containing them to ``CpuFallbackExec`` where ``_eval_pandas``
implements the semantics, and the distributed planner's dictionary
lowering (``dist_planner._try_dict_lower``) still evaluates
GetJsonObject host-side over the K distinct values so queries over
encoded columns stay on the mesh.
"""

from __future__ import annotations

import json
import re
from typing import List, Optional

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops.expressions import Expression

# array<string> exists only on the host surface (CPU-fallback frames
# hold python lists; Column stores int32 dictionary codes + a host
# string table); the device columnar layer is single-level, so the
# type is constructed directly instead of via ArrayType's validator.
# Storage matches the code representation so any accidental device
# buffer build stays dtype-consistent.
ARRAY_STRING = DataType("array<string>", np.dtype(np.int32),
                        element=dts.STRING)


_PATH_RE = re.compile(r"\.([A-Za-z_][A-Za-z0-9_]*)|\[(\d+)\]|\['([^']*)'\]")


def parse_json_path(path: str) -> Optional[List[object]]:
    """'$.a.b[0]' -> ['a', 'b', 0]; None when not the supported subset."""
    if not path.startswith("$"):
        return None
    out: List[object] = []
    i = 1
    while i < len(path):
        m = _PATH_RE.match(path, i)
        if m is None:
            return None
        if m.group(1) is not None:
            out.append(m.group(1))
        elif m.group(2) is not None:
            out.append(int(m.group(2)))
        else:
            out.append(m.group(3))
        i = m.end()
    return out


def eval_json_path(doc: str, steps: List[object]) -> Optional[str]:
    """Spark get_json_object semantics: strings come back raw, other
    values as compact JSON text, missing paths/bad JSON as null."""
    try:
        v = json.loads(doc)
    except (ValueError, TypeError):
        return None
    for s in steps:
        if isinstance(s, int):
            if not isinstance(v, list) or not 0 <= s < len(v):
                return None
            v = v[s]
        else:
            if not isinstance(v, dict) or s not in v:
                return None
            v = v[s]
    if v is None:
        return None
    if isinstance(v, str):
        return v
    return json.dumps(v, separators=(",", ":"))


class GetJsonObject(Expression):
    """get_json_object(json_str, '$.path') -> string."""

    def __init__(self, child: Expression, path: str):
        self.children = (child,)
        self.path = path
        self.steps = parse_json_path(path)

    def with_children(self, children):
        return GetJsonObject(children[0], self.path)

    @property
    def dtype(self):
        return dts.STRING

    @property
    def nullable(self):
        return True

    @property
    def name(self):
        return f"get_json_object({self.children[0].name}, {self.path})"

    def emit(self, ctx):
        raise RuntimeError(
            "GetJsonObject has no device kernel; it executes on the CPU "
            "fallback (or via the distributed planner's dictionary "
            "lowering)")

    def cache_key(self):
        return ("GetJsonObject", self.children[0].cache_key(), self.path)

    def eval_host(self, value: Optional[str]) -> Optional[str]:
        if value is None or self.steps is None:
            return None
        return eval_json_path(value, self.steps)


class StringSplit(Expression):
    """split(str, regex[, limit]) -> array<string> (Spark split)."""

    def __init__(self, child: Expression, pattern: str, limit: int = -1):
        self.children = (child,)
        self.pattern = pattern
        self.limit = limit

    def with_children(self, children):
        return StringSplit(children[0], self.pattern, self.limit)

    @property
    def dtype(self):
        return ARRAY_STRING

    @property
    def nullable(self):
        return True

    @property
    def name(self):
        return f"split({self.children[0].name}, {self.pattern!r})"

    def emit(self, ctx):
        raise RuntimeError(
            "StringSplit (array-producing) has no device kernel; it "
            "executes on the CPU fallback — use split_part for the "
            "indexed device form")

    def cache_key(self):
        return ("StringSplit", self.children[0].cache_key(),
                self.pattern, self.limit)

    def eval_host(self, value: Optional[str]):
        if value is None:
            return None
        # Spark split: regex semantics; limit<=0 keeps trailing empties
        if self.limit > 0:
            return re.split(self.pattern, value, self.limit - 1)
        return re.split(self.pattern, value)
