"""Misc expressions: hashes and id generators.

Reference analogs: ``HashFunctions.scala:56`` (GpuMurmur3Hash),
``GpuMonotonicallyIncreasingID``/``GpuSparkPartitionID`` (75/52 LoC) and
the Md5 rule (Appendix A.1 "Misc").  Murmur3Hash runs fully on device via
the partitioner's canonical hash; Md5 is host-only (bit-rotation digests
don't vectorize usefully onto the VPU) and lives in the CPU fallback.

MonotonicallyIncreasingID and SparkPartitionID need per-batch state
(Spark: partition id in the high bits, row offset in the low 33), which
stateless expressions cannot carry; the planner routes them through
``TpuBatchIdExec`` which appends the id columns per batch, exactly the
pattern Generate and Window use.
"""

from __future__ import annotations

from typing import Iterator, Sequence

import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.exec.base import Schema, TpuExec
from spark_rapids_tpu.ops.expressions import (
    ColVal, EmitContext, Expression)


class Murmur3Hash(Expression):
    """hash(cols...): int32, matching the engine's partitioning hash so
    hash(col) is consistent with shuffle placement."""

    def __init__(self, *children: Expression, seed: int = 42):
        self.children = tuple(children)
        self.seed = seed

    def with_children(self, children):
        return Murmur3Hash(*children, seed=self.seed)

    @property
    def dtype(self):
        return dts.INT32

    @property
    def nullable(self) -> bool:
        return False

    def cache_key(self):
        return ("Murmur3Hash", self.seed,
                tuple(c.cache_key() for c in self.children))

    def emit(self, ctx: EmitContext) -> ColVal:
        from spark_rapids_tpu.parallel.partitioning import hash_columns
        cols = []
        for c in self.children:
            cv = c.emit(ctx)
            v = cv.values
            if getattr(v, "ndim", 0) == 0:
                v = jnp.broadcast_to(v, (ctx.capacity,))
                cv = ColVal(cv.dtype, v, cv.validity, cv.offsets)
            cols.append(cv)
        h = hash_columns(cols, seed=self.seed)
        return ColVal(dts.INT32, h.astype(jnp.int32), None)


class Md5(Expression):
    """md5(string): host-only (no device rule is registered, so any plan
    containing it falls back and ``_eval_pandas`` computes it)."""

    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    def with_children(self, children):
        return Md5(children[0])

    @property
    def dtype(self):
        return dts.STRING


class _BatchIdMarker(Expression):
    """select-time marker routed into TpuBatchIdExec by DataFrame.select
    (monotonically_increasing_id / spark_partition_id)."""

    def __init__(self, kind: str):
        self.kind = kind  # "mid" | "pid"
        self.children = ()

    @property
    def dtype(self):
        return dts.INT64 if self.kind == "mid" else dts.INT32

    @property
    def nullable(self) -> bool:
        return False

    @property
    def name(self) -> str:
        return ("monotonically_increasing_id()" if self.kind == "mid"
                else "spark_partition_id()")


class TpuBatchIdExec(TpuExec):
    """Appends per-batch id columns: each input batch is a 'partition' —
    mid = (batch_ordinal << 33) | row_offset (Spark's bit split), pid =
    batch_ordinal."""

    MID_COL = "__mid"
    PID_COL = "__pid"

    def __init__(self, child: TpuExec):
        super().__init__(child)

    @property
    def child(self) -> TpuExec:
        return self.children[0]

    @property
    def schema(self) -> Schema:
        return list(self.child.schema) + [
            (self.MID_COL, dts.INT64), (self.PID_COL, dts.INT32)]

    def describe(self):
        return "TpuBatchIdExec"

    def do_execute(self) -> Iterator[ColumnarBatch]:
        for ordinal, batch in enumerate(self.child.execute()):
            cap = batch.capacity
            base = jnp.int64(ordinal << 33)
            mid = jnp.arange(cap, dtype=jnp.int64) + base
            pid = jnp.full(cap, ordinal, dtype=jnp.int32)
            out = batch.with_column(
                self.MID_COL, Column(dts.INT64, mid, batch.nrows))
            out = out.with_column(
                self.PID_COL, Column(dts.INT32, pid, batch.nrows))
            yield out
