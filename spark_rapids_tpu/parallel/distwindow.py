"""Distributed window functions over the mesh.

The reference plans WindowExec as an ordinary exchange consumer: a hash/
range partition on the PARTITION BY keys, a local sort on (partition,
order), then the windowed evaluation per task (GpuWindowExec.scala).
The TPU formulation rides the existing range-partitioned distributed
sort with a **partition prefix**: splitters are drawn from the PARTITION
BY keys only, so every row of one window partition is guaranteed to land
on a single shard (a partition never splits), while the local sort uses
the full (partition, order) key.  One more compiled shard_map step then
evaluates every window expression shard-locally with the same kernels
the single-process operator uses (``exec.window.eval_window_expr``) —
no cross-shard carry is ever needed.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops import window as W
from spark_rapids_tpu.ops.aggregates import widen_colval
from spark_rapids_tpu.ops.expressions import ColVal, EmitContext
from spark_rapids_tpu.parallel.distsort import DistributedSort


class DistributedWindow:
    """Append window-function columns to a sharded frame.

    ``window_exprs``: (name, WindowExpression) pairs ALREADY lowered for
    the mesh (dictionary codes in place of strings); all share one spec
    with at least one partition expression.
    """

    def __init__(self, mesh: Mesh, in_dtypes: Sequence[DataType],
                 window_exprs: Sequence[Tuple[str, "WindowExpression"]]):
        from spark_rapids_tpu.ops.jit_cache import cached_jit
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.in_dtypes = list(in_dtypes)
        self.window_exprs = list(window_exprs)
        spec = self.window_exprs[0][1].spec
        self.spec = spec
        if not spec.partition_exprs:
            raise ValueError("DistributedWindow requires PARTITION BY")
        sort_keys = list(spec.partition_exprs) + \
            [e for e, _, _ in spec.orders]
        desc = [False] * len(spec.partition_exprs) + \
            [d for _, d, _ in spec.orders]
        nf = [True] * len(spec.partition_exprs) + \
            [n for _, _, n in spec.orders]
        self.sort = DistributedSort(
            mesh, in_dtypes, sort_keys, desc, nf,
            partition_prefix=len(spec.partition_exprs))
        self._cached_jit = cached_jit
        self._sig = ("dist_window", tuple(mesh.axis_names),
                     tuple(mesh.devices.shape),
                     tuple(str(d) for d in mesh.devices.flat),
                     tuple(dt.name for dt in self.in_dtypes),
                     tuple(we.cache_key()
                           for _, we in self.window_exprs))
        self.last_stats: Optional[dict] = None

    def _step(self, flat_cols, nrows_arr):
        from spark_rapids_tpu.exec.window import (_boundaries,
                                                  eval_window_expr)
        nrows = nrows_arr[0]
        cols = [ColVal(dt, v, val)
                for (v, val), dt in zip(flat_cols, self.in_dtypes)]
        cap = cols[0].values.shape[0]
        ctx = EmitContext(cols, nrows, cap)
        part = [widen_colval(e.emit(ctx), cap)
                for e in self.spec.partition_exprs]
        order = [widen_colval(e.emit(ctx), cap)
                 for e, _, _ in self.spec.orders]
        live = jnp.arange(cap, dtype=jnp.int32) < nrows
        seg_b = _boundaries(part, live, cap)
        run_b = _boundaries(order, live, cap) if order else \
            jnp.zeros(cap, dtype=jnp.bool_)
        sp = W.SortedPartitions(seg_b, run_b, live, cap)
        outs = []
        for _, we in self.window_exprs:
            c = None
            if we.child_expr is not None:
                c = widen_colval(we.child_expr.emit(ctx), cap)
            out, _ = eval_window_expr(we, sp, c, seg_b, cap)
            v = out.values
            if getattr(v, "ndim", 0) == 0:
                v = jnp.broadcast_to(v, (cap,))
            valid = out.validity
            if valid is None:
                valid = jnp.ones(cap, dtype=jnp.bool_)
            elif getattr(valid, "ndim", 1) == 0:
                valid = jnp.broadcast_to(valid, (cap,))
            outs.append((v, valid))
        return tuple(flat_cols) + tuple(outs), nrows_arr

    def __call__(self, flat_cols, nrows_per_shard):
        s_cols, s_n = self.sort(flat_cols, nrows_per_shard)
        self.last_stats = self.sort.last_stats
        out = self._cached_jit(
            self._sig + ("eval",), lambda: jax.shard_map(
                self._step, mesh=self.mesh,
                in_specs=(P(self.axis), P(self.axis)),
                out_specs=P(self.axis), check_vma=False))(
            tuple(s_cols), s_n.reshape(-1))
        return out
