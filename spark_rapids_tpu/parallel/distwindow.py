"""Distributed window functions over the mesh.

The reference plans WindowExec as an ordinary exchange consumer: a hash/
range partition on the PARTITION BY keys, a local sort on (partition,
order), then the windowed evaluation per task (GpuWindowExec.scala).
The TPU formulation rides the existing range-partitioned distributed
sort with a **partition prefix**: splitters are drawn from the PARTITION
BY keys only, so every row of one window partition is guaranteed to land
on a single shard (a partition never splits), while the local sort uses
the full (partition, order) key.  One more compiled shard_map step then
evaluates every window expression shard-locally with the same kernels
the single-process operator uses (``exec.window.eval_window_expr``) —
no cross-shard carry is ever needed.

Wire format: both window lowerings ride the embedded
``DistributedSort``'s exchange, so they inherit the fused packed
all-to-all (one collective per width group, shared compaction gather)
and the SlotPlanner's EMA-sticky slot sizing for free — the window's
exchange site is the sort's jit signature, which embeds the window's
(partition, order) key set.  Shuffle-wire metrics recorded by the sort
therefore attribute the window's exchange too (parallel/shuffle.py).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops import window as W
from spark_rapids_tpu.ops.aggregates import widen_colval
from spark_rapids_tpu.ops.expressions import ColVal, EmitContext
from spark_rapids_tpu.parallel.mesh import shard_map as _shard_map
from spark_rapids_tpu.parallel.distsort import DistributedSort


def _key_eq(a_vals, a_valid, b_vals, b_valid):
    """Spark-order equality of two gathered key scalars/vectors: nulls
    equal each other, NaN equals NaN (peers), else value equality."""
    if jnp.issubdtype(a_vals.dtype, jnp.floating):
        veq = jnp.logical_or(
            a_vals == b_vals,
            jnp.logical_and(jnp.isnan(a_vals), jnp.isnan(b_vals)))
    else:
        veq = a_vals == b_vals
    both_valid = jnp.logical_and(a_valid, b_valid)
    both_null = jnp.logical_and(jnp.logical_not(a_valid),
                                jnp.logical_not(b_valid))
    return jnp.logical_or(jnp.logical_and(both_valid, veq), both_null)


class DistributedGlobalWindow:
    """Window WITHOUT partition by across the mesh: one global partition
    spanning every shard, evaluated with a collective cross-shard carry.

    The reference's running-window optimization carries running state
    across batches on one device (GpuWindowExec.scala:423-446 fixup);
    the mesh analog: globally range-partition + locally sort by the
    ORDER BY keys (shards hold contiguous chunks of the global order),
    evaluate every window expression shard-locally, then fix up with
    gathered per-shard statistics — an exclusive prefix combine for
    running frames, order-key tie CHAINS across shard boundaries for
    rank/dense_rank and RANGE frames (a tie run may span any number of
    shards), and a plain psum/pmin/pmax for whole-partition frames.

    Supported kinds: row_number, rank, dense_rank, percent_rank, and
    sum/count/avg/min/max over running (UNBOUNDED PRECEDING..CURRENT
    ROW, rows or range) or whole-partition frames.  lead/lag and
    finite rows-frame offsets would need a halo exchange — the planner
    rejects them (NotDistributable) before building this.
    """

    def __init__(self, mesh: Mesh, in_dtypes: Sequence[DataType],
                 window_exprs: Sequence[Tuple[str, "WindowExpression"]]):
        from spark_rapids_tpu.ops.jit_cache import cached_jit
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.nshards = mesh.devices.size
        self.in_dtypes = list(in_dtypes)
        self.window_exprs = list(window_exprs)
        spec = self.window_exprs[0][1].spec
        self.spec = spec
        if spec.partition_exprs:
            raise ValueError("DistributedGlobalWindow is the "
                             "no-PARTITION-BY path")
        sort_keys = [e for e, _, _ in spec.orders]
        self.sort = DistributedSort(
            mesh, in_dtypes, sort_keys,
            [d for _, d, _ in spec.orders],
            [n for _, _, n in spec.orders]) if sort_keys else None
        self._cached_jit = cached_jit
        self._sig = ("dist_gwindow", tuple(mesh.axis_names),
                     tuple(mesh.devices.shape),
                     tuple(str(d) for d in mesh.devices.flat),
                     tuple(dt.name for dt in self.in_dtypes),
                     tuple(we.cache_key()
                           for _, we in self.window_exprs))
        self.last_stats: Optional[dict] = None

    # -- cross-shard tie chains -------------------------------------------
    def _gather_key_edges(self, order, nrows, cap):
        """Per order key column: (first, last) gathered values+validity
        per shard, forward-filled through EMPTY shards so pairwise
        equality composes across them; plus eqpair[u] = shard u's last
        key ties with shard u+1's first key."""
        n = self.nshards
        g_n = jax.lax.all_gather(nrows, self.axis)       # (n,)
        empty = g_n == 0
        last_i = jnp.clip(nrows - 1, 0, cap - 1)
        eqpair = jnp.ones(max(n - 1, 1), dtype=jnp.bool_)
        for c in order:
            v, val = c.values, c.validity
            if val is None:
                val = jnp.ones(cap, dtype=jnp.bool_)
            fv = jax.lax.all_gather(v[0], self.axis)
            fb = jax.lax.all_gather(val[0], self.axis)
            lv = jax.lax.all_gather(v[last_i], self.axis)
            lb = jax.lax.all_gather(val[last_i], self.axis)
            # forward-fill last-edge through empty shards; an empty
            # shard's first edge inherits the fill too, so eqpair
            # composes across it; track whether any real row exists
            # at-or-before each shard (no spurious ties off garbage)
            lv_f, lb_f = [lv[0]], [lb[0]]
            fv_f, fb_f = [fv[0]], [fb[0]]
            exists = [jnp.logical_not(empty[0])]
            for k in range(1, n):
                lv_f.append(jnp.where(empty[k], lv_f[k - 1], lv[k]))
                lb_f.append(jnp.where(empty[k], lb_f[k - 1], lb[k]))
                fv_f.append(jnp.where(empty[k], lv_f[k - 1], fv[k]))
                fb_f.append(jnp.where(empty[k], lb_f[k - 1], fb[k]))
                exists.append(jnp.logical_or(exists[k - 1],
                                             jnp.logical_not(empty[k])))
            if n > 1:
                pair = jnp.stack([
                    jnp.logical_and(
                        _key_eq(lv_f[u], lb_f[u], fv_f[u + 1],
                                fb_f[u + 1]),
                        exists[u])
                    for u in range(n - 1)])
                eqpair = jnp.logical_and(eqpair, pair)
        return g_n, empty, eqpair

    def _chains(self, eqpair, fully):
        """chain[j][t] (j<t): shard t's leading order-key run is a
        continuation of shard j's trailing run — every boundary in
        between ties and every interior shard is one single run."""
        n = self.nshards
        chain = [[None] * n for _ in range(n)]
        for j in range(n - 1):
            acc = eqpair[j]
            chain[j][j + 1] = acc
            for t in range(j + 2, n):
                acc = jnp.logical_and(
                    acc, jnp.logical_and(fully[t - 1], eqpair[t - 1]))
                chain[j][t] = acc
        return chain

    @staticmethod
    def _masked_sum(g, mask_rows):
        return jnp.sum(jnp.where(mask_rows, g, jnp.zeros((), g.dtype)))

    def _step(self, flat_cols, nrows_arr):
        from spark_rapids_tpu.exec.window import (_boundaries,
                                                  eval_window_expr)
        nrows = nrows_arr[0]
        cols = [ColVal(dt, v, val)
                for (v, val), dt in zip(flat_cols, self.in_dtypes)]
        cap = cols[0].values.shape[0]
        n = self.nshards
        ctx = EmitContext(cols, nrows, cap)
        order = [widen_colval(e.emit(ctx), cap)
                 for e, _, _ in self.spec.orders]
        pos = jnp.arange(cap, dtype=jnp.int32)
        live = pos < nrows
        seg_b = jnp.logical_and(live, pos == 0)   # one global partition
        run_b = _boundaries(order, live, cap) if order else \
            jnp.zeros(cap, dtype=jnp.bool_)
        sp = W.SortedPartitions(seg_b, run_b, live, cap)

        idx = jax.lax.axis_index(self.axis)
        shard_rank = jnp.arange(n)
        before = shard_rank < idx
        after = shard_rank > idx
        g_n, empty, eqpair = self._gather_key_edges(order, nrows, cap) \
            if order else (jax.lax.all_gather(nrows, self.axis),
                           None, None)
        rows_before = self._masked_sum(g_n.astype(jnp.int64), before)
        total_rows = jnp.sum(g_n.astype(jnp.int64))
        chain = None
        if order and n > 1:
            # run structure: count of runs, leading/trailing masks
            nruns = jnp.sum(jnp.logical_and(
                jnp.logical_or(run_b, seg_b), live).astype(jnp.int32))
            fully_l = jax.lax.all_gather(nruns <= 1, self.axis)
            chain = self._chains(eqpair, fully_l)
            last_live = jnp.clip(nrows - 1, 0, cap - 1)
            lead_mask = jnp.logical_and(live, sp.run_start == 0)
            trail_mask = jnp.logical_and(
                live, sp.run_end == last_live)
            trail_mask = jnp.logical_and(trail_mask, nrows > 0)
            g_trail_len = jax.lax.all_gather(
                jnp.sum(trail_mask.astype(jnp.int64)), self.axis)
            # rows of previous shards belonging to MY leading run
            pre_tied = jnp.zeros((), jnp.int64)
            for j in range(n - 1):
                c_js = [chain[j][t] for t in range(j + 1, n)]
                hit = jnp.zeros((), jnp.bool_)
                for t, cjt in zip(range(j + 1, n), c_js):
                    hit = jnp.logical_or(hit, jnp.logical_and(
                        cjt, t == idx))
                pre_tied = pre_tied + jnp.where(hit, g_trail_len[j], 0)
            merged_lead = jnp.zeros((), jnp.bool_)
            for j in range(n - 1):
                for t in range(j + 1, n):
                    merged_lead = jnp.logical_or(
                        merged_lead,
                        jnp.logical_and(chain[j][t], t == idx))
            # an empty shard merges nothing (its gathered edges are
            # forward-fill artifacts)
            merged_lead = jnp.logical_and(merged_lead, nrows > 0)
        else:
            lead_mask = trail_mask = None
            pre_tied = jnp.zeros((), jnp.int64)
            merged_lead = jnp.zeros((), jnp.bool_)

        outs = []
        for _, we in self.window_exprs:
            c = None
            if we.child_expr is not None:
                c = widen_colval(we.child_expr.emit(ctx), cap)
            # only kinds whose LOCAL output feeds the carry need the
            # local kernel; aggregates/percent_rank recompute inside
            # _fixup from the frame kernels directly
            if we.kind in ("row_number", "rank", "dense_rank"):
                out, _ = eval_window_expr(we, sp, c, seg_b, cap)
            else:
                out = None
            out = self._fixup(we, out, sp, c, live, lead_mask,
                              trail_mask, rows_before, total_rows,
                              pre_tied, merged_lead, chain, empty,
                              idx, before, cap)
            v = out.values
            if getattr(v, "ndim", 0) == 0:
                v = jnp.broadcast_to(v, (cap,))
            valid = out.validity
            if valid is None:
                valid = jnp.ones(cap, dtype=jnp.bool_)
            elif getattr(valid, "ndim", 1) == 0:
                valid = jnp.broadcast_to(valid, (cap,))
            outs.append((v, jnp.logical_and(valid, live)))
        return tuple(flat_cols) + tuple(outs), nrows_arr

    def _fixup(self, we, out, sp, c, live, lead_mask, trail_mask,
               rows_before, total_rows, pre_tied, merged_lead, chain,
               empty, idx, before, cap):
        """Combine shard-local window output with the global carry."""
        from spark_rapids_tpu.ops.aggregates import _sentinel
        kind = we.kind
        f = we.spec.frame
        n = self.nshards
        if kind == "row_number":
            return ColVal(out.dtype,
                          jnp.where(live, out.values +
                                    rows_before.astype(out.values.dtype),
                                    out.values), out.validity)
        if kind in ("rank", "percent_rank"):
            # global rank = local rank + rows before this shard, except
            # rows of a leading run that CONTINUES an earlier shard's
            # trailing run: their run started pre_tied rows earlier
            local_rank = out.values if kind == "rank" else \
                W.rank(sp).values
            if lead_mask is not None:
                adj = jnp.where(
                    jnp.logical_and(lead_mask, merged_lead),
                    rows_before - pre_tied, rows_before)
            else:
                adj = jnp.broadcast_to(rows_before, (cap,))
            rank_g = local_rank + adj.astype(local_rank.dtype)
            if kind == "rank":
                return ColVal(out.dtype,
                              jnp.where(live, rank_g, local_rank),
                              out.validity)
            denom = jnp.maximum(total_rows - 1, 1).astype(jnp.float64)
            pr = (rank_g.astype(jnp.float64) - 1.0) / denom
            pr = jnp.where(total_rows <= 1, jnp.zeros_like(pr), pr)
            return ColVal(we.dtype, jnp.where(live, pr, 0.0), None)
        if kind == "dense_rank":
            # local dense + distinct runs in previous shards, counting
            # each boundary-merged run once
            rb = jnp.logical_and(sp.run_start == sp.pos, live)
            my_runs = jnp.sum(rb.astype(jnp.int64))
            g_runs = jax.lax.all_gather(my_runs, self.axis)
            g_merged = jax.lax.all_gather(merged_lead, self.axis)
            distinct_before = self._masked_sum(g_runs, before) - \
                self._masked_sum(g_merged.astype(jnp.int64), before)
            dv = out.values + distinct_before.astype(out.values.dtype) \
                - jnp.where(merged_lead, 1, 0).astype(out.values.dtype)
            return ColVal(out.dtype, jnp.where(live, dv, out.values),
                          out.validity)

        whole = f.lo is None and f.hi is None
        rows_frame = f.kind == "rows"
        result_dt = we.dtype   # aggregates skip the local kernel
        if kind in ("sum", "count", "avg"):
            cin = c if kind != "count" else (c or ColVal(
                dts.INT64, jnp.ones(cap, dtype=jnp.int64)))
            vals = cin.values.astype(result_dt.storage) \
                if kind == "sum" else cin.values
            if kind == "avg":
                vals = vals.astype(jnp.float64)
            valid = live if cin.validity is None else \
                jnp.logical_and(live, cin.validity)
            zero = jnp.zeros((), vals.dtype)
            s_tot = jnp.sum(jnp.where(valid, vals, zero))
            n_tot = jnp.sum(valid.astype(jnp.int64))
            g_s = jax.lax.all_gather(s_tot, self.axis)
            g_c = jax.lax.all_gather(n_tot, self.axis)
            if whole:
                s_all = jnp.sum(g_s)
                n_all = jnp.sum(g_c)
                return self._sum_result(kind, result_dt,
                                        jnp.broadcast_to(s_all, (cap,)),
                                        jnp.broadcast_to(n_all, (cap,)),
                                        live)
            cs = self._masked_sum(g_s, before)
            cn = self._masked_sum(g_c, before)
            # local running (s, n) per row — recompute cheaply from the
            # frame formulation the local kernel used
            s_loc, n_loc = W.frame_sum(
                sp, ColVal(cin.dtype, vals, cin.validity), None, 0,
                rows=rows_frame)
            s2 = s_loc + cs
            n2 = n_loc + cn
            if not rows_frame and chain is not None and \
                    trail_mask is not None:
                # RANGE running: the trailing tie run extends into
                # following shards — add their chained leading-run sums
                lead_s = jnp.sum(jnp.where(
                    jnp.logical_and(lead_mask, valid), vals, zero))
                lead_n = jnp.sum(jnp.logical_and(
                    lead_mask, valid).astype(jnp.int64))
                g_ls = jax.lax.all_gather(lead_s, self.axis)
                g_ln = jax.lax.all_gather(lead_n, self.axis)
                ext_s = jnp.zeros((), vals.dtype)
                ext_n = jnp.zeros((), jnp.int64)
                for t in range(1, n):
                    hit = jnp.zeros((), jnp.bool_)
                    for j in range(t):
                        hit = jnp.logical_or(hit, jnp.logical_and(
                            chain[j][t], j == idx))
                    ext_s = ext_s + jnp.where(hit, g_ls[t], zero)
                    ext_n = ext_n + jnp.where(hit, g_ln[t], 0)
                s2 = jnp.where(trail_mask, s2 + ext_s, s2)
                n2 = jnp.where(trail_mask, n2 + ext_n, n2)
            return self._sum_result(kind, result_dt, s2, n2, live)

        if kind in ("min", "max"):
            op = jnp.minimum if kind == "min" else jnp.maximum
            valid = live if c.validity is None else \
                jnp.logical_and(live, c.validity)
            sent = jnp.asarray(_sentinel(kind, c.values.dtype),
                               dtype=c.values.dtype)
            masked = jnp.where(valid, c.values, sent)
            v_tot = (jnp.min if kind == "min" else jnp.max)(masked)
            n_tot = jnp.sum(valid.astype(jnp.int64))
            g_v = jax.lax.all_gather(v_tot, self.axis)
            g_c = jax.lax.all_gather(n_tot, self.axis)
            if whole:
                v_all = (jnp.min if kind == "min" else jnp.max)(g_v)
                n_all = jnp.sum(g_c)
                return ColVal(result_dt,
                              jnp.broadcast_to(v_all, (cap,)),
                              jnp.logical_and(live, n_all > 0))
            cv = (jnp.min if kind == "min" else jnp.max)(
                jnp.where(before, g_v, sent))
            cn = self._masked_sum(g_c, before)
            v_loc, n_loc = W.running_minmax(
                sp, c, kind,
                jnp.logical_and(sp.pos == 0, live))
            if not rows_frame:
                v_loc = v_loc[sp.run_end]
                n_loc = n_loc[sp.run_end]
            v2 = jnp.where(cn > 0, op(v_loc, cv), v_loc)
            n2 = n_loc + cn
            if not rows_frame and chain is not None and \
                    trail_mask is not None:
                lead_v = (jnp.min if kind == "min" else jnp.max)(
                    jnp.where(jnp.logical_and(lead_mask, valid),
                              c.values, sent))
                lead_n = jnp.sum(jnp.logical_and(
                    lead_mask, valid).astype(jnp.int64))
                g_lv = jax.lax.all_gather(lead_v, self.axis)
                g_ln = jax.lax.all_gather(lead_n, self.axis)
                ext_v = sent
                ext_n = jnp.zeros((), jnp.int64)
                for t in range(1, self.nshards):
                    hit = jnp.zeros((), jnp.bool_)
                    for j in range(t):
                        hit = jnp.logical_or(hit, jnp.logical_and(
                            chain[j][t], j == idx))
                    ext_v = op(ext_v, jnp.where(hit, g_lv[t], sent))
                    ext_n = ext_n + jnp.where(hit, g_ln[t], 0)
                v2 = jnp.where(jnp.logical_and(trail_mask, ext_n > 0),
                               op(v2, ext_v), v2)
                n2 = jnp.where(trail_mask, n2 + ext_n, n2)
            return ColVal(result_dt, v2, jnp.logical_and(live, n2 > 0))
        raise ValueError(f"global distributed window kind {kind}")

    @staticmethod
    def _sum_result(kind, result_dt, s, ncount, live):
        if kind == "count":
            return ColVal(dts.INT64, ncount, live)
        if kind == "avg":
            return ColVal(dts.FLOAT64,
                          s / jnp.maximum(ncount, 1).astype(jnp.float64),
                          jnp.logical_and(live, ncount > 0))
        return ColVal(result_dt, s, jnp.logical_and(live, ncount > 0))

    def __call__(self, flat_cols, nrows_per_shard):
        if self.sort is not None:
            s_cols, s_n = self.sort(flat_cols, nrows_per_shard)
            self.last_stats = self.sort.last_stats
        else:
            s_cols, s_n = flat_cols, nrows_per_shard
            self.last_stats = {"sorted": False}
        out = self._cached_jit(
            self._sig + ("eval",), lambda: _shard_map(
                self._step, mesh=self.mesh,
                in_specs=(P(self.axis), P(self.axis)),
                out_specs=P(self.axis), check_vma=False))(
            tuple(s_cols), jnp.asarray(s_n).reshape(-1))
        return out


class DistributedWindow:
    """Append window-function columns to a sharded frame.

    ``window_exprs``: (name, WindowExpression) pairs ALREADY lowered for
    the mesh (dictionary codes in place of strings); all share one spec
    with at least one partition expression.
    """

    def __init__(self, mesh: Mesh, in_dtypes: Sequence[DataType],
                 window_exprs: Sequence[Tuple[str, "WindowExpression"]]):
        from spark_rapids_tpu.ops.jit_cache import cached_jit
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.in_dtypes = list(in_dtypes)
        self.window_exprs = list(window_exprs)
        spec = self.window_exprs[0][1].spec
        self.spec = spec
        if not spec.partition_exprs:
            raise ValueError("DistributedWindow requires PARTITION BY")
        sort_keys = list(spec.partition_exprs) + \
            [e for e, _, _ in spec.orders]
        desc = [False] * len(spec.partition_exprs) + \
            [d for _, d, _ in spec.orders]
        nf = [True] * len(spec.partition_exprs) + \
            [n for _, _, n in spec.orders]
        self.sort = DistributedSort(
            mesh, in_dtypes, sort_keys, desc, nf,
            partition_prefix=len(spec.partition_exprs))
        self._cached_jit = cached_jit
        self._sig = ("dist_window", tuple(mesh.axis_names),
                     tuple(mesh.devices.shape),
                     tuple(str(d) for d in mesh.devices.flat),
                     tuple(dt.name for dt in self.in_dtypes),
                     tuple(we.cache_key()
                           for _, we in self.window_exprs))
        self.last_stats: Optional[dict] = None

    def _step(self, flat_cols, nrows_arr):
        from spark_rapids_tpu.exec.window import (_boundaries,
                                                  eval_window_expr)
        nrows = nrows_arr[0]
        cols = [ColVal(dt, v, val)
                for (v, val), dt in zip(flat_cols, self.in_dtypes)]
        cap = cols[0].values.shape[0]
        ctx = EmitContext(cols, nrows, cap)
        part = [widen_colval(e.emit(ctx), cap)
                for e in self.spec.partition_exprs]
        order = [widen_colval(e.emit(ctx), cap)
                 for e, _, _ in self.spec.orders]
        live = jnp.arange(cap, dtype=jnp.int32) < nrows
        seg_b = _boundaries(part, live, cap)
        run_b = _boundaries(order, live, cap) if order else \
            jnp.zeros(cap, dtype=jnp.bool_)
        sp = W.SortedPartitions(seg_b, run_b, live, cap)
        outs = []
        for _, we in self.window_exprs:
            c = None
            if we.child_expr is not None:
                c = widen_colval(we.child_expr.emit(ctx), cap)
            out, _ = eval_window_expr(we, sp, c, seg_b, cap)
            v = out.values
            if getattr(v, "ndim", 0) == 0:
                v = jnp.broadcast_to(v, (cap,))
            valid = out.validity
            if valid is None:
                valid = jnp.ones(cap, dtype=jnp.bool_)
            elif getattr(valid, "ndim", 1) == 0:
                valid = jnp.broadcast_to(valid, (cap,))
            outs.append((v, valid))
        return tuple(flat_cols) + tuple(outs), nrows_arr

    def __call__(self, flat_cols, nrows_per_shard):
        s_cols, s_n = self.sort(flat_cols, nrows_per_shard)
        self.last_stats = self.sort.last_stats
        out = self._cached_jit(
            self._sig + ("eval",), lambda: _shard_map(
                self._step, mesh=self.mesh,
                in_specs=(P(self.axis), P(self.axis)),
                out_specs=P(self.axis), check_vma=False))(
            tuple(s_cols), s_n.reshape(-1))
        return out
