"""Distributed query planner: lower logical plans onto the device mesh.

This is the piece that turns ``session.sql("...")`` into an SPMD program:
when the session holds a ``jax.sharding.Mesh``, every query's logical
plan is first offered to this planner; a fully-supported plan executes
as compiled shard_map pipelines over the mesh (the reference's
planner-inserted exchange — ``GpuShuffleExchangeExec.scala:120-199``,
``RapidsShuffleInternalManagerBase.scala:114-127`` — SURVEY.md section
2.5), anything else falls back to the single-process engine with the
reason recorded on ``session.last_dist_explain``.

Design (TPU-first, whole-stage SPMD):

* A query executes as a chain of **ShardedFrame** transforms — every
  column is one leading-axis-sharded array ``[nshards * capacity]``
  plus a per-shard row-count vector.  Static shapes per stage; the only
  host syncs are the adaptive phase boundaries (histogram -> slot
  sizing) inside aggregate/join/sort.
* **Strings dictionary-encode at the scan** with ORDER-PRESERVING codes
  (``ops.dictionary.ordered_dict_encode``): group-by, sort, min/max and
  literal comparisons all run on int64 codes on device; values decode at
  collect.  Comparisons against string literals lower to code-space
  comparisons via binary search in the sorted dictionary.
* Aggregates/joins/sorts wrap the SPMD kernels in
  ``parallel/distributed.py`` / ``parallel/distsort.py``.
* The planner is an **eager executor with a dry mode**: the same
  recursion first runs with ``dry=True`` (schemas and empty
  dictionaries, no kernels, no data) as the support pre-flight, so an
  unsupported query falls back before any scan runs; the second pass
  executes for real.
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_rapids_tpu.columnar import dtypes as dts
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, bucket_capacity
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops import predicates as preds
from spark_rapids_tpu.ops.expressions import (
    Alias, BoundReference, ColVal, EmitContext, Expression, Literal)
from spark_rapids_tpu.parallel import mesh as mesh_lib
from spark_rapids_tpu.parallel.mesh import shard_map as _shard_map
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.logical import AggregateExpression


class NotDistributable(Exception):
    """Plan (or expression) cannot lower onto the mesh; single-process
    fallback with this reason."""


class _UnsplittableScan(Exception):
    """Internal: the file list cannot be sharded (no footer row counts,
    unlistable paths, or a shard overflowed its bound) — the scan falls
    back to the controller-side read+scatter path."""


def _file_row_bound(path: str, fmt: str) -> Optional[int]:
    """Exact per-file row count from footer metadata (parquet/orc) — an
    UPPER bound on post-pushdown rows, used to size shard capacity
    without reading data."""
    try:
        if fmt == "parquet":
            import pyarrow.parquet as pq
            return int(pq.ParquetFile(path).metadata.num_rows)
        if fmt == "orc":
            from pyarrow import orc
            return int(orc.ORCFile(path).nrows)
    except Exception:
        return None
    return None


@jax.jit
def _remap_codes(rank, vals):
    """Elementwise lookup of a small replicated rank table over a
    sharded codes array (stays sharded; no collectives)."""
    return rank[vals]


class ShardedFrame:
    """Columns as leading-axis sharded device arrays + per-shard counts.

    ``cols``: [(values, validity)] each ``[nshards * capacity]``;
    ``nrows``: int32 ``[nshards]``; ``enc``: ordinal -> sorted dictionary
    values for string columns travelling as int64 codes.  In dry mode
    (the support pre-flight) ``cols``/``nrows`` are None and ``enc``
    maps string ordinals to empty dictionaries."""

    def __init__(self, mesh, names: List[str], log_dtypes: List[DataType],
                 cols, nrows, enc: Dict[int, List[Optional[str]]]):
        self.mesh = mesh
        self.names = names
        self.log_dtypes = log_dtypes
        self.cols = cols
        self.nrows = nrows
        self.enc = enc

    @property
    def dry(self) -> bool:
        return self.cols is None

    @property
    def phys_dtypes(self) -> List[DataType]:
        return [_phys(dt) for dt in self.log_dtypes]

    @property
    def nshards(self) -> int:
        return self.mesh.devices.size

    @property
    def capacity(self) -> int:
        return int(self.cols[0][0].shape[0]) // self.nshards if self.cols \
            else 0

    @property
    def schema(self) -> List[Tuple[str, DataType]]:
        return list(zip(self.names, self.log_dtypes))

    def replace(self, **kw) -> "ShardedFrame":
        args = dict(mesh=self.mesh, names=self.names,
                    log_dtypes=self.log_dtypes, cols=self.cols,
                    nrows=self.nrows, enc=self.enc)
        args.update(kw)
        return ShardedFrame(**args)


def _phys(dt: DataType) -> DataType:
    return dts.INT64 if dt.is_string else dt


# --------------------------------------------------- expression lowering --

_CMP = (preds.EqualTo, preds.LessThan, preds.LessThanOrEqual,
        preds.GreaterThan, preds.GreaterThanOrEqual)


class DictLookup(Expression):
    """Gather through a per-dictionary lookup table: ``lut[codes]``.

    The distributed lowering for ANY expression over a single encoded
    string column (LIKE, regex, substring, length, ...): the original
    expression is evaluated ONCE host-side over the K dictionary values
    (K = distinct strings, tiny) and becomes an O(1)-per-row gather on
    device.  String-valued results re-encode against a fresh sorted
    dictionary (``dict_values``), so they stay sortable/groupable codes.
    """

    def __init__(self, child: Expression, lut_values, lut_valid,
                 dtype: DataType, dict_values=None, label: str = "f"):
        self.children = (child,)
        self.lut_values = np.asarray(lut_values)
        self.lut_valid = np.asarray(lut_valid, dtype=bool)
        self._dtype = dtype
        self.dict_values = dict_values  # set when result is encoded str
        self.label = label

    @property
    def dtype(self) -> DataType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return True

    @property
    def name(self) -> str:
        return self.label

    def with_children(self, children):
        return DictLookup(children[0], self.lut_values, self.lut_valid,
                          self._dtype, self.dict_values, self.label)

    def emit(self, ctx) -> ColVal:
        import jax.numpy as jnp
        from spark_rapids_tpu.ops.expressions import combine_validity
        c = self.children[0].emit(ctx)
        k = max(len(self.lut_values), 1)
        lut = jnp.asarray(self.lut_values) if len(self.lut_values) else \
            jnp.zeros(1, dtype=self._dtype.storage)
        lval = jnp.asarray(self.lut_valid) if len(self.lut_valid) else \
            jnp.zeros(1, dtype=jnp.bool_)
        idx = jnp.clip(c.values, 0, k - 1).astype(jnp.int32)
        return ColVal(self._dtype, lut[idx],
                      combine_validity(c.validity, lval[idx]))

    def cache_key(self):
        import hashlib
        h = hashlib.sha1(self.lut_values.tobytes() +
                         self.lut_valid.tobytes()).hexdigest()[:16]
        return ("DictLookup", self.children[0].cache_key(),
                self._dtype.name, h)

    def __str__(self):
        return f"DictLookup[{self.label}]"


# register with the support-tagging framework (reused by
# _check_supported); any fixed-width result type flows through
from spark_rapids_tpu.plan import typechecks as _ts  # noqa: E402
from spark_rapids_tpu.plan.overrides import expr_rule as _expr_rule  # noqa: E402

_expr_rule(DictLookup, _ts.ALL)


class ExprLowering:
    """Rewrite a bound expression for the encoded physical frame:
    references to string columns become int64 code references, and
    comparisons against string literals become code-space comparisons
    via binary search in the (sorted) dictionary.  With empty
    dictionaries (dry mode) the rewrite still type-checks — codes just
    come out as never-matching sentinels."""

    def __init__(self, enc: Dict[int, List[Optional[str]]], conf=None):
        self.enc = enc
        self.conf = conf

    def lower(self, e: Expression) -> Expression:
        if isinstance(e, Alias):
            return Alias(self.lower(e.children[0]), e.alias)
        if isinstance(e, BoundReference):
            if e.ordinal in self.enc:
                return BoundReference(e.ordinal, dts.INT64, name=e.name,
                                      nullable=e.nullable)
            if e.dtype.is_string or e.dtype.has_offsets or e.dtype.is_nested:
                raise NotDistributable(
                    f"column {e.name!r} of type {e.dtype} has no encoded "
                    "device representation on the mesh")
            return e
        if isinstance(e, _CMP) and (e.children[0].dtype.is_string or
                                    e.children[1].dtype.is_string):
            return self._lower_cmp(e)
        if isinstance(e, preds.In) and e.children[0].dtype.is_string:
            return self._lower_in(e)
        if isinstance(e, (preds.IsNull, preds.IsNotNull)) and \
                e.children[0].dtype.is_string:
            return type(e)(self.lower(e.children[0]))
        if isinstance(e, AggregateExpression):
            return self.lower_agg(e)
        if any(c.dtype.is_string for c in e.children) or e.dtype.is_string:
            # expression over / producing strings: try the dictionary
            # lowering (host-evaluate over the K distinct values, gather
            # through a LUT on device)
            d = self._try_dict_lower(e)
            if d is not None:
                return d
            raise NotDistributable(
                f"{type(e).__name__} over strings has no code-space "
                "lowering (not a function of one encoded column and "
                "literals)")
        if not e.children:
            return e
        return e.with_children([self.lower(c) for c in e.children])

    # -- dictionary lowering ---------------------------------------------
    def _dict_lower_candidate(self, e: Expression) -> Optional[int]:
        """The single encoded ordinal this subtree is a function of, or
        None when it is not dict-lowerable (multiple columns, non-
        literal leaves, aggregates/windows/UDFs inside)."""
        from spark_rapids_tpu.exec.window import WindowExpression
        ords = set()
        ok = True

        def walk(x):
            nonlocal ok
            if isinstance(x, (AggregateExpression, WindowExpression)):
                ok = False
                return
            if type(x).__name__ in ("PythonUDF", "JaxUDF"):
                ok = False
                return
            if isinstance(x, BoundReference):
                if x.ordinal in self.enc:
                    ords.add(x.ordinal)
                else:
                    ok = False  # mixed with a non-encoded column
                return
            for c in x.children:
                walk(c)

        walk(e)
        if not ok or len(ords) != 1:
            return None
        if e.dtype.has_offsets and not e.dtype.is_string:
            return None
        if e.dtype.is_nested:
            return None
        return ords.pop()

    def _try_dict_lower(self, e: Expression) -> Optional[Expression]:
        """Evaluate ``e`` host-side over the dictionary of its single
        encoded column; return a DictLookup, or None."""
        ordinal = self._dict_lower_candidate(e)
        if ordinal is None:
            return None
        # device-supported subtrees evaluate via the engine's own emit;
        # CPU-fallback-only expressions (GetJsonObject, exotic regex...)
        # evaluate via the pandas fallback evaluator instead — either
        # way the work is O(K distinct values) on host
        use_pandas = False
        if self.conf is not None:
            from spark_rapids_tpu.plan.overrides import ExprMeta
            em = ExprMeta(e, self.conf)
            em.tag()
            use_pandas = not em.can_replace
        values = [v for v in self.enc[ordinal] if v is not None]
        k = len(values)
        codes = BoundReference(ordinal, dts.INT64, name=f"_c{ordinal}")

        def replace(x):
            if isinstance(x, BoundReference) and x.ordinal == ordinal:
                return BoundReference(0, x.dtype, name=x.name,
                                      nullable=False)
            if not x.children:
                return x
            return x.with_children([replace(c) for c in x.children])

        label = f"{type(e).__name__}(dict)"
        if k == 0:
            if e.dtype.is_string:
                return DictLookup(codes, np.zeros(0, np.int64),
                                  np.zeros(0, bool), dts.INT64,
                                  dict_values=[], label=label)
            return DictLookup(codes, np.zeros(0, e.dtype.storage),
                              np.zeros(0, bool), e.dtype, label=label)
        if use_pandas:
            import pandas as pd
            from spark_rapids_tpu.exec.fallback import _eval_pandas
            try:
                res = _eval_pandas(replace(e),
                                   pd.DataFrame({"_c": values}))
            except NotImplementedError:
                return None
            if e.dtype.is_string:
                strs = [None if pd.isna(r) else r for r in res]
                return self._string_lut(codes, strs, label)
            valid = res.notna().to_numpy()
            vals = res.fillna(0).to_numpy().astype(e.dtype.storage)
            return DictLookup(codes, vals, valid, e.dtype, label=label)
        col = Column.from_strings(values)
        cv = ColVal(dts.STRING, col.data, None, col.offsets)
        ctx = EmitContext([cv], jnp.int32(k), col.capacity)
        out = replace(e).emit(ctx)
        if e.dtype.is_string:
            res = Column(dts.STRING, out.values, k, validity=out.validity,
                         offsets=out.offsets).to_pylist()
            return self._string_lut(codes, res, label)
        vo = np.asarray(out.values)
        vals = np.broadcast_to(vo, (k,)) if vo.ndim == 0 else vo[:k]
        if out.validity is None:
            valid = np.ones(k, dtype=bool)
        else:
            vv = np.asarray(out.validity)
            valid = np.broadcast_to(vv, (k,)) if vv.ndim == 0 else vv[:k]
        return DictLookup(codes, vals.astype(e.dtype.storage), valid,
                          e.dtype, label=label)

    @staticmethod
    def _string_lut(codes, res, label):
        """Re-encode K string results against a fresh sorted dict."""
        new_dict = sorted({r for r in res if r is not None})
        lut = np.array(
            [bisect.bisect_left(new_dict, r) if r is not None else 0
             for r in res], dtype=np.int64)
        lut_valid = np.array([r is not None for r in res], dtype=bool)
        return DictLookup(codes, lut, lut_valid, dts.INT64,
                          dict_values=new_dict, label=label)

    def lower_agg(self, e: AggregateExpression) -> AggregateExpression:
        import copy
        from spark_rapids_tpu.ops import aggregates as agg
        func = e.func
        if func.child is None:
            return e
        if func.child.dtype.is_string and not isinstance(
                func, (agg.Min, agg.Max, agg.First, agg.Last)):
            raise NotDistributable(
                f"aggregate {func.name} over strings not supported on "
                "the mesh (only min/max/first/last are order/identity "
                "preserving under dictionary codes)")
        f2 = copy.copy(func)
        f2.child = self.lower(func.child)
        return AggregateExpression(f2)

    def encoded_ref(self, e: Expression):
        """The encoded BoundReference behind e (through one Alias)."""
        inner = e.children[0] if isinstance(e, Alias) else e
        if isinstance(inner, BoundReference) and inner.ordinal in self.enc:
            return inner
        return None

    def out_dict(self, lowered: Expression):
        """Dictionary of a LOWERED expression's output codes, if it has
        one (bare encoded ref pass-through, or a DictLookup re-encode)."""
        inner = lowered.children[0] if isinstance(lowered, Alias) \
            else lowered
        if isinstance(inner, BoundReference) and inner.ordinal in self.enc:
            return self.enc[inner.ordinal]
        if isinstance(inner, DictLookup) and inner.dict_values is not None:
            return inner.dict_values
        return None

    def _encoded_operand(self, e: Expression):
        """(codes_expr, sorted_values) for a string subtree with a code
        representation: a bare encoded ref, or a dict-lowerable function
        of one (substring(c_phone, 1, 2), concat(s, '_x'), ...)."""
        inner = e.children[0] if isinstance(e, Alias) else e
        if isinstance(inner, BoundReference) and inner.ordinal in self.enc:
            codes = BoundReference(inner.ordinal, dts.INT64,
                                   name=inner.name,
                                   nullable=inner.nullable)
            return codes, [v for v in self.enc[inner.ordinal]
                           if v is not None]
        if inner.dtype.is_string:
            d = self._try_dict_lower(inner)
            if d is not None and d.dict_values is not None:
                return d, d.dict_values
        return None

    def _ref_and_literal(self, e):
        l, r = e.children
        if isinstance(r, Literal) and not isinstance(l, Literal):
            return l, r, False
        if isinstance(l, Literal) and not isinstance(r, Literal):
            return r, l, True
        return None

    def _lower_cmp(self, e):
        pair = self._ref_and_literal(e)
        op = self._encoded_operand(pair[0]) if pair else None
        if pair is None or op is None or \
                not isinstance(pair[1].value, str):
            d = self._try_dict_lower(e)
            if d is not None:
                return d
            raise NotDistributable(
                f"string comparison {e} is not (encoded expression vs "
                "literal); no code-space lowering")
        _, lit, flipped = pair
        codes, values = op
        cls = type(e)
        if flipped:  # lit OP ref  ->  ref OP' lit
            cls = {preds.LessThan: preds.GreaterThan,
                   preds.LessThanOrEqual: preds.GreaterThanOrEqual,
                   preds.GreaterThan: preds.LessThan,
                   preds.GreaterThanOrEqual: preds.LessThanOrEqual,
                   preds.EqualTo: preds.EqualTo}[cls]
        s = lit.value
        if cls is preds.EqualTo:
            i = bisect.bisect_left(values, s)
            code = i if i < len(values) and values[i] == s else -1
            return preds.EqualTo(codes, Literal(np.int64(code), dts.INT64))
        lo = bisect.bisect_left(values, s)
        hi = bisect.bisect_right(values, s)
        if cls is preds.LessThan:        # code < first index >= s
            return preds.LessThan(codes, Literal(np.int64(lo), dts.INT64))
        if cls is preds.LessThanOrEqual:  # code < first index > s
            return preds.LessThan(codes, Literal(np.int64(hi), dts.INT64))
        if cls is preds.GreaterThan:
            return preds.GreaterThanOrEqual(
                codes, Literal(np.int64(hi), dts.INT64))
        return preds.GreaterThanOrEqual(
            codes, Literal(np.int64(lo), dts.INT64))

    def _lower_in(self, e: preds.In):
        op = self._encoded_operand(e.children[0])
        opts = e.children[1:]
        if op is None or not all(
                isinstance(o, Literal) and isinstance(o.value, str)
                for o in opts):
            d = self._try_dict_lower(e)
            if d is not None:
                return d
            raise NotDistributable(
                "string IN is only supported as an encoded expression "
                "IN (literals...) on the mesh")
        codes, values = op
        hits = []
        for o in opts:
            i = bisect.bisect_left(values, o.value)
            if i < len(values) and values[i] == o.value:
                hits.append(Literal(np.int64(i), dts.INT64))
        if not hits:
            hits = [Literal(np.int64(-1), dts.INT64)]
        return preds.In(codes, hits)


def _check_supported(exprs: Sequence[Expression], conf) -> None:
    """Run the single-process support tagging over the LOWERED (numeric)
    expressions so per-op disables and TypeSig checks apply on the mesh
    too (RapidsMeta tagging, reused)."""
    from spark_rapids_tpu.plan.overrides import ExprMeta, _deep_reasons
    for e in exprs:
        em = ExprMeta(e, conf)
        em.tag()
        if not em.can_replace:
            raise NotDistributable(
                f"expression {type(e).__name__}: "
                + "; ".join(_deep_reasons(em)))


# ------------------------------------------------------- kernel wrappers --

def _mesh_sig(mesh):
    return (tuple(mesh.axis_names), tuple(mesh.devices.shape),
            tuple(str(d) for d in mesh.devices.flat))


def _ones_like_validity(c: ColVal, cap: int):
    return c.validity if c.validity is not None else \
        jnp.ones(cap, dtype=jnp.bool_)


def _run_project(f: ShardedFrame, exprs: Sequence[Expression], tag: str):
    """Compiled shard_map projection; returns the output column pairs."""
    import jax
    from spark_rapids_tpu.ops.aggregates import widen_colval
    from spark_rapids_tpu.ops.jit_cache import cached_jit
    phys = f.phys_dtypes

    def step(flat_cols, nrows_arr):
        nrows = nrows_arr[0]
        cols = [ColVal(dt, v, val)
                for (v, val), dt in zip(flat_cols, phys)]
        cap = cols[0].values.shape[0]
        ctx = EmitContext(cols, nrows, cap)
        outs = [widen_colval(e.emit(ctx), cap) for e in exprs]
        return tuple((c.values, _ones_like_validity(c, cap))
                     for c in outs)

    sig = (tag, _mesh_sig(f.mesh), tuple(dt.name for dt in phys),
           tuple(e.cache_key() for e in exprs))
    axis = f.mesh.axis_names[0]
    return cached_jit(sig, lambda: _shard_map(
        step, mesh=f.mesh, in_specs=(P(axis), P(axis)),
        out_specs=P(axis), check_vma=False))(f.cols, f.nrows)


def _run_expand(f: ShardedFrame, projections, out_phys):
    """Compiled shard_map Expand: K projection replicas per shard,
    compacted to the shard's live prefix via a replica/row gather — no
    exchange, capacity grows by K."""
    import jax
    from spark_rapids_tpu.ops.aggregates import widen_colval
    from spark_rapids_tpu.ops.jit_cache import cached_jit
    phys = f.phys_dtypes
    K = len(projections)

    def step(flat_cols, nrows_arr):
        nrows = nrows_arr[0]
        cols = [ColVal(dt, v, val)
                for (v, val), dt in zip(flat_cols, phys)]
        cap = cols[0].values.shape[0]
        ctx = EmitContext(cols, nrows, cap)
        out_cap = cap * K
        idx = jnp.arange(out_cap, dtype=jnp.int32)
        n = jnp.maximum(nrows, 1)
        rep = jnp.minimum(idx // n, K - 1)
        row = jnp.minimum(idx % n, cap - 1)
        outs = []
        for j, dt in enumerate(out_phys):
            stacked_v, stacked_m = [], []
            for proj in projections:
                c = widen_colval(proj[j].emit(ctx), cap)
                stacked_v.append(c.values.astype(dt.storage))
                stacked_m.append(_ones_like_validity(c, cap))
            sv = jnp.stack(stacked_v)   # (K, cap)
            sm = jnp.stack(stacked_m)
            outs.append((sv[rep, row], sm[rep, row]))
        return tuple(outs), (nrows * K).astype(jnp.int32)[None]

    sig = ("dplan_expand", _mesh_sig(f.mesh),
           tuple(dt.name for dt in phys),
           tuple(tuple(e.cache_key() for e in p) for p in projections))
    axis = f.mesh.axis_names[0]
    cols, nrows = cached_jit(sig, lambda: _shard_map(
        step, mesh=f.mesh, in_specs=(P(axis), P(axis)),
        out_specs=P(axis), check_vma=False))(f.cols, f.nrows)
    return cols, nrows.reshape(-1)


def _run_union(child_frames, out_phys, mesh):
    """Compiled shard_map Union: shard i concatenates its slices of
    every child's columns (live prefixes back to back) — no exchange."""
    import jax
    from spark_rapids_tpu.ops.jit_cache import cached_jit

    def step(*args):
        col_sets, nrow_arrs = args[0::2], args[1::2]
        caps = [cs[0][0].shape[0] for cs in col_sets]
        out_cap = sum(caps)
        ns = [a[0] for a in nrow_arrs]
        total = sum(ns)
        idx = jnp.arange(out_cap, dtype=jnp.int32)
        outs = []
        for j, dt in enumerate(out_phys):
            v = jnp.zeros(out_cap, dtype=dt.storage)
            m = jnp.zeros(out_cap, dtype=jnp.bool_)
            at = jnp.int32(0)
            for cs, n, cap in zip(col_sets, ns, caps):
                cv, cm = cs[j]
                src_pos = idx - at
                take = (src_pos >= 0) & (src_pos < n)
                safe = jnp.clip(src_pos, 0, cap - 1)
                v = jnp.where(take, cv.astype(dt.storage)[safe], v)
                m = jnp.where(take, cm[safe], m)
                at = at + n
            outs.append((v, m))
        return tuple(outs), total.astype(jnp.int32)[None]

    sig = ("dplan_union", _mesh_sig(mesh),
           tuple(dt.name for dt in out_phys),
           tuple(int(cf[0][0][0].shape[0]) for cf in child_frames))
    axis = mesh.axis_names[0]
    ins = []
    for cols, nrows in child_frames:
        ins.append(tuple(cols))
        ins.append(nrows)
    in_specs = tuple(P(axis) for _ in ins)
    cols, nrows = cached_jit(sig, lambda: _shard_map(
        step, mesh=mesh, in_specs=in_specs,
        out_specs=P(axis), check_vma=False))(*ins)
    return cols, nrows.reshape(-1)


def _run_slice(f: ShardedFrame, los, his):
    """Compiled shard_map row slice: each shard keeps its live rows in
    [lo, hi), compacted to the prefix (probe-side chunking for the
    chunked join emission)."""
    import jax
    from spark_rapids_tpu.ops.jit_cache import cached_jit

    def step(flat_cols, lo_arr, hi_arr):
        lo, hi = lo_arr[0], hi_arr[0]
        cap = flat_cols[0][0].shape[0]
        idx = jnp.arange(cap, dtype=jnp.int32) + lo
        safe = jnp.clip(idx, 0, cap - 1)
        outs = tuple((v[safe], m[safe]) for v, m in flat_cols)
        n = jnp.maximum(hi - lo, 0)
        return outs, n.astype(jnp.int32)[None]

    sig = ("dplan_slice", _mesh_sig(f.mesh),
           tuple(dt.name for dt in f.phys_dtypes))
    axis = f.mesh.axis_names[0]
    return cached_jit(sig, lambda: _shard_map(
        step, mesh=f.mesh, in_specs=(P(axis), P(axis), P(axis)),
        out_specs=P(axis), check_vma=False))(
        f.cols, mesh_lib.host_put(f.mesh, np.asarray(los, np.int32)),
        mesh_lib.host_put(f.mesh, np.asarray(his, np.int32)))


def _run_fused(f: ShardedFrame, exprs: Sequence[Expression],
               conds: Sequence[Expression]):
    """Compiled shard_map for a FUSED Filter/Project chain: every
    member's expressions evaluate in ONE computation — the member
    predicates (bottom-first) AND into a row mask carried inside the
    trace (each conjunct's ANSI checks masked by the conjuncts below
    it, the FilterStageFn discipline), projections stay in registers,
    and the selection compacts once at the stage boundary.  One
    dispatch per chain instead of one per member (exec/fusion.py; the
    distributed face of whole-stage fusion)."""
    import jax
    from spark_rapids_tpu.ops import selection
    from spark_rapids_tpu.ops.aggregates import widen_colval
    from spark_rapids_tpu.ops.jit_cache import cached_jit
    phys = f.phys_dtypes

    def step(flat_cols, nrows_arr):
        nrows = nrows_arr[0]
        cols = [ColVal(dt, v, val)
                for (v, val), dt in zip(flat_cols, phys)]
        cap = cols[0].values.shape[0]
        ctx = EmitContext(cols, nrows, cap)
        keep = None
        if conds:
            from spark_rapids_tpu.ops.expressions import fold_conjuncts
            # leaves the ANSI check mask at the survivor set for the
            # projections below (expressions.fold_conjuncts)
            keep = fold_conjuncts(ctx, conds)
        outs = [widen_colval(e.emit(ctx), cap) for e in exprs]
        if keep is None:
            return (tuple((c.values, _ones_like_validity(c, cap))
                          for c in outs),
                    nrows.astype(jnp.int32)[None])
        compacted, n = selection.compact(outs, keep)
        return (tuple((c.values, _ones_like_validity(c, cap))
                      for c in compacted),
                n.astype(jnp.int32)[None])

    sig = ("dplan_fused", _mesh_sig(f.mesh),
           tuple(dt.name for dt in phys),
           tuple(e.cache_key() for e in exprs),
           tuple(c.cache_key() for c in conds))
    axis = f.mesh.axis_names[0]
    cols, nrows = cached_jit(sig, lambda: _shard_map(
        step, mesh=f.mesh, in_specs=(P(axis), P(axis)),
        out_specs=P(axis), check_vma=False))(f.cols, f.nrows)
    return cols, nrows.reshape(-1)


def _run_filter(f: ShardedFrame, cond: Expression):
    import jax
    from spark_rapids_tpu.ops import selection
    from spark_rapids_tpu.ops.jit_cache import cached_jit
    phys = f.phys_dtypes

    def step(flat_cols, nrows_arr):
        nrows = nrows_arr[0]
        cols = [ColVal(dt, v, val)
                for (v, val), dt in zip(flat_cols, phys)]
        cap = cols[0].values.shape[0]
        ctx = EmitContext(cols, nrows, cap)
        pred = cond.emit(ctx)
        keep = pred.values
        if pred.validity is not None:
            keep = jnp.logical_and(keep, pred.validity)
        keep = jnp.logical_and(keep, ctx.row_mask())
        out, n = selection.compact(cols, keep)
        return (tuple((c.values, _ones_like_validity(c, cap))
                      for c in out),
                n.astype(jnp.int32)[None])

    sig = ("dplan_filter", _mesh_sig(f.mesh),
           tuple(dt.name for dt in phys), cond.cache_key())
    axis = f.mesh.axis_names[0]
    return cached_jit(sig, lambda: _shard_map(
        step, mesh=f.mesh, in_specs=(P(axis), P(axis)),
        out_specs=P(axis), check_vma=False))(f.cols, f.nrows)


def _append_key_cols(f: ShardedFrame, key_exprs) -> ShardedFrame:
    """Materialize key expressions as trailing columns (one compiled
    projection), so join kernels take plain column indices."""
    key_cols = _run_project(f, list(key_exprs), "dplan_keys")
    return ShardedFrame(
        f.mesh, f.names + [f"__k{i}" for i in range(len(key_exprs))],
        f.log_dtypes + [e.dtype for e in key_exprs],
        list(f.cols) + list(key_cols), f.nrows, f.enc)


# ---------------------------------------------------------------- planner --

class DistPlanner:
    """Eager recursive executor with a dry pre-flight mode."""

    # global cap on a distributed join's output buffer (rows across all
    # shards); beyond this the planner falls back rather than allocate
    MAX_OUT_ROWS = 1 << 27

    # exchange-consuming operators: their completed output is a stage
    # boundary the lineage log may checkpoint (robustness/checkpoint.py)
    _STAGE_OPS = None  # built lazily (L.Window import order)

    def __init__(self, session, mesh, resume: bool = False):
        self.session = session
        self.mesh = mesh
        self.conf = session.conf
        # wire-bytes watermark for this query: collect() stamps the
        # output batch with the exchange payload footprint recorded
        # between here and the final materialization (the transient-2x
        # HBM accounting, memory/spill.py SpillableHandle.wire_bytes)
        from spark_rapids_tpu.parallel.shuffle import (
            metrics_for_session, packed_enabled)
        self._wire0 = metrics_for_session(session).snapshot()
        # stage-checkpoint lineage: the per-query manager the driver
        # installed on the session (None when disabled / no catalog);
        # resume=True only on a retry-class re-attempt — the first
        # attempt never restores, it only writes.  A session-persistent
        # store (robustness/incremental.py) sets always_resume: its
        # input-fingerprinted stage ids are safe to splice across
        # queries, so continuous-ingest ticks restore on attempt one
        self._ckpt = getattr(session, "checkpoints", None)
        self._resume = self._ckpt is not None and self._ckpt.enabled \
            and (bool(resume) or
                 getattr(self._ckpt, "always_resume", False))
        # input fingerprints are folded into stage ids only for a
        # session-persistent store (cross-query splice needs input
        # identity); the per-query manager skips the stat walk — its
        # keys only need intra-query stability.  The memo caches each
        # scan node's walk for one planner run (inputs cannot change
        # mid-attempt)
        self._fp_inputs = getattr(self._ckpt, "always_resume", False)
        self._fp_memo: Dict[int, str] = {}
        self._packed = packed_enabled()
        # whole-stage fusion (exec/fusion.py, the distributed face):
        # Filter/Project chains — and the chain feeding an Aggregate —
        # collapse into one shard_map dispatch.  Never across an
        # exchange: fusion happens strictly BELOW the stage boundaries
        # the checkpoint lineage keys on, so stage_ids are untouched.
        from spark_rapids_tpu.config import rapids_conf as _rc
        self._fusion = bool(self.conf.get(_rc.FUSION_ENABLED))
        self._fusion_max = int(self.conf.get(_rc.FUSION_MAX_OPS))
        from spark_rapids_tpu.plan.costmodel import active_model
        # THIS session's model (or None), passed explicitly to every
        # consumer this planner constructs: a concurrent session
        # flipping TpuSession._active mid-query must never leak its
        # model into (or out of) this query's plan
        self._cost_model = active_model(session)
        if self._cost_model is not None:
            # self-tuning planner: one fusion-boundary decision shared
            # with the single-process planner (conf stays an override)
            self._fusion_max = self._cost_model.fusion_chain_limit()
        # async exchange/compute overlap (parallel/exchange_async.py):
        # exchange-bearing launches admit a handle into this bounded
        # window instead of blocking on their post-launch verification;
        # handles resolve at the next stage boundary (checkpoint save,
        # the next exchange under byte pressure, collect).  OFF on
        # recovery re-attempts (resume=True): a re-driven attempt runs
        # the synchronous path — AsyncExchangeOverflow's contract
        self._xwindow = None
        if self.conf.get(_rc.EXCHANGE_ASYNC_ENABLED) and not resume:
            from spark_rapids_tpu.parallel import exchange_async as _xa
            self._xwindow = _xa.ExchangeWindow(
                int(self.conf.get(_rc.EXCHANGE_INFLIGHT_WINDOW_BYTES)),
                metrics=_xa.overlap_metrics_for_session(session))
        self.fusion: Dict[str, int] = {
            "enabled": self._fusion, "fusedStages": 0,
            "fusedOperators": 0, "dispatchesSaved": 0,
            "fusibleChains": 0, "fallbacks": 0}
        # chain members already counted as fusible (when fusion is off
        # the members still convert one-by-one — the inner run must not
        # re-count as its own, shorter chain)
        self._counted_chain: set = set()

    @classmethod
    def _stage_ops(cls):
        if cls._STAGE_OPS is None:
            cls._STAGE_OPS = (L.Aggregate, L.Join, L.Sort, L.Window)
        return cls._STAGE_OPS

    def _checkpointable(self, plan: L.LogicalPlan) -> bool:
        """Stage boundaries worth checkpointing: every exchange
        consumer, plus top-N (a Limit over a Sort lowers into one
        distributed pass of its own)."""
        if isinstance(plan, self._stage_ops()):
            return True
        return isinstance(plan, L.Limit) and \
            isinstance(plan.child, L.Sort)

    def _count_stages(self, plan: L.LogicalPlan) -> int:
        """Exchange stages inside a subtree — what a resume of this
        checkpoint saves (CheckpointResume.stagesSaved)."""
        n = 1 if self._checkpointable(plan) else 0
        return n + sum(self._count_stages(c) for c in plan.children)

    def _emit_stats(self, op: str, stats, **extra) -> None:
        ev = getattr(self.session, "events", None)
        if ev is not None and ev.enabled and stats:
            clean = {k: v.tolist() if hasattr(v, "tolist") else v
                     for k, v in stats.items()}
            ev.emit("DistExchange", op=op, stats=clean, **extra)

    # -- recursion --------------------------------------------------------
    def run(self, plan: L.LogicalPlan, dry: bool) -> ShardedFrame:
        """Execute (or dry-run) one subtree, splicing in / registering
        stage checkpoints at exchange boundaries: on a resume attempt a
        completed subtree restores from the lineage log — its readers,
        stages, and collectives never run — and every freshly completed
        exchange stage registers its post-shuffle frame for the next
        attempt.  A checkpoint that fails verification or was evicted
        is dropped by the manager and the subtree re-runs here."""
        if not dry and self._checkpointable(plan):
            # fair-interleaver stage boundary: a distributed query's
            # "batches" are its exchange stages — gate here so a
            # heavy multi-stage query yields the mesh to co-tenants
            # between stages (serving/scheduler.py; no-op when the
            # interleave knob is off or no ticket is registered)
            from spark_rapids_tpu.serving.scheduler import \
                yield_current
            yield_current(self.session)
        if dry or self._ckpt is None or not self._ckpt.enabled or \
                not self._checkpointable(plan):
            return self._dispatch(plan, dry)
        from spark_rapids_tpu.robustness import checkpoint as cp
        from spark_rapids_tpu.utils import tracing
        sid = cp.stage_id(plan, self.mesh, self._packed,
                          memo=self._fp_memo, inputs=self._fp_inputs)
        if self._resume:
            frame = self._ckpt.restore(sid, self.mesh)
            if frame is not None:
                return frame
        if tracing._armed:
            # per-stage span keyed by the structural stage id: nested
            # stages subtract, so the rollup's per-site exclusive time
            # is each exchange stage's own cost — and the observation
            # store gets span_ms evidence under the same id the
            # checkpoint/jit machinery uses
            with tracing.span("stage.dist", site=sid,
                              op=type(plan).__name__):
                frame = self._dispatch(plan, dry)
        else:
            frame = self._dispatch(plan, dry)
        # async-exchange barrier BEFORE the checkpoint write: a frame
        # with an unverified speculative slot must never enter the
        # lineage log (a later resume would splice truncated bytes —
        # the one wrong-results hole the deferred overflow check opens)
        if self._xwindow is not None:
            self._xwindow.resolve_all()
        # shareable hint: a sid whose fingerprint folds ONLY file
        # triples (no id()-keyed in-memory batches) is derivable by
        # any query holding the identical subtree — the epoch-aware
        # shared tier publishes exactly those at commit.  Only
        # meaningful under input-fingerprinted ids (always_resume
        # stores); the walk is cheap (node count) and saves are rare.
        self._ckpt.save(sid, frame, stages=self._count_stages(plan),
                        shareable=self._fp_inputs and
                        not self._has_mem_relation(plan))
        return frame

    @staticmethod
    def _has_mem_relation(plan: L.LogicalPlan) -> bool:
        if isinstance(plan, L.InMemoryRelation):
            return True
        return any(DistPlanner._has_mem_relation(c)
                   for c in plan.children)

    def _dispatch(self, plan: L.LogicalPlan, dry: bool) -> ShardedFrame:
        if isinstance(plan, (L.InMemoryRelation, L.FileRelation, L.Range)):
            return self._scan(plan, dry)
        if isinstance(plan, (L.Filter, L.Project)):
            fused = self._fused_chain(plan, dry)
            if fused is not None:
                return fused
        if isinstance(plan, L.Filter):
            return self._filter(plan, dry)
        if isinstance(plan, L.Project):
            return self._project(plan, dry)
        if isinstance(plan, L.Aggregate):
            return self._aggregate(plan, dry)
        if isinstance(plan, L.Join):
            return self._join(plan, dry)
        if isinstance(plan, L.Sort):
            return self._sort(plan, dry)
        if isinstance(plan, L.Limit):
            if isinstance(plan.child, L.Sort):
                return self._topn(plan, dry)
            return self._limit(plan, dry)
        if isinstance(plan, L.Window):
            return self._window(plan, dry)
        if isinstance(plan, L.Union):
            return self._union(plan, dry)
        from spark_rapids_tpu.exec.expand import Expand as _Expand
        if isinstance(plan, _Expand):
            return self._expand(plan, dry)
        if isinstance(plan, L.Generate):
            # explode/posexplode: array columns have no mesh encoding
            # yet, so the generate itself runs on the controller as a
            # materialize barrier — but its OUTPUT is flat, and the
            # post-explode pipeline (where row counts are largest) still
            # distributes.  _scan executes the subtree single-process
            # and scatters row blocks (GpuGenerateExec stays an
            # exchange producer in the reference too).
            return self._scan(plan, dry)
        raise NotDistributable(
            f"{type(plan).__name__} has no distributed lowering")

    # -- scan -------------------------------------------------------------
    def _scan(self, plan: L.LogicalPlan, dry: bool) -> ShardedFrame:
        schema = list(plan.schema)
        for name, dt in schema:
            if not dt.is_string and (dt.has_offsets or dt.is_nested):
                raise NotDistributable(
                    f"scan column {name!r} of type {dt} not supported "
                    "on the mesh")
        names = [n for n, _ in schema]
        log_dtypes = [dt for _, dt in schema]
        if dry:
            enc = {i: [] for i, dt in enumerate(log_dtypes)
                   if dt.is_string}
            return ShardedFrame(self.mesh, names, log_dtypes, None, None,
                                enc)
        if isinstance(plan, L.FileRelation) and \
                plan.file_format in ("parquet", "orc"):
            try:
                return self._scan_sharded_files(plan, schema)
            except _UnsplittableScan:
                pass
        from spark_rapids_tpu.ops.concat import concat_batches
        from spark_rapids_tpu.ops.dictionary import ordered_dict_encode
        exec_plan = self.session.plan(plan)
        batches = list(exec_plan.execute())
        nshards = self.mesh.devices.size
        merged = concat_batches(batches) if batches else None
        total = merged.nrows if merged is not None else 0
        cap = bucket_capacity(max((total + nshards - 1) // nshards, 1),
                              minimum=8)
        base, rem = divmod(total, nshards)
        counts = np.array([base + (1 if i < rem else 0)
                           for i in range(nshards)], dtype=np.int32)
        offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])
        cols, enc = [], {}
        for i, (name, dt) in enumerate(schema):
            if merged is None:
                host = np.zeros(0, dtype=_phys(dt).storage)
                valid = np.ones(0, dtype=bool)
                if dt.is_string:
                    enc[i] = []
            else:
                col = merged.columns[name]
                valid = col.validity_numpy()
                if dt.is_string:
                    host, enc[i] = ordered_dict_encode(col)
                else:
                    host = col.host_values()[:total]
            vbuf = np.zeros((nshards, cap),
                            dtype=host.dtype if host.size
                            else _phys(dt).storage)
            mbuf = np.zeros((nshards, cap), dtype=bool)
            for s in range(nshards):
                sl = slice(offsets[s], offsets[s] + counts[s])
                vbuf[s, :counts[s]] = host[sl]
                mbuf[s, :counts[s]] = valid[sl]
            # host_put, not jnp.asarray: under a multi-controller mesh
            # every process executed the identical scan above, so each
            # contributes its addressable shards of the SAME global
            # buffer (single-controller this IS jnp.asarray)
            cols.append((mesh_lib.host_put(self.mesh, vbuf.reshape(-1)),
                         mesh_lib.host_put(self.mesh, mbuf.reshape(-1))))
        return ShardedFrame(self.mesh, names, log_dtypes, cols,
                            mesh_lib.host_put(self.mesh, counts), enc)

    def _scan_sharded_files(self, plan, schema) -> ShardedFrame:
        """Genuinely distributed scan: the FILE LIST is sharded across
        the mesh (greedy by per-file row counts from parquet/orc footer
        metadata) and each shard's split is read, encoded, and placed on
        its device one shard at a time — the controller never holds more
        than one shard's rows (the GpuMultiFileReader.scala:300 /
        GpuParquetScan.scala:973-1199 role: every task reads its own
        split).  Single-controller only for now: under multi-process
        JAX the per-host split read is not yet implemented, so the scan
        falls back instead of device_put-ing to a non-addressable
        device.

        String columns encode through a SHARED first-seen dictionary per
        column while reading, then remap on device to the sorted
        order-preserving codes the rest of the engine expects."""
        from spark_rapids_tpu.io.readers import _dataset
        from spark_rapids_tpu.ops.dictionary import dict_encode_stable
        nshards = self.mesh.devices.size
        devices = self.mesh.devices.reshape(-1)
        axis = self.mesh.axis_names[0]
        if jax.process_count() > 1 or any(
                d.process_index != jax.process_index() for d in devices):
            raise _UnsplittableScan("multi-process mesh")

        dataset = _dataset(plan.paths, plan.file_format)
        files = list(getattr(dataset, "files", None) or [])
        if not files:
            raise _UnsplittableScan("no listable files")
        bounds = [_file_row_bound(f, plan.file_format) for f in files]
        if any(b is None for b in bounds):
            raise _UnsplittableScan("row bounds unavailable")

        # greedy longest-first assignment of files to shards
        order = sorted(range(len(files)), key=lambda i: -bounds[i])
        shard_files: List[List[str]] = [[] for _ in range(nshards)]
        shard_bound = np.zeros(nshards, dtype=np.int64)
        for i in order:
            s = int(np.argmin(shard_bound))
            shard_files[s].append(files[i])
            shard_bound[s] += bounds[i]
        cap = bucket_capacity(max(int(shard_bound.max()), 1), minimum=8)

        names = [n for n, _ in schema]
        log_dtypes = [dt for _, dt in schema]
        str_idx = [i for i, dt in enumerate(log_dtypes) if dt.is_string]
        dicts = {i: ({}, []) for i in str_idx}  # codes, values
        counts = np.zeros(nshards, dtype=np.int32)
        peak_host_rows = 0
        # per column, the per-shard single-device buffers
        shard_bufs: List[List] = [[] for _ in range(2 * len(schema))]

        for s in range(nshards):
            if shard_files[s]:
                sub = L.FileRelation(shard_files[s], plan.file_format,
                                     plan._schema, plan.options,
                                     plan.bucket_spec)
                sub.pushed_filters = list(plan.pushed_filters)
                sub.required_columns = plan.required_columns
                sub.file_meta = set(plan.file_meta)
                batches = list(self.session.plan(sub).execute())
                rows = sum(b.nrows for b in batches)
            else:
                batches, rows = [], 0
            if rows > cap:
                raise _UnsplittableScan("row bound exceeded")
            counts[s] = rows
            peak_host_rows = max(peak_host_rows, rows)
            for i, (name, dt) in enumerate(schema):
                vbuf = np.zeros(cap, dtype=_phys(dt).storage)
                mbuf = np.zeros(cap, dtype=bool)
                at = 0
                for b in batches:
                    col = b.columns[name]
                    nb = col.nrows
                    if dt.is_string:
                        codes, values = dicts[i]
                        vbuf[at:at + nb] = dict_encode_stable(
                            col, codes, values, null_code=0)
                    else:
                        vbuf[at:at + nb] = col.host_values()[:nb]
                    mbuf[at:at + nb] = col.validity_numpy()
                    at += nb
                dev = devices[s]
                shard_bufs[2 * i].append(jax.device_put(vbuf, dev))
                shard_bufs[2 * i + 1].append(jax.device_put(mbuf, dev))
            del batches  # host copies of this shard are done

        sharding = NamedSharding(self.mesh, P(axis))
        gshape = (nshards * cap,)
        cols, enc = [], {}
        for i, (name, dt) in enumerate(schema):
            vals = jax.make_array_from_single_device_arrays(
                gshape, sharding, shard_bufs[2 * i])
            mask = jax.make_array_from_single_device_arrays(
                gshape, sharding, shard_bufs[2 * i + 1])
            if dt.is_string:
                codes_map, values = dicts[i]
                if values:
                    # remap first-seen codes -> sorted order-preserving
                    order_v = np.argsort(
                        np.array(values, dtype=object), kind="stable")
                    rank = np.empty(len(values), dtype=np.int64)
                    rank[order_v] = np.arange(len(values))
                    vals = _remap_codes(jnp.asarray(rank), vals)
                    enc[i] = [values[j] for j in order_v]
                else:
                    enc[i] = []
            cols.append((vals, mask))
        self.session.last_scan_stats = {
            "sharded_files": True, "files": len(files),
            "peak_host_rows": int(peak_host_rows),
            "total_rows": int(counts.sum())}
        return ShardedFrame(self.mesh, names, log_dtypes, cols,
                            jnp.asarray(counts), enc)

    # -- filter / project / fused chains ---------------------------------
    def _filter(self, plan: L.Filter, dry: bool) -> ShardedFrame:
        return self._filter_frame(self.run(plan.child, dry), plan, dry)

    def _filter_frame(self, f: ShardedFrame, plan: L.Filter,
                      dry: bool) -> ShardedFrame:
        low = ExprLowering(f.enc, self.conf)
        cond = low.lower(plan.condition)
        _check_supported([cond], self.conf)
        if dry:
            return f
        out_cols, nrows = _run_filter(f, cond)
        return f.replace(cols=list(out_cols), nrows=nrows)

    def _project(self, plan: L.Project, dry: bool) -> ShardedFrame:
        return self._project_frame(self.run(plan.child, dry), plan, dry)

    def _project_frame(self, f: ShardedFrame, plan: L.Project,
                       dry: bool) -> ShardedFrame:
        low = ExprLowering(f.enc, self.conf)
        exprs, enc = [], {}
        for i, e in enumerate(plan.exprs):
            le = low.lower(e)
            exprs.append(le)
            d = low.out_dict(le)
            if d is not None:
                enc[i] = d
        _check_supported(exprs, self.conf)
        names = [n for n, _ in plan.schema]
        log_dtypes = [dt for _, dt in plan.schema]
        if dry:
            return ShardedFrame(self.mesh, names, log_dtypes, None, None,
                                enc)
        out_cols = _run_project(f, exprs, "dplan_project")
        return ShardedFrame(self.mesh, names, log_dtypes, list(out_cols),
                            f.nrows, enc)

    def _chain_members(self, plan: L.LogicalPlan):
        """Maximal Filter/Project run starting at ``plan`` (top-down)
        and the tail node feeding it."""
        members: List[L.LogicalPlan] = []
        node = plan
        while isinstance(node, (L.Filter, L.Project)) and \
                len(members) < self._fusion_max:
            members.append(node)
            node = node.child
        return members, node

    def _replay_members(self, f: ShardedFrame, members,
                        dry: bool) -> ShardedFrame:
        """Unfused fallback: apply the chain member-by-member over the
        already-computed tail frame (the tail never re-runs).  The
        replay is per-shard re-execution with no collective inside, so
        it is hedge-eligible: when the mesh spans a SUSPECT host and
        gray failure is armed, an overrunning replay re-dispatches on
        the healthy ``dist.member_replay.hedge`` path and the first
        result wins (robustness/grayfailure.py)."""
        def _replay():
            from spark_rapids_tpu.robustness import grayfailure, watchdog
            from spark_rapids_tpu.robustness.inject import fire
            out = f
            point = grayfailure.hedge_point("dist.member_replay")
            with watchdog.section(point, session=self.session):
                if not dry:
                    fire(point)
                for node in reversed(members):
                    if isinstance(node, L.Filter):
                        out = self._filter_frame(out, node, dry)
                    else:
                        out = self._project_frame(out, node, dry)
            return out

        if dry:
            return _replay()
        from spark_rapids_tpu.robustness import grayfailure
        suspect = grayfailure.suspect_host_in(self.session, self.mesh)
        return grayfailure.hedged_call(
            self.session, "dist.member_replay", suspect, _replay)

    def _fused_chain(self, plan: L.LogicalPlan,
                     dry: bool) -> Optional[ShardedFrame]:
        """Collapse a Filter/Project chain into one shard_map dispatch;
        None when there is no chain (single member) — a member the
        composed lowering cannot ingest falls back to per-member
        execution over the same tail frame."""
        from spark_rapids_tpu.exec.fusion import compose_chain
        members, tail = self._chain_members(plan)
        if len(members) < 2:
            return None
        if not dry and id(plan) not in self._counted_chain:
            self.fusion["fusibleChains"] += 1
            self._counted_chain.update(id(m) for m in members)
        if not self._fusion:
            return None
        f = self.run(tail, dry)
        exprs, conds = None, []
        for node in members:
            exprs, conds = compose_chain(exprs, conds, node, node.schema)
        try:
            frame = self._fused_frame(f, exprs, conds, plan, dry)
        except NotDistributable:
            if not dry:
                self.fusion["fallbacks"] += 1
            return self._replay_members(f, members, dry)
        if not dry:
            self.fusion["fusedStages"] += 1
            self.fusion["fusedOperators"] += len(members)
            self.fusion["dispatchesSaved"] += len(members) - 1
        return frame

    def _fused_frame(self, f: ShardedFrame, exprs, conds, plan,
                     dry: bool) -> ShardedFrame:
        low = ExprLowering(f.enc, self.conf)
        lexprs, enc = [], {}
        for i, e in enumerate(exprs):
            le = low.lower(e)
            lexprs.append(le)
            d = low.out_dict(le)
            if d is not None:
                enc[i] = d
        lconds = [low.lower(c) for c in conds]
        _check_supported(lexprs + lconds, self.conf)
        names = [n for n, _ in plan.schema]
        log_dtypes = [dt for _, dt in plan.schema]
        if dry:
            return ShardedFrame(self.mesh, names, log_dtypes, None, None,
                                enc)
        out_cols, nrows = _run_fused(f, lexprs, lconds)
        return ShardedFrame(self.mesh, names, log_dtypes, list(out_cols),
                            nrows, enc)

    # -- aggregate --------------------------------------------------------
    def _aggregate(self, plan: L.Aggregate, dry: bool) -> ShardedFrame:
        """Aggregate, with the PRE-SHUFFLE fusion fold: a Filter/Project
        chain under the Aggregate composes into the aggregation kernel
        itself (projections substitute into key/agg expressions, the
        combined predicate rides as DistributedAggregate's filter_cond
        row mask) — filter, project, partial aggregate AND the
        partition-id computation all launch as ONE program per shard.
        A chain the composed lowering cannot ingest replays unfused
        over the same tail frame."""
        members, tail = self._chain_members(plan.child)
        if members and not self._fusion:
            # A/B baseline: the chain (even a single member — the
            # aggregate fold would absorb it) ran unfused; count it for
            # the health check and keep the members from re-counting as
            # their own chain during the per-op dispatch below
            if not dry and id(plan.child) not in self._counted_chain:
                self.fusion["fusibleChains"] += 1
                self._counted_chain.update(id(m) for m in members)
            members = []
        if not members:
            return self._aggregate_frame(
                plan, self.run(plan.child, dry), plan.group_exprs,
                plan.agg_exprs, None, dry)
        from spark_rapids_tpu.exec.fusion import compose_chain
        from spark_rapids_tpu.ops.expressions import substitute_bound
        if not dry:
            self.fusion["fusibleChains"] += 1
        exprs, conds = None, []
        for node in members:
            exprs, conds = compose_chain(exprs, conds, node, node.schema)
        group2 = [substitute_bound(e, exprs) for e in plan.group_exprs]
        aggs2 = [substitute_bound(e, exprs) for e in plan.agg_exprs]
        f = self.run(tail, dry)
        try:
            frame = self._aggregate_frame(plan, f, group2, aggs2,
                                          conds or None, dry)
        except NotDistributable:
            if not dry:
                self.fusion["fallbacks"] += 1
            f = self._replay_members(f, members, dry)
            return self._aggregate_frame(plan, f, plan.group_exprs,
                                         plan.agg_exprs, None, dry)
        if not dry:
            self.fusion["fusedStages"] += 1
            self.fusion["fusedOperators"] += len(members) + 1
            self.fusion["dispatchesSaved"] += len(members)
        return frame

    def _aggregate_frame(self, plan: L.Aggregate, f: ShardedFrame,
                         group_in, agg_in, pre_cond,
                         dry: bool) -> ShardedFrame:
        from spark_rapids_tpu.ops import aggregates as agg
        low = ExprLowering(f.enc, self.conf)
        group_exprs = [low.lower(e) for e in group_in]
        nkeys = len(group_exprs)

        # split agg outputs into bare aggregate calls + result exprs
        # (the _plan_aggregate split, Catalyst's resultExpressions)
        agg_list: List[AggregateExpression] = []

        group_keys = [ge.cache_key() for ge in group_exprs]

        def _has_agg(e):
            return isinstance(e, AggregateExpression) or \
                any(_has_agg(c) for c in e.children)

        def extract(e):
            if isinstance(e, AggregateExpression):
                le = low.lower_agg(e)
                idx = len(agg_list)
                agg_list.append(le)
                return BoundReference(nkeys + idx, le.dtype,
                                      name=f"_a{idx}",
                                      nullable=le.nullable)
            if not _has_agg(e):
                # group-key subtrees read the agg frame's key column,
                # not the child ordinal (Catalyst resultExpressions)
                le = low.lower(e)
                ck = le.cache_key()
                if ck in group_keys:
                    ki = group_keys.index(ck)
                    ge = group_exprs[ki]
                    return BoundReference(ki, ge.dtype, name=ge.name,
                                          nullable=ge.nullable)
                if not e.children:
                    if isinstance(le, BoundReference):
                        raise NotDistributable(
                            f"column {le.name!r} in aggregate output is "
                            "neither an aggregate nor in the GROUP BY")
                    return le
            return e.with_children([extract(c) for c in e.children])

        out_named = []
        trivial = True
        for e in agg_in:
            inner = e.children[0] if isinstance(e, Alias) else e
            rewritten = extract(inner)
            if not isinstance(inner, AggregateExpression):
                trivial = False
            out_named.append((e.name, rewritten))
        _check_supported(group_exprs, self.conf)
        _check_supported(agg_list, self.conf)
        # fused pre-shuffle chain: the upstream predicates (bottom-first
        # conjuncts) ride into the update kernel as a row mask with
        # progressive ANSI-check masking (exec/fusion.py)
        lcond = [low.lower(c) for c in pre_cond] if pre_cond else None
        if lcond:
            _check_supported(lcond, self.conf)

        # enc propagation: encoded group keys (bare or re-encoded) and
        # min/max/first/last over encoded children keep their
        # dictionaries
        agg_enc = {}
        for i, ge in enumerate(group_exprs):
            d = low.out_dict(ge)
            if d is not None:
                agg_enc[i] = d
        for idx, a in enumerate(agg_list):
            if isinstance(a.func, (agg.Min, agg.Max, agg.First, agg.Last)):
                d = low.out_dict(a.func.child) \
                    if a.func.child is not None else None
                if d is not None:
                    agg_enc[nkeys + idx] = d
        key_schema = [(e.name, e.dtype) for e in plan.group_exprs]
        agg_schema = key_schema + [(f"_a{i}", a.dtype)
                                   for i, a in enumerate(agg_list)]

        if dry:
            agg_frame = ShardedFrame(
                self.mesh, [n for n, _ in agg_schema],
                [dt for _, dt in agg_schema], None, None, agg_enc)
        else:
            from spark_rapids_tpu.parallel.distributed import (
                DistributedAggregate)
            dist = DistributedAggregate(
                self.mesh, in_dtypes=f.phys_dtypes,
                group_exprs=group_exprs,
                funcs=[a.func for a in agg_list],
                filter_cond=lcond,
                cost_model=self._cost_model,
                # compressed wire: the exchanged partial frame's code
                # columns (encoded group keys + encoded min/max/first/
                # last partials) with their dictionaries
                encoded_keys={i: d for i, d in agg_enc.items()
                              if i < nkeys},
                encoded_funcs={i - nkeys: d
                               for i, d in agg_enc.items()
                               if i >= nkeys})
            outs = dist([(v, val, None) for v, val in f.cols], f.nrows,
                        window=self._xwindow)
            self._emit_stats("aggregate", dist.last_stats)
            if not group_exprs:
                # grand totals are replicated (psum) on every shard;
                # count the single output row on shard 0 only
                nrows = np.zeros(f.nshards, dtype=np.int32)
                nrows[0] = 1
                nrows = mesh_lib.host_put(self.mesh, nrows)
            else:
                nrows = outs[0][2].reshape(-1)
            agg_frame = ShardedFrame(
                self.mesh, [n for n, _ in agg_schema],
                [dt for _, dt in agg_schema],
                [(v, val) for v, val, _ in outs], nrows, agg_enc)
        if trivial:
            # bare aggregates: rename outputs to the requested names
            return agg_frame.replace(names=[n for n, _ in plan.schema])
        # non-trivial outputs: project keys + result expressions
        proj = [BoundReference(i, dt, name=n)
                for i, (n, dt) in enumerate(agg_schema[:nkeys])]
        proj += [Alias(rewritten, name) for name, rewritten in out_named]
        # dictionaries follow bare references through the projection
        # (group keys AND encoded min/max aggregate outputs)
        agg_low = ExprLowering(agg_enc)
        penc = {}
        for i, e in enumerate(proj):
            src = agg_low.encoded_ref(e)
            if src is not None:
                penc[i] = agg_enc[src.ordinal]
        names = [n for n, _ in plan.schema]
        log_dtypes = [dt for _, dt in plan.schema]
        if dry:
            _check_supported(proj, self.conf)
            return ShardedFrame(self.mesh, names, log_dtypes, None, None,
                                penc)
        out_cols = _run_project(agg_frame, proj, "dplan_aggproj")
        return ShardedFrame(self.mesh, names, log_dtypes, list(out_cols),
                            agg_frame.nrows, penc)

    # -- join -------------------------------------------------------------
    def _join(self, plan: L.Join, dry: bool) -> ShardedFrame:
        if not plan.left_keys:
            raise NotDistributable(
                "cross / pure-residual joins have no distributed "
                "lowering")
        if plan.condition is not None and plan.join_type != "inner":
            raise NotDistributable(
                "residual conditions only distribute for inner joins")
        if plan.condition is not None and plan.using:
            raise NotDistributable(
                "residual conditions with USING joins not supported")
        str_keys = [i for i, (lk, rk) in enumerate(
            zip(plan.left_keys, plan.right_keys))
            if lk.dtype.is_string or rk.dtype.is_string]
        if str_keys and plan.using and plan.join_type == "full":
            raise NotDistributable(
                "full-outer USING join over string keys would coalesce "
                "codes from two dictionaries")
        left = self.run(plan.left, dry)
        right = self.run(plan.right, dry)
        low_l = ExprLowering(left.enc, self.conf)
        low_r = ExprLowering(right.enc, self.conf)
        lkeys = [low_l.lower(e) for e in plan.left_keys]
        rkeys = [low_r.lower(e) for e in plan.right_keys]
        _check_supported(lkeys + rkeys, self.conf)
        for i in str_keys:
            # string keys join as codes: the probe side re-codes into
            # the build side's dictionary below (GpuHashJoin.scala:96-150
            # treats string keys first-class; here the exchanged payload
            # stays int64)
            if low_l.out_dict(lkeys[i]) is None or \
                    low_r.out_dict(rkeys[i]) is None:
                raise NotDistributable(
                    "string join key has no dictionary on the mesh")

        swapped = plan.join_type == "right"
        join_type = "left" if swapped else plan.join_type
        if swapped:
            probe, build = right, left
            probe_keys, build_keys = rkeys, lkeys
        else:
            probe, build = left, right
            probe_keys, build_keys = lkeys, rkeys

        # output layout before reorder: probe cols + build cols (or probe
        # only for semi/anti); rebuild left+right then Join.schema order
        if plan.join_type in ("semi", "anti"):
            out_names = list(left.names)
            out_dtypes = list(left.log_dtypes)
            out_enc = dict(left.enc)
        else:
            out_names = list(left.names) + list(right.names)
            out_dtypes = list(left.log_dtypes) + list(right.log_dtypes)
            out_enc = dict(left.enc)
            for o, d in right.enc.items():
                out_enc[len(left.names) + o] = d

        cond = None
        if plan.condition is not None:
            cond = ExprLowering(out_enc, self.conf).lower(plan.condition)
            _check_supported([cond], self.conf)

        # USING joins dedup the key columns; the PRESERVED side supplies
        # the key value (right for right joins, coalesce for full) —
        # mirrors TpuHashJoinExec's stitch
        proj = None
        if plan.using and plan.join_type not in ("semi", "anti"):
            keyset = set(plan.using)
            nleft = len(left.names)
            proj, penc = [], {}

            def ref(i):
                return BoundReference(i, out_dtypes[i], name=out_names[i])

            for n in left.names:
                if n not in keyset:
                    continue
                li = left.names.index(n)
                ri = nleft + right.names.index(n)
                if plan.join_type == "full":
                    proj.append(Alias(preds.Coalesce(ref(li), ref(ri)), n))
                elif swapped:
                    if ri in out_enc:
                        penc[len(proj)] = out_enc[ri]
                    proj.append(Alias(ref(ri), n))
                else:
                    if li in out_enc:
                        penc[len(proj)] = out_enc[li]
                    proj.append(ref(li))
            for i, n in enumerate(left.names):
                if n not in keyset:
                    if i in out_enc:
                        penc[len(proj)] = out_enc[i]
                    proj.append(ref(i))
            for i, n in enumerate(right.names):
                if n not in keyset:
                    if nleft + i in out_enc:
                        penc[len(proj)] = out_enc[nleft + i]
                    proj.append(ref(nleft + i))
            proj_schema = [(e.name, e.dtype) for e in proj]

        if dry:
            if proj is not None:
                return ShardedFrame(self.mesh,
                                    [n for n, _ in proj_schema],
                                    [dt for _, dt in proj_schema],
                                    None, None, penc)
            return ShardedFrame(self.mesh, out_names, out_dtypes, None,
                                None, out_enc)

        probe_m = _append_key_cols(probe, probe_keys)
        build_m = _append_key_cols(build, build_keys)
        pk_idx = list(range(len(probe.names),
                            len(probe.names) + len(probe_keys)))
        bk_idx = list(range(len(build.names),
                            len(build.names) + len(build_keys)))
        # compressed wire: every code-valued exchanged column — body
        # columns from each side's frame enc, plus the appended string
        # key columns (the probe key's dictionary is the BUILD side's
        # after the remap below)
        probe_enc = dict(probe_m.enc)
        build_enc = dict(build_m.enc)
        if str_keys:
            # re-code the probe side's string key codes into the build
            # dictionary: value-equal codes become equal ints, values
            # absent from the build side map to -1 (never a build code)
            low_p, low_b = (low_r, low_l) if swapped else (low_l, low_r)
            cols = list(probe_m.cols)
            for i in str_keys:
                pd_ = low_p.out_dict(probe_keys[i])
                bd = low_b.out_dict(build_keys[i])
                pos = {v: c for c, v in enumerate(bd)}
                mapping = np.array([pos.get(v, -1) for v in pd_] or [-1],
                                   dtype=np.int64)
                vals, valid = cols[pk_idx[i]]
                cols[pk_idx[i]] = (
                    _remap_codes(jnp.asarray(mapping),
                                 jnp.clip(vals, 0, len(mapping) - 1)),
                    valid)
                probe_enc[pk_idx[i]] = bd
                build_enc[bk_idx[i]] = bd
            probe_m = probe_m.replace(cols=cols)
        flat, n_out = self._exec_join(probe_m, build_m, pk_idx, bk_idx,
                                      join_type, plan.join_type,
                                      probe_enc=probe_enc,
                                      build_enc=build_enc)
        n_out = n_out.reshape(-1)
        n_probe = len(probe.names)
        n_build = len(build.names)
        if plan.join_type in ("semi", "anti"):
            cols = list(flat[:n_probe])
        else:
            probe_cols = list(flat[:n_probe])
            build_cols = list(flat[len(probe_m.names):
                                   len(probe_m.names) + n_build])
            if swapped:
                cols = build_cols + probe_cols
            else:
                cols = probe_cols + build_cols
        frame = ShardedFrame(self.mesh, out_names, out_dtypes, cols,
                             n_out.reshape(-1), out_enc)
        if cond is not None:
            out_cols, nrows = _run_filter(frame, cond)
            frame = frame.replace(cols=list(out_cols),
                                  nrows=nrows.reshape(-1))
        if proj is not None:
            out_cols = _run_project(frame, proj, "dplan_joinproj")
            frame = ShardedFrame(self.mesh, [n for n, _ in proj_schema],
                                 [dt for _, dt in proj_schema],
                                 list(out_cols), frame.nrows, penc)
        return frame

    def _exec_join(self, probe_m, build_m, pk_idx, bk_idx, join_type,
                   plan_join_type, depth: int = 0,
                   probe_enc=None, build_enc=None):
        """Run the distributed hash join with output-size retry; when
        the needed output exceeds MAX_OUT_ROWS, degrade to CHUNKED
        emission (probe-side slices joined separately and unioned per
        shard — the JoinGatherer.scala:36-60 role) instead of falling
        off the mesh."""
        from spark_rapids_tpu.parallel.distributed import (
            DistributedHashJoin)
        probe_cap = probe_m.capacity
        nshards = self.mesh.devices.size
        out_factor = 1
        while True:
            join = DistributedHashJoin(
                self.mesh, probe_dtypes=probe_m.phys_dtypes,
                build_dtypes=build_m.phys_dtypes,
                probe_key_idx=pk_idx, build_key_idx=bk_idx,
                join_type=join_type, out_factor=out_factor,
                probe_encoded=probe_enc, build_encoded=build_enc,
                cost_model=self._cost_model)
            flat, n_out, total = join(
                probe_m.cols, probe_m.nrows, build_m.cols,
                build_m.nrows, window=self._xwindow)
            # process_count-aware fetch: the retry decision must be
            # identical on every controller (host_sync allgathers under
            # multi-process SPMD)
            from spark_rapids_tpu.parallel.distributed import host_sync
            h_total, h_nout = host_sync((total, n_out))
            if bool(np.all(h_total <= h_nout)):
                break
            # size the retry from the observed truncation; out_cap is
            # relative to the (possibly tiny) probe capacity, so the
            # factor itself may legitimately grow large
            need = int(h_total.max())
            next_factor = out_factor * 2
            while next_factor * probe_cap < need:
                next_factor *= 2  # power-of-two: bounded compile cache
            if (next_factor * probe_cap * nshards > self.MAX_OUT_ROWS):
                return self._exec_join_chunked(
                    probe_m, build_m, pk_idx, bk_idx, join_type,
                    plan_join_type, depth, probe_enc=probe_enc,
                    build_enc=build_enc)
            out_factor = next_factor
        self._emit_stats(f"join:{plan_join_type}", join.last_stats,
                         out_factor=out_factor, depth=depth)
        return flat, n_out

    def _exec_join_chunked(self, probe_m, build_m, pk_idx, bk_idx,
                           join_type, plan_join_type, depth: int,
                           probe_enc=None, build_enc=None):
        if join_type == "full":
            # probe-side chunking is linear only when each probe row's
            # output is independent; a full join also emits
            # unmatched-BUILD rows, which chunking would duplicate
            raise NotDistributable(
                "full-outer join output exceeds the distributed output "
                "cap (chunked emission covers inner/left/semi/anti)")
        if depth >= 6:
            raise NotDistributable(
                "join output exceeds the distributed output cap even "
                "with 64-way chunked emission")
        from spark_rapids_tpu.parallel.distributed import host_sync
        counts = host_sync(probe_m.nrows).reshape(-1)
        chunks = []
        for i in range(2):
            los = (counts * i) // 2
            his = (counts * (i + 1)) // 2
            cols, nr = _run_slice(probe_m, los, his)
            sliced = probe_m.replace(cols=list(cols),
                                     nrows=nr.reshape(-1))
            flat, n_out = self._exec_join(sliced, build_m, pk_idx,
                                          bk_idx, join_type,
                                          plan_join_type, depth + 1,
                                          probe_enc=probe_enc,
                                          build_enc=build_enc)
            chunks.append((list(flat), n_out.reshape(-1)))
        if len(chunks[0][0]) > len(probe_m.names):
            dtypes = probe_m.phys_dtypes + build_m.phys_dtypes
        else:
            dtypes = probe_m.phys_dtypes
        dtypes = dtypes[: len(chunks[0][0])]
        cols, nrows = _run_union(chunks, dtypes, self.mesh)
        return tuple(cols), nrows

    # -- sort / limit / topn ---------------------------------------------
    def _lower_orders(self, orders, f: ShardedFrame):
        low = ExprLowering(f.enc, self.conf)
        keys = [low.lower(e) for e, _, _ in orders]
        _check_supported(keys, self.conf)
        desc = [d for _, d, _ in orders]
        nf = [n for _, _, n in orders]
        return keys, desc, nf

    def _sort(self, plan: L.Sort, dry: bool) -> ShardedFrame:
        from spark_rapids_tpu.parallel.distsort import DistributedSort
        f = self.run(plan.child, dry)
        keys, desc, nf = self._lower_orders(plan.orders, f)
        if dry:
            return f
        dist = DistributedSort(self.mesh, f.phys_dtypes, keys, desc, nf,
                               cost_model=self._cost_model)
        out_cols, nrows = dist(f.cols, f.nrows)
        self._emit_stats("sort", dist.last_stats)
        return f.replace(cols=list(out_cols), nrows=nrows.reshape(-1))

    # -- window -----------------------------------------------------------
    def _window(self, plan: L.Window, dry: bool) -> ShardedFrame:
        """Window as an exchange consumer (GpuWindowExec role).

        Expressions are grouped by their window spec; each spec group
        runs one distributed pass — partitioned specs range-partition on
        the PARTITION BY prefix (a partition never splits a shard) then
        evaluate shard-locally; GLOBAL specs (no PARTITION BY) sort
        globally and fix up with the collective cross-shard carry
        (parallel/distwindow.DistributedGlobalWindow, the mesh analog of
        GpuWindowExec.scala:423-446's running-window optimization).
        Later groups see earlier groups' outputs as ordinary payload
        columns; the final column order is restored to plan.schema."""
        from spark_rapids_tpu.exec.window import (WindowExpression,
                                                  WindowSpec,
                                                  group_by_spec)
        from spark_rapids_tpu.parallel.distwindow import (
            DistributedGlobalWindow, DistributedWindow)
        f = self.run(plan.child, dry)
        exprs = plan.window_exprs
        nchild = len(f.names)
        groups = group_by_spec(exprs)

        names = [n for n, _ in plan.schema]
        log_dtypes = [dt for _, dt in plan.schema]
        cur_names = list(f.names)
        cur_dts = list(f.log_dtypes)
        cur_enc = dict(f.enc)
        cur_cols, cur_nrows = f.cols, f.nrows
        appended_pos: Dict[int, int] = {}
        for grp in groups:
            spec0 = grp[0][2].spec
            is_global = not spec0.partition_exprs
            low = ExprLowering(cur_enc, self.conf)
            lspec = WindowSpec(
                [low.lower(e) for e in spec0.partition_exprs],
                [(low.lower(e), d, nf) for e, d, nf in spec0.orders],
                spec0.frame)
            _check_supported(list(lspec.partition_exprs) +
                             [e for e, _, _ in lspec.orders], self.conf)
            lowered = []
            enc_new = {}
            base = len(cur_names)
            for i, (j, name, we) in enumerate(grp):
                reason = we.supported_reason()
                if reason:
                    raise NotDistributable(f"window {name}: {reason}")
                if is_global:
                    fr = we.spec.frame
                    if we.kind in ("lead", "lag"):
                        raise NotDistributable(
                            "global lead/lag needs a cross-shard halo "
                            "exchange")
                    if we.kind in ("sum", "count", "avg") and not (
                            fr.lo is None and fr.hi in (0, None)):
                        raise NotDistributable(
                            "global window frames with finite row "
                            "offsets need a cross-shard halo exchange")
                ch = None
                if we.child_expr is not None:
                    ch = low.lower(we.child_expr)
                    _check_supported([ch], self.conf)
                    d = low.out_dict(ch)
                    if d is not None:
                        if we.kind in ("min", "max", "lead", "lag"):
                            # order-preserving codes: output is codes too
                            enc_new[base + i] = d
                        elif we.kind != "count":  # count: validity only
                            raise NotDistributable(
                                f"window {we.kind} over strings not "
                                "supported on the mesh")
                dflt = low.lower(we.default) if we.default is not None \
                    else None
                lowered.append((name, WindowExpression(
                    we.kind, lspec, ch, we.offset, dflt)))
            phys_before = [_phys(dt) for dt in cur_dts]
            for i, (j, name, we) in enumerate(grp):
                appended_pos[j] = base + i
                cur_names.append(name)
                cur_dts.append(log_dtypes[nchild + j])
            cur_enc.update(enc_new)
            if not dry:
                cls = DistributedWindow if not is_global \
                    else DistributedGlobalWindow
                dist = cls(self.mesh, phys_before, lowered)
                cols, nrows2 = dist(cur_cols, cur_nrows)
                cur_cols = list(cols)
                cur_nrows = jnp.asarray(nrows2).reshape(-1)
                self._emit_stats("window", dist.last_stats,
                                 window_global=is_global)

        # restore plan.schema order: child columns stay first, window
        # columns return to their original expression order
        perm = list(range(nchild)) + \
            [appended_pos[j] for j in range(len(exprs))]
        enc = {o: d for o, d in cur_enc.items() if o < nchild}
        inv = {p: nchild + j for j, p in appended_pos.items()}
        for p, d in cur_enc.items():
            if p >= nchild and p in inv:
                enc[inv[p]] = d
        if dry:
            return ShardedFrame(self.mesh, names, log_dtypes, None, None,
                                enc)
        out_cols = [cur_cols[p] for p in perm]
        return ShardedFrame(self.mesh, names, log_dtypes, out_cols,
                            cur_nrows, enc)

    # -- expand / union ---------------------------------------------------
    def _expand(self, plan, dry: bool) -> ShardedFrame:
        """Expand is embarrassingly parallel: each shard emits its K
        projection replicas locally; no exchange (GpuExpandExec role)."""
        from spark_rapids_tpu.exec.expand import NullLiteral
        f = self.run(plan.child, dry)
        low = ExprLowering(f.enc, self.conf)
        projections = []
        enc_new = {}
        for k, proj in enumerate(plan.projections):
            lowered = []
            for j, e in enumerate(proj):
                if isinstance(e, NullLiteral):
                    le = NullLiteral(_phys(e.dtype))
                else:
                    le = low.lower(e)
                    d = low.out_dict(le)
                    if d is not None:
                        prev = enc_new.get(j)
                        if prev is not None and prev is not d:
                            raise NotDistributable(
                                "expand projections disagree on a "
                                "string column's dictionary")
                        enc_new[j] = d
                lowered.append(le)
            projections.append(lowered)
        for proj in projections:
            _check_supported(
                [e for e in proj
                 if not isinstance(e, NullLiteral)], self.conf)
        names = [n for n, _ in plan.schema]
        log_dtypes = [dt for _, dt in plan.schema]
        for j, dt in enumerate(log_dtypes):
            if dt.is_string and j not in enc_new:
                raise NotDistributable(
                    f"expand string column {names[j]!r} has no "
                    "dictionary on the mesh")
        if dry:
            return ShardedFrame(self.mesh, names, log_dtypes, None, None,
                                enc_new)
        cols, nrows = _run_expand(f, projections,
                                  [_phys(dt) for dt in log_dtypes])
        return ShardedFrame(self.mesh, names, log_dtypes, list(cols),
                            nrows, enc_new)

    def _union(self, plan: L.Union, dry: bool) -> ShardedFrame:
        """Union keeps rows where they are: shard i's output is the
        concatenation of shard i's slices of every child (no exchange)."""
        frames = [self.run(c, dry) for c in plan.children]
        names = [n for n, _ in plan.schema]
        log_dtypes = [dt for _, dt in plan.schema]
        # encoded string columns would need dictionary alignment across
        # children; only distribute when no column is a string
        if any(dt.is_string for dt in log_dtypes):
            raise NotDistributable(
                "union over string columns needs dictionary alignment "
                "(not yet distributed)")
        if dry:
            return ShardedFrame(self.mesh, names, log_dtypes, None, None,
                                {})
        cols, nrows = _run_union([(fr.cols, fr.nrows) for fr in frames],
                                 [_phys(dt) for dt in log_dtypes],
                                 self.mesh)
        return ShardedFrame(self.mesh, names, log_dtypes, list(cols),
                            nrows, {})

    def _limit(self, plan: L.Limit, dry: bool) -> ShardedFrame:
        f = self.run(plan.child, dry)
        if dry:
            return f
        counts = mesh_lib.to_host(f.nrows).copy()
        left = plan.n
        for i in range(len(counts)):
            take = min(int(counts[i]), left)
            counts[i] = take
            left -= take
        return f.replace(nrows=mesh_lib.host_put(
            self.mesh, counts.astype(np.int32)))

    def _topn(self, plan: L.Limit, dry: bool) -> ShardedFrame:
        from spark_rapids_tpu.parallel.distsort import (
            DistributedTopN, host_order)
        sort = plan.child
        f = self.run(sort.child, dry)
        keys, desc, nf = self._lower_orders(sort.orders, f)
        if dry:
            return f
        dist = DistributedTopN(self.mesh, f.phys_dtypes, keys, desc, nf,
                               plan.n)
        flat, key_flat, nrows = dist(f.cols, f.nrows)
        nshards = f.nshards
        counts = mesh_lib.to_host(nrows).reshape(-1)
        cap = int(flat[0][0].shape[0]) // nshards

        def host_rows(pair):
            v = mesh_lib.to_host(pair[0]).reshape(nshards, cap)
            m = mesh_lib.to_host(pair[1]).reshape(nshards, cap)
            vs = np.concatenate([v[i, :counts[i]] for i in range(nshards)])
            ms = np.concatenate([m[i, :counts[i]] for i in range(nshards)])
            return vs, ms

        hkeys = [host_rows(p) for p in key_flat]
        order = host_order([v for v, _ in hkeys], [m for _, m in hkeys],
                           desc, nf)[:plan.n]
        n = len(order)
        out_cap = bucket_capacity(max(n, 1), minimum=8)
        cols = []
        for pair in flat:
            vs, ms = host_rows(pair)
            vbuf = np.zeros(nshards * out_cap, dtype=vs.dtype)
            mbuf = np.zeros(nshards * out_cap, dtype=bool)
            vbuf[:n] = vs[order]
            mbuf[:n] = ms[order]
            cols.append((mesh_lib.host_put(self.mesh, vbuf),
                         mesh_lib.host_put(self.mesh, mbuf)))
        out_counts = np.zeros(nshards, dtype=np.int32)
        out_counts[0] = n
        return f.replace(cols=cols,
                         nrows=mesh_lib.host_put(self.mesh, out_counts))

    # -- collect ----------------------------------------------------------
    def collect(self, f: ShardedFrame) -> ColumnarBatch:
        # final stage boundary: every in-flight exchange must verify
        # before its bytes materialize to the host (a deferred overflow
        # raises here and the ladder re-drives — truncated frames never
        # reach a client)
        if self._xwindow is not None:
            self._xwindow.resolve_all()
        nshards = f.nshards
        cap = f.capacity
        counts = mesh_lib.to_host(f.nrows).reshape(-1)
        total = int(counts.sum())
        out = {}
        for i, ((name, dt), (v, m)) in enumerate(zip(f.schema, f.cols)):
            vals = mesh_lib.to_host(v).reshape(nshards, cap)
            mask = mesh_lib.to_host(m).reshape(nshards, cap)
            if total:
                vs = np.concatenate(
                    [vals[s, :counts[s]] for s in range(nshards)])
                ms = np.concatenate(
                    [mask[s, :counts[s]] for s in range(nshards)])
            else:
                vs = np.zeros(0, dtype=vals.dtype)
                ms = np.zeros(0, dtype=bool)
            if i in f.enc:
                values = f.enc[i]
                decoded = [values[int(c)] if ok else None
                           for c, ok in zip(vs, ms)]
                out[name] = Column.from_strings(decoded)
            else:
                storage = np.dtype(dt.storage)
                out[name] = Column.from_numpy(
                    vs.astype(storage, copy=False), dtype=dt,
                    validity=None if bool(ms.all()) else ms)
        batch = ColumnarBatch(out, total)
        # per-device share of the LAST exchange's payload bytes: the
        # ShardedFrame's device arrays (and the exchange lane buffers
        # backing them) stay alive until this result drops, so a
        # consumer that spill-registers the batch (pipeline / coalesce)
        # reserves that headroom.  Today the distributed result is
        # usually consumed straight by collect/to_arrow — the
        # reservation engages when the batch re-enters the engine (an
        # InMemoryRelation scan of a distributed result) and is the
        # wiring point for a future device-resident handoff that skips
        # the host round trip entirely.  Only when this query exchanged
        # at all (delta guard) — and only the most recent launch's
        # payload, never the query's cumulative bytes (earlier
        # exchanges' buffers are already reused; summing them would
        # overstate the reservation and trigger spurious spills).
        from spark_rapids_tpu.parallel.shuffle import (
            ShuffleWireMetrics, metrics_for_session)
        m = metrics_for_session(self.session)
        delta = ShuffleWireMetrics.delta(m.snapshot(), self._wire0)
        if delta.get("exchanges", 0):
            batch.transient_wire_bytes = \
                m.last_exchange_bytes // max(self.mesh.devices.size, 1)
        return batch


def try_distributed(session, plan: L.LogicalPlan, resume: bool = False):
    """Entry point from DataFrame execution: returns a list of
    ColumnarBatches when the plan ran on the mesh, else None (single-
    process fallback; reason on ``session.last_dist_explain``).
    ``resume=True`` on a recovery re-attempt lets the planner splice in
    stage checkpoints recorded by the failed attempt."""
    mesh = getattr(session, "mesh", None)
    if mesh is None:
        return None
    from spark_rapids_tpu.config import rapids_conf as rc
    if not session.conf.get(rc.DISTRIBUTED_ENABLED):
        session.last_dist_explain = "distributed disabled by conf"
        return None
    planner = DistPlanner(session, mesh, resume=resume)
    session.last_scan_stats = None  # per-query: no stale sharded stats
    session.last_fusion_stats = None  # per-query fusion attribution
    from spark_rapids_tpu.parallel import exchange_async as _xa
    _xa.set_current_window(planner._xwindow)
    try:
        planner.run(plan, dry=True)  # support pre-flight: no data moves
        # data-dependent limits (e.g. join fan-out vs output capacity)
        # can only surface while executing; they fall back too
        batch = planner.collect(planner.run(plan, dry=False))
    except NotDistributable as e:
        # an unverified exchange from a partially-executed attempt is
        # moot — the single-process fallback recomputes from source
        if planner._xwindow is not None:
            planner._xwindow.discard_all()
        session.last_dist_explain = f"fallback: {e}"
        ev = getattr(session, "events", None)
        if ev is not None and ev.enabled:
            ev.emit("DistFallback", reason=str(e))
        return None
    except BaseException:
        # failed attempt: the recovery ladder re-drives the whole query
        # (on the synchronous path); pending handles just release their
        # window bytes, nothing to verify
        if planner._xwindow is not None:
            planner._xwindow.discard_all()
        raise
    finally:
        _xa.set_current_window(None)
    session.last_dist_explain = "distributed"
    session.last_fusion_stats = dict(planner.fusion)
    if planner._ckpt is not None:
        # per-execution completion signal, delivered on THIS query's
        # thread (robustness/checkpoint.py note_distributed_complete)
        planner._ckpt.note_distributed_complete()
    return [batch]
