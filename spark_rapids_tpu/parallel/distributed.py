"""Distributed query steps: SPMD pipelines over the device mesh.

The reference executes a distributed aggregation as: partial agg per task ->
hash-partitioned UCX shuffle -> final agg per reducer (SURVEY.md sections 3.3
and 3.4).  Here the entire sequence — filter, partial aggregate, shuffle
by key, final aggregate — is ONE ``shard_map``-ped XLA program: the shuffle
is a compiled all-to-all riding ICI, overlapping with compute under XLA's
scheduler, with zero host round trips between stages.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops import aggregates as agg
from spark_rapids_tpu.ops import selection
from spark_rapids_tpu.ops.expressions import ColVal, EmitContext, Expression
from spark_rapids_tpu.parallel.partitioning import hash_partition_ids
from spark_rapids_tpu.parallel.shuffle import exchange


class DistributedAggregate:
    """filter? -> partial group-by -> all-to-all by key hash -> final agg.

    Inputs are leading-axis sharded arrays: each of the mesh's N shards holds
    a [capacity] slice of every column plus its own row count.  Outputs stay
    sharded — each shard owns the key range that hashed to it (the reducer
    layout); a collect all-gathers afterwards if needed.
    """

    def __init__(self, mesh: Mesh, in_dtypes: Sequence[DataType],
                 group_exprs: Sequence[Expression],
                 funcs: Sequence[agg.AggregateFunction],
                 filter_cond: Optional[Expression] = None):
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.nshards = mesh.devices.size
        self.in_dtypes = list(in_dtypes)
        self.group_exprs = list(group_exprs)
        self.funcs = list(funcs)
        self.filter_cond = filter_cond

        self._buf_specs = []
        self._buf_slices = []
        for f in self.funcs:
            specs = f.buffers()
            self._buf_slices.append(
                slice(len(self._buf_specs), len(self._buf_specs) + len(specs)))
            self._buf_specs.extend(specs)

        from spark_rapids_tpu.ops.jit_cache import cached_jit
        sig = ("dist_agg", tuple(self.mesh.axis_names),
               tuple(self.mesh.devices.shape),
               tuple(str(d) for d in self.mesh.devices.flat),
               tuple(dt.name for dt in self.in_dtypes),
               tuple(e.cache_key() for e in self.group_exprs),
               tuple(f.cache_key() for f in self.funcs),
               self.filter_cond.cache_key()
               if self.filter_cond is not None else None)
        self._jitted = cached_jit(
            sig, lambda: jax.shard_map(
                self._step, mesh=mesh,
                in_specs=(P(self.axis), P(self.axis)),
                out_specs=P(self.axis), check_vma=False))

    # ---- SPMD body (runs per shard) -----------------------------------------
    def _step(self, flat_cols, nrows_arr):
        nrows = nrows_arr[0]
        capacity = None
        for v, _, _ in flat_cols:
            capacity = v.shape[0]
            break
        inputs = [ColVal(dt, v, val, offs)
                  for (v, val, offs), dt in zip(flat_cols, self.in_dtypes)]
        ctx = EmitContext(inputs, nrows, capacity)

        # 1. fused filter
        if self.filter_cond is not None:
            pred = self.filter_cond.emit(ctx)
            keep = pred.values
            if pred.validity is not None:
                keep = jnp.logical_and(keep, pred.validity)
            keep = jnp.logical_and(keep, ctx.row_mask())
            compacted, nrows = selection.compact(inputs, keep)
            ctx = EmitContext(compacted, nrows, capacity)

        # 2. local partial aggregate
        keys = [e.emit(ctx) for e in self.group_exprs]
        buf_inputs = []
        for f in self.funcs:
            c = f.child.emit(ctx) if f.child is not None else None
            if c is not None and getattr(c.values, "ndim", 0) == 0:
                c = ColVal(c.dtype,
                           jnp.broadcast_to(c.values, (capacity,)), c.validity)
            for spec, cv in zip(f.buffers(), f.update_inputs(c, capacity)):
                buf_inputs.append((spec.kind, cv))

        if not keys:
            # grand total: local reduce then a psum-style merge via exchange
            outs = agg.reduce_aggregate(buf_inputs, nrows, capacity)
            merged = self._merge_grand_totals(outs)
            one = jnp.ones((1,), dtype=jnp.int32)
            return tuple((o.values, _v(o), one) for o in merged)

        pkeys, pbufs, n_groups = agg.groupby_aggregate(
            keys, buf_inputs, nrows, capacity)

        # 3. shuffle partial groups by key hash (the ICI all-to-all)
        pids = hash_partition_ids(pkeys, self.nshards)
        all_cols = list(pkeys) + list(pbufs)
        recv, recv_n = exchange(all_cols, pids, n_groups, self.axis,
                                self.nshards)
        rkeys = recv[:len(pkeys)]
        rbufs = recv[len(pkeys):]

        # 4. final merge + finalize on the receiving shard
        merge_inputs = [(_merge_kind(s.kind), c)
                        for s, c in zip(self._buf_specs, rbufs)]
        fkeys, fbufs, fn_groups = agg.groupby_aggregate(
            rkeys, merge_inputs, recv_n, rkeys[0].values.shape[0])
        results = [f.finalize(fbufs[sl])
                   for f, sl in zip(self.funcs, self._buf_slices)]
        outs = list(fkeys) + list(results)
        n_out = jnp.reshape(fn_groups, (1,))
        return tuple((o.values, _v(o), n_out) for o in outs)

    def _merge_grand_totals(self, outs: List[ColVal]) -> List[ColVal]:
        """psum/pmin/pmax the single-row locals across the mesh."""
        merged = []
        for spec_idx, (spec, o) in enumerate(zip(self._buf_specs, outs)):
            kind = _merge_kind(spec.kind)
            v = o.values
            valid = o.validity if o.validity is not None else \
                jnp.ones_like(v, dtype=jnp.bool_)
            if kind == "sum":
                mv = jax.lax.psum(jnp.where(valid, v, 0), self.axis)
            elif kind == "min":
                mv = jax.lax.pmin(
                    jnp.where(valid, v, agg._sentinel("min", v.dtype)),
                    self.axis)
            elif kind == "max":
                mv = jax.lax.pmax(
                    jnp.where(valid, v, agg._sentinel("max", v.dtype)),
                    self.axis)
            else:
                mv = v  # first/last over shards: keep local
            any_valid = jax.lax.pmax(valid.astype(jnp.int8), self.axis) > 0
            merged.append(ColVal(o.dtype, mv, any_valid))
        # finalize per function
        results = [f.finalize(merged[sl])
                   for f, sl in zip(self.funcs, self._buf_slices)]
        return results

    # ---- host API ------------------------------------------------------------
    def __call__(self, flat_cols, nrows_per_shard):
        """flat_cols: [(values, validity, offsets)] with leading dim
        nshards*capacity; nrows_per_shard: int32[nshards]."""
        return self._jitted(flat_cols, nrows_per_shard)


def _merge_kind(update_kind: str) -> str:
    return {"sum": "sum", "count": "sum", "min": "min", "max": "max",
            "first": "first", "last": "last"}[update_kind]


def _v(o: ColVal):
    if o.validity is None:
        return jnp.ones_like(o.values, dtype=jnp.bool_)
    return o.validity


class DistributedHashJoin:
    """Equi-join over the mesh, two strategies (reference analogs:
    GpuBroadcastHashJoinExec and GpuShuffledHashJoinExec, SURVEY.md
    section 2.4 "Joins"):

    - ``broadcast``: the (small) build side is all-gathered to every shard
      over ICI — one collective replaces the reference's driver-hosted
      broadcast round trip — and each shard joins its probe slice locally.
    - ``shuffle``: both sides are hash-partitioned by join key with the
      padded ragged all-to-all, co-locating equal keys on one shard, then
      joined locally.

    Probe (left) columns stream sharded on the leading axis; the join runs
    inside ONE shard_map'd XLA program.  Output stays sharded with a
    per-shard row count; ``out_factor`` sizes the static output capacity
    (per-shard output rows <= probe_capacity * out_factor — exceeding it
    drops rows, so callers size it like the reference sizes its join
    output batches via JoinGatherer).  Fixed-width keys/payloads only
    (strings are dictionary-encoded upstream, as for the aggregate).
    """

    def __init__(self, mesh: Mesh,
                 probe_dtypes: Sequence[DataType],
                 build_dtypes: Sequence[DataType],
                 probe_key_idx: Sequence[int],
                 build_key_idx: Sequence[int],
                 join_type: str = "inner",
                 strategy: str = "broadcast",
                 out_factor: int = 1):
        from spark_rapids_tpu.ops.jit_cache import cached_jit
        if join_type not in ("inner", "left"):
            raise ValueError("distributed join supports inner/left")
        if strategy not in ("broadcast", "shuffle"):
            raise ValueError(f"unknown strategy {strategy}")
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.nshards = mesh.devices.size
        self.probe_dtypes = list(probe_dtypes)
        self.build_dtypes = list(build_dtypes)
        self.probe_key_idx = list(probe_key_idx)
        self.build_key_idx = list(build_key_idx)
        self.join_type = join_type
        self.strategy = strategy
        self.out_factor = out_factor
        sig = ("dist_join", tuple(mesh.axis_names),
               tuple(mesh.devices.shape),
               tuple(str(d) for d in mesh.devices.flat),
               tuple(dt.name for dt in self.probe_dtypes),
               tuple(dt.name for dt in self.build_dtypes),
               tuple(self.probe_key_idx), tuple(self.build_key_idx),
               join_type, strategy, out_factor)
        self._jitted = cached_jit(
            sig, lambda: jax.shard_map(
                self._step, mesh=mesh,
                in_specs=(P(self.axis), P(self.axis),
                          P(self.axis), P(self.axis)),
                out_specs=P(self.axis), check_vma=False))

    def _step(self, probe_flat, probe_nrows_arr, build_flat,
              build_nrows_arr):
        from spark_rapids_tpu.ops import joins as J
        from spark_rapids_tpu.parallel.shuffle import all_gather_cols

        pn = probe_nrows_arr[0]
        bn = build_nrows_arr[0]
        probe = [ColVal(dt, v, val)
                 for (v, val), dt in zip(probe_flat, self.probe_dtypes)]
        build = [ColVal(dt, v, val)
                 for (v, val), dt in zip(build_flat, self.build_dtypes)]

        if self.strategy == "broadcast":
            build, bn = all_gather_cols(build, bn, self.axis, self.nshards)
        else:
            pkeys = [probe[i] for i in self.probe_key_idx]
            bkeys = [build[i] for i in self.build_key_idx]
            ppids = hash_partition_ids(pkeys, self.nshards)
            bpids = hash_partition_ids(bkeys, self.nshards)
            probe, pn = exchange(probe, ppids, pn, self.axis, self.nshards)
            build, bn = exchange(build, bpids, bn, self.axis, self.nshards)

        pkeys = [probe[i] for i in self.probe_key_idx]
        bkeys = [build[i] for i in self.build_key_idx]
        m = J.join_match(bkeys, pkeys, jnp.int32(bn), jnp.int32(pn))
        outer = self.join_type == "left"
        count, starts, ends, total = J.join_out_starts(
            m["probe_count"], jnp.int32(pn), outer)
        out_cap = probe[0].values.shape[0] * self.out_factor
        p, brow, matched, _ = J.join_gather_indices(
            starts, ends, m["probe_count"], m["probe_bstart"],
            m["sorted_to_build"], total, out_cap)
        n_out = jnp.minimum(total, out_cap).astype(jnp.int32)
        probe_out = selection.gather(probe, p, n_out)
        build_out = J.gather_build_side(build, brow, matched, n_out)
        flat = [(c.values,
                 c.validity if c.validity is not None
                 else jnp.ones(out_cap, dtype=jnp.bool_))
                for c in probe_out + build_out]
        # also expose the UNclamped total: when total > n_out the output was
        # truncated to out_cap and the caller must retry with a larger
        # out_factor (the reference instead splits join output batches,
        # JoinGatherer.scala:36-60 — silent truncation = wrong results)
        return flat, n_out[None], total.astype(jnp.int32)[None]

    def __call__(self, probe_flat, probe_nrows_per_shard, build_flat,
                 build_nrows_per_shard):
        """probe_flat/build_flat: [(values, validity)] with leading-axis
        sharded arrays; nrows arrays have one entry per shard.  Returns
        (flat output cols [probe cols then build cols], nrows per shard,
        unclamped match total per shard).  Any shard where total > nrows
        was truncated at out_factor * capacity rows: the caller must
        retry with a larger out_factor."""
        return self._jitted(probe_flat, probe_nrows_per_shard,
                            build_flat, build_nrows_per_shard)
