"""Distributed query steps: SPMD pipelines over the device mesh.

The reference executes a distributed aggregation as: partial agg per task ->
hash-partitioned UCX shuffle -> final agg per reducer (SURVEY.md sections 3.3
and 3.4).  Here the sequence — filter, partial aggregate, shuffle by
key, final aggregate — runs as compiled ``shard_map`` programs with the
shuffle as an all-to-all riding ICI.  Keyed aggregates and shuffle joins
are ADAPTIVE, in two compiled phases: phase 1 materializes per-destination
histograms (the stage statistics, like the reference's AQE reading map
output sizes), the host sizes the all-to-all slots from the true max
slice, and phase 2 exchanges with those static slots.  The phase boundary
is a blocking host sync, so ``__call__`` is NOT traceable under an outer
jit.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops import aggregates as agg
from spark_rapids_tpu.ops import selection
from spark_rapids_tpu.ops.expressions import ColVal, EmitContext, Expression
from spark_rapids_tpu.parallel.mesh import shard_map as _shard_map
from spark_rapids_tpu.parallel.partitioning import hash_partition_ids
from spark_rapids_tpu.parallel.shuffle import exchange


def host_sync(x):
    """Host copy of sharded stats array(s) for the phase boundary.

    Single-process: a plain device fetch.  Multi-process SPMD (one
    controller per host, the multi-host pod layout): every process
    holds only its addressable shards, so the stats all-gather across
    processes — each controller then makes the IDENTICAL slot/LUT
    decision, which the SPMD contract requires.  Accepts a pytree so
    co-located stats pay ONE cross-host collective."""
    from spark_rapids_tpu.robustness import watchdog
    # every phase boundary is a membership checkpoint: beat our own
    # record and judge the peers, so a silent host surfaces as a typed
    # HostLossFault (-> shrink rung) at the first point that would
    # otherwise wait on it forever
    _membership_check()
    # deadline on the phase boundary: a dead peer that never answers
    # the stats all-gather becomes a TimeoutFault instead of an
    # eternal wait (the transport-heartbeat analog).  The observed wall
    # also feeds the gray-failure health score's dist.host_sync axis —
    # host_sync is a COLLECTIVE, so it is evidence only, never hedged
    # (re-entering a fleet rendezvous concurrently would wedge SPMD).
    import time as _time
    t0 = _time.monotonic()
    try:
        with watchdog.section("dist.host_sync"):
            return _host_sync_body(x)
    finally:
        try:
            from spark_rapids_tpu.api.session import TpuSession
            from spark_rapids_tpu.robustness import grayfailure
            grayfailure.note_wall(
                TpuSession._active, "dist.host_sync",
                (_time.monotonic() - t0) * 1e3)
        except ImportError:
            pass


def _membership_check() -> None:
    try:
        from spark_rapids_tpu.api.session import TpuSession
        session = TpuSession._active
    except ImportError:  # torn-down interpreter only
        return
    membership = getattr(session, "fleet_membership", None)
    if membership is not None:
        membership.check()  # raises HostLossFault on a newly-lost peer


def _host_sync_body(x):
    import numpy as np
    from spark_rapids_tpu.robustness.faults import HostSyncError
    from spark_rapids_tpu.robustness.inject import fire
    from spark_rapids_tpu.utils.hostsync import count_sync
    fire("dist.host_sync")
    # the phase boundary is a device->host round trip like any other:
    # count it so per-site sync budgets (the adaptive slot planner's
    # "<= 1 hostsync per exchange site") are assertable via the same
    # host_sync_count attribution as the pipeline's deferred syncs
    count_sync()
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        try:
            return jax.tree_util.tree_map(
                np.asarray,
                multihost_utils.process_allgather(x, tiled=True))
        except (RuntimeError, OSError) as e:
            # a dead/slow peer surfaces as a DEADLINE_EXCEEDED /
            # UNAVAILABLE XlaRuntimeError (a RuntimeError) or a socket
            # error; type it so the query driver knows the phase
            # boundary (not the query) failed.  Only transport-shaped
            # errors are re-typed: an error the taxonomy already names
            # — device OOM (enters the ladder at the spill rung) or a
            # marker-less XlaRuntimeError (a real bug, FATAL) — must
            # keep its own classification, never become retryable
            from spark_rapids_tpu.robustness.faults import classify
            if classify(e).kind in ("preemption", "unknown"):
                raise HostSyncError(
                    f"multi-host stats all-gather failed: {e}") from e
            raise
    return jax.tree_util.tree_map(np.asarray, x)


class DistributedAggregate:
    """filter? -> partial group-by -> all-to-all by key hash -> final agg.

    Inputs are leading-axis sharded arrays: each of the mesh's N shards holds
    a [capacity] slice of every column plus its own row count.  Outputs stay
    sharded — each shard owns the key range that hashed to it (the reducer
    layout); a collect all-gathers afterwards if needed.
    """

    def __init__(self, mesh: Mesh, in_dtypes: Sequence[DataType],
                 group_exprs: Sequence[Expression],
                 funcs: Sequence[agg.AggregateFunction],
                 filter_cond: Optional[Expression] = None,
                 encoded_keys=None, encoded_funcs=None,
                 cost_model="auto"):
        """``encoded_keys`` / ``encoded_funcs``: dictionaries behind
        group-key positions / function positions whose exchanged
        values are int64 dictionary codes — with
        spark.rapids.tpu.encoding.wire.enabled those columns narrow to
        i32 lanes on the wire (codes + a once-per-site dictionary
        delta broadcast instead of materialized rows)."""
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.nshards = mesh.devices.size
        # AQE partition coalescing (GpuCustomShuffleReaderExec.scala:131
        # role on collective slots): hash into FINER buckets than shards,
        # then greedily pack buckets onto shards from the materialized
        # histogram — small buckets coalesce, hot buckets spread apart,
        # shrinking the all-to-all slot (= padding bandwidth)
        self.buckets = 4 * self.nshards
        self.in_dtypes = list(in_dtypes)
        self.group_exprs = list(group_exprs)
        self.funcs = list(funcs)
        # fused upstream predicates, BOTTOM-FIRST chain order (whole-
        # stage fusion, exec/fusion.py): evaluated with progressive
        # ANSI-check masking before the in-trace compaction
        self.filter_conds = list(filter_cond) if isinstance(
            filter_cond, (list, tuple)) else (
            [filter_cond] if filter_cond is not None else [])

        self._buf_specs = []
        self._buf_slices = []
        for f in self.funcs:
            specs = f.buffers()
            self._buf_slices.append(
                slice(len(self._buf_specs), len(self._buf_specs) + len(specs)))
            self._buf_specs.extend(specs)

        from spark_rapids_tpu.ops.jit_cache import cached_jit
        from spark_rapids_tpu.parallel.shuffle import (
            packed_enabled, ragged_enabled, topology_strategy,
            wire_encoding_enabled, wire_fusion_enabled)
        self._cached_jit = cached_jit
        # resolved at construction and baked into the jit signature: a
        # packed.enabled flip must retrace, never hit a stale cache
        self.packed = packed_enabled()
        # wire-fused stages (fusion.wire.enabled): warm speculative
        # launches run partial agg + lane packing + exchange + final
        # merge as ONE program per shard.  NOT part of self._sig —
        # stage ids / report sites stay byte-identical fused or not;
        # the fused program's own jit key carries the component.
        self.wire_fused = wire_fusion_enabled()
        # topology-aware collective selection (parallel/mesh.py): ICI
        # axes keep the padded all_to_all, DCN-spanning axes lower the
        # exchange to gather-then-redistribute
        self.exchange_strategy = topology_strategy(mesh)
        self.ragged, self.ragged_min_savings = ragged_enabled()
        # compressed wire: exchange-column index -> dictionary for
        # every code-valued column in the exchanged payload (group
        # keys + single-buffer min/max/first/last partials)
        nkeys = len(self.group_exprs)
        self._encoded_cols = {int(i): d
                              for i, d in (encoded_keys or {}).items()}
        for j, d in (encoded_funcs or {}).items():
            self._encoded_cols[nkeys + self._buf_slices[j].start] = d
        self.wire_encoding = wire_encoding_enabled() and \
            bool(self._encoded_cols)
        self._wire_encode = tuple(sorted(self._encoded_cols)) \
            if self.wire_encoding else ()
        self._sig = ("dist_agg", tuple(self.mesh.axis_names),
                     tuple(self.mesh.devices.shape),
                     tuple(str(d) for d in self.mesh.devices.flat),
                     tuple(dt.name for dt in self.in_dtypes),
                     tuple(e.cache_key() for e in self.group_exprs),
                     tuple(f.cache_key() for f in self.funcs),
                     tuple(c.cache_key() for c in self.filter_conds)
                     if self.filter_conds else None,
                     ("packed", self.packed),
                     ("exch", self.exchange_strategy),
                     ("wenc", self.wire_encoding))
        # self-tuning planner (plan/costmodel.py): ONE evidence-fed
        # decision for this site's exchange strategy — uniform vs
        # ragged vs gather vs host-staged — replacing the independent
        # ragged/staging confs (which stay as overrides when
        # explicitly set).  A "ragged" plan makes the stats histogram
        # mandatory (the site never launches speculatively); the
        # staging threshold comes budget-derived instead of hand-set.
        from spark_rapids_tpu.plan.costmodel import \
            resolve_consumer_exchange
        resolve_consumer_exchange(self, "aggregate", model=cost_model)
        # keyless grand totals never exchange rows: single fused program
        self._jitted_keyless = cached_jit(
            self._sig + ("keyless",), lambda: _shard_map(
                self._step_keyless, mesh=mesh,
                in_specs=(P(self.axis), P(self.axis)),
                out_specs=P(self.axis), check_vma=False))
        self._jitted_local = cached_jit(
            self._sig + ("local",), lambda: _shard_map(
                self._step_local, mesh=mesh,
                in_specs=(P(self.axis), P(self.axis)),
                out_specs=P(self.axis), check_vma=False))
        self.last_stats: Optional[dict] = None

    # ---- SPMD bodies (run per shard) ----------------------------------------
    def _local_partials(self, flat_cols, nrows_arr):
        """filter + local partial aggregate (shared by both bodies)."""
        nrows = nrows_arr[0]
        capacity = None
        for v, _, _ in flat_cols:
            capacity = v.shape[0]
            break
        inputs = [ColVal(dt, v, val, offs)
                  for (v, val, offs), dt in zip(flat_cols, self.in_dtypes)]
        ctx = EmitContext(inputs, nrows, capacity)

        if self.filter_conds:
            from spark_rapids_tpu.ops.expressions import fold_conjuncts
            keep = fold_conjuncts(ctx, self.filter_conds)
            compacted, nrows = selection.compact(inputs, keep)
            ctx = EmitContext(compacted, nrows, capacity)

        keys = [e.emit(ctx) for e in self.group_exprs]
        buf_inputs = []
        for f in self.funcs:
            c = f.child.emit(ctx) if f.child is not None else None
            if c is not None and getattr(c.values, "ndim", 0) == 0:
                c = ColVal(c.dtype,
                           jnp.broadcast_to(c.values, (capacity,)), c.validity)
            for spec, cv in zip(f.buffers(), f.update_inputs(c, capacity)):
                buf_inputs.append((spec.kind, cv))
        return keys, buf_inputs, ctx, nrows, capacity

    def _step_keyless(self, flat_cols, nrows_arr):
        _, buf_inputs, _, nrows, capacity = self._local_partials(
            flat_cols, nrows_arr)
        # grand total: local reduce then a psum-style merge
        outs = agg.reduce_aggregate(buf_inputs, nrows, capacity)
        merged = self._merge_grand_totals(outs)
        one = jnp.ones((1,), dtype=jnp.int32)
        return tuple((o.values, _v(o), one) for o in merged)

    def _step_local(self, flat_cols, nrows_arr):
        """Phase 1: partial aggregate + per-destination histogram.  The
        histogram is this stage's materialized statistics — the analog of
        the reference's AQE reading map-output sizes before re-planning
        the exchange (GpuCustomShuffleReaderExec intent)."""
        from spark_rapids_tpu.ops.pallas_kernels import histogram
        keys, buf_inputs, _, nrows, capacity = self._local_partials(
            flat_cols, nrows_arr)
        pkeys, pbufs, n_groups = agg.groupby_aggregate(
            keys, buf_inputs, nrows, capacity)
        bids = hash_partition_ids(pkeys, self.buckets)
        live = jnp.arange(capacity, dtype=jnp.int32) < n_groups
        hist = histogram(bids, live, self.buckets)
        outs = list(pkeys) + list(pbufs)
        # validity stays None for non-nullable columns so phase 2's
        # exchange skips the per-column validity all_to_all entirely
        return (tuple((o.values, o.validity) for o in outs),
                jnp.reshape(n_groups, (1,)), hist)

    def _step_final(self, slot, ragged, wenc, lut, partial_flat,
                    n_groups_arr):
        """Phase 2: exchange partials with the stats-sized slot (bucket
        -> shard assignment rides in as the traced ``lut``), then the
        final merge + finalize on the receiving shard.  ``ragged`` (a
        static RaggedPlan, part of the jit key) routes hot-slice
        surplus over collective-permutes; the "gather" exchange
        strategy replaces the all_to_all with gather-then-redistribute
        on DCN-spanning axes.  The trailing output leaf is the
        per-shard slot-overflow flag — nonzero when a speculative
        (EMA-predicted) slot was too small and the launch must be
        re-run (rows would otherwise be dropped)."""
        from spark_rapids_tpu.parallel.shuffle import exchange_via_gather
        n_groups = n_groups_arr[0]
        nkeys = len(self.group_exprs)
        dtypes = [e.dtype for e in self.group_exprs] + \
            [s.dtype for s in self._buf_specs]
        cols = [ColVal(dt, v, val)
                for dt, (v, val) in zip(dtypes, partial_flat)]
        pkeys, pbufs = cols[:nkeys], cols[nkeys:]
        pids = lut[hash_partition_ids(pkeys, self.buckets)]
        if self.exchange_strategy == "gather":
            recv, recv_n, overflow = exchange_via_gather(
                list(pkeys) + list(pbufs), pids, n_groups, self.axis,
                self.nshards, packed=self.packed, with_overflow=True,
                report_site=self._sig + ("final", wenc),
                wire_encode=wenc)
        else:
            recv, recv_n, overflow = exchange(
                list(pkeys) + list(pbufs), pids, n_groups, self.axis,
                self.nshards, slot=slot, packed=self.packed,
                with_overflow=True,
                report_site=self._sig + ("final", wenc),
                ragged=ragged, wire_encode=wenc)
        return self._merge_finalize(recv[:nkeys], recv[nkeys:],
                                    recv_n, overflow)

    def _step_fused(self, slot, wenc, lut, flat_cols, nrows_arr):
        """The wire-fused stage: scan-mask/filter, partial aggregate,
        lane packing + counts, the all_to_all and the final merge as
        ONE program per shard — the packed wire payload is built by
        shuffle.pack_for_wire inside the exchange's send side with no
        dispatch boundary anywhere in the chain.  Math is the exact
        composition of ``_step_local`` and ``_step_final`` (minus the
        histogram the warm path never reads), so outputs are
        bit-identical to the two-dispatch sequence."""
        keys, buf_inputs, _, nrows, capacity = self._local_partials(
            flat_cols, nrows_arr)
        pkeys, pbufs, n_groups = agg.groupby_aggregate(
            keys, buf_inputs, nrows, capacity)
        outs = list(pkeys) + list(pbufs)
        partial_flat = tuple((o.values, o.validity) for o in outs)
        return self._step_final(slot, None, wenc, lut, partial_flat,
                                jnp.reshape(n_groups, (1,)))

    def _step_final_local(self, partial_flat, n_rows_arr):
        """Final merge over ALREADY co-located partials (the host-RAM
        staging path repartitioned them off-device): no exchange, one
        merge + finalize program per shard."""
        n_rows = n_rows_arr[0]
        nkeys = len(self.group_exprs)
        dtypes = [e.dtype for e in self.group_exprs] + \
            [s.dtype for s in self._buf_specs]
        cols = [ColVal(dt, v, val)
                for dt, (v, val) in zip(dtypes, partial_flat)]
        return self._merge_finalize(cols[:nkeys], cols[nkeys:], n_rows,
                                    jnp.zeros((), dtype=jnp.bool_))

    def _merge_finalize(self, rkeys, rbufs, recv_n, overflow):
        merge_inputs = [(_merge_kind(s.kind), c)
                        for s, c in zip(self._buf_specs, rbufs)]
        fkeys, fbufs, fn_groups = agg.groupby_aggregate(
            rkeys, merge_inputs, recv_n, rkeys[0].values.shape[0])
        results = [f.finalize(fbufs[sl])
                   for f, sl in zip(self.funcs, self._buf_slices)]
        outs = list(fkeys) + list(results)
        n_out = jnp.reshape(fn_groups, (1,))
        return tuple((o.values, _v(o), n_out) for o in outs) + \
            (jnp.reshape(overflow.astype(jnp.int32), (1,)),)

    def _merge_grand_totals(self, outs: List[ColVal]) -> List[ColVal]:
        """psum/pmin/pmax the single-row locals across the mesh."""
        merged = []
        for spec_idx, (spec, o) in enumerate(zip(self._buf_specs, outs)):
            kind = _merge_kind(spec.kind)
            v = o.values
            valid = o.validity if o.validity is not None else \
                jnp.ones_like(v, dtype=jnp.bool_)
            if kind == "sum":
                mv = jax.lax.psum(jnp.where(valid, v, 0), self.axis)
            elif kind == "min":
                mv = jax.lax.pmin(
                    jnp.where(valid, v, agg._sentinel("min", v.dtype)),
                    self.axis)
            elif kind == "max":
                mv = jax.lax.pmax(
                    jnp.where(valid, v, agg._sentinel("max", v.dtype)),
                    self.axis)
            elif kind in ("first", "last"):
                # first/last over shards: the winner is the lowest/
                # highest shard index holding a VALID (present) partial
                # — a dead shard (all rows filtered out locally) must
                # never surface its garbage local value (the keyless
                # flavor of the dead-partial bug; shard order is global
                # row order because shards are contiguous leading-axis
                # chunks)
                idx = jax.lax.axis_index(self.axis)
                if kind == "first":
                    rank = jnp.where(valid, idx, self.nshards)
                    best = jax.lax.pmin(rank, self.axis)
                else:
                    rank = jnp.where(valid, idx, -1)
                    best = jax.lax.pmax(rank, self.axis)
                pick = jnp.logical_and(valid, rank == best)
                vz = v.astype(jnp.int8) if v.dtype == jnp.bool_ else v
                mv = jax.lax.psum(
                    jnp.where(pick, vz, jnp.zeros((), dtype=vz.dtype)),
                    self.axis)
                if v.dtype == jnp.bool_:
                    mv = mv != 0
            else:
                raise ValueError(f"unknown grand-total merge kind {kind}")
            any_valid = jax.lax.pmax(valid.astype(jnp.int8), self.axis) > 0
            merged.append(ColVal(o.dtype, mv, any_valid))
        # finalize per function
        results = [f.finalize(merged[sl])
                   for f, sl in zip(self.funcs, self._buf_slices)]
        return results

    # ---- host API ------------------------------------------------------------
    def _final_jitted(self, slot: int, ragged=None, wenc=()):
        rkey = ragged.cache_key() if ragged is not None else None
        return self._cached_jit(
            self._sig + ("final", slot, rkey, wenc), lambda: _shard_map(
                partial(self._step_final, slot, ragged, wenc),
                mesh=self.mesh,
                in_specs=(P(), P(self.axis), P(self.axis)),
                out_specs=P(self.axis), check_vma=False))

    def _fused_jitted(self, slot: int, wenc=()):
        return self._cached_jit(
            self._sig + ("wire_fused", slot, wenc), lambda: _shard_map(
                partial(self._step_fused, slot, wenc), mesh=self.mesh,
                in_specs=(P(), P(self.axis), P(self.axis)),
                out_specs=P(self.axis), check_vma=False))

    def _final_local_jitted(self):
        return self._cached_jit(
            self._sig + ("final_local",), lambda: _shard_map(
                self._step_final_local, mesh=self.mesh,
                in_specs=(P(self.axis), P(self.axis)),
                out_specs=P(self.axis), check_vma=False))

    def _wire_dtypes(self):
        return [e.dtype for e in self.group_exprs] + \
            [s.dtype for s in self._buf_specs]

    def __call__(self, flat_cols, nrows_per_shard, window=None):
        """flat_cols: [(values, validity, offsets)] with leading dim
        nshards*capacity; nrows_per_shard: int32[nshards].

        Adaptive in two compiled phases: the local phase materializes
        the per-destination histogram; the host sizes the all-to-all
        slot through the session's SlotPlanner (power-of-two bucketed
        from the TRUE max slice, EMA-smoothed so the jit-cache key is
        sticky) and the exchange phase runs with that static slot.
        Warm adaptive sites skip the stats hostsync entirely and launch
        speculatively with the cached slot + bucket LUT, verifying a
        per-shard overflow flag afterwards — an overflow re-runs the
        launch at full capacity (rows are never dropped) and records a
        degradable recovery action.  Either way the exchange site pays
        at most ONE budgeted hostsync per launch.

        Three PR-9 refinements ride the stats-sized path: a skewed
        histogram lowers to a RaggedPlan (hot-slice surplus over
        collective-permutes); a payload past the host-staging threshold
        repartitions through host RAM + the frame codec instead of the
        device collective; and when ``window`` (an ExchangeWindow) is
        passed, the launch's host-side tail is deferred into an
        AsyncExchangeHandle the window owns — resolved at the next
        stage boundary (checkpoint save, collect, window pressure), so
        downstream compute dispatches while the collective is in
        flight."""
        import numpy as np
        from spark_rapids_tpu.parallel.exchange_async import (
            overlap_metrics_for_session)
        from spark_rapids_tpu.parallel.shuffle import (
            broadcast_wire_dicts, launch_checkpoint,
            metrics_for_session, plan_ragged, planner_for_session,
            record_exchange_metrics, wire_row_bytes)
        if not self.group_exprs:
            self.last_stats = {"keyless": True}
            return self._jitted_keyless(flat_cols, nrows_per_shard)
        # partial capacity equals input capacity (groupby preserves it),
        # so the warm-path decision needs no dispatch: a wire-fused
        # launch replaces local+final with ONE program per shard
        capacity = int(flat_cols[0][0].shape[0]) // self.nshards
        planner = planner_for_session()
        metrics = metrics_for_session()
        site = self._sig

        # compressed wire: the dictionary-DELTA broadcast runs only
        # when a DEVICE-collective launch is imminent (the join-path
        # rule — a host-staged launch ships nothing on the wire, so it
        # must not mark deltas sent or account wireDictBytes); a
        # corrupt delta degrades this launch to the wide wire (typed
        # EncodedWireInvalid, full rebroadcast next launch), and an
        # encodable payload shipping decoded counts the health signal
        wenc = ()

        def resolve_wire() -> None:
            nonlocal wenc
            if not self._encoded_cols:
                return
            if not self._wire_encode:
                metrics.record_encodable_decoded()
                return
            dicts = [self._encoded_cols[i] for i in self._wire_encode]
            if broadcast_wire_dicts(site + ("dict",), dicts, metrics):
                wenc = self._wire_encode

        from spark_rapids_tpu.plan.costmodel import (
            consumer_staging_threshold)
        # model-derived when the conf is unset (payloads past a
        # fraction of the device budget stage through host RAM), else
        # the conf helper's semantics
        thr = 0 if self.exchange_strategy == "gather" \
            else consumer_staging_threshold(self)
        # sizing uses the INTENDED wire; a corrupt-delta wide fallback
        # only makes the estimate conservative-side wrong for one launch
        row_bytes = max(
            wire_row_bytes(self._wire_dtypes())
            - 4 * len(self._wire_encode), 1)
        # a model-planned RAGGED site never launches speculatively:
        # plan_ragged needs the materialized histogram every launch
        spec = None if self._planned_mode == "ragged" \
            else planner.speculative(site, capacity)
        if spec is not None and thr and \
                self.nshards * self.nshards * spec["slot"] * row_bytes \
                > thr:
            # a payload past the staging threshold must NEVER ride the
            # device collective — a warm site's cached slot proves the
            # estimate, so fall through to the stats path, which stages
            spec = None
        warm = spec is not None and "lut" in spec and \
            len(spec["lut"]) == self.buckets
        if warm and self.wire_fused:
            resolve_wire()
            outs = self._launch_fused(site, spec, flat_cols,
                                      nrows_per_shard, capacity,
                                      planner, metrics, window=window,
                                      wenc=wenc)
            if outs is not None:
                self.last_stats["wire"] = metrics.snapshot()
                return outs
            # fused slot overflow: degrade to the current two-dispatch
            # stats-sized path below (rows are never dropped)
            warm = False
        partial_flat, n_groups, hist = self._jitted_local(
            flat_cols, nrows_per_shard)
        metrics.record_fused_dispatch(False)
        if self.wire_fused:
            # conf ON but this launch ran unfused (cold site, staged,
            # ragged-planned, or a fused overflow degrade): the
            # "fusible chain ran unfused" health-check breadcrumb
            from spark_rapids_tpu.exec.fusion import fusion_metrics
            fusion_metrics.bump("wireUnfusedLaunches")
        if warm:
            resolve_wire()
            outs = self._launch_speculative(site, spec, partial_flat,
                                            n_groups, capacity, planner,
                                            metrics, window=window,
                                            wenc=wenc)
        else:
            counts = host_sync(hist).reshape(self.nshards, self.buckets)
            lut, dst_counts = coalesce_buckets(counts, self.nshards)
            max_slice = int(dst_counts.max())
            rows = int(dst_counts.sum())
            slot = planner.plan(site, max_slice, capacity)
            est_bytes = self.nshards * self.nshards * slot * row_bytes
            if self._cost_model is not None:
                # launch-time evidence feed: what the next plan-time
                # decision (and a warm start's) reads
                self._cost_model.note_exchange(
                    site, rows=rows, max_slice=max_slice,
                    useful_bytes=rows * row_bytes)
            if thr and est_bytes > thr:
                outs = self._launch_staged(partial_flat, lut,
                                           dst_counts, metrics)
                if self._cost_model is not None:
                    self._cost_model.observe_staged(
                        site, self.last_stats.get("stagedBytes", 0))
                return outs
            resolve_wire()
            ragged = None
            if self.ragged and self.exchange_strategy != "gather":
                ragged = plan_ragged(dst_counts, capacity,
                                     self.ragged_min_savings)
            planner.observe(site, max_slice, slot, capacity, lut=lut,
                            rows=rows)
            self.last_stats = {
                "bucket_counts": counts,     # [src_shard, bucket]
                "bucket_map": lut,           # bucket -> dst shard
                "partition_counts": dst_counts,  # [src, dst_shard]
                "slot": slot,
                "capacity": capacity,
                "packed": self.packed,
            }
            if ragged is not None:
                self.last_stats["ragged"] = repr(ragged)
            with launch_checkpoint():
                raw = self._final_jitted(slot, ragged, wenc)(
                    jnp.asarray(lut), partial_flat, n_groups)
            outs = raw[:-1]  # drop the overflow flag (slot >= max_slice)
            record_exchange_metrics(
                metrics, dtypes=self._wire_dtypes(),
                # the gather strategy moves full-capacity buffers (slot
                # planning does not apply to an all_gather)
                slot=capacity if self.exchange_strategy == "gather"
                else slot,
                num_parts=self.nshards, nshards=self.nshards,
                rows_useful=rows, packed=self.packed,
                site=self._sig + ("final", wenc), ragged=ragged,
                counts=dst_counts, wire_encode_cols=len(wenc))
            if self._cost_model is not None:
                # fold the observed wire cost onto the ledger decision,
                # then check the launch against the plan: a uniform
                # launch whose measured histogram says ragged would
                # have won past the hysteresis band re-drives through
                # the ladder (ReplanRequested) with the evidence above
                # already folded — completed stages splice, only this
                # subtree re-plans
                self._cost_model.observe_outcome(
                    "exchange", site,
                    float(metrics.last_exchange_bytes))
                if ragged is None and self.exchange_strategy != "gather":
                    self._cost_model.check_contradiction(
                        site, "aggregate", counts=dst_counts,
                        capacity=capacity, nshards=self.nshards,
                        slot=slot)
            if window is not None:
                # stats-sized slots are proven (slot >= true max / the
                # ragged limits cover every pair): no verification to
                # defer, the handle only tracks in-flight overlap
                window.admit(site + ("final",),
                             metrics.last_exchange_bytes)
            else:
                overlap_metrics_for_session().record_sync()
        self.last_stats["wire"] = metrics.snapshot()
        return outs

    def _launch_staged(self, partial_flat, lut, dst_counts, metrics):
        """Host-RAM staging: the exchange payload exceeded the staging
        threshold, so partials repartition through host memory (frame-
        codec round trip — compressed, pinned-host analog) and the
        final merge runs a no-exchange program over the co-located
        rows.  The oversized shuffle lands in host RAM instead of
        marching into the recovery ladder's split rung.  The stats
        branch already paid this launch's ONE counted hostsync (the
        histogram): per-shard live group counts derive from
        ``dst_counts`` — no second sync."""
        from spark_rapids_tpu.parallel.exchange_async import (
            stage_host_side)
        from spark_rapids_tpu.parallel.shuffle import launch_checkpoint
        nkeys = len(self.group_exprs)
        staged, dest_counts, staged_bytes = stage_host_side(
            partial_flat, dst_counts, range(nkeys), self.buckets,
            self.nshards, lut=lut)
        rows = int(dest_counts.sum())
        # staged rows move dense (no padding) — account them so the
        # wire trail shows the exchange happened, in compressed bytes
        metrics.record_exchange(
            collectives=0, rows_moved=rows, rows_useful=rows,
            bytes_moved=staged_bytes, packed=self.packed)
        flat = tuple((jnp.asarray(v), jnp.asarray(m))
                     for v, m in staged)
        with launch_checkpoint():
            raw = self._final_local_jitted()(
                flat, jnp.asarray(dest_counts))
        self.last_stats = {"staged": True, "stagedBytes": staged_bytes,
                           "partition_counts": dst_counts,
                           "packed": self.packed,
                           "wire": metrics.snapshot()}
        return raw[:-1]

    def _launch_fused(self, site, spec, flat_cols, nrows_per_shard,
                      capacity, planner, metrics, window=None, wenc=()):
        """Warm-path launch with the wire payload emitted inside the
        compute program: ONE dispatch per shard covers scan/filter,
        partial aggregate, lane packing, the all_to_all and the final
        merge.  Slot overflow returns None — the caller degrades to
        the current two-dispatch stats-sized path (rows are never
        dropped) after the same planner latch + recovery-trail entry
        the unfused speculative launch records."""
        import numpy as np
        from spark_rapids_tpu.exec.fusion import fusion_metrics
        from spark_rapids_tpu.parallel.exchange_async import (
            overlap_metrics_for_session)
        from spark_rapids_tpu.parallel.shuffle import (
            launch_checkpoint, record_exchange_metrics)
        slot, lut = spec["slot"], spec["lut"]
        self.last_stats = {"slot": slot, "capacity": capacity,
                           "speculative": True, "packed": self.packed,
                           "wire_fused": True}
        with launch_checkpoint():
            raw = self._fused_jitted(slot, wenc=wenc)(
                jnp.asarray(lut), flat_cols, nrows_per_shard)
        outs, ovf = raw[:-1], raw[-1]
        fusion_metrics.bump("fusedWireStages")
        metrics.record_fused_dispatch(True)
        record_exchange_metrics(
            metrics, dtypes=self._wire_dtypes(),
            slot=capacity if self.exchange_strategy == "gather"
            else slot,
            num_parts=self.nshards, nshards=self.nshards,
            rows_useful=spec.get("rows", 0), packed=self.packed,
            site=self._sig + ("final", wenc),
            wire_encode_cols=len(wenc))
        if window is not None:
            overlap = overlap_metrics_for_session()

            def verify():
                if not bool(np.asarray(host_sync(ovf)).any()):
                    return
                planner.observe_overflow(site)
                metrics.record_overflow()
                overlap.record_deferred_overflow()
                from spark_rapids_tpu.api.session import TpuSession
                from spark_rapids_tpu.robustness.driver import (
                    record_degradation)
                from spark_rapids_tpu.robustness.faults import (
                    AsyncExchangeOverflow)
                err = AsyncExchangeOverflow("aggregate", slot, capacity)
                record_degradation(TpuSession._active, err.kind,
                                   "shuffle-slot-async-replan", str(err))
                raise err

            window.admit(site + ("final",),
                         metrics.last_exchange_bytes, verify)
            return outs
        overlap_metrics_for_session().record_sync()
        if not bool(np.asarray(host_sync(ovf)).any()):
            return outs
        # slot overflow inside the fused program: latch the site off
        # speculation, record the handled fault, and let the caller
        # re-run the unfused stats-sized sequence
        planner.observe_overflow(site)
        metrics.record_overflow()
        from spark_rapids_tpu.api.session import TpuSession
        from spark_rapids_tpu.robustness.driver import record_degradation
        from spark_rapids_tpu.robustness.faults import ShuffleSlotOverflow
        err = ShuffleSlotOverflow("aggregate", slot, capacity)
        record_degradation(TpuSession._active, err.kind,
                           "shuffle-slot-capacity-rerun", str(err))
        self.last_stats["overflow"] = True
        return None

    def _launch_speculative(self, site, spec, partial_flat, n_groups,
                            capacity, planner, metrics, window=None,
                            wenc=()):
        """Steady-state launch: cached slot + bucket LUT, no stats
        hostsync; the post-launch overflow check is the site's single
        budgeted sync.  Overflow re-runs at full capacity and records a
        degradable recovery action — never dropped rows.  With an async
        ``window`` the overflow check itself defers into a handle the
        window owns: downstream compute dispatches first, and a
        deferred overflow surfaces as a RETRYABLE AsyncExchangeOverflow
        at resolve time (the ladder re-drives; the planner has latched
        the site back onto the stats-sized synchronous path)."""
        import numpy as np
        from spark_rapids_tpu.parallel.exchange_async import (
            overlap_metrics_for_session)
        from spark_rapids_tpu.parallel.shuffle import (
            launch_checkpoint, record_exchange_metrics)
        slot, lut = spec["slot"], spec["lut"]
        self.last_stats = {"slot": slot, "capacity": capacity,
                           "speculative": True, "packed": self.packed}
        with launch_checkpoint():
            raw = self._final_jitted(slot, wenc=wenc)(
                jnp.asarray(lut), partial_flat, n_groups)
        outs, ovf = raw[:-1], raw[-1]
        record_exchange_metrics(
            metrics, dtypes=self._wire_dtypes(),
            slot=capacity if self.exchange_strategy == "gather"
            else slot,
            num_parts=self.nshards, nshards=self.nshards,
            rows_useful=spec.get("rows", 0), packed=self.packed,
            site=self._sig + ("final", wenc),
            wire_encode_cols=len(wenc))
        if window is not None:
            overlap = overlap_metrics_for_session()

            def verify():
                if not bool(np.asarray(host_sync(ovf)).any()):
                    return
                # the truncated frame already fed downstream dispatches:
                # the local capacity re-run cannot help anymore.  Latch
                # the site off speculation and re-drive the attempt.
                planner.observe_overflow(site)
                metrics.record_overflow()
                overlap.record_deferred_overflow()
                from spark_rapids_tpu.api.session import TpuSession
                from spark_rapids_tpu.robustness.driver import (
                    record_degradation)
                from spark_rapids_tpu.robustness.faults import (
                    AsyncExchangeOverflow)
                err = AsyncExchangeOverflow("aggregate", slot, capacity)
                record_degradation(TpuSession._active, err.kind,
                                   "shuffle-slot-async-replan", str(err))
                raise err

            window.admit(site + ("final",),
                         metrics.last_exchange_bytes, verify)
            return outs
        # the overflow check IS this launch's phase boundary: route it
        # through host_sync so (a) multi-process controllers all see
        # the same flags and make the identical rerun decision, (b) a
        # dead peer surfaces here under the dist.host_sync watchdog
        # deadline, and (c) chaos rules armed on the phase boundary
        # keep firing on warm (speculative) sites — at most ONE counted
        # hostsync per exchange site per launch either way
        overlap_metrics_for_session().record_sync()
        if not bool(np.asarray(host_sync(ovf)).any()):
            return outs
        # slot overflow: the EMA prediction was too small for this
        # launch's skew.  Re-run at full capacity (always correct) and
        # surface the event on the recovery trail as a locally-handled
        # degradable fault; the planner grows the site's EMA and forces
        # the next launch back onto the stats-sized path.
        planner.observe_overflow(site)
        metrics.record_overflow()
        from spark_rapids_tpu.api.session import TpuSession
        from spark_rapids_tpu.robustness.driver import record_degradation
        from spark_rapids_tpu.robustness.faults import ShuffleSlotOverflow
        err = ShuffleSlotOverflow("aggregate", slot, capacity)
        record_degradation(TpuSession._active, err.kind,
                           "shuffle-slot-capacity-rerun", str(err))
        self.last_stats["overflow"] = True
        with launch_checkpoint():
            raw = self._final_jitted(capacity, wenc=wenc)(
                jnp.asarray(lut), partial_flat, n_groups)
        record_exchange_metrics(
            metrics, dtypes=self._wire_dtypes(), slot=capacity,
            num_parts=self.nshards, nshards=self.nshards,
            rows_useful=spec.get("rows", 0), packed=self.packed,
            site=self._sig + ("final", wenc),
            wire_encode_cols=len(wenc))
        return raw[:-1]


from spark_rapids_tpu.ops.aggregates import merge_kind as _merge_kind  # noqa: E402


def coalesce_buckets(counts, nshards: int):
    """Greedy balanced assignment of hash buckets to shards from the
    materialized [src_shard, bucket] histogram (the AQE partition
    coalescing / skew-spreading step).  Returns (lut int32[buckets],
    dst_counts [src_shard, dst_shard])."""
    import numpy as np
    totals = counts.sum(axis=0)
    buckets = counts.shape[1]
    load = np.zeros(nshards, dtype=np.int64)
    lut = np.zeros(buckets, dtype=np.int32)
    for b in np.argsort(-totals, kind="stable"):
        dst = int(np.argmin(load))
        lut[b] = dst
        load[dst] += int(totals[b])
    dst_counts = np.zeros((counts.shape[0], nshards), dtype=np.int64)
    for b in range(buckets):
        dst_counts[:, lut[b]] += counts[:, b]
    return lut, dst_counts


def concat_prefixes(cols_a: Sequence[ColVal], n_a,
                    cols_b: Sequence[ColVal], n_b):
    """Merge two dense-prefix column lists into one of capacity
    cap_a + cap_b: rows [0, n_a) from a, [n_a, n_a + n_b) from b, dead
    padding after.  Shared by the skew-join build merge and the
    full-outer unmatched-build append."""
    cap_a = cols_a[0].values.shape[0]
    cap_b = cols_b[0].values.shape[0]
    cap = cap_a + cap_b
    pos = jnp.arange(cap, dtype=jnp.int32)
    first = pos < n_a
    ia = jnp.clip(pos, 0, cap_a - 1)
    ib = jnp.clip(pos - n_a, 0, cap_b - 1)
    out = []
    for a, b in zip(cols_a, cols_b):
        vals = jnp.where(first, a.values[ia], b.values[ib])
        av = a.validity if a.validity is not None else \
            jnp.ones(cap_a, dtype=jnp.bool_)
        bv = b.validity if b.validity is not None else \
            jnp.ones(cap_b, dtype=jnp.bool_)
        valid = jnp.where(first, av[ia],
                          jnp.where(pos < n_a + n_b, bv[ib], False))
        out.append(ColVal(a.dtype, vals, valid))
    return out, (n_a + n_b).astype(jnp.int32)


def _v(o: ColVal):
    if o.validity is None:
        return jnp.ones_like(o.values, dtype=jnp.bool_)
    return o.validity


class DistributedHashJoin:
    """Equi-join over the mesh, two strategies (reference analogs:
    GpuBroadcastHashJoinExec and GpuShuffledHashJoinExec, SURVEY.md
    section 2.4 "Joins"):

    - ``broadcast``: the (small) build side is all-gathered to every shard
      over ICI — one collective replaces the reference's driver-hosted
      broadcast round trip — and each shard joins its probe slice locally.
    - ``shuffle``: both sides are hash-partitioned by join key with the
      padded ragged all-to-all, co-locating equal keys on one shard, then
      joined locally.

    Probe (left) columns stream sharded on the leading axis; the join
    runs as compiled shard_map programs (plus a histogram stats pass and
    host sync when shuffling — see the module docstring).  Output stays
    sharded with a
    per-shard row count; ``out_factor`` sizes the static output capacity
    (per-shard output rows <= probe_capacity * out_factor — exceeding it
    drops rows, so callers size it like the reference sizes its join
    output batches via JoinGatherer).  Fixed-width keys/payloads only
    (strings are dictionary-encoded upstream, as for the aggregate).
    """

    def __init__(self, mesh: Mesh,
                 probe_dtypes: Sequence[DataType],
                 build_dtypes: Sequence[DataType],
                 probe_key_idx: Sequence[int],
                 build_key_idx: Sequence[int],
                 join_type: str = "inner",
                 strategy: str = "auto",
                 out_factor: int = 1,
                 broadcast_threshold_rows: Optional[int] = None,
                 skew_factor: Optional[float] = None,
                 skew_min_rows: Optional[int] = None,
                 skew_enabled: Optional[bool] = None,
                 probe_encoded=None, build_encoded=None,
                 cost_model="auto"):
        from spark_rapids_tpu.ops.jit_cache import cached_jit
        from spark_rapids_tpu.config import rapids_conf as rc

        def _conf_default(value, entry):
            """Explicit arg > active session conf > entry default."""
            if value is not None:
                return value
            from spark_rapids_tpu.api.session import TpuSession
            s = TpuSession._active
            return s.conf.get(entry) if s is not None else entry.default

        broadcast_threshold_rows = _conf_default(
            broadcast_threshold_rows, rc.BROADCAST_JOIN_THRESHOLD_ROWS)
        skew_factor = _conf_default(skew_factor, rc.SKEW_JOIN_FACTOR)
        skew_min_rows = _conf_default(skew_min_rows, rc.SKEW_JOIN_MIN_ROWS)
        self.skew_enabled = _conf_default(skew_enabled,
                                          rc.SKEW_JOIN_ENABLED)
        if join_type not in ("inner", "left", "semi", "anti", "full"):
            # right joins run as a planner-side probe/build swap into
            # "left" + column reorder (GpuHashJoin does the same
            # buildSide flip for RightOuter)
            raise ValueError(
                "distributed join supports inner/left/semi/anti/full "
                f"(got {join_type!r}); lower right joins by swapping "
                "sides")
        if strategy not in ("auto", "broadcast", "shuffle"):
            raise ValueError(f"unknown strategy {strategy}")
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.nshards = mesh.devices.size
        self.probe_dtypes = list(probe_dtypes)
        self.build_dtypes = list(build_dtypes)
        self.probe_key_idx = list(probe_key_idx)
        self.build_key_idx = list(build_key_idx)
        self.join_type = join_type
        self.strategy = strategy
        self.out_factor = out_factor
        self.broadcast_threshold_rows = broadcast_threshold_rows
        # skew mitigation (OptimizeSkewedJoin / GpuCustomShuffleReader
        # analog): a destination receiving > skew_factor * median rows
        # (and > skew_min_rows) is "skewed" — its probe rows scatter
        # round-robin across ALL shards and its build rows replicate to
        # all shards, so one hot key cannot serialize on one chip
        self.skew_factor = skew_factor
        self.skew_min_rows = skew_min_rows
        self._cached_jit = cached_jit
        from spark_rapids_tpu.parallel.shuffle import (
            packed_enabled, ragged_enabled, topology_strategy,
            wire_encoding_enabled)
        self.packed = packed_enabled()
        # topology-aware collective selection + skew-adaptive ragged
        # slots (see DistributedAggregate); both bake into the jit sig
        self.exchange_strategy = topology_strategy(mesh)
        self.ragged, self.ragged_min_savings = ragged_enabled()
        # compressed wire: per-side ordinal -> dictionary for columns
        # exchanged as int64 codes (string keys AND code-valued payload
        # columns both narrow)
        self._probe_encoded = {int(i): d
                               for i, d in (probe_encoded or {}).items()}
        self._build_encoded = {int(i): d
                               for i, d in (build_encoded or {}).items()}
        self.wire_encoding = wire_encoding_enabled() and \
            bool(self._probe_encoded or self._build_encoded)
        self._p_wenc = tuple(sorted(self._probe_encoded)) \
            if self.wire_encoding else ()
        self._b_wenc = tuple(sorted(self._build_encoded)) \
            if self.wire_encoding else ()
        self._sig = ("dist_join", tuple(mesh.axis_names),
                     tuple(mesh.devices.shape),
                     tuple(str(d) for d in mesh.devices.flat),
                     tuple(dt.name for dt in self.probe_dtypes),
                     tuple(dt.name for dt in self.build_dtypes),
                     tuple(self.probe_key_idx), tuple(self.build_key_idx),
                     join_type, out_factor, ("packed", self.packed),
                     ("exch", self.exchange_strategy),
                     ("wenc", self.wire_encoding))
        # self-tuning planner: the same one-decision exchange policy
        # the aggregate resolves (see DistributedAggregate.__init__)
        from spark_rapids_tpu.plan.costmodel import \
            resolve_consumer_exchange
        resolve_consumer_exchange(self, "join", model=cost_model)
        self.last_stats: Optional[dict] = None

    def _jitted(self, strategy: str, slots, skewed=(), wencs=((), ())):
        """Compiled program per (strategy, exchange slots, skew set,
        per-side wire-encoding).  A slot entry may be a RaggedPlan; its
        cache_key stands in for it in the jit signature."""
        from spark_rapids_tpu.parallel.shuffle import RaggedPlan
        slots_sig = tuple(
            s.cache_key() if isinstance(s, RaggedPlan) else s
            for s in slots)
        return self._cached_jit(
            self._sig + (strategy, slots_sig, tuple(skewed), wencs),
            lambda: _shard_map(
                partial(self._step, strategy, slots, tuple(skewed),
                        wencs),
                mesh=self.mesh,
                in_specs=(P(self.axis), P(self.axis),
                          P(self.axis), P(self.axis)),
                out_specs=P(self.axis), check_vma=False))

    def _stats_jitted(self):
        """Per-destination histograms of both sides (the AQE stats pass
        that sizes the shuffle slots)."""
        def stats(probe_flat, probe_nrows_arr, build_flat,
                  build_nrows_arr):
            from spark_rapids_tpu.ops.pallas_kernels import histogram
            probe = [ColVal(dt, v, val)
                     for (v, val), dt in zip(probe_flat, self.probe_dtypes)]
            build = [ColVal(dt, v, val)
                     for (v, val), dt in zip(build_flat, self.build_dtypes)]
            cap_p = probe[0].values.shape[0]
            cap_b = build[0].values.shape[0]
            ppids = hash_partition_ids(
                [probe[i] for i in self.probe_key_idx], self.nshards)
            bpids = hash_partition_ids(
                [build[i] for i in self.build_key_idx], self.nshards)
            plive = jnp.arange(cap_p, dtype=jnp.int32) < probe_nrows_arr[0]
            blive = jnp.arange(cap_b, dtype=jnp.int32) < build_nrows_arr[0]
            return (histogram(ppids, plive, self.nshards),
                    histogram(bpids, blive, self.nshards))

        return self._cached_jit(
            self._sig + ("stats",), lambda: _shard_map(
                stats, mesh=self.mesh,
                in_specs=(P(self.axis), P(self.axis),
                          P(self.axis), P(self.axis)),
                out_specs=P(self.axis), check_vma=False))

    @staticmethod
    def _in_skewed(pids, skewed):
        """Boolean mask: pid is in the (static, small) skewed tuple."""
        m = jnp.zeros(pids.shape, dtype=jnp.bool_)
        for s in skewed:
            m = jnp.logical_or(m, pids == s)
        return m

    def _exchange_one(self, cols, pids, n, slot, site_tag, wenc=()):
        """One side's exchange under the resolved collective strategy:
        gather-then-redistribute on DCN-ish axes, ragged (RaggedPlan
        slot) or uniform all_to_all otherwise.  The uniform fallback
        slot for a ragged plan is base+surplus — an upper bound on
        every slice, used only when the lane packer cannot ingest the
        columns (trace-time consistent).  ``wenc``: code-column
        indices narrowing on the wire."""
        from spark_rapids_tpu.parallel.shuffle import (
            RaggedPlan, exchange_via_gather)
        if self.exchange_strategy == "gather":
            return exchange_via_gather(
                cols, pids, n, self.axis, self.nshards,
                packed=self.packed,
                report_site=self._sig + (site_tag, wenc),
                wire_encode=wenc)
        if isinstance(slot, RaggedPlan):
            return exchange(
                cols, pids, n, self.axis, self.nshards,
                slot=slot.base_slot + slot.surplus_slot,
                packed=self.packed,
                report_site=self._sig + (site_tag, wenc), ragged=slot,
                wire_encode=wenc)
        return exchange(cols, pids, n, self.axis, self.nshards,
                        slot=slot, packed=self.packed,
                        report_site=self._sig + (site_tag, wenc),
                        wire_encode=wenc)

    def _step(self, strategy, slots, skewed, wencs, probe_flat,
              probe_nrows_arr, build_flat, build_nrows_arr):
        from spark_rapids_tpu.ops import joins as J
        from spark_rapids_tpu.parallel.shuffle import all_gather_cols

        pn = probe_nrows_arr[0]
        bn = build_nrows_arr[0]
        probe = [ColVal(dt, v, val)
                 for (v, val), dt in zip(probe_flat, self.probe_dtypes)]
        build = [ColVal(dt, v, val)
                 for (v, val), dt in zip(build_flat, self.build_dtypes)]
        # output capacity contract: per-shard output rows <=
        # probe_capacity * out_factor, where probe_capacity is the
        # PRE-exchange capacity (the adaptive slot must not shrink it)
        in_probe_cap = probe[0].values.shape[0]

        wenc_p, wenc_b = wencs
        if strategy == "local":
            # host-staged exchange already co-located both sides by key
            # hash off-device: no collective, straight local join
            pass
        elif strategy == "broadcast":
            build, bn = all_gather_cols(build, bn, self.axis, self.nshards,
                                        packed=self.packed,
                                        report_site=self._sig
                                        + ("bcast", wenc_b),
                                        wire_encode=wenc_b)
        else:
            pkeys = [probe[i] for i in self.probe_key_idx]
            bkeys = [build[i] for i in self.build_key_idx]
            ppids = hash_partition_ids(pkeys, self.nshards)
            bpids = hash_partition_ids(bkeys, self.nshards)
            if skewed:
                # skew-join mitigation: probe rows bound for a skewed
                # destination scatter round-robin over ALL shards; the
                # matching build rows replicate everywhere.  Non-skewed
                # keys hash to different pids, so the replicated rows
                # can never produce cross matches or duplicates.
                sk_p = self._in_skewed(ppids, skewed)
                # enumerate SKEWED rows only (cumsum over the mask):
                # raw-position % nshards would bias toward one
                # destination for strided layouts and overflow the
                # slot bound sized in __call__
                order = jnp.cumsum(sk_p.astype(jnp.int32)) - 1
                rr = (order % self.nshards).astype(ppids.dtype)
                ppids = jnp.where(sk_p, rr, ppids)
                live_b = jnp.arange(bpids.shape[0],
                                    dtype=jnp.int32) < bn
                sk_b = self._in_skewed(bpids, skewed)
                norm_cols, n_norm = selection.compact(
                    build, jnp.logical_and(live_b, ~sk_b))
                sk_cols, n_sk = selection.compact(
                    build, jnp.logical_and(live_b, sk_b))
                probe, pn = self._exchange_one(probe, ppids, pn,
                                               slots[0], "probe",
                                               wenc=wenc_p)
                norm_keys = [norm_cols[i] for i in self.build_key_idx]
                b1, bn1 = self._exchange_one(
                    norm_cols, hash_partition_ids(norm_keys,
                                                  self.nshards),
                    n_norm, slots[1], "build", wenc=wenc_b)
                # gather only a bounded prefix: the host sized
                # slots[2] from the true max per-shard skewed build
                # count, so the full cap_b column never rides ICI
                gcap = slots[2]
                sk_sliced = [
                    ColVal(c.dtype, c.values[:gcap],
                           None if c.validity is None
                           else c.validity[:gcap])
                    for c in sk_cols]
                b2, bn2 = all_gather_cols(sk_sliced, n_sk, self.axis,
                                          self.nshards,
                                          packed=self.packed,
                                          report_site=self._sig
                                          + ("gather", wenc_b),
                                          wire_encode=wenc_b)
                build, bn = concat_prefixes(b1, bn1, b2, bn2)
            else:
                probe, pn = self._exchange_one(probe, ppids, pn,
                                               slots[0], "probe",
                                               wenc=wenc_p)
                build, bn = self._exchange_one(build, bpids, bn,
                                               slots[1], "build",
                                               wenc=wenc_b)

        pkeys = [probe[i] for i in self.probe_key_idx]
        bkeys = [build[i] for i in self.build_key_idx]
        m = J.join_match(bkeys, pkeys, jnp.int32(bn), jnp.int32(pn))

        if self.join_type in ("semi", "anti"):
            # existence joins: a compaction of the probe side, no phase B
            # (GpuHashJoin existence path); null-keyed probe rows never
            # match, so they survive anti (Spark LeftAnti semantics)
            p_cap = probe[0].values.shape[0]
            live_p = jnp.arange(p_cap, dtype=jnp.int32) < pn
            has = m["probe_count"] > 0
            keep = jnp.logical_and(
                has if self.join_type == "semi" else ~has, live_p)
            out_cols, n_out = selection.compact(probe, keep)
            flat = [(c.values,
                     c.validity if c.validity is not None
                     else jnp.ones(p_cap, dtype=jnp.bool_))
                    for c in out_cols]
            n_out = n_out.astype(jnp.int32)
            return flat, n_out[None], n_out[None]

        outer = self.join_type in ("left", "full")
        count, starts, ends, total = J.join_out_starts(
            m["probe_count"], jnp.int32(pn), outer)
        out_cap = max(in_probe_cap,
                      probe[0].values.shape[0]) * self.out_factor
        p, brow, matched, _ = J.join_gather_indices(
            starts, ends, m["probe_count"], m["probe_bstart"],
            m["sorted_to_build"], total, out_cap)
        n_out = jnp.minimum(total, out_cap).astype(jnp.int32)
        probe_out = selection.gather(probe, p, n_out)
        build_out = J.gather_build_side(build, brow, matched, n_out)

        if self.join_type == "full":
            # append build rows that matched nothing, with null probe
            # columns (shuffle strategy only: each build row lives on
            # exactly one shard, so the never-matched set partitions
            # cleanly across shards)
            b_cap = build[0].values.shape[0]
            live_b = jnp.arange(b_cap, dtype=jnp.int32) < bn
            un_cols, un_n = selection.compact(
                build, jnp.logical_and(~m["build_matched"], live_b))
            null_probe = [
                ColVal(c.dtype, jnp.zeros(b_cap, dtype=c.values.dtype),
                       jnp.zeros(b_cap, dtype=jnp.bool_))
                for c in probe_out]
            merged, n_full = concat_prefixes(
                list(probe_out) + list(build_out), n_out,
                null_probe + list(un_cols), un_n.astype(jnp.int32))
            flat = [(c.values, c.validity) for c in merged]
            return flat, n_full[None], (total.astype(jnp.int32) +
                                        un_n.astype(jnp.int32))[None]

        flat = [(c.values,
                 c.validity if c.validity is not None
                 else jnp.ones(out_cap, dtype=jnp.bool_))
                for c in probe_out + build_out]
        # also expose the UNclamped total: when total > n_out the output was
        # truncated to out_cap and the caller must retry with a larger
        # out_factor (the reference instead splits join output batches,
        # JoinGatherer.scala:36-60 — silent truncation = wrong results)
        return flat, n_out[None], total.astype(jnp.int32)[None]

    def __call__(self, probe_flat, probe_nrows_per_shard, build_flat,
                 build_nrows_per_shard, window=None):
        """probe_flat/build_flat: [(values, validity)] with leading-axis
        sharded arrays; nrows arrays have one entry per shard.  Returns
        (flat output cols, nrows per shard, unclamped match total per
        shard).  Output columns by join type: inner/left/full are probe
        cols then build cols; semi/anti are probe cols ONLY (an
        existence compaction).  Output capacity per shard is
        probe_capacity * out_factor for inner/left, plus build_capacity
        for full (the unmatched-build append), and probe_capacity for
        semi/anti.  Any shard where total > nrows was truncated (the
        probe-match region hit out_factor * capacity): the caller must
        retry with a larger out_factor; semi/anti never truncate.

        ``strategy='auto'`` picks broadcast vs shuffled-hash from the
        build-side row stats (the reference's planner picks
        GpuBroadcastHashJoinExec vs GpuShuffledHashJoinExec by build
        size); the shuffle path additionally sizes its all-to-all slots
        from per-destination histograms instead of full-capacity padding.
        """
        import numpy as np
        from spark_rapids_tpu.parallel.exchange_async import (
            overlap_metrics_for_session)
        from spark_rapids_tpu.parallel.shuffle import (
            broadcast_wire_dicts, metrics_for_session,
            planner_for_session, record_exchange_metrics)
        strategy = self.strategy
        total_build = int(host_sync(build_nrows_per_shard).sum())
        if strategy == "auto":
            strategy = "broadcast" \
                if total_build <= self.broadcast_threshold_rows else \
                "shuffle"
        if self.join_type == "full":
            # a replicated build side would emit its never-matched rows
            # once per shard; full outer must co-partition
            strategy = "shuffle"
        planner = planner_for_session()
        metrics = metrics_for_session()
        # compressed wire (see DistributedAggregate.__call__): one
        # dictionary-delta broadcast per launch, covering ONLY the
        # sides that actually ship encoded under the resolved strategy
        # (broadcast joins never exchange the probe side; host-staged
        # launches exchange nothing on the device wire) — a failed
        # verification degrades this launch to the wide wire
        wenc_p, wenc_b = (), ()

        def resolve_wire(probe_side: bool, build_side: bool) -> None:
            nonlocal wenc_p, wenc_b
            if not (self._probe_encoded or self._build_encoded):
                return
            if not self.wire_encoding:
                metrics.record_encodable_decoded()
                return
            sel_p = self._p_wenc if probe_side else ()
            sel_b = self._b_wenc if build_side else ()
            dicts = [self._probe_encoded[i] for i in sel_p] \
                + [self._build_encoded[i] for i in sel_b]
            if not dicts:
                return
            if broadcast_wire_dicts(
                    self._sig + ("dict", probe_side, build_side),
                    dicts, metrics):
                wenc_p, wenc_b = sel_p, sel_b

        slots = (None, None)
        skewed = ()
        stats = {"strategy": strategy, "build_rows": total_build}
        # payload bytes of EVERY exchange this launch puts in flight at
        # once (probe + build + any skew-gather) — what the async
        # window's in-flight budget must charge
        launch_bytes = 0
        if strategy == "broadcast":
            resolve_wire(False, True)
            # the all-gather moves every shard's full build capacity
            cap_b = int(build_flat[0][0].shape[0]) // self.nshards
            record_exchange_metrics(
                metrics, dtypes=self.build_dtypes, slot=cap_b,
                num_parts=self.nshards, nshards=self.nshards,
                rows_useful=total_build, packed=self.packed,
                site=self._sig + ("bcast", wenc_b),
                wire_encode_cols=len(wenc_b))
        if strategy == "shuffle":
            phist, bhist = self._stats_jitted()(
                probe_flat, probe_nrows_per_shard,
                build_flat, build_nrows_per_shard)
            pcounts, bcounts = host_sync((phist, bhist))
            pcounts = pcounts.reshape(self.nshards, self.nshards)
            bcounts = bcounts.reshape(self.nshards, self.nshards)
            from spark_rapids_tpu.parallel.shuffle import pick_slot
            cap_p = int(probe_flat[0][0].shape[0]) // self.nshards
            cap_b = int(build_flat[0][0].shape[0]) // self.nshards
            # host-RAM staging: a payload past the threshold never
            # rides the device collective — both sides repartition
            # through host memory + the frame codec and the join runs
            # the no-exchange "local" program (the split-rung dodge)
            from spark_rapids_tpu.parallel.shuffle import wire_row_bytes
            from spark_rapids_tpu.plan.costmodel import (
                consumer_staging_threshold)
            thr = consumer_staging_threshold(self)
            if self._cost_model is not None:
                # launch-time evidence: probe-side skew (the side the
                # skew machinery keys on) + both sides' useful bytes
                p_useful = int(pcounts.sum()) * max(
                    wire_row_bytes(self.probe_dtypes)
                    - 4 * len(self._p_wenc), 1)
                b_useful = int(bcounts.sum()) * max(
                    wire_row_bytes(self.build_dtypes)
                    - 4 * len(self._b_wenc), 1)
                self._cost_model.note_exchange(
                    self._sig, rows=int(pcounts.sum()),
                    max_slice=int(pcounts.max()),
                    useful_bytes=p_useful + b_useful)
            if thr and self.exchange_strategy != "gather":
                # staging sized from POST-encoding byte counts: the
                # narrowed wire halves each code column's contribution
                # (the INTENDED wire — the dict broadcast below runs
                # only when the launch stays on the device collective)
                est = (self.nshards * self.nshards
                       * pick_slot(int(pcounts.max()), cap_p)
                       * max(wire_row_bytes(self.probe_dtypes)
                             - 4 * len(self._p_wenc), 1)
                       + self.nshards * self.nshards
                       * pick_slot(int(bcounts.max()), cap_b)
                       * max(wire_row_bytes(self.build_dtypes)
                             - 4 * len(self._b_wenc), 1))
                if est > thr:
                    out = self._staged_call(
                        probe_flat, pcounts, build_flat, bcounts,
                        metrics)
                    if self._cost_model is not None:
                        self._cost_model.observe_staged(
                            self._sig,
                            self.last_stats.get("stagedBytes", 0))
                    return out
            resolve_wire(True, True)
            # skew detection on the probe destination totals
            # (OptimizeSkewedJoin: partition > factor * median)
            dest_p = pcounts.sum(axis=0)
            med = max(1.0, float(np.median(dest_p)))
            skewed = tuple(
                int(d) for d in np.nonzero(
                    (dest_p > self.skew_factor * med)
                    & (dest_p > self.skew_min_rows))[0]) \
                if self.skew_enabled and self.join_type != "full" else ()
            # both sides' slots go through the SlotPlanner (EMA-sticky
            # power-of-two buckets per site, so a stable workload keeps
            # a stable jit-cache key); the histograms are mandatory
            # here regardless — skew detection needs them — so the join
            # never launches speculatively
            p_site = self._sig + ("probe", bool(skewed))
            b_site = self._sig + ("build", bool(skewed))
            if skewed:
                sk = np.zeros(self.nshards, dtype=bool)
                sk[list(skewed)] = True
                # after mitigation each src spreads its skewed rows
                # exactly evenly (cumsum round-robin), so the
                # per-(src,dst) slice bound is the non-skewed count
                # plus that src's share
                share = np.ceil(
                    pcounts[:, sk].sum(axis=1) / self.nshards) + 1
                padj = pcounts.copy()
                padj[:, sk] = 0
                padj = padj + share[:, None]
                badj = bcounts.copy()
                badj[:, sk] = 0
                # third slot: capacity of the skewed-build all-gather
                # prefix (max skewed build rows on any one shard)
                gather_cap = pick_slot(
                    int(bcounts[:, sk].sum(axis=1).max()), cap_b)
                slots = (planner.plan(p_site, int(padj.max()), cap_p),
                         planner.plan(b_site, int(badj.max()), cap_b),
                         gather_cap)
                # rows= feeds the per-site observation store (skew =
                # max_slice/rows): join exchange sites carry evidence
                # like aggregate sites do, so the ragged-vs-uniform
                # decision has history on every exchange-bearing op
                planner.observe(p_site, int(padj.max()), slots[0],
                                cap_p, rows=int(pcounts.sum()))
                planner.observe(b_site, int(badj.max()), slots[1],
                                cap_b, rows=int(bcounts.sum()))
                # the skewed-build bounded all-gather is a third data
                # movement on ICI (gather_cap rows replicated to every
                # shard) — it can dominate a heavily skewed build side,
                # so it must show up in the wire accounting too
                record_exchange_metrics(
                    metrics, dtypes=self.build_dtypes, slot=gather_cap,
                    num_parts=self.nshards, nshards=self.nshards,
                    rows_useful=int(bcounts[:, sk].sum()),
                    packed=self.packed,
                    site=self._sig + ("gather", wenc_b),
                    wire_encode_cols=len(wenc_b))
                launch_bytes += metrics.last_exchange_bytes
            else:
                u_p = planner.plan(p_site, int(pcounts.max()), cap_p)
                u_b = planner.plan(b_site, int(bcounts.max()), cap_b)
                # rows= so join sites feed skew/row evidence into the
                # observation store (see the skewed branch above)
                planner.observe(p_site, int(pcounts.max()), u_p, cap_p,
                                rows=int(pcounts.sum()))
                planner.observe(b_site, int(bcounts.max()), u_b, cap_b,
                                rows=int(bcounts.sum()))
                slots = (u_p, u_b)
                if self.ragged and self.exchange_strategy != "gather":
                    # skew-adaptive ragged wire: the [src, dst]
                    # histograms are already materialized for slot
                    # sizing, so a hot destination lowers to a
                    # RaggedPlan per side — base all_to_all sized from
                    # the cold slices, hot-pair surplus over
                    # collective-permutes (parallel/shuffle.py)
                    from spark_rapids_tpu.parallel.shuffle import \
                        plan_ragged
                    rp = plan_ragged(pcounts, cap_p,
                                     self.ragged_min_savings)
                    rb = plan_ragged(bcounts, cap_b,
                                     self.ragged_min_savings)
                    slots = (rp or u_p, rb or u_b)
            from spark_rapids_tpu.parallel.shuffle import RaggedPlan
            rag_p = slots[0] if isinstance(slots[0], RaggedPlan) else None
            rag_b = slots[1] if isinstance(slots[1], RaggedPlan) else None
            # the gather strategy all-gathers full-capacity buffers
            # (slot planning does not apply), so account capacity
            gather = self.exchange_strategy == "gather"
            record_exchange_metrics(
                metrics, dtypes=self.probe_dtypes,
                slot=cap_p if gather
                else (slots[0] if rag_p is None else 0),
                num_parts=self.nshards, nshards=self.nshards,
                rows_useful=int(pcounts.sum()), packed=self.packed,
                site=self._sig + ("probe", wenc_p), ragged=rag_p,
                counts=pcounts, wire_encode_cols=len(wenc_p))
            launch_bytes += metrics.last_exchange_bytes
            record_exchange_metrics(
                metrics, dtypes=self.build_dtypes,
                slot=cap_b if gather
                else (slots[1] if rag_b is None else 0),
                num_parts=self.nshards, nshards=self.nshards,
                rows_useful=int(bcounts.sum()), packed=self.packed,
                site=self._sig + ("build", wenc_b), ragged=rag_b,
                counts=bcounts, wire_encode_cols=len(wenc_b))
            launch_bytes += metrics.last_exchange_bytes
            stats.update(probe_counts=pcounts, build_counts=bcounts,
                         slots=tuple(repr(s) if isinstance(s, RaggedPlan)
                                     else s for s in slots),
                         skewed=skewed)
        stats["wire"] = metrics.snapshot()
        self.last_stats = stats
        import contextlib
        from spark_rapids_tpu.parallel.shuffle import launch_checkpoint
        # only the shuffle strategy launches an exchange; broadcast is
        # a bare all-gather with no "shuffle.exchange" checkpoint
        cp = launch_checkpoint() if strategy == "shuffle" \
            else contextlib.nullcontext()
        if strategy == "shuffle":
            # the join's stats pass is mandatory (skew detection), so
            # its exchange always launches as the two-dispatch
            # sequence; with fusion.wire.enabled on, the stage leaves
            # the "fusible chain ran unfused" breadcrumb
            metrics.record_fused_dispatch(False)
            from spark_rapids_tpu.parallel.shuffle import (
                wire_fusion_enabled)
            if wire_fusion_enabled():
                from spark_rapids_tpu.exec.fusion import fusion_metrics
                fusion_metrics.bump("wireUnfusedLaunches")
        with cp:
            out = self._jitted(strategy, slots, skewed,
                               (wenc_p, wenc_b))(
                probe_flat, probe_nrows_per_shard,
                build_flat, build_nrows_per_shard)
        if strategy == "shuffle":
            if self._cost_model is not None:
                # ledger outcome + the plan-vs-measured contradiction
                # check (see DistributedAggregate.__call__): a uniform
                # launch over a histogram a ragged plan would have
                # beaten past the hysteresis band re-drives ONCE
                # through the ladder with the evidence already folded
                self._cost_model.observe_outcome(
                    "exchange", self._sig, float(launch_bytes))
                if rag_p is None and rag_b is None and not skewed and \
                        self.exchange_strategy != "gather":
                    self._cost_model.check_contradiction(
                        self._sig, "join", counts=pcounts,
                        capacity=cap_p, nshards=self.nshards,
                        slot=slots[0] if isinstance(slots[0], int)
                        else 0)
            if window is not None:
                # join slots are stats-sized (histograms are mandatory
                # for skew detection), so there is no deferred
                # verification — the handle tracks the in-flight bytes
                # (BOTH sides' payloads, plus any skew-gather, are
                # resident at once) and the dispatch->resolve overlap
                window.admit(self._sig + ("exchange",), launch_bytes)
            else:
                overlap_metrics_for_session().record_sync()
        return out

    def _staged_call(self, probe_flat, probe_hist, build_flat,
                     build_hist, metrics):
        """Host-RAM staging for an oversized shuffle join: BOTH sides
        repartition through host memory (frame-codec round trip — the
        pinned-bounce-buffer analog) with the same murmur mix the
        device kernels use, then the no-collective "local" program
        joins the already co-located rows.  The oversized exchange
        lands in host RAM instead of marching into the recovery
        ladder's split rung.  Per-shard live rows derive from the
        ``[src, dst]`` histograms the stats pass already synced — no
        extra counted hostsyncs."""
        from spark_rapids_tpu.parallel.exchange_async import (
            stage_host_side)
        from spark_rapids_tpu.parallel.shuffle import launch_checkpoint
        staged_p, pcounts, pbytes = stage_host_side(
            probe_flat, probe_hist, self.probe_key_idx, self.nshards,
            self.nshards)
        staged_b, bcounts, bbytes = stage_host_side(
            build_flat, build_hist, self.build_key_idx, self.nshards,
            self.nshards)
        rows = int(pcounts.sum()) + int(bcounts.sum())
        # staged rows move dense (no padding); bytes are the compressed
        # frames that actually crossed host RAM
        metrics.record_exchange(
            collectives=0, rows_moved=rows, rows_useful=rows,
            bytes_moved=pbytes + bbytes, packed=self.packed)
        pf = tuple((jnp.asarray(v), jnp.asarray(m)) for v, m in staged_p)
        bf = tuple((jnp.asarray(v), jnp.asarray(m)) for v, m in staged_b)
        self.last_stats = {"strategy": "local", "staged": True,
                           "stagedBytes": pbytes + bbytes,
                           "build_rows": int(bcounts.sum()),
                           "wire": metrics.snapshot()}
        with launch_checkpoint():
            return self._jitted("local", (None, None),
                                wencs=((), ()))(
                pf, jnp.asarray(pcounts), bf, jnp.asarray(bcounts))
