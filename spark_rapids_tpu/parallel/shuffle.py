"""Shuffle as an SPMD collective: ONE fused packed all-to-all per exchange.

This replaces the reference's entire UCX transport stack (shuffle-plugin/,
RapidsShuffleClient/Server, bounce buffers, heartbeats — SURVEY.md section
2.5): instead of point-to-point pull with metadata requests, every shard
partitions its rows by destination, lays them out contiguously, and a
collective moves all slices across ICI simultaneously.  Peer discovery,
connection management, and retry logic disappear — the collective is
compiled into the XLA program.

Wire format (the fused data path): all fixed-width columns of a batch are
byte-reinterpreted (``jax.lax.bitcast_convert_type`` — always to *narrower*
lanes, because the TPU X64 rewriter cannot lower 64<->64 float/int
bitcasts) into width-homogeneous lane groups:

* **u32 group** — 4-byte columns contribute one uint32 lane, 8-byte
  columns two; payload shape ``[num_parts, slot, lanes32]``.
* **u8 group** — bool/int8 columns contribute one uint8 lane, int16 two,
  and every validity mask is bit-packed eight-to-a-lane at the tail;
  payload shape ``[num_parts, slot, lanes8]``.

Each group moves with ONE ``all_to_all`` and the slice→dense compaction
index map is computed once per exchange and shared by every lane — an
exchange costs O(distinct widths) ≤ 2 collectives plus the counts vector,
instead of O(columns + validity masks).  ``packed.enabled=false`` (or an
unpackable column) falls back to the per-column collectives, which still
reuse the shared compaction indices.

Raggedness: all_to_all needs equal-sized slices, so each (src, dst) slice
is padded to ``slot`` rows, with true counts exchanged alongside.  Slot
sizing is the :class:`SlotPlanner`'s job (modes ``adaptive`` / ``fixed`` /
``capacity``): exchange sites feed it their materialized per-destination
histogram max, it answers with a power-of-two slot smoothed by a per-site
EMA (stable slots = stable jit-cache keys), and warm ``adaptive`` sites
may launch *speculatively* — skipping the stats hostsync entirely — with
a slot-overflow check after the launch that re-runs at full capacity and
records a degradable recovery action rather than ever dropping rows.

Every exchange also reports wire observability (collectives launched,
payload bytes, padding ratio, overflow retries) through
:class:`ShuffleWireMetrics` → eventlog ``QueryInfo.shuffle`` →
``tools/profiling`` health checks (docs/performance.md "Shuffle wire
format").
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict, Hashable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.ops.expressions import ColVal
from spark_rapids_tpu.parallel.partitioning import layout_by_partition
from spark_rapids_tpu.robustness.inject import register_point

# chaos surface: bit-flip the compressed dictionary-delta broadcast a
# wire-encoded exchange ships (WireDictBroadcast) — verification failure
# degrades that launch to the wide wire, exact results either way
register_point("shuffle.wire.dict")


@contextmanager
def launch_checkpoint():
    """The single host-side checkpoint per exchange-bearing program
    launch: fires the "shuffle.exchange" injection point exactly once
    (count-based chaos rules see one checkpoint per launch whether the
    traced program was cached or not — packed or per-column alike) and
    runs the host-side launch (trace + dispatch) under a watchdog
    deadline.  XLA dispatch is asynchronous, so a collective that
    wedges DURING execution surfaces at the stage's host sync / the
    whole-query deadline instead — cancellation is cooperative and only
    host-touching checkpoints can deliver it (robustness/watchdog.py)."""
    from spark_rapids_tpu.robustness import watchdog
    from spark_rapids_tpu.robustness.inject import fire
    with watchdog.section("shuffle.exchange",
                          deadline_ms=_launch_deadline_ms()):
        fire("shuffle.exchange")
        yield


def _launch_deadline_ms() -> Optional[float]:
    """Exchange-launch deadline, DCN-aware: a cross-host collective is
    orders of magnitude slower than the same bytes over ICI, so when
    the active session's data axis spans hosts the per-point deadline
    scales by ``spark.rapids.tpu.fleet.dcnDeadlineScale`` — otherwise
    the deadline tuned for ICI misfires on every healthy DCN exchange.
    None defers to the watchdog's own per-point resolution (the
    single-host behavior, unchanged)."""
    try:
        from spark_rapids_tpu.api.session import TpuSession
        session = TpuSession._active
    except ImportError:  # torn-down interpreter only
        return None
    mesh = getattr(session, "mesh", None)
    if session is None or mesh is None:
        return None
    from spark_rapids_tpu.config import rapids_conf as rc
    from spark_rapids_tpu.parallel.mesh import axis_link_kind
    if axis_link_kind(mesh) != "dcn":
        return None
    base = session.conf.watchdog_deadline_ms("shuffle.exchange")
    if base is None or base <= 0:
        return None
    return float(base) * float(
        session.conf.get(rc.FLEET_DCN_DEADLINE_SCALE))


def pick_slot(max_slice: int, capacity: int, floor: int = 8) -> int:
    """Slot size for ``exchange`` from a materialized per-destination
    histogram: the true max slice count bucketed up to a power of two
    (<= 2x the ideal bytes on ICI), capped at the full capacity."""
    s = floor
    while s < max_slice:
        s <<= 1
    return min(s, capacity)


class RaggedPlan:
    """Skew-adaptive slot plan for one stats-sized exchange.

    The base all_to_all is sized from the COLD (src, dst) slices; the
    few hot slices' surplus rows (beyond ``base_slot``) ride
    collective-permutes that transmit only on their own link — wire
    rows stop scaling as ``num_parts * hottest_slice``.  ``pairs`` is
    the static hot set; rounds decompose it into partial permutations
    (each src/dst at most once per round, the ppermute contract).
    Hashable: a plan is part of the consumer's jit-cache signature.
    """

    def __init__(self, num_parts: int, base_slot: int, surplus_slot: int,
                 pairs):
        import numpy as np
        self.num_parts = num_parts
        self.base_slot = int(base_slot)
        self.surplus_slot = int(surplus_slot)
        self.pairs = tuple(sorted(tuple(map(int, p)) for p in pairs))
        # greedy round decomposition into partial permutations
        remaining = list(self.pairs)
        rounds = []
        while remaining:
            used_s, used_d, rnd = set(), set(), []
            for p in list(remaining):
                s, d = p
                if s not in used_s and d not in used_d:
                    rnd.append(p)
                    used_s.add(s)
                    used_d.add(d)
                    remaining.remove(p)
            rounds.append(tuple(rnd))
        self.rounds = tuple(rounds)
        # static lookup tables the SPMD trace indexes by axis_index
        n = num_parts
        self.round_dst_by_src = np.zeros((len(rounds), n), dtype=np.int32)
        self.round_for_src = np.zeros((n, n), dtype=np.int32)
        self.limits = np.full((n, n), self.base_slot, dtype=np.int32)
        pairs_per_dest = np.zeros(n, dtype=np.int64)
        for r, rnd in enumerate(self.rounds):
            for s, d in rnd:
                self.round_dst_by_src[r, s] = d
                self.round_for_src[d, s] = r
                self.limits[s, d] = self.base_slot + self.surplus_slot
                pairs_per_dest[d] += 1
        self.max_pairs_per_dest = int(pairs_per_dest.max()) if n else 0

    @property
    def out_capacity(self) -> int:
        """Static receive capacity every shard allocates: the base
        slices plus the worst destination's surplus buffers."""
        return self.num_parts * self.base_slot + \
            self.max_pairs_per_dest * self.surplus_slot

    def wire_rows(self, nshards: int) -> int:
        """Exact wire rows one launch moves: every shard transmits the
        full base payload; each surplus pair transmits once (a
        collective-permute only moves the named link)."""
        return nshards * self.num_parts * self.base_slot + \
            len(self.pairs) * self.surplus_slot

    def cache_key(self):
        return ("ragged", self.num_parts, self.base_slot,
                self.surplus_slot, self.pairs)

    def __repr__(self):
        return (f"RaggedPlan(base={self.base_slot}, "
                f"surplus={self.surplus_slot}x{len(self.pairs)}, "
                f"rounds={len(self.rounds)})")


def plan_ragged(counts, capacity: int, min_savings: float = 1.5,
                max_pairs: Optional[int] = None) -> Optional[RaggedPlan]:
    """Ragged plan from a materialized [src, dst] histogram, or None
    when the uniform slot wins (no skew, too many hot pairs, or the
    wire-rows saving is below ``min_savings``)."""
    import numpy as np
    counts = np.asarray(counts)
    if counts.ndim != 2 or not counts.size:
        return None
    n_src, n_dst = counts.shape
    max_pairs = max_pairs if max_pairs is not None else 2 * n_dst
    u_slot = pick_slot(int(counts.max()), capacity)
    uniform_rows = n_src * n_dst * u_slot
    best = None
    best_rows = uniform_rows
    base = 8
    while base < u_slot:
        pairs = np.argwhere(counts > base)
        if 0 < len(pairs) <= max_pairs:
            surplus = pick_slot(int((counts - base).max()), capacity)
            rows = n_src * n_dst * base + len(pairs) * surplus
            if rows < best_rows:
                best_rows = rows
                best = (base, surplus, [tuple(p) for p in pairs])
        base <<= 1
    if best is None or uniform_rows / max(best_rows, 1) < min_savings:
        return None
    return RaggedPlan(n_dst, best[0], best[1], best[2])


def ragged_enabled(conf=None) -> Tuple[bool, float]:
    """(enabled, minSavings) for skew-adaptive ragged slot planning."""
    from spark_rapids_tpu.config import rapids_conf as rc
    if conf is None:
        from spark_rapids_tpu.api.session import TpuSession
        s = TpuSession._active
        if s is None:
            return (rc.SHUFFLE_SLOT_RAGGED_ENABLED.default,
                    rc.SHUFFLE_SLOT_RAGGED_FACTOR.default)
        conf = s.conf
    return (conf.get(rc.SHUFFLE_SLOT_RAGGED_ENABLED),
            conf.get(rc.SHUFFLE_SLOT_RAGGED_FACTOR))


def topology_strategy(mesh, conf=None) -> str:
    """Collective strategy for the mesh's exchange axis: the conf knob,
    with 'auto' resolving by link kind (all_to_all on ICI, gather-then-
    redistribute on a DCN-spanning axis) — parallel/mesh.py topology."""
    from spark_rapids_tpu.config import rapids_conf as rc
    if conf is None:
        from spark_rapids_tpu.api.session import TpuSession
        s = TpuSession._active
        conf = s.conf if s is not None else None
    strategy = conf.get(rc.SHUFFLE_TOPOLOGY_STRATEGY) if conf is not None \
        else rc.SHUFFLE_TOPOLOGY_STRATEGY.default
    if strategy != "auto":
        return strategy
    from spark_rapids_tpu.parallel.mesh import axis_link_kind
    return "gather" if axis_link_kind(mesh) == "dcn" else "all_to_all"


def wire_encoding_enabled(conf=None) -> bool:
    """Resolve spark.rapids.tpu.encoding.wire.enabled (the compressed
    device wire for dictionary-code columns); consumers resolve at
    construction and bake the narrowed column set into their jit
    signatures."""
    from spark_rapids_tpu.config import rapids_conf as rc
    if conf is None:
        from spark_rapids_tpu.api.session import TpuSession
        s = TpuSession._active
        if s is None:
            return rc.ENCODING_WIRE_ENABLED.default
        conf = s.conf
    from spark_rapids_tpu.plan.costmodel import model_for_conf
    cm = model_for_conf(conf)
    if cm is not None:
        # self-tuning planner: the model decides when the conf key is
        # unset (an explicitly-set key stays an override inside it);
        # conf-gated so a knobs-off session planning while a model-on
        # session is _active keeps bit-identical HEAD parity
        return cm.wire_encoding()
    return conf.get(rc.ENCODING_WIRE_ENABLED)


def wire_fusion_enabled(conf=None) -> bool:
    """Resolve spark.rapids.tpu.fusion.wire.enabled: explicit conf >
    active session > entry default.  Consumers resolve at construction;
    the fused program's jit key carries its own component (never the
    shared stage signature, so stage ids stay byte-identical fused or
    not)."""
    from spark_rapids_tpu.config import rapids_conf as rc
    if conf is None:
        from spark_rapids_tpu.api.session import TpuSession
        s = TpuSession._active
        if s is None:
            return rc.FUSION_WIRE_ENABLED.default
        conf = s.conf
    return conf.get(rc.FUSION_WIRE_ENABLED)


def packed_enabled(conf=None) -> bool:
    """Resolve spark.rapids.tpu.shuffle.packed.enabled: explicit conf >
    active session > entry default.  Exchange consumers resolve this at
    construction and bake it into their jit-cache signatures, so a conf
    flip can never be masked by a cached trace."""
    from spark_rapids_tpu.config import rapids_conf as rc
    if conf is None:
        from spark_rapids_tpu.api.session import TpuSession
        s = TpuSession._active
        if s is None:
            return rc.SHUFFLE_PACKED_ENABLED.default
        conf = s.conf
    return conf.get(rc.SHUFFLE_PACKED_ENABLED)


# ------------------------------------------------------------- lane packing --

_U32 = "u32"
_U8 = "u8"


class _PackPlan:
    """Lane assignment for one exchange's columns: which width group and
    lane range each column occupies, plus the bit position of every
    validity mask in the u8 group's packed-validity tail lanes."""

    def __init__(self, cols: Sequence[ColVal]):
        self.col_group: List[str] = []
        self.col_start: List[int] = []
        self.col_lanes: List[int] = []
        self.col_dtype = [c.values.dtype for c in cols]
        self.valid_bit: List[Optional[int]] = []
        import numpy as np
        n32 = n8 = nbits = 0
        for c in cols:
            w = np.dtype(c.values.dtype).itemsize
            if w in (4, 8):
                grp, lanes, n32 = _U32, w // 4, n32 + w // 4
                self.col_start.append(n32 - w // 4)
            elif w in (1, 2):
                grp, lanes, n8 = _U8, w, n8 + w
                self.col_start.append(n8 - w)
            else:
                raise _Unpackable(f"column width {w} has no lane group")
            self.col_group.append(grp)
            self.col_lanes.append(lanes)
            if c.validity is not None:
                self.valid_bit.append(nbits)
                nbits += 1
            else:
                self.valid_bit.append(None)
        self.n32 = n32
        self.n8_data = n8
        self.n8 = n8 + (nbits + 7) // 8

    @property
    def collectives(self) -> int:
        """Data collectives this plan launches (counts vector excluded)."""
        return (1 if self.n32 else 0) + (1 if self.n8 else 0)


class _Unpackable(Exception):
    """A column the lane packer cannot transport (non-fixed-width)."""


# site -> trace-time lane report ({"collectives", "row_bytes",
# "row_bytes32", "row_bytes8"}): the EXACT wire cost of the program a
# consumer site compiled, recorded by the exchange body itself (it
# alone sees runtime dtypes/nullability).  Keyed by the consumer's jit
# signature, so it persists across consumer reconstruction exactly as
# long as the compiled program does; metrics fall back to the
# conservative estimate only before first trace.
_WIRE_REPORTS: Dict[Hashable, dict] = {}


def wire_report(site) -> Optional[dict]:
    return _WIRE_REPORTS.get(site)


def _ragged_site(site, rp: "RaggedPlan"):
    """Report key for the RAGGED variant of an exchange site: the same
    consumer site compiles distinct uniform/ragged programs (different
    collectives, same jit-sig prefix), so their trace-time reports must
    not overwrite each other.  Derived identically by the exchange body
    (write) and record_exchange_metrics (read)."""
    return None if site is None else (site, "ragged", rp.cache_key())


def _record_wire_report(site, cols, plan, surplus_rounds: int = 0,
                        fallback: bool = False,
                        saved_per_row: int = 0) -> None:
    import numpy as np
    if site is None:
        return
    nullable = sum(1 for c in cols if c.validity is not None)
    if plan is not None:
        # a ragged plan adds one collective-permute per surplus round
        # per width group on top of the base all_to_alls
        collectives = 1 + plan.collectives * (1 + surplus_rounds)
        row_bytes = 4 * plan.n32 + plan.n8
        rb32, rb8 = 4 * plan.n32, plan.n8
    else:
        # per-column wire: one collective per column + mask; validity
        # rides as full bool lanes (1 byte/row), not bit-packed
        collectives = 1 + len(cols) + nullable
        row_bytes = sum(
            max(np.dtype(c.values.dtype).itemsize, 1) for c in cols) \
            + nullable
        rb32, rb8 = 0, 0
    # saved_per_row: bytes/row the wire-encoding narrow transform shaved
    # BEFORE packing — cols already hold the narrowed dtypes, so
    # row_bytes above is the true (post-encoding) wire cost and this
    # field attributes the delta (encodedBytesSaved)
    _WIRE_REPORTS[site] = {"collectives": collectives,
                           "row_bytes": row_bytes,
                           "row_bytes32": rb32, "row_bytes8": rb8,
                           "row_bytes_saved": saved_per_row,
                           "fallback": fallback}


def _narrow_wire_cols(cols: Sequence[ColVal],
                      wire_encode) -> Tuple[List[ColVal], Tuple[int, ...]]:
    """Trace-time wire transform for dictionary-code columns: an int64
    code column ships as ONE i32 lane instead of two (codes are dense
    dictionary ranks, so they fit i32 by construction — the encoders
    bound dictionaries far below 2^31).  Returns the transformed list
    plus the indices actually narrowed (for the inverse widen)."""
    if not wire_encode:
        return list(cols), ()
    out = list(cols)
    narrowed = []
    for i in wire_encode:
        c = out[i]
        if getattr(c.values, "dtype", None) == jnp.int64:
            out[i] = ColVal(c.dtype, c.values.astype(jnp.int32),
                            c.validity, c.offsets)
            narrowed.append(int(i))
    return out, tuple(narrowed)


def _widen_wire_cols(out_cols: List[ColVal],
                     narrowed: Tuple[int, ...]) -> List[ColVal]:
    """Invert :func:`_narrow_wire_cols` on the received columns —
    downstream consumers see the exact int64 code values (dead padding
    rows widen to different-but-dead garbage; validity/in-range masks
    already exclude them)."""
    for i in narrowed:
        c = out_cols[i]
        out_cols[i] = ColVal(c.dtype, c.values.astype(jnp.int64),
                             c.validity, c.offsets)
    return out_cols


def _plan_pack(cols: Sequence[ColVal]) -> Optional[_PackPlan]:
    if not cols:
        return None
    try:
        for c in cols:
            if c.offsets is not None or getattr(c.values, "ndim", 0) != 1:
                raise _Unpackable("offsets / non-vector column")
        return _PackPlan(cols)
    except _Unpackable:
        return None


def _pack_payloads(cols: Sequence[ColVal], plan: _PackPlan, sel=None):
    """Build the (u32, u8) lane payloads.  ``sel`` is an optional gather
    index array (the padded-slot send layout); lanes inherit its shape
    with one trailing lane axis."""

    def take(a):
        return a if sel is None else a[sel]

    lanes32: List[jnp.ndarray] = [None] * plan.n32
    lanes8: List[jnp.ndarray] = [None] * plan.n8
    shape = None
    for c, grp, start, nlanes in zip(cols, plan.col_group, plan.col_start,
                                     plan.col_lanes):
        send = take(c.values)
        shape = send.shape
        if grp == _U32:
            if nlanes == 1:
                lanes32[start] = jax.lax.bitcast_convert_type(
                    send, jnp.uint32)
            else:
                w = jax.lax.bitcast_convert_type(send, jnp.uint32)
                for i in range(nlanes):
                    lanes32[start + i] = w[..., i]
        elif send.dtype == jnp.bool_:
            lanes8[start] = send.astype(jnp.uint8)
        elif nlanes == 1:
            lanes8[start] = jax.lax.bitcast_convert_type(send, jnp.uint8)
        else:
            w = jax.lax.bitcast_convert_type(send, jnp.uint8)
            for i in range(nlanes):
                lanes8[start + i] = w[..., i]
    # validity tail: eight masks per uint8 lane
    for lane in range(plan.n8_data, plan.n8):
        lanes8[lane] = jnp.zeros(shape, dtype=jnp.uint8)
    for c, bit in zip(cols, plan.valid_bit):
        if bit is None:
            continue
        lane = plan.n8_data + bit // 8
        lanes8[lane] = lanes8[lane] | jnp.left_shift(
            take(c.validity).astype(jnp.uint8), jnp.uint8(bit % 8))
    p32 = jnp.stack(lanes32, axis=-1) if lanes32 else None
    p8 = jnp.stack(lanes8, axis=-1) if lanes8 else None
    return p32, p8


def _unpack_payloads(cols: Sequence[ColVal], plan: _PackPlan,
                     flat32, flat8, in_range) -> List[ColVal]:
    """Invert :func:`_pack_payloads` on already index-compacted lane
    matrices (``flat32``: [cap, lanes32], ``flat8``: [cap, lanes8])."""
    out: List[ColVal] = []
    for c, grp, start, nlanes, bit in zip(
            cols, plan.col_group, plan.col_start, plan.col_lanes,
            plan.valid_bit):
        if grp == _U32:
            sub = flat32[:, start:start + nlanes]
            vals = jax.lax.bitcast_convert_type(
                sub[:, 0] if nlanes == 1 else sub, c.values.dtype)
        elif c.values.dtype == jnp.bool_:
            vals = flat8[:, start] != 0
        else:
            sub = flat8[:, start:start + nlanes]
            vals = jax.lax.bitcast_convert_type(
                sub[:, 0] if nlanes == 1 else sub, c.values.dtype)
        validity = None
        if bit is not None:
            bits = jnp.bitwise_and(
                jnp.right_shift(flat8[:, plan.n8_data + bit // 8],
                                jnp.uint8(bit % 8)), jnp.uint8(1))
            validity = jnp.where(in_range, bits != 0, False)
        out.append(ColVal(c.dtype, vals, validity))
    return out


# ---------------------------------------------------------------- exchange --

class WirePayload:
    """The wire-ready send side of one exchange, produced by
    :func:`pack_for_wire` inside the SAME traced program as the compute
    that fed it: partition-sorted columns, narrowed code columns, the
    per-destination counts, and (when the lane packer accepts the
    columns) the (u32, u8) lane payloads in the padded-slot send
    layout.  ``exchange`` composes this with the all_to_all and the
    receive-side unpack; a fused distributed stage emits it without any
    intermediate dispatch boundary."""

    __slots__ = ("cols", "narrowed", "counts", "starts", "src",
                 "plan", "p32", "p8")

    def __init__(self, cols, narrowed, counts, starts, src, plan,
                 p32, p8):
        self.cols = cols
        self.narrowed = narrowed
        self.counts = counts
        self.starts = starts
        self.src = src
        self.plan = plan
        self.p32 = p32
        self.p8 = p8


def pack_for_wire(cols: Sequence[ColVal], pids: jnp.ndarray, nrows,
                  num_parts: int, slot: int,
                  packed: bool = True,
                  wire_encode: Sequence[int] = ()) -> WirePayload:
    """Composable traced lane packer: everything the send side of an
    exchange does before the collective — layout_by_partition, wire
    narrowing, padded-slot gather indices, bitcast lane payloads and
    packed validity tails — as one traceable function.  Callers fuse
    it into the producing program so the stage's compute and its
    wire-ready payload come out of ONE dispatch per shard; ``plan`` is
    None when the columns are unpackable (or ``packed`` is False) and
    the caller ships per-column."""
    capacity = pids.shape[0]
    sorted_cols, counts, starts = layout_by_partition(
        cols, pids, nrows, num_parts)
    sorted_cols, narrowed = _narrow_wire_cols(sorted_cols, wire_encode)
    j = jnp.arange(slot, dtype=jnp.int32)[None, :]
    src = jnp.clip(starts[:, None] + j, 0, capacity - 1)
    plan = _plan_pack(sorted_cols) if packed else None
    p32 = p8 = None
    if plan is not None:
        p32, p8 = _pack_payloads(sorted_cols, plan, sel=src)
    return WirePayload(sorted_cols, narrowed, counts, starts, src,
                       plan, p32, p8)


def _compaction_indices(recv_counts, total, num_parts: int, slot: int):
    """Slice→dense map shared by every lane/column of one exchange:
    for each dense output position, the (source slice, offset) it reads
    and whether it is a live row."""
    recv_starts = jnp.concatenate(
        [jnp.zeros(1, dtype=jnp.int32), jnp.cumsum(recv_counts)[:-1]])
    pos = jnp.arange(num_parts * slot, dtype=jnp.int32)
    part = jnp.searchsorted(recv_starts, pos, side="right") - 1
    part = jnp.clip(part, 0, num_parts - 1)
    offset = jnp.clip(pos - recv_starts[part], 0, slot - 1)
    in_range = pos < total
    return part, offset, in_range


def exchange(cols: Sequence[ColVal], pids: jnp.ndarray, nrows,
             axis_name: str, num_parts: int,
             slot: Optional[int] = None,
             packed: Optional[bool] = None,
             with_overflow: bool = False,
             report_site=None,
             ragged: Optional[RaggedPlan] = None,
             wire_encode: Sequence[int] = ()):
    """All-to-all exchange inside shard_map.

    Every shard sends row r to shard ``pids[r]``.  Returns (received
    cols, received nrows) — plus a per-shard overflow flag (any local
    (src, dst) slice larger than ``slot``, i.e. rows were dropped and
    the launch must be re-run with a bigger slot) when
    ``with_overflow`` is set.  Received capacity is
    ``num_parts * slot``.  Only fixed-width columns (strings must be
    dictionary-encoded upstream).

    ``packed`` selects the fused lane-payload wire format (module
    docstring); None resolves the session conf.  Callers that jit-cache
    programs containing this body must bake the resolved flag into
    their cache signature.

    The "shuffle.exchange" injection point does NOT fire here: this
    body runs at trace time (and not at all on a jit-cache hit), and a
    launch with several exchanges (shuffle join) would multi-fire.
    ``launch_checkpoint`` above is the single host-side checkpoint per
    exchange-bearing program launch — callers invoke it right before
    dispatching the compiled program.
    """
    capacity = pids.shape[0]
    slot = slot or capacity
    if packed is None:
        packed = packed_enabled()
    # the send side — partition layout, wire narrowing (compressed
    # wire narrows caller-marked code columns AFTER partitioning and
    # BEFORE lane packing, so every wire variant ships the narrow form
    # and the trace-time report meters post-encoding bytes), padded-
    # slot gather and lane payloads — is the composable packer; fused
    # stages emit it from the producing program directly
    pay = pack_for_wire(cols, pids, nrows, num_parts, slot,
                        packed=packed, wire_encode=wire_encode)
    sorted_cols, narrowed = pay.cols, pay.narrowed
    counts, starts, src, plan = pay.counts, pay.starts, pay.src, pay.plan
    saved_pr = 4 * len(narrowed)

    # counts for my slices on every peer: all_to_all of the counts vector
    recv_counts = jax.lax.all_to_all(
        counts.reshape(num_parts, 1), axis_name, split_axis=0,
        concat_axis=0).reshape(num_parts)
    if ragged is not None and plan is not None:
        # skew-adaptive ragged wire (needs the lane-packed format; an
        # unpackable column set falls through to the uniform slot the
        # caller also passed — trace-time consistent either way)
        _record_wire_report(_ragged_site(report_site, ragged),
                            sorted_cols, plan,
                            surplus_rounds=len(ragged.rounds),
                            saved_per_row=saved_pr)
        res = _exchange_ragged(sorted_cols, plan, counts, recv_counts,
                               starts, capacity, axis_name, num_parts,
                               ragged, with_overflow)
        if with_overflow:
            rcols, rtotal, rovf = res
            return _widen_wire_cols(rcols, narrowed), rtotal, rovf
        rcols, rtotal = res
        return _widen_wire_cols(rcols, narrowed), rtotal
    if ragged is not None:
        # ragged was requested but the lane packer refused the columns:
        # this program runs the uniform per-column wire at the caller's
        # fallback slot.  Mark the RAGGED report key at trace time so
        # consumer accounting bills the program that actually moved
        # bytes (the plain-site report may belong to a different
        # variant compiled at the same signature).
        _record_wire_report(_ragged_site(report_site, ragged),
                            sorted_cols, None, fallback=True,
                            saved_per_row=saved_pr)

    total = recv_counts.sum()
    # the slice→dense compaction map, computed ONCE and shared by every
    # lane (packed) or column (fallback)
    part, offset, in_range = _compaction_indices(
        recv_counts, total, num_parts, slot)

    _record_wire_report(report_site, sorted_cols, plan,
                        saved_per_row=saved_pr)
    if packed and plan is None and cols:
        # trace-time breadcrumb: the fused wire was requested but these
        # columns are unpackable, so this program runs per-column
        # collectives.  Counted here (not at the consumer, which only
        # knows the conf flag) so perColumnFallbacks — and the
        # profiling health check built on it — reflects the EFFECTIVE
        # wire format.  Trace-time means once per compiled program, not
        # per launch; a nonzero count is the signal, not a launch tally.
        metrics_for_session().record_fallback()
    if plan is not None:
        p32, p8 = pay.p32, pay.p8
        flat32 = flat8 = None
        if p32 is not None:
            r32 = jax.lax.all_to_all(p32, axis_name, split_axis=0,
                                     concat_axis=0)
            flat32 = r32[part, offset]
        if p8 is not None:
            r8 = jax.lax.all_to_all(p8, axis_name, split_axis=0,
                                    concat_axis=0)
            flat8 = r8[part, offset]
        out_cols = _unpack_payloads(sorted_cols, plan, flat32, flat8,
                                    in_range)
    else:
        out_cols = []
        for c in sorted_cols:
            recv = jax.lax.all_to_all(c.values[src], axis_name,
                                      split_axis=0, concat_axis=0)
            flat = recv[part, offset]
            validity = None
            if c.validity is not None:
                vrecv = jax.lax.all_to_all(c.validity[src], axis_name,
                                           split_axis=0, concat_axis=0)
                validity = jnp.where(in_range, vrecv[part, offset], False)
            out_cols.append(ColVal(c.dtype, flat, validity))
    out_cols = _widen_wire_cols(out_cols, narrowed)
    if with_overflow:
        return out_cols, total, jnp.any(counts > slot)
    return out_cols, total


def _exchange_ragged(sorted_cols, plan, counts, recv_counts, starts,
                     capacity, axis_name: str, num_parts: int,
                     rp: RaggedPlan, with_overflow: bool):
    """Ragged exchange body: base all_to_all at the cold slot plus one
    collective-permute round per partial permutation of hot pairs.
    Every shard traces the same program (SPMD); per-shard differences
    ride static tables indexed by ``axis_index``.  A slice exceeding
    its static limit (base + surplus for hot pairs, base for cold)
    raises the overflow flag — the caller's full-capacity re-run rung,
    rows are never dropped."""
    base, sur = rp.base_slot, rp.surplus_slot
    me = jax.lax.axis_index(axis_name)
    cap_out = rp.out_capacity

    total = recv_counts.sum()
    recv_starts = jnp.concatenate(
        [jnp.zeros(1, dtype=jnp.int32),
         jnp.cumsum(recv_counts)[:-1].astype(jnp.int32)])
    pos = jnp.arange(cap_out, dtype=jnp.int32)
    part = jnp.searchsorted(recv_starts, pos, side="right") - 1
    part = jnp.clip(part, 0, num_parts - 1)
    offset = jnp.clip(pos - recv_starts[part], 0, base + sur - 1)
    in_range = pos < total

    # base payloads: the uniform wire at the COLD slot
    j = jnp.arange(base, dtype=jnp.int32)[None, :]
    src = jnp.clip(starts[:, None] + j, 0, capacity - 1)
    p32, p8 = _pack_payloads(sorted_cols, plan, sel=src)
    r32 = jax.lax.all_to_all(p32, axis_name, split_axis=0,
                             concat_axis=0) if p32 is not None else None
    r8 = jax.lax.all_to_all(p8, axis_name, split_axis=0,
                            concat_axis=0) if p8 is not None else None

    # surplus rounds: each round is a partial permutation; a shard not
    # in the round still traces the (garbage) buffer but the
    # collective-permute transmits only the named links
    s32_rounds, s8_rounds = [], []
    jj = jnp.arange(sur, dtype=jnp.int32)
    for r, rnd in enumerate(rp.rounds):
        my_dst = jnp.asarray(rp.round_dst_by_src[r])[me]
        sel = jnp.clip(starts[my_dst] + base + jj, 0, capacity - 1)
        q32, q8 = _pack_payloads(sorted_cols, plan, sel=sel)
        perm = [tuple(p) for p in rnd]
        if q32 is not None:
            s32_rounds.append(jax.lax.ppermute(q32, axis_name, perm=perm))
        if q8 is not None:
            s8_rounds.append(jax.lax.ppermute(q8, axis_name, perm=perm))

    # receive: offset < base reads the all_to_all slice; beyond it, the
    # surplus buffer of the (src -> me) pair via the static round table
    my_rounds = jnp.asarray(rp.round_for_src)[me]     # [n_src]
    sur_round = my_rounds[part]
    so = jnp.clip(offset - base, 0, sur - 1)

    def combine(rbase, rounds_list):
        if rbase is None:
            return None
        if rounds_list:
            stacked = jnp.stack(rounds_list)          # [rounds, sur, l]
        else:
            stacked = jnp.zeros((1, sur) + rbase.shape[2:], rbase.dtype)
        base_v = rbase[part, jnp.clip(offset, 0, base - 1)]
        sur_v = stacked[sur_round, so]
        pick = (offset < base)
        return jnp.where(pick[:, None], base_v, sur_v)

    flat32 = combine(r32, s32_rounds)
    flat8 = combine(r8, s8_rounds)
    out_cols = _unpack_payloads(sorted_cols, plan, flat32, flat8,
                                in_range)
    if with_overflow:
        limits = jnp.asarray(rp.limits)[me]           # [n_dst]
        return out_cols, total, jnp.any(counts > limits)
    return out_cols, total


def exchange_via_gather(cols: Sequence[ColVal], pids: jnp.ndarray, nrows,
                        axis_name: str, num_parts: int,
                        packed: Optional[bool] = None,
                        with_overflow: bool = False,
                        report_site=None,
                        wire_encode: Sequence[int] = ()):
    """Gather-then-redistribute exchange: ONE all_gather per width
    group (rows + their destination ids), then every shard compacts its
    own rows locally — no all_to_all on the wire.  Fewer, larger
    transfers: the DCN-friendly strategy topology-auto picks for axes
    spanning hosts/slices ("Theseus" data-movement shape; see
    docs/performance.md "Topology-aware collective selection").  Slot
    planning does not apply (the gather moves full capacity), so the
    overflow flag is constant-false."""
    from spark_rapids_tpu.columnar import dtypes as dts
    from spark_rapids_tpu.ops import selection
    pid_col = ColVal(dts.INT32, pids.astype(jnp.int32))
    gathered, total = all_gather_cols(
        list(cols) + [pid_col], nrows, axis_name, num_parts,
        packed=packed, report_site=report_site,
        wire_encode=wire_encode)
    out_pids = gathered[-1].values
    me = jax.lax.axis_index(axis_name)
    cap = out_pids.shape[0]
    keep = jnp.logical_and(out_pids == me,
                           jnp.arange(cap, dtype=jnp.int32) < total)
    out_cols, n_mine = selection.compact(list(gathered[:-1]), keep)
    n_mine = n_mine.astype(jnp.int32)
    if with_overflow:
        return out_cols, n_mine, jnp.zeros((), dtype=jnp.bool_)
    return out_cols, n_mine


def all_gather_cols(cols: Sequence[ColVal], nrows, axis_name: str,
                    num_parts: int,
                    packed: Optional[bool] = None,
                    report_site=None,
                    wire_encode: Sequence[int] = ()
                    ) -> Tuple[List[ColVal], jnp.ndarray]:
    """Broadcast-style collective: every shard receives every shard's rows.

    The TPU analog of GpuBroadcastExchangeExec (one-to-all replication,
    SURVEY.md section 2.4 "Exchanges") — except all-gather is symmetric, so
    "broadcast" of a small table costs one collective, no driver round trip.
    Rides the same lane-packed wire format as ``exchange``: one
    ``all_gather`` per width group instead of one per column + mask.
    """
    capacity = cols[0].values.shape[0] if cols else 0
    if packed is None:
        packed = packed_enabled()
    cols, narrowed = _narrow_wire_cols(cols, wire_encode)
    counts = jax.lax.all_gather(nrows, axis_name)  # [num_parts]
    starts = jnp.concatenate([jnp.zeros(1, dtype=jnp.int32),
                              jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    total = counts.sum()
    cap = num_parts * capacity
    pos = jnp.arange(cap, dtype=jnp.int32)
    part = jnp.searchsorted(starts, pos, side="right") - 1
    part = jnp.clip(part, 0, num_parts - 1)
    offset = jnp.clip(pos - starts[part], 0, capacity - 1)
    in_range = pos < total
    plan = _plan_pack(cols) if packed else None
    _record_wire_report(report_site, cols, plan,
                        saved_per_row=4 * len(narrowed))
    if packed and plan is None and cols:
        metrics_for_session().record_fallback()  # see exchange()
    if plan is not None:
        p32, p8 = _pack_payloads(cols, plan)
        flat32 = flat8 = None
        if p32 is not None:
            flat32 = jax.lax.all_gather(p32, axis_name)[part, offset]
        if p8 is not None:
            flat8 = jax.lax.all_gather(p8, axis_name)[part, offset]
        return _widen_wire_cols(
            _unpack_payloads(cols, plan, flat32, flat8, in_range),
            narrowed), total
    out_cols: List[ColVal] = []
    for c in cols:
        g = jax.lax.all_gather(c.values, axis_name)  # [num_parts, capacity]
        flat = g[part, offset]
        validity = None
        if c.validity is not None:
            gv = jax.lax.all_gather(c.validity, axis_name)
            validity = jnp.where(in_range, gv[part, offset], False)
        out_cols.append(ColVal(c.dtype, flat, validity))
    return _widen_wire_cols(out_cols, narrowed), total


# ------------------------------------------------------------- slot planner --

class SlotPlanner:
    """Per-exchange-site all-to-all slot sizing.

    One instance per session (``planner_for_session``), one entry per
    exchange *site* (the consumer's jit signature).  Modes
    (spark.rapids.tpu.shuffle.slot.mode):

    * ``adaptive`` (default) — slots come from the launch's histogram
      max smoothed with a per-site EMA of observed maxima, so the
      power-of-two bucket is STICKY across launches (a stable slot is a
      stable jit-cache key — no recompile churn when data sizes wobble).
      Warm sites may also launch *speculatively*: skip the stats
      hostsync, reuse the cached slot (and bucket LUT), and verify a
      per-shard overflow flag after the launch — at most ONE budgeted
      hostsync per exchange site either way.
    * ``fixed`` — every launch sized from its own histogram only (the
      pre-EMA behavior; recompiles whenever the bucket moves).
    * ``capacity`` — full-capacity padding, always correct,
      ``num_parts``x the useful bytes on the wire (the A/B baseline).

    A speculative overflow multiplies the site's EMA by
    ``slot.overflowGrowth`` and disables speculation until the next
    observed (stats-sized) launch re-arms it.  Warm sites also return
    to the stats-sized path every ``REFRESH_EVERY`` speculative
    launches so the EMA keeps sampling — without the refresh a site
    that once saw a skewed batch would ship its inflated slot forever
    (successful speculative launches observe nothing).
    """

    REFRESH_EVERY = 16

    def __init__(self, mode: str = "adaptive", growth: float = 2.0):
        self.mode = mode
        self.growth = growth
        self._lock = threading.Lock()
        self.sites: Dict[Hashable, dict] = {}

    def plan(self, site: Hashable, max_slice: int, capacity: int) -> int:
        """Slot for a stats-sized launch (histogram max in hand)."""
        if self.mode == "capacity":
            return capacity
        if self.mode == "fixed":
            return pick_slot(max_slice, capacity)
        with self._lock:
            e = self.sites.get(site)
            ema = e["ema"] if e and e.get("capacity") == capacity else 0.0
        if not ema:
            # cold site + cost model: seed the EMA from the persisted
            # rows x skew evidence so a warm START lands in the same
            # power-of-two bucket (= same jit key) as the last process
            from spark_rapids_tpu.plan.costmodel import active_model
            cm = active_model()
            if cm is not None:
                prior = cm.slot_prior(site)
                if 0 < prior <= capacity:
                    ema = float(prior)
        return pick_slot(max(int(max_slice), int(ema)), capacity)

    def observe(self, site: Hashable, max_slice: int, slot: int,
                capacity: int, lut=None, rows: int = 0) -> None:
        """Record a stats-sized launch: update the EMA, cache the slot
        (+ optional bucket LUT) for speculative reuse, clear any
        overflow latch."""
        with self._lock:
            e = self.sites.setdefault(site, {})
            prev = e.get("ema", 0.0)
            e["ema"] = float(max_slice) if not prev else \
                0.7 * prev + 0.3 * float(max_slice)
            e["slot"] = slot
            e["capacity"] = capacity
            e["rows"] = rows
            if lut is not None:
                e["lut"] = lut
            e.pop("overflowed", None)
        from spark_rapids_tpu.utils import tracing
        if tracing._armed and rows:
            # per-site evidence for the observation store (ROADMAP
            # item 3 producer): observed rows, hottest-slice fraction
            # (1.0 = every row in one (src,dst) slice), and — once the
            # exchange body has trace-reported its lane layout — the
            # payload bytes this site moves per launch
            fields = {"rows": float(rows),
                      "skew": round(max_slice / max(rows, 1), 4)}
            rep = wire_report(site)
            if rep:
                fields["bytes"] = float(rows * rep["row_bytes"])
            tracing.observe_site(site, **fields)

    def speculative(self, site: Hashable, capacity: int
                    ) -> Optional[dict]:
        """Steady-state entry for a warm adaptive site (slot + cached
        LUT), or None when the site must run the stats hostsync: cold,
        capacity changed, non-adaptive mode, an unresolved overflow, or
        the periodic EMA refresh (every REFRESH_EVERY warm launches)."""
        if self.mode != "adaptive":
            return None
        with self._lock:
            e = self.sites.get(site)
            if not e or e.get("capacity") != capacity or \
                    e.get("overflowed") or "slot" not in e:
                return None
            e["warm"] = e.get("warm", 0) + 1
            if e["warm"] % self.REFRESH_EVERY == 0:
                return None  # periodic re-observation keeps the EMA live
            return dict(e)

    def observe_overflow(self, site: Hashable) -> None:
        """A speculative slot dropped rows: grow the EMA by the
        configured factor and force the next launch back onto the
        stats-sized path."""
        with self._lock:
            e = self.sites.setdefault(site, {})
            e["overflowed"] = True
            e["ema"] = max(e.get("ema", 0.0) * self.growth,
                           e.get("slot", 8) * self.growth)


_default_planner = SlotPlanner()
_default_metrics = None  # built lazily below


def planner_for_session(session=None) -> SlotPlanner:
    """The session's SlotPlanner (created on first use; mode/growth
    re-read from the conf each call so tests can flip them live).
    Without an active session (bare kernel tests) a process-global
    default planner is shared."""
    if session is None:
        from spark_rapids_tpu.api.session import TpuSession
        session = TpuSession._active
    if session is None:
        return _default_planner
    from spark_rapids_tpu.config import rapids_conf as rc
    p = getattr(session, "shuffle_planner", None)
    if p is None:
        p = SlotPlanner()
        session.shuffle_planner = p
    p.mode = session.conf.get(rc.SHUFFLE_SLOT_MODE)
    p.growth = session.conf.get(rc.SHUFFLE_SLOT_OVERFLOW_GROWTH)
    return p


# ---------------------------------------------------------- wire observability --

class ShuffleWireMetrics:
    """Cumulative shuffle-wire counters (one per session; process-global
    fallback for bare kernel use).  Exchange consumers record each
    launch host-side; per-query deltas land in the QueryEnd ``shuffle``
    dict → eventlog ``QueryInfo.shuffle`` → profiling health checks."""

    FIELDS = ("exchanges", "collectives", "rowsMoved", "rowsUseful",
              "bytesMoved", "slotOverflowRetries", "perColumnFallbacks",
              "raggedExchanges", "encodedBytesSaved", "wireDictBytes",
              "encodableDecodedExchanges", "wireDictFallbacks",
              "fusedWireDispatches", "unfusedWireDispatches")

    def __init__(self):
        self._lock = threading.Lock()
        self.counters = {k: 0 for k in self.FIELDS}
        # per-width-group and per-destination breakdowns (padding is a
        # property of a destination's slot, not of the exchange as a
        # whole — one hot destination must not hide behind the mean)
        self.per_group: Dict[str, Dict[str, int]] = {}
        self.per_dest: Dict[str, Dict[str, int]] = {}
        # payload bytes of the most recently recorded exchange — the
        # launch whose lane buffers are still resident, which is what
        # the transient_wire_bytes HBM reservation should reflect (a
        # query's CUMULATIVE bytes would overstate it several-fold on
        # multi-exchange plans; earlier payloads were already reused)
        self.last_exchange_bytes = 0

    def record_exchange(self, collectives: int, rows_moved: int,
                        rows_useful: int, bytes_moved: int,
                        packed: bool = True, ragged: bool = False,
                        group_bytes: Optional[Dict[str, int]] = None,
                        per_dest=None, encoded_saved: int = 0) -> None:
        with self._lock:
            c = self.counters
            c["exchanges"] += 1
            c["collectives"] += int(collectives)
            c["rowsMoved"] += int(rows_moved)
            c["rowsUseful"] += int(rows_useful)
            c["bytesMoved"] += int(bytes_moved)
            c["encodedBytesSaved"] += int(encoded_saved)
            if ragged:
                c["raggedExchanges"] += 1
            self.last_exchange_bytes = int(bytes_moved)
            if not packed:
                c["perColumnFallbacks"] += 1
            for g, b in (group_bytes or {}).items():
                e = self.per_group.setdefault(
                    g, {"bytesMoved": 0, "rowsMoved": 0})
                e["bytesMoved"] += int(b)
                e["rowsMoved"] += int(rows_moved)
            for d, (wire, useful) in (per_dest or {}).items():
                e = self.per_dest.setdefault(
                    str(d), {"rowsMoved": 0, "rowsUseful": 0})
                e["rowsMoved"] += int(wire)
                e["rowsUseful"] += int(useful)

    def record_overflow(self) -> None:
        with self._lock:
            self.counters["slotOverflowRetries"] += 1

    def record_encodable_decoded(self) -> None:
        """An exchange whose payload carries dictionary-code columns
        ran with wire encoding OFF — bytes that were free to crush
        shipped wide (the profiling health-check signal)."""
        with self._lock:
            self.counters["encodableDecodedExchanges"] += 1

    def record_wire_dict(self, delta_bytes: int, ok: bool) -> None:
        """One dictionary-delta broadcast for an encoded exchange
        launch (ok=False: the delta frame failed verification and the
        launch degraded to the wide wire)."""
        with self._lock:
            self.counters["wireDictBytes"] += int(delta_bytes)
            if not ok:
                self.counters["wireDictFallbacks"] += 1

    def record_fused_dispatch(self, fused: bool) -> None:
        """One distributed-stage launch: ``fused`` means the stage's
        compute and its wire-ready payload came out of ONE program per
        shard (fusion.wire.enabled warm path); unfused launches ran the
        two-dispatch local+exchange sequence.  Bench emits the pair as
        ``fused_wire_dispatches`` per distributed emission."""
        with self._lock:
            key = "fusedWireDispatches" if fused \
                else "unfusedWireDispatches"
            self.counters[key] += 1

    def record_fallback(self) -> None:
        """An exchange that requested the packed wire but traced the
        per-column path (unpackable columns).  Fired at trace time by
        the exchange body itself — once per compiled program."""
        with self._lock:
            self.counters["perColumnFallbacks"] += 1

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self.counters)
            out["perGroup"] = {g: dict(v)
                               for g, v in self.per_group.items()}
            out["perDestination"] = {d: dict(v)
                                     for d, v in self.per_dest.items()}
            return out

    @staticmethod
    def delta(after: Dict[str, int], before: Dict[str, int]
              ) -> Dict[str, int]:
        out = {}
        for k, v in after.items():
            if isinstance(v, dict):
                b = before.get(k, {}) or {}
                out[k] = {
                    sub: {f: sv.get(f, 0) - b.get(sub, {}).get(f, 0)
                          for f in sv}
                    for sub, sv in v.items()}
            else:
                out[k] = v - before.get(k, 0)
        return out

    @staticmethod
    def summarize(d: Dict[str, int]) -> Dict[str, float]:
        """Attach the derived padding ratios (wire rows / useful rows —
        1.0 is a perfectly dense exchange, ``num_parts`` is
        full-capacity padding): the aggregate, plus the per-width-group
        and per-destination breakdowns when recorded."""
        out = dict(d)
        out["paddingRatio"] = round(
            d.get("rowsMoved", 0) / max(d.get("rowsUseful", 0), 1), 3)
        pd_ = d.get("perDestination") or {}
        if pd_:
            out["paddingRatioPerDestination"] = {
                k: round(v.get("rowsMoved", 0)
                         / max(v.get("rowsUseful", 0), 1), 3)
                for k, v in sorted(pd_.items(), key=lambda kv: int(kv[0]))}
        pg = d.get("perGroup") or {}
        if pg:
            out["perGroupBytes"] = {g: v.get("bytesMoved", 0)
                                    for g, v in sorted(pg.items())}
        saved = d.get("encodedBytesSaved", 0)
        if saved:
            # decoded-wire bytes / encoded-wire bytes (>= 1.0): the
            # headline wire-compression number bench emits
            out["wireCompressionRatio"] = round(
                (d.get("bytesMoved", 0) + saved)
                / max(d.get("bytesMoved", 0), 1), 3)
        return out


def metrics_for_session(session=None) -> ShuffleWireMetrics:
    global _default_metrics
    if session is None:
        from spark_rapids_tpu.api.session import TpuSession
        session = TpuSession._active
    if session is None:
        if _default_metrics is None:
            _default_metrics = ShuffleWireMetrics()
        return _default_metrics
    m = getattr(session, "shuffle_metrics", None)
    if m is None:
        m = ShuffleWireMetrics()
        session.shuffle_metrics = m
    return m


class WireDictBroadcast:
    """Once-per-exchange dictionary DELTA broadcast for the compressed
    wire (one instance per session).

    An encoded exchange ships i32 codes; the receive side's eventual
    decode needs the dictionary.  On a single-controller mesh the
    dictionary is host-shared, so what actually moves is the DELTA —
    the entries this exchange *site* has not broadcast yet — and this
    registry makes that edge real: the delta serializes through the
    shared frame codec (a real compressed payload, accounted as
    ``wireDictBytes``), passes the ``shuffle.wire.dict`` fire_mutate
    chaos point, and round-trips with a crc32 gate.  A delta frame
    that fails verification degrades THAT launch to the wide
    (unnarrowed) wire, emits a typed ``EncodedWireInvalid`` event, and
    resets the site so the next launch rebroadcasts the full
    dictionary — exact results either way, never wrong bytes."""

    def __init__(self):
        self._lock = threading.Lock()
        # site -> per-column (entries_broadcast, crc_of_those_entries,
        # last_seen_list): holding the REFERENCE (not id() — CPython
        # recycles addresses after GC) lets the steady-state launch
        # (same dictionary object, nothing appended) skip hashing
        # entirely, and the crc chains incrementally over the delta —
        # per-launch host work is O(delta), not O(dictionary)
        self.sent: Dict[Hashable, List[tuple]] = {}

    @staticmethod
    def _crc(entries, start: int = 0) -> int:
        import zlib
        crc = start
        for v in entries:
            b = b"\x00" if v is None else v.encode("utf-8")
            crc = zlib.crc32(len(b).to_bytes(4, "big") + b, crc)
        return crc & 0xFFFFFFFF

    def broadcast(self, site, dicts, codec_level: int = 2
                  ) -> Tuple[int, bool]:
        """(delta_bytes, ok) for one encoded launch at ``site`` over
        the exchange's code-column dictionaries."""
        import numpy as np
        import zlib
        from spark_rapids_tpu import native
        from spark_rapids_tpu.robustness.inject import fire_mutate
        with self._lock:
            state = self.sent.get(site)
            if state is None or len(state) != len(dicts):
                state = [(0, 0, None)] * len(dicts)
            deltas = []
            new_state = []
            for (n_sent, crc_sent, last_ref), d in zip(state, dicts):
                if last_ref is d and n_sent <= len(d):
                    # the SAME append-only list: identity proves the
                    # sent prefix unchanged — no prefix re-hash; an
                    # unchanged length is a zero-cost empty delta
                    if len(d) == n_sent:
                        deltas.append([])
                        new_state.append((n_sent, crc_sent, last_ref))
                        continue
                elif n_sent > len(d) or \
                        self._crc(d[:n_sent]) != crc_sent:
                    # the dictionary diverged from what this site
                    # already broadcast (a different query's dict at
                    # the same site): full rebroadcast
                    n_sent, crc_sent = 0, 0
                deltas.append(list(d[n_sent:]))
                # chain the crc over ONLY the delta entries
                new_state.append((len(d),
                                  self._crc(d[n_sent:], crc_sent), d))
        flat = [v for delta in deltas for v in delta]
        payload = b"\x00".join(
            b"\x01" if v is None else v.encode("utf-8") for v in flat)
        want_crc = zlib.crc32(payload) & 0xFFFFFFFF
        blob = b""
        ok = True
        if payload:
            blob = native.serialize_batch(
                1, [(0, np.frombuffer(payload, dtype=np.uint8), None,
                     None)], compress=codec_level)
            blob = fire_mutate("shuffle.wire.dict", blob)
            try:
                _, cols = native.deserialize_batch(blob)
                got = cols[0][1]
                got_crc = zlib.crc32(
                    b"" if got is None else got.tobytes()) & 0xFFFFFFFF
                ok = got_crc == want_crc
            except Exception:
                ok = False
        with self._lock:
            if ok:
                self.sent[site] = new_state
            else:
                # force a full rebroadcast next launch; this launch
                # ships wide
                self.sent.pop(site, None)
        return len(blob), ok


_default_wire_dicts: Optional[WireDictBroadcast] = None


def wire_dicts_for_session(session=None) -> WireDictBroadcast:
    global _default_wire_dicts
    if session is None:
        from spark_rapids_tpu.api.session import TpuSession
        session = TpuSession._active
    if session is None:
        if _default_wire_dicts is None:
            _default_wire_dicts = WireDictBroadcast()
        return _default_wire_dicts
    w = getattr(session, "wire_dicts", None)
    if w is None:
        w = WireDictBroadcast()
        session.wire_dicts = w
    return w


def broadcast_wire_dicts(site, dicts, metrics) -> bool:
    """Consumer-side helper: run the dictionary-delta broadcast for an
    encoded launch, account the bytes, and on a failed verification
    emit the typed event and report False (the caller launches the
    wide-wire program variant)."""
    if not dicts:
        return True
    from spark_rapids_tpu import native
    delta_bytes, ok = wire_dicts_for_session().broadcast(
        site, dicts, codec_level=native.frame_codec_level())
    metrics.record_wire_dict(delta_bytes, ok)
    if not ok:
        from spark_rapids_tpu.utils.events import emit_on_session
        emit_on_session("EncodedWireInvalid", site=str(site),
                        deltaBytes=delta_bytes)
    return ok


def wire_row_bytes(dtypes, nullable: Optional[int] = None) -> int:
    """Estimated wire bytes per row for a column set (data lanes plus
    bit-packed validity; ``nullable`` defaults to every column, an
    upper bound — exact nullability is a trace-time property)."""
    import numpy as np
    data = sum(max(np.dtype(dt.storage).itemsize, 1) for dt in dtypes)
    n = len(dtypes) if nullable is None else nullable
    return data + (n + 7) // 8


def estimate_collectives(dtypes, packed: bool,
                         nullable: Optional[int] = None) -> int:
    """Collectives one exchange launches: the counts vector plus one per
    width group (packed) or one per column + validity mask (fallback)."""
    import numpy as np
    n = len(dtypes) if nullable is None else nullable
    if not packed:
        return 1 + len(dtypes) + n
    widths = [np.dtype(dt.storage).itemsize for dt in dtypes]
    has32 = any(w in (4, 8) for w in widths)
    has8 = any(w in (1, 2) for w in widths) or n > 0
    return 1 + int(has32) + int(has8)


def record_exchange_metrics(metrics: ShuffleWireMetrics, *, dtypes,
                            slot: int, num_parts: int, nshards: int,
                            rows_useful: int, packed: bool,
                            nullable: Optional[int] = None,
                            site=None, exchanges: int = 1,
                            ragged: Optional[RaggedPlan] = None,
                            counts=None,
                            wire_encode_cols: int = 0) -> None:
    """One consumer-side accounting call per exchange launch: wire rows
    are the padded slots every shard puts on ICI (for a ragged plan,
    the base slots plus each surplus pair's one transmitted buffer);
    useful rows come from the site's histogram (or the planner's last
    observation on speculative launches).  When the site's compiled
    program recorded its trace-time lane report (``report_site`` on the
    exchange), the EXACT collective count and row bytes are used; the
    all-nullable static estimate only covers launches before first
    trace.  ``counts`` (the [src, dst] histogram, when materialized)
    feeds the per-destination padding breakdown."""
    import numpy as np
    rep = None
    if ragged is not None:
        rep = wire_report(_ragged_site(site, ragged))
        if rep is not None and rep.get("fallback"):
            # the compiled program fell back to the uniform wire (the
            # lane packer refused the columns — exchange() takes the
            # ragged branch only when packing succeeds): account the
            # program that actually moved bytes.  The exchange body
            # marks the RAGGED report key ``fallback`` at trace time;
            # that breadcrumb is the ONLY valid evidence — the plain
            # -site report may belong to a different variant compiled
            # at the same signature (e.g. a uniform-slot session), and
            # accounting runs before the launch, so a first launch
            # trusts the caller's plan until the program traces.
            # Callers that sized the program from the plan pass slot=0;
            # the fallback program ran at the plan's base+surplus
            # upper bound.
            slot = slot or (ragged.base_slot + ragged.surplus_slot)
            ragged = None
    if ragged is not None:
        rows_moved = ragged.wire_rows(nshards) * exchanges
    else:
        rows_moved = nshards * num_parts * slot * exchanges
        if rep is None:
            rep = wire_report(site)
    if rep is not None:
        collectives = rep["collectives"]
        row_bytes = rep["row_bytes"]
        rb32, rb8 = rep.get("row_bytes32", 0), rep.get("row_bytes8", 0)
        saved_pr = rep.get("row_bytes_saved", 0)
    else:
        collectives = estimate_collectives(dtypes, packed, nullable)
        # pre-trace estimate: each wire-encoded int64 code column ships
        # one i32 lane instead of two
        saved_pr = 4 * int(wire_encode_cols)
        row_bytes = max(wire_row_bytes(dtypes, nullable) - saved_pr, 0)
        rb32 = rb8 = 0
    if rb32 or rb8:
        group_bytes = {g: rows_moved * rb
                       for g, rb in (("u32", rb32), ("u8", rb8)) if rb}
    else:
        group_bytes = {"percol": rows_moved * row_bytes}
    per_dest = None
    if counts is not None:
        counts = np.asarray(counts)
        per_dest = {}
        for d in range(counts.shape[1]):
            if ragged is not None:
                pairs_to_d = sum(1 for _, dd in ragged.pairs if dd == d)
                wire = (nshards * ragged.base_slot
                        + pairs_to_d * ragged.surplus_slot) * exchanges
            else:
                wire = nshards * slot * exchanges
            per_dest[d] = (wire, int(counts[:, d].sum()) * exchanges)
    metrics.record_exchange(
        collectives=collectives * exchanges,
        rows_moved=rows_moved,
        rows_useful=int(rows_useful),
        bytes_moved=rows_moved * row_bytes,
        packed=packed, ragged=ragged is not None,
        group_bytes=group_bytes, per_dest=per_dest,
        encoded_saved=rows_moved * saved_pr)
