"""Shuffle as an SPMD collective: padded ragged all-to-all over the mesh.

This replaces the reference's entire UCX transport stack (shuffle-plugin/,
RapidsShuffleClient/Server, bounce buffers, heartbeats — SURVEY.md section
2.5): instead of point-to-point pull with metadata requests, every shard
partitions its rows by destination, lays them out contiguously, and one
``lax.all_to_all`` moves all slices across ICI simultaneously.  Peer
discovery, connection management, and retry logic disappear — the collective
is compiled into the XLA program.

Raggedness: all_to_all needs equal-sized slices, so each (src, dst) slice is
padded to ``slot`` rows, with true counts exchanged alongside (an int vector
all_to_all).  Receivers compact the slices back to a dense batch.  ``slot``
defaults to the full per-shard capacity (always correct); callers with
skew-free data can pass a smaller slot to cut the padding bandwidth.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.ops.expressions import ColVal
from spark_rapids_tpu.parallel.partitioning import layout_by_partition


@contextmanager
def launch_checkpoint():
    """The single host-side checkpoint per exchange-bearing program
    launch: fires the "shuffle.exchange" injection point exactly once
    (count-based chaos rules see one checkpoint per launch whether the
    traced program was cached or not) and runs the host-side launch
    (trace + dispatch) under a watchdog deadline.  XLA dispatch is
    asynchronous, so a collective that wedges DURING execution
    surfaces at the stage's host sync / the whole-query deadline
    instead — cancellation is cooperative and only host-touching
    checkpoints can deliver it (robustness/watchdog.py)."""
    from spark_rapids_tpu.robustness import watchdog
    from spark_rapids_tpu.robustness.inject import fire
    with watchdog.section("shuffle.exchange"):
        fire("shuffle.exchange")
        yield


def pick_slot(max_slice: int, capacity: int, floor: int = 8) -> int:
    """Slot size for ``exchange`` from a materialized per-destination
    histogram: the true max slice count bucketed up to a power of two
    (<= 2x the ideal bytes on ICI), capped at the full capacity."""
    s = floor
    while s < max_slice:
        s <<= 1
    return min(s, capacity)


def exchange(cols: Sequence[ColVal], pids: jnp.ndarray, nrows,
             axis_name: str, num_parts: int,
             slot: Optional[int] = None) -> Tuple[List[ColVal], jnp.ndarray]:
    """All-to-all exchange inside shard_map.

    Every shard sends row r to shard ``pids[r]``.  Returns (received cols,
    received nrows); received capacity is ``num_parts * slot``.
    Only fixed-width columns (strings must be dictionary-encoded upstream).

    The "shuffle.exchange" injection point does NOT fire here: this
    body runs at trace time (and not at all on a jit-cache hit), and a
    launch with several exchanges (shuffle join) would multi-fire.
    ``launch_checkpoint`` below is the single host-side checkpoint per
    exchange-bearing program launch — callers invoke it right before
    dispatching the compiled program.
    """
    capacity = pids.shape[0]
    slot = slot or capacity
    sorted_cols, counts, starts = layout_by_partition(
        cols, pids, nrows, num_parts)

    # counts for my slices on every peer: all_to_all of the counts vector
    recv_counts = jax.lax.all_to_all(
        counts.reshape(num_parts, 1), axis_name, split_axis=0,
        concat_axis=0).reshape(num_parts)

    # gather each destination's rows into its padded slot: send[d, j]
    d = jnp.arange(num_parts, dtype=jnp.int32)[:, None]
    j = jnp.arange(slot, dtype=jnp.int32)[None, :]
    src = jnp.clip(starts[:, None] + j, 0, capacity - 1)
    slot_valid = j < counts[:, None]

    out_cols: List[ColVal] = []
    total = recv_counts.sum()
    # positions of received valid rows after compaction
    recv_starts = jnp.concatenate(
        [jnp.zeros(1, dtype=jnp.int32), jnp.cumsum(recv_counts)[:-1]])
    for c in sorted_cols:
        send = c.values[src]                      # [num_parts, slot]
        recv = jax.lax.all_to_all(send, axis_name, split_axis=0,
                                  concat_axis=0)
        flat, validity = _compact_received(
            recv, None if c.validity is None else c.validity, src, slot_valid,
            recv_counts, recv_starts, axis_name, num_parts, slot)
        out_cols.append(ColVal(c.dtype, flat, validity))
    return out_cols, total


def _compact_received(recv, send_validity, src, slot_valid, recv_counts,
                      recv_starts, axis_name, num_parts, slot):
    """Flatten [num_parts, slot] received rows into a dense prefix."""
    validity_flat = None
    if send_validity is not None:
        vsend = send_validity[src]
        vrecv = jax.lax.all_to_all(vsend, axis_name, split_axis=0,
                                   concat_axis=0)
    cap = num_parts * slot
    pos = jnp.arange(cap, dtype=jnp.int32)
    # source slice for each dense output position
    part = jnp.searchsorted(recv_starts, pos, side="right") - 1
    part = jnp.clip(part, 0, num_parts - 1)
    offset = pos - recv_starts[part]
    in_range = pos < recv_counts.sum()
    flat = recv[part, jnp.clip(offset, 0, slot - 1)]
    if send_validity is not None:
        validity_flat = jnp.where(
            in_range, vrecv[part, jnp.clip(offset, 0, slot - 1)], False)
    return flat, validity_flat


def all_gather_cols(cols: Sequence[ColVal], nrows, axis_name: str,
                    num_parts: int) -> Tuple[List[ColVal], jnp.ndarray]:
    """Broadcast-style collective: every shard receives every shard's rows.

    The TPU analog of GpuBroadcastExchangeExec (one-to-all replication,
    SURVEY.md section 2.4 "Exchanges") — except all-gather is symmetric, so
    "broadcast" of a small table costs one collective, no driver round trip.
    """
    capacity = cols[0].values.shape[0] if cols else 0
    counts = jax.lax.all_gather(nrows, axis_name)  # [num_parts]
    out_cols: List[ColVal] = []
    starts = jnp.concatenate([jnp.zeros(1, dtype=jnp.int32),
                              jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    total = counts.sum()
    cap = num_parts * capacity
    pos = jnp.arange(cap, dtype=jnp.int32)
    part = jnp.searchsorted(starts, pos, side="right") - 1
    part = jnp.clip(part, 0, num_parts - 1)
    offset = jnp.clip(pos - starts[part], 0, capacity - 1)
    for c in cols:
        g = jax.lax.all_gather(c.values, axis_name)  # [num_parts, capacity]
        flat = g[part, offset]
        validity = None
        if c.validity is not None:
            gv = jax.lax.all_gather(c.validity, axis_name)
            validity = jnp.where(pos < total, gv[part, offset], False)
        out_cols.append(ColVal(c.dtype, flat, validity))
    return out_cols, total
