"""Distributed sort + TopN over the mesh: range partition, then local sort.

The reference sorts distributed data by range-partitioning on sampled
bounds and sorting each partition locally (GpuRangePartitioning.scala +
GpuSortExec under a shuffle; SURVEY.md section 2.4 "Partitioning").  The
TPU formulation runs three compiled shard_map programs with two host
syncs, mirroring the adaptive two-phase shape of ``DistributedAggregate``:

1. **sample** — each shard strided-samples up to k key rows; the host
   all-gathers the (tiny) sample and picks ``nshards-1`` splitter rows by
   sorting the sample in the query's total order (desc / nulls-first /
   NaN-largest / -0.0 == 0.0, exactly the single-node kernel's order).
2. **stats** — per-destination histogram of range-partition ids against
   the splitters (sizes the all-to-all slot like the aggregate's
   histogram pass).
3. **final** — exchange rows to their range bucket and lexsort each
   shard locally.  Concatenating shards in mesh order yields the total
   order.

Splitter values ride in as traced array arguments, so recompilation
happens per (schema, slot) — not per data.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.ops import selection
from spark_rapids_tpu.ops.aggregates import sort_permutation
from spark_rapids_tpu.ops.expressions import ColVal, EmitContext, Expression
from spark_rapids_tpu.parallel.mesh import shard_map as _shard_map
from spark_rapids_tpu.parallel.shuffle import exchange, pick_slot


def _norm_one(v):
    """(primary, nan_flag): normalized comparable pieces for one column.
    NaN sorts largest; -0.0 == 0.0; ints/bools pass through."""
    if jnp.issubdtype(v.dtype, jnp.floating):
        nan = jnp.isnan(v)
        f = jnp.where(v == 0.0, 0.0, v)
        f = jnp.where(nan, 0.0, f)
        return f, nan
    if v.dtype == jnp.bool_:
        v = v.astype(jnp.int8)
    return v, jnp.zeros(v.shape, dtype=jnp.bool_)


def _cmp_one(c: ColVal, desc: bool, nulls_first: bool, sv, svalid):
    """(lt, eq) of each row's key vs one splitter scalar, in the total
    order for this sort key (desc flips lt, nulls order by nulls_first,
    null == null)."""
    f, nan = _norm_one(c.values)
    sf, snan = _norm_one(sv)
    lt = (nan < snan) | ((nan == snan) & (f < sf))
    eq = (nan == snan) & (f == sf)
    if desc:
        lt = ~lt & ~eq
    rv = c.validity if c.validity is not None else \
        jnp.ones(f.shape, dtype=jnp.bool_)
    null_lt = jnp.bool_(nulls_first)  # null vs non-null
    lt = jnp.where(rv & svalid, lt,
                   jnp.where(~rv & svalid, null_lt,
                             jnp.where(rv & ~svalid, ~null_lt, False)))
    eq = jnp.where(rv & svalid, eq, ~rv & ~svalid)
    return lt, eq


def range_pids(key_cols: Sequence[ColVal], descending: Sequence[bool],
               nulls_first: Sequence[bool], spl_vals, spl_valid,
               nshards: int) -> jnp.ndarray:
    """Destination shard of each row: the count of splitters <= the row
    in the total order.  ``spl_vals[k]``: [nshards-1] raw splitter values
    for key k; ``spl_valid[k]``: their validity."""
    cap = key_cols[0].values.shape[0]
    pid = jnp.zeros(cap, dtype=jnp.int32)
    for s in range(nshards - 1):
        lt = jnp.zeros(cap, dtype=jnp.bool_)
        eq = jnp.ones(cap, dtype=jnp.bool_)
        for k, c in enumerate(key_cols):
            k_lt, k_eq = _cmp_one(c, descending[k], nulls_first[k],
                                  spl_vals[k][s], spl_valid[k][s])
            lt = lt | (eq & k_lt)
            eq = eq & k_eq
        pid = pid + jnp.where(lt, 0, 1).astype(jnp.int32)
    return pid


def host_order(cols: Sequence[np.ndarray], valids: Sequence[np.ndarray],
               descending: Sequence[bool], nulls_first: Sequence[bool],
               live: Optional[np.ndarray] = None) -> np.ndarray:
    """np.lexsort permutation realizing the same total order host-side
    (dead rows last).  Used for splitter selection and TopN final merge."""
    n = cols[0].shape[0]
    lex: List[np.ndarray] = []
    for v, valid, desc, nf in zip(reversed(list(cols)),
                                  reversed(list(valids)),
                                  reversed(list(descending)),
                                  reversed(list(nulls_first))):
        if np.issubdtype(v.dtype, np.floating):
            nan = np.isnan(v)
            f = np.where(v == 0.0, 0.0, v)
            f = np.where(nan, 0.0, f)
            lex.extend([-f, -nan.astype(np.int8)] if desc
                       else [f, nan.astype(np.int8)])
        else:
            iv = v.astype(np.int64) if v.dtype == np.bool_ else v
            lex.append(~iv if desc else iv)
        null_key = (~valid).astype(np.int8)
        lex.append(-null_key if nf else null_key)
    if live is not None:
        lex.append((~live).astype(np.int8))
    return np.lexsort(lex)


class DistributedSort:
    """Range-partitioned distributed sort.  Inputs/outputs are
    leading-axis sharded ``[(values, validity)]`` columns + per-shard row
    counts; after ``__call__`` shard i holds range bucket i, locally
    sorted, so mesh-order concatenation is the total order."""

    SAMPLE_PER_SHARD = 256

    def __init__(self, mesh: Mesh, in_dtypes: Sequence[DataType],
                 key_exprs: Sequence[Expression],
                 descending: Sequence[bool],
                 nulls_first: Sequence[bool],
                 partition_prefix: Optional[int] = None,
                 cost_model="auto"):
        """``partition_prefix``: range-partition on only the first N
        keys (local sort still uses all of them), so rows equal on the
        prefix are guaranteed to land on ONE shard — the window
        lowering's requirement that a partition never splits.
        ``cost_model``: the owning session's cost model (the
        distributed planner passes it explicitly; "auto" resolves the
        active session's — direct kernel use)."""
        from spark_rapids_tpu.ops.jit_cache import cached_jit
        from spark_rapids_tpu.parallel.shuffle import packed_enabled
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.nshards = mesh.devices.size
        self.in_dtypes = list(in_dtypes)
        self.key_exprs = list(key_exprs)
        self.descending = list(descending)
        self.nulls_first = list(nulls_first)
        self.prefix = len(self.key_exprs) if partition_prefix is None \
            else int(partition_prefix)
        self._cached_jit = cached_jit
        self.packed = packed_enabled()
        self._sig = ("dist_sort", tuple(mesh.axis_names),
                     tuple(mesh.devices.shape),
                     tuple(str(d) for d in mesh.devices.flat),
                     tuple(dt.name for dt in self.in_dtypes),
                     tuple(e.cache_key() for e in self.key_exprs),
                     tuple(self.descending), tuple(self.nulls_first),
                     self.prefix, ("packed", self.packed))
        from spark_rapids_tpu.plan.costmodel import (AUTO_MODEL,
                                                     active_model)
        self._cost_model = active_model() \
            if isinstance(cost_model, str) and cost_model == AUTO_MODEL \
            else cost_model
        self.last_stats: Optional[dict] = None

    def _emit_keys(self, cols: List[ColVal], nrows) -> List[ColVal]:
        from spark_rapids_tpu.ops.aggregates import widen_colval
        cap = cols[0].values.shape[0]
        ctx = EmitContext(cols, nrows, cap)
        return [widen_colval(e.emit(ctx), cap) for e in self.key_exprs]

    def _cols_of(self, flat_cols) -> List[ColVal]:
        return [ColVal(dt, v, val)
                for (v, val), dt in zip(flat_cols, self.in_dtypes)]

    # phase 1: strided sample of the (prefix) key columns
    def _step_sample(self, flat_cols, nrows_arr):
        nrows = nrows_arr[0]
        cols = self._cols_of(flat_cols)
        cap = cols[0].values.shape[0]
        keys = self._emit_keys(cols, nrows)[: self.prefix]
        k = min(self.SAMPLE_PER_SHARD, cap)
        idx = jnp.clip(
            (jnp.arange(k, dtype=jnp.int32) *
             jnp.maximum(nrows, 1)) // k, 0, cap - 1)
        live = idx < nrows
        out = []
        for c in keys:
            sv = c.values[idx]
            valid = c.validity[idx] if c.validity is not None else \
                jnp.ones(k, dtype=jnp.bool_)
            out.append((sv, jnp.where(live, valid, False)))
        return tuple(out), live

    # phase 2: histogram of range pids (for slot sizing)
    def _step_stats(self, spl_vals, spl_valid, flat_cols, nrows_arr):
        from spark_rapids_tpu.ops.pallas_kernels import histogram
        nrows = nrows_arr[0]
        cols = self._cols_of(flat_cols)
        cap = cols[0].values.shape[0]
        keys = self._emit_keys(cols, nrows)[: self.prefix]
        pids = range_pids(keys, self.descending[: self.prefix],
                          self.nulls_first[: self.prefix],
                          spl_vals, spl_valid, self.nshards)
        live = jnp.arange(cap, dtype=jnp.int32) < nrows
        return histogram(pids, live, self.nshards)

    # phase 3: exchange to range buckets + local sort
    def _step_final(self, slot, spl_vals, spl_valid, flat_cols, nrows_arr):
        nrows = nrows_arr[0]
        cols = self._cols_of(flat_cols)
        keys = self._emit_keys(cols, nrows)[: self.prefix]
        pids = range_pids(keys, self.descending[: self.prefix],
                          self.nulls_first[: self.prefix],
                          spl_vals, spl_valid, self.nshards)
        recv, recv_n = exchange(cols, pids, nrows, self.axis, self.nshards,
                                slot=slot, packed=self.packed,
                                report_site=self._sig + ("final",))
        rcap = recv[0].values.shape[0]
        rkeys = self._emit_keys(recv, recv_n)
        valid_rows = jnp.arange(rcap, dtype=jnp.int32) < recv_n
        perm = sort_permutation(rkeys, valid_rows, rcap, self.descending,
                                self.nulls_first)
        out = selection.gather(recv, perm, recv_n)
        flat = []
        for c in out:
            validity = c.validity if c.validity is not None else \
                jnp.ones(rcap, dtype=jnp.bool_)
            flat.append((c.values, validity))
        return tuple(flat), recv_n.astype(jnp.int32)[None]

    def _splitters(self, flat_cols, nrows_per_shard):
        """Host sync: run the sample pass, pick splitter rows."""
        sample = self._cached_jit(
            self._sig + ("sample",), lambda: _shard_map(
                self._step_sample, mesh=self.mesh,
                in_specs=(P(self.axis), P(self.axis)),
                out_specs=P(self.axis), check_vma=False))(
            flat_cols, nrows_per_shard)
        key_samples, live = sample
        live = np.asarray(live)
        cols = [np.asarray(v) for v, _ in key_samples]
        valids = [np.where(live, np.asarray(val), False)
                  for _, val in key_samples]
        order = host_order(cols, valids, self.descending[: self.prefix],
                           self.nulls_first[: self.prefix], live=live)
        m = int(live.sum())
        spl_vals, spl_valid = [], []
        if m == 0:
            idx = np.zeros(self.nshards - 1, dtype=np.int64)
        else:
            ranks = np.clip(
                ((np.arange(1, self.nshards) * m) // self.nshards),
                0, m - 1)
            idx = order[ranks]
        for v, valid in zip(cols, valids):
            spl_vals.append(jnp.asarray(v[idx]))
            spl_valid.append(jnp.asarray(
                valid[idx] if m else np.ones(self.nshards - 1, bool)))
        return spl_vals, spl_valid

    def __call__(self, flat_cols, nrows_per_shard):
        from spark_rapids_tpu.parallel.shuffle import (
            metrics_for_session, planner_for_session,
            record_exchange_metrics)
        spl_vals, spl_valid = self._splitters(flat_cols, nrows_per_shard)
        hist = self._cached_jit(
            self._sig + ("stats",), lambda: _shard_map(
                self._step_stats, mesh=self.mesh,
                in_specs=(P(), P(), P(self.axis), P(self.axis)),
                out_specs=P(self.axis), check_vma=False))(
            spl_vals, spl_valid, flat_cols, nrows_per_shard)
        counts = np.asarray(hist).reshape(self.nshards, self.nshards)
        capacity = int(flat_cols[0][0].shape[0]) // self.nshards
        # slot through the planner: EMA-sticky power-of-two bucket per
        # sort site (stable jit keys); the stats pass is mandatory here
        # — splitters are data-dependent every launch — so the sort
        # never launches speculatively
        planner = planner_for_session()
        max_slice = int(counts.max())
        slot = planner.plan(self._sig, max_slice, capacity)
        planner.observe(self._sig, max_slice, slot, capacity,
                        rows=int(counts.sum()))
        if self._cost_model is not None:
            # sort exchange sites feed the cost model's evidence too —
            # all three exchange-bearing operators carry skew history
            from spark_rapids_tpu.parallel.shuffle import wire_row_bytes
            self._cost_model.note_exchange(
                self._sig, rows=int(counts.sum()),
                max_slice=max_slice,
                useful_bytes=int(counts.sum())
                * wire_row_bytes(self.in_dtypes))
        record_exchange_metrics(
            metrics_for_session(), dtypes=self.in_dtypes, slot=slot,
            num_parts=self.nshards, nshards=self.nshards,
            rows_useful=int(counts.sum()), packed=self.packed,
            site=self._sig + ("final",))
        self.last_stats = {"partition_counts": counts, "slot": slot,
                           "packed": self.packed}
        from spark_rapids_tpu.parallel.shuffle import launch_checkpoint
        with launch_checkpoint():
            return self._cached_jit(
                self._sig + ("final", slot), lambda: _shard_map(
                    partial(self._step_final, slot), mesh=self.mesh,
                    in_specs=(P(), P(), P(self.axis), P(self.axis)),
                    out_specs=P(self.axis), check_vma=False))(
                spl_vals, spl_valid, flat_cols, nrows_per_shard)


class DistributedTopN:
    """Per-shard TopN under shard_map (local sort + prefix); the tiny
    per-shard winners are merged host-side by the caller (the reference's
    TakeOrderedAndProject does the same partial-then-driver-merge).
    Returns (flat cols, flat MATERIALIZED key cols, nrows) — the key
    columns let the host merge without re-evaluating key expressions."""

    def __init__(self, mesh: Mesh, in_dtypes: Sequence[DataType],
                 key_exprs: Sequence[Expression],
                 descending: Sequence[bool], nulls_first: Sequence[bool],
                 n: int):
        from spark_rapids_tpu.ops.jit_cache import cached_jit
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.nshards = mesh.devices.size
        self.in_dtypes = list(in_dtypes)
        self.key_exprs = list(key_exprs)
        self.descending = list(descending)
        self.nulls_first = list(nulls_first)
        self.n = n
        sig = ("dist_topn", tuple(mesh.axis_names),
               tuple(mesh.devices.shape),
               tuple(str(d) for d in mesh.devices.flat),
               tuple(dt.name for dt in self.in_dtypes),
               tuple(e.cache_key() for e in self.key_exprs),
               tuple(self.descending), tuple(self.nulls_first), n)
        self._jitted = cached_jit(sig, lambda: _shard_map(
            self._step, mesh=self.mesh,
            in_specs=(P(self.axis), P(self.axis)),
            out_specs=P(self.axis), check_vma=False))

    def _step(self, flat_cols, nrows_arr):
        from spark_rapids_tpu.ops.aggregates import widen_colval
        nrows = nrows_arr[0]
        cols = [ColVal(dt, v, val)
                for (v, val), dt in zip(flat_cols, self.in_dtypes)]
        cap = cols[0].values.shape[0]
        ctx = EmitContext(cols, nrows, cap)
        keys = [widen_colval(e.emit(ctx), cap) for e in self.key_exprs]
        valid_rows = jnp.arange(cap, dtype=jnp.int32) < nrows
        perm = sort_permutation(keys, valid_rows, cap, self.descending,
                                self.nulls_first)
        n_out = jnp.minimum(nrows, jnp.int32(self.n))
        out = selection.gather(cols, perm, n_out)
        key_out = selection.gather(keys, perm, n_out)

        def flatten(cs):
            return tuple(
                (c.values, c.validity if c.validity is not None
                 else jnp.ones(cap, dtype=jnp.bool_)) for c in cs)

        return flatten(out), flatten(key_out), n_out.astype(jnp.int32)[None]

    def __call__(self, flat_cols, nrows_per_shard):
        return self._jitted(flat_cols, nrows_per_shard)
