"""Device mesh management and multi-host fleet membership.

The reference's distributed substrate is one GPU per Spark executor connected
by UCX (shuffle-plugin/, SURVEY.md section 2.5).  The TPU substrate is a
``jax.sharding.Mesh`` over the pod slice: shuffle partitions map onto mesh
shards and exchange rides ICI collectives instead of UCX point-to-point.

One mesh axis ("data") is enough for the SQL workload: all reference
parallelism is data parallelism over partitions (SURVEY.md section 2.5
"Parallelism strategies").

Three host notions layer on top of the device mesh:

- **Multi-controller fleet** (``init_fleet``): N processes — one per
  host — each contribute their local devices to one global mesh via
  ``jax.distributed.initialize``; collectives across the process
  boundary ride DCN.  ``device_host`` is the device's process index.
- **Logical hosts** (``assign_logical_hosts``): a SINGLE-process mesh
  partitioned into simulated hosts so the fleet machinery — DCN
  collective selection, deadline scaling, membership, the shrink rung
  — is testable under tier-1 without real multi-process bring-up.
- **Membership** (``HostMembership``): a file-backed per-host beat
  registry.  Hosts beat at ``heartbeatMs``; a peer silent past
  ``heartbeatMs * missedBeatsFatal`` is declared lost (HostLoss event
  + retryable ``HostLossFault``), which the recovery ladder answers
  with its shrink rung (``surviving_mesh``).
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Dict, List, Optional, Set

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"


def shard_map(f, *, mesh: Mesh, in_specs, out_specs, check_vma=False):
    """Version portability wrapper for ``jax.shard_map``: older jax
    releases ship it as ``jax.experimental.shard_map.shard_map`` with
    the ``check_vma`` knob still named ``check_rep``.  Every SPMD
    module routes through here so the engine runs on both."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def make_mesh(n_devices: Optional[int] = None,
              axis_name: str = DATA_AXIS) -> Mesh:
    devices = jax.devices()
    if n_devices is not None and len(devices) < n_devices:
        # single-TPU dev boxes: fall back to the virtual CPU mesh (the
        # xla_force_host_platform_device_count path used by dry runs/tests)
        try:
            cpu = jax.devices("cpu")
            if len(cpu) >= n_devices:
                devices = cpu
        except RuntimeError:
            pass
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis_name,))


# ------------------------------------------------- host identification --

# device id -> simulated host index, set by assign_logical_hosts on a
# single-process mesh.  Empty means hosts = processes (the real fleet
# mapping, and the trivial single-host mapping for one process).
_LOGICAL_HOST_BY_DEVICE: Dict[int, int] = {}


def assign_logical_hosts(mesh: Mesh, n_hosts: int) -> None:
    """Partition ``mesh``'s devices into ``n_hosts`` contiguous
    simulated hosts (spark.rapids.tpu.fleet.logicalHosts).  Ignored in
    real multi-controller mode — process boundaries define hosts there
    and pretending otherwise would misclassify real DCN links."""
    if is_multi_controller():
        return
    _LOGICAL_HOST_BY_DEVICE.clear()
    devs = list(mesh.devices.flat)
    if n_hosts <= 1 or len(devs) < 2:
        return
    n_hosts = min(n_hosts, len(devs))
    per = -(-len(devs) // n_hosts)  # ceil
    for i, d in enumerate(devs):
        _LOGICAL_HOST_BY_DEVICE[d.id] = min(i // per, n_hosts - 1)


def clear_logical_hosts() -> None:
    _LOGICAL_HOST_BY_DEVICE.clear()


def device_host(device) -> int:
    """Which host owns ``device``: the logical-host assignment when one
    is active, else the device's controller process."""
    if _LOGICAL_HOST_BY_DEVICE:
        return _LOGICAL_HOST_BY_DEVICE.get(
            device.id, getattr(device, "process_index", 0))
    return getattr(device, "process_index", 0)


def mesh_hosts(mesh: Mesh) -> List[int]:
    """Sorted distinct hosts owning this mesh's devices."""
    return sorted({device_host(d) for d in mesh.devices.flat})


def is_multi_controller() -> bool:
    """True in a real multi-controller fleet (>1 jax process)."""
    try:
        return jax.process_count() > 1
    except RuntimeError:
        return False


def axis_link_kind(mesh: Mesh, axis_name: Optional[str] = None) -> str:
    """Link class of one mesh axis: ``"ici"`` when every device on the
    axis lives on one host AND one pod slice (chip-to-chip
    interconnect — all_to_all is cheap), ``"dcn"`` when the axis spans
    hosts or slices (data-center network — prefer fewer, larger
    transfers: gather-then-redistribute).  "Host" means the controller
    process in a real fleet, or the logical-host assignment on a
    simulated one; the plain virtual CPU mesh used by tests/dryruns is
    single-host single-slice, so it reads "ici" and topology-auto
    keeps today's collective selection."""
    axis_name = axis_name or mesh.axis_names[0]
    try:
        ax = mesh.axis_names.index(axis_name)
    except ValueError:
        return "ici"
    # representative devices along this axis, other axes fixed at 0
    idx = [0] * mesh.devices.ndim
    devs = []
    for i in range(mesh.devices.shape[ax]):
        idx[ax] = i
        devs.append(mesh.devices[tuple(idx)])
    hosts = {device_host(d) for d in devs}
    slices = {getattr(d, "slice_index", 0) for d in devs}
    return "dcn" if len(hosts) > 1 or len(slices) > 1 else "ici"


def topology(mesh: Mesh) -> dict:
    """Topology metadata for planner/metrics consumption: per-axis link
    kinds plus device and host counts (docs/performance.md
    "Topology-aware collective selection")."""
    return {"devices": int(mesh.devices.size),
            "hosts": len(mesh_hosts(mesh)),
            "axes": {name: axis_link_kind(mesh, name)
                     for name in mesh.axis_names}}


def surviving_mesh(mesh: Mesh, lost_hosts: Set[int]) -> Mesh:
    """Rebuild ``mesh`` over the devices of hosts NOT in
    ``lost_hosts`` — the shrink rung's new layout.  Raises ValueError
    when nothing survives (the ladder then escalates past shrink)."""
    keep = [d for d in mesh.devices.flat
            if device_host(d) not in lost_hosts]
    if not keep:
        raise ValueError("no surviving hosts to rebuild the mesh over")
    return Mesh(np.array(keep), mesh.axis_names[:1])


def shard_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(mesh.axis_names[0]))


def replicated_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


# ------------------------------------------------ multi-controller init --

# jax.distributed may initialize exactly once per process; remember the
# bring-up so a second session in the same process reuses it
_FLEET_STATE: Dict[str, object] = {"initialized": False}


def init_fleet(coordinator: str, process_id: int, num_processes: int,
               timeout_s: int = 60) -> bool:
    """Multi-controller bring-up: join ``coordinator``'s fleet as
    process ``process_id`` of ``num_processes`` via
    ``jax.distributed.initialize``.  Returns True when this process is
    part of a live multi-controller fleet, False for single-controller
    configs (empty coordinator / num_processes < 2).  Idempotent — jax
    allows one initialize per process, so a second session reuses the
    standing bring-up (and mismatched coordinates raise)."""
    if not coordinator or num_processes < 2:
        return False
    if _FLEET_STATE["initialized"]:
        prev = (_FLEET_STATE["coordinator"], _FLEET_STATE["process_id"],
                _FLEET_STATE["num_processes"])
        if prev != (coordinator, process_id, num_processes):
            raise RuntimeError(
                f"fleet already initialized as {prev}, cannot re-join "
                f"as {(coordinator, process_id, num_processes)}")
        return True
    if process_id < 0:
        raise ValueError("fleet.processId must be set (>= 0) when "
                         "fleet.coordinator is configured")
    # the CPU backend's cross-process collectives need gloo selected
    # BEFORE initialize (the env-var spelling the old multihost worker
    # used does not exist — the since-seed env-fail)
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if "cpu" in platforms.split(","):
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator,
                               num_processes=num_processes,
                               process_id=process_id,
                               initialization_timeout=timeout_s)
    _FLEET_STATE.update(initialized=True, coordinator=coordinator,
                        process_id=process_id,
                        num_processes=num_processes)
    return True


def shutdown_fleet() -> None:
    """Tear down the multi-controller runtime.  Required on the CPU
    test fleet: a non-coordinator process that exits without shutdown
    hangs in the distributed client's destructor."""
    if not _FLEET_STATE["initialized"]:
        return
    _FLEET_STATE["initialized"] = False
    try:
        jax.distributed.shutdown()
    except Exception:
        pass  # already torn down / coordinator gone


# ----------------------------------------------- host<->device transfer --

def host_put(mesh: Mesh, host_array, sharded: bool = True):
    """Build a device array from identical per-process host data.  In
    single-controller mode this is ``jnp.asarray`` (today's behavior:
    uncommitted, downstream jit shards it).  In a multi-controller
    fleet a plain ``jnp.asarray`` would be a PROCESS-LOCAL array that
    cannot enter a global computation — instead every process, holding
    the same full host copy, contributes its addressable shards via
    ``make_array_from_callback`` under the global mesh."""
    import jax.numpy as jnp
    if not is_multi_controller():
        return jnp.asarray(host_array)
    host_array = np.asarray(host_array)
    spec = shard_spec(mesh) if sharded and host_array.ndim and \
        host_array.shape[0] % mesh.devices.size == 0 \
        else replicated_spec(mesh)
    return jax.make_array_from_callback(
        host_array.shape, spec, lambda idx: host_array[idx])


def to_host(x) -> np.ndarray:
    """Fetch ``x`` to a full host copy.  Addressable arrays (all of
    single-controller) are a plain ``np.asarray``; a multi-controller
    global array holds only local shards per process, so replicate it
    across the fleet first (jit identity into a replicated layout,
    with ``process_allgather`` as the fallback for inputs jit won't
    take)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        mesh = getattr(getattr(x, "sharding", None), "mesh", None)
        if mesh is not None:
            try:
                rep = jax.jit(lambda a: a,
                              out_shardings=replicated_spec(mesh))(x)
                return np.asarray(rep)
            except Exception:
                pass
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(
            x, tiled=True))
    return np.asarray(x)


# ------------------------------------------------------ host membership --

def membership_dir(conf_dir: str, coordinator: str) -> str:
    """Resolve the beat-registry directory: the configured one, else a
    temp-dir path keyed by coordinator so one fleet's hosts agree on a
    location without config plumbing (CPU test fleets)."""
    if conf_dir:
        return conf_dir
    key = (coordinator or "local").replace(":", "_").replace("/", "_")
    return os.path.join(tempfile.gettempdir(),
                        f"sr_tpu_fleet_{key}")


class HostMembership:
    """File-backed per-host liveness registry: each host atomically
    rewrites its own ``host-<id>.json`` beat record (wall-clock ``ts``
    plus pid); everyone reads everyone's.  A peer whose record ages
    past ``heartbeat_ms * missed_fatal`` — or that disappears after
    having joined — is declared LOST exactly once: a ``HostLoss``
    event is emitted and ``check()`` raises the retryable
    ``HostLossFault`` that enters the recovery ladder at its shrink
    rung.  File-backed keeps the registry coordinator-free on CPU test
    meshes and logical-host fleets; a real fleet points
    ``fleet.membershipDir`` at shared storage.

    Every ``beat()`` runs through the ``fleet.heartbeat`` injection
    point, so the chaos suite can silence a host (raise) or stall it
    (delay) exactly where a real network partition would."""

    def __init__(self, dirpath: str, host_id: int, n_hosts: int,
                 heartbeat_ms: int = 500, missed_fatal: int = 3,
                 session=None):
        from spark_rapids_tpu.robustness import inject
        from spark_rapids_tpu.robustness.faults import HostLossFault
        inject.register_point("fleet.heartbeat", HostLossFault)
        self.dir = dirpath
        self.host = int(host_id)
        self.n_hosts = int(n_hosts)
        self.heartbeat_ms = int(heartbeat_ms)
        self.missed_fatal = int(missed_fatal)
        self._session = session
        self.lost: Set[int] = set()
        self._seen: Set[int] = set()
        self._last_beat = 0.0
        self._joined = False
        # last successfully-parsed record per peer: a beat file whose
        # CONTENT is torn/corrupt (external corruption — the atomic
        # tmp+fsync+replace write itself never publishes a torn
        # record) must not read as "vanished after join"; the peer is
        # judged by its last good timestamp until a fresh record lands
        self._last_rec: Dict[int, dict] = {}
        os.makedirs(dirpath, exist_ok=True)

    @property
    def _tracker(self):
        """The session's gray-failure health tracker (None unless
        fleet.grayFailure.enabled) — beat records gossip local walls
        to it and check() feeds it peers' evidence."""
        return getattr(self._session, "gray_health", None) \
            if self._session is not None else None

    # ----------------------------------------------------------- paths --
    def _path(self, host: int) -> str:
        return os.path.join(self.dir, f"host-{host}.json")

    def _emit(self, event: str, **fields) -> None:
        try:
            from spark_rapids_tpu.utils.events import emit_on_session
            emit_on_session(event, self._session, **fields)
        except Exception:
            pass  # membership must work without an event log

    # ---------------------------------------------------------- beating --
    def beat(self, force: bool = False) -> None:
        """Write this host's beat record (rate-limited to the
        heartbeat period unless ``force``).  The write is atomic with
        the temp+fsync+``os.replace`` discipline used by every other
        durable blob in the engine, so a reader never sees a torn
        record — even across a power cut between the rename and the
        data reaching the platters."""
        now = time.time()
        if not force and (now - self._last_beat) * 1000.0 < \
                self.heartbeat_ms:
            return
        from spark_rapids_tpu.robustness import inject
        inject.fire("fleet.heartbeat")
        rec = {"host": self.host, "pid": os.getpid(),
               "ts": round(now, 3)}
        tracker = self._tracker
        if tracker is not None:
            # gossip this host's per-point walls on the beat record:
            # peers fold them into their health view of us, which is
            # how per-host wall evidence crosses process boundaries
            # without a coordinator
            walls = tracker.local_walls()
            if walls:
                rec["walls"] = walls
        path = self._path(self.host)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(rec, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            return  # a missed write is just a missed beat
        self._last_beat = now
        if not self._joined:
            self._joined = True
            self._emit("HostJoin", host=self.host, pid=os.getpid(),
                       hosts=self.n_hosts)

    # --------------------------------------------------------- checking --
    def _read(self, host: int) -> Optional[dict]:
        """Parse ``host``'s beat record.  A MISSING file is None (the
        vanished-after-join judgment needs it); a file whose content
        is torn or corrupt answers the last successfully-parsed record
        instead — external corruption of the registry must age the
        peer out by silence, never false-kill it on the spot."""
        try:
            with open(self._path(host), encoding="utf-8") as f:
                rec = json.load(f)
            self._last_rec[host] = rec
            return rec
        except FileNotFoundError:
            return None
        except (OSError, ValueError):
            return self._last_rec.get(host)

    def silent_ms(self, host: int) -> Optional[float]:
        """How long since ``host``'s last beat (None = never beat)."""
        rec = self._read(host)
        if rec is None:
            return None
        return max(0.0, (time.time() - float(rec.get("ts", 0))) * 1000.0)

    def check(self, raise_on_loss: bool = True) -> Set[int]:
        """Beat, then judge every peer.  A peer is lost when its beat
        record aged past the fatal window, or vanished after having
        joined.  A peer that never beat is merely not-yet-joined —
        bring-up must not read as death.  Newly-lost hosts emit
        ``HostLoss`` once; with ``raise_on_loss`` the first loss
        raises ``HostLossFault`` so the caller's recovery ladder takes
        over.  Returns the full lost set."""
        self.beat()
        fatal_ms = float(self.heartbeat_ms * self.missed_fatal)
        tracker = self._tracker
        newly = []
        for h in range(self.n_hosts):
            if h == self.host or h in self.lost:
                continue
            rec = self._read(h)
            if rec is None:
                if h in self._seen:
                    newly.append((h, fatal_ms))  # joined, then vanished
                continue
            self._seen.add(h)
            if tracker is not None:
                # gray-failure evidence: the peer's achieved beat
                # interval (jitter shows a fail-slow writer long
                # before fatal silence) plus its gossiped walls
                tracker.observe_beat(h, float(rec.get("ts", 0)))
                tracker.observe_peer_walls(h, rec.get("walls"))
            silent = max(0.0, (time.time() -
                               float(rec.get("ts", 0))) * 1000.0)
            if silent > fatal_ms:
                newly.append((h, silent))
        for h, silent in newly:
            self.lost.add(h)
            self._emit("HostLoss", host=h, silentMs=round(silent, 1),
                       missed=self.missed_fatal)
        if newly and raise_on_loss:
            from spark_rapids_tpu.robustness.faults import HostLossFault
            h, silent = newly[0]
            raise HostLossFault(
                note=f"host {h} silent {silent:.0f}ms "
                     f"(> {self.heartbeat_ms}ms x {self.missed_fatal})",
                host=h)
        return set(self.lost)

    def alive_hosts(self) -> List[int]:
        return [h for h in range(self.n_hosts) if h not in self.lost]

    def rejoin(self, host: int) -> None:
        """Readmit a previously-lost (or quarantined) host: drop it
        from the lost set and from the seen set, so a host whose
        record has not re-appeared yet reads as not-yet-joined (never
        instantly re-lost as vanished-after-join) and fresh evidence
        starts clean."""
        self.lost.discard(host)
        self._seen.discard(host)
        self._last_rec.pop(host, None)

    # ------------------------------------------------------ test levers --
    def simulate_loss(self, host: int) -> None:
        """Age ``host``'s beat record past the fatal window — the test
        stand-in for a crashed/partitioned peer."""
        rec = self._read(host) or {"host": host, "pid": 0}
        rec["ts"] = time.time() - (self.heartbeat_ms *
                                   self.missed_fatal * 10) / 1000.0
        self._seen.add(host)
        try:
            with open(self._path(host), "w", encoding="utf-8") as f:
                json.dump(rec, f)
        except OSError:
            pass

    def leave(self) -> None:
        """Withdraw this host's beat record (clean shutdown — peers
        see an orderly age-out, tests see a clean dir)."""
        try:
            os.unlink(self._path(self.host))
        except OSError:
            pass
