"""Device mesh management.

The reference's distributed substrate is one GPU per Spark executor connected
by UCX (shuffle-plugin/, SURVEY.md section 2.5).  The TPU substrate is a
``jax.sharding.Mesh`` over the pod slice: shuffle partitions map onto mesh
shards and exchange rides ICI collectives instead of UCX point-to-point.

One mesh axis ("data") is enough for the SQL workload: all reference
parallelism is data parallelism over partitions (SURVEY.md section 2.5
"Parallelism strategies").
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"


def shard_map(f, *, mesh: Mesh, in_specs, out_specs, check_vma=False):
    """Version portability wrapper for ``jax.shard_map``: older jax
    releases ship it as ``jax.experimental.shard_map.shard_map`` with
    the ``check_vma`` knob still named ``check_rep``.  Every SPMD
    module routes through here so the engine runs on both."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def make_mesh(n_devices: Optional[int] = None,
              axis_name: str = DATA_AXIS) -> Mesh:
    devices = jax.devices()
    if n_devices is not None and len(devices) < n_devices:
        # single-TPU dev boxes: fall back to the virtual CPU mesh (the
        # xla_force_host_platform_device_count path used by dry runs/tests)
        try:
            cpu = jax.devices("cpu")
            if len(cpu) >= n_devices:
                devices = cpu
        except RuntimeError:
            pass
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis_name,))


def axis_link_kind(mesh: Mesh, axis_name: Optional[str] = None) -> str:
    """Link class of one mesh axis: ``"ici"`` when every device on the
    axis lives in one process AND one pod slice (chip-to-chip
    interconnect — all_to_all is cheap), ``"dcn"`` when the axis spans
    processes or slices (data-center network — prefer fewer, larger
    transfers: gather-then-redistribute).  The virtual CPU mesh used by
    tests/dryruns is single-process single-slice, so it reads "ici"
    and topology-auto keeps today's collective selection."""
    axis_name = axis_name or mesh.axis_names[0]
    try:
        ax = mesh.axis_names.index(axis_name)
    except ValueError:
        return "ici"
    # representative devices along this axis, other axes fixed at 0
    idx = [0] * mesh.devices.ndim
    devs = []
    for i in range(mesh.devices.shape[ax]):
        idx[ax] = i
        devs.append(mesh.devices[tuple(idx)])
    procs = {getattr(d, "process_index", 0) for d in devs}
    slices = {getattr(d, "slice_index", 0) for d in devs}
    return "dcn" if len(procs) > 1 or len(slices) > 1 else "ici"


def topology(mesh: Mesh) -> dict:
    """Topology metadata for planner/metrics consumption: per-axis link
    kinds plus device count (docs/performance.md "Topology-aware
    collective selection")."""
    return {"devices": int(mesh.devices.size),
            "axes": {name: axis_link_kind(mesh, name)
                     for name in mesh.axis_names}}


def shard_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(mesh.axis_names[0]))


def replicated_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
