"""Partitioning kernels: assign every row a destination shard.

Counterpart of ``GpuPartitioning.scala`` + Gpu{Hash,Range,RoundRobin,Single}
Partitioning (SURVEY.md section 2.4): where cudf computes partition indices
then ``Table.contiguousSplit``, the TPU path computes destination ids and
*sorts rows by destination* so each shard's outgoing rows are contiguous —
the layout the padded all-to-all collective wants.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.ops.expressions import ColVal


def _mix64(h):
    """splitmix64 finalizer — good avalanche, vectorizes trivially."""
    h = (h ^ (h >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
    h = (h ^ (h >> 27)) * jnp.uint64(0x94D049BB133111EB)
    return h ^ (h >> 31)


def hash_columns(cols: Sequence[ColVal], seed: int = 42) -> jnp.ndarray:
    """uint64 hash per row over the key columns (murmur-mix based).

    Floats are canonicalized (-0.0 -> 0.0, NaN payloads collapsed) so rows
    that compare equal hash equal, matching the reference's requirement on
    GpuHashPartitioning (murmur3 over canonical bytes).
    """
    acc = None
    for c in cols:
        v = c.values
        if jnp.issubdtype(v.dtype, jnp.floating):
            v = jnp.where(v == 0.0, 0.0, v)
            v = jnp.where(jnp.isnan(v), jnp.nan, v)
            bits = v.astype(jnp.float64).view(jnp.uint64)
        elif v.dtype == jnp.bool_:
            bits = v.astype(jnp.uint64)
        else:
            bits = v.astype(jnp.int64).view(jnp.uint64)
        if c.validity is not None:
            bits = jnp.where(c.validity, bits, jnp.uint64(0x9E3779B97F4A7C15))
        h = _mix64(bits + jnp.uint64(seed))
        acc = h if acc is None else _mix64(acc * jnp.uint64(31) + h)
    return acc


def hash_partition_ids(key_cols: Sequence[ColVal], num_parts: int
                       ) -> jnp.ndarray:
    h = hash_columns(key_cols)
    return (h % jnp.uint64(num_parts)).astype(jnp.int32)


def round_robin_partition_ids(capacity: int, num_parts: int,
                              start: int = 0) -> jnp.ndarray:
    return ((jnp.arange(capacity, dtype=jnp.int32) + start) % num_parts)


def single_partition_ids(capacity: int) -> jnp.ndarray:
    return jnp.zeros(capacity, dtype=jnp.int32)


def range_partition_ids(key: ColVal, bounds: jnp.ndarray) -> jnp.ndarray:
    """Destination by sampled range bounds (ascending), like
    GpuRangePartitioning with host-sampled bounds."""
    return jnp.searchsorted(bounds, key.values, side="right").astype(jnp.int32)


def layout_by_partition(cols: Sequence[ColVal], pids: jnp.ndarray,
                        nrows, num_parts: int
                        ) -> Tuple[List[ColVal], jnp.ndarray, jnp.ndarray]:
    """Sort rows by destination; return (sorted cols, counts, starts).

    counts[d] = rows destined to shard d; starts = exclusive prefix sum.
    Padding rows sort last and are counted in no partition.
    """
    from spark_rapids_tpu.ops import selection

    capacity = pids.shape[0]
    row_mask = jnp.arange(capacity, dtype=jnp.int32) < nrows
    sort_key = jnp.where(row_mask, pids, num_parts)
    perm = jnp.argsort(sort_key, stable=True).astype(jnp.int32)
    sorted_cols = selection.gather(cols, perm, nrows)
    counts = jax.ops.segment_sum(
        jnp.where(row_mask, 1, 0), sort_key, num_segments=num_parts + 1
    )[:num_parts].astype(jnp.int32)
    starts = jnp.concatenate(
        [jnp.zeros(1, dtype=jnp.int32), jnp.cumsum(counts)[:-1]])
    return sorted_cols, counts, starts
