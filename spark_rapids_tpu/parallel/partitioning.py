"""Partitioning kernels: assign every row a destination shard.

Counterpart of ``GpuPartitioning.scala`` + Gpu{Hash,Range,RoundRobin,Single}
Partitioning (SURVEY.md section 2.4): where cudf computes partition indices
then ``Table.contiguousSplit``, the TPU path computes destination ids and
*sorts rows by destination* so each shard's outgoing rows are contiguous —
the layout the padded all-to-all collective wants.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.ops.expressions import ColVal


def _mix32(h):
    """murmur3 fmix32 — good avalanche, all 32-bit ops (TPU's X64 rewriter
    cannot lower f64<->u64 bitcast-convert, and 64-bit lane math is
    emulated; 32-bit mixing is native on the VPU)."""
    h = (h ^ (h >> 16)) * jnp.uint32(0x85EBCA6B)
    h = (h ^ (h >> 13)) * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> 16)


def _column_words(c: ColVal):
    """Per-row (lo, hi) uint32 words encoding a column's value such that
    rows comparing equal yield equal words.  Floats canonicalize
    (-0.0 -> 0.0, NaN collapsed) then split as f32-bitcast of the value
    plus f32-bitcast of the scaled residual — no 64-bit bitcasts."""
    v = c.values
    if jnp.issubdtype(v.dtype, jnp.floating):
        v = jnp.where(v == 0.0, 0.0, v).astype(jnp.float64)
        v = jnp.where(jnp.isnan(v), jnp.float64(0.0), v)  # collapse NaN
        top = v.astype(jnp.float32)
        resid = (v - top.astype(jnp.float64)).astype(jnp.float32)
        resid = resid * jnp.float32(2.0) ** 29
        lo = jax.lax.bitcast_convert_type(top, jnp.uint32)
        hi = jax.lax.bitcast_convert_type(resid, jnp.uint32)
        return lo, hi
    if v.dtype == jnp.bool_:
        return v.astype(jnp.uint32), jnp.zeros_like(v, dtype=jnp.uint32)
    w = v.astype(jnp.int64)
    lo = jnp.bitwise_and(w, jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = jnp.right_shift(w, 32).astype(jnp.uint32)
    return lo, hi


def hash_columns(cols: Sequence[ColVal], seed: int = 42) -> jnp.ndarray:
    """uint32 hash per row over the key columns (murmur3-mix based).

    Floats are canonicalized (-0.0 -> 0.0, NaN payloads collapsed) so rows
    that compare equal hash equal, matching the reference's requirement on
    GpuHashPartitioning (murmur3 over canonical bytes)."""
    acc = None
    for c in cols:
        lo, hi = _column_words(c)
        h = _mix32(lo ^ jnp.uint32(seed))
        h = _mix32(h * jnp.uint32(31) + _mix32(hi ^ jnp.uint32(seed)))
        if c.validity is not None:
            h = jnp.where(c.validity, h, jnp.uint32(0x9E3779B9))
        acc = h if acc is None else _mix32(acc * jnp.uint32(31) + h)
    return acc


def hash_partition_ids(key_cols: Sequence[ColVal], num_parts: int
                       ) -> jnp.ndarray:
    h = hash_columns(key_cols)
    return (h % jnp.uint32(num_parts)).astype(jnp.int32)


# -- host-side parity port (numpy) ----------------------------------------
# The host-RAM staging tier (parallel/exchange_async.py) repartitions
# OFF-device, so its placement must be bit-identical to the device
# kernels above.  The numpy port lives here, next to the jnp original,
# so the two mixes cannot drift apart silently.

def _np_mix32(h):
    h = np.uint32(h)
    h = (h ^ (h >> np.uint32(16))) * np.uint32(0x85EBCA6B)
    h = (h ^ (h >> np.uint32(13))) * np.uint32(0xC2B2AE35)
    return h ^ (h >> np.uint32(16))


def _np_column_words(values: np.ndarray):
    """numpy port of :func:`_column_words` — bit-identical (lo, hi)
    words so host-staged placement matches the device collective's."""
    v = values
    if np.issubdtype(v.dtype, np.floating):
        v = np.where(v == 0.0, 0.0, v).astype(np.float64)
        v = np.where(np.isnan(v), np.float64(0.0), v)
        top = v.astype(np.float32)
        resid = ((v - top.astype(np.float64)).astype(np.float32)
                 * np.float32(2.0) ** 29)
        return top.view(np.uint32), resid.view(np.uint32)
    if v.dtype == np.bool_:
        return v.astype(np.uint32), np.zeros_like(v, dtype=np.uint32)
    w = v.astype(np.int64)
    lo = (w & np.int64(0xFFFFFFFF)).astype(np.uint32)
    hi = (w >> 32).astype(np.uint32)
    return lo, hi


def host_hash_partition_ids(key_cols, num_parts: int,
                            seed: int = 42) -> np.ndarray:
    """Host-side murmur-mix partition ids matching
    :func:`hash_partition_ids` row for row (same mix, same null
    sentinel).  ``key_cols``: [(values ndarray, validity ndarray|None)].
    Parity is pinned by tests/test_shuffle_packed.py."""
    acc = None
    with np.errstate(over="ignore"):
        for values, validity in key_cols:
            lo, hi = _np_column_words(values)
            h = _np_mix32(lo ^ np.uint32(seed))
            h = _np_mix32(h * np.uint32(31)
                          + _np_mix32(hi ^ np.uint32(seed)))
            if validity is not None:
                h = np.where(validity, h, np.uint32(0x9E3779B9))
            acc = h if acc is None else _np_mix32(
                acc * np.uint32(31) + h)
    return (acc % np.uint32(num_parts)).astype(np.int32)


def round_robin_partition_ids(capacity: int, num_parts: int,
                              start: int = 0) -> jnp.ndarray:
    return ((jnp.arange(capacity, dtype=jnp.int32) + start) % num_parts)


def single_partition_ids(capacity: int) -> jnp.ndarray:
    return jnp.zeros(capacity, dtype=jnp.int32)


def range_partition_ids(key: ColVal, bounds: jnp.ndarray) -> jnp.ndarray:
    """Destination by sampled range bounds (ascending), like
    GpuRangePartitioning with host-sampled bounds."""
    return jnp.searchsorted(bounds, key.values, side="right").astype(jnp.int32)


def layout_by_partition(cols: Sequence[ColVal], pids: jnp.ndarray,
                        nrows, num_parts: int
                        ) -> Tuple[List[ColVal], jnp.ndarray, jnp.ndarray]:
    """Sort rows by destination; return (sorted cols, counts, starts).

    counts[d] = rows destined to shard d; starts = exclusive prefix sum.
    Padding rows sort last and are counted in no partition.
    """
    from spark_rapids_tpu.ops import selection

    from spark_rapids_tpu.ops import pallas_kernels as pk

    capacity = pids.shape[0]
    row_mask = jnp.arange(capacity, dtype=jnp.int32) < nrows
    sort_key = jnp.where(row_mask, pids, num_parts)
    perm = jnp.argsort(sort_key, stable=True).astype(jnp.int32)
    sorted_cols = selection.gather(cols, perm, nrows)
    # per-destination counts: pallas one-hot accumulation on TPU (XLA's
    # segment_sum lowers to a serialized scatter there), one-hot matmul
    # fallback elsewhere
    counts = pk.histogram(pids.astype(jnp.int32), row_mask,
                          num_parts).astype(jnp.int32)
    starts = jnp.concatenate(
        [jnp.zeros(1, dtype=jnp.int32), jnp.cumsum(counts)[:-1]])
    return sorted_cols, counts, starts
